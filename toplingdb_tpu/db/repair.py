"""DB repair: rebuild a usable MANIFEST from the SSTs on disk
(reference db/repair.cc in /root/reference).

Strategy (same as the reference's RepairDB): archive the old MANIFEST/CURRENT,
scan every .sst for bounds/seqnos (checksum-verified), replay any WALs into
fresh L0 tables, then write a new MANIFEST placing every surviving table in L0
— overlap-safe because L0 allows overlapping ranges; the next compaction
re-sorts the tree.

Column families are reconstructed from the column_family_id/name stored in
every table's properties block (the reference keeps the same property,
table/table_properties.cc) — WAL records carry their CF ids natively.
"""

from __future__ import annotations

import os

from toplingdb_tpu.db import dbformat, filename
from toplingdb_tpu.db.dbformat import InternalKeyComparator
from toplingdb_tpu.db.log import LogReader, LogWriter
from toplingdb_tpu.db.memtable import MemTable
from toplingdb_tpu.db.flush_job import flush_memtable_to_table
from toplingdb_tpu.db.version_edit import FileMetaData, VersionEdit
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.options import Options
from toplingdb_tpu.table.factory import open_table
from toplingdb_tpu.utils import errors as _errors


def repair_db(dbname: str, options: Options | None = None, env=None) -> dict:
    """Returns a report dict: tables kept/dropped, wal records recovered."""
    options = options or Options()
    from toplingdb_tpu.env import default_env

    env = env or default_env()
    icmp = InternalKeyComparator(options.comparator)
    report = {"tables_kept": 0, "tables_dropped": 0, "wal_records": 0,
              "archived": []}

    children = env.get_children(dbname)
    # 1. Archive old metadata (lost+found style).
    archive = os.path.join(dbname, "lost")
    env.create_dir(archive)
    for child in children:
        ftype, num = filename.parse_file_name(child)
        if ftype in (filename.FileType.MANIFEST, filename.FileType.CURRENT):
            env.rename_file(f"{dbname}/{child}", f"{archive}/{child}")
            report["archived"].append(child)

    # 2. Scan tables: verified ones survive with recomputed metadata,
    # grouped into their column family (id+name from the properties block).
    metas: dict[int, list[FileMetaData]] = {}
    cf_names: dict[int, str] = {0: "default"}
    max_file_number = 1
    max_seq = 0
    for child in children:
        ftype, num = filename.parse_file_name(child)
        if ftype == filename.FileType.BLOB:
            max_file_number = max(max_file_number, num)  # don't reuse
        if ftype != filename.FileType.TABLE:
            continue
        max_file_number = max(max_file_number, num)
        path = filename.table_file_name(dbname, num)
        try:
            r = open_table(env.new_random_access_file(path), icmp,
                            options.table_options)
            it = r.new_iterator()
            it.seek_to_first()
            smallest = None
            largest = None
            n = 0
            blob_refs = set()
            from toplingdb_tpu.db.blob import decode_blob_index

            for k, v in it.entries():  # checksum-verified full scan
                if smallest is None:
                    smallest = k
                largest = k
                n += 1
                if k[-8] == dbformat.ValueType.BLOB_INDEX:
                    # Keep the referenced blob files alive in the rebuilt
                    # MANIFEST, or obsolete-file GC would orphan the values.
                    blob_refs.add(decode_blob_index(v)[0])
            for b, e in r.range_del_entries():
                if smallest is None or icmp.compare(b, smallest) < 0:
                    smallest = b
                end_ikey = dbformat.make_internal_key(
                    e, dbformat.MAX_SEQUENCE_NUMBER,
                    dbformat.VALUE_TYPE_FOR_SEEK,
                )
                if largest is None or icmp.compare(end_ikey, largest) > 0:
                    largest = end_ikey
            if smallest is None:
                raise ValueError("empty table")
            props = r.properties
            cf_id = props.column_family_id
            if props.column_family_name:
                cf_names[cf_id] = props.column_family_name
            else:
                cf_names.setdefault(cf_id, f"cf{cf_id}")
            metas.setdefault(cf_id, []).append(FileMetaData(
                number=num, file_size=env.get_file_size(path),
                smallest=smallest, largest=largest,
                smallest_seqno=props.smallest_seqno,
                largest_seqno=props.largest_seqno,
                num_entries=n,
                num_range_deletions=props.num_range_deletions,
                blob_refs=sorted(blob_refs),
            ))
            max_seq = max(max_seq, props.largest_seqno)
            report["tables_kept"] += 1
        except Exception as e:
            _errors.swallow(reason="repair-table-unreadable", exc=e)
            env.rename_file(path, f"{archive}/{child}")
            report["tables_dropped"] += 1

    # 3. Replay WALs into a fresh L0 table. Only CORRUPTION stops a WAL
    # (its tail is unrecoverable); anything else is a real error the caller
    # must see — swallowing it would silently drop acknowledged writes.
    from toplingdb_tpu.utils.status import Corruption, NotFound

    report["wal_errors"] = 0
    mems: dict[int, MemTable] = {}
    for child in children:
        ftype, num = filename.parse_file_name(child)
        if ftype != filename.FileType.WAL:
            continue
        max_file_number = max(max_file_number, num)
        try:
            reader = LogReader(env.new_sequential_file(
                filename.log_file_name(dbname, num)), log_number=num)
            for rec in reader.records():
                batch = WriteBatch(rec)
                for cf, _, _, _ in batch.entries_cf():
                    if cf not in mems:
                        mems[cf] = MemTable(icmp)
                        cf_names.setdefault(cf, f"cf{cf}")
                batch.insert_into(mems)
                report["wal_records"] += batch.count()
                max_seq = max(max_seq, batch.sequence() + batch.count() - 1)
        except (Corruption, NotFound):
            report["wal_errors"] += 1
    for cf_id, mem in sorted(mems.items()):
        if mem.empty():
            continue
        fnum = max_file_number + 1
        max_file_number = fnum
        meta = flush_memtable_to_table(
            env, dbname, fnum, icmp, [mem], options.table_options,
            column_family=(cf_id, cf_names[cf_id]),
        )
        if meta is not None:
            metas.setdefault(cf_id, []).append(meta)
            report["tables_kept"] += 1

    # 4. Fresh MANIFEST: everything goes to L0 (overlap-legal), with one
    # CF-add record per reconstructed column family.
    manifest_number = max_file_number + 1
    all_cfs = sorted(set(cf_names) | set(metas) | {0})
    records = [VersionEdit(
        comparator=icmp.user_comparator.name(),
        log_number=max_file_number + 2,
        next_file_number=max_file_number + 3,
        last_sequence=max_seq,
        column_family_add="default",
        max_column_family=max(all_cfs),
    )]
    for cf_id in all_cfs:
        if cf_id != 0:
            records.append(VersionEdit(
                column_family=cf_id, column_family_add=cf_names[cf_id]
            ))
        if metas.get(cf_id):
            e = VersionEdit(column_family=cf_id)
            for m in metas[cf_id]:
                e.add_file(0, m)
            records.append(e)
    w = LogWriter(env.new_writable_file(
        filename.manifest_file_name(dbname, manifest_number)))
    for e in records:
        w.add_record(e.encode())
    w.sync()
    w.close()
    filename.set_current_file(env, dbname, manifest_number)
    report["column_families"] = {cf: cf_names[cf] for cf in all_cfs}
    return report
