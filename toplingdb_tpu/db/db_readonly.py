"""Read-only and secondary DB access (reference db/db_impl/db_impl_readonly.cc
and db_impl_secondary.cc in /root/reference).

ReadOnlyDB: a DB opened without WAL replay into mutable state and without
taking ownership of the dir — writes raise. SecondaryDB additionally follows
the primary: try_catch_up_with_primary() re-reads CURRENT/MANIFEST and tails
new WALs into its own memtable view.
"""

from __future__ import annotations

from toplingdb_tpu.db import filename
from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.log import LogReader
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.options import Options
from toplingdb_tpu.utils.status import NotFound, NotSupported
from toplingdb_tpu.utils import errors as _errors


class ReadOnlyDB(DB):
    @staticmethod
    def open(dbname: str, options: Options | None = None, env=None) -> "ReadOnlyDB":
        options = options or Options()
        options.create_if_missing = False
        options.disable_auto_compactions = True
        options.read_only = True
        from toplingdb_tpu.env import default_env

        env = env or default_env()
        db = ReadOnlyDB(dbname, options, env)
        db.versions.recover(readonly=True)
        db._replay_wals_into_mem()
        db._compaction_scheduler = None
        return db

    def _replay_wals_into_mem(self) -> None:
        self._materialize_cfs()
        mems = {cf_id: cfd.mem for cf_id, cfd in self._cfs.items()}
        wal_numbers = sorted(
            num for child in self.env.get_children(self.dbname)
            for ftype, num in [filename.parse_file_name(child)]
            if ftype == filename.FileType.WAL and num >= self.versions.log_number
        )
        for num in wal_numbers:
            try:
                reader = LogReader(self.env.new_sequential_file(
                    filename.log_file_name(self.dbname, num)),
                    log_number=num)
            except NotFound:
                # The primary flushed and GC'd this WAL between our listing
                # and the open: its contents are durable in SSTs the next
                # catch-up will see. Skip to the next live log.
                continue
            try:
                for rec in reader.records():
                    batch = WriteBatch(rec)
                    batch.insert_into(mems)
                    end = batch.sequence() + batch.count() - 1
                    if end > self.versions.last_sequence:
                        self.versions.last_sequence = end
            except Exception as e:
                # primary may be appending; read what's durable
                _errors.swallow(reason="catch-up-tail-race", exc=e)

    def write(self, batch, opts=None) -> None:
        raise NotSupported("DB is open read-only")

    def flush(self, fopts=None) -> None:
        raise NotSupported("DB is open read-only")

    def compact_range(self, begin=None, end=None) -> None:
        raise NotSupported("DB is open read-only")

    def close(self) -> None:
        with self._mutex:
            if self._closed:
                return
            self.versions._manifest_writer = None
            self.table_cache.close()
            self.blob_source.close()
            if self._log_file is not None:
                self._log_file.close()
            self._closed = True


class SecondaryDB(ReadOnlyDB):
    """Follows a live primary (reference DBImplSecondary)."""

    @staticmethod
    def open(dbname: str, options: Options | None = None, env=None) -> "SecondaryDB":
        options = options or Options()
        options.create_if_missing = False
        options.disable_auto_compactions = True
        options.read_only = True
        from toplingdb_tpu.env import default_env

        env = env or default_env()
        db = SecondaryDB(dbname, options, env)
        db.versions.recover(readonly=True)
        db._replay_wals_into_mem()
        db._compaction_scheduler = None
        return db

    def try_catch_up_with_primary(self) -> None:
        """Re-read CURRENT → MANIFEST and WAL tails (reference
        TryCatchUpWithPrimary). Handles column families created or dropped
        by the primary between catch-ups, and WALs the primary deleted
        mid-tail (skips to the next live log)."""
        with self._mutex:
            self._reload_manifest_view()
            self._replay_wals_into_mem()

    def _reload_manifest_view(self) -> None:
        """Swap in the primary's current MANIFEST state: fresh VersionSet,
        per-CF memtables rebuilt to match (created CFs appear, dropped CFs
        vanish — their stale memtable entries with them; surviving CFs get
        EMPTY memtables so flushed-then-compacted history can't linger at
        newer sequence numbers than the SSTs). Caller holds _mutex."""
        from toplingdb_tpu.db.version_set import VersionSet

        vs = VersionSet(self.env, self.dbname, self.icmp,
                        self.options.num_levels)
        vs.recover(readonly=True)
        self.versions = vs
        live = set(vs.column_families)
        for cf_id in list(self._cfs):
            if cf_id not in live:
                del self._cfs[cf_id]  # dropped by the primary
        for cfd in self._cfs.values():
            cfd.mem = self._fresh_memtable()
            cfd.imm = []
        self._materialize_cfs()  # CFs the primary created since
