// Native C++ core for toplingdb_tpu.
//
// The reference implements these primitives in C++ (util/crc32c.cc,
// util/xxhash.h, util/hash.cc in /root/reference); we do the same, exposed
// through a plain C ABI consumed via ctypes. Design is original: table-driven
// slicing-by-8 CRC32C and a from-spec xxhash64.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread \
//          -o _tpulsm_native.so tpulsm_native.cc
#include <algorithm>
#include <array>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstddef>
#include <cstring>
#include <functional>
#include <list>
#include <memory>
#include <mutex>
#include <numeric>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>
#ifndef _WIN32
#include <unistd.h>
#endif
#ifdef __linux__
#include <sched.h>
#endif
#ifndef _WIN32
#include <dlfcn.h>
#endif

// CPUs this PROCESS may run on (cgroup quota / affinity mask), not the
// host's core count — containers routinely pin far fewer than
// hardware_concurrency() reports.
static size_t effective_cpus() {
#ifdef __linux__
  cpu_set_t s;
  if (sched_getaffinity(0, sizeof(s), &s) == 0) {
    int c = CPU_COUNT(&s);
    if (c > 0) return static_cast<size_t>(c);
  }
#endif
  unsigned h = std::thread::hardware_concurrency();
  return h ? h : 1;
}

extern "C" {

// ABI version handshake: the ctypes loader refuses a .so whose version
// differs from its own expectation, so a stale artifact (mtime lies —
// e.g. a restored backup or clock skew) can never drift silently.
// Bump whenever any exported signature changes shape.
#define TPULSM_ABI_VERSION 1

int32_t tpulsm_abi_version(void) { return TPULSM_ABI_VERSION; }

// Shared packed-entry representation of the <=8B-user-key fast path:
// tpulsm_sort_entries and tpulsm_merge_runs promise BIT-EXACT identical
// output, so the struct, comparator, and entry build live in ONE place.
extern "C++" {
struct PackedEntry {
  uint64_t kw;      // BE-packed user key, zero-padded
  uint64_t packed;  // (seq << 8) | type; DESCENDING
  uint32_t len;
  int32_t idx;
};

static inline bool packed_entry_less(const PackedEntry& a,
                                     const PackedEntry& b) {
  if (a.kw != b.kw) return a.kw < b.kw;
  if (a.len != b.len) return a.len < b.len;
  if (a.packed != b.packed) return a.packed > b.packed;  // newer seq first
  return a.idx < b.idx;
}

// Run fn on a new thread, or inline when spawning fails (cgroup pid
// limits, transient EAGAIN) — no exception crosses the extern "C"
// boundary. Shared by every multi-threaded native routine here.
static inline void spawn_or_inline_th(std::vector<std::thread>& pool,
                                      std::function<void()> fn) {
  try {
    pool.emplace_back(fn);
  } catch (...) {
    fn();
  }
}

static inline PackedEntry packed_entry_of(const uint8_t* key_buf,
                                          const int64_t* offs,
                                          const int64_t* lens, int64_t i) {
  const uint8_t* k = key_buf + offs[i];
  const int64_t l = lens[i] - 8;
  // The 8-byte trailer always follows the user key, so an 8-byte load at
  // k is in-bounds for any l >= 0; mask off the trailer bytes that leak
  // into the word when l < 8. ~3x faster than the byte loops at 10M rows.
  uint64_t raw, p;
  std::memcpy(&raw, k, 8);
  std::memcpy(&p, k + l, 8);
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_BIG_ENDIAN__
  uint64_t kw_full = raw;
  p = __builtin_bswap64(p);
#else
  uint64_t kw_full = __builtin_bswap64(raw);
#endif
  uint64_t kw = l >= 8 ? kw_full
                       : (l ? (kw_full & (~0ull << (8 * (8 - l)))) : 0);
  return {kw, p, static_cast<uint32_t>(l), static_cast<int32_t>(i)};
}
}  // extern "C++"


// ---------------------------------------------------------------------------
// Internal-key sort: order entries by (user key bytes asc, key length asc,
// seqno desc) — the exact order the device sort realizes with zero-padded
// big-endian key words + length tie-break + inverted packed trailer. Also
// emits the adjacent new-user-key boundaries the GC mask needs.
// Returns 0 on success.
// ---------------------------------------------------------------------------
int32_t tpulsm_sort_entries(const uint8_t* key_buf, const int64_t* offs,
                            const int64_t* lens, int64_t n,
                            int32_t* order_out, uint8_t* new_key_out,
                            uint64_t* packed_out /* nullable */) {
  auto packed_of = [&](int32_t i) -> uint64_t {
    // 8 LE trailer bytes assembled with shifts: endian-independent.
    const uint8_t* t = key_buf + offs[i] + lens[i] - 8;
    uint64_t p = 0;
    for (int b = 0; b < 8; b++) p |= static_cast<uint64_t>(t[b]) << (8 * b);
    return p;  // (seq << 8) | type
  };
  int64_t max_uklen = 0;
  for (int64_t i = 0; i < n; i++) {
    const int64_t l = lens[i] - 8;
    if (l > max_uklen) max_uklen = l;
  }
  if (max_uklen <= 8) {
    // Packed fast path: user keys fit one big-endian word, so the whole
    // comparator is three integer compares on a cache-friendly struct —
    // ~6x faster than the indirect memcmp form at multi-million entries.
    using E = PackedEntry;
    std::vector<E> es(n);
    for (int64_t i = 0; i < n; i++) {
      es[i] = packed_entry_of(key_buf, offs, lens, i);
      // Per-ORIGINAL-index trailers for the caller, decoded exactly once.
      if (packed_out) packed_out[i] = es[i].packed;
    }
    // idx as the final tiebreak makes the order STRICT and total, so an
    // unstable chunked parallel sort + merges yields exactly the sequence
    // stable_sort would — independent of thread count. The single-core
    // radix path below realises the same order (stable LSD over the same
    // composite), so every path emits identical bytes. The comparator is
    // the SHARED packed_entry_less — merge_runs must stay bit-identical.
    auto cmp = [](const E& a, const E& b) {
      return packed_entry_less(a, b);
    };
    size_t nthreads = effective_cpus();
    if (nthreads > 8) nthreads = 8;
    if (n < (1 << 16)) {
      std::sort(es.begin(), es.end(), cmp);
    } else if (nthreads < 4) {
      // Stable LSD radix, 16-bit digits, least-significant first over the
      // composite (kw, len, packed DESC): ~packed low..high, len, kw
      // low..high. Constant digits (shared key prefixes, small seqnos)
      // skip their scatter pass entirely. No exception may cross the
      // extern "C" boundary: failed scratch allocation degrades to a
      // comparison sort in place.
      std::vector<E> tmp;
      std::vector<int64_t> hist;
      try {
        tmp.resize(n);
        hist.resize(1 << 16);
      } catch (...) {
        std::sort(es.begin(), es.end(), cmp);
        tmp.clear();
      }
      std::vector<E>* src = &es;
      std::vector<E>* dst = &tmp;
      auto digit_of = [](const E& e, int d) -> uint32_t {
        if (d < 4) return (uint32_t)((~e.packed) >> (16 * d)) & 0xffff;
        if (d == 4) return e.len & 0xffff;
        return (uint32_t)(e.kw >> (16 * (d - 5))) & 0xffff;
      };
      for (int d = 0; d < 9 && !tmp.empty(); d++) {
        std::fill(hist.begin(), hist.end(), 0);
        const E* s = src->data();
        for (int64_t i = 0; i < n; i++) hist[digit_of(s[i], d)]++;
        uint32_t first = digit_of(s[0], d);
        if (hist[first] == n) continue;  // constant digit: order unchanged
        int64_t sum = 0;
        for (int64_t b = 0; b < (1 << 16); b++) {
          int64_t c = hist[b];
          hist[b] = sum;
          sum += c;
        }
        E* o = dst->data();
        for (int64_t i = 0; i < n; i++) o[hist[digit_of(s[i], d)]++] = s[i];
        std::swap(src, dst);
      }
      if (src != &es) es = std::move(*src);
    } else {
      // No exception may cross the extern "C" boundary: a failed thread
      // spawn (cgroup pid limit, transient EAGAIN) runs the task inline on
      // this thread instead, and a failed scratch allocation degrades to a
      // serial sort over the already-sorted chunks.
      auto spawn_or_inline = spawn_or_inline_th;
      std::vector<size_t> bounds(nthreads + 1);
      for (size_t t = 0; t <= nthreads; t++)
        bounds[t] = static_cast<size_t>(n) * t / nthreads;
      std::vector<std::thread> workers;
      for (size_t t = 1; t < nthreads; t++)
        spawn_or_inline(workers, [&es, &bounds, t, &cmp] {
          std::sort(es.begin() + bounds[t], es.begin() + bounds[t + 1], cmp);
        });
      std::sort(es.begin(), es.begin() + bounds[1], cmp);
      for (auto& w : workers) w.join();
      std::vector<E> tmp;
      try {
        tmp.resize(n);
      } catch (...) {
        tmp.clear();
      }
      if (tmp.empty()) {
        std::sort(es.begin(), es.end(), cmp);
      } else {
        // Bottom-up pairwise merges; pairs within a pass run concurrently.
        std::vector<E>* src = &es;
        std::vector<E>* dst = &tmp;
        while (bounds.size() > 2) {
          std::vector<size_t> nb;
          nb.push_back(0);
          std::vector<std::thread> mergers;
          for (size_t r = 0; r + 2 < bounds.size(); r += 2) {
            size_t lo = bounds[r], mid = bounds[r + 1], hi = bounds[r + 2];
            spawn_or_inline(mergers, [src, dst, lo, mid, hi, &cmp] {
              std::merge(src->begin() + lo, src->begin() + mid,
                         src->begin() + mid, src->begin() + hi,
                         dst->begin() + lo, cmp);
            });
            nb.push_back(hi);
          }
          if (bounds.size() % 2 == 0) {  // odd run count: copy the tail run
            size_t lo = bounds[bounds.size() - 2], hi = bounds.back();
            std::copy(src->begin() + lo, src->begin() + hi, dst->begin() + lo);
            nb.push_back(hi);
          }
          for (auto& w : mergers) w.join();
          std::swap(src, dst);
          bounds = std::move(nb);
        }
        if (src != &es) es = std::move(*src);
      }
    }
    for (int64_t i = 0; i < n; i++) {
      order_out[i] = es[i].idx;
      new_key_out[i] =
          (i == 0 || es[i].kw != es[i - 1].kw || es[i].len != es[i - 1].len)
              ? 1
              : 0;
    }
    return 0;
  }
  if (packed_out) {
    // Slow (>8B-key) path: emit per-ORIGINAL-index trailers here.
    for (int64_t i = 0; i < n; i++)
      packed_out[i] = packed_of(static_cast<int32_t>(i));
  }
  std::vector<int32_t> idx(n);
  std::iota(idx.begin(), idx.end(), 0);
  // stable: duplicate internal keys keep input order (the survivor choice
  // must be deterministic, matching the np.lexsort twin).
  std::stable_sort(idx.begin(), idx.end(), [&](int32_t a, int32_t b) {
    const uint8_t* ka = key_buf + offs[a];
    const uint8_t* kb = key_buf + offs[b];
    const size_t la = static_cast<size_t>(lens[a] - 8);
    const size_t lb = static_cast<size_t>(lens[b] - 8);
    const int c = std::memcmp(ka, kb, la < lb ? la : lb);
    if (c != 0) return c < 0;
    if (la != lb) return la < lb;
    return packed_of(a) > packed_of(b);  // newer seq first
  });
  std::memcpy(order_out, idx.data(), n * sizeof(int32_t));
  for (int64_t i = 0; i < n; i++) {
    if (i == 0) {
      new_key_out[i] = 1;
      continue;
    }
    const int32_t a = idx[i - 1], b = idx[i];
    const size_t la = static_cast<size_t>(lens[a] - 8);
    const size_t lb = static_cast<size_t>(lens[b] - 8);
    new_key_out[i] =
        (la != lb ||
         std::memcmp(key_buf + offs[a], key_buf + offs[b], la) != 0)
            ? 1
            : 0;
  }
  return 0;
}


// ---------------------------------------------------------------------------
// K-way merge of PRESORTED runs — the host twin of the device segmented
// merge (and the reference's heap merge, table/merging_iterator.cc:476):
// compaction inputs are already internal-key-sorted runs, so re-deriving
// the order with a full sort does O(N log N) work the structure already
// paid for. Each of T threads owns a splitter-bounded slice of EVERY run
// (binary-searched bounds → contiguous output range) and k-way merges its
// slices with a linear head scan. Output contract matches
// tpulsm_sort_entries exactly (same comparator incl. the idx tiebreak).
// Returns 0, or -1 when ineligible (user keys > 8B: caller falls back).
// ---------------------------------------------------------------------------
int32_t tpulsm_merge_runs(const uint8_t* key_buf, const int64_t* offs,
                          const int64_t* lens, int64_t n,
                          const int64_t* run_starts, int32_t n_runs,
                          int32_t* order_out, uint8_t* new_key_out,
                          uint64_t* packed_out /* nullable */) {
  if (n <= 0 || n_runs <= 0) return -1;
  for (int64_t i = 0; i < n; i++)
    if (lens[i] - 8 > 8) return -1;  // packed fast path only
  using E = PackedEntry;
  auto cmp = [](const E& a, const E& b) { return packed_entry_less(a, b); };
  size_t nthreads = effective_cpus();
  if (nthreads > 8) nthreads = 8;
  if (n < (1 << 16)) nthreads = 1;
  std::vector<E> es, out;
  std::vector<std::vector<int64_t>> lb;
  try {
    es.resize(n);
    out.resize(n);
    lb.assign(nthreads + 1, std::vector<int64_t>(n_runs));
  } catch (...) {
    return -1;  // no exception may cross the extern "C" boundary
  }
  auto spawn_or_inline = spawn_or_inline_th;
  {
    // Parallel entry build (+ packed_out per ORIGINAL index).
    auto build = [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; i++) {
        es[i] = packed_entry_of(key_buf, offs, lens, i);
        if (packed_out) packed_out[i] = es[i].packed;
      }
    };
    std::vector<std::thread> pool;
    for (size_t t = 1; t < nthreads; t++)
      spawn_or_inline(pool, [&, t] {
        build(n * (int64_t)t / (int64_t)nthreads,
              n * (int64_t)(t + 1) / (int64_t)nthreads);
      });
    build(0, n / (int64_t)nthreads);
    for (auto& w : pool) w.join();
  }
  // Splitters from the largest run; per-run bounds via lower_bound.
  int32_t big = 0;
  for (int32_t r = 1; r < n_runs; r++)
    if (run_starts[r + 1] - run_starts[r] >
        run_starts[big + 1] - run_starts[big])
      big = r;
  for (int32_t r = 0; r < n_runs; r++) {
    lb[0][r] = run_starts[r];
    lb[nthreads][r] = run_starts[r + 1];
  }
  for (size_t t = 1; t < nthreads; t++) {
    int64_t blo = run_starts[big], bhi = run_starts[big + 1];
    const E& sp = es[blo + (bhi - blo) * (int64_t)t / (int64_t)nthreads];
    for (int32_t r = 0; r < n_runs; r++) {
      int64_t lo = run_starts[r], hi = run_starts[r + 1];
      while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (cmp(es[mid], sp))
          lo = mid + 1;
        else
          hi = mid;
      }
      lb[t][r] = lo;
    }
  }
  // Per-thread k-way merge into its contiguous output range. head/end
  // scratch is preallocated HERE (a bad_alloc on a spawned thread would
  // std::terminate the process).
  std::vector<std::vector<int64_t>> heads, ends;
  try {
    heads.assign(nthreads, std::vector<int64_t>(n_runs));
    ends.assign(nthreads, std::vector<int64_t>(n_runs));
  } catch (...) {
    return -1;  // no exception may cross the extern "C" boundary
  }
  auto merge_slice = [&](size_t t) {
    int64_t pos = 0;
    for (int32_t r = 0; r < n_runs; r++) pos += lb[t][r] - run_starts[r];
    std::vector<int64_t>& head = heads[t];
    std::vector<int64_t>& end = ends[t];
    for (int32_t r = 0; r < n_runs; r++) {
      head[r] = lb[t][r];
      end[r] = lb[t + 1][r];
    }
    while (true) {
      int32_t best = -1;
      for (int32_t r = 0; r < n_runs; r++) {
        if (head[r] >= end[r]) continue;
        if (best < 0 || cmp(es[head[r]], es[head[best]])) best = r;
      }
      if (best < 0) break;
      out[pos++] = es[head[best]++];
    }
  };
  {
    std::vector<std::thread> pool;
    for (size_t t = 1; t < nthreads; t++)
      spawn_or_inline(pool, [&, t] { merge_slice(t); });
    merge_slice(0);
    for (auto& w : pool) w.join();
  }
  for (int64_t i = 0; i < n; i++) {
    order_out[i] = out[i].idx;
    new_key_out[i] =
        (i == 0 || out[i].kw != out[i - 1].kw ||
         out[i].len != out[i - 1].len)
            ? 1
            : 0;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Fused k-way run merge + MVCC GC (host twin of the fused device kernel,
// semantics of ops/compaction_kernels.host_gc_mask — the reference
// CompactionIterator's snapshot-stripe dedup, db/compaction/
// compaction_iterator.cc role). ONE pass: merge presorted runs in internal-
// key order and emit only the surviving rows — no sorted scratch pass, no
// numpy mask passes. Complex user-key groups (MERGE / SINGLE_DELETION
// present) are emitted whole with cx=1 for the host state machine.
//   snaps:  sorted-ascending live-snapshot seqnos (may be null when none)
//   cover:  nullable per-ORIGINAL-row max covering range-tombstone seqno,
//           stripe-clamped by the caller
//   zero_out/cx_out: per SURVIVOR (parallel to the returned prefix of
//           order_out)
//   packed_out: per ORIGINAL row (seq<<8|type), like tpulsm_merge_runs
// Returns the survivor count, or -1 when ineligible (keys > 8B, bad runs).
// ---------------------------------------------------------------------------
int64_t tpulsm_merge_gc_runs(const uint8_t* key_buf, const int64_t* offs,
                             const int64_t* lens, int64_t n,
                             const int64_t* run_starts, int32_t n_runs,
                             const uint64_t* snaps, int32_t n_snaps,
                             const uint64_t* cover, int32_t bottommost,
                             int32_t* order_out, uint8_t* zero_out,
                             uint8_t* cx_out, uint64_t* packed_out,
                             int32_t* has_complex_out) {
  if (n <= 0 || n_runs <= 0) return -1;
  for (int64_t i = 0; i < n; i++)
    if (lens[i] - 8 > 8) return -1;  // packed fast path only
  using E = PackedEntry;
  auto cmp = [](const E& a, const E& b) { return packed_entry_less(a, b); };
  size_t nthreads = effective_cpus();
  if (nthreads > 8) nthreads = 8;
  if (n < (1 << 16)) nthreads = 1;
  // Test hook: the group-aligned splitter path only engages multi-core,
  // so parity tests force a thread count to exercise it on small boxes.
  if (const char* ft = std::getenv("TPULSM_MERGE_THREADS")) {
    long v = std::atol(ft);
    if (v >= 1 && v <= 16) nthreads = (size_t)v;
  }
  std::vector<E> es;
  std::vector<std::vector<int64_t>> lb;
  std::vector<int64_t> tcount(nthreads, 0), tbase(nthreads, 0);
  std::vector<uint8_t> tcomplex(nthreads, 0);
  try {
    es.resize(n);
    lb.assign(nthreads + 1, std::vector<int64_t>(n_runs));
  } catch (...) {
    return -1;  // no exception may cross the extern "C" boundary
  }
  {
    auto build = [&](int64_t lo, int64_t hi) {
      for (int64_t i = lo; i < hi; i++) {
        es[i] = packed_entry_of(key_buf, offs, lens, i);
        if (packed_out) packed_out[i] = es[i].packed;
      }
    };
    std::vector<std::thread> pool;
    for (size_t t = 1; t < nthreads; t++)
      spawn_or_inline_th(pool, [&, t] {
        build(n * (int64_t)t / (int64_t)nthreads,
              n * (int64_t)(t + 1) / (int64_t)nthreads);
      });
    build(0, n / (int64_t)nthreads);
    for (auto& w : pool) w.join();
  }
  // Group-ALIGNED splitters: a synthetic (kw, len, seq=+inf) key compares
  // before every real row of that user key, so lower_bound lands each
  // boundary at a group start and no user-key group spans two threads
  // (the per-group complex/stripe logic below needs whole groups).
  int32_t big = 0;
  for (int32_t r = 1; r < n_runs; r++)
    if (run_starts[r + 1] - run_starts[r] >
        run_starts[big + 1] - run_starts[big])
      big = r;
  for (int32_t r = 0; r < n_runs; r++) {
    lb[0][r] = run_starts[r];
    lb[nthreads][r] = run_starts[r + 1];
  }
  for (size_t t = 1; t < nthreads; t++) {
    int64_t blo = run_starts[big], bhi = run_starts[big + 1];
    E sp = es[blo + (bhi - blo) * (int64_t)t / (int64_t)nthreads];
    sp.packed = ~0ull;
    sp.idx = INT32_MIN;
    for (int32_t r = 0; r < n_runs; r++) {
      int64_t lo = run_starts[r], hi = run_starts[r + 1];
      while (lo < hi) {
        int64_t mid = (lo + hi) >> 1;
        if (cmp(es[mid], sp))
          lo = mid + 1;
        else
          hi = mid;
      }
      lb[t][r] = lo;
    }
  }
  std::vector<std::vector<int64_t>> heads, ends;
  try {
    heads.assign(nthreads, std::vector<int64_t>(n_runs));
    ends.assign(nthreads, std::vector<int64_t>(n_runs));
  } catch (...) {
    return -1;
  }
  constexpr uint8_t kDeletion = 0x0, kValue = 0x1, kMerge = 0x2,
                    kSingleDel = 0x7;
  auto stripe_of = [&](uint64_t seq) -> int32_t {
    // count of snaps < seq (searchsorted left); n_snaps is usually 0.
    int32_t lo = 0, hi = n_snaps;
    while (lo < hi) {
      int32_t mid = (lo + hi) >> 1;
      if (snaps[mid] < seq)
        lo = mid + 1;
      else
        hi = mid;
    }
    return lo;
  };
  // Per-thread merge with inline per-group GC. Survivors are written into
  // the thread's slice region of order_out/zero_out/cx_out (slice offsets
  // bound the survivor count from above), then compacted after the join.
  auto merge_slice = [&](size_t t) {
    int64_t base = 0;
    for (int32_t r = 0; r < n_runs; r++) base += lb[t][r] - run_starts[r];
    tbase[t] = base;
    int64_t pos = base;
    std::vector<int64_t>& head = heads[t];
    std::vector<int64_t>& end = ends[t];
    for (int32_t r = 0; r < n_runs; r++) {
      head[r] = lb[t][r];
      end[r] = lb[t + 1][r];
    }
    // Current user-key group buffer; emit decisions happen on group close.
    uint64_t gkw = 0;
    uint32_t glen = 0;
    bool gcomplex = false;
    int64_t gn = 0;             // rows buffered for this group
    std::vector<E> grp;
    auto flush_group = [&]() {
      if (!gn) return;
      if (gcomplex) {
        tcomplex[t] = 1;
        for (int64_t i = 0; i < gn; i++) {
          order_out[pos] = grp[i].idx;
          zero_out[pos] = 0;
          cx_out[pos] = 1;
          pos++;
        }
      } else {
        int32_t ps = -1;
        for (int64_t i = 0; i < gn; i++) {
          const E& e = grp[i];
          uint64_t seq = e.packed >> 8;
          uint8_t vt = (uint8_t)(e.packed & 0xFF);
          int32_t st = n_snaps ? stripe_of(seq) : 0;
          bool first_in_stripe = (i == 0) || (st != ps);
          ps = st;
          bool covered = cover && cover[e.idx] != 0 && cover[e.idx] > seq;
          bool keep = first_in_stripe && !covered;
          if (bottommost && st == 0 && vt == kDeletion) keep = false;
          if (!keep) continue;
          bool zero = bottommost && st == 0 && vt == kValue;
          order_out[pos] = e.idx;
          zero_out[pos] = zero ? 1 : 0;
          cx_out[pos] = 0;
          pos++;
        }
      }
      gn = 0;
      grp.clear();
    };
    while (true) {
      int32_t best = -1;
      for (int32_t r = 0; r < n_runs; r++) {
        if (head[r] >= end[r]) continue;
        if (best < 0 || cmp(es[head[r]], es[head[best]])) best = r;
      }
      if (best < 0) break;
      const E& e = es[head[best]++];
      if (gn == 0 || e.kw != gkw || e.len != glen) {
        flush_group();
        gkw = e.kw;
        glen = e.len;
        gcomplex = false;
      }
      uint8_t vt = (uint8_t)(e.packed & 0xFF);
      if (vt == kMerge || vt == kSingleDel) gcomplex = true;
      grp.push_back(e);
      gn++;
    }
    flush_group();
    tcount[t] = pos - base;
  };
  {
    std::vector<std::thread> pool;
    for (size_t t = 1; t < nthreads; t++)
      spawn_or_inline_th(pool, [&, t] { merge_slice(t); });
    merge_slice(0);
    for (auto& w : pool) w.join();
  }
  // Compact the per-thread survivor regions to a dense prefix.
  int64_t n_out = tcount[0];
  for (size_t t = 1; t < nthreads; t++) {
    if (tbase[t] != n_out && tcount[t]) {
      std::memmove(order_out + n_out, order_out + tbase[t],
                   tcount[t] * sizeof(int32_t));
      std::memmove(zero_out + n_out, zero_out + tbase[t], tcount[t]);
      std::memmove(cx_out + n_out, cx_out + tbase[t], tcount[t]);
    }
    n_out += tcount[t];
  }
  if (has_complex_out) {
    int32_t hc = 0;
    for (size_t t = 0; t < nthreads; t++) hc |= tcomplex[t];
    *has_complex_out = hc;
  }
  return n_out;
}

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, polynomial 0x82f63b78 reflected), slicing-by-8.
// Semantics match the reference util/crc32c.h: Value/Extend plus the rotated
// mask used to store CRCs of CRC-carrying payloads.
// ---------------------------------------------------------------------------

static uint32_t kCrcTable[8][256];
static std::once_flag kCrcOnce;

static void crc32c_build_tables() {
  const uint32_t poly = 0x82f63b78u;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = kCrcTable[0][i];
    for (int t = 1; t < 8; t++) {
      c = kCrcTable[0][c & 0xff] ^ (c >> 8);
      kCrcTable[t][i] = c;
    }
  }
}

static inline void crc32c_init() {
  // Parallel compression workers may race the first CRC use; a plain
  // boolean guard was UB (torn table visibility) — call_once fences.
  std::call_once(kCrcOnce, crc32c_build_tables);
}

uint32_t tpulsm_crc32c_extend(uint32_t crc, const uint8_t* data, size_t n) {
  crc32c_init();
  uint32_t c = crc ^ 0xffffffffu;
  // Align to 8 bytes.
  while (n && (reinterpret_cast<uintptr_t>(data) & 7)) {
    c = kCrcTable[0][(c ^ *data++) & 0xff] ^ (c >> 8);
    n--;
  }
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, data, 8);
    w ^= c;
    c = kCrcTable[7][w & 0xff] ^ kCrcTable[6][(w >> 8) & 0xff] ^
        kCrcTable[5][(w >> 16) & 0xff] ^ kCrcTable[4][(w >> 24) & 0xff] ^
        kCrcTable[3][(w >> 32) & 0xff] ^ kCrcTable[2][(w >> 40) & 0xff] ^
        kCrcTable[1][(w >> 48) & 0xff] ^ kCrcTable[0][(w >> 56) & 0xff];
    data += 8;
    n -= 8;
  }
  while (n--) {
    c = kCrcTable[0][(c ^ *data++) & 0xff] ^ (c >> 8);
  }
  return c ^ 0xffffffffu;
}

// ---------------------------------------------------------------------------
// xxHash64 — implemented from the public spec. Used for bloom-filter probes
// and general hashing (the reference vendors xxhash in util/xxhash.h).
// ---------------------------------------------------------------------------

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t xxh_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t xxh_merge_round(uint64_t acc, uint64_t val) {
  val = xxh_round(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

uint64_t tpulsm_xxh64(const uint8_t* data, size_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = xxh_round(v1, read64(p)); p += 8;
      v2 = xxh_round(v2, read64(p)); p += 8;
      v3 = xxh_round(v3, read64(p)); p += 8;
      v4 = xxh_round(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh_merge_round(h, v1);
    h = xxh_merge_round(h, v2);
    h = xxh_merge_round(h, v3);
    h = xxh_merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= xxh_round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

// ---------------------------------------------------------------------------
// Block codec: the restart-point entry format of toplingdb_tpu/table/block.py
//   entry = varint32 shared | varint32 non_shared | varint32 value_len
//           | key_delta | value
// with a fixed32 restart array + fixed32 restart count at the end.
// These functions are the native fast path for bulk scans (decode) and
// compaction output building (encode); byte-compatible with the Python
// BlockBuilder/BlockIter by construction (tests assert equality).
// ---------------------------------------------------------------------------

static inline const uint8_t* get_varint32(const uint8_t* p, const uint8_t* end,
                                          uint32_t* v) {
  uint32_t result = 0;
  int shift = 0;
  while (p < end && shift <= 28) {
    uint32_t b = *p++;
    result |= (b & 0x7f) << shift;
    if (b < 0x80) { *v = result; return p; }
    shift += 7;
  }
  return nullptr;
}

static inline const uint8_t* get_varint64(const uint8_t* p, const uint8_t* end,
                                          uint64_t* v) {
  uint64_t result = 0;
  int shift = 0;
  while (p < end && shift <= 63) {
    uint64_t b = *p++;
    result |= (b & 0x7f) << shift;
    if (b < 0x80) { *v = result; return p; }
    shift += 7;
  }
  return nullptr;
}

static inline size_t varint32_len(uint32_t v) {
  size_t n = 1;
  while (v >= 0x80) { v >>= 7; n++; }
  return n;
}

// Length of the common prefix of a[0..n) and b[0..n), word-at-a-time.
static inline uint32_t common_prefix_len(const uint8_t* a, const uint8_t* b,
                                         uint32_t n) {
  uint32_t i = 0;
  while (i + 8 <= n) {
    uint64_t x, y;
    std::memcpy(&x, a + i, 8);
    std::memcpy(&y, b + i, 8);
    uint64_t d = x ^ y;
    if (d) {
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_BIG_ENDIAN__
      return i + (uint32_t)(__builtin_clzll(d) >> 3);
#else
      return i + (uint32_t)(__builtin_ctzll(d) >> 3);
#endif
    }
    i += 8;
  }
  while (i < n && a[i] == b[i]) i++;
  return i;
}

static inline uint8_t* put_varint32(uint8_t* p, uint32_t v) {
  while (v >= 0x80) { *p++ = (v & 0x7f) | 0x80; v >>= 7; }
  *p++ = (uint8_t)v;
  return p;
}

// Decode one block. Returns the number of entries, or a negative error:
//   -1 corrupt, -2 key buffer too small, -3 value buffer too small,
//   -4 entry arrays too small.
// key bytes are prefix-restored into key_out; values copied into val_out.
int64_t tpulsm_decode_block(
    const uint8_t* block, int64_t block_len,
    uint8_t* key_out, int64_t key_cap,
    uint8_t* val_out, int64_t val_cap,
    int32_t* key_offs, int32_t* key_lens,
    int32_t* val_offs, int32_t* val_lens, int64_t max_entries) {
  if (block_len < 4) return -1;
  uint32_t num_restarts;
  std::memcpy(&num_restarts, block + block_len - 4, 4);
  int64_t limit = block_len - 4 - 4 * (int64_t)num_restarts;
  if (limit < 0) return -1;
  const uint8_t* p = block;
  const uint8_t* end = block + limit;
  int64_t n = 0;
  int64_t key_used = 0, val_used = 0;
  uint8_t* last_key = nullptr;
  uint32_t last_len = 0;
  while (p < end) {
    uint32_t shared, non_shared, vlen;
    if (p + 3 <= end && (p[0] | p[1] | p[2]) < 0x80) {
      // All three lengths are single-byte varints — the dominant case for
      // small-KV workloads; skips three bounds-checked decode calls.
      shared = p[0];
      non_shared = p[1];
      vlen = p[2];
      p += 3;
    } else {
      p = get_varint32(p, end, &shared);
      if (!p) return -1;
      p = get_varint32(p, end, &non_shared);
      if (!p) return -1;
      p = get_varint32(p, end, &vlen);
      if (!p) return -1;
    }
    if (p + non_shared + vlen > end) return -1;
    if (shared > last_len) return -1;
    if (n >= max_entries) return -4;
    uint32_t klen = shared + non_shared;
    if (key_used + klen > key_cap) return -2;
    if (val_used + vlen > val_cap) return -3;
    // Offsets are int32 on the Python side: refuse >2GiB columnar buffers
    // (-7 = too large for the native path; caller falls back).
    if (key_used + klen > 0x7FFFFF00LL || val_used + vlen > 0x7FFFFF00LL)
      return -7;
    uint8_t* kdst = key_out + key_used;
    if (shared) std::memcpy(kdst, last_key, shared);
    std::memcpy(kdst + shared, p, non_shared);
    p += non_shared;
    std::memcpy(val_out + val_used, p, vlen);
    p += vlen;
    key_offs[n] = (int32_t)key_used;
    key_lens[n] = (int32_t)klen;
    val_offs[n] = (int32_t)val_used;
    val_lens[n] = (int32_t)vlen;
    last_key = kdst;
    last_len = klen;
    key_used += klen;
    val_used += vlen;
    n++;
  }
  return n;
}

// Build one data block from columnar entries in `order` starting at `start`.
// Consumes entries until the size estimate reaches block_size_limit (always
// at least one). trailer_override[i] >= 0 replaces the key's trailing 8
// bytes with that little-endian value (seqno zeroing). Returns entries
// consumed; *out_len receives the block byte length (including restart
// array). Returns negative on overflow of out_cap (-2).
int64_t tpulsm_build_block(
    const uint8_t* key_buf, const int32_t* key_offs, const int32_t* key_lens,
    const uint8_t* val_buf, const int32_t* val_offs, const int32_t* val_lens,
    const int64_t* trailer_override,
    const int32_t* order, int64_t start, int64_t n_total,
    int64_t block_size_limit, int64_t restart_interval,
    uint8_t* out, int64_t out_cap, int64_t* out_len) {
  uint8_t last_key[4096];
  uint32_t last_len = 0;
  uint8_t cur_key[4096];
  int64_t used = 0;
  int64_t consumed = 0;
  uint32_t restarts[1024];
  uint32_t num_restarts = 1;
  restarts[0] = 0;
  int64_t counter = 0;
  for (int64_t i = start; i < n_total; i++) {
    int32_t e = order[i];
    uint32_t klen = (uint32_t)key_lens[e];
    if (klen > sizeof(cur_key)) return -3;  // key too long for native path
    std::memcpy(cur_key, key_buf + key_offs[e], klen);
    if (trailer_override[e] >= 0 && klen >= 8) {
      uint64_t t = (uint64_t)trailer_override[e];
      for (int b = 0; b < 8; b++) cur_key[klen - 8 + b] = (t >> (8 * b)) & 0xff;
    }
    uint32_t vlen = (uint32_t)val_lens[e];
    uint32_t shared = 0;
    if (counter < restart_interval) {
      uint32_t mx = klen < last_len ? klen : last_len;
      shared = common_prefix_len(last_key, cur_key, mx);
    } else {
      if (num_restarts >= 1024) {
        // Restart table full: cutting here would diverge byte-wise from the
        // Python BlockBuilder (unbounded restarts) — refuse (-8) so the
        // caller falls back to the per-entry path.
        return -8;
      }
      restarts[num_restarts++] = (uint32_t)used;
      counter = 0;
    }
    uint32_t non_shared = klen - shared;
    bool fast_lens = (shared | non_shared | vlen) < 0x80;
    int64_t need = (fast_lens ? 3
                              : (int64_t)varint32_len(shared) +
                                    varint32_len(non_shared) +
                                    varint32_len(vlen)) +
                   non_shared + vlen;
    if (used + need + 4 * (num_restarts + 1) + 4 > out_cap) return -2;
    uint8_t* p = out + used;
    if (fast_lens) {
      p[0] = (uint8_t)shared;
      p[1] = (uint8_t)non_shared;
      p[2] = (uint8_t)vlen;
      p += 3;
    } else {
      p = put_varint32(p, shared);
      p = put_varint32(p, non_shared);
      p = put_varint32(p, vlen);
    }
    std::memcpy(p, cur_key + shared, non_shared);
    p += non_shared;
    std::memcpy(p, val_buf + val_offs[e], vlen);
    p += vlen;
    used = p - out;
    std::memcpy(last_key, cur_key, klen);
    last_len = klen;
    counter++;
    consumed++;
    // Size estimate mirrors BlockBuilder.current_size_estimate().
    if (used + 4 * (int64_t)num_restarts + 4 >= block_size_limit) break;
  }
  // Restart array + count.
  for (uint32_t r = 0; r < num_restarts; r++) {
    std::memcpy(out + used, &restarts[r], 4);
    used += 4;
  }
  std::memcpy(out + used, &num_restarts, 4);
  used += 4;
  *out_len = used;
  return consumed;
}

// Build a RUN of framed data blocks in one call: each block is the exact
// bytes tpulsm_build_block emits, followed by the uncompressed type byte (0)
// and the masked crc32c trailer — i.e. write_block(NO_COMPRESSION) framing
// (reference table/format.cc block trailer). Stops when entries in
// [start, limit) are exhausted, when the output-file cut budget is reached
// (base_file_size + bytes emitted so far >= max_file_size, checked BEFORE
// every block except the first, mirroring the caller's per-iteration cut
// check), or when the per-block metadata arrays fill. Always emits at least
// one block or returns an error. block_counts[b]/block_payload_lens[b]
// receive entries-consumed and UNFRAMED payload length per block; *out_len
// the total framed section length. Returns blocks emitted, or negative:
// -2 out buffer too small for even one block, -3/-8 propagated from
// tpulsm_build_block on the first block (later blocks: returns the partial
// run and the next call surfaces the error).
int64_t tpulsm_build_data_section(
    const uint8_t* key_buf, const int32_t* key_offs, const int32_t* key_lens,
    const uint8_t* val_buf, const int32_t* val_offs, const int32_t* val_lens,
    const int64_t* trailer_override,
    const int32_t* order, int64_t start, int64_t limit,
    int64_t block_size_limit, int64_t restart_interval,
    int64_t base_file_size, int64_t max_file_size,
    int64_t* block_counts, int64_t* block_payload_lens, int64_t max_blocks,
    uint8_t* out, int64_t out_cap, int64_t* out_len) {
  int64_t pos = start;
  int64_t used = 0;
  int64_t nb = 0;
  while (pos < limit) {
    if (nb > 0) {
      if (base_file_size + used >= max_file_size) break;
      if (nb >= max_blocks) break;
    }
    int64_t payload_len = 0;
    int64_t avail = out_cap - used - 5;  // leave room for the 5-byte trailer
    int64_t rc = (avail <= 0) ? -2 : tpulsm_build_block(
        key_buf, key_offs, key_lens, val_buf, val_offs, val_lens,
        trailer_override, order, pos, limit,
        block_size_limit, restart_interval,
        out + used, avail, &payload_len);
    if (rc <= 0) {
      if (nb > 0) break;  // partial run; next call retries/fails this block
      return rc;
    }
    uint8_t* trailer = out + used + payload_len;
    trailer[0] = 0;  // kNoCompression
    uint32_t crc = tpulsm_crc32c_extend(0, out + used, (size_t)(payload_len + 1));
    uint32_t masked = ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
    std::memcpy(trailer + 1, &masked, 4);
    block_counts[nb] = rc;
    block_payload_lens[nb] = payload_len;
    nb++;
    used += payload_len + 5;
    pos += rc;
  }
  *out_len = used;
  return nb;
}

static inline uint8_t* put_varint64(uint8_t* p, uint64_t v) {
  while (v >= 128) {
    *p++ = (uint8_t)(v | 128);
    v >>= 7;
  }
  *p++ = (uint8_t)v;
  return p;
}

// ---------------------------------------------------------------------------
// Whole-file INDEX block build: per data block, the shortest internal-key
// separator to the next block's first key (InternalKeyComparator::
// FindShortestSeparator over the bytewise user comparator — reference
// db/dbformat.cc:217-239 role, bindings in db/dbformat.py:250) + the
// BlockHandle value, assembled with BlockBuilder prefix/restart semantics.
// Replaces ~2 Python calls per data block (the dominant per-block cost of
// the columnar writer at bench scale). The final entry uses the short
// successor of the last block's last key. Returns index entries emitted,
// -2 when out_cap is too small (caller grows), -3 oversized key.
// ---------------------------------------------------------------------------
int64_t tpulsm_build_index_block(
    const uint8_t* key_buf, const int32_t* key_offs, const int32_t* key_lens,
    const int64_t* trailer_override, const int32_t* order,
    const int64_t* block_pos, const int64_t* block_cnt,
    const int64_t* block_offsets, const int64_t* block_plens,
    int64_t n_blocks, int64_t restart_interval,
    uint8_t* out, int64_t out_cap, int64_t* out_len) {
  if (n_blocks <= 0) return -1;
  constexpr uint32_t kMaxKey = 4096;
  // packed (MAX_SEQUENCE_NUMBER, ValueType::MAX) trailer, little-endian.
  static const uint8_t kSeekTrailer[8] = {0x7F, 0xFF, 0xFF, 0xFF,
                                          0xFF, 0xFF, 0xFF, 0xFF};
  std::vector<uint8_t> last(kMaxKey), nextf(kMaxKey), sep(kMaxKey + 9),
      prev_added(kMaxKey + 9);
  std::vector<uint32_t> restarts;
  restarts.push_back(0);
  uint32_t prev_len = 0;
  int64_t used = 0;
  int64_t counter = 0;
  auto load_key = [&](int64_t pos, uint8_t* dst, uint32_t* len) -> bool {
    int32_t e = order[pos];
    uint32_t kl = (uint32_t)key_lens[e];
    if (kl > kMaxKey) return false;
    std::memcpy(dst, key_buf + key_offs[e], kl);
    if (trailer_override[e] >= 0 && kl >= 8) {
      uint64_t t = (uint64_t)trailer_override[e];
      for (int b = 0; b < 8; b++)
        dst[kl - 8 + b] = (uint8_t)((t >> (8 * b)) & 0xff);
    }
    *len = kl;
    return true;
  };
  for (int64_t b = 0; b < n_blocks; b++) {
    uint32_t last_len = 0;
    if (!load_key(block_pos[b] + block_cnt[b] - 1, last.data(), &last_len))
      return -3;
    uint32_t sep_len = 0;
    if (b + 1 < n_blocks) {
      uint32_t next_len = 0;
      if (!load_key(block_pos[b + 1], nextf.data(), &next_len)) return -3;
      // InternalKeyComparator::FindShortestSeparator (bytewise user cmp).
      uint32_t su = last_len - 8, lu = next_len - 8;
      uint32_t mn = su < lu ? su : lu;
      uint32_t i = 0;
      while (i < mn && last[i] == nextf[i]) i++;
      bool shortened = false;
      if (i < mn) {
        uint8_t c = last[i];
        if (c < 0xFF && (uint32_t)(c + 1) < (uint32_t)nextf[i]) {
          // user separator = last[0..i] + (c+1); shorter than su => tag
          // with the MAX (seq,type) trailer.
          if (i + 1 < su) {
            std::memcpy(sep.data(), last.data(), i);
            sep[i] = (uint8_t)(c + 1);
            std::memcpy(sep.data() + i + 1, kSeekTrailer, 8);
            sep_len = i + 1 + 8;
            shortened = true;
          }
        }
      }
      if (!shortened) {
        std::memcpy(sep.data(), last.data(), last_len);
        sep_len = last_len;
      }
    } else {
      // find_short_successor on the user key.
      uint32_t su = last_len - 8;
      uint32_t i = 0;
      while (i < su && last[i] == 0xFF) i++;
      if (i < su && i + 1 < su) {
        std::memcpy(sep.data(), last.data(), i);
        sep[i] = (uint8_t)(last[i] + 1);
        std::memcpy(sep.data() + i + 1, kSeekTrailer, 8);
        sep_len = i + 1 + 8;
      } else {
        std::memcpy(sep.data(), last.data(), last_len);
        sep_len = last_len;
      }
    }
    uint8_t hval[20];
    uint8_t* hp = put_varint64(hval, (uint64_t)block_offsets[b]);
    hp = put_varint64(hp, (uint64_t)block_plens[b]);
    uint32_t vlen = (uint32_t)(hp - hval);
    // BlockBuilder::add semantics.
    uint32_t shared = 0;
    if (counter < restart_interval) {
      uint32_t mx = sep_len < prev_len ? sep_len : prev_len;
      while (shared < mx && prev_added[shared] == sep[shared]) shared++;
    } else {
      restarts.push_back((uint32_t)used);
      counter = 0;
    }
    uint32_t non_shared = sep_len - shared;
    int64_t need = (int64_t)varint32_len(shared) + varint32_len(non_shared) +
                   varint32_len(vlen) + non_shared + vlen;
    if (used + need + 4 * (int64_t)(restarts.size() + 1) + 4 > out_cap)
      return -2;
    uint8_t* p = out + used;
    p = put_varint32(p, shared);
    p = put_varint32(p, non_shared);
    p = put_varint32(p, vlen);
    std::memcpy(p, sep.data() + shared, non_shared);
    p += non_shared;
    std::memcpy(p, hval, vlen);
    p += vlen;
    used = p - out;
    std::memcpy(prev_added.data(), sep.data(), sep_len);
    prev_len = sep_len;
    counter++;
  }
  for (uint32_t r : restarts) {
    std::memcpy(out + used, &r, 4);
    used += 4;
  }
  uint32_t nr = (uint32_t)restarts.size();
  std::memcpy(out + used, &nr, 4);
  used += 4;
  *out_len = used;
  return n_blocks;
}

// Bulk whole-file decode: every data block parsed in one native call.
// Blocks must be uncompressed (type byte 0) — returns -5 otherwise so the
// caller can fall back to per-block Python decompression. verify_crc != 0
// checks each block's masked crc32c trailer (returns -6 on mismatch).
// Returns total entries, or negative error (same codes as decode_block).
int64_t tpulsm_decode_blocks(
    const uint8_t* file_buf, int64_t file_len,
    const int64_t* block_offs, const int64_t* block_lens, int64_t n_blocks,
    int32_t verify_crc,
    uint8_t* key_out, int64_t key_cap,
    uint8_t* val_out, int64_t val_cap,
    int32_t* key_offs, int32_t* key_lens,
    int32_t* val_offs, int32_t* val_lens, int64_t max_entries) {
  int64_t total = 0;
  int64_t key_used = 0, val_used = 0;
  for (int64_t b = 0; b < n_blocks; b++) {
    int64_t off = block_offs[b];
    int64_t len = block_lens[b];
    // Overflow-safe (see tpulsm_scan_blocks): corrupt handles can carry
    // negative or int64-wrapping off/len.
    if (off < 0 || len < 0 || file_len < 5 || off > file_len - 5 ||
        len > file_len - 5 - off)
      return -1;
    uint8_t ctype = file_buf[off + len];
    if (ctype != 0) return -5;
    if (verify_crc) {
      uint32_t stored;
      std::memcpy(&stored, file_buf + off + len + 1, 4);
      // unmask: rot right 17 after subtracting delta (see utils/crc32c.py).
      uint32_t rot = stored - 0xa282ead8u;
      uint32_t crc = (rot >> 17) | (rot << 15);
      uint32_t actual = tpulsm_crc32c_extend(0, file_buf + off, (size_t)(len + 1));
      if (crc != actual) return -6;
    }
    int64_t rc = tpulsm_decode_block(
        file_buf + off, len,
        key_out + key_used, key_cap - key_used,
        val_out + val_used, val_cap - val_used,
        key_offs + total, key_lens + total,
        val_offs + total, val_lens + total, max_entries - total);
    if (rc < 0) return rc;
    if (key_used > 0x7FFFFF00LL || val_used > 0x7FFFFF00LL) return -7;
    // Shift offsets to the global buffers.
    for (int64_t i = 0; i < rc; i++) {
      key_offs[total + i] += (int32_t)key_used;
      val_offs[total + i] += (int32_t)val_used;
    }
    if (rc > 0) {
      key_used = key_offs[total + rc - 1] + key_lens[total + rc - 1];
      val_used = val_offs[total + rc - 1] + val_lens[total + rc - 1];
    }
    total += rc;
  }
  return total;
}

// Cache-line blocked bloom fill; must match table/filter.py
// BlockedBloomFilterPolicy (the reference's FastLocalBloom role): one
// 64B line per key (line = h % num_lines), in-line probes
// (h + (i+1)*h2) % 512.
void tpulsm_bloom_build_blocked(
    const uint8_t* key_buf, const int32_t* key_offs, const int32_t* key_lens,
    int64_t n, uint64_t num_lines, uint32_t num_probes, uint8_t* data) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = tpulsm_xxh64(key_buf + key_offs[i], (size_t)key_lens[i],
                              0xA0761D64ULL);
    uint64_t h2 = ((h >> 33) | (h << 31)) | 1ULL;
    uint8_t* line = data + (h % num_lines) * 64;
    uint64_t x = h;
    for (uint32_t k = 0; k < num_probes; k++) {
      x += h2;
      uint64_t b = x & 511;
      line[b >> 3] |= (uint8_t)(1u << (b & 7));
    }
  }
}

// Bloom filter bit array fill; must match table/filter.py BloomFilterPolicy:
// h = xxh64(key, 0xA0761D64); h2 = rotr(h, 33) | 1; probe_i = (h + i*h2) % bits.
void tpulsm_bloom_build(
    const uint8_t* key_buf, const int32_t* key_offs, const int32_t* key_lens,
    int64_t n, uint64_t num_bits, uint32_t num_probes, uint8_t* bits) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t h = tpulsm_xxh64(key_buf + key_offs[i], (size_t)key_lens[i],
                              0xA0761D64ULL);
    uint64_t h2 = ((h >> 33) | (h << 31)) | 1ULL;
    // NOTE: the probe sequence is (h + k*h2) mod 2^64 mod num_bits — the
    // 2^64 wraparound is part of the format (table/filter.py:47), so the
    // per-probe modulo cannot be replaced by incremental reduction.
    uint64_t x = h;
    for (uint32_t k = 0; k < num_probes; k++) {
      uint64_t b = x % num_bits;
      bits[b >> 3] |= (uint8_t)(1u << (b & 7));
      x += h2;
    }
  }
}

// ---------------------------------------------------------------------------
// Arena skiplist memtable rep (the native analogue of the reference's
// InlineSkipList memtable, memtable/inlineskiplist.h; the CSPP-memtable seam
// in Python is MemTableRep — this is its native implementation).
// Ordering: user_key bytewise ascending, then inv_packed (u64) ascending
// (inv = ~(seq<<8|type), so newer versions sort first).
//
// Concurrency: inserts are LOCK-FREE (CAS splice per level, the reference's
// InsertConcurrently shape, memtable/inlineskiplist.h:61) and the batch
// entry point is called WITHOUT the GIL (ctypes.CDLL), so multiple Python
// writer threads insert in parallel. Readers (ctypes.PyDLL, under the GIL)
// traverse acquire-loaded next pointers of fully-initialized nodes — safe
// against concurrent writers with no reader-side locking.
// ---------------------------------------------------------------------------

namespace {

struct SLNode {
  const uint8_t* key;
  uint32_t key_len;
  uint64_t inv_packed;
  // Value = pointer to a [u32 len][bytes] arena record; a single atomic so
  // in-place replace (WAL-replay duplicate) can't tear against readers.
  std::atomic<const uint8_t*> val;
  int height;
  std::atomic<SLNode*> next[1];  // variable length

  SLNode* nxt(int level, std::memory_order o = std::memory_order_acquire) {
    return next[level].load(o);
  }
};

struct Arena {
  std::vector<uint8_t*> blocks;
  size_t used = 0;
  size_t cap = 0;
  // Block size grows geometrically from min_block to 1MiB: 257 trie
  // stripes at a fixed 1MiB first block held ~257MiB of mostly-empty
  // arenas for byte-spread keys; the skiplist keeps a 1MiB start.
  size_t min_block = 1u << 20;
  std::atomic<size_t> total{0};   // allocated block bytes (physical)
  std::atomic<size_t> handed{0};  // bytes handed to callers (tight bound)
  std::mutex mu;

  uint8_t* alloc(size_t n) {
    n = (n + 7) & ~size_t(7);
    std::lock_guard<std::mutex> g(mu);
    if (used + n > cap) {
      size_t bs = n > min_block ? n : min_block;
      if (min_block < (1u << 20)) min_block *= 2;
      blocks.push_back(new uint8_t[bs]);
      used = 0;
      cap = bs;
      total.fetch_add(bs, std::memory_order_relaxed);
    }
    uint8_t* p = blocks.back() + used;
    used += n;
    handed.fetch_add(n, std::memory_order_relaxed);
    return p;
  }
  ~Arena() {
    for (auto* b : blocks) delete[] b;
  }
};

static const int kMaxHeight = 12;

static uint64_t random_height_seed() {
  static std::atomic<uint64_t> c{0x9E3779B97F4A7C15ULL};
  return c.fetch_add(0xBF58476D1CE4E5B9ULL, std::memory_order_relaxed);
}

struct SkipList {
  Arena arena;
  SLNode* head;
  std::atomic<int> max_height{1};
  std::atomic<int64_t> count{0};

  SkipList() {
    head = alloc_node(kMaxHeight);
    head->key = nullptr;
    head->key_len = 0;
    for (int i = 0; i < kMaxHeight; i++)
      head->next[i].store(nullptr, std::memory_order_relaxed);
  }

  SLNode* alloc_node(int height) {
    size_t sz = sizeof(SLNode) + (height - 1) * sizeof(std::atomic<SLNode*>);
    SLNode* n = reinterpret_cast<SLNode*>(arena.alloc(sz));
    n->height = height;
    return n;
  }

  int random_height() {
    thread_local uint64_t rnd = random_height_seed();
    rnd ^= rnd << 13; rnd ^= rnd >> 7; rnd ^= rnd << 17;
    int h = 1;
    uint64_t r = rnd;
    while (h < kMaxHeight && (r & 3) == 0) { h++; r >>= 2; }
    return h;
  }

  // <0: a < b (a = node key triple, b = probe)
  static int cmp(const uint8_t* ak, uint32_t al, uint64_t ainv,
                 const uint8_t* bk, uint32_t bl, uint64_t binv) {
    uint32_t m = al < bl ? al : bl;
    int r = m ? std::memcmp(ak, bk, m) : 0;
    if (r) return r;
    if (al != bl) return al < bl ? -1 : 1;
    if (ainv != binv) return ainv < binv ? -1 : 1;
    return 0;
  }

  static int cmp_node(SLNode* a, const uint8_t* k, uint32_t kl, uint64_t inv) {
    return cmp(a->key, a->key_len, a->inv_packed, k, kl, inv);
  }

  // First node with node >= probe; fills prev[] when non-null.
  SLNode* seek_ge(const uint8_t* k, uint32_t kl, uint64_t inv,
                  SLNode** prev) {
    SLNode* x = head;
    int level = max_height.load(std::memory_order_acquire) - 1;
    while (true) {
      SLNode* nxt_ = x->nxt(level);
      bool go_right = nxt_ && cmp_node(nxt_, k, kl, inv) < 0;
      if (go_right) {
        x = nxt_;
      } else {
        if (prev) prev[level] = x;
        if (level == 0) return nxt_;
        level--;
      }
    }
  }

  static void set_val(SLNode* n, Arena& a, const uint8_t* v, uint32_t vl) {
    uint8_t* rec = a.alloc(4 + vl);
    std::memcpy(rec, &vl, 4);
    if (vl) std::memcpy(rec + 4, v, vl);
    n->val.store(rec, std::memory_order_release);
  }

  // Returns 1 on fresh insert, 0 on in-place replace of an exact duplicate.
  // Safe for concurrent callers (CAS splice; duplicates replace the value
  // atomically — only WAL replay produces them, and that is single-threaded,
  // but the path is still race-safe).
  int insert(const uint8_t* k, uint32_t kl, uint64_t inv,
             const uint8_t* v, uint32_t vl) {
    SLNode* prev[kMaxHeight];
    for (int i = 0; i < kMaxHeight; i++) prev[i] = head;
    SLNode* ge = seek_ge(k, kl, inv, prev);
    if (ge && cmp_node(ge, k, kl, inv) == 0) {
      set_val(ge, arena, v, vl);
      return 0;
    }
    int h = random_height();
    int mh = max_height.load(std::memory_order_relaxed);
    while (h > mh &&
           !max_height.compare_exchange_weak(mh, h,
                                             std::memory_order_relaxed)) {
    }
    SLNode* n = alloc_node(h);
    uint8_t* kcopy = arena.alloc(kl);
    std::memcpy(kcopy, k, kl);
    n->key = kcopy;
    n->key_len = kl;
    n->inv_packed = inv;
    set_val(n, arena, v, vl);
    // Splice bottom-up (reference InsertConcurrently): the node becomes
    // reachable at level 0 first; higher levels are shortcuts. Only level 0
    // may observe an exact duplicate (n not yet linked there) — at that
    // point replace-in-place and abandon n entirely.
    for (int i = 0; i < h; i++) {
      while (true) {
        // prev[i] may be stale after a lost race: re-walk right as needed.
        SLNode* p = prev[i];
        SLNode* nx = p->nxt(i);
        while (nx && nx != n && cmp_node(nx, k, kl, inv) < 0) {
          p = nx;
          nx = p->nxt(i);
        }
        if (i == 0 && nx && cmp_node(nx, k, kl, inv) == 0) {
          // Concurrent/replayed duplicate: last value wins, atomically.
          set_val(nx, arena, v, vl);
          return 0;
        }
        n->next[i].store(nx, std::memory_order_relaxed);
        if (p->next[i].compare_exchange_strong(nx, n,
                                               std::memory_order_release)) {
          break;
        }
        prev[i] = p;  // retry from the rescanned position
      }
    }
    count.fetch_add(1, std::memory_order_relaxed);
    return 1;
  }
};

}  // namespace

void* tpulsm_skiplist_new() { return new SkipList(); }
void tpulsm_skiplist_free(void* h) { delete static_cast<SkipList*>(h); }

int32_t tpulsm_skiplist_insert(void* h, const uint8_t* k, uint32_t kl,
                               uint64_t inv, const uint8_t* v, uint32_t vl) {
  return static_cast<SkipList*>(h)->insert(k, kl, inv, v, vl);
}

int64_t tpulsm_skiplist_count(void* h) {
  return static_cast<SkipList*>(h)->count.load(std::memory_order_relaxed);
}

int64_t tpulsm_skiplist_memory(void* h) {
  // Handed-out bytes (content + node overhead), matching the trie rep's
  // accounting so flush cadence compares reps on equal footing.
  return (int64_t)static_cast<SkipList*>(h)->arena.handed.load(
      std::memory_order_relaxed);
}

void* tpulsm_skiplist_seek_ge(void* h, const uint8_t* k, uint32_t kl,
                              uint64_t inv) {
  return static_cast<SkipList*>(h)->seek_ge(k, kl, inv, nullptr);
}

void* tpulsm_skiplist_first(void* h) {
  return static_cast<SkipList*>(h)->head->nxt(0);
}

void* tpulsm_skiplist_next(void* node) {
  return static_cast<SLNode*>(node)->nxt(0);
}

// Last node strictly BEFORE the probe (nullptr if none) — the O(log n)
// backward step of the iterator protocol.
void* tpulsm_skiplist_seek_lt(void* h, const uint8_t* k, uint32_t kl,
                              uint64_t inv) {
  SkipList* sl = static_cast<SkipList*>(h);
  SLNode* prev[kMaxHeight];
  for (int i = 0; i < kMaxHeight; i++) prev[i] = sl->head;
  sl->seek_ge(k, kl, inv, prev);
  return prev[0] == sl->head ? nullptr : prev[0];
}

void* tpulsm_skiplist_last(void* h) {
  SkipList* sl = static_cast<SkipList*>(h);
  SLNode* x = sl->head;
  for (int level = sl->max_height.load(std::memory_order_acquire) - 1;
       level >= 0; level--) {
    while (x->nxt(level)) x = x->nxt(level);
  }
  return x == sl->head ? nullptr : x;
}

void tpulsm_skiplist_node(void* node, const uint8_t** k, uint32_t* kl,
                          uint64_t* inv, const uint8_t** v, uint32_t* vl) {
  SLNode* n = static_cast<SLNode*>(node);
  *k = n->key;
  *kl = n->key_len;
  *inv = n->inv_packed;
  const uint8_t* rec = n->val.load(std::memory_order_acquire);
  uint32_t len;
  std::memcpy(&len, rec, 4);
  *v = rec + 4;
  *vl = len;
}

// Batch insert: n entries from flat buffers, ONE ctypes crossing with the
// GIL released for the whole loop (registered on the CDLL handle). Safe to
// call from multiple threads concurrently (lock-free splice). Returns the
// number of FRESH inserts (duplicates replaced in place don't count).
int64_t tpulsm_skiplist_insert_batch(
    void* h, const uint8_t* keybuf, const int64_t* key_offs,
    const int32_t* key_lens, const uint64_t* invs, const uint8_t* valbuf,
    const int64_t* val_offs, const int32_t* val_lens, int64_t n) {
  SkipList* sl = static_cast<SkipList*>(h);
  int64_t fresh = 0;
  for (int64_t i = 0; i < n; i++) {
    fresh += sl->insert(keybuf + key_offs[i], (uint32_t)key_lens[i], invs[i],
                        valbuf + val_offs[i], (uint32_t)val_lens[i]);
  }
  return fresh;
}

// Bulk ordered export of the whole skiplist into flat columnar buffers —
// the memtable half of the columnar flush fast path (one GIL-released
// crossing instead of one Python iteration per entry; the role of
// FlushJob::WriteLevel0Table's memtable scan, reference db/flush_job.cc:833).
// Keys are emitted as INTERNAL keys: user_key bytes followed by the 8-byte
// little-endian packed trailer ((seq<<8)|type == ~inv_packed), i.e. exactly
// the SST key encoding. seqs[i]/vtypes[i] receive the split trailer.
//
// Sizing call: key_buf == nullptr → fills out_sizes[3] = {key_bytes (incl.
// the 8B trailers), val_bytes, rows} and returns rows. Fill call: writes up
// to max_rows rows, bounded by the byte capacities the caller passes back
// in out_sizes[0]/[1] (the sizing results); returns rows written, or -1 on
// any overflow — row count OR byte budget — so a mutation between the two
// calls (contract violation: flush runs on an immutable memtable) can
// never write past the caller's buffers.
int64_t tpulsm_skiplist_export(
    void* h, uint8_t* key_buf, int64_t* key_offs, int32_t* key_lens,
    uint64_t* seqs, int32_t* vtypes, uint8_t* val_buf, int64_t* val_offs,
    int32_t* val_lens, int64_t max_rows, int64_t* out_sizes) {
  SkipList* sl = static_cast<SkipList*>(h);
  if (key_buf == nullptr) {
    int64_t kb = 0, vb = 0, rows = 0;
    for (SLNode* n = sl->head->nxt(0); n; n = n->nxt(0)) {
      const uint8_t* rec = n->val.load(std::memory_order_acquire);
      uint32_t vl;
      std::memcpy(&vl, rec, 4);
      kb += n->key_len + 8;
      vb += vl;
      rows++;
    }
    out_sizes[0] = kb;
    out_sizes[1] = vb;
    out_sizes[2] = rows;
    return rows;
  }
  const int64_t key_cap = out_sizes[0], val_cap = out_sizes[1];
  int64_t ko = 0, vo = 0, rows = 0;
  for (SLNode* n = sl->head->nxt(0); n; n = n->nxt(0)) {
    if (rows >= max_rows) return -1;
    const uint8_t* rec = n->val.load(std::memory_order_acquire);
    uint32_t vl;
    std::memcpy(&vl, rec, 4);
    if (ko + (int64_t)n->key_len + 8 > key_cap || vo + (int64_t)vl > val_cap)
      return -1;
    uint64_t packed = ~n->inv_packed;
    std::memcpy(key_buf + ko, n->key, n->key_len);
    for (int b = 0; b < 8; b++)
      key_buf[ko + n->key_len + b] = (uint8_t)(packed >> (8 * b));
    key_offs[rows] = ko;
    key_lens[rows] = (int32_t)(n->key_len + 8);
    seqs[rows] = packed >> 8;
    vtypes[rows] = (int32_t)(packed & 0xFF);
    std::memcpy(val_buf + vo, rec + 4, vl);
    val_offs[rows] = vo;
    val_lens[rows] = (int32_t)vl;
    ko += n->key_len + 8;
    vo += vl;
    rows++;
  }
  return rows;
}

// ---------------------------------------------------------------------------
// Bulk block inflate: decompress EVERY data block of an SST image in one
// GIL-free call (snappy / zstd dlopen'd at runtime like the Python codecs
// module binds them), emitting a synthetic uncompressed file image
// (payload + 5-byte trailer per block) that feeds tpulsm_decode_blocks
// directly. Parallelized across the process's CPUs. The per-block Python
// loop this replaces was GIL-bound at ~40us/block.
// ---------------------------------------------------------------------------

namespace {

typedef int (*snappy_len_fn)(const char*, size_t, size_t*);
typedef int (*snappy_unc_fn)(const char*, size_t, char*, size_t*);
typedef size_t (*snappy_maxlen_fn)(size_t);
typedef int (*snappy_cmp_fn)(const char*, size_t, char*, size_t*);
typedef size_t (*zstd_sizefn)(const void*, size_t);
typedef size_t (*zstd_dec_fn)(void*, size_t, const void*, size_t);
typedef size_t (*zstd_cmp_fn)(void*, size_t, const void*, size_t, int);
typedef size_t (*zstd_bound_fn)(size_t);
typedef unsigned (*zstd_err_fn)(size_t);
typedef void* (*zstd_ctx_new_fn)();
typedef size_t (*zstd_ctx_free_fn)(void*);
typedef size_t (*zstd_cmp_dict_fn)(void*, void*, size_t, const void*, size_t,
                                   const void*, size_t, int);
typedef size_t (*zstd_dec_dict_fn)(void*, void*, size_t, const void*, size_t,
                                   const void*, size_t);
typedef size_t (*zdict_train_fn)(void*, size_t, const void*, const size_t*,
                                 unsigned);
typedef unsigned (*zdict_err_fn)(size_t);

struct Codecs {
  snappy_len_fn snappy_len = nullptr;
  snappy_unc_fn snappy_unc = nullptr;
  snappy_maxlen_fn snappy_maxlen = nullptr;
  snappy_cmp_fn snappy_cmp = nullptr;
  zstd_sizefn zstd_size = nullptr;
  zstd_dec_fn zstd_dec = nullptr;
  zstd_cmp_fn zstd_cmp = nullptr;
  zstd_bound_fn zstd_bound = nullptr;
  zstd_err_fn zstd_err = nullptr;
  // Dictionary surface for the zip-table kernels. Same libzstd the
  // Python utils/codecs.py binds: trained dicts and compressed frames
  // must be bit-identical across the two paths (parity oracle).
  zstd_ctx_new_fn zstd_cctx_new = nullptr;
  zstd_ctx_free_fn zstd_cctx_free = nullptr;
  zstd_cmp_dict_fn zstd_cmp_dict = nullptr;
  zstd_ctx_new_fn zstd_dctx_new = nullptr;
  zstd_ctx_free_fn zstd_dctx_free = nullptr;
  zstd_dec_dict_fn zstd_dec_dict = nullptr;
  zdict_train_fn zdict_train = nullptr;
  zdict_err_fn zdict_err = nullptr;
};

const Codecs& codecs() {
  static Codecs c = [] {
    Codecs r;
#ifndef _WIN32
    void* s = dlopen("libsnappy.so.1", RTLD_NOW);
    if (!s) s = dlopen("libsnappy.so", RTLD_NOW);
    if (s) {
      r.snappy_len =
          (snappy_len_fn)dlsym(s, "snappy_uncompressed_length");
      r.snappy_unc = (snappy_unc_fn)dlsym(s, "snappy_uncompress");
      r.snappy_maxlen =
          (snappy_maxlen_fn)dlsym(s, "snappy_max_compressed_length");
      r.snappy_cmp = (snappy_cmp_fn)dlsym(s, "snappy_compress");
    }
    void* z = dlopen("libzstd.so.1", RTLD_NOW);
    if (!z) z = dlopen("libzstd.so", RTLD_NOW);
    if (z) {
      r.zstd_size = (zstd_sizefn)dlsym(z, "ZSTD_getFrameContentSize");
      r.zstd_dec = (zstd_dec_fn)dlsym(z, "ZSTD_decompress");
      r.zstd_cmp = (zstd_cmp_fn)dlsym(z, "ZSTD_compress");
      r.zstd_bound = (zstd_bound_fn)dlsym(z, "ZSTD_compressBound");
      r.zstd_err = (zstd_err_fn)dlsym(z, "ZSTD_isError");
      r.zstd_cctx_new = (zstd_ctx_new_fn)dlsym(z, "ZSTD_createCCtx");
      r.zstd_cctx_free = (zstd_ctx_free_fn)dlsym(z, "ZSTD_freeCCtx");
      r.zstd_cmp_dict =
          (zstd_cmp_dict_fn)dlsym(z, "ZSTD_compress_usingDict");
      r.zstd_dctx_new = (zstd_ctx_new_fn)dlsym(z, "ZSTD_createDCtx");
      r.zstd_dctx_free = (zstd_ctx_free_fn)dlsym(z, "ZSTD_freeDCtx");
      r.zstd_dec_dict =
          (zstd_dec_dict_fn)dlsym(z, "ZSTD_decompress_usingDict");
      r.zdict_train = (zdict_train_fn)dlsym(z, "ZDICT_trainFromBuffer");
      r.zdict_err = (zdict_err_fn)dlsym(z, "ZDICT_isError");
    }
#endif
    return r;
  }();
  return c;
}

}  // namespace

// Inflate n framed blocks (payload at offs[b], len lens[b], type byte at
// offs[b]+lens[b]; types: 0 raw, 1 snappy, 7 zstd-no-dict) into `out` as
// payload + 5-byte zero trailer per block; out_offs/out_lens describe the
// emitted payloads. verify_crc checks the COMPRESSED frame crc first
// (masked crc32c, table/format.py framing). Returns total bytes used, or
// -1 codec unavailable / unsupported type (caller: Python fallback),
// -2 out_cap too small, -3 corrupt, -6 crc mismatch.
int64_t tpulsm_inflate_blocks(const uint8_t* file_buf, int64_t file_len,
                              const int64_t* offs, const int64_t* lens,
                              int64_t n, int32_t verify_crc,
                              uint8_t* out, int64_t out_cap,
                              int64_t* out_offs, int64_t* out_lens) {
  const Codecs& c = codecs();
  // Pass 1: sizes (serial; header peeks are cheap).
  int64_t used = 0;
  for (int64_t b = 0; b < n; b++) {
    int64_t off = offs[b], len = lens[b];
    // Overflow-safe (see tpulsm_scan_blocks): corrupt handles can carry
    // negative or int64-wrapping off/len.
    if (off < 0 || len < 0 || file_len < 5 || off > file_len - 5 ||
        len > file_len - 5 - off)
      return -3;
    uint8_t t = file_buf[off + len];
    size_t ulen = 0;
    if (t == 0) {
      ulen = (size_t)len;
    } else if (t == 1) {
      if (!c.snappy_len || !c.snappy_unc) return -1;
      if (c.snappy_len((const char*)file_buf + off, (size_t)len, &ulen) != 0)
        return -3;
    } else if (t == 7) {
      if (!c.zstd_size || !c.zstd_dec || !c.zstd_err) return -1;
      unsigned long long s =
          (unsigned long long)c.zstd_size(file_buf + off, (size_t)len);
      if (s == (unsigned long long)-1 || s == (unsigned long long)-2)
        return -1;  // unknown size / not a frame (dict etc.): Python path
      if (s > (1ull << 31)) return -3;
      ulen = (size_t)s;
    } else {
      return -1;  // lz4/zlib/bzip2: Python fallback
    }
    out_offs[b] = used;
    out_lens[b] = (int64_t)ulen;
    used += (int64_t)ulen + 5;
  }
  if (used > out_cap) return -2;
  // Pass 2: decompress in parallel.
  size_t nthreads = effective_cpus();
  if (nthreads > 8) nthreads = 8;
  if (n < 16) nthreads = 1;
  std::atomic<int64_t> next{0};
  std::atomic<int> err{0};
  auto worker = [&] {
    while (true) {
      int64_t b = next.fetch_add(1, std::memory_order_relaxed);
      if (b >= n || err.load(std::memory_order_relaxed)) return;
      int64_t off = offs[b], len = lens[b];
      uint8_t t = file_buf[off + len];
      if (verify_crc) {
        uint32_t stored;
        std::memcpy(&stored, file_buf + off + len + 1, 4);
        uint32_t rot = stored - 0xa282ead8u;
        uint32_t crc = (rot >> 17) | (rot << 15);
        uint32_t actual =
            tpulsm_crc32c_extend(0, file_buf + off, (size_t)(len + 1));
        if (crc != actual) {
          err.store(6, std::memory_order_relaxed);
          return;
        }
      }
      uint8_t* dst = out + out_offs[b];
      size_t ulen = (size_t)out_lens[b];
      bool ok = true;
      if (t == 0) {
        std::memcpy(dst, file_buf + off, (size_t)len);
      } else if (t == 1) {
        size_t got = ulen;
        ok = c.snappy_unc((const char*)file_buf + off, (size_t)len,
                          (char*)dst, &got) == 0 && got == ulen;
      } else {
        size_t got = c.zstd_dec(dst, ulen, file_buf + off, (size_t)len);
        if (c.zstd_err(got)) {
          // Dictionary frames land here: not corruption — route the file
          // back to the Python per-block path, which has the dict.
          err.store(1, std::memory_order_relaxed);
          return;
        }
        ok = got == ulen;
      }
      if (!ok) {
        err.store(3, std::memory_order_relaxed);
        return;
      }
      std::memset(dst + ulen, 0, 5);  // type=0 + dummy crc (verify off)
    }
  };
  if (nthreads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    for (size_t i = 1; i < nthreads; i++) {
      try {
        pool.emplace_back(worker);
      } catch (...) {
        break;
      }
    }
    worker();
    for (auto& w : pool) w.join();
  }
  int e = err.load();
  if (e == 6) return -6;
  if (e == 1) return -1;
  if (e) return -3;
  return used;
}

// ---------------------------------------------------------------------------
// Fused whole-file scan: inflate (if compressed) + decode EVERY data block
// in ONE call, writing straight into caller-provided slices of a shared
// columnar buffer (offsets emitted ABSOLUTE via key_base/val_base) — no
// synthetic uncompressed image, no Python-side copies, no concat. The
// per-block scratch is reused, so peak extra memory is one block.
// Returns total entries, or: -1 codec unavailable / exotic type (caller
// falls back), -2/-3 key/val capacity, -4 max_entries, -6 crc mismatch,
// -7 offsets exceed the int32 columnar budget, -8 corrupt.
// ---------------------------------------------------------------------------
int64_t tpulsm_scan_blocks(
    const uint8_t* file_buf, int64_t file_len,
    const int64_t* block_offs, const int64_t* block_lens, int64_t n_blocks,
    int32_t verify_crc,
    uint8_t* key_out, int64_t key_cap,
    uint8_t* val_out, int64_t val_cap,
    int32_t* key_offs, int32_t* key_lens,
    int32_t* val_offs, int32_t* val_lens, int64_t max_entries,
    int64_t key_base, int64_t val_base) {
  const Codecs& c = codecs();
  std::vector<uint8_t> scratch;
  int64_t total = 0, key_used = 0, val_used = 0;
  for (int64_t b = 0; b < n_blocks; b++) {
    int64_t off = block_offs[b];
    int64_t len = block_lens[b];
    // Overflow-safe bounds: a corrupt index handle can carry a negative
    // len or an off/len pair whose sum wraps int64; `off + len + 5` would
    // then pass the naive check and read out of bounds BEFORE the CRC
    // ever sees the block. Every comparison below stays within
    // [0, file_len], so nothing can wrap.
    if (off < 0 || len < 0 || file_len < 5 || off > file_len - 5 ||
        len > file_len - 5 - off)
      return -8;
    uint8_t t = file_buf[off + len];
    if (verify_crc) {
      uint32_t stored;
      std::memcpy(&stored, file_buf + off + len + 1, 4);
      uint32_t rot = stored - 0xa282ead8u;
      uint32_t crc = (rot >> 17) | (rot << 15);
      uint32_t actual =
          tpulsm_crc32c_extend(0, file_buf + off, (size_t)(len + 1));
      if (crc != actual) return -6;
    }
    const uint8_t* payload = file_buf + off;
    int64_t plen = len;
    if (t == 1) {
      if (!c.snappy_len || !c.snappy_unc) return -1;
      size_t ulen = 0;
      if (c.snappy_len((const char*)payload, (size_t)len, &ulen) != 0)
        return -8;
      try {
        if (scratch.size() < ulen) scratch.resize(ulen);
      } catch (...) {
        return -1;  // resource exhaustion, NOT corruption: fall back
      }
      size_t got = ulen;
      if (c.snappy_unc((const char*)payload, (size_t)len, (char*)scratch.data(),
                       &got) != 0 ||
          got != ulen)
        return -8;
      payload = scratch.data();
      plen = (int64_t)ulen;
    } else if (t == 7) {
      if (!c.zstd_size || !c.zstd_dec || !c.zstd_err) return -1;
      unsigned long long s =
          (unsigned long long)c.zstd_size(payload, (size_t)len);
      if (s == (unsigned long long)-1 || s == (unsigned long long)-2)
        return -1;  // unknown size / dict frame: Python path has the dict
      if (s > (1ull << 31)) return -1;  // oversized: compatible path
      try {
        if (scratch.size() < (size_t)s) scratch.resize((size_t)s);
      } catch (...) {
        return -1;  // resource exhaustion, NOT corruption: fall back
      }
      size_t got = c.zstd_dec(scratch.data(), (size_t)s, payload, (size_t)len);
      if (c.zstd_err(got) || got != (size_t)s) return -8;
      payload = scratch.data();
      plen = (int64_t)s;
    } else if (t != 0) {
      return -1;  // lz4/zlib/bzip2: Python fallback
    }
    int64_t rc = tpulsm_decode_block(
        payload, plen, key_out + key_used, key_cap - key_used,
        val_out + val_used, val_cap - val_used, key_offs + total,
        key_lens + total, val_offs + total, val_lens + total,
        max_entries - total);
    if (rc < 0) return rc;
    if (key_base + key_used > 0x7FFFFF00LL ||
        val_base + val_used > 0x7FFFFF00LL)
      return -7;
    int64_t kshift = key_base + key_used, vshift = val_base + val_used;
    for (int64_t i = 0; i < rc; i++) {
      key_offs[total + i] += (int32_t)kshift;
      val_offs[total + i] += (int32_t)vshift;
    }
    if (rc > 0) {
      key_used = key_offs[total + rc - 1] + key_lens[total + rc - 1] -
                 key_base;
      val_used = val_offs[total + rc - 1] + val_lens[total + rc - 1] -
                 val_base;
    }
    total += rc;
  }
  return total;
}

// ---------------------------------------------------------------------------
// Keys-copied / VALUES-REFERENCED whole-file scan: like tpulsm_scan_blocks
// but blocks must already be UNCOMPRESSED in file_buf (a raw nocomp file
// or an inflate_blocks synthetic image), and value offsets point INTO
// that image (val_image_base + block offset + in-block position) instead
// of copying ~val-size bytes per entry out. The caller keeps the image
// alive as the columnar val_buf — at 10M-entry compactions the value
// copy was ~0.2-0.3s of pure memcpy. Returns entries, -2 key capacity,
// -4 entry capacity, -6 crc, -7 int32 offset budget, -8 corrupt,
// -5 a compressed block (caller inflates first).
// ---------------------------------------------------------------------------
int64_t tpulsm_scan_blocks_refvals(
    const uint8_t* file_buf, int64_t file_len,
    const int64_t* block_offs, const int64_t* block_lens, int64_t n_blocks,
    int32_t verify_crc,
    uint8_t* key_out, int64_t key_cap,
    int32_t* key_offs, int32_t* key_lens,
    int32_t* val_offs, int32_t* val_lens, int64_t max_entries,
    int64_t key_base, int64_t val_image_base) {
  int64_t total = 0, key_used = 0;
  uint8_t last_key[4096];
  for (int64_t b = 0; b < n_blocks; b++) {
    int64_t off = block_offs[b];
    int64_t len = block_lens[b];
    // Same overflow-safe bounds as tpulsm_scan_blocks: reject negative
    // lengths and signed-wrap off+len before touching file_buf.
    if (off < 0 || len < 0 || file_len < 5 || off > file_len - 5 ||
        len > file_len - 5 - off)
      return -8;
    if (file_buf[off + len] != 0) return -5;  // compressed: inflate first
    if (verify_crc) {
      uint32_t stored;
      std::memcpy(&stored, file_buf + off + len + 1, 4);
      uint32_t rot = stored - 0xa282ead8u;
      uint32_t crc = (rot >> 17) | (rot << 15);
      uint32_t actual =
          tpulsm_crc32c_extend(0, file_buf + off, (size_t)(len + 1));
      if (crc != actual) return -6;
    }
    const uint8_t* block = file_buf + off;
    if (len < 4) return -8;
    uint32_t num_restarts;
    std::memcpy(&num_restarts, block + len - 4, 4);
    int64_t limit = len - 4 - 4 * (int64_t)num_restarts;
    if (limit < 0) return -8;
    const uint8_t* p = block;
    const uint8_t* end = block + limit;
    uint32_t last_len = 0;
    while (p < end) {
      uint32_t shared, non_shared, vlen;
      if (p + 3 <= end && (p[0] | p[1] | p[2]) < 0x80) {
        shared = p[0];
        non_shared = p[1];
        vlen = p[2];
        p += 3;
      } else {
        p = get_varint32(p, end, &shared);
        if (!p) return -8;
        p = get_varint32(p, end, &non_shared);
        if (!p) return -8;
        p = get_varint32(p, end, &vlen);
        if (!p) return -8;
      }
      if (p + non_shared + vlen > end) return -8;
      if (shared > last_len) return -8;
      if (total >= max_entries) return -4;
      uint32_t klen = shared + non_shared;
      if (klen > sizeof(last_key)) return -8;
      if (key_used + klen > key_cap) return -2;
      if (key_base + key_used + klen > 0x7FFFFF00LL) return -7;
      uint8_t* kdst = key_out + key_used;
      if (shared) std::memcpy(kdst, last_key, shared);
      std::memcpy(kdst + shared, p, non_shared);
      std::memcpy(last_key, kdst, klen);
      last_len = klen;
      p += non_shared;
      int64_t vpos = val_image_base + off + (p - block);
      if (vpos + vlen > 0x7FFFFF00LL) return -7;
      key_offs[total] = (int32_t)(key_base + key_used);
      key_lens[total] = (int32_t)klen;
      val_offs[total] = (int32_t)vpos;
      val_lens[total] = (int32_t)vlen;
      key_used += klen;
      p += vlen;
      total++;
    }
  }
  return total;
}

// ---------------------------------------------------------------------------
// In-block point seek: restart binary search + linear scan entirely in C —
// the BlockIter.seek() hot path of every Get (reference
// Block::Iter::Seek, table/block_based/block_iter.h). Keys are INTERNAL
// keys under the standard comparator (user bytes asc, then seq desc).
// ---------------------------------------------------------------------------

namespace {

inline int ikey_compare(const uint8_t* a, int32_t al, const uint8_t* b,
                        int32_t bl) {
  int32_t au = al - 8, bu = bl - 8;
  if (au < 0 || bu < 0) {  // not internal keys; caller gated wrong
    int m = al < bl ? al : bl;
    int c = std::memcmp(a, b, (size_t)m);
    if (c) return c;
    return al < bl ? -1 : (al > bl ? 1 : 0);
  }
  int m = au < bu ? au : bu;
  int c = std::memcmp(a, b, (size_t)m);
  if (c) return c;
  if (au != bu) return au < bu ? -1 : 1;
  uint64_t pa = 0, pb = 0;
  for (int i = 0; i < 8; i++) {
    pa |= (uint64_t)a[au + i] << (8 * i);
    pb |= (uint64_t)b[bu + i] << (8 * i);
  }
  if (pa != pb) return pa > pb ? -1 : 1;  // higher seqno sorts FIRST
  return 0;
}

}  // namespace

// Position at the first entry with key >= target. Outputs BlockIter's
// cursor state into out[6]: {cur, next_off, val_off, val_len, key_len,
// restart_idx}; the full key bytes land in key_out (<= key_cap).
// Returns 1 = found, 0 = every key < target (invalid), -2 = key_cap too
// small, -1 = corrupt/unsupported (caller reruns the Python path, which
// raises the proper error).
int32_t tpulsm_block_seek(const uint8_t* data, int64_t len,
                          const uint8_t* target, int32_t tlen,
                          uint8_t* key_out, int32_t key_cap,
                          int32_t* out) {
  if (len < 4) return -1;
  uint32_t nr;
  std::memcpy(&nr, data + len - 4, 4);
  if (nr == 0) return -1;
  int64_t restart_off = len - 4 - 4 * (int64_t)nr;
  if (restart_off < 0) return -1;
  const int64_t limit = restart_off;
  auto restart_point = [&](uint32_t i) -> uint32_t {
    uint32_t v;
    std::memcpy(&v, data + restart_off + 4 * (int64_t)i, 4);
    return v;
  };
  // Decode the FULL key at a restart (shared == 0 there).
  auto restart_key = [&](uint32_t r, const uint8_t** k, uint32_t* kl,
                         const uint8_t** next) -> bool {
    const uint8_t* p = data + restart_point(r);
    const uint8_t* end = data + limit;
    uint32_t shared, non_shared, vlen;
    p = get_varint32(p, end, &shared);
    if (!p) return false;
    p = get_varint32(p, end, &non_shared);
    if (!p) return false;
    p = get_varint32(p, end, &vlen);
    if (!p || shared != 0 || p + non_shared + vlen > end) return false;
    *k = p;
    *kl = non_shared;
    *next = p + non_shared + vlen;
    return true;
  };
  // Binary search: last restart whose key < target.
  uint32_t lo = 0, hi = nr - 1;
  while (lo < hi) {
    uint32_t mid = (lo + hi + 1) / 2;
    const uint8_t* k;
    uint32_t kl;
    const uint8_t* nxt;
    if (!restart_key(mid, &k, &kl, &nxt)) return -1;
    if (ikey_compare(k, (int32_t)kl, target, tlen) < 0) {
      lo = mid;
    } else {
      hi = mid - 1;
    }
  }
  // Linear scan from restart lo, reconstructing keys in key_out.
  int64_t off = restart_point(lo);
  int32_t cur_len = 0;
  const uint8_t* end = data + limit;
  while (off < limit) {
    const uint8_t* p = data + off;
    uint32_t shared, non_shared, vlen;
    p = get_varint32(p, end, &shared);
    if (!p) return -1;
    p = get_varint32(p, end, &non_shared);
    if (!p) return -1;
    p = get_varint32(p, end, &vlen);
    if (!p || p + non_shared + vlen > end) return -1;
    if ((int32_t)shared > cur_len) return -1;
    if ((int64_t)shared + non_shared > key_cap) return -2;
    std::memcpy(key_out + shared, p, non_shared);
    cur_len = (int32_t)(shared + non_shared);
    int64_t val_off = (p - data) + non_shared;
    int64_t next_off = val_off + vlen;
    if (ikey_compare(key_out, cur_len, target, tlen) >= 0) {
      out[0] = (int32_t)off;
      out[1] = (int32_t)next_off;
      out[2] = (int32_t)val_off;
      out[3] = (int32_t)vlen;
      out[4] = cur_len;
      out[5] = (int32_t)lo;
      return 1;
    }
    off = next_off;
  }
  return 0;
}

// Compressed variant of tpulsm_build_data_section: each block builds RAW
// into scratch, compresses with `ctype` (1=snappy, 7=zstd at `level`;
// kept only when < raw - raw/8, the fmt.compress_for_block rule — else
// stored raw with type 0), then frames with the type byte + masked crc.
// block_raw_lens[b] = uncompressed payload length (props accounting).
// Extra return codes: -9 codec unavailable (caller: Python write path).
int64_t tpulsm_build_data_section_c(
    const uint8_t* key_buf, const int32_t* key_offs, const int32_t* key_lens,
    const uint8_t* val_buf, const int32_t* val_offs, const int32_t* val_lens,
    const int64_t* trailer_override,
    const int32_t* order, int64_t start, int64_t limit,
    int64_t block_size_limit, int64_t restart_interval,
    int32_t ctype, int32_t level,
    int64_t base_file_size, int64_t max_file_size,
    int64_t* block_counts, int64_t* block_payload_lens,
    int64_t* block_raw_lens, int64_t max_blocks,
    uint8_t* out, int64_t out_cap, int64_t* out_len) {
  const Codecs& c = codecs();
  if (ctype == 1 && (!c.snappy_maxlen || !c.snappy_cmp)) return -9;
  if (ctype == 7 && (!c.zstd_cmp || !c.zstd_bound || !c.zstd_err)) return -9;
  if (ctype != 1 && ctype != 7) return -9;
  // level semantics must MATCH the Python path byte-for-byte: the caller
  // passes INT32_MIN for "unset" (Python None -> zstd default 3); real
  // levels — including zstd's valid negative fast levels and 0 — pass
  // through unchanged.
  if (level == INT32_MIN) level = 3;

  // The reference's parallel block compression
  // (ParallelCompressionRep, block_based_table_builder.cc:818-825),
  // one-call form: blocks are CUT serially (entry consumption is
  // data-dependent), compressed in PARALLEL in windows (the per-block
  // raw-vs-compressed choice depends only on that block's bytes, so the
  // output is byte-identical to the serial form), then emitted serially
  // under the exact same file-size/out_cap cut rules. Blocks built past
  // a mid-window cut are discarded — wasted work only at file ends.
  struct Blk {
    std::vector<uint8_t> raw;      // unframed payload
    std::vector<uint8_t> framed;   // payload + type byte + masked crc
    int64_t raw_len = 0;
    int64_t payload_len = 0;
    int64_t count = 0;
    size_t bound = 0;
  };
  size_t nthreads = effective_cpus();
  if (nthreads > 8) nthreads = 8;
  int64_t pos = start;
  int64_t used = 0;
  int64_t nb = 0;
  std::vector<Blk> blks;
  bool stopped = false;
  while (pos < limit && !stopped) {
    // Window ≈ blocks remaining in THIS run's byte budget (callers pass
    // a budget every run, not only at file ends), so speculative
    // compression rarely overshoots the emit cut; capped to bound the
    // transient raw/framed memory at large block sizes.
    int64_t remaining = max_file_size - (base_file_size + used);
    int64_t est_blocks = remaining > 0
        ? remaining / (block_size_limit > 0 ? block_size_limit : 4096) + 2
        : 1;
    int64_t window = nthreads >= 2
        ? std::min<int64_t>(est_blocks, 64 * (int64_t)nthreads)
        : 1;
    if (window * (block_size_limit * 2 + 8192) > (int64_t)(256u << 20))
      window = std::max<int64_t>(
          1, (int64_t)(256u << 20) / (block_size_limit * 2 + 8192));
    // Phase 1: serially cut up to `window` raw blocks (speculative).
    blks.clear();
    try {
      blks.reserve((size_t)window);
    } catch (...) {
      *out_len = used;
      return nb > 0 ? nb : -2;
    }
    int64_t wpos = pos;
    for (int64_t w = 0; w < window && wpos < limit; w++) {
      Blk b;
      int64_t cap = block_size_limit * 2 + 8192;
      int64_t rc = -2;
      for (;;) {
        try {
          b.raw.resize((size_t)cap);
        } catch (...) {
          rc = -2;
          break;
        }
        rc = tpulsm_build_block(
            key_buf, key_offs, key_lens, val_buf, val_offs, val_lens,
            trailer_override, order, wpos, limit,
            block_size_limit, restart_interval,
            b.raw.data(), cap, &b.raw_len);
        if (rc == -2) {
          cap *= 2;
          continue;
        }
        break;
      }
      if (rc <= 0) {
        if (nb == 0 && w == 0) return rc;
        stopped = true;
        break;
      }
      b.count = rc;
      wpos += rc;
      blks.push_back(std::move(b));
    }
    if (blks.empty()) break;

    // Phase 2: parallel compress + frame each block into its own buffer.
    std::atomic<int64_t> next{0};
    std::atomic<int> fail{0};
    auto work = [&] {
      // Per-WORKER compress scratch, grown monotonically and reused
      // across this worker's blocks (a fresh zero-filled vector per
      // block would memset > block_size bytes each time).
      std::vector<uint8_t> cbuf;
      for (;;) {
        int64_t i = next.fetch_add(1, std::memory_order_relaxed);
        if (i >= (int64_t)blks.size()) return;
        Blk& b = blks[(size_t)i];
        size_t bound = ctype == 1 ? c.snappy_maxlen((size_t)b.raw_len)
                                  : c.zstd_bound((size_t)b.raw_len);
        b.bound = bound;
        try {
          if (cbuf.size() < bound) cbuf.resize(bound);
        } catch (...) {
          fail.store(1, std::memory_order_relaxed);
          return;
        }
        bool ok = true;
        size_t clen = bound;
        if (ctype == 1) {
          ok = c.snappy_cmp((const char*)b.raw.data(), (size_t)b.raw_len,
                            (char*)cbuf.data(), &clen) == 0;
        } else {
          clen = c.zstd_cmp(cbuf.data(), bound, b.raw.data(),
                            (size_t)b.raw_len, level);
          ok = !c.zstd_err(clen);
        }
        const uint8_t* payload;
        uint8_t tbyte;
        if (ok && (int64_t)clen < b.raw_len - b.raw_len / 8) {
          payload = cbuf.data();
          b.payload_len = (int64_t)clen;
          tbyte = (uint8_t)ctype;
        } else {
          payload = b.raw.data();
          b.payload_len = b.raw_len;
          tbyte = 0;
        }
        try {
          b.framed.resize((size_t)b.payload_len + 5);
        } catch (...) {
          fail.store(1, std::memory_order_relaxed);
          return;
        }
        std::memcpy(b.framed.data(), payload, (size_t)b.payload_len);
        b.framed[(size_t)b.payload_len] = tbyte;
        uint32_t crc = tpulsm_crc32c_extend(0, b.framed.data(),
                                            (size_t)(b.payload_len + 1));
        uint32_t masked = ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
        std::memcpy(b.framed.data() + b.payload_len + 1, &masked, 4);
      }
    };
    {
      std::vector<std::thread> pool;
      size_t nt = std::min(nthreads, blks.size());
      for (size_t t = 1; t < nt; t++) spawn_or_inline_th(pool, work);
      work();
      for (auto& w : pool) w.join();
    }
    if (fail.load()) {
      *out_len = used;
      return nb > 0 ? nb : -2;
    }

    // Phase 3: serial emit under the EXACT serial-path cut rules.
    for (Blk& b : blks) {
      if (nb > 0) {
        if (base_file_size + used >= max_file_size) {
          stopped = true;
          break;
        }
        if (nb >= max_blocks) {
          stopped = true;
          break;
        }
      }
      // Same bound check the serial form applied before compressing.
      if (used + (int64_t)b.bound + 5 > out_cap) {
        if (nb > 0) {
          stopped = true;
          break;
        }
        return -2;
      }
      if (b.framed[b.framed.size() - 5] == 0 &&
          used + b.raw_len + 5 > out_cap) {
        if (nb > 0) {
          stopped = true;
          break;
        }
        return -2;
      }
      std::memcpy(out + used, b.framed.data(), b.framed.size());
      block_counts[nb] = b.count;
      block_payload_lens[nb] = b.payload_len;
      block_raw_lens[nb] = b.raw_len;
      nb++;
      used += (int64_t)b.framed.size();
      pos += b.count;
    }
  }
  *out_len = used;
  return nb;
}

// Insert every counted record of a WriteBatch WIRE IMAGE (db/write_batch.py
// format: fixed64 seq | fixed32 count | [type][varint klen][key]
// [varint vlen][value]...) into the skiplist — ONE GIL-free ctypes call
// per batch, no per-record Python or numpy. Parses in two passes: a
// validation scan first, so a batch this parser cannot take (non-default
// CF record, range deletion, corruption) is rejected with NOTHING
// inserted and the caller falls back to the Python path.
// Returns inserted count; out[0] = memtable byte delta (k+v+24 per
// record), out[1] = point-delete count. rc: -2 unsupported record,
// -4 corrupt. Concurrency-safe (lock-free splice per record).
// Shared WriteBatch wire-image parse/apply loop: validates the whole
// image on pass 0 (count header, varint bounds, supported record types),
// applies on pass 1 through the insert callback. Returns the record
// count, or -2 (unsupported record: Python path) / -4 (corrupt image).
extern "C++" {
template <typename InsertFn, typename CheckFn>
static int64_t wb_wire_apply_chk(const uint8_t* rep, int64_t len,
                                 uint64_t first_seq, int64_t* out,
                                 InsertFn&& ins, CheckFn&& chk) {
  static const uint8_t kValue = 0x1, kDelete = 0x0, kMerge = 0x2,
                       kSingleDelete = 0x7, kLogData = 0x3,
                       kWideEntity = 0x16;
  if (len < 12) return -4;
  const uint8_t* end = rep + len;
  uint32_t hdr_count = (uint32_t)rep[8] | ((uint32_t)rep[9] << 8) |
                       ((uint32_t)rep[10] << 16) | ((uint32_t)rep[11] << 24);
  for (int pass = 0; pass < 2; pass++) {
    const uint8_t* p = rep + 12;
    uint64_t seq = first_seq;
    int64_t count = 0, delta = 0, deletes = 0;
    while (p < end) {
      uint8_t t = *p++;
      if (t & 0x80) return -2;  // CF-prefixed record: Python path
      uint32_t klen, vlen = 0;
      p = get_varint32(p, end, &klen);
      if (!p || p + klen > end) return -4;
      const uint8_t* k = p;
      p += klen;
      const uint8_t* v = p;
      if (t == kValue || t == kMerge || t == kWideEntity) {
        p = get_varint32(p, end, &vlen);
        if (!p || p + vlen > end) return -4;
        v = p;
        p += vlen;
      } else if (t == kDelete || t == kSingleDelete) {
        // key only
      } else if (t == kLogData) {
        continue;  // not counted, not applied (klen was the blob)
      } else {
        return -2;  // RANGE_DELETION etc.: Python path
      }
      if (pass == 0) {
        // Validation pass: a failing check rejects the WHOLE batch with
        // nothing inserted (-5 - index of the offending record).
        if (!chk(count, t, k, klen, v, vlen)) return -5 - count;
      } else {
        uint64_t inv = ~((seq << 8) | (uint64_t)t);
        ins(k, klen, inv, v, vlen);
        delta += (int64_t)klen + vlen + 24;
        if (t == kDelete || t == kSingleDelete) deletes++;
      }
      seq++;
      count++;
    }
    if (pass == 0) {
      if ((uint32_t)count != hdr_count) return -4;
    } else {
      out[0] = delta;
      out[1] = deletes;
      return count;
    }
  }
  return -4;  // unreachable
}

template <typename InsertFn>
static int64_t wb_wire_apply(const uint8_t* rep, int64_t len,
                             uint64_t first_seq, int64_t* out,
                             InsertFn&& ins) {
  return wb_wire_apply_chk(
      rep, len, first_seq, out, static_cast<InsertFn&&>(ins),
      [](int64_t, uint8_t, const uint8_t*, uint32_t, const uint8_t*,
         uint32_t) { return true; });
}
}  // extern "C++"

int64_t tpulsm_skiplist_insert_wb(void* h, const uint8_t* rep, int64_t len,
                                  uint64_t first_seq, int64_t* out) {
  SkipList* sl = static_cast<SkipList*>(h);
  return wb_wire_apply(rep, len, first_seq, out,
                       [sl](const uint8_t* k, uint32_t kl, uint64_t inv,
                            const uint8_t* v, uint32_t vl) {
                         sl->insert(k, kl, inv, v, vl);
                       });
}

// ---------------------------------------------------------------------------
// Per-entry protection info (utils/protection.py): one native pass over a
// WriteBatch wire image computing every counted record's checksum — the
// write path's integrity hot loop (compute at batch build, re-verify at
// the batch->memtable handoff) without per-record Python. The hash MUST
// bit-match utils/protection.py: zlib crc32 per component, one
// multiply-xorshift lane mix, XOR of key/value/type/cf components.
// ---------------------------------------------------------------------------

extern "C++" {
namespace {

// zlib/IEEE crc32 (poly 0xEDB88320 reflected), slicing-by-8.
struct ZCrcTables {
  uint32_t t[8][256];
  ZCrcTables() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
      for (int j = 1; j < 8; j++)
        t[j][i] = (t[j - 1][i] >> 8) ^ t[0][t[j - 1][i] & 0xFF];
  }
};

static inline uint32_t zcrc32(const uint8_t* p, size_t n) {
  static const ZCrcTables T;
  uint32_t c = 0xFFFFFFFFu;
  while (n >= 8) {
    uint32_t lo;
    uint32_t hi;
    memcpy(&lo, p, 4);
    memcpy(&hi, p + 4, 4);
    lo ^= c;
    c = T.t[7][lo & 0xFF] ^ T.t[6][(lo >> 8) & 0xFF] ^
        T.t[5][(lo >> 16) & 0xFF] ^ T.t[4][lo >> 24] ^
        T.t[3][hi & 0xFF] ^ T.t[2][(hi >> 8) & 0xFF] ^
        T.t[1][(hi >> 16) & 0xFF] ^ T.t[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) c = T.t[0][(c ^ *p++) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

static inline uint64_t prot_mix(uint64_t x) {
  x *= 0xBF58476D1CE4E5B9ull;
  return x ^ (x >> 29);
}

}  // namespace
}  // extern "C++"

// Computes the truncated protection checksum of every counted record in
// `rep` (a WriteBatch wire image, header included) into out[0..count).
// `strip_cf` != 0 emits the CF-stripped (cf=0) memtable-carried form.
// Returns the record count, or -3 (out_cap too small) / -4 (corrupt).
int64_t tpulsm_wb_protect(const uint8_t* rep, int64_t len, int32_t pb,
                          int32_t strip_cf, uint64_t* out, int64_t out_cap) {
  static const uint8_t kValue = 0x1, kDelete = 0x0, kMerge = 0x2,
                       kSingleDelete = 0x7, kLogData = 0x3,
                       kRangeDel = 0xF, kWideEntity = 0x16;
  const uint64_t kKey = 0x9E3779B97F4A7C15ull, kVal = 0xC2B2AE3D27D4EB4Full,
                 kType = 0x165667B19E3779F9ull, kCf = 0x27D4EB2F165667C5ull;
  if (len < 12) return -4;
  const uint8_t* end = rep + len;
  const uint8_t* p = rep + 12;
  uint32_t hdr_count = (uint32_t)rep[8] | ((uint32_t)rep[9] << 8) |
                       ((uint32_t)rep[10] << 16) | ((uint32_t)rep[11] << 24);
  const uint64_t mask =
      (pb >= 8 || pb <= 0) ? ~0ull : ((1ull << (8 * pb)) - 1);
  const uint64_t empty_val_term = prot_mix(kVal ^ (uint64_t)zcrc32(p, 0));
  int64_t count = 0;
  while (p < end) {
    uint8_t t = *p++;
    uint32_t cf = 0;
    if ((t & 0x80) && t != kLogData) {
      t &= 0x7F;
      p = get_varint32(p, end, &cf);
      if (!p) return -4;
    }
    uint32_t klen;
    const uint8_t* kp = p = get_varint32(p, end, &klen);
    if (!p || p + klen > end) return -4;
    p += klen;
    if (t == kLogData) continue;  // not counted, not protected
    uint64_t vterm = empty_val_term;
    if (t == kValue || t == kMerge || t == kWideEntity || t == kRangeDel) {
      uint32_t vlen;
      const uint8_t* vp = p = get_varint32(p, end, &vlen);
      if (!p || p + vlen > end) return -4;
      p += vlen;
      vterm = prot_mix(kVal ^ (uint64_t)zcrc32(vp, vlen) ^
                       ((uint64_t)vlen << 32));
    } else if (t != kDelete && t != kSingleDelete) {
      return -4;  // unknown record type
    }
    if (count >= out_cap) return -3;
    uint64_t cs = prot_mix(kKey ^ (uint64_t)zcrc32(kp, klen) ^
                           ((uint64_t)klen << 32)) ^
                  vterm ^ prot_mix(kType ^ (uint64_t)t) ^
                  prot_mix(kCf ^ (uint64_t)((strip_cf ? 0 : cf) + 1));
    out[count++] = cs & mask;
  }
  if ((uint32_t)count != hdr_count) return -4;
  return count;
}

// XOR-aggregate protection over a columnar export (INTERNAL keys: user key
// + 8B packed trailer). Computes each entry's CF-0 truncated checksum —
// bit-identical to utils/protection.py protect_entry(t, uk, v) — and folds
// them into *xor_out. XOR is the right aggregate because the checksum is
// already XOR-composable per component: equality of (count, xor) against
// the memtable's carried side proves the flush export intact without a
// per-entry Python walk; on mismatch the caller re-walks per entry for the
// precise culprit. Returns n, or -4 on a malformed (short) internal key.
int64_t tpulsm_columnar_protect(const uint8_t* key_buf,
                                const int32_t* key_offs,
                                const int32_t* key_lens,
                                const uint8_t* val_buf,
                                const int32_t* val_offs,
                                const int32_t* val_lens,
                                const int32_t* vtypes, int64_t n, int32_t pb,
                                uint64_t* xor_out) {
  const uint64_t kKey = 0x9E3779B97F4A7C15ull, kVal = 0xC2B2AE3D27D4EB4Full,
                 kType = 0x165667B19E3779F9ull, kCf = 0x27D4EB2F165667C5ull;
  const uint64_t mask =
      (pb >= 8 || pb <= 0) ? ~0ull : ((1ull << (8 * pb)) - 1);
  const uint64_t cf_term = prot_mix(kCf ^ 1ull);
  uint64_t acc = 0;
  for (int64_t i = 0; i < n; i++) {
    if (key_lens[i] < 8) return -4;
    uint32_t uklen = (uint32_t)key_lens[i] - 8;
    uint32_t vlen = (uint32_t)val_lens[i];
    uint64_t cs =
        prot_mix(kKey ^ (uint64_t)zcrc32(key_buf + key_offs[i], uklen) ^
                 ((uint64_t)uklen << 32)) ^
        prot_mix(kVal ^ (uint64_t)zcrc32(val_buf + val_offs[i], vlen) ^
                 ((uint64_t)vlen << 32)) ^
        prot_mix(kType ^ (uint64_t)(uint8_t)vtypes[i]) ^ cf_term;
    acc ^= cs & mask;
  }
  *xor_out = acc;
  return n;
}

extern "C++" {
namespace {

// Pass-0 record check for the fused verify+insert wire apply: recomputes
// the CF-0 protection checksum of each counted record and compares it to
// the batch's carried vector. Default-CF only (wb_wire_apply already
// rejects CF-prefixed records with -2 before this runs).
struct ProtCheck {
  const uint64_t* prots;
  int64_t n;
  uint64_t mask;
  bool operator()(int64_t i, uint8_t t, const uint8_t* k, uint32_t kl,
                  const uint8_t* v, uint32_t vl) const {
    const uint64_t kKey = 0x9E3779B97F4A7C15ull, kVal = 0xC2B2AE3D27D4EB4Full,
                   kType = 0x165667B19E3779F9ull, kCf = 0x27D4EB2F165667C5ull;
    if (i >= n) return false;
    uint64_t cs = prot_mix(kKey ^ (uint64_t)zcrc32(k, kl) ^
                           ((uint64_t)kl << 32)) ^
                  prot_mix(kVal ^ (uint64_t)zcrc32(v, vl) ^
                           ((uint64_t)vl << 32)) ^
                  prot_mix(kType ^ (uint64_t)t) ^ prot_mix(kCf ^ 1ull);
    return (cs & mask) == prots[i];
  }
};

inline uint64_t prot_trunc_mask(int32_t pb) {
  return (pb >= 8 || pb <= 0) ? ~0ull : ((1ull << (8 * pb)) - 1);
}

}  // namespace
}  // extern "C++"

// Fused verify+insert: ONE call re-hashes every counted record against
// `prots` (validation pass — a mismatch rejects the whole batch with
// NOTHING inserted, rc = -5 - bad_index) then inserts (apply pass). This
// keeps the protected write path at one native crossing per batch instead
// of verify + insert as two (each re-parsing the wire image from Python).
int64_t tpulsm_skiplist_insert_wb_prot(void* h, const uint8_t* rep,
                                       int64_t len, uint64_t first_seq,
                                       const uint64_t* prots, int64_t n_prots,
                                       int32_t pb, int64_t* out) {
  SkipList* sl = static_cast<SkipList*>(h);
  int64_t rc = wb_wire_apply_chk(
      rep, len, first_seq, out,
      [sl](const uint8_t* k, uint32_t kl, uint64_t inv, const uint8_t* v,
           uint32_t vl) { sl->insert(k, kl, inv, v, vl); },
      ProtCheck{prots, n_prots, prot_trunc_mask(pb)});
  if (rc >= 0 && rc != n_prots) return -5 - rc;  // carried vector too long
  return rc;
}
// Crash-Safe Parallel Patricia trie memtable, the 45M ops/s headline
// component; main-tree seam include/rocksdb/memtablerep.h:309).
//
// Design is our own, NOT a port: an adaptive radix tree (4/16/48/256-way
// nodes with path compression) per FIRST-BYTE STRIPE — 257 independent
// roots (one per leading byte + one for the empty key), each under its
// own mutex, so concurrent writers on different key regions never
// contend, and in-stripe descent is mutex-simple rather than lock-free.
// A leaf holds one USER KEY and its version list sorted by inv
// ((~(seq<<8|type))) ascending == seqno descending — the memtable order.
// Versions carry a back-pointer to their leaf, so a position handle is
// just a Ver*, and the stateless successor re-descends from the root
// (O(key) — iteration is the cold path; inserts are the hot one).
// ---------------------------------------------------------------------------

extern "C++" {  // templates may not have C linkage
namespace {

struct TVer {
  uint64_t inv;
  std::atomic<const uint8_t*> val;  // [u32 len][bytes] arena record
  // Readers traverse version lists WITHOUT the stripe mutex (the tree
  // descent locks; the returned leaf's list does not), while writers
  // publish under it — so the links are release-published atomics like
  // the skiplist's next pointers.
  std::atomic<TVer*> next;          // next-older (inv ascending)
  struct TLeafHdr* leaf;
};

struct TLeafHdr {
  const uint8_t* key;  // FULL user key (arena copy)
  uint32_t key_len;
  std::atomic<TVer*> head;
};

struct TNode {
  uint16_t ntype;       // 4, 16, 48, 256
  uint16_t nkeys;
  uint32_t prefix_len;
  const uint8_t* prefix;
  TLeafHdr* leaf;       // key ending exactly after this node's prefix
  // N4/N16: keys[] + children[] parallel (sorted); N48: index[256] into
  // children; N256: children[256].
  uint8_t* keys;        // N4/N16: size ntype; N48: 256-byte index
  TNode** children;     // size ntype (N48: 48, N256: 256)
};

struct TrieStripe {
  std::mutex mu;
  Arena arena;
  TNode* root = nullptr;
};

struct TrieRep {
  TrieStripe stripes[257];  // [b] = keys starting with byte b; [256] = ""
  std::atomic<int64_t> count{0};

  int64_t memory() {
    // Handed-out bytes, not block caps: the flush/WBM charge tracks real
    // content + node overhead without penalizing half-filled blocks
    // (geometric growth bounds the cap/handed gap to <2x anyway).
    int64_t m = 0;
    for (auto& s : stripes)
      m += (int64_t)s.arena.handed.load(std::memory_order_relaxed);
    return m;
  }
};

TNode* tnode_new(Arena& a, uint16_t ntype, const uint8_t* prefix,
                 uint32_t plen) {
  TNode* n = (TNode*)a.alloc(sizeof(TNode));
  n->ntype = ntype;
  n->nkeys = 0;
  n->prefix_len = plen;
  if (plen) {
    uint8_t* p = a.alloc(plen);
    std::memcpy(p, prefix, plen);
    n->prefix = p;
  } else {
    n->prefix = nullptr;
  }
  n->leaf = nullptr;
  if (ntype == 4 || ntype == 16) {
    // LAZY arrays: tail nodes (one per unique key suffix) never gain a
    // child — not allocating keys/children until the first tnode_add
    // saves ~40B on the dominant node population.
    n->keys = nullptr;
    n->children = nullptr;
  } else if (ntype == 48) {
    n->keys = a.alloc(256);
    std::memset(n->keys, 0xFF, 256);
    n->children = (TNode**)a.alloc(sizeof(TNode*) * 48);
  } else {
    n->keys = nullptr;
    n->children = (TNode**)a.alloc(sizeof(TNode*) * 256);
    std::memset(n->children, 0, sizeof(TNode*) * 256);
  }
  return n;
}

TNode** tnode_find(TNode* n, uint8_t c) {
  if (n->ntype == 4 || n->ntype == 16) {
    for (uint16_t i = 0; i < n->nkeys; i++)
      if (n->keys[i] == c) return &n->children[i];
    return nullptr;
  }
  if (n->ntype == 48) {
    return n->keys[c] == 0xFF ? nullptr : &n->children[n->keys[c]];
  }
  return n->children[c] ? &n->children[c] : nullptr;
}

// Grow n to the next node size, copying children. Returns the new node
// (caller re-links the parent slot).
TNode* tnode_grow(Arena& a, TNode* n) {
  if (n->ntype == 4 || n->ntype == 16) {
    uint16_t nt = n->ntype == 4 ? 16 : 48;
    TNode* g = tnode_new(a, nt, n->prefix, n->prefix_len);
    g->leaf = n->leaf;
    if (nt == 16) {
      // tnode_new leaves N16 arrays lazy — materialize before copying.
      g->keys = a.alloc(16);
      g->children = (TNode**)a.alloc(sizeof(TNode*) * 16);
      std::memcpy(g->keys, n->keys, n->nkeys);
      std::memcpy(g->children, n->children, sizeof(TNode*) * n->nkeys);
      g->nkeys = n->nkeys;
    } else {
      for (uint16_t i = 0; i < n->nkeys; i++) {
        g->keys[n->keys[i]] = (uint8_t)i;
        g->children[i] = n->children[i];
      }
      g->nkeys = n->nkeys;
    }
    return g;
  }
  // 48 -> 256
  TNode* g = tnode_new(a, 256, n->prefix, n->prefix_len);
  g->leaf = n->leaf;
  for (int c = 0; c < 256; c++)
    if (n->keys[c] != 0xFF) g->children[c] = n->children[n->keys[c]];
  g->nkeys = n->nkeys;
  return g;
}

// Add child c to n (must not exist); may replace n via growth.
void tnode_add(Arena& a, TNode** slot, uint8_t c, TNode* child) {
  TNode* n = *slot;
  if ((n->ntype == 4 || n->ntype == 16 || n->ntype == 48) &&
      n->nkeys >= (n->ntype == 48 ? 48 : n->ntype)) {
    n = tnode_grow(a, n);
    *slot = n;
  }
  if (n->ntype == 4 || n->ntype == 16) {
    if (!n->keys) {  // lazily materialize (see tnode_new)
      n->keys = a.alloc(n->ntype);
      n->children = (TNode**)a.alloc(sizeof(TNode*) * n->ntype);
    }
    uint16_t i = n->nkeys;
    while (i > 0 && n->keys[i - 1] > c) {
      n->keys[i] = n->keys[i - 1];
      n->children[i] = n->children[i - 1];
      i--;
    }
    n->keys[i] = c;
    n->children[i] = child;
    n->nkeys++;
  } else if (n->ntype == 48) {
    n->keys[c] = (uint8_t)n->nkeys;
    n->children[n->nkeys] = child;
    n->nkeys++;
  } else {
    n->children[c] = child;
    n->nkeys++;
  }
}

void tleaf_set_val(Arena& a, TVer* v, const uint8_t* val, uint32_t vl) {
  uint8_t* rec = a.alloc(4 + vl);
  std::memcpy(rec, &vl, 4);
  if (vl) std::memcpy(rec + 4, val, vl);
  v->val.store(rec, std::memory_order_release);
}

// Insert a version into leaf's inv-ascending list; replace on exact dup.
// Returns 1 on fresh insert. Writer-side only (stripe mutex held); the
// new node is fully initialized before the release-publish, so lockless
// readers see either the old list or the complete new one.
int tleaf_add(Arena& a, TLeafHdr* lf, uint64_t inv, const uint8_t* val,
              uint32_t vl) {
  std::atomic<TVer*>* pp = &lf->head;
  TVer* cur = pp->load(std::memory_order_relaxed);
  while (cur && cur->inv < inv) {
    pp = &cur->next;
    cur = pp->load(std::memory_order_relaxed);
  }
  if (cur && cur->inv == inv) {
    tleaf_set_val(a, cur, val, vl);  // WAL-replay duplicate: replace
    return 0;
  }
  TVer* v = (TVer*)a.alloc(sizeof(TVer));
  v->inv = inv;
  v->next.store(cur, std::memory_order_relaxed);
  v->leaf = lf;
  tleaf_set_val(a, v, val, vl);
  pp->store(v, std::memory_order_release);
  return 1;
}

TLeafHdr* tleaf_new(Arena& a, const uint8_t* full_key, uint32_t kl) {
  TLeafHdr* lf = (TLeafHdr*)a.alloc(sizeof(TLeafHdr));
  uint8_t* kc = a.alloc(kl);
  if (kl) std::memcpy(kc, full_key, kl);
  lf->key = kc;
  lf->key_len = kl;
  lf->head.store(nullptr, std::memory_order_relaxed);
  return lf;
}

// Insert (full user key, inv, value) into one stripe (mutex held).
// `k`/`kl` exclude the stripe byte; `fk`/`fkl` are the full key.
int trie_insert_locked(TrieStripe& st, const uint8_t* k, uint32_t kl,
                       const uint8_t* fk, uint32_t fkl, uint64_t inv,
                       const uint8_t* val, uint32_t vl) {
  Arena& a = st.arena;
  if (!st.root) st.root = tnode_new(a, 4, nullptr, 0);
  TNode** slot = &st.root;
  uint32_t d = 0;
  while (true) {
    TNode* n = *slot;
    uint32_t m = 0;
    uint32_t rem = kl - d;
    while (m < n->prefix_len && m < rem && n->prefix[m] == k[d + m]) m++;
    if (m < n->prefix_len) {
      // Split: parent keeps prefix[0..m); old node trims to m+1..;
      // the new key either ends at the split (parent leaf) or branches.
      TNode* parent = tnode_new(a, 4, n->prefix, m);
      uint8_t old_c = n->prefix[m];
      // trim n's prefix in place
      n->prefix = n->prefix + m + 1;
      n->prefix_len -= m + 1;
      tnode_add(a, &parent, old_c, n);
      if (rem == m) {
        parent->leaf = tleaf_new(a, fk, fkl);
        *slot = parent;
        return tleaf_add(a, parent->leaf, inv, val, vl);
      }
      TNode* nb = tnode_new(a, 4, k + d + m + 1, rem - m - 1);
      nb->leaf = tleaf_new(a, fk, fkl);
      tnode_add(a, &parent, k[d + m], nb);
      *slot = parent;
      return tleaf_add(a, nb->leaf, inv, val, vl);
    }
    d += n->prefix_len;
    if (d == kl) {
      if (!n->leaf) n->leaf = tleaf_new(a, fk, fkl);
      return tleaf_add(a, n->leaf, inv, val, vl);
    }
    uint8_t c = k[d];
    TNode** child = tnode_find(n, c);
    if (!child) {
      TNode* nb = tnode_new(a, 4, k + d + 1, kl - d - 1);
      nb->leaf = tleaf_new(a, fk, fkl);
      tnode_add(a, slot, c, nb);
      return tleaf_add(a, nb->leaf, inv, val, vl);
    }
    slot = child;
    d++;
  }
}

int trie_insert(TrieRep* t, const uint8_t* k, uint32_t kl, uint64_t inv,
                const uint8_t* val, uint32_t vl) {
  int s = kl ? k[0] : 256;
  TrieStripe& st = t->stripes[s];
  std::lock_guard<std::mutex> g(st.mu);
  int fresh = trie_insert_locked(st, kl ? k + 1 : k, kl ? kl - 1 : 0,
                                 k, kl, inv, val, vl);
  if (fresh) t->count.fetch_add(1, std::memory_order_relaxed);
  return fresh;
}

// Smallest / largest leaf of a subtree (descending by child order).
TLeafHdr* tmin_leaf(TNode* n) {
  while (n) {
    if (n->leaf) return n->leaf;  // key-ends-here sorts before children
    if (n->ntype == 4 || n->ntype == 16) {
      n = n->nkeys ? n->children[0] : nullptr;
    } else if (n->ntype == 48) {
      TNode* nx = nullptr;
      for (int c = 0; c < 256 && !nx; c++)
        if (n->keys[c] != 0xFF) nx = n->children[n->keys[c]];
      n = nx;
    } else {
      TNode* nx = nullptr;
      for (int c = 0; c < 256 && !nx; c++)
        if (n->children[c]) nx = n->children[c];
      n = nx;
    }
  }
  return nullptr;
}

TLeafHdr* tmax_leaf(TNode* n) {
  TLeafHdr* best = nullptr;
  while (n) {
    TNode* nx = nullptr;
    if (n->ntype == 4 || n->ntype == 16) {
      nx = n->nkeys ? n->children[n->nkeys - 1] : nullptr;
    } else if (n->ntype == 48) {
      for (int c = 255; c >= 0 && !nx; c--)
        if (n->keys[c] != 0xFF) nx = n->children[n->keys[c]];
    } else {
      for (int c = 255; c >= 0 && !nx; c--)
        if (n->children[c]) nx = n->children[c];
    }
    if (!nx) return n->leaf ? n->leaf : best;
    if (n->leaf) best = n->leaf;  // deeper children are LARGER than leaf
    n = nx;
  }
  return best;
}

// First leaf with key >= probe within one stripe (nullptr if none).
TLeafHdr* trie_lower_bound(TNode* root, const uint8_t* k, uint32_t kl) {
  TNode* n = root;
  uint32_t d = 0;
  TLeafHdr* succ = nullptr;  // min leaf of the nearest greater subtree
  while (n) {
    uint32_t rem = kl - d;
    uint32_t m = 0;
    while (m < n->prefix_len && m < rem && n->prefix[m] == k[d + m]) m++;
    if (m < n->prefix_len) {
      if (m == rem || k[d + m] < n->prefix[m]) return tmin_leaf(n);
      return succ;  // whole subtree < probe
    }
    d += n->prefix_len;
    if (d == kl) return tmin_leaf(n);  // node's min is >= probe
    uint8_t c = k[d];
    // Successor candidate: smallest child byte > c.
    TNode* nx_gt = nullptr;
    if (n->ntype == 4 || n->ntype == 16) {
      for (uint16_t i = 0; i < n->nkeys; i++)
        if (n->keys[i] > c) { nx_gt = n->children[i]; break; }
    } else if (n->ntype == 48) {
      for (int b = c + 1; b < 256 && !nx_gt; b++)
        if (n->keys[b] != 0xFF) nx_gt = n->children[n->keys[b]];
    } else {
      for (int b = c + 1; b < 256 && !nx_gt; b++)
        if (n->children[b]) nx_gt = n->children[b];
    }
    if (nx_gt) {
      TLeafHdr* lm = tmin_leaf(nx_gt);
      if (lm) succ = lm;
    }
    TNode** child = tnode_find(n, c);
    if (!child) return succ;
    n = *child;
    d++;
  }
  return succ;
}

// Last leaf with key strictly < probe within one stripe.
TLeafHdr* trie_pred(TNode* root, const uint8_t* k, uint32_t kl) {
  TNode* n = root;
  uint32_t d = 0;
  TLeafHdr* pred = nullptr;
  while (n) {
    uint32_t rem = kl - d;
    uint32_t m = 0;
    while (m < n->prefix_len && m < rem && n->prefix[m] == k[d + m]) m++;
    if (m < n->prefix_len) {
      if (m == rem || k[d + m] < n->prefix[m]) return pred;
      return tmax_leaf(n);  // whole subtree < probe
    }
    d += n->prefix_len;
    if (d == kl) return pred;  // node min == probe's position
    if (n->leaf) pred = n->leaf;  // "ends here" < any longer key
    uint8_t c = k[d];
    TNode* nx_lt = nullptr;
    if (n->ntype == 4 || n->ntype == 16) {
      for (int i = (int)n->nkeys - 1; i >= 0; i--)
        if (n->keys[i] < c) { nx_lt = n->children[i]; break; }
    } else if (n->ntype == 48) {
      for (int b = c - 1; b >= 0 && !nx_lt; b--)
        if (n->keys[b] != 0xFF) nx_lt = n->children[n->keys[b]];
    } else {
      for (int b = c - 1; b >= 0 && !nx_lt; b--)
        if (n->children[b]) nx_lt = n->children[b];
    }
    if (nx_lt) {
      TLeafHdr* lm = tmax_leaf(nx_lt);
      if (lm) pred = lm;
    }
    TNode** child = tnode_find(n, c);
    if (!child) return pred;
    n = *child;
    d++;
  }
  return pred;
}

// Stripe-aware leaf lookups over the whole rep.
TLeafHdr* trie_leaf_ge(TrieRep* t, const uint8_t* k, uint32_t kl) {
  int s0 = kl ? k[0] : 256;
  if (s0 == 256) {  // empty probe: empty-key stripe first, then 0..255
    TrieStripe& se = t->stripes[256];
    {
      std::lock_guard<std::mutex> g(se.mu);
      if (se.root) {
        TLeafHdr* lf = tmin_leaf(se.root);
        if (lf) return lf;
      }
    }
    for (int s = 0; s < 256; s++) {
      std::lock_guard<std::mutex> g(t->stripes[s].mu);
      if (t->stripes[s].root) {
        TLeafHdr* lf = tmin_leaf(t->stripes[s].root);
        if (lf) return lf;
      }
    }
    return nullptr;
  }
  {
    TrieStripe& st = t->stripes[s0];
    std::lock_guard<std::mutex> g(st.mu);
    if (st.root) {
      TLeafHdr* lf = trie_lower_bound(st.root, k + 1, kl - 1);
      if (lf) return lf;
    }
  }
  for (int s = s0 + 1; s < 256; s++) {
    std::lock_guard<std::mutex> g(t->stripes[s].mu);
    if (t->stripes[s].root) {
      TLeafHdr* lf = tmin_leaf(t->stripes[s].root);
      if (lf) return lf;
    }
  }
  return nullptr;
}

TLeafHdr* trie_leaf_lt(TrieRep* t, const uint8_t* k, uint32_t kl) {
  int s0 = kl ? k[0] : 256;
  if (s0 != 256) {
    TrieStripe& st = t->stripes[s0];
    std::lock_guard<std::mutex> g(st.mu);
    if (st.root) {
      TLeafHdr* lf = trie_pred(st.root, k + 1, kl - 1);
      if (lf) return lf;
    }
  }
  int hi = s0 == 256 ? -1 : s0 - 1;  // empty key: nothing precedes
  for (int s = hi; s >= 0; s--) {
    std::lock_guard<std::mutex> g(t->stripes[s].mu);
    if (t->stripes[s].root) {
      TLeafHdr* lf = tmax_leaf(t->stripes[s].root);
      if (lf) return lf;
    }
  }
  if (s0 != 256) {  // empty-key stripe precedes every non-empty key
    TrieStripe& se = t->stripes[256];
    std::lock_guard<std::mutex> g(se.mu);
    if (se.root) {
      TLeafHdr* lf = tmax_leaf(se.root);
      if (lf) return lf;
    }
  }
  return nullptr;
}

// DFS export of one stripe (mutex held by caller), leaves in key order.
template <typename F>
void trie_walk(TNode* n, F&& fn) {
  if (!n) return;
  if (n->leaf) fn(n->leaf);
  if (n->ntype == 4 || n->ntype == 16) {
    for (uint16_t i = 0; i < n->nkeys; i++) trie_walk(n->children[i], fn);
  } else if (n->ntype == 48) {
    for (int c = 0; c < 256; c++)
      if (n->keys[c] != 0xFF) trie_walk(n->children[n->keys[c]], fn);
  } else {
    for (int c = 0; c < 256; c++)
      if (n->children[c]) trie_walk(n->children[c], fn);
  }
}

template <typename F>
void trie_walk_all(TrieRep* t, F&& fn) {
  {
    // The empty key sorts before every non-empty key.
    TrieStripe& se = t->stripes[256];
    std::lock_guard<std::mutex> g(se.mu);
    trie_walk(se.root, fn);
  }
  for (int s = 0; s < 256; s++) {
    TrieStripe& st = t->stripes[s];
    std::lock_guard<std::mutex> g(st.mu);
    trie_walk(st.root, fn);
  }
}

}  // namespace
}  // extern "C++"

void* tpulsm_trie_new() {
  TrieRep* t = new (std::nothrow) TrieRep();
  if (t) {
    // Per-stripe arenas start small (16KiB, doubling to 1MiB): most of
    // the 257 stripes see few keys.
    for (auto& s : t->stripes) s.arena.min_block = 16u << 10;
  }
  return t;
}
void tpulsm_trie_free(void* h) { delete static_cast<TrieRep*>(h); }

int32_t tpulsm_trie_insert(void* h, const uint8_t* k, uint32_t kl,
                           uint64_t inv, const uint8_t* v, uint32_t vl) {
  return trie_insert(static_cast<TrieRep*>(h), k, kl, inv, v, vl);
}

int64_t tpulsm_trie_count(void* h) {
  return static_cast<TrieRep*>(h)->count.load(std::memory_order_relaxed);
}

int64_t tpulsm_trie_memory(void* h) {
  return static_cast<TrieRep*>(h)->memory();
}

int64_t tpulsm_trie_insert_batch(
    void* h, const uint8_t* keybuf, const int64_t* key_offs,
    const int32_t* key_lens, const uint64_t* invs, const uint8_t* valbuf,
    const int64_t* val_offs, const int32_t* val_lens, int64_t n) {
  TrieRep* t = static_cast<TrieRep*>(h);
  int64_t fresh = 0;
  for (int64_t i = 0; i < n; i++) {
    fresh += trie_insert(t, keybuf + key_offs[i], (uint32_t)key_lens[i],
                         invs[i], valbuf + val_offs[i],
                         (uint32_t)val_lens[i]);
  }
  return fresh;
}

int64_t tpulsm_trie_insert_wb(void* h, const uint8_t* rep, int64_t len,
                              uint64_t first_seq, int64_t* out) {
  TrieRep* t = static_cast<TrieRep*>(h);
  return wb_wire_apply(rep, len, first_seq, out,
                       [t](const uint8_t* k, uint32_t kl, uint64_t inv,
                           const uint8_t* v, uint32_t vl) {
                         trie_insert(t, k, kl, inv, v, vl);
                       });
}

int64_t tpulsm_trie_insert_wb_prot(void* h, const uint8_t* rep, int64_t len,
                                   uint64_t first_seq, const uint64_t* prots,
                                   int64_t n_prots, int32_t pb, int64_t* out) {
  TrieRep* t = static_cast<TrieRep*>(h);
  int64_t rc = wb_wire_apply_chk(
      rep, len, first_seq, out,
      [t](const uint8_t* k, uint32_t kl, uint64_t inv, const uint8_t* v,
          uint32_t vl) { trie_insert(t, k, kl, inv, v, vl); },
      ProtCheck{prots, n_prots, prot_trunc_mask(pb)});
  if (rc >= 0 && rc != n_prots) return -5 - rc;  // carried vector too long
  return rc;
}

// Position protocol: a position is a TVer*. seek_ge finds the first
// (key, inv) pair >= probe; next follows the version list, then
// re-descends for the successor key (stateless).
void* tpulsm_trie_seek_ge(void* h, const uint8_t* k, uint32_t kl,
                          uint64_t inv) {
  TrieRep* t = static_cast<TrieRep*>(h);
  TLeafHdr* lf = trie_leaf_ge(t, k, kl);
  while (lf) {
    if ((lf->key_len == kl && kl && std::memcmp(lf->key, k, kl) == 0)
        || (lf->key_len == 0 && kl == 0)) {
      for (TVer* v = lf->head.load(std::memory_order_acquire); v;
           v = v->next.load(std::memory_order_acquire))
        if (v->inv >= inv) return v;
    } else {
      return lf->head.load(std::memory_order_acquire);  // greater key
    }
    // Same key exhausted below inv: successor key = first leaf > key.
    // Re-probe with key + 0x00 appended (smallest strict extension).
    std::string tmp((const char*)lf->key, lf->key_len);
    tmp.push_back('\0');
    TLeafHdr* nx = trie_leaf_ge(t, (const uint8_t*)tmp.data(),
                                (uint32_t)tmp.size());
    if (nx == lf) return nullptr;  // defensive; cannot match
    lf = nx;
    if (lf) return lf->head.load(std::memory_order_acquire);
    return nullptr;
  }
  return nullptr;
}

void* tpulsm_trie_first(void* h) {
  TrieRep* t = static_cast<TrieRep*>(h);
  TLeafHdr* lf = trie_leaf_ge(t, nullptr, 0);
  return lf ? lf->head.load(std::memory_order_acquire) : nullptr;
}

void* tpulsm_trie_last(void* h) {
  TrieRep* t = static_cast<TrieRep*>(h);
  for (int s = 255; s >= 0; s--) {
    std::lock_guard<std::mutex> g(t->stripes[s].mu);
    if (t->stripes[s].root) {
      TLeafHdr* lf = tmax_leaf(t->stripes[s].root);
      if (lf) {
        TVer* v = lf->head.load(std::memory_order_acquire);
        while (v) {
          TVer* nx = v->next.load(std::memory_order_acquire);
          if (!nx) break;
          v = nx;
        }
        return v;
      }
    }
  }
  {
    std::lock_guard<std::mutex> g(t->stripes[256].mu);
    if (t->stripes[256].root) {
      TLeafHdr* lf = tmax_leaf(t->stripes[256].root);
      if (lf) {
        TVer* v = lf->head.load(std::memory_order_acquire);
        while (v) {
          TVer* nx = v->next.load(std::memory_order_acquire);
          if (!nx) break;
          v = nx;
        }
        return v;
      }
    }
  }
  return nullptr;
}

void* tpulsm_trie_next(void* h, void* pos) {
  TVer* v = static_cast<TVer*>(pos);
  TVer* nv = v->next.load(std::memory_order_acquire);
  if (nv) return nv;
  TLeafHdr* lf = v->leaf;
  TrieRep* t = static_cast<TrieRep*>(h);
  std::string tmp((const char*)lf->key, lf->key_len);
  tmp.push_back('\0');
  TLeafHdr* nx = trie_leaf_ge(t, (const uint8_t*)tmp.data(),
                              (uint32_t)tmp.size());
  return nx ? nx->head.load(std::memory_order_acquire) : nullptr;
}

// Last (key, inv) strictly BEFORE the probe pair.
void* tpulsm_trie_seek_lt(void* h, const uint8_t* k, uint32_t kl,
                          uint64_t inv) {
  TrieRep* t = static_cast<TrieRep*>(h);
  // Same-key versions with v->inv < inv come first (they sort before).
  TLeafHdr* lf = nullptr;
  {
    int s0 = kl ? k[0] : 256;
    TrieStripe& st = t->stripes[s0];
    std::lock_guard<std::mutex> g(st.mu);
    if (st.root) {
      // exact-key leaf?
      TLeafHdr* cand =
          s0 == 256 ? (st.root->prefix_len == 0 ? st.root->leaf : nullptr)
                    : trie_lower_bound(st.root, k + 1, kl - 1);
      if (cand && cand->key_len == kl &&
          (kl == 0 || std::memcmp(cand->key, k, kl) == 0))
        lf = cand;
    }
  }
  if (lf) {
    TVer* best = nullptr;
    for (TVer* v = lf->head.load(std::memory_order_acquire);
         v && v->inv < inv; v = v->next.load(std::memory_order_acquire))
      best = v;
    if (best) return best;
  }
  TLeafHdr* pl = trie_leaf_lt(t, k, kl);
  if (!pl) return nullptr;
  TVer* v = pl->head.load(std::memory_order_acquire);
  while (v) {
    TVer* nx = v->next.load(std::memory_order_acquire);
    if (!nx) break;
    v = nx;
  }
  return v;
}

void tpulsm_trie_ver(void* pos, const uint8_t** k, uint32_t* kl,
                     uint64_t* inv, const uint8_t** v, uint32_t* vl) {
  TVer* ver = static_cast<TVer*>(pos);
  *k = ver->leaf->key;
  *kl = ver->leaf->key_len;
  *inv = ver->inv;
  const uint8_t* rec = ver->val.load(std::memory_order_acquire);
  uint32_t len;
  std::memcpy(&len, rec, 4);
  *v = rec + 4;
  *vl = len;
}

// Ordered whole-rep export — same contract as tpulsm_skiplist_export.
int64_t tpulsm_trie_export(
    void* h, uint8_t* key_buf, int64_t* key_offs, int32_t* key_lens,
    uint64_t* seqs, int32_t* vtypes, uint8_t* val_buf, int64_t* val_offs,
    int32_t* val_lens, int64_t max_rows, int64_t* out_sizes) {
  TrieRep* t = static_cast<TrieRep*>(h);
  if (key_buf == nullptr) {
    int64_t kb = 0, vb = 0, rows = 0;
    trie_walk_all(t, [&](TLeafHdr* lf) {
      for (TVer* v = lf->head.load(std::memory_order_acquire); v;
           v = v->next.load(std::memory_order_acquire)) {
        const uint8_t* rec = v->val.load(std::memory_order_acquire);
        uint32_t vl;
        std::memcpy(&vl, rec, 4);
        kb += lf->key_len + 8;
        vb += vl;
        rows++;
      }
    });
    out_sizes[0] = kb;
    out_sizes[1] = vb;
    out_sizes[2] = rows;
    return rows;
  }
  const int64_t key_cap = out_sizes[0], val_cap = out_sizes[1];
  int64_t ko = 0, vo = 0, rows = 0;
  bool overflow = false;
  trie_walk_all(t, [&](TLeafHdr* lf) {
    if (overflow) return;
    for (TVer* v = lf->head.load(std::memory_order_acquire); v;
         v = v->next.load(std::memory_order_acquire)) {
      if (rows >= max_rows) {
        overflow = true;
        return;
      }
      const uint8_t* rec = v->val.load(std::memory_order_acquire);
      uint32_t vl;
      std::memcpy(&vl, rec, 4);
      if (ko + (int64_t)lf->key_len + 8 > key_cap ||
          vo + (int64_t)vl > val_cap) {
        overflow = true;
        return;
      }
      uint64_t packed = ~v->inv;
      std::memcpy(key_buf + ko, lf->key, lf->key_len);
      for (int b = 0; b < 8; b++)
        key_buf[ko + lf->key_len + b] = (uint8_t)(packed >> (8 * b));
      key_offs[rows] = ko;
      key_lens[rows] = (int32_t)(lf->key_len + 8);
      seqs[rows] = packed >> 8;
      vtypes[rows] = (int32_t)(packed & 0xFF);
      std::memcpy(val_buf + vo, rec + 4, vl);
      val_offs[rows] = vo;
      val_lens[rows] = (int32_t)vl;
      ko += lf->key_len + 8;
      vo += vl;
      rows++;
    }
  });
  return overflow ? -1 : rows;
}

// ---------------------------------------------------------------------------
// Native point-read engine: the whole DBImpl::GetImpl hot chain in one
// GIL-released call (reference db/db_impl/db_impl.cc:2079 GetImpl →
// Version::Get → BlockBasedTable::Get, block_based_table_reader.cc:2095).
// Python registers per-table handles (dup'd fd + in-memory index/filter
// blocks + key bounds) and per-version handles (L0 list newest-first +
// sorted deeper levels); tpulsm_db_get then probes memtable skiplists and
// the SST chain with a shared decompressed-block LRU, returning the value
// or a FALLBACK code for anything the Python state machine must handle
// (merge operands, single-delete, blob indexes, range tombstones).
// ---------------------------------------------------------------------------

namespace {

struct NTable {
  int fd = -1;                 // dup'd; owned
  int64_t file_size = 0;       // bounds every BlockHandle before pread
  uint64_t number = 0;         // block-cache key namespace
  int32_t eligible = 0;        // 0 → chain walk returns FALLBACK on contact
  std::string index;           // uncompressed single-level index block
  std::string filter;          // whole-key bloom block ("" → no filter)
  int32_t filter_kind = 0;     // 0 = classic bloom, 1 = blocked bloom
  std::string smallest_uk, largest_uk;
  // Decoded index (built once per handle): flat arrays for a cache-
  // friendly binary search — probing the raw multi-MB index block paid
  // ~15 scattered cache misses per Get. idx_prefix holds the zero-padded
  // big-endian first 8 USER-KEY bytes (coarse order: ties fall back to a
  // full compare of the stored key). Empty when the block didn't decode
  // cleanly (the BCur path remains as fallback).
  std::vector<uint64_t> idx_prefix;
  std::vector<uint32_t> idx_koff, idx_klen;
  std::vector<uint64_t> idx_boff, idx_bsize;
  std::string idx_keys;
  // --- zip-table sections (kind == 1). BORROWED: the Python reader owns
  // the section buffers and keeps them alive until it frees the handle
  // (weakref.finalize closure), so no copies of the multi-MB blob. ---
  int32_t kind = 0;  // 0 = block SST, 1 = zip table
  int32_t zg = 0, zvg = 0;
  int64_t zn = 0;
  int32_t zmeta16 = 0, zlens32 = 0;
  const uint8_t* zkmeta = nullptr;
  const uint8_t* zksfx = nullptr;
  int64_t zksfx_len = 0;
  const uint8_t* zkgso = nullptr;
  int64_t zng = 0;  // key groups
  const uint8_t* zvlens = nullptr;
  const uint8_t* zvgo = nullptr;  // (znvg + 1) u32 payload offsets
  const uint8_t* zvflags = nullptr;
  int64_t zvflags_len = 0;
  const uint8_t* zvdict = nullptr;
  int64_t zvdict_len = 0;
  const uint8_t* zvblob = nullptr;
  int64_t zvblob_len = 0;
  int64_t znvg = 0;                   // value groups
  std::vector<uint64_t> zhead_pre;    // nuk_prefix of each group head
  ~NTable() {
    if (fd >= 0) ::close(fd);
  }
};

static inline uint32_t zload_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

// (plen, slen) meta pair of zip entry i.
static inline void zmeta_pair(const NTable* t, int64_t i, uint32_t* pl,
                              uint32_t* sl) {
  if (t->zmeta16) {
    uint16_t a, b;
    std::memcpy(&a, t->zkmeta + 4 * i, 2);
    std::memcpy(&b, t->zkmeta + 4 * i + 2, 2);
    *pl = a;
    *sl = b;
  } else {
    *pl = t->zkmeta[2 * i];
    *sl = t->zkmeta[2 * i + 1];
  }
}

static inline uint64_t zvlen_at(const NTable* t, int64_t i) {
  if (t->zlens32) return zload_u32(t->zvlens + 4 * i);
  uint16_t v;
  std::memcpy(&v, t->zvlens + 2 * i, 2);
  return v;
}

// Zero-padded big-endian first-8-bytes of a user key: never orders two
// keys WRONGLY, only ties (equal prefixes) need a full compare.
static inline uint64_t nuk_prefix(const uint8_t* uk, int32_t ulen) {
  uint64_t w = 0;
  int32_t n = ulen < 8 ? ulen : 8;
  for (int32_t i = 0; i < n; i++) w |= (uint64_t)uk[i] << (8 * (7 - i));
  return w;
}

struct NVersion {
  std::vector<NTable*> l0;                   // newest first
  std::vector<std::vector<NTable*>> levels;  // levels 1.. sorted by key
};

// Sharded LRU of decompressed data blocks keyed by (table number, offset).
struct NBlockCache {
  struct Entry {
    std::shared_ptr<std::string> data;
    uint64_t number, off;  // full key: a mixed-hash collision must MISS
    std::list<std::pair<uint64_t, uint64_t>>::iterator lru_it;
  };
  struct Shard {
    std::mutex mu;
    std::unordered_map<uint64_t, Entry> map;
    std::list<std::pair<uint64_t, uint64_t>> lru;  // front = hottest
    size_t bytes = 0;
  };
  static const int kShards = 16;
  Shard shards[kShards];
  std::atomic<size_t> budget{256u << 20};
  std::atomic<uint64_t> hits{0}, misses{0};

  static uint64_t key_of(uint64_t number, uint64_t off) {
    // splitmix64 over the pair; the map stores the mixed key. A collision
    // would serve wrong bytes, so fold BOTH inputs through two rounds.
    uint64_t x = number * 0x9E3779B97F4A7C15ULL ^ (off + 0xBF58476D1CE4E5B9ULL);
    x ^= x >> 30; x *= 0xBF58476D1CE4E5B9ULL;
    x ^= x >> 27; x *= 0x94D049BB133111EBULL;
    x ^= x >> 31;
    return x;
  }

  std::shared_ptr<std::string> lookup(uint64_t number, uint64_t off) {
    uint64_t k = key_of(number, off);
    Shard& s = shards[k % kShards];
    std::lock_guard<std::mutex> g(s.mu);
    auto it = s.map.find(k);
    if (it == s.map.end() || it->second.number != number ||
        it->second.off != off) {
      misses.fetch_add(1, std::memory_order_relaxed);
      return nullptr;
    }
    s.lru.splice(s.lru.begin(), s.lru, it->second.lru_it);
    hits.fetch_add(1, std::memory_order_relaxed);
    return it->second.data;
  }

  void insert(uint64_t number, uint64_t off,
              std::shared_ptr<std::string> data) {
    uint64_t k = key_of(number, off);
    Shard& s = shards[k % kShards];
    size_t per_shard = budget.load(std::memory_order_relaxed) / kShards;
    std::lock_guard<std::mutex> g(s.mu);
    if (s.map.count(k)) return;
    s.bytes += data->size();
    s.lru.emplace_front(k, (uint64_t)data->size());
    s.map[k] = Entry{std::move(data), number, off, s.lru.begin()};
    while (s.bytes > per_shard && !s.lru.empty()) {
      auto victim = s.lru.back();
      s.lru.pop_back();
      s.bytes -= victim.second;
      s.map.erase(victim.first);
    }
  }
};

NBlockCache& nblock_cache() {
  static NBlockCache c;
  return c;
}

// In-block cursor over the restart-compressed entry stream.
struct BCur {
  const uint8_t* data;
  const uint8_t* p;
  const uint8_t* limit;  // start of restart array
  uint8_t key[4096];
  uint32_t klen = 0;
  const uint8_t* val = nullptr;
  uint32_t vlen = 0;

  bool init(const uint8_t* d, int64_t len) {
    if (len < 8) return false;
    uint32_t nr;
    std::memcpy(&nr, d + len - 4, 4);
    int64_t restart_off = len - 4 - 4 * (int64_t)nr;
    if (nr == 0 || restart_off < 0) return false;
    data = d;
    p = d;
    limit = d + restart_off;
    klen = 0;
    return true;
  }

  bool at_end() const { return p >= limit; }

  // 1 = entry decoded, 0 = end of block, -1 = corrupt OR key too large
  // for the cursor buffer (callers must FALL BACK, not report a miss — a
  // legitimate >4KB stored key is not corruption).
  int next() {
    if (p >= limit) return 0;
    uint32_t shared, non_shared, v;
    p = get_varint32(p, limit, &shared);
    if (!p) return -1;
    p = get_varint32(p, limit, &non_shared);
    if (!p) return -1;
    p = get_varint32(p, limit, &v);
    if (!p) return -1;
    if (shared > klen || non_shared > sizeof(key) - shared) return -1;
    if (p + non_shared + v > limit) return -1;
    std::memcpy(key + shared, p, non_shared);
    klen = shared + non_shared;
    p += non_shared;
    val = p;
    vlen = v;
    p += v;
    return 1;
  }
};

// Decoded-entry comparator vs target, using the internal-key order helper
// defined in the block-seek section above.
inline int bcur_cmp(const BCur& c, const uint8_t* target, int32_t tlen) {
  return ikey_compare(c.key, (int32_t)c.klen, target, tlen);
}

// Position cursor at the first entry >= target (restart bsearch + scan).
// Returns 1 = cursor holds that entry, 0 = every key < target (or empty),
// -1 = corruption.
int bcur_seek(BCur& c, const uint8_t* d, int64_t len, const uint8_t* target,
              int32_t tlen) {
  if (len < 8) return -1;
  uint32_t nr;
  std::memcpy(&nr, d + len - 4, 4);
  int64_t restart_off = len - 4 - 4 * (int64_t)nr;
  if (nr == 0 || restart_off < 0) return -1;
  auto restart_point = [&](uint32_t i) -> uint32_t {
    uint32_t v;
    std::memcpy(&v, d + restart_off + 4 * (int64_t)i, 4);
    return v;
  };
  // Find the last restart whose key < target.
  uint32_t lo = 0, hi = nr - 1;
  while (lo < hi) {
    uint32_t mid = (lo + hi + 1) / 2;
    BCur probe;
    if (!probe.init(d, len)) return -1;
    probe.p = d + restart_point(mid);
    probe.klen = 0;
    if (probe.next() != 1) return -1;
    if (bcur_cmp(probe, target, tlen) < 0) {
      lo = mid;
    } else {
      hi = mid - 1;
    }
  }
  if (!c.init(d, len)) return -1;
  c.p = d + restart_point(lo);
  c.klen = 0;
  int nr2;
  while ((nr2 = c.next()) == 1) {
    if (bcur_cmp(c, target, tlen) >= 0) return 1;
  }
  if (nr2 < 0) return -1;
  return 0;  // all keys < target
}

// Decode a single-level index block into NTable's flat arrays; leaves
// them empty (BCur fallback) on any irregularity.
void ntable_decode_index(NTable* t) {
  auto fail = [&] {
    t->idx_prefix.clear();
    t->idx_koff.clear();
    t->idx_klen.clear();
    t->idx_boff.clear();
    t->idx_bsize.clear();
    t->idx_keys.clear();
  };
  BCur c;
  if (t->index.empty() ||
      !c.init((const uint8_t*)t->index.data(), (int64_t)t->index.size()))
    return;
  size_t approx = t->index.size() / 24 + 8;
  t->idx_prefix.reserve(approx);
  t->idx_boff.reserve(approx);
  t->idx_bsize.reserve(approx);
  int r;
  while ((r = c.next()) == 1) {
    const uint8_t* vp = c.val;
    const uint8_t* vend = c.val + c.vlen;
    uint64_t boff = 0, bsize = 0;
    vp = get_varint64(vp, vend, &boff);
    if (vp) vp = get_varint64(vp, vend, &bsize);
    if (!vp || c.klen < 8 || t->idx_keys.size() > 0xFFFFFF00u) {
      fail();
      return;
    }
    t->idx_prefix.push_back(nuk_prefix(c.key, (int32_t)c.klen - 8));
    t->idx_koff.push_back((uint32_t)t->idx_keys.size());
    t->idx_klen.push_back(c.klen);
    t->idx_boff.push_back(boff);
    t->idx_bsize.push_back(bsize);
    t->idx_keys.append((const char*)c.key, c.klen);
  }
  if (r < 0) fail();
}

// First decoded-index entry whose key >= target (internal-key order).
int64_t nindex_lower_bound(NTable* t, const uint8_t* target, int32_t tlen) {
  uint64_t tp = nuk_prefix(target, tlen - 8);
  const uint64_t* pre = t->idx_prefix.data();
  const uint8_t* keys = (const uint8_t*)t->idx_keys.data();
  int64_t lo = 0, hi = (int64_t)t->idx_prefix.size();
  while (lo < hi) {
    int64_t mid = (lo + hi) >> 1;
    bool less;
    if (pre[mid] != tp)
      less = pre[mid] < tp;
    else
      less = ikey_compare(keys + t->idx_koff[mid],
                          (int32_t)t->idx_klen[mid], target, tlen) < 0;
    if (less)
      lo = mid + 1;
    else
      hi = mid;
  }
  return lo;
}

// Whole-key bloom probe: layout varint32 num_bits | 1B k | bits.
// kind 1 = blocked bloom (varint32 num_lines | 1B k | 64B lines): ONE
// cache line touched per probe (table/filter.py BlockedBloomFilterPolicy).
bool nfilter_may_match(const std::string& f, int32_t kind,
                       const uint8_t* key, int32_t klen) {
  if (f.empty()) return true;
  const uint8_t* p = (const uint8_t*)f.data();
  const uint8_t* end = p + f.size();
  uint32_t hdr;
  p = get_varint32(p, end, &hdr);
  if (!p || p >= end) return true;
  uint32_t k = *p++;
  const uint8_t* bits = p;
  uint64_t h = tpulsm_xxh64(key, (size_t)klen, 0xA0761D64);
  uint64_t h2 = ((h >> 33) | (h << 31)) | 1;
  if (kind == 1) {
    uint64_t num_lines = hdr;
    if (num_lines == 0 || (size_t)(end - bits) < (size_t)num_lines * 64)
      return true;
    const uint8_t* line = bits + (h % num_lines) * 64;
    uint64_t x = h;
    for (uint32_t i = 0; i < k; i++) {
      x += h2;
      uint64_t b = x & 511;
      if (!((line[b >> 3] >> (b & 7)) & 1)) return false;
    }
    return true;
  }
  uint32_t num_bits = hdr;
  if (num_bits == 0 || (size_t)(end - bits) * 8 < num_bits) return true;
  for (uint32_t i = 0; i < k; i++) {
    uint64_t b = (h + (uint64_t)i * h2) % num_bits;
    if (!((bits[b >> 3] >> (b & 7)) & 1)) return false;
  }
  return true;
}

// Per-call read counters surfaced to PerfContext/Statistics (indexes
// documented at tpulsm_db_get).
enum {
  NC_MEMS = 0,
  NC_BLOOM_MISS = 1,
  NC_BLOOM_HIT = 2,
  NC_CACHE_HIT = 3,
  NC_CACHE_MISS = 4,
  NC_READ_BYTES = 5,
  NC_COUNT = 6,
};

// Fetch + decompress one data block through the shared LRU.
// nullptr → error (unsupported codec / IO / corruption).
std::shared_ptr<std::string> nfetch_block(NTable* t, uint64_t off,
                                          uint64_t size, int64_t* ctr) {
  // A corrupt index entry must become a Python-path fallback (which
  // surfaces Corruption), not an OOM abort or a wrapped-arithmetic OOB
  // read — bound the handle against the file with non-wrapping checks.
  if (t->file_size <= 0) return nullptr;
  uint64_t fsz = (uint64_t)t->file_size;
  if (size > fsz || 5 > fsz - size || off > fsz - size - 5)
    return nullptr;
  NBlockCache& cache = nblock_cache();
  auto hit = cache.lookup(t->number, off);
  if (hit) {
    ctr[NC_CACHE_HIT]++;
    return hit;
  }
  ctr[NC_CACHE_MISS]++;
  ctr[NC_READ_BYTES] += (int64_t)size + 5;
  std::string raw;
  raw.resize(size + 5);  // payload + type byte + masked crc32c
  ssize_t got = ::pread(t->fd, &raw[0], size + 5, (off_t)off);
  if (got != (ssize_t)(size + 5)) return nullptr;
  uint8_t type = (uint8_t)raw[size];
  // Verify the masked trailer crc (table/format.py framing) — the Python
  // read path verifies by default, so the fast path must not be laxer.
  uint32_t stored;
  std::memcpy(&stored, raw.data() + size + 1, 4);
  uint32_t rot = stored - 0xA282EAD8u;
  uint32_t unmasked = (rot >> 17) | (rot << 15);
  uint32_t actual =
      tpulsm_crc32c_extend(0, (const uint8_t*)raw.data(), size + 1);
  if (unmasked != actual) return nullptr;
  raw.resize(size + 1);
  auto out = std::make_shared<std::string>();
  const Codecs& c = codecs();
  if (type == 0) {
    raw.resize(size);
    *out = std::move(raw);
  } else if (type == 1) {
    if (!c.snappy_len || !c.snappy_unc) return nullptr;
    size_t ulen = 0;
    if (c.snappy_len(raw.data(), size, &ulen) != 0) return nullptr;
    out->resize(ulen);
    if (c.snappy_unc(raw.data(), size, &(*out)[0], &ulen) != 0)
      return nullptr;
    out->resize(ulen);
  } else if (type == 7) {
    if (!c.zstd_size || !c.zstd_dec) return nullptr;
    unsigned long long ulen = c.zstd_size(raw.data(), size);
    if (ulen == 0ULL || ulen + 1 == 0ULL || ulen > (1ull << 31))
      return nullptr;
    out->resize((size_t)ulen);
    size_t r = c.zstd_dec(&(*out)[0], (size_t)ulen, raw.data(), size);
    if (c.zstd_err && c.zstd_err(r)) return nullptr;
    out->resize(r);
  } else {
    return nullptr;  // dict-compressed or unknown: python path
  }
  cache.insert(t->number, off, out);
  return out;
}

// rc codes for the probe chain.
enum { NGET_NOTFOUND = 0, NGET_FOUND = 1, NGET_FALLBACK = 2, NGET_ERR = -1 };

// Get threads are long-lived, so a thread_local DCtx amortizes context
// setup across probes; the wrapper frees it at thread exit.
struct ZDctx {
  void* ctx = nullptr;
  ~ZDctx() {
    if (ctx) {
      const Codecs& c = codecs();
      if (c.zstd_dctx_free) c.zstd_dctx_free(ctx);
    }
  }
};

// Value bytes of zip entry i. Raw groups are served zero-copy from the
// borrowed blob; compressed groups decode once into the shared LRU keyed
// by (table number, group payload offset). false → fall back to Python.
bool nzvalue(NTable* t, int64_t i, const uint8_t** base, uint64_t* len,
             std::shared_ptr<std::string>* keep, int64_t* ctr) {
  int64_t gi = i / t->zvg;
  uint64_t off = 0;
  for (int64_t j = gi * (int64_t)t->zvg; j < i; j++) off += zvlen_at(t, j);
  *len = zvlen_at(t, i);
  uint64_t p0 = zload_u32(t->zvgo + 4 * gi);
  uint64_t p1 = zload_u32(t->zvgo + 4 * (gi + 1));
  if (!((t->zvflags[gi >> 3] >> (gi & 7)) & 1)) {
    if (off + *len > p1 - p0) return false;
    *base = t->zvblob + p0 + off;
    return true;
  }
  NBlockCache& cache = nblock_cache();
  auto hit = cache.lookup(t->number, p0);
  if (hit) {
    ctr[NC_CACHE_HIT]++;
  } else {
    ctr[NC_CACHE_MISS]++;
    ctr[NC_READ_BYTES] += (int64_t)(p1 - p0);
    const Codecs& c = codecs();
    if (!c.zstd_dec_dict || !c.zstd_dctx_new) return false;
    static thread_local ZDctx d;
    if (!d.ctx) d.ctx = c.zstd_dctx_new();
    if (!d.ctx) return false;
    uint64_t raw = 0;
    int64_t gend = (gi + 1) * (int64_t)t->zvg;
    if (gend > t->zn) gend = t->zn;
    for (int64_t j = gi * (int64_t)t->zvg; j < gend; j++)
      raw += zvlen_at(t, j);
    auto out = std::make_shared<std::string>();
    out->resize(raw);
    size_t got = c.zstd_dec_dict(
        d.ctx, raw ? &(*out)[0] : nullptr, (size_t)raw, t->zvblob + p0,
        (size_t)(p1 - p0), t->zvdict_len ? t->zvdict : nullptr,
        (size_t)t->zvdict_len);
    if ((c.zstd_err && c.zstd_err(got)) || got != raw) return false;
    cache.insert(t->number, p0, out);
    hit = std::move(out);
  }
  if (off + *len > hit->size()) return false;
  *base = (const uint8_t*)hit->data() + off;
  *keep = std::move(hit);
  return true;
}

// Sequential cursor over the front-coded zip key stream. The suffix blob
// is contiguous across group boundaries, so one running offset suffices.
struct ZCur {
  NTable* t = nullptr;
  int64_t i = -1;   // current entry index
  uint64_t so = 0;  // suffix offset of the NEXT entry
  uint8_t key[4096 + 16];
  uint32_t klen = 0;

  // 1 = positioned at group g's head, 0 = empty, -1 = corrupt.
  int seek_group(int64_t g) {
    if (g < 0 || g >= t->zng) return -1;
    so = zload_u32(t->zkgso + 4 * g);
    i = g * (int64_t)t->zg - 1;
    klen = 0;
    return next();
  }

  // 1 = entry decoded, 0 = end of table, -1 = corrupt.
  int next() {
    if (i + 1 >= t->zn) return 0;
    i++;
    uint32_t pl, sl;
    zmeta_pair(t, i, &pl, &sl);
    if (pl > klen || (uint64_t)pl + sl > sizeof(key)) return -1;
    if (so + sl > (uint64_t)t->zksfx_len) return -1;
    std::memcpy(key + pl, t->zksfx + so, sl);
    so += sl;
    klen = pl + sl;
    return klen >= 8 ? 1 : -1;
  }
};

// Zip-table probe: bsearch group-head prefixes for the last head <=
// target, then walk the front-coded stream with the same user-key /
// seqno dispatch as the block path below.
int nztable_get(NTable* t, const uint8_t* ukey, int32_t klen,
                const uint8_t* target, int32_t tlen, uint64_t snap_seq,
                uint8_t* val_out, int32_t val_cap, int32_t* val_len,
                int* decided, int64_t* ctr) {
  if (t->zn <= 0 || t->zng <= 0) return NGET_FALLBACK;
  uint64_t tp = nuk_prefix(target, tlen - 8);
  int64_t lo = 0, hi = t->zng;  // first head > target
  while (lo < hi) {
    int64_t mid = (lo + hi) >> 1;
    bool gt;
    if (t->zhead_pre[(size_t)mid] != tp) {
      gt = t->zhead_pre[(size_t)mid] > tp;
    } else {
      uint32_t pl, sl;
      zmeta_pair(t, mid * (int64_t)t->zg, &pl, &sl);
      uint64_t hso = zload_u32(t->zkgso + 4 * mid);
      gt = ikey_compare(t->zksfx + hso, (int32_t)sl, target, tlen) > 0;
    }
    if (gt)
      hi = mid;
    else
      lo = mid + 1;
  }
  int64_t g = lo > 0 ? lo - 1 : 0;  // target < first key: walk from start
  ZCur c;
  c.t = t;
  int nr = c.seek_group(g);
  while (nr == 1) {
    if (c.klen < 8) return NGET_FALLBACK;
    int32_t cu = (int32_t)c.klen - 8;
    int m = cu < klen ? cu : klen;
    int cmp = std::memcmp(c.key, ukey, (size_t)m);
    if (cmp == 0 && cu != klen) cmp = cu < klen ? -1 : 1;
    if (cmp > 0) return NGET_NOTFOUND;  // walked past ukey: absent here
    if (cmp == 0) {
      uint64_t p2 = 0;
      for (int b = 0; b < 8; b++)
        p2 |= (uint64_t)c.key[cu + b] << (8 * b);
      uint64_t seq = p2 >> 8;
      uint8_t vt = (uint8_t)(p2 & 0xFF);
      if (seq <= snap_seq) {
        if (vt == 0x1) {  // VALUE
          *decided = 1;
          const uint8_t* vb = nullptr;
          uint64_t vl = 0;
          std::shared_ptr<std::string> keep;
          if (!nzvalue(t, c.i, &vb, &vl, &keep, ctr) || vl > 0x7FFFFFFF)
            return NGET_FALLBACK;
          if ((int32_t)vl > val_cap) {
            *val_len = (int32_t)vl;
            return NGET_ERR;  // caller re-sizes and retries
          }
          std::memcpy(val_out, vb, vl);
          *val_len = (int32_t)vl;
          return NGET_FOUND;
        }
        if (vt == 0x0) {  // DELETION → definitive miss
          *decided = 1;
          return NGET_NOTFOUND;
        }
        return NGET_FALLBACK;  // MERGE / SINGLE_DELETE / BLOB_INDEX...
      }
    }
    nr = c.next();
  }
  return nr < 0 ? NGET_FALLBACK : NGET_NOTFOUND;
}

// Probe one table for ukey at snap_seq. Decisive answers only; anything
// needing the Python state machine returns NGET_FALLBACK. NGET_NOTFOUND
// here means "not in this table — continue the chain".
int ntable_get(NTable* t, const uint8_t* ukey, int32_t klen,
               uint64_t snap_seq, uint8_t* val_out, int32_t val_cap,
               int32_t* val_len, int* decided, int64_t* ctr) {
  *decided = 0;
  if (!t || !t->eligible) return NGET_FALLBACK;
  if (!t->filter.empty()) {
    if (!nfilter_may_match(t->filter, t->filter_kind, ukey, klen)) {
      ctr[NC_BLOOM_MISS]++;
      return NGET_NOTFOUND;
    }
    ctr[NC_BLOOM_HIT]++;
  }
  // Seek target: (ukey, snap_seq, type 0x7F) — highest type sorts first.
  uint8_t target[4096 + 8];
  if (klen > 4096) return NGET_FALLBACK;
  std::memcpy(target, ukey, klen);
  uint64_t packed = (snap_seq << 8) | 0x7F;
  for (int i = 0; i < 8; i++) target[klen + i] = (uint8_t)(packed >> (8 * i));
  int32_t tlen = klen + 8;

  if (t->kind == 1)
    return nztable_get(t, ukey, klen, target, tlen, snap_seq, val_out,
                       val_cap, val_len, decided, ctr);

  // Candidate block via the decoded flat index (one cache-friendly
  // binary search) when available; raw-block cursor otherwise.
  bool use_arr = !t->idx_prefix.empty();
  BCur idx;
  int64_t ipos = 0;
  int64_t icount = (int64_t)t->idx_prefix.size();
  if (use_arr) {
    ipos = nindex_lower_bound(t, target, tlen);
    if (ipos >= icount) return NGET_NOTFOUND;  // past the last block
  } else {
    int sr = bcur_seek(idx, (const uint8_t*)t->index.data(),
                       (int64_t)t->index.size(), target, tlen);
    if (sr < 0) return NGET_FALLBACK;
    if (sr == 0) return NGET_NOTFOUND;  // past the last block
  }

  bool first_block = true;
  while (true) {
    uint64_t boff, bsize;
    if (use_arr) {
      boff = t->idx_boff[ipos];
      bsize = t->idx_bsize[ipos];
    } else {
      // idx cursor sits at the candidate block's index entry; its value
      // is the BlockHandle (varint64 offset, varint64 size).
      const uint8_t* vp = idx.val;
      const uint8_t* vend = idx.val + idx.vlen;
      vp = get_varint64(vp, vend, &boff);
      if (!vp) return NGET_FALLBACK;
      vp = get_varint64(vp, vend, &bsize);
      if (!vp) return NGET_FALLBACK;
    }
    auto block = nfetch_block(t, boff, bsize, ctr);
    if (!block) return NGET_FALLBACK;
    BCur c;
    const uint8_t* bd = (const uint8_t*)block->data();
    bool have = false;
    if (first_block) {
      int br = bcur_seek(c, bd, (int64_t)block->size(), target, tlen);
      if (br < 0) return NGET_FALLBACK;
      have = br == 1;  // br == 0: target past this block's keys — the run
      first_block = false;  // may continue in the next block
    } else {
      if (!c.init(bd, (int64_t)block->size())) return NGET_FALLBACK;
      int nr = c.next();  // scan continues from the block's first entry
      if (nr < 0) return NGET_FALLBACK;
      have = nr == 1;
    }
    while (have) {
      if (c.klen < 8) return NGET_FALLBACK;
      int32_t cu = (int32_t)c.klen - 8;
      int m = cu < klen ? cu : klen;
      int cmp = std::memcmp(c.key, ukey, (size_t)m);
      if (cmp == 0 && cu != klen) cmp = cu < klen ? -1 : 1;
      if (cmp > 0) return NGET_NOTFOUND;  // walked past ukey: absent here
      if (cmp == 0) {
        uint64_t p2 = 0;
        for (int i = 0; i < 8; i++)
          p2 |= (uint64_t)c.key[cu + i] << (8 * i);
        uint64_t seq = p2 >> 8;
        uint8_t vt = (uint8_t)(p2 & 0xFF);
        if (seq <= snap_seq) {
          if (vt == 0x1) {  // VALUE
            *decided = 1;
            if ((int32_t)c.vlen > val_cap) {
              *val_len = (int32_t)c.vlen;
              return NGET_ERR;  // caller re-sizes and retries
            }
            std::memcpy(val_out, c.val, c.vlen);
            *val_len = (int32_t)c.vlen;
            return NGET_FOUND;
          }
          if (vt == 0x0) {  // DELETION → definitive miss
            *decided = 1;
            return NGET_NOTFOUND;
          }
          return NGET_FALLBACK;  // MERGE / SINGLE_DELETE / BLOB_INDEX...
        }
      }
      {
        int nr = c.next();
        if (nr < 0) return NGET_FALLBACK;
        have = nr == 1;
      }
    }
    // Block exhausted without passing ukey: the version run may continue
    // in the next data block.
    if (use_arr) {
      if (++ipos >= icount) return NGET_NOTFOUND;  // no further blocks
    } else {
      int nr = idx.next();
      if (nr < 0) return NGET_FALLBACK;
      if (nr == 0) return NGET_NOTFOUND;  // no further blocks
    }
  }
}

int nversion_get(NVersion* v, const uint8_t* ukey, int32_t klen,
                 uint64_t snap_seq, uint8_t* val_out, int32_t val_cap,
                 int32_t* val_len, int32_t* src_out, int64_t* ctr) {
  int decided = 0;
  for (NTable* t : v->l0) {
    if (!t) return NGET_FALLBACK;
    if (!t->smallest_uk.empty() || !t->largest_uk.empty()) {
      if (std::string_view((const char*)ukey, (size_t)klen)
              < std::string_view(t->smallest_uk) ||
          std::string_view(t->largest_uk)
              < std::string_view((const char*)ukey, (size_t)klen))
        continue;
    }
    int rc = ntable_get(t, ukey, klen, snap_seq, val_out, val_cap, val_len,
                        &decided, ctr);
    if (rc == NGET_FOUND || rc == NGET_FALLBACK || rc == NGET_ERR ||
        (rc == NGET_NOTFOUND && decided)) {
      *src_out = 1;  // level 0 + 1
      return rc;
    }
  }
  for (size_t li = 0; li < v->levels.size(); li++) {
    auto& fl = v->levels[li];
    if (fl.empty()) continue;
    std::string_view uk((const char*)ukey, (size_t)klen);
    // Binary search: first file whose largest >= ukey.
    size_t lo = 0, hi = fl.size();
    while (lo < hi) {
      size_t mid = (lo + hi) / 2;
      if (!fl[mid]) return NGET_FALLBACK;  // conservatively bail
      if (std::string_view(fl[mid]->largest_uk) < uk)
        lo = mid + 1;
      else
        hi = mid;
    }
    // Mirror files_for_get: subsequent files whose smallest <= ukey are
    // also candidates (tombstone-widened bounds).
    for (size_t pick = lo; pick < fl.size(); pick++) {
      NTable* t = fl[pick];
      if (!t) return NGET_FALLBACK;
      if (uk < std::string_view(t->smallest_uk)) break;
      int rc = ntable_get(t, ukey, klen, snap_seq, val_out, val_cap,
                          val_len, &decided, ctr);
      if (rc == NGET_FOUND || rc == NGET_FALLBACK || rc == NGET_ERR ||
          (rc == NGET_NOTFOUND && decided)) {
        *src_out = (int32_t)li + 2;
        return rc;
      }
    }
  }
  *src_out = -1;
  return NGET_NOTFOUND;
}

}  // namespace

void* tpulsm_table_handle_new(int32_t fd, uint64_t number, int32_t eligible,
                              const uint8_t* index, int64_t index_len,
                              const uint8_t* filter, int64_t filter_len,
                              const uint8_t* smallest_uk, int32_t sl,
                              const uint8_t* largest_uk, int32_t ll) {
  NTable* t = new (std::nothrow) NTable();
  if (!t) return nullptr;
  // eligible is a FLAG WORD: bit0 = eligible, bit1 = blocked-bloom filter
  // layout (old callers pass 0/1, which decodes identically).
  t->filter_kind = (eligible >> 1) & 1;
  eligible = eligible & 1;
  if (eligible && fd >= 0) {
    t->fd = ::dup(fd);
    if (t->fd < 0) {
      delete t;
      return nullptr;
    }
    off_t end = ::lseek(t->fd, 0, SEEK_END);
    t->file_size = end > 0 ? (int64_t)end : 0;
  }
  t->number = number;
  t->eligible = eligible && t->fd >= 0;
  if (index_len > 0) t->index.assign((const char*)index, (size_t)index_len);
  if (t->eligible) {
    ntable_decode_index(t);
    if (!t->idx_prefix.empty())
      std::string().swap(t->index);  // decoded copy supersedes the raw block
  }
  if (filter_len > 0)
    t->filter.assign((const char*)filter, (size_t)filter_len);
  if (sl > 0) t->smallest_uk.assign((const char*)smallest_uk, (size_t)sl);
  if (ll > 0) t->largest_uk.assign((const char*)largest_uk, (size_t)ll);
  return t;
}

void tpulsm_table_handle_free(void* t) { delete static_cast<NTable*>(t); }

// Zip-table Get handle. Section buffers are BORROWED — the Python reader
// keeps them alive until tpulsm_table_handle_free. flags: bit0 eligible,
// bit1 blocked-bloom filter layout. Every section is validated ONCE here
// (one O(n) pass) so the per-Get walk can trust offsets; any violation
// demotes the handle to eligible=0 (Python fallback) instead of failing,
// keeping the version chain intact.
void* tpulsm_zip_table_handle_new(
    uint64_t number, int32_t flags, int32_t group, int32_t vgroup,
    int64_t n, int32_t meta16, int32_t lens32, const uint8_t* kmeta,
    int64_t kmeta_len, const uint8_t* ksfx, int64_t ksfx_len,
    const uint8_t* kgso, int64_t kgso_len, const uint8_t* vlens,
    int64_t vlens_len, const uint8_t* vgo, int64_t vgo_len,
    const uint8_t* vflags, int64_t vflags_len, const uint8_t* vdict,
    int64_t vdict_len, const uint8_t* vblob, int64_t vblob_len,
    const uint8_t* filter, int64_t filter_len, const uint8_t* smallest_uk,
    int32_t sl, const uint8_t* largest_uk, int32_t ll) {
  NTable* t = new (std::nothrow) NTable();
  if (!t) return nullptr;
  t->kind = 1;
  t->number = number;
  t->filter_kind = (flags >> 1) & 1;
  if (filter_len > 0)
    t->filter.assign((const char*)filter, (size_t)filter_len);
  if (sl > 0) t->smallest_uk.assign((const char*)smallest_uk, (size_t)sl);
  if (ll > 0) t->largest_uk.assign((const char*)largest_uk, (size_t)ll);
  t->eligible = 0;
  if (!(flags & 1) || group <= 0 || vgroup <= 0 || n <= 0 || !kmeta ||
      !ksfx || !kgso || !vlens || !vgo || !vflags || !vblob)
    return t;
  int64_t ng = (n + group - 1) / group;
  int64_t ngv = (n + vgroup - 1) / vgroup;
  int64_t msz = meta16 ? 4 : 2, lsz = lens32 ? 4 : 2;
  if (kmeta_len < n * msz || kgso_len < 4 * ng || vlens_len < n * lsz ||
      vgo_len < 4 * (ngv + 1) || vflags_len < (ngv + 7) / 8)
    return t;
  t->zg = group;
  t->zvg = vgroup;
  t->zn = n;
  t->zmeta16 = meta16;
  t->zlens32 = lens32;
  t->zkmeta = kmeta;
  t->zksfx = ksfx;
  t->zksfx_len = ksfx_len;
  t->zkgso = kgso;
  t->zng = ng;
  t->zvlens = vlens;
  t->zvgo = vgo;
  t->zvflags = vflags;
  t->zvflags_len = vflags_len;
  t->zvdict = vdict;
  t->zvdict_len = vdict_len;
  t->zvblob = vblob;
  t->zvblob_len = vblob_len;
  t->znvg = ngv;
  // Key-section walk: meta pairs must reconstruct, suffix offsets must
  // agree with the per-group directory and consume the blob exactly.
  t->zhead_pre.reserve((size_t)ng);
  uint64_t so = 0;
  uint32_t prev_klen = 0;
  for (int64_t i = 0; i < n; i++) {
    uint32_t pl, sl2;
    zmeta_pair(t, i, &pl, &sl2);
    uint64_t klen = (uint64_t)pl + sl2;
    if (i % group == 0) {
      if (pl != 0 || so != zload_u32(kgso + 4 * (i / group))) return t;
      if (klen < 8) return t;
      t->zhead_pre.push_back(nuk_prefix(ksfx + so, (int32_t)klen - 8));
    }
    if (pl > prev_klen || klen < 8 || klen > 4096 + 8) return t;
    if (so + sl2 > (uint64_t)ksfx_len) return t;
    so += sl2;
    prev_klen = (uint32_t)klen;
  }
  if (so != (uint64_t)ksfx_len) return t;
  // Value directory: monotone payload offsets covering the blob; raw
  // groups' payloads must equal the sum of their entry lengths.
  uint64_t prev_off = zload_u32(vgo);
  if (prev_off != 0) return t;
  for (int64_t gi = 0; gi < ngv; gi++) {
    uint64_t p0 = zload_u32(vgo + 4 * gi);
    uint64_t p1 = zload_u32(vgo + 4 * (gi + 1));
    if (p1 < p0 || p1 > (uint64_t)vblob_len) return t;
    int64_t e1 = (gi + 1) * (int64_t)vgroup;
    if (e1 > n) e1 = n;
    uint64_t raw = 0;
    for (int64_t j = gi * (int64_t)vgroup; j < e1; j++)
      raw += zvlen_at(t, j);
    bool flagged = (vflags[gi >> 3] >> (gi & 7)) & 1;
    if (!flagged && p1 - p0 != raw) return t;
    if (flagged && (p1 == p0 || (vdict_len > 0 && !vdict))) return t;
  }
  t->eligible = 1;
  return t;
}

// tables: L0 handles (newest first) then levels 1.. concatenated;
// level_offs[i]..level_offs[i+1] indexes level i+1's slice, with
// level_offs[0] == n_l0. A null handle marks a python-only table (chain
// walk returns FALLBACK on contact).
void* tpulsm_version_handle_new(void** tables, int32_t n_l0,
                                const int32_t* level_offs,
                                int32_t n_deeper_levels) {
  NVersion* v = new (std::nothrow) NVersion();
  if (!v) return nullptr;
  for (int32_t i = 0; i < n_l0; i++)
    v->l0.push_back(static_cast<NTable*>(tables[i]));
  for (int32_t li = 0; li < n_deeper_levels; li++) {
    v->levels.emplace_back();
    for (int32_t i = level_offs[li]; i < level_offs[li + 1]; i++)
      v->levels.back().push_back(static_cast<NTable*>(tables[i]));
  }
  return v;
}

void tpulsm_version_handle_free(void* v) { delete static_cast<NVersion*>(v); }

void tpulsm_block_cache_config(int64_t bytes, int64_t* out_stats) {
  NBlockCache& c = nblock_cache();
  if (bytes > 0) c.budget.store((size_t)bytes, std::memory_order_relaxed);
  if (out_stats) {
    out_stats[0] = (int64_t)c.hits.load(std::memory_order_relaxed);
    out_stats[1] = (int64_t)c.misses.load(std::memory_order_relaxed);
  }
}

// Persistent get context: binds (memtables, version, out buffers) once so
// the per-call ctypes surface shrinks to (ctx, key, klen, seq) — arg
// marshaling was ~40% of the measured per-get cost. Results land in
// ctx-owned memory the caller maps once: out[0]=val_len, out[1]=src,
// out[2..7]=counters (NC_* order).
struct NGetCtx {
  std::vector<void*> mems;
  std::vector<int32_t> kinds;  // 0 = skiplist, 1 = trie
  void* version = nullptr;
  int64_t out[8];
  std::vector<uint8_t> val;
};

void* tpulsm_getctx_new(void** mem_handles, int32_t n_mems, void* version,
                        int64_t val_cap) {
  NGetCtx* c = new (std::nothrow) NGetCtx();
  if (!c) return nullptr;
  for (int32_t i = 0; i < n_mems; i++) c->mems.push_back(mem_handles[i]);
  c->kinds.assign((size_t)n_mems, 0);
  c->version = version;
  c->val.resize((size_t)(val_cap > 0 ? val_cap : 4096));
  std::memset(c->out, 0, sizeof(c->out));
  return c;
}

// Mark memtable i as a trie-rep handle (layout differs from the skiplist).
void tpulsm_getctx_set_mem_kind(void* ctx, int32_t i, int32_t kind) {
  NGetCtx* c = static_cast<NGetCtx*>(ctx);
  if (i >= 0 && (size_t)i < c->kinds.size()) c->kinds[i] = kind;
}

void tpulsm_getctx_free(void* ctx) { delete static_cast<NGetCtx*>(ctx); }

int64_t* tpulsm_getctx_out(void* ctx) {
  return static_cast<NGetCtx*>(ctx)->out;
}

uint8_t* tpulsm_getctx_val(void* ctx) {
  return static_cast<NGetCtx*>(ctx)->val.data();
}

// Forward decls (definitions below keep the original entry points).
int32_t tpulsm_db_get(void** mem_handles, int32_t n_mems, void* version,
                      const uint8_t* ukey, int32_t klen, uint64_t snap_seq,
                      uint8_t* val_out, int32_t val_cap, int32_t* val_len,
                      int32_t* src_out, int64_t* counters);
int32_t tpulsm_db_get_kinds(void** mem_handles, const int32_t* mem_kinds,
                            int32_t n_mems, void* version,
                            const uint8_t* ukey, int32_t klen,
                            uint64_t snap_seq, uint8_t* val_out,
                            int32_t val_cap, int32_t* val_len,
                            int32_t* src_out, int64_t* counters);

int32_t tpulsm_getctx_get(void* ctx, const uint8_t* ukey, int32_t klen,
                          uint64_t snap_seq) {
  NGetCtx* c = static_cast<NGetCtx*>(ctx);
  int32_t vlen = 0, src = -1;
  int32_t rc = tpulsm_db_get_kinds(
      c->mems.data(), c->kinds.data(), (int32_t)c->mems.size(), c->version,
      ukey, klen, snap_seq, c->val.data(), (int32_t)c->val.size(), &vlen,
      &src, c->out + 2);
  if (rc == -1 && vlen > (int32_t)c->val.size()) {
    // Value outgrew the buffer: grow and retry — the caller detects
    // out[0] > its mapped capacity and re-maps tpulsm_getctx_val().
    c->val.resize((size_t)vlen + 1024);
    rc = tpulsm_db_get_kinds(
        c->mems.data(), c->kinds.data(), (int32_t)c->mems.size(), c->version,
        ukey, klen, snap_seq, c->val.data(), (int32_t)c->val.size(), &vlen,
        &src, c->out + 2);
  }
  c->out[0] = vlen;
  c->out[1] = src;
  return rc;
}

// Batched lookups against a get context — the reference's MultiGet role
// (db_impl.cc:3026-3227): one GIL-released call for the whole batch, each
// key running the full chain. status_out[i]: 1 found, 0 not found,
// 2 fallback-to-python (resolve that key on the Python path). Values pack
// into val_arena at val_offs_out/val_lens_out. Returns 0 ok, -2 arena too
// small (caller grows + retries). Counters accumulate across keys.
int32_t tpulsm_getctx_multiget(void* ctx, const uint8_t* keybuf,
                               const int64_t* key_offs,
                               const int32_t* key_lens, int64_t n,
                               uint64_t snap_seq, int8_t* status_out,
                               int64_t* val_offs_out, int64_t* val_lens_out,
                               uint8_t* val_arena, int64_t arena_cap,
                               int64_t* arena_used, int64_t* counters) {
  NGetCtx* c = static_cast<NGetCtx*>(ctx);
  for (int i = 0; i < NC_COUNT; i++) counters[i] = 0;

  // One key's chain walk (writing into [lo, hi) of the arena). Returns
  // bytes consumed, or -1 on arena-slice overflow.
  auto walk = [&](int64_t i, int64_t lo, int64_t hi,
                  int64_t* ctr) -> int64_t {
    const uint8_t* k = keybuf + key_offs[i];
    int32_t kl = key_lens[i];
    int32_t vlen = 0, src = -1;
    int64_t tmp_ctr[NC_COUNT];
    int32_t rc = tpulsm_db_get_kinds(
        c->mems.data(), c->kinds.data(), (int32_t)c->mems.size(), c->version,
        k, kl, snap_seq, val_arena + lo,
        (int32_t)std::min<int64_t>(hi - lo, (1u << 31) - 1),
        &vlen, &src, tmp_ctr);
    for (int t = 0; t < NC_COUNT; t++) ctr[t] += tmp_ctr[t];
    if (rc == -1) return -1;
    if (rc == 1) {
      status_out[i] = 1;
      val_offs_out[i] = lo;
      val_lens_out[i] = vlen;
      return vlen;
    }
    status_out[i] = rc == 0 ? 0 : 2;
    val_offs_out[i] = 0;
    val_lens_out[i] = 0;
    return 0;
  };

  // Parallel chain walks for big batches — the fiber/io_uring MultiGet
  // role (reference db_impl.cc:3026-3227): every structure on the path
  // is read-safe (mutex-sharded block cache, atomic skiplist/trie links,
  // pread), so keys fan out across threads, each with its own contiguous
  // arena slice (value offsets stay global; no post-join copying).
  size_t want = effective_cpus();
  size_t nthreads = std::min<size_t>(std::min<size_t>(want, 8),
                                     (size_t)(n / 64));
  if (nthreads >= 2) {
    std::vector<std::thread> pool;
    std::vector<int64_t> used_per(nthreads, 0);
    std::vector<std::array<int64_t, NC_COUNT>> ctrs(nthreads);
    std::atomic<int> overflow{0};
    bool spawn_fail = false;
    int64_t slice = arena_cap / (int64_t)nthreads;
    auto work = [&](size_t t) {
      int64_t lo = slice * (int64_t)t;
      int64_t hi = t + 1 == nthreads ? arena_cap : lo + slice;
      int64_t pos = lo;
      ctrs[t].fill(0);
      int64_t i0 = n * (int64_t)t / (int64_t)nthreads;
      int64_t i1 = n * (int64_t)(t + 1) / (int64_t)nthreads;
      for (int64_t i = i0; i < i1; i++) {
        int64_t got = walk(i, pos, hi, ctrs[t].data());
        if (got < 0) {
          overflow.store(1, std::memory_order_relaxed);
          return;
        }
        pos += got;
      }
      used_per[t] = pos - lo;
    };
    for (size_t t = 1; t < nthreads; t++) {
      try {
        pool.emplace_back(work, t);
      } catch (...) {
        spawn_fail = true;  // resource exhaustion: sequential fallback
        break;
      }
    }
    if (!spawn_fail) {
      work(0);
      for (auto& th : pool) th.join();
      for (size_t t = 0; t < nthreads; t++)
        for (int x = 0; x < NC_COUNT; x++) counters[x] += ctrs[t][x];
      if (overflow.load()) return -2;  // caller grows + retries
      *arena_used =
          slice * (int64_t)(nthreads - 1) + used_per[nthreads - 1];
      return 0;
    }
    // Thread spawn failed: join what started, then run everything
    // sequentially below (statuses/offsets are simply overwritten);
    // returning -2 here would make the caller grow the arena forever.
    for (auto& th : pool) th.join();
    for (int x = 0; x < NC_COUNT; x++) counters[x] = 0;
  }

  int64_t used = 0;
  for (int64_t i = 0; i < n; i++) {
    int64_t got = walk(i, used, arena_cap, counters);
    if (got < 0) return -2;  // arena exhausted: grow + retry whole batch
    used += got;
  }
  *arena_used = used;
  return 0;
}

// The full read chain: memtable skiplists (newest first), then the SST
// version. Returns 1 found (value in val_out, *val_len set), 0 not found,
// 2 fallback-to-python, -1 val_cap too small (*val_len = needed size).
// src_out: 0 = memtable, 1 = L0, n>=2 = level n-1, -1 = nothing.
// counters: int64[6] = {memtables probed, bloom useful (filtered out),
// bloom consulted-and-passed, block-cache hits, block-cache misses (device
// preads), bytes read from disk}. Always written.
int32_t tpulsm_db_get(void** mem_handles, int32_t n_mems, void* version,
                      const uint8_t* ukey, int32_t klen, uint64_t snap_seq,
                      uint8_t* val_out, int32_t val_cap, int32_t* val_len,
                      int32_t* src_out, int64_t* counters) {
  return tpulsm_db_get_kinds(mem_handles, nullptr, n_mems, version, ukey,
                             klen, snap_seq, val_out, val_cap, val_len,
                             src_out, counters);
}

int32_t tpulsm_db_get_kinds(void** mem_handles, const int32_t* mem_kinds,
                            int32_t n_mems, void* version,
                            const uint8_t* ukey, int32_t klen,
                            uint64_t snap_seq, uint8_t* val_out,
                            int32_t val_cap, int32_t* val_len,
                            int32_t* src_out, int64_t* counters) {
  *src_out = -1;
  for (int i = 0; i < NC_COUNT; i++) counters[i] = 0;
  if (klen > 4096) return NGET_FALLBACK;
  uint64_t packed = (snap_seq << 8) | 0x7F;
  uint64_t inv = ~packed;
  for (int32_t m = 0; m < n_mems; m++) {
    counters[NC_MEMS]++;
    uint64_t p2;
    const uint8_t* rec;
    if (mem_kinds && mem_kinds[m] == 1) {
      // Trie rep: newest visible version of exactly this key.
      TVer* v = static_cast<TVer*>(
          tpulsm_trie_seek_ge(mem_handles[m], ukey, (uint32_t)klen, inv));
      if (!v || v->leaf->key_len != (uint32_t)klen ||
          (klen && std::memcmp(v->leaf->key, ukey, (size_t)klen) != 0))
        continue;
      p2 = ~v->inv;
      rec = v->val.load(std::memory_order_acquire);
    } else {
      SkipList* sl = static_cast<SkipList*>(mem_handles[m]);
      SLNode* n = sl->seek_ge(ukey, (uint32_t)klen, inv, nullptr);
      if (!n || n->key_len != (uint32_t)klen ||
          std::memcmp(n->key, ukey, (size_t)klen) != 0)
        continue;
      p2 = ~n->inv_packed;
      rec = n->val.load(std::memory_order_acquire);
    }
    uint8_t vt = (uint8_t)(p2 & 0xFF);
    *src_out = 0;
    if (vt == 0x1) {
      uint32_t vl;
      std::memcpy(&vl, rec, 4);
      if ((int32_t)vl > val_cap) {
        *val_len = (int32_t)vl;
        return -1;
      }
      std::memcpy(val_out, rec + 4, vl);
      *val_len = (int32_t)vl;
      return NGET_FOUND;
    }
    if (vt == 0x0 || vt == 0x7) return NGET_NOTFOUND;  // (single-)delete
    return NGET_FALLBACK;  // merge / blob / anything else
  }
  if (!version) return NGET_NOTFOUND;
  return nversion_get(static_cast<NVersion*>(version), ukey, klen, snap_seq,
                      val_out, val_cap, val_len, src_out, counters);
}

// ---------------------------------------------------------------------------
// Fused group-commit write plane (db/db.py write path). ONE call per write
// group: pass 0 validates every member batch's wire image (supported record
// types, per-batch header counts, optional protection re-hash against the
// carried vectors); then mode bit 0 frames the MERGED WAL record
// gather-style — the 12-byte re-sequenced header plus each member's body
// stream straight into log-format fragments, byte-identical to db/log.py
// LogWriter.add_record, with no merged-batch copy on the Python side — and
// mode bit 1 applies every counted record to the target memtable rep with
// consecutive seqnos. A batch this parser cannot take (CF-prefixed records,
// range deletes, corruption) rejects the WHOLE group with NOTHING framed or
// inserted, and the caller falls back to the Python interiors.
// ---------------------------------------------------------------------------

extern "C++" {
#include <condition_variable>
namespace {

// Persistent worker pool for the group-apply phase: per-group
// std::thread spawns cost ~30-50us — more than the insert work of a
// typical group — so the write plane keeps a small lazily-grown pool
// alive for the process. One job runs at a time (run_mu): the caller
// publishes a shared closure, k workers plus the caller execute it, the
// caller waits for all k. Workers idle on a condvar between groups.
struct ApplyPool {
  std::mutex run_mu;  // serializes whole jobs
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  const std::function<void()>* fn = nullptr;
  uint64_t gen = 0;
  int want = 0, started = 0, done_count = 0;
  bool shutdown = false;
  std::vector<std::thread> ths;

  ~ApplyPool() {
    {
      std::lock_guard<std::mutex> lk(mu);
      shutdown = true;
    }
    cv_work.notify_all();
    for (auto& t : ths) t.join();
  }

  void worker() {
    uint64_t seen = 0;
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      cv_work.wait(lk, [&] {
        return shutdown || (gen != seen && started < want);
      });
      if (shutdown) return;
      seen = gen;
      if (started >= want) continue;
      started++;
      const std::function<void()>* f = fn;
      lk.unlock();
      (*f)();
      lk.lock();
      if (++done_count == want) cv_done.notify_all();
    }
  }

  // Runs f on min(k, pool) workers concurrently with the caller.
  void run(const std::function<void()>& f, int k) {
    std::lock_guard<std::mutex> job(run_mu);
    std::unique_lock<std::mutex> lk(mu);
    while ((int)ths.size() < k) {
      try {
        ths.emplace_back([this] { worker(); });
      } catch (...) {
        break;  // pid limits: run with what we have
      }
    }
    if ((int)ths.size() < k) k = (int)ths.size();
    if (k <= 0) {
      lk.unlock();
      f();
      return;
    }
    fn = &f;
    want = k;
    started = 0;
    done_count = 0;
    gen++;
    cv_work.notify_all();
    lk.unlock();
    f();  // caller participates
    lk.lock();
    cv_done.wait(lk, [&] { return done_count == want; });
  }
};

static ApplyPool& apply_pool() {
  static ApplyPool p;
  return p;
}

struct GcPiece {
  const uint8_t* p;
  int64_t n;
};

// Gather cursor over the virtual concatenation [header | body0 | body1 ...]:
// copies fragment bytes into the framed output while extending the record
// CRC, so the merged WAL image is never materialized contiguously.
struct GcCursor {
  const GcPiece* pieces;
  int64_t n;
  int64_t pi = 0;
  int64_t off = 0;
  void copy(uint8_t* dst, int64_t m, uint32_t* crc) {
    while (m > 0) {
      int64_t avail = pieces[pi].n - off;
      if (avail <= 0) {
        pi++;
        off = 0;
        continue;
      }
      int64_t take = avail < m ? avail : m;
      std::memcpy(dst, pieces[pi].p + off, (size_t)take);
      *crc = tpulsm_crc32c_extend(*crc, dst, (size_t)take);
      dst += take;
      off += take;
      m -= take;
    }
  }
};

// Frame one logical record of total_len bytes (read through cur) into the
// 32KiB-block log format, starting at block_offset. log_number >= 0 selects
// the recyclable record types stamped with that number. Byte-identical to
// LogWriter.add_record / _emit (db/log.py). Returns framed bytes written,
// or -3 when out_cap is too small.
static int64_t gc_frame_merged(GcCursor& cur, int64_t total_len,
                               int64_t block_offset, int64_t log_number,
                               uint8_t* out, int64_t cap,
                               int64_t* new_block_offset) {
  const int64_t kBlock = 32768;
  const bool recycled = log_number >= 0;
  const int64_t hdr = recycled ? 11 : 7;
  int64_t used = 0, left = total_len;
  bool begin = true;
  while (true) {
    int64_t leftover = kBlock - block_offset;
    if (leftover < hdr) {
      if (leftover > 0) {
        if (used + leftover > cap) return -3;
        std::memset(out + used, 0, (size_t)leftover);
        used += leftover;
      }
      block_offset = 0;
      leftover = kBlock;
    }
    int64_t avail = leftover - hdr;
    int64_t frag = left < avail ? left : avail;
    bool end = (left == frag);
    uint8_t t = begin && end ? 1 : (begin ? 2 : (end ? 4 : 3));
    if (recycled) t = (uint8_t)(t + 4);
    if (used + hdr + frag > cap) return -3;
    uint8_t* h = out + used;
    uint32_t crc = tpulsm_crc32c_extend(0, &t, 1);
    if (recycled) {
      uint32_t ln = (uint32_t)log_number;
      std::memcpy(h + 7, &ln, 4);
      crc = tpulsm_crc32c_extend(crc, h + 7, 4);
    }
    cur.copy(h + hdr, frag, &crc);
    uint32_t masked = ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
    std::memcpy(h, &masked, 4);
    h[4] = (uint8_t)(frag & 0xFF);
    h[5] = (uint8_t)((frag >> 8) & 0xFF);
    h[6] = t;
    used += hdr + frag;
    block_offset += hdr + frag;
    left -= frag;
    begin = false;
    if (left == 0) break;
  }
  *new_block_offset = block_offset;
  return used;
}

}  // namespace
}  // extern "C++"

// mem/mem_kind: target rep (0 = SkipList*, 1 = TrieRep*); may be null when
//   mode bit 1 is clear.
// reps/lens/n_batches: member batch wire images, group order.
// prots/n_prots/pb: concatenated per-record protection vectors in group
//   order, or null (unprotected).
// mode: bit 0 (1) = frame WAL, bit 1 (2) = insert into the memtable,
//   bit 2 (4) = skip the validation pass — ONLY legal when a prior call on
//   the SAME buffers (the leader's frame call, microseconds earlier under
//   the commit mutex) already validated them; protection was checked there.
//   bit 3 (8) = protection FILL: prots is an OUT buffer of capacity
//   n_prots — the validation pass writes each counted record's truncated
//   checksum instead of comparing (fusing tpulsm_wb_protect into the WAL
//   frame walk: the protected write path hashes each record ONCE).
// block_offset/log_number: the LogWriter's framing state (log_number >= 0
//   selects the recyclable format stamped with that number; -1 = classic).
// out[0]=framed bytes, out[1]=new block offset, out[2]=memtable byte delta,
// out[3]=point-delete count, out[4]=merged (unframed) record length,
// out[5..7]=interior phase timings in ns (validate / WAL frame / memtable
// insert) for the telemetry plane — the caller must size out >= 8.
// Returns total counted records, or -2 (unsupported record: Python path),
// -3 (wal_cap too small), -4 (corrupt image), -5 - i (protection mismatch
// at group record index i).
int64_t tpulsm_wb_group_commit(void* mem, int32_t mem_kind,
                               const uint8_t* const* reps,
                               const int64_t* lens,
                               int64_t n_batches, uint64_t first_seq,
                               uint64_t* prots, int64_t n_prots,
                               int32_t pb, int32_t mode, int64_t block_offset,
                               int64_t log_number, uint8_t* wal_out,
                               int64_t wal_cap, int64_t* out) {
  const uint64_t kKey = 0x9E3779B97F4A7C15ull, kVal = 0xC2B2AE3D27D4EB4Full,
                 kType = 0x165667B19E3779F9ull, kCf = 0x27D4EB2F165667C5ull;
  const uint64_t mask = prot_trunc_mask(pb);
  auto gc_now_ns = []() -> int64_t {
    return (int64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  };
  const int64_t t_entry_ns = gc_now_ns();
  int64_t total = 0;
  if (mode & 4) {
    // Caller vouches (see above): counts come from the batch headers.
    for (int64_t b = 0; b < n_batches; b++) {
      const uint8_t* rep = (const uint8_t*)reps[b];
      total += (uint32_t)rep[8] | ((uint32_t)rep[9] << 8) |
               ((uint32_t)rep[10] << 16) | ((uint32_t)rep[11] << 24);
    }
  }
  // Pass 0: validate every batch — nothing is framed or inserted unless the
  // WHOLE group parses and (when protected) every record re-hashes clean.
  for (int64_t b = 0; (mode & 4) == 0 && b < n_batches; b++) {
    const uint8_t* rep = (const uint8_t*)reps[b];
    int64_t len = lens[b];
    if (len < 12) return -4;
    const uint8_t* end = rep + len;
    const uint8_t* p = rep + 12;
    uint32_t hdr_count = (uint32_t)rep[8] | ((uint32_t)rep[9] << 8) |
                         ((uint32_t)rep[10] << 16) | ((uint32_t)rep[11] << 24);
    int64_t count = 0;
    while (p < end) {
      uint8_t t = *p++;
      if (t & 0x80) return -2;  // CF-prefixed record: Python path
      uint32_t klen, vlen = 0;
      p = get_varint32(p, end, &klen);
      if (!p || p + klen > end) return -4;
      const uint8_t* k = p;
      p += klen;
      const uint8_t* v = p;
      if (t == 0x1 || t == 0x2 || t == 0x16) {  // VALUE / MERGE / WIDE
        p = get_varint32(p, end, &vlen);
        if (!p || p + vlen > end) return -4;
        v = p;
        p += vlen;
      } else if (t == 0x0 || t == 0x7) {  // (SINGLE_)DELETION: key only
      } else if (t == 0x3) {              // LOG_DATA: klen was the blob
        continue;
      } else {
        return -2;  // RANGE_DELETION etc.: Python path
      }
      if (prots) {
        int64_t gi = total + count;
        if (gi >= n_prots) return (mode & 8) ? -3 : -5 - gi;
        uint64_t cs = prot_mix(kKey ^ (uint64_t)zcrc32(k, klen) ^
                               ((uint64_t)klen << 32)) ^
                      prot_mix(kVal ^ (uint64_t)zcrc32(v, vlen) ^
                               ((uint64_t)vlen << 32)) ^
                      prot_mix(kType ^ (uint64_t)t) ^ prot_mix(kCf ^ 1ull);
        if (mode & 8)
          prots[gi] = cs & mask;
        else if ((cs & mask) != prots[gi])
          return -5 - gi;
      }
      count++;
    }
    if ((uint32_t)count != hdr_count) return -4;
    total += count;
  }
  if ((mode & 4) == 0 && prots && (mode & 8) == 0 && total != n_prots)
    return -5 - total;
  const int64_t t_validated_ns = gc_now_ns();
  int64_t merged_len = 12;
  for (int64_t b = 0; b < n_batches; b++) merged_len += lens[b] - 12;
  int64_t wal_len = 0, new_bo = block_offset;
  if (mode & 1) {
    uint8_t hdr12[12];
    for (int i = 0; i < 8; i++) hdr12[i] = (uint8_t)(first_seq >> (8 * i));
    uint32_t tc = (uint32_t)total;
    for (int i = 0; i < 4; i++) hdr12[8 + i] = (uint8_t)(tc >> (8 * i));
    std::vector<GcPiece> pieces;
    pieces.reserve((size_t)n_batches + 1);
    pieces.push_back({hdr12, 12});
    for (int64_t b = 0; b < n_batches; b++)
      if (lens[b] > 12)
        pieces.push_back({(const uint8_t*)reps[b] + 12, lens[b] - 12});
    GcCursor cur{pieces.data(), (int64_t)pieces.size()};
    wal_len = gc_frame_merged(cur, merged_len, block_offset, log_number,
                              wal_out, wal_cap, &new_bo);
    if (wal_len < 0) return wal_len;
  }
  const int64_t t_framed_ns = gc_now_ns();
  int64_t delta = 0, deletes = 0;
  if (mode & 2) {
    SkipList* sl = mem_kind == 0 ? static_cast<SkipList*>(mem) : nullptr;
    TrieRep* tr = mem_kind == 1 ? static_cast<TrieRep*>(mem) : nullptr;
    if (!sl && !tr) return -2;
    // Work units: contiguous record ranges with a known start seq — one
    // per small batch, plus INTRA-batch splits for large batches (a quick
    // varint walk, ~10x cheaper than the inserts it parallelizes), so
    // even a single-batch group fans out across the ApplyPool. Both
    // native reps take concurrent inserts (CAS splice / per-stripe
    // mutexes) and records are order-independent (distinct seqnos), so
    // unit order does not matter.
    struct GcUnit {
      const uint8_t* p;
      const uint8_t* end;
      uint64_t seq;
    };
    size_t nt_max = std::min(effective_cpus(), (size_t)8);
    int64_t S = total / (int64_t)(2 * nt_max);
    if (S < 256) S = 256;
    std::vector<GcUnit> units;
    units.reserve((size_t)(total / S + n_batches + 1));
    {
      uint64_t seq = first_seq;
      for (int64_t b = 0; b < n_batches; b++) {
        const uint8_t* rep = (const uint8_t*)reps[b];
        const uint8_t* end = rep + lens[b];
        uint32_t cnt = (uint32_t)rep[8] | ((uint32_t)rep[9] << 8) |
                       ((uint32_t)rep[10] << 16) | ((uint32_t)rep[11] << 24);
        if ((int64_t)cnt <= S) {
          units.push_back({rep + 12, end, seq});
          seq += cnt;
          continue;
        }
        const uint8_t* p = rep + 12;
        const uint8_t* ustart = p;
        uint64_t useq = seq;
        int64_t in_unit = 0;
        while (p < end) {
          uint8_t t = *p++;
          uint32_t klen, vlen;
          p = get_varint32(p, end, &klen);
          if (!p) break;  // validated earlier; defensive
          p += klen;
          if (t == 0x1 || t == 0x2 || t == 0x16) {
            p = get_varint32(p, end, &vlen);
            if (!p) break;
            p += vlen;
          } else if (t == 0x3) {
            continue;
          }
          in_unit++;
          seq++;
          if (in_unit >= S) {
            units.push_back({ustart, p, useq});
            ustart = p;
            useq = seq;
            in_unit = 0;
          }
        }
        if (p > ustart) units.push_back({ustart, p, useq});
      }
    }
    std::atomic<int64_t> a_delta{0}, a_deletes{0};
    std::atomic<size_t> next_unit{0};
    size_t n_units = units.size();
    auto apply = [&]() {
      int64_t d = 0, dl = 0;
      for (;;) {
        size_t u = next_unit.fetch_add(1, std::memory_order_relaxed);
        if (u >= n_units) break;
        const uint8_t* p = units[u].p;
        const uint8_t* end = units[u].end;
        uint64_t seq = units[u].seq;
        while (p < end) {
          uint8_t t = *p++;
          uint32_t klen, vlen = 0;
          p = get_varint32(p, end, &klen);
          if (!p) break;  // validated earlier; defensive
          const uint8_t* k = p;
          p += klen;
          const uint8_t* v = p;
          if (t == 0x1 || t == 0x2 || t == 0x16) {
            p = get_varint32(p, end, &vlen);
            if (!p) break;
            v = p;
            p += vlen;
          } else if (t == 0x3) {
            continue;
          }
          uint64_t inv = ~((seq << 8) | (uint64_t)t);
          if (sl)
            sl->insert(k, klen, inv, v, vlen);
          else
            trie_insert(tr, k, klen, inv, v, vlen);
          d += (int64_t)klen + vlen + 24;
          if (t == 0x0 || t == 0x7) dl++;
          seq++;
        }
      }
      a_delta.fetch_add(d, std::memory_order_relaxed);
      a_deletes.fetch_add(dl, std::memory_order_relaxed);
    };
    size_t nt = 1;
    if (n_units > 1 && total >= 512) nt = std::min(nt_max, n_units);
    if (nt > 1) {
      apply_pool().run(apply, (int)nt - 1);
    } else {
      apply();
    }
    delta = a_delta.load();
    deletes = a_deletes.load();
  }
  out[0] = wal_len;
  out[1] = new_bo;
  out[2] = delta;
  out[3] = deletes;
  out[4] = merged_len;
  out[5] = t_validated_ns - t_entry_ns;
  out[6] = t_framed_ns - t_validated_ns;
  out[7] = gc_now_ns() - t_framed_ns;
  return total;
}

// ---------------------------------------------------------------------------
// Zip-table data plane (table/zip_table.py): batched builder kernels that
// replace the numpy matrix materialization in write_tables_zip_columnar
// (key gather + front-coding + value group compression were the whole
// serial cost), and reader kernels that decode front-coded key groups /
// compressed value groups straight into the scan plane's columnar
// buffers. The builder kernels must be BIT-IDENTICAL to the Python
// encoders — same front-coding ties, same ZDICT sampling stride, same
// per-group "compress only if smaller" decision — because the Python
// writer is the parity oracle (tests/test_zip_table.py).
// ---------------------------------------------------------------------------

// newkey[i] = 1 iff the first `uklen` key bytes of row i differ from row
// i-1 (row 0 always 1): the survivor-boundary vector the zip writer cuts
// value groups on. offs are per-row byte offsets into key_buf. Returns n,
// or -3 on out-of-range offsets.
int64_t tpulsm_zip_newkey(const uint8_t* key_buf, int64_t key_buf_len,
                          const int64_t* offs, int64_t n, int32_t uklen,
                          uint8_t* out) {
  if (n <= 0 || uklen < 0) return -3;
  for (int64_t i = 0; i < n; i++)
    if (offs[i] < 0 || offs[i] > key_buf_len - uklen) return -3;
  out[0] = 1;
  size_t nthreads = effective_cpus();
  if (nthreads > 8) nthreads = 8;
  if (n < (1 << 16)) nthreads = 1;
  std::atomic<int64_t> next_c{1};
  const int64_t kChunk = 1 << 15;
  auto worker = [&] {
    while (true) {
      int64_t lo = next_c.fetch_add(kChunk, std::memory_order_relaxed);
      if (lo >= n) return;
      int64_t hi = lo + kChunk < n ? lo + kChunk : n;
      for (int64_t i = lo; i < hi; i++)
        out[i] = std::memcmp(key_buf + offs[i], key_buf + offs[i - 1],
                             (size_t)uklen) != 0;
    }
  };
  if (nthreads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    for (size_t i = 1; i < nthreads; i++) {
      try {
        pool.emplace_back(worker);
      } catch (...) {
        break;
      }
    }
    worker();
    for (auto& w : pool) w.join();
  }
  return n;
}

// Front-code one zip segment: rows are full internal keys of uniform
// length `klen` at key_buf[offs[i]], with the 8-byte trailer REPLACED by
// the little-endian bytes of trailer_ov[i] when >= 0 (the compaction's
// seqno-zeroing patch, applied on the fly instead of on a materialized
// matrix). Emits (plen, slen) meta pairs (u16 LE when meta16 else u8),
// the concatenated suffix stream, and the per-group suffix offsets
// (u32). Prefix lengths tie byte-for-byte with the numpy argmin over the
// FULL key including the patched trailer. Returns the suffix length, or
// -2 sfx_cap too small, -3 invalid shape/offsets.
int64_t tpulsm_zip_encode_keys(
    const uint8_t* key_buf, int64_t key_buf_len, const int64_t* offs,
    int64_t n, int32_t klen, const int64_t* trailer_ov, int32_t group,
    int32_t meta16, uint8_t* meta_out, uint8_t* sfx_out, int64_t sfx_cap,
    uint8_t* gso_out) {
  if (n <= 0 || group <= 0 || klen < 8) return -3;
  if (meta16 ? klen > 0xFFFF : klen > 0xFF) return -3;
  for (int64_t i = 0; i < n; i++)
    if (offs[i] < 0 || offs[i] > key_buf_len - klen) return -3;
  const int32_t uk = klen - 8;
  auto tbyte = [&](int64_t i, int32_t j) -> uint8_t {
    int64_t ov = trailer_ov[i];
    if (ov >= 0) return (uint8_t)((uint64_t)ov >> (8 * (j - uk)));
    return key_buf[offs[i] + j];
  };
  std::vector<uint32_t> pl(n, 0);
  size_t nthreads = effective_cpus();
  if (nthreads > 8) nthreads = 8;
  if (n < (1 << 14)) nthreads = 1;
  {
    std::atomic<int64_t> next_c{0};
    const int64_t kChunk = 1 << 13;
    auto worker = [&] {
      while (true) {
        int64_t lo = next_c.fetch_add(kChunk, std::memory_order_relaxed);
        if (lo >= n) return;
        int64_t hi = lo + kChunk < n ? lo + kChunk : n;
        for (int64_t i = lo; i < hi; i++) {
          if (i == 0 || i % group == 0) continue;  // group heads: plen 0
          const uint8_t* a = key_buf + offs[i - 1];
          const uint8_t* b = key_buf + offs[i];
          int32_t p = 0;
          while (p < uk && a[p] == b[p]) p++;
          if (p == uk)
            while (p < klen && tbyte(i - 1, p) == tbyte(i, p)) p++;
          pl[i] = (uint32_t)p;
        }
      }
    };
    if (nthreads <= 1) {
      worker();
    } else {
      std::vector<std::thread> pool;
      for (size_t i = 1; i < nthreads; i++) {
        try {
          pool.emplace_back(worker);
        } catch (...) {
          break;
        }
      }
      worker();
      for (auto& w : pool) w.join();
    }
  }
  // Serial: meta pairs, per-row suffix offsets, group directory.
  std::vector<int64_t> soff(n);
  int64_t cum = 0;
  for (int64_t i = 0; i < n; i++) {
    uint32_t p = pl[i], s = (uint32_t)klen - p;
    if (meta16) {
      uint16_t a = (uint16_t)p, b = (uint16_t)s;
      std::memcpy(meta_out + 4 * i, &a, 2);
      std::memcpy(meta_out + 4 * i + 2, &b, 2);
    } else {
      meta_out[2 * i] = (uint8_t)p;
      meta_out[2 * i + 1] = (uint8_t)s;
    }
    soff[i] = cum;
    if (i % group == 0) {
      if (cum > 0xFFFFFFFFll) return -3;  // u32 directory would wrap
      uint32_t v = (uint32_t)cum;
      std::memcpy(gso_out + 4 * (i / group), &v, 4);
    }
    cum += s;
  }
  if (cum > sfx_cap) return -2;
  // Parallel: suffix byte emission.
  {
    std::atomic<int64_t> next_c{0};
    const int64_t kChunk = 1 << 13;
    auto worker = [&] {
      while (true) {
        int64_t lo = next_c.fetch_add(kChunk, std::memory_order_relaxed);
        if (lo >= n) return;
        int64_t hi = lo + kChunk < n ? lo + kChunk : n;
        for (int64_t i = lo; i < hi; i++) {
          int32_t j = (int32_t)pl[i];
          uint8_t* dst = sfx_out + soff[i];
          const uint8_t* src = key_buf + offs[i];
          if (j < uk) {
            std::memcpy(dst, src + j, (size_t)(uk - j));
            dst += uk - j;
            j = uk;
          }
          int64_t ov = trailer_ov[i];
          if (ov >= 0) {
            for (; j < klen; j++)
              *dst++ = (uint8_t)((uint64_t)ov >> (8 * (j - uk)));
          } else if (j < klen) {
            std::memcpy(dst, src + j, (size_t)(klen - j));
          }
        }
      }
    };
    if (nthreads <= 1) {
      worker();
    } else {
      std::vector<std::thread> pool;
      for (size_t i = 1; i < nthreads; i++) {
        try {
          pool.emplace_back(worker);
        } catch (...) {
          break;
        }
      }
      worker();
      for (auto& w : pool) w.join();
    }
  }
  return cum;
}

// Value-plane encoder for one zip segment: gathers each VG-entry value
// group from the columnar value buffer, trains one ZDICT dictionary over
// every (ngroups//256)-th group (the Python sampling stride), compresses
// groups >= 32 raw bytes in parallel, and packs payloads ("compress only
// if strictly smaller" per group, flag bit set) with the u32 offset
// directory. dict_out must hold max_dict_bytes; flags_out arrives
// zeroed. out_meta returns [blob_len, dict_len]. Returns the group
// count, or -1 zstd/ZDICT entry points unavailable (Python fallback),
// -2 blob_cap/dict_cap too small, -3 invalid offsets or a compressor
// error.
int64_t tpulsm_zip_encode_values(
    const uint8_t* val_buf, int64_t val_buf_len, const int64_t* offs,
    const int64_t* lens, int64_t n, int32_t vg, int32_t compress,
    int32_t level, int32_t max_dict_bytes, uint8_t* dict_out,
    int64_t dict_cap, uint8_t* blob_out, int64_t blob_cap,
    uint8_t* go_out, uint8_t* flags_out, int64_t* out_meta) {
  if (n <= 0 || vg <= 0) return -3;
  const int64_t ng = (n + vg - 1) / vg;
  std::vector<int64_t> gb(ng + 1, 0);
  for (int64_t i = 0; i < n; i++) {
    if (lens[i] < 0 || offs[i] < 0 || lens[i] > val_buf_len ||
        offs[i] > val_buf_len - lens[i])
      return -3;
    gb[i / vg + 1] += lens[i];
  }
  for (int64_t g = 0; g < ng; g++) gb[g + 1] += gb[g];
  auto gather = [&](int64_t g, uint8_t* dst) {
    int64_t e1 = (g + 1) * (int64_t)vg;
    if (e1 > n) e1 = n;
    for (int64_t i = g * (int64_t)vg; i < e1; i++) {
      std::memcpy(dst, val_buf + offs[i], (size_t)lens[i]);
      dst += lens[i];
    }
  };
  const Codecs& c = codecs();
  int64_t dlen = 0;
  if (compress) {
    if (!c.zstd_cmp || !c.zstd_bound || !c.zstd_err) return -1;
    if (max_dict_bytes > 0 && ng >= 8) {
      if (!c.zdict_train || !c.zdict_err || !c.zstd_cmp_dict ||
          !c.zstd_cctx_new || !c.zstd_cctx_free)
        return -1;
      if (dict_cap < max_dict_bytes) return -2;
      int64_t stride = ng / 256;
      if (stride < 1) stride = 1;
      std::string sblob;
      std::vector<size_t> sizes;
      for (int64_t g = 0; g < ng; g += stride) {
        size_t base = sblob.size();
        sblob.resize(base + (size_t)(gb[g + 1] - gb[g]));
        gather(g, (uint8_t*)&sblob[base]);
        sizes.push_back((size_t)(gb[g + 1] - gb[g]));
      }
      size_t r = c.zdict_train(dict_out, (size_t)max_dict_bytes,
                               sblob.data(), sizes.data(),
                               (unsigned)sizes.size());
      // Training failure is NOT an error: the Python path gets b"" and
      // compresses dictionary-less (utils/codecs.py contract).
      if (!c.zdict_err(r)) dlen = (int64_t)r;
    }
  }
  std::vector<std::string> zs(ng);  // "" → raw payload
  if (compress) {
    size_t nthreads = effective_cpus();
    if (nthreads > 8) nthreads = 8;
    if (ng < 4) nthreads = 1;
    std::atomic<int64_t> nextg{0};
    std::atomic<int> err{0};
    auto worker = [&] {
      void* cctx = nullptr;
      if (dlen > 0) {
        cctx = c.zstd_cctx_new();
        if (!cctx) {
          err.store(1, std::memory_order_relaxed);
          return;
        }
      }
      std::vector<uint8_t> raw;
      while (true) {
        int64_t g = nextg.fetch_add(1, std::memory_order_relaxed);
        if (g >= ng || err.load(std::memory_order_relaxed)) break;
        int64_t rsz = gb[g + 1] - gb[g];
        if (rsz < 32) continue;  // python skips tiny groups entirely
        if ((int64_t)raw.size() < rsz) raw.resize((size_t)rsz);
        gather(g, raw.data());
        size_t bound = c.zstd_bound((size_t)rsz);
        std::string z;
        z.resize(bound);
        size_t zn = dlen > 0
                        ? c.zstd_cmp_dict(cctx, &z[0], bound, raw.data(),
                                          (size_t)rsz, dict_out,
                                          (size_t)dlen, level)
                        : c.zstd_cmp(&z[0], bound, raw.data(), (size_t)rsz,
                                     level);
        if (c.zstd_err(zn)) {
          err.store(2, std::memory_order_relaxed);
          break;
        }
        z.resize(zn);
        zs[g] = std::move(z);
      }
      if (cctx) c.zstd_cctx_free(cctx);
    };
    if (nthreads <= 1) {
      worker();
    } else {
      std::vector<std::thread> pool;
      for (size_t i = 1; i < nthreads; i++) {
        try {
          pool.emplace_back(worker);
        } catch (...) {
          break;
        }
      }
      worker();
      for (auto& w : pool) w.join();
    }
    if (err.load()) return err.load() == 1 ? -1 : -3;
  }
  // Serial pack: compressed payload only when strictly smaller.
  int64_t cum = 0;
  uint32_t zero = 0;
  std::memcpy(go_out, &zero, 4);
  for (int64_t g = 0; g < ng; g++) {
    int64_t rsz = gb[g + 1] - gb[g];
    bool use_z = !zs[g].empty() && (int64_t)zs[g].size() < rsz;
    int64_t psz = use_z ? (int64_t)zs[g].size() : rsz;
    if (psz > blob_cap - cum) return -2;
    if (use_z) {
      std::memcpy(blob_out + cum, zs[g].data(), (size_t)psz);
      flags_out[g >> 3] |= (uint8_t)(1 << (g & 7));
    } else {
      gather(g, blob_out + cum);
    }
    cum += psz;
    if (cum > 0xFFFFFFFFll) return -3;  // u32 directory would wrap
    uint32_t v = (uint32_t)cum;
    std::memcpy(go_out + 4 * (g + 1), &v, 4);
  }
  out_meta[0] = cum;
  out_meta[1] = dlen;
  return ng;
}

// Reconstruct full internal keys for zip entries [e0, e1) into a
// columnar slab: key_offs/key_lens are emitted per entry (offsets
// ABSOLUTE via key_base). The meta/suffix/directory buffers come straight
// from an on-disk file, so every offset is treated as hostile and
// bounds-checked before use. Returns bytes written, or -2 key_cap too
// small, -3 malformed sections/ranges.
int64_t tpulsm_zip_decode_keys(
    const uint8_t* kmeta, int64_t kmeta_len, int32_t meta16,
    const uint8_t* ksfx, int64_t ksfx_len, const uint8_t* kgso,
    int64_t kgso_len, int64_t n, int32_t group, int64_t e0, int64_t e1,
    uint8_t* key_out, int64_t key_cap, int64_t* key_offs,
    int64_t* key_lens, int64_t key_base) {
  const int64_t kMaxKey = 1 << 17;
  if (n < 0 || group <= 0 || e0 < 0 || e0 > e1 || e1 > n) return -3;
  if (e0 == e1) return 0;
  const int64_t msz = meta16 ? 4 : 2;
  if (n > kmeta_len / msz) return -3;
  const int64_t ng = (n + group - 1) / group;
  if (ng > kgso_len / 4) return -3;
  auto meta_at = [&](int64_t i, uint32_t* p, uint32_t* s) {
    if (meta16) {
      uint16_t a, b;
      std::memcpy(&a, kmeta + 4 * i, 2);
      std::memcpy(&b, kmeta + 4 * i + 2, 2);
      *p = a;
      *s = b;
    } else {
      *p = kmeta[2 * i];
      *s = kmeta[2 * i + 1];
    }
  };
  const int64_t g0 = e0 / group, g1 = (e1 - 1) / group;
  // Serial validation + length prefix: the parallel decode below trusts
  // exactly what this pass proves (front-coding chain, suffix bounds).
  int64_t cum = 0;
  for (int64_t g = g0; g <= g1; g++) {
    uint64_t so = zload_u32(kgso + 4 * g);
    if (so > (uint64_t)ksfx_len) return -3;
    uint64_t klen_prev = 0;
    int64_t jend = (g + 1) * (int64_t)group;
    if (jend > e1) jend = e1;
    for (int64_t j = g * (int64_t)group; j < jend; j++) {
      uint32_t p, s;
      meta_at(j, &p, &s);
      if (j % group == 0 && p != 0) return -3;
      uint64_t klen = (uint64_t)p + s;
      if (p > klen_prev || klen == 0 || klen > (uint64_t)kMaxKey) return -3;
      if (s > (uint64_t)ksfx_len - so) return -3;
      so += s;
      klen_prev = klen;
      if (j >= e0) {
        key_offs[j - e0] = key_base + cum;
        key_lens[j - e0] = (int64_t)klen;
        cum += (int64_t)klen;
      }
    }
  }
  if (cum > key_cap) return -2;
  size_t nthreads = effective_cpus();
  if (nthreads > 8) nthreads = 8;
  if (g1 - g0 < 8) nthreads = 1;
  std::atomic<int64_t> nextg{g0};
  auto worker = [&] {
    std::vector<uint8_t> cur((size_t)kMaxKey);
    while (true) {
      int64_t g = nextg.fetch_add(1, std::memory_order_relaxed);
      if (g > g1) return;
      uint64_t so = zload_u32(kgso + 4 * g);
      int64_t jend = (g + 1) * (int64_t)group;
      if (jend > e1) jend = e1;
      for (int64_t j = g * (int64_t)group; j < jend; j++) {
        uint32_t p, s;
        meta_at(j, &p, &s);
        std::memcpy(cur.data() + p, ksfx + so, s);
        so += s;
        if (j >= e0)
          std::memcpy(key_out + (key_offs[j - e0] - key_base), cur.data(),
                      (size_t)(p + s));
      }
    }
  };
  if (nthreads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    for (size_t i = 1; i < nthreads; i++) {
      try {
        pool.emplace_back(worker);
      } catch (...) {
        break;
      }
    }
    worker();
    for (auto& w : pool) w.join();
  }
  return cum;
}

// Bulk-decode zip value groups [g0, g1) into one contiguous raw buffer:
// raw_offs (g1-g0+1 entries, raw_offs[0] == 0) gives each group's output
// offset AND expected raw size — the caller derives both from the
// v.lens section, and a group that inflates to anything else is
// corruption. Raw (unflagged) groups memcpy straight through. Returns
// total bytes, or -1 zstd unavailable for a flagged group, -2 out_cap
// too small, -3 malformed directory/payload.
int64_t tpulsm_zip_group_decode(
    const uint8_t* vblob, int64_t vblob_len, const uint8_t* vgo,
    int64_t vgo_len, const uint8_t* vflags, int64_t vflags_len,
    const uint8_t* vdict, int64_t vdict_len, int64_t g0, int64_t g1,
    const int64_t* raw_offs, uint8_t* out, int64_t out_cap) {
  if (g0 < 0 || g1 < g0) return -3;
  if (g0 == g1) return 0;
  if (g1 > vgo_len / 4 - 1) return -3;
  if (vflags_len < (g1 + 7) / 8) return -3;
  if (raw_offs[0] != 0) return -3;
  bool any_z = false;
  for (int64_t g = g0; g < g1; g++) {
    int64_t k = g - g0;
    if (raw_offs[k + 1] < raw_offs[k]) return -3;
    uint64_t p0 = zload_u32(vgo + 4 * g);
    uint64_t p1 = zload_u32(vgo + 4 * (g + 1));
    if (p1 < p0 || p1 > (uint64_t)vblob_len) return -3;
    bool flagged = (vflags[g >> 3] >> (g & 7)) & 1;
    if (flagged)
      any_z = true;
    else if (p1 - p0 != (uint64_t)(raw_offs[k + 1] - raw_offs[k]))
      return -3;
  }
  if (raw_offs[g1 - g0] > out_cap) return -2;
  const Codecs& c = codecs();
  if (any_z && (!c.zstd_dec_dict || !c.zstd_dctx_new || !c.zstd_dctx_free))
    return -1;
  if (any_z && vdict_len > 0 && !vdict) return -3;
  size_t nthreads = effective_cpus();
  if (nthreads > 8) nthreads = 8;
  if (g1 - g0 < 4) nthreads = 1;
  std::atomic<int64_t> nextg{g0};
  std::atomic<int> err{0};
  auto worker = [&] {
    void* dctx = nullptr;
    while (true) {
      int64_t g = nextg.fetch_add(1, std::memory_order_relaxed);
      if (g >= g1 || err.load(std::memory_order_relaxed)) break;
      int64_t k = g - g0;
      uint64_t p0 = zload_u32(vgo + 4 * g);
      uint64_t p1 = zload_u32(vgo + 4 * (g + 1));
      uint8_t* dst = out + raw_offs[k];
      size_t rawsz = (size_t)(raw_offs[k + 1] - raw_offs[k]);
      if (!((vflags[g >> 3] >> (g & 7)) & 1)) {
        std::memcpy(dst, vblob + p0, rawsz);
        continue;
      }
      if (!dctx) {
        dctx = c.zstd_dctx_new();
        if (!dctx) {
          err.store(1, std::memory_order_relaxed);
          break;
        }
      }
      size_t got = c.zstd_dec_dict(dctx, dst, rawsz, vblob + p0,
                                   (size_t)(p1 - p0),
                                   vdict_len > 0 ? vdict : nullptr,
                                   (size_t)vdict_len);
      if ((c.zstd_err && c.zstd_err(got)) || got != rawsz) {
        err.store(2, std::memory_order_relaxed);
        break;
      }
    }
    if (dctx) c.zstd_dctx_free(dctx);
  };
  if (nthreads <= 1) {
    worker();
  } else {
    std::vector<std::thread> pool;
    for (size_t i = 1; i < nthreads; i++) {
      try {
        pool.emplace_back(worker);
      } catch (...) {
        break;
      }
    }
    worker();
    for (auto& w : pool) w.join();
  }
  int e = err.load();
  if (e == 1) return -1;
  if (e) return -3;
  return raw_offs[g1 - g0];
}

}  // extern "C"
