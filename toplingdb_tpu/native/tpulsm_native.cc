// Native C++ core for toplingdb_tpu.
//
// The reference implements these primitives in C++ (util/crc32c.cc,
// util/xxhash.h, util/hash.cc in /root/reference); we do the same, exposed
// through a plain C ABI consumed via ctypes. Design is original: table-driven
// slicing-by-8 CRC32C and a from-spec xxhash64.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -o _tpulsm_native.so tpulsm_native.cc
#include <cstdint>
#include <cstddef>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, polynomial 0x82f63b78 reflected), slicing-by-8.
// Semantics match the reference util/crc32c.h: Value/Extend plus the rotated
// mask used to store CRCs of CRC-carrying payloads.
// ---------------------------------------------------------------------------

static uint32_t kCrcTable[8][256];
static bool kCrcInit = false;

static void crc32c_init() {
  if (kCrcInit) return;
  const uint32_t poly = 0x82f63b78u;
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    kCrcTable[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = kCrcTable[0][i];
    for (int t = 1; t < 8; t++) {
      c = kCrcTable[0][c & 0xff] ^ (c >> 8);
      kCrcTable[t][i] = c;
    }
  }
  kCrcInit = true;
}

uint32_t tpulsm_crc32c_extend(uint32_t crc, const uint8_t* data, size_t n) {
  crc32c_init();
  uint32_t c = crc ^ 0xffffffffu;
  // Align to 8 bytes.
  while (n && (reinterpret_cast<uintptr_t>(data) & 7)) {
    c = kCrcTable[0][(c ^ *data++) & 0xff] ^ (c >> 8);
    n--;
  }
  while (n >= 8) {
    uint64_t w;
    std::memcpy(&w, data, 8);
    w ^= c;
    c = kCrcTable[7][w & 0xff] ^ kCrcTable[6][(w >> 8) & 0xff] ^
        kCrcTable[5][(w >> 16) & 0xff] ^ kCrcTable[4][(w >> 24) & 0xff] ^
        kCrcTable[3][(w >> 32) & 0xff] ^ kCrcTable[2][(w >> 40) & 0xff] ^
        kCrcTable[1][(w >> 48) & 0xff] ^ kCrcTable[0][(w >> 56) & 0xff];
    data += 8;
    n -= 8;
  }
  while (n--) {
    c = kCrcTable[0][(c ^ *data++) & 0xff] ^ (c >> 8);
  }
  return c ^ 0xffffffffu;
}

// ---------------------------------------------------------------------------
// xxHash64 — implemented from the public spec. Used for bloom-filter probes
// and general hashing (the reference vendors xxhash in util/xxhash.h).
// ---------------------------------------------------------------------------

static const uint64_t P1 = 11400714785074694791ULL;
static const uint64_t P2 = 14029467366897019727ULL;
static const uint64_t P3 = 1609587929392839161ULL;
static const uint64_t P4 = 9650029242287828579ULL;
static const uint64_t P5 = 2870177450012600261ULL;

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t xxh_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t xxh_merge_round(uint64_t acc, uint64_t val) {
  val = xxh_round(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

static inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

uint64_t tpulsm_xxh64(const uint8_t* data, size_t len, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = xxh_round(v1, read64(p)); p += 8;
      v2 = xxh_round(v2, read64(p)); p += 8;
      v3 = xxh_round(v3, read64(p)); p += 8;
      v4 = xxh_round(v4, read64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh_merge_round(h, v1);
    h = xxh_merge_round(h, v2);
    h = xxh_merge_round(h, v3);
    h = xxh_merge_round(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= xxh_round(0, read64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)read32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

}  // extern "C"
