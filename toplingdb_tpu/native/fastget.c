/* CPython C-extension fast path for the native point-read call.
 *
 * The hot Get's per-call cost under ctypes is dominated by argument
 * marshaling (~0.6-0.8us of a ~2.2us call). This METH_FASTCALL shim
 * calls tpulsm_getctx_get directly (symbols resolved from the already-
 * built _tpulsm_native.so via dlopen) and returns the value as bytes —
 * the reference's JNI/C-API binding-layer role for the read path.
 *
 * Protocol: get(ctx_addr, key, snap_seq) ->
 *   bytes  found (value)
 *   None   decisive miss
 *   False  native fallback (the Python state machine must run)
 * The GIL is released around the native chain walk, matching the ctypes
 * path's concurrency (ctx is per-thread).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <dlfcn.h>
#include <stdint.h>

typedef int32_t (*getctx_get_fn)(void*, const uint8_t*, int32_t, uint64_t);
typedef int64_t* (*getctx_out_fn)(void*);
typedef uint8_t* (*getctx_val_fn)(void*);

static getctx_get_fn p_get;
static getctx_out_fn p_out;
static getctx_val_fn p_val;

static PyObject* fg_bind(PyObject* self, PyObject* args) {
  const char* path;
  (void)self;
  if (!PyArg_ParseTuple(args, "s", &path)) return NULL;
  void* h = dlopen(path, RTLD_NOW | RTLD_GLOBAL);
  if (!h) {
    PyErr_SetString(PyExc_OSError, dlerror());
    return NULL;
  }
  p_get = (getctx_get_fn)dlsym(h, "tpulsm_getctx_get");
  p_out = (getctx_out_fn)dlsym(h, "tpulsm_getctx_out");
  p_val = (getctx_val_fn)dlsym(h, "tpulsm_getctx_val");
  if (!p_get || !p_out || !p_val) {
    PyErr_SetString(PyExc_OSError, "tpulsm_getctx_* symbols missing");
    return NULL;
  }
  Py_RETURN_NONE;
}

static PyObject* fg_get(PyObject* self, PyObject* const* args,
                        Py_ssize_t nargs) {
  (void)self;
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError, "get(ctx_addr, key, snap_seq)");
    return NULL;
  }
  if (!p_get) {
    PyErr_SetString(PyExc_RuntimeError, "bind() not called");
    return NULL;
  }
  void* ctx = PyLong_AsVoidPtr(args[0]);
  if (!ctx && PyErr_Occurred()) return NULL;
  char* kbuf;
  Py_ssize_t klen;
  if (PyBytes_AsStringAndSize(args[1], &kbuf, &klen) != 0) return NULL;
  unsigned long long seq = PyLong_AsUnsignedLongLong(args[2]);
  if (PyErr_Occurred()) return NULL;
  int32_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = p_get(ctx, (const uint8_t*)kbuf, (int32_t)klen, (uint64_t)seq);
  Py_END_ALLOW_THREADS
  if (rc == 1) {
    int64_t* out = p_out(ctx);
    return PyBytes_FromStringAndSize((const char*)p_val(ctx),
                                     (Py_ssize_t)out[0]);
  }
  if (rc == 0) Py_RETURN_NONE;
  Py_RETURN_FALSE; /* fallback: run the Python chain */
}

static PyMethodDef fg_methods[] = {
    {"bind", fg_bind, METH_VARARGS,
     "bind(native_so_path): resolve the getctx symbols"},
    {"get", (PyCFunction)(void (*)(void))fg_get, METH_FASTCALL,
     "get(ctx_addr, key, snap_seq) -> bytes | None | False"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fg_module = {
    PyModuleDef_HEAD_INIT, "tpulsm_fastget",
    "ctypes-free fast path for tpulsm_getctx_get", -1, fg_methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit_tpulsm_fastget(void) {
  return PyModule_Create(&fg_module);
}
