/* CPython C-extension fast path for the native point-read call.
 *
 * The hot Get's per-call cost under ctypes is dominated by argument
 * marshaling (~0.6-0.8us of a ~2.2us call). This METH_FASTCALL shim
 * calls tpulsm_getctx_get directly (symbols resolved from the already-
 * built _tpulsm_native.so via dlopen) and returns the value as bytes —
 * the reference's JNI/C-API binding-layer role for the read path.
 *
 * Protocol: get(ctx_addr, key, snap_seq) ->
 *   bytes  found (value)
 *   None   decisive miss
 *   False  native fallback (the Python state machine must run)
 * The GIL is released around the native chain walk, matching the ctypes
 * path's concurrency (ctx is per-thread).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <dlfcn.h>
#include <stdint.h>

typedef int32_t (*getctx_get_fn)(void*, const uint8_t*, int32_t, uint64_t);
typedef int64_t* (*getctx_out_fn)(void*);
typedef uint8_t* (*getctx_val_fn)(void*);
typedef int32_t (*getctx_mget_fn)(void*, const uint8_t*, const int64_t*,
                                  const int32_t*, int64_t, uint64_t,
                                  int8_t*, int64_t*, int64_t*, uint8_t*,
                                  int64_t, int64_t*, int64_t*);

static getctx_get_fn p_get;
static getctx_out_fn p_out;
static getctx_val_fn p_val;
static getctx_mget_fn p_mget;

/* Result-arena cache: taking/returning happens WHILE HOLDING the GIL, so
 * no lock is needed; a second thread entering mid-call simply allocates
 * its own arena. Grown capacity persists (a fresh 1MiB alloc per batch —
 * an mmap + page faults — previously dominated small-batch multigets). */
static uint8_t* g_arena_cache = NULL;
static int64_t g_arena_cache_cap = 0;

static uint8_t* arena_take(int64_t* cap) {
  if (g_arena_cache) {
    uint8_t* a = g_arena_cache;
    *cap = g_arena_cache_cap;
    g_arena_cache = NULL;
    g_arena_cache_cap = 0;
    return a;
  }
  *cap = 1 << 20;
  return (uint8_t*)PyMem_Malloc((size_t)*cap);
}

static void arena_give(uint8_t* a, int64_t cap) {
  if (!a) return;
  if (!g_arena_cache || cap > g_arena_cache_cap) {
    PyMem_Free(g_arena_cache);
    g_arena_cache = a;
    g_arena_cache_cap = cap;
  } else {
    PyMem_Free(a);
  }
}

static PyObject* fg_bind(PyObject* self, PyObject* args) {
  const char* path;
  (void)self;
  if (!PyArg_ParseTuple(args, "s", &path)) return NULL;
  void* h = dlopen(path, RTLD_NOW | RTLD_GLOBAL);
  if (!h) {
    PyErr_SetString(PyExc_OSError, dlerror());
    return NULL;
  }
  p_get = (getctx_get_fn)dlsym(h, "tpulsm_getctx_get");
  p_out = (getctx_out_fn)dlsym(h, "tpulsm_getctx_out");
  p_val = (getctx_val_fn)dlsym(h, "tpulsm_getctx_val");
  p_mget = (getctx_mget_fn)dlsym(h, "tpulsm_getctx_multiget");
  if (!p_get || !p_out || !p_val) {
    PyErr_SetString(PyExc_OSError, "tpulsm_getctx_* symbols missing");
    return NULL;
  }
  Py_RETURN_NONE;
}

/* multiget(ctx_addr, keys: list[bytes], snap_seq) ->
 *   (results: list[bytes | None | False], counters: tuple[int x 6])
 * False entries need the Python state machine (merge/blob/entity...).
 * The whole batch walk + result materialization happens here — the
 * per-key Python/numpy assembly dominated the batched read wall. */
static PyObject* fg_multiget(PyObject* self, PyObject* const* args,
                             Py_ssize_t nargs) {
  (void)self;
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError, "multiget(ctx_addr, keys, snap_seq)");
    return NULL;
  }
  if (!p_mget) {
    PyErr_SetString(PyExc_RuntimeError, "multiget symbol unavailable");
    return NULL;
  }
  void* ctx = PyLong_AsVoidPtr(args[0]);
  if (!ctx && PyErr_Occurred()) return NULL;
  PyObject* keys = args[1];
  if (!PyList_Check(keys)) {
    PyErr_SetString(PyExc_TypeError, "keys must be a list of bytes");
    return NULL;
  }
  unsigned long long seq = PyLong_AsUnsignedLongLong(args[2]);
  if (PyErr_Occurred()) return NULL;
  Py_ssize_t n = PyList_GET_SIZE(keys);
  if (n == 0) return Py_BuildValue("([], (iiiiii))", 0, 0, 0, 0, 0, 0);

  int64_t* offs = (int64_t*)PyMem_Malloc(sizeof(int64_t) * n);
  int32_t* lens = (int32_t*)PyMem_Malloc(sizeof(int32_t) * n);
  int8_t* status = (int8_t*)PyMem_Malloc(n);
  int64_t* voffs = (int64_t*)PyMem_Malloc(sizeof(int64_t) * n);
  int64_t* vlens = (int64_t*)PyMem_Malloc(sizeof(int64_t) * n);
  uint8_t* keybuf = NULL;
  uint8_t* arena = NULL;
  PyObject* out = NULL;
  PyObject* cctr = NULL;
  PyObject* res = NULL;
  int64_t total = 0;
  int64_t arena_cap = 1 << 20;
  int64_t used = 0;
  int64_t ctr[6] = {0, 0, 0, 0, 0, 0};
  int32_t rc = -2;
  Py_ssize_t i;
  int oom = 0;

  if (!offs || !lens || !status || !voffs || !vlens) goto oom_exit;
  for (i = 0; i < n; i++) {
    PyObject* k = PyList_GET_ITEM(keys, i);
    char* kb;
    Py_ssize_t kl;
    if (PyBytes_AsStringAndSize(k, &kb, &kl) != 0) goto fail_exit;
    offs[i] = total;
    lens[i] = (int32_t)kl;
    total += kl;
  }
  keybuf = (uint8_t*)PyMem_Malloc(total ? (size_t)total : 1);
  if (!keybuf) goto oom_exit;
  for (i = 0; i < n; i++) {
    PyObject* k = PyList_GET_ITEM(keys, i);
    memcpy(keybuf + offs[i], PyBytes_AS_STRING(k),
           (size_t)PyBytes_GET_SIZE(k));
  }
  arena = arena_take(&arena_cap);
  while (rc == -2 && arena_cap <= ((int64_t)1 << 32)) {
    if (!arena) goto oom_exit;
    Py_BEGIN_ALLOW_THREADS
    rc = p_mget(ctx, keybuf, offs, lens, (int64_t)n, (uint64_t)seq,
                status, voffs, vlens, arena, arena_cap, &used, ctr);
    Py_END_ALLOW_THREADS
    if (rc == -2) {
      arena_cap *= 4;
      PyMem_Free(arena);
      arena = (uint8_t*)PyMem_Malloc((size_t)arena_cap);
    }
  }
  if (rc != 0) {
    /* batch-level fallback: caller uses the ctypes/Python path */
    res = Py_None;
    Py_INCREF(res);
    goto cleanup;
  }
  out = PyList_New(n);
  if (!out) goto oom_exit;
  for (i = 0; i < n; i++) {
    PyObject* v;
    if (status[i] == 1) {
      v = PyBytes_FromStringAndSize((const char*)arena + voffs[i],
                                    (Py_ssize_t)vlens[i]);
      if (!v) goto oom_exit;
    } else if (status[i] == 2) {
      v = Py_False;
      Py_INCREF(v);
    } else {
      v = Py_None;
      Py_INCREF(v);
    }
    PyList_SET_ITEM(out, i, v);
  }
  cctr = Py_BuildValue("(LLLLLL)", (long long)ctr[0], (long long)ctr[1],
                       (long long)ctr[2], (long long)ctr[3],
                       (long long)ctr[4], (long long)ctr[5]);
  if (!cctr) goto fail_exit;
  res = PyTuple_Pack(2, out, cctr);
  goto cleanup;

oom_exit:
  oom = 1;
fail_exit:
  if (oom) PyErr_NoMemory();
  res = NULL;
cleanup:
  Py_XDECREF(out);
  Py_XDECREF(cctr);
  PyMem_Free(keybuf);
  arena_give(arena, arena_cap);
  PyMem_Free(offs);
  PyMem_Free(lens);
  PyMem_Free(status);
  PyMem_Free(voffs);
  PyMem_Free(vlens);
  return res;
}

static PyObject* fg_get(PyObject* self, PyObject* const* args,
                        Py_ssize_t nargs) {
  (void)self;
  if (nargs != 3) {
    PyErr_SetString(PyExc_TypeError, "get(ctx_addr, key, snap_seq)");
    return NULL;
  }
  if (!p_get) {
    PyErr_SetString(PyExc_RuntimeError, "bind() not called");
    return NULL;
  }
  void* ctx = PyLong_AsVoidPtr(args[0]);
  if (!ctx && PyErr_Occurred()) return NULL;
  char* kbuf;
  Py_ssize_t klen;
  if (PyBytes_AsStringAndSize(args[1], &kbuf, &klen) != 0) return NULL;
  unsigned long long seq = PyLong_AsUnsignedLongLong(args[2]);
  if (PyErr_Occurred()) return NULL;
  int32_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = p_get(ctx, (const uint8_t*)kbuf, (int32_t)klen, (uint64_t)seq);
  Py_END_ALLOW_THREADS
  if (rc == 1) {
    int64_t* out = p_out(ctx);
    return PyBytes_FromStringAndSize((const char*)p_val(ctx),
                                     (Py_ssize_t)out[0]);
  }
  if (rc == 0) Py_RETURN_NONE;
  Py_RETURN_FALSE; /* fallback: run the Python chain */
}

static PyMethodDef fg_methods[] = {
    {"bind", fg_bind, METH_VARARGS,
     "bind(native_so_path): resolve the getctx symbols"},
    {"get", (PyCFunction)(void (*)(void))fg_get, METH_FASTCALL,
     "get(ctx_addr, key, snap_seq) -> bytes | None | False"},
    {"multiget", (PyCFunction)(void (*)(void))fg_multiget, METH_FASTCALL,
     "multiget(ctx_addr, keys, snap_seq) -> (results, counters) | None"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fg_module = {
    PyModuleDef_HEAD_INIT, "tpulsm_fastget",
    "ctypes-free fast path for tpulsm_getctx_get", -1, fg_methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit_tpulsm_fastget(void) {
  return PyModule_Create(&fg_module);
}
