"""Loader for the native C++ library.

Builds `_tpulsm_native.so` from the C++ sources on first import (cached by
mtime) and exposes the C ABI via ctypes. Falls back gracefully: callers check
`lib()` for None and use pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "tpulsm_native.cc")
_SO = os.path.join(_DIR, "_tpulsm_native.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build() -> bool:
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", _SO + ".tmp", _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_SO + ".tmp", _SO)
        return True
    except (subprocess.SubprocessError, OSError):
        return False


def lib() -> ctypes.CDLL | None:
    """Returns the loaded native library, building it if needed; None if
    the toolchain is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        need_build = not os.path.exists(_SO) or (
            os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        if need_build and not _build():
            return None
        try:
            l = ctypes.CDLL(_SO)
        except OSError:
            return None
        l.tpulsm_crc32c_extend.restype = ctypes.c_uint32
        l.tpulsm_crc32c_extend.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t,
        ]
        l.tpulsm_xxh64.restype = ctypes.c_uint64
        l.tpulsm_xxh64.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
        ]
        _lib = l
        return _lib
