"""Loader for the native C++ library.

Builds `_tpulsm_native.so` from the C++ sources on first import (cached by
mtime) and exposes the C ABI via ctypes. Falls back gracefully: callers check
`lib()` for None and use pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils import errors as _errors

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "tpulsm_native.cc")
# TPULSM_NATIVE_SANITIZE=asan|undefined builds (and loads) a separate
# sanitized .so — slower, instrumented, used by tests/test_sanitize_native
# to replay the fuzz corpus under ASan/UBSan without disturbing the
# regular artifact. For asan, run python under
# LD_PRELOAD=$(g++ -print-file-name=libasan.so).
_SANITIZE = os.environ.get("TPULSM_NATIVE_SANITIZE", "").strip().lower()
_SAN_FLAGS = {
    "asan": ["-fsanitize=address"],
    "address": ["-fsanitize=address"],
    "undefined": ["-fsanitize=undefined",
                  "-fno-sanitize-recover=undefined"],
    "ubsan": ["-fsanitize=undefined", "-fno-sanitize-recover=undefined"],
}
if _SANITIZE and _SANITIZE in _SAN_FLAGS:
    _SO = os.path.join(_DIR, f"_tpulsm_native.{_SANITIZE}.so")
else:
    _SANITIZE = ""
    _SO = os.path.join(_DIR, "_tpulsm_native.so")

_lock = ccy.Lock("native._lock")
_lib: ctypes.CDLL | None = None
_tried = False

# Must match TPULSM_ABI_VERSION in tpulsm_native.cc. The loader refuses a
# .so reporting a different version: mtime staleness alone cannot catch a
# restored backup or a clock-skewed rebuild.
_ABI_VERSION = 1


def _compile(src: str, so: str, extra_flags: list[str]) -> bool:
    """Shared compile-to-tmp-then-swap build step (per-pid tmp name: two
    processes may race the first build)."""
    tmp = f"{so}.{os.getpid()}.tmp"
    opt = ["-O1", "-g"] if _SANITIZE else ["-O3"]
    cmd = ["g++", *opt, "-shared", "-fPIC", *extra_flags,
           *_SAN_FLAGS.get(_SANITIZE, []),
           "-o", tmp, src, "-ldl"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return True
    except (subprocess.SubprocessError, OSError):
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def _stale(so: str, src: str) -> bool:
    try:
        return not os.path.exists(so) or (
            os.path.getmtime(so) < os.path.getmtime(src))
    except OSError:
        return True


def _build() -> bool:
    return _compile(_SRC, _SO, ["-std=c++17", "-pthread"])


def lib() -> ctypes.CDLL | None:
    """Returns the loaded native library, building it if needed; None if
    the toolchain is unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _stale(_SO, _SRC) and not _build():
            return None
        try:
            l = ctypes.CDLL(_SO)
        except OSError:
            return None
        try:
            l.tpulsm_abi_version.restype = ctypes.c_int32
            l.tpulsm_abi_version.argtypes = []
            abi_ok = l.tpulsm_abi_version() == _ABI_VERSION
        except AttributeError:
            abi_ok = False  # artifact predates the handshake symbol
        if not abi_ok:
            # mtime lied (restored backup / clock skew): one forced
            # rebuild, then give up rather than run a drifted ABI.
            if not _build():
                return None
            l = ctypes.CDLL(_SO)
            l.tpulsm_abi_version.restype = ctypes.c_int32
            l.tpulsm_abi_version.argtypes = []
            if l.tpulsm_abi_version() != _ABI_VERSION:
                return None
        l.tpulsm_crc32c_extend.restype = ctypes.c_uint32
        l.tpulsm_crc32c_extend.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t,
        ]
        l.tpulsm_xxh64.restype = ctypes.c_uint64
        l.tpulsm_xxh64.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64,
        ]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        l.tpulsm_decode_block.restype = ctypes.c_int64
        l.tpulsm_decode_block.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,            # block, len
            u8p, ctypes.c_int64,                        # key_out, cap
            u8p, ctypes.c_int64,                        # val_out, cap
            i32p, i32p, i32p, i32p, ctypes.c_int64,     # offs/lens, max_entries
        ]
        l.tpulsm_build_block.restype = ctypes.c_int64
        l.tpulsm_build_block.argtypes = [
            u8p, i32p, i32p,                            # key buf/offs/lens
            u8p, i32p, i32p,                            # val buf/offs/lens
            i64p,                                       # trailer_override
            i32p, ctypes.c_int64, ctypes.c_int64,       # order, start, n_total
            ctypes.c_int64, ctypes.c_int64,             # block_size, restart_int
            u8p, ctypes.c_int64, i64p,                  # out, cap, out_len
        ]
        l.tpulsm_decode_blocks.restype = ctypes.c_int64
        l.tpulsm_decode_blocks.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,            # file buf, len
            i64p, i64p, ctypes.c_int64,                 # block offs/lens, n
            ctypes.c_int32,                             # verify_crc
            u8p, ctypes.c_int64, u8p, ctypes.c_int64,   # key/val out + caps
            i32p, i32p, i32p, i32p, ctypes.c_int64,
        ]
        l.tpulsm_bloom_build.restype = None
        l.tpulsm_bloom_build.argtypes = [
            u8p, i32p, i32p, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_uint32, u8p,
        ]
        try:
            l.tpulsm_bloom_build_blocked.restype = None
            l.tpulsm_bloom_build_blocked.argtypes = [
                u8p, i32p, i32p, ctypes.c_int64,
                ctypes.c_uint64, ctypes.c_uint32, u8p,
            ]
        except AttributeError:
            pass
        try:
            # A stale .so may predate this symbol; degrade to the numpy
            # sort twin instead of breaking every native caller.
            l.tpulsm_sort_entries.restype = ctypes.c_int32
            l.tpulsm_sort_entries.argtypes = [
                u8p, i64p, i64p, ctypes.c_int64,        # key buf/offs/lens, n
                i32p, u8p,                              # order_out, new_key_out
                ctypes.POINTER(ctypes.c_uint64),        # packed_out (nullable)
            ]
            l.tpulsm_build_data_section.restype = ctypes.c_int64
            l.tpulsm_build_data_section.argtypes = [
                u8p, i32p, i32p,                        # key buf/offs/lens
                u8p, i32p, i32p,                        # val buf/offs/lens
                i64p,                                   # trailer_override
                i32p, ctypes.c_int64, ctypes.c_int64,   # order, start, limit
                ctypes.c_int64, ctypes.c_int64,         # block_size, restart_int
                ctypes.c_int64, ctypes.c_int64,         # base_size, max_size
                i64p, i64p, ctypes.c_int64,             # counts, plens, max_blocks
                u8p, ctypes.c_int64, i64p,              # out, cap, out_len
            ]
        except AttributeError:
            pass
        try:
            # Batch memtable insert on the GIL-RELEASING handle: the whole
            # loop runs without the GIL (the skiplist insert is lock-free),
            # so concurrent writer threads scale past the interpreter lock.
            u64p = ctypes.POINTER(ctypes.c_uint64)
            l.tpulsm_skiplist_insert_batch.restype = ctypes.c_int64
            l.tpulsm_skiplist_insert_batch.argtypes = [
                ctypes.c_void_p, u8p, i64p, i32p, u64p,
                u8p, i64p, i32p, ctypes.c_int64,
            ]
        except AttributeError:
            pass
        try:
            # Compressed section builder: build + compress + frame whole
            # runs of blocks in one call (snappy/zstd dlopen'd).
            l.tpulsm_build_data_section_c.restype = ctypes.c_int64
            l.tpulsm_build_data_section_c.argtypes = [
                u8p, i32p, i32p, u8p, i32p, i32p, i64p, i32p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int64, ctypes.c_int64,
                i64p, i64p, i64p, ctypes.c_int64,
                u8p, ctypes.c_int64, i64p,
            ]
        except AttributeError:
            pass
        try:
            # In-block point seek (restart bsearch + linear scan in C):
            # the BlockIter.seek hot path of every Get.
            l.tpulsm_block_seek.restype = ctypes.c_int32
            l.tpulsm_block_seek.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
                ctypes.c_int32, u8p, ctypes.c_int32, i32p,
            ]
        except AttributeError:
            pass
        try:
            # Bulk block inflate (snappy/zstd dlopen'd in C++): one
            # GIL-free, multi-threaded call per compressed SST scan.
            l.tpulsm_inflate_blocks.restype = ctypes.c_int64
            l.tpulsm_inflate_blocks.argtypes = [
                u8p, ctypes.c_int64, i64p, i64p, ctypes.c_int64,
                ctypes.c_int32, u8p, ctypes.c_int64, i64p, i64p,
            ]
        except AttributeError:
            pass
        try:
            # WriteBatch wire-image insert: parse + insert natively, one
            # GIL-free call per batch (no per-record Python/numpy at all).
            l.tpulsm_skiplist_insert_wb.restype = ctypes.c_int64
            l.tpulsm_skiplist_insert_wb.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_uint64, i64p,
            ]
        except AttributeError:
            pass
        try:
            # Fused verify+insert for protected batches: re-hash every
            # record against the carried vector, insert only if ALL match.
            _u64p = ctypes.POINTER(ctypes.c_uint64)
            l.tpulsm_skiplist_insert_wb_prot.restype = ctypes.c_int64
            l.tpulsm_skiplist_insert_wb_prot.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_uint64, _u64p, ctypes.c_int64, ctypes.c_int32, i64p,
            ]
        except AttributeError:
            pass
        try:
            # Per-entry protection over a WriteBatch wire image: one call
            # computes every counted record's checksum (utils/protection
            # bit-compatible) — the protected write path's hot loop.
            u64p = ctypes.POINTER(ctypes.c_uint64)
            l.tpulsm_wb_protect.restype = ctypes.c_int64
            l.tpulsm_wb_protect.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, u64p, ctypes.c_int64,
            ]
            # XOR-aggregate protection over a columnar export (flush's
            # memtable->SST handoff check without per-entry Python).
            l.tpulsm_columnar_protect.restype = ctypes.c_int64
            l.tpulsm_columnar_protect.argtypes = [
                u8p, i32p, i32p, u8p, i32p, i32p, i32p,
                ctypes.c_int64, ctypes.c_int32, u64p,
            ]
        except AttributeError:
            pass
        try:
            # Fused group-commit write plane: validate + protect-verify a
            # whole write group, frame the merged WAL record gather-style,
            # and apply every record to the memtable rep — one GIL-free
            # call per group (db.py _native_group_commit).
            u64p = ctypes.POINTER(ctypes.c_uint64)
            l.tpulsm_wb_group_commit.restype = ctypes.c_int64
            l.tpulsm_wb_group_commit.argtypes = [
                ctypes.c_void_p, ctypes.c_int32,            # mem, mem_kind
                ctypes.POINTER(ctypes.c_char_p), i64p,      # reps, lens
                ctypes.c_int64, ctypes.c_uint64,            # n_batches, seq
                u64p, ctypes.c_int64, ctypes.c_int32,       # prots, n, pb
                ctypes.c_int32,                             # mode
                ctypes.c_int64, ctypes.c_int64,             # blk_off, log_no
                u8p, ctypes.c_int64, i64p,                  # wal out/cap, out
            ]
        except AttributeError:
            pass
        try:
            # Host k-way merge of presorted runs (separate block: a stale
            # .so missing THIS symbol must not void older registrations).
            l.tpulsm_merge_runs.restype = ctypes.c_int32
            l.tpulsm_merge_runs.argtypes = [
                u8p, i64p, i64p, ctypes.c_int64,
                i64p, ctypes.c_int32,                   # run_starts, n_runs
                i32p, u8p, ctypes.POINTER(ctypes.c_uint64),
            ]
        except AttributeError:
            pass
        try:
            # Whole-file index block build (separators + BlockHandle
            # entries in C) for the columnar writer's section path.
            l.tpulsm_build_index_block.restype = ctypes.c_int64
            l.tpulsm_build_index_block.argtypes = [
                u8p, i32p, i32p, i64p, i32p,
                i64p, i64p, i64p, i64p,                 # pos/cnt/offs/plens
                ctypes.c_int64, ctypes.c_int64,         # n_blocks, restart
                u8p, ctypes.c_int64, i64p,              # out, cap, out_len
            ]
        except AttributeError:
            pass
        try:
            # Fused whole-file scan (inflate + decode + absolute offsets)
            # into caller-provided slices of a shared columnar buffer.
            l.tpulsm_scan_blocks.restype = ctypes.c_int64
            l.tpulsm_scan_blocks.argtypes = [
                u8p, ctypes.c_int64,                    # file buf, len
                i64p, i64p, ctypes.c_int64,             # block offs/lens, n
                ctypes.c_int32,                         # verify_crc
                u8p, ctypes.c_int64, u8p, ctypes.c_int64,  # key/val out+caps
                i32p, i32p, i32p, i32p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,         # key_base, val_base
            ]
        except AttributeError:
            pass
        try:
            # Keys-copied / values-REFERENCED whole-file scan: val offsets
            # point into the (uncompressed) file image the caller keeps
            # alive as val_buf — no per-entry value memcpy.
            l.tpulsm_scan_blocks_refvals.restype = ctypes.c_int64
            l.tpulsm_scan_blocks_refvals.argtypes = [
                u8p, ctypes.c_int64,                    # file buf, len
                i64p, i64p, ctypes.c_int64,             # block offs/lens, n
                ctypes.c_int32,                         # verify_crc
                u8p, ctypes.c_int64,                    # key out + cap
                i32p, i32p, i32p, i32p, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,  # key_base, val_image_base
            ]
        except AttributeError:
            pass
        try:
            # Fused k-way merge + MVCC GC: ONE pass over presorted runs,
            # survivors only — replaces merge + numpy mask passes.
            l.tpulsm_merge_gc_runs.restype = ctypes.c_int64
            l.tpulsm_merge_gc_runs.argtypes = [
                u8p, i64p, i64p, ctypes.c_int64,
                i64p, ctypes.c_int32,                   # run_starts, n_runs
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_int32,  # snaps
                ctypes.POINTER(ctypes.c_uint64),        # cover (nullable)
                ctypes.c_int32,                         # bottommost
                i32p, u8p, u8p,                         # order/zero/cx out
                ctypes.POINTER(ctypes.c_uint64),        # packed_out
                i32p,                                   # has_complex_out
            ]
        except AttributeError:
            pass
        try:
            # Ordered whole-memtable export into columnar buffers: the
            # memtable half of the columnar flush fast path.
            u64p = ctypes.POINTER(ctypes.c_uint64)
            l.tpulsm_skiplist_export.restype = ctypes.c_int64
            l.tpulsm_skiplist_export.argtypes = [
                ctypes.c_void_p, u8p, i64p, i32p, u64p, i32p,
                u8p, i64p, i32p, ctypes.c_int64, i64p,
            ]
        except AttributeError:
            pass
        try:
            # Trie rep (CSPP role) GIL-released entry points.
            u64p = ctypes.POINTER(ctypes.c_uint64)
            l.tpulsm_trie_insert_batch.restype = ctypes.c_int64
            l.tpulsm_trie_insert_batch.argtypes = [
                ctypes.c_void_p, u8p, i64p, i32p, u64p,
                u8p, i64p, i32p, ctypes.c_int64,
            ]
            l.tpulsm_trie_insert_wb.restype = ctypes.c_int64
            l.tpulsm_trie_insert_wb.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_uint64, i64p,
            ]
            l.tpulsm_trie_insert_wb_prot.restype = ctypes.c_int64
            l.tpulsm_trie_insert_wb_prot.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.c_uint64, u64p, ctypes.c_int64, ctypes.c_int32, i64p,
            ]
            l.tpulsm_trie_export.restype = ctypes.c_int64
            l.tpulsm_trie_export.argtypes = [
                ctypes.c_void_p, u8p, i64p, i32p, u64p, i32p,
                u8p, i64p, i32p, ctypes.c_int64, i64p,
            ]
        except AttributeError:
            pass
        try:
            # Native point-read engine: table/version handles + the whole
            # GetImpl chain in one GIL-released call.
            l.tpulsm_table_handle_new.restype = ctypes.c_void_p
            l.tpulsm_table_handle_new.argtypes = [
                ctypes.c_int32, ctypes.c_uint64, ctypes.c_int32,
                u8p, ctypes.c_int64, u8p, ctypes.c_int64,
                u8p, ctypes.c_int32, u8p, ctypes.c_int32,
            ]
            l.tpulsm_table_handle_free.restype = None
            l.tpulsm_table_handle_free.argtypes = [ctypes.c_void_p]
            l.tpulsm_version_handle_new.restype = ctypes.c_void_p
            l.tpulsm_version_handle_new.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32,
                i32p, ctypes.c_int32,
            ]
            l.tpulsm_version_handle_free.restype = None
            l.tpulsm_version_handle_free.argtypes = [ctypes.c_void_p]
            l.tpulsm_block_cache_config.restype = None
            l.tpulsm_block_cache_config.argtypes = [ctypes.c_int64, i64p]
            l.tpulsm_db_get.restype = ctypes.c_int32
            l.tpulsm_db_get.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
                ctypes.c_uint64, u8p, ctypes.c_int32, i32p, i32p, i64p,
            ]
            l.tpulsm_db_get_kinds.restype = ctypes.c_int32
            l.tpulsm_db_get_kinds.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), i32p, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
                ctypes.c_uint64, u8p, ctypes.c_int32, i32p, i32p, i64p,
            ]
            l.tpulsm_getctx_new.restype = ctypes.c_void_p
            l.tpulsm_getctx_new.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_int64,
            ]
            l.tpulsm_getctx_free.restype = None
            l.tpulsm_getctx_free.argtypes = [ctypes.c_void_p]
            l.tpulsm_getctx_out.restype = ctypes.c_void_p
            l.tpulsm_getctx_out.argtypes = [ctypes.c_void_p]
            l.tpulsm_getctx_val.restype = ctypes.c_void_p
            l.tpulsm_getctx_val.argtypes = [ctypes.c_void_p]
            l.tpulsm_getctx_set_mem_kind.restype = None
            l.tpulsm_getctx_set_mem_kind.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
            ]
            l.tpulsm_getctx_get.restype = ctypes.c_int32
            l.tpulsm_getctx_get.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32,
                ctypes.c_uint64,
            ]
            i8p = ctypes.POINTER(ctypes.c_int8)
            l.tpulsm_getctx_multiget.restype = ctypes.c_int32
            l.tpulsm_getctx_multiget.argtypes = [
                ctypes.c_void_p, u8p, i64p, i32p, ctypes.c_int64,
                ctypes.c_uint64, i8p, i64p, i64p, u8p, ctypes.c_int64,
                i64p, i64p,
            ]
        except AttributeError:
            pass
        try:
            # Zip-table data plane: batched builder kernels (bit-identical
            # to the Python encoders in table/zip_table.py), the columnar
            # key/value-group decoders, and the zip Get handle.
            l.tpulsm_zip_newkey.restype = ctypes.c_int64
            l.tpulsm_zip_newkey.argtypes = [
                u8p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int32,
                u8p,
            ]
            l.tpulsm_zip_encode_keys.restype = ctypes.c_int64
            l.tpulsm_zip_encode_keys.argtypes = [
                u8p, ctypes.c_int64, i64p, ctypes.c_int64, ctypes.c_int32,
                i64p, ctypes.c_int32, ctypes.c_int32, u8p, u8p,
                ctypes.c_int64, u8p,
            ]
            l.tpulsm_zip_encode_values.restype = ctypes.c_int64
            l.tpulsm_zip_encode_values.argtypes = [
                u8p, ctypes.c_int64, i64p, i64p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, u8p, ctypes.c_int64, u8p, ctypes.c_int64,
                u8p, u8p, i64p,
            ]
            l.tpulsm_zip_decode_keys.restype = ctypes.c_int64
            l.tpulsm_zip_decode_keys.argtypes = [
                u8p, ctypes.c_int64, ctypes.c_int32, u8p, ctypes.c_int64,
                u8p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int64, ctypes.c_int64, u8p, ctypes.c_int64, i64p,
                i64p, ctypes.c_int64,
            ]
            l.tpulsm_zip_group_decode.restype = ctypes.c_int64
            l.tpulsm_zip_group_decode.argtypes = [
                u8p, ctypes.c_int64, u8p, ctypes.c_int64, u8p,
                ctypes.c_int64, u8p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, i64p, u8p, ctypes.c_int64,
            ]
            l.tpulsm_zip_table_handle_new.restype = ctypes.c_void_p
            l.tpulsm_zip_table_handle_new.argtypes = [
                ctypes.c_uint64, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_int64, ctypes.c_int32,
                ctypes.c_int32, u8p, ctypes.c_int64, u8p, ctypes.c_int64,
                u8p, ctypes.c_int64, u8p, ctypes.c_int64, u8p,
                ctypes.c_int64, u8p, ctypes.c_int64, u8p, ctypes.c_int64,
                u8p, ctypes.c_int64, u8p, ctypes.c_int64, u8p,
                ctypes.c_int32, u8p, ctypes.c_int32,
            ]
        except AttributeError:
            pass
        _lib = l
        return _lib


_pylib: "ctypes.PyDLL | None" = None


def pylib() -> "ctypes.PyDLL | None":
    """GIL-holding handle for the skiplist memtable: calls do NOT release the
    GIL, so single-writer mutation is safe against lockless Python readers."""
    global _pylib
    if _pylib is not None:
        return _pylib
    if lib() is None:  # ensures the .so is built
        return None
    l = ctypes.PyDLL(_SO)
    vp = ctypes.c_void_p
    u8p = ctypes.POINTER(ctypes.c_uint8)
    l.tpulsm_skiplist_new.restype = vp
    l.tpulsm_skiplist_new.argtypes = []
    l.tpulsm_skiplist_free.restype = None
    l.tpulsm_skiplist_free.argtypes = [vp]
    l.tpulsm_skiplist_insert.restype = ctypes.c_int32
    l.tpulsm_skiplist_insert.argtypes = [
        vp, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_uint32,
    ]
    l.tpulsm_skiplist_count.restype = ctypes.c_int64
    l.tpulsm_skiplist_count.argtypes = [vp]
    l.tpulsm_skiplist_memory.restype = ctypes.c_int64
    l.tpulsm_skiplist_memory.argtypes = [vp]
    l.tpulsm_skiplist_seek_ge.restype = vp
    l.tpulsm_skiplist_seek_ge.argtypes = [
        vp, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
    l.tpulsm_skiplist_seek_lt.restype = vp
    l.tpulsm_skiplist_seek_lt.argtypes = [
        vp, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
    l.tpulsm_skiplist_first.restype = vp
    l.tpulsm_skiplist_first.argtypes = [vp]
    l.tpulsm_skiplist_last.restype = vp
    l.tpulsm_skiplist_last.argtypes = [vp]
    l.tpulsm_skiplist_next.restype = vp
    l.tpulsm_skiplist_next.argtypes = [vp]
    l.tpulsm_skiplist_node.restype = None
    l.tpulsm_skiplist_node.argtypes = [
        vp, ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint32),
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint32),
    ]
    try:
        # Trie memtable rep (the CSPP role) — same shape of surface.
        l.tpulsm_trie_new.restype = vp
        l.tpulsm_trie_new.argtypes = []
        l.tpulsm_trie_free.restype = None
        l.tpulsm_trie_free.argtypes = [vp]
        l.tpulsm_trie_insert.restype = ctypes.c_int32
        l.tpulsm_trie_insert.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint32,
        ]
        l.tpulsm_trie_count.restype = ctypes.c_int64
        l.tpulsm_trie_count.argtypes = [vp]
        l.tpulsm_trie_memory.restype = ctypes.c_int64
        l.tpulsm_trie_memory.argtypes = [vp]
        l.tpulsm_trie_seek_ge.restype = vp
        l.tpulsm_trie_seek_ge.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
        l.tpulsm_trie_seek_lt.restype = vp
        l.tpulsm_trie_seek_lt.argtypes = [
            vp, ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
        l.tpulsm_trie_first.restype = vp
        l.tpulsm_trie_first.argtypes = [vp]
        l.tpulsm_trie_last.restype = vp
        l.tpulsm_trie_last.argtypes = [vp]
        l.tpulsm_trie_next.restype = vp
        l.tpulsm_trie_next.argtypes = [vp, vp]
        l.tpulsm_trie_ver.restype = None
        l.tpulsm_trie_ver.argtypes = [
            vp, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint32),
            ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_uint32),
        ]
    except AttributeError:
        pass
    _pylib = l
    return _pylib


_FASTGET_SRC = os.path.join(_DIR, "fastget.c")
_fastget_mod = None
_fastget_tried = False


def _fastget_so_path() -> str:
    # The interpreter's cache tag rides in the filename so an extension
    # built under an older CPython ABI is never dlopen'd after an
    # interpreter upgrade (layout mismatches can segfault past any
    # except clause).
    import sys as _sys

    tag = getattr(_sys.implementation, "cache_tag", "py") or "py"
    if _SANITIZE:
        tag = f"{tag}.{_SANITIZE}"  # keep the sanitized artifact separate
    return os.path.join(_DIR, f"tpulsm_fastget.{tag}.so")


def fastmultiget():
    """The C-extension whole-batch MultiGet (list-of-bytes in, list out),
    or None when unavailable."""
    if fastget() is None:
        return None
    return getattr(_fastget_mod, "multiget", None)


def fastget():
    """The C-extension fast path for tpulsm_getctx_get (fastget.c), or
    None when unavailable (missing Python headers / toolchain): callers
    keep the ctypes path. Returns the bound module's `get` callable."""
    global _fastget_mod, _fastget_tried
    if _fastget_mod is not None:
        return _fastget_mod.get
    if _fastget_tried:
        return None
    if lib() is None:  # resolve the native .so FIRST (it takes _lock too)
        return None
    with _lock:
        if _fastget_mod is not None:
            return _fastget_mod.get
        if _fastget_tried:
            return None
        _fastget_tried = True
        so = _fastget_so_path()
        if _stale(so, _FASTGET_SRC):
            import sysconfig

            inc = sysconfig.get_paths().get("include")
            if not inc or not os.path.exists(
                    os.path.join(inc, "Python.h")):
                return None
            if not _compile(_FASTGET_SRC, so, [f"-I{inc}", "-O2"]):
                return None
        try:
            import importlib.machinery
            import importlib.util

            loader = importlib.machinery.ExtensionFileLoader(
                "tpulsm_fastget", so)
            spec = importlib.util.spec_from_loader("tpulsm_fastget", loader)
            mod = importlib.util.module_from_spec(spec)
            loader.exec_module(mod)
            mod.bind(_SO)
            _fastget_mod = mod
            return mod.get
        except Exception as e:
            _errors.swallow(reason="fastget-bind-fallback", exc=e)
            return None


def np_u8p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def np_i32p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def np_i64p(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
