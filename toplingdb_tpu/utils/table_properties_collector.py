"""User table-properties collectors.

Analogue of the reference's TablePropertiesCollector / Factory
(include/rocksdb/table_properties.h, utilities/table_properties_collectors/
in /root/reference): a per-table hook that observes every added entry, emits
user properties into the table's properties block, and may flag the file as
needing compaction — the mechanism behind CompactOnDeletionCollector
(compact_on_deletion_collector.cc): trigger compaction when a sliding window
of entries is tombstone-dense.
"""

from __future__ import annotations

from collections import deque

from toplingdb_tpu.db.dbformat import ValueType


class TablePropertiesCollector:
    """Per-table observer; a fresh instance is created for every SST."""

    def name(self) -> str:
        raise NotImplementedError

    def add_user_key(self, key: bytes, value: bytes, entry_type: int,
                     seq: int, file_size: int) -> None:
        """Called for every entry added to the table, in key order."""

    def finish(self) -> dict[str, bytes]:
        """Returns user properties to store in the properties block."""
        return {}

    def need_compact(self) -> bool:
        """True marks the output file for priority compaction."""
        return False


class TablePropertiesCollectorFactory:
    def name(self) -> str:
        raise NotImplementedError

    def create(self) -> TablePropertiesCollector:
        raise NotImplementedError


class CompactOnDeletionCollector(TablePropertiesCollector):
    """Sliding-window tombstone-density trigger (reference
    utilities/table_properties_collectors/compact_on_deletion_collector.cc):
    need_compact once any window of `window_size` consecutive entries holds
    >= `deletion_trigger` deletes, or the whole file's deletion ratio
    reaches `deletion_ratio` (0 disables the ratio check)."""

    def __init__(self, window_size: int, deletion_trigger: int,
                 deletion_ratio: float = 0.0):
        self._window_size = max(1, window_size)
        self._trigger = deletion_trigger
        self._ratio = deletion_ratio
        self._window: deque[bool] = deque()
        self._in_window = 0
        self._deletions = 0
        self._entries = 0
        self._need = False

    def name(self) -> str:
        return "CompactOnDeletionCollector"

    def add_user_key(self, key, value, entry_type, seq, file_size):
        is_del = entry_type in (ValueType.DELETION, ValueType.SINGLE_DELETION)
        self._entries += 1
        if is_del:
            self._deletions += 1
        if self._need:
            return
        self._window.append(is_del)
        self._in_window += is_del
        if len(self._window) > self._window_size:
            self._in_window -= self._window.popleft()
        if self._in_window >= self._trigger:
            self._need = True

    def need_compact(self) -> bool:
        if self._need:
            return True
        if self._ratio > 0 and self._entries:
            return self._deletions / self._entries >= self._ratio
        return False


class CompactOnDeletionCollectorFactory(TablePropertiesCollectorFactory):
    def __init__(self, window_size: int = 128, deletion_trigger: int = 64,
                 deletion_ratio: float = 0.0):
        self.window_size = window_size
        self.deletion_trigger = deletion_trigger
        self.deletion_ratio = deletion_ratio

    def name(self) -> str:
        return "CompactOnDeletionCollectorFactory"

    def create(self) -> CompactOnDeletionCollector:
        return CompactOnDeletionCollector(
            self.window_size, self.deletion_trigger, self.deletion_ratio
        )

    def serialize(self) -> dict:
        return {"name": self.name(), "window_size": self.window_size,
                "deletion_trigger": self.deletion_trigger,
                "deletion_ratio": self.deletion_ratio}


def serialize_collector_factory(f: TablePropertiesCollectorFactory) -> dict:
    """For the dcompact boundary (ObjectRpcParam analogue): factories must
    be serializable or the executor raises and the scheduler falls back to
    a local compaction."""
    ser = getattr(f, "serialize", None)
    if ser is None:
        from toplingdb_tpu.utils.status import NotSupported

        raise NotSupported(
            f"collector factory {f.name()!r} is not serializable for the "
            f"remote-compaction boundary"
        )
    return ser()


def create_collector_factory(d: dict) -> TablePropertiesCollectorFactory:
    if d.get("name") == "CompactOnDeletionCollectorFactory":
        return CompactOnDeletionCollectorFactory(
            d["window_size"], d["deletion_trigger"], d.get("deletion_ratio", 0.0)
        )
    from toplingdb_tpu.utils.status import InvalidArgument

    raise InvalidArgument(f"unknown collector factory {d.get('name')!r}")
