"""Concurrency correctness plane: named lock factories, instrumented
debug wrappers, and a ThreadRegistry (ISSUE 13).

Every lock and background thread in the package is created through this
module so that (a) the static analyzer (`tools/check_concurrency.py`)
can assign each creation site a stable *lock class* and check the
declared hierarchy in ARCHITECTURE.md §2.10.1, and (b) a runtime debug
mode can interpose on every acquisition.

Production mode (default): `Lock(name)` / `RLock(name)` / `Condition`
return **plain** `threading` primitives — the name argument costs one
function call at creation time and nothing per acquire, in the
RESYSTANCE spirit of instrumentation that lives in the execution path
at near-zero cost.

Debug mode (`TPULSM_LOCK_DEBUG=1`, or `set_debug(True)` before the
locks are created): the factories return instrumented wrappers that
maintain a per-thread held-set and a global lock-class acquisition-order
graph.  Acquiring B while holding A records the edge A→B with the
acquiring stack; if the reverse path B⇝A is already on record the
acquisition raises `LockInversionError` carrying BOTH stacks (ours and
the recorded witness).  A hold longer than `TPULSM_LOCK_WATCHDOG_MS`
(default 30000) reports through the watchdog handler at release time —
`scan_long_holds()` finds still-held offenders (e.g. a real deadlock)
on demand, with the holder's live stack via sys._current_frames().

Threads: `spawn(name, target, ...)` creates a **named** daemon-or-not
thread, registers it with the global `ThreadRegistry`, and deregisters
it automatically when the target returns.  `registry.check_leaks(owner)`
backs the `DB.close()` leak check and the pytest fixture.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
import warnings
import weakref

__all__ = [
    "Lock", "RLock", "Condition", "spawn", "registry", "ThreadRegistry",
    "LockInversionError", "lock_debug_enabled", "set_debug",
    "reset_lock_graph", "lock_order_edges", "scan_long_holds",
    "set_watchdog_handler", "set_watchdog_ms", "held_lock_classes",
]

_DEBUG = os.environ.get("TPULSM_LOCK_DEBUG", "") not in ("", "0")
_WATCHDOG_MS = float(os.environ.get("TPULSM_LOCK_WATCHDOG_MS", "30000"))


def lock_debug_enabled() -> bool:
    return _DEBUG


def set_debug(on: bool) -> None:
    """Flip debug mode for locks created *after* this call (tests/bench).
    Already-created locks keep their mode."""
    global _DEBUG
    _DEBUG = bool(on)


def set_watchdog_ms(ms: float) -> None:
    global _WATCHDOG_MS
    _WATCHDOG_MS = float(ms)


class LockInversionError(RuntimeError):
    """Acquisition order cycle between lock classes — carries both the
    acquiring stack and the recorded witness stack of the reverse edge."""


def _snap_stack(skip: int = 2, limit: int = 16) -> list:
    """Cheap stack snapshot: (filename, lineno, funcname) per frame.
    Formatting (source-line lookup, string build) is what makes
    traceback.format_stack cost ~30µs per acquire; deferring it to
    _fmt_snap keeps the per-acquire debug tax at a frame walk."""
    try:
        f = sys._getframe(skip)
    except ValueError:
        return []
    out = []
    while f is not None and len(out) < limit:
        co = f.f_code
        out.append((co.co_filename, f.f_lineno, co.co_name))
        f = f.f_back
    out.reverse()
    return out


def _fmt_snap(snap: list) -> str:
    import linecache

    lines = []
    for fn, ln, name in snap:
        lines.append(f'  File "{fn}", line {ln}, in {name}\n')
        src = linecache.getline(fn, ln).strip()
        if src:
            lines.append(f"    {src}\n")
    return "".join(lines)


# ---------------------------------------------------------------------------
# Global acquisition-order graph (debug mode only)
# ---------------------------------------------------------------------------


class _LockGraph:
    """Lock-class level order graph.  Nodes are lock-class names; an edge
    A→B means some thread acquired a B-class lock while holding an
    A-class lock.  The graph only ever grows (edges are never removed on
    release): ordering is a global program property, not a per-moment
    one, which is exactly what makes inversions detectable before the
    interleaving that would actually deadlock."""

    def __init__(self):
        # The graph's own mutex stays a RAW threading lock: it must never
        # itself be tracked (that would recurse).
        self._mu = threading.Lock()
        # (from_class, to_class) -> witness dict
        self.edges: dict[tuple[str, str], dict] = {}
        self._adj: dict[str, set[str]] = {}

    def clear(self) -> None:
        with self._mu:
            self.edges.clear()
            self._adj.clear()

    def _path(self, src: str, dst: str) -> list[str] | None:
        """DFS path src ⇝ dst over current adjacency (caller holds _mu)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self._adj.get(node, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note(self, held_class: str, new_class: str, snap: list,
             thread_name: str) -> None:
        """Record edge held→new; raise LockInversionError if the reverse
        path already exists."""
        if held_class == new_class:
            # Same lock class (lock striping / two instances of one
            # class): instance-level order is not statically nameable, so
            # class self-edges are ignored — mirrors the analyzer.
            return
        key = (held_class, new_class)
        if key in self.edges:
            return  # steady-state fast path: edges only ever grow
        with self._mu:
            if key in self.edges:
                return
            rev = self._path(new_class, held_class)
            if rev is not None:
                # Build the witness chain of the reverse path.
                parts = []
                for a, b in zip(rev, rev[1:]):
                    w = self.edges[(a, b)]
                    parts.append(
                        f"  edge {a} -> {b} (thread {w['thread']}):\n"
                        + _fmt_snap(w["snap"]))
                raise LockInversionError(
                    f"lock order inversion: acquiring {new_class!r} while "
                    f"holding {held_class!r} (thread {thread_name}), but "
                    f"the order {' -> '.join(rev)} is already on record.\n"
                    f"--- acquiring stack (this thread) ---\n"
                    f"{_fmt_snap(snap)}"
                    f"--- recorded witness path ---\n" + "\n".join(parts))
            self.edges[key] = {"snap": snap, "thread": thread_name,
                               "time": time.time()}
            self._adj.setdefault(held_class, set()).add(new_class)


_graph = _LockGraph()
_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def held_lock_classes() -> list[str]:
    """Lock classes currently held by the calling thread (debug mode)."""
    return [e[1] for e in _held()]


def reset_lock_graph() -> None:
    _graph.clear()


def lock_order_edges() -> dict[tuple[str, str], dict]:
    return dict(_graph.edges)


# Watchdog: long holds report through this handler (default: RuntimeWarning).
def _default_watchdog(lock_class: str, held_s: float, stack: str) -> None:
    warnings.warn(
        f"lock {lock_class!r} held for {held_s:.3f}s (> watchdog "
        f"{_WATCHDOG_MS / 1000.0:.3f}s); acquired at:\n{stack}",
        RuntimeWarning, stacklevel=3)


_watchdog_handler = _default_watchdog


def set_watchdog_handler(fn) -> None:
    """fn(lock_class, held_seconds, acquire_stack) — None restores default."""
    global _watchdog_handler
    _watchdog_handler = fn or _default_watchdog


def scan_long_holds(threshold_ms: float | None = None) -> list[dict]:
    """Still-held locks exceeding the threshold, with the holder's LIVE
    stack — the on-demand probe for wedged threads (a deadlocked holder
    never reaches the release-time check)."""
    thr = (_WATCHDOG_MS if threshold_ms is None else threshold_ms) / 1000.0
    now = time.monotonic()
    out = []
    frames = sys._current_frames()
    for lock in list(_DebugLockBase._live):
        t0 = lock._acquired_at
        tid = lock._owner
        if t0 is None or tid is None or now - t0 < thr:
            continue
        fr = frames.get(tid)
        out.append({
            "lock_class": lock.lock_class,
            "held_s": now - t0,
            "thread_id": tid,
            "holder_stack": "".join(traceback.format_stack(fr))
            if fr is not None else "<thread gone>",
        })
    return out


# ---------------------------------------------------------------------------
# Debug wrappers
# ---------------------------------------------------------------------------


class _DebugLockBase:
    """Shared acquire/release bookkeeping.  Also implements the
    _release_save/_acquire_restore/_is_owned protocol so a
    threading.Condition built over a wrapper keeps the held-set honest
    across wait()."""

    _live: "weakref.WeakSet[_DebugLockBase]"

    __slots__ = ("lock_class", "_inner", "_owner", "_count",
                 "_acquired_at", "_acquire_snap", "__weakref__")

    def __init__(self, lock_class: str, inner):
        self.lock_class = lock_class
        self._inner = inner
        self._owner: int | None = None
        self._count = 0
        self._acquired_at: float | None = None
        self._acquire_snap: list | None = None
        _DebugLockBase._live.add(self)

    # -- tracking helpers ------------------------------------------------
    def _track_acquire(self) -> None:
        me = threading.get_ident()
        if self._owner == me:           # re-entrant (RLock only)
            self._count += 1
            return
        snap = _snap_stack(skip=3)
        held = _held()
        try:
            for _lk, held_class, _st in held:
                _graph.note(held_class, self.lock_class, snap,
                            threading.current_thread().name)
        except LockInversionError:
            # The acquisition SUCCEEDED at the threading layer; undo it so
            # the raise does not leave an orphaned hold.
            self._inner.release()
            raise
        self._owner = me
        self._count = 1
        self._acquired_at = time.monotonic()
        self._acquire_snap = snap
        held.append((self, self.lock_class, snap))

    def _track_release(self) -> None:
        me = threading.get_ident()
        if self._owner != me:
            return
        self._count -= 1
        if self._count > 0:
            return
        if self._acquired_at is not None and _WATCHDOG_MS > 0:
            held_s = time.monotonic() - self._acquired_at
            if held_s * 1000.0 > _WATCHDOG_MS:
                _watchdog_handler(self.lock_class, held_s,
                                  _fmt_snap(self._acquire_snap or []))
        self._owner = None
        self._acquired_at = None
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                del held[i]
                break

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._track_acquire()
        return ok

    def release(self) -> None:
        self._track_release()
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    # -- Condition protocol (wait must drop the held-set entry too) ------
    def _release_save(self):
        count = self._count
        self._count = 1                 # _track_release drops it fully
        self._track_release()
        if hasattr(self._inner, "_release_save"):
            state = self._inner._release_save()
        else:
            self._inner.release()
            state = None
        return (count, state)

    def _acquire_restore(self, saved):
        count, state = saved
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        self._track_acquire()
        self._count = count

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def __repr__(self):
        return (f"<{type(self).__name__} {self.lock_class!r} "
                f"owner={self._owner}>")


_DebugLockBase._live = weakref.WeakSet()


class _DebugLock(_DebugLockBase):
    __slots__ = ()

    def __init__(self, lock_class: str):
        super().__init__(lock_class, threading.Lock())


class _DebugRLock(_DebugLockBase):
    __slots__ = ()

    def __init__(self, lock_class: str):
        super().__init__(lock_class, threading.RLock())


# ---------------------------------------------------------------------------
# Factories (the only lock constructors the package may use)
# ---------------------------------------------------------------------------


def Lock(name: str):
    """A mutex whose creation site carries a stable lock-class name.
    Plain threading.Lock in production; instrumented under debug."""
    if _DEBUG:
        return _DebugLock(name)
    return threading.Lock()


def RLock(name: str):
    if _DEBUG:
        return _DebugRLock(name)
    return threading.RLock()


def Condition(name: str | None = None, lock=None):
    """Condition over a named fresh lock, or over an existing (possibly
    wrapped) lock created by these factories — `Condition(lock=self._mu)`
    shares _mu's lock class."""
    if lock is not None:
        return threading.Condition(lock)
    if name is None:
        raise TypeError("Condition() needs a lock-class name or lock=")
    if _DEBUG:
        return threading.Condition(_DebugLock(name))
    return threading.Condition()


# ---------------------------------------------------------------------------
# ThreadRegistry + spawn
# ---------------------------------------------------------------------------


class ThreadRegistry:
    """Tracks every background thread the package starts.  Entries
    auto-deregister when the thread's target returns; whatever is still
    live and owned by X when `check_leaks(X)` runs is a lifecycle leak
    (e.g. the unstopped-scrubber case in DB.close())."""

    def __init__(self):
        self._mu = threading.Lock()     # raw: registry is infrastructure
        self._entries: dict[int, dict] = {}

    def register(self, thread: threading.Thread, owner=None,
                 stop=None) -> None:
        if not thread.name or thread.name.startswith("Thread-"):
            raise ValueError(
                f"refusing to register unnamed thread {thread!r}: every "
                f"package thread must carry a name= (check_concurrency T2)")
        with self._mu:
            self._entries[id(thread)] = {
                "thread": thread,
                "name": thread.name,
                "owner_id": id(owner) if owner is not None else None,
                "owner_repr": type(owner).__name__ if owner is not None
                else None,
                "stop": stop,
                "started_at": time.time(),
            }

    def deregister(self, thread: threading.Thread) -> None:
        with self._mu:
            self._entries.pop(id(thread), None)

    def _select(self, owner=None) -> list[dict]:
        with self._mu:
            entries = list(self._entries.values())
        out = []
        for e in entries:
            t = e["thread"]
            if t.ident is None:
                # Registered but not yet started (spawn(start=False)):
                # neither live nor reapable yet.
                continue
            if not t.is_alive():
                # Reap threads that exited without the spawn wrapper
                # running its deregister (e.g. killed interpreter-side).
                self.deregister(t)
                continue
            if owner is not None and e["owner_id"] != id(owner):
                continue
            out.append(e)
        return out

    def live(self, owner=None) -> list[threading.Thread]:
        return [e["thread"] for e in self._select(owner)]

    def check_leaks(self, owner=None) -> list[str]:
        """Names of still-live registered threads (for `owner`)."""
        return sorted(e["name"] for e in self._select(owner))

    def stop_all(self, owner=None, timeout: float = 5.0) -> list[str]:
        """Invoke each entry's stop callable (if any) then join; returns
        the names that survived anyway."""
        for e in self._select(owner):
            stop = e.get("stop")
            if stop is not None:
                try:
                    stop()
                except Exception as e:  # noqa: BLE001 — best-effort sweep
                    # Lazy: errors.py imports this module at module level.
                    from toplingdb_tpu.utils import errors as _errors
                    _errors.swallow(reason="thread-stop-sweep", exc=e)
        return self.join_all(owner, timeout)

    def join_all(self, owner=None, timeout: float = 5.0) -> list[str]:
        deadline = time.monotonic() + timeout
        leaked = []
        for e in self._select(owner):
            t = e["thread"]
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                leaked.append(e["name"])
        return sorted(leaked)


registry = ThreadRegistry()


def spawn(name: str, target, *, args=(), kwargs=None, daemon: bool = True,
          owner=None, stop=None, start: bool = True) -> threading.Thread:
    """The package's only thread constructor: named, registered, and
    auto-deregistering.  `owner` ties the thread to a lifecycle scope
    (e.g. a DB) for leak checks; `stop` is an optional callable
    `registry.stop_all` can use to shut it down."""
    kwargs = kwargs or {}

    def _run():
        try:
            target(*args, **kwargs)
        finally:
            registry.deregister(t)

    t = threading.Thread(target=_run, name=name, daemon=daemon)
    registry.register(t, owner=owner, stop=stop)
    if start:
        t.start()
    return t
