"""EventListener callbacks + structured EventLogger.

Reference include/rocksdb/listener.h:565 (EventListener) and
logging/event_logger.cc (JSON event stream) in /root/reference. Listeners
also travel to distributed compaction workers in the reference
(CompactionParams::listeners); ours fire on the DB side after results merge.
"""

from __future__ import annotations

import json
import time
from toplingdb_tpu.utils import errors as _errors
from dataclasses import dataclass, field


@dataclass
class FlushJobInfo:
    db_name: str
    file_number: int
    file_size: int
    num_entries: int
    smallest_seqno: int
    largest_seqno: int


@dataclass
class CompactionJobInfo:
    db_name: str
    input_level: int
    output_level: int
    input_files: list = field(default_factory=list)
    output_files: list = field(default_factory=list)
    input_records: int = 0
    output_records: int = 0
    elapsed_micros: int = 0
    device: str = "cpu"
    reason: str = ""


@dataclass
class IngestionInfo:
    db_name: str
    external_file_path: str
    internal_file_number: int
    level: int


@dataclass
class DcompactAttemptInfo:
    """One remote compaction attempt (compaction/resilience.py): fired on
    success AND failure, so monitoring can attribute every retry and
    fallback to a worker."""

    db_name: str
    job_id: int
    attempt: int          # 0-based
    url: str              # "" for non-URL transports (subprocess/device)
    ok: bool
    error: str | None
    elapsed_micros: int
    will_retry: bool


@dataclass
class WorkerHealthInfo:
    """A worker circuit-breaker state TRANSITION (open/close)."""

    url: str
    state: str            # CircuitBreaker.CLOSED / OPEN / HALF_OPEN
    consecutive_failures: int


@dataclass
class CorruptionInfo:
    """The IntegrityScrubber (db/integrity.py) found a live file whose
    on-disk bytes no longer match the MANIFEST-recorded checksum; the
    file has been quarantined."""

    db_name: str
    file_number: int
    path: str
    reason: str
    recorded_checksum: str = ""      # hex
    checksum_func_name: str = ""


@dataclass
class SLOAlertInfo:
    """A multi-window burn-rate SLO alert TRANSITION (utils/slo.py):
    fired when both the fast and slow windows burn error budget faster
    than the spec's thresholds, resolved when the fast window recovers."""

    db_name: str
    slo_name: str
    kind: str             # "latency" / "fraction" / "stall" / "replication_lag"
    state: str            # "firing" / "resolved"
    burn_rate_fast: float
    burn_rate_slow: float
    value: float          # last bad-fraction over the fast window
    objective: float
    window_fast_sec: float
    window_slow_sec: float


@dataclass
class DiskPressureInfo:
    """A disk-pressure level TRANSITION from the SstFileManager's
    free-space poller (utils/rate_limiter.py). `level`/`prev` are one of
    "ok" / "amber" / "red"; a red→ok recovery is also a transition."""

    db_name: str
    path: str
    level: str
    prev_level: str
    free_fraction: float
    tracked_bytes: int
    trash_bytes: int
    budget_bytes: int     # 0 = no max_allowed_space_usage budget set


@dataclass
class ErrorRecoveryInfo:
    """A cleared background-error latch (manual resume() or the
    auto-recover loop), reference ErrorHandler recovery notifications."""

    db_name: str
    reason: str           # the latched error's bg reason ("" if unknown)
    auto: bool            # True when the auto-recover loop cleared it


class EventListener:
    """Override any subset (reference EventListener)."""

    def on_flush_completed(self, db, info: FlushJobInfo) -> None:
        pass

    def on_compaction_completed(self, db, info: CompactionJobInfo) -> None:
        pass

    def on_table_file_created(self, db, path: str, file_number: int) -> None:
        pass

    def on_table_file_deleted(self, db, path: str) -> None:
        pass

    def on_external_file_ingested(self, db, info: IngestionInfo) -> None:
        pass

    def on_background_error(self, db, error: BaseException) -> None:
        pass

    def on_dcompact_attempt(self, db, info: DcompactAttemptInfo) -> None:
        pass

    def on_worker_health_changed(self, db, info: WorkerHealthInfo) -> None:
        pass

    def on_corruption_detected(self, db, info: CorruptionInfo) -> None:
        pass

    def on_slo_alert(self, db, info: SLOAlertInfo) -> None:
        pass

    def on_disk_pressure(self, db, info: DiskPressureInfo) -> None:
        pass

    def on_error_recovery_completed(self, db, info: ErrorRecoveryInfo) -> None:
        pass


def notify(listeners, method: str, *args) -> None:
    # listener failures must never take down the engine
    for l in listeners or ():
        with _errors.guard(listener=method):
            getattr(l, method)(*args)


class EventLogger:
    """Structured JSON event stream (reference logging/event_logger.cc):
    one JSON object per line, `time_micros` + `event` + payload. Thread-safe:
    user write/flush threads and background compaction threads share one
    sink."""

    def __init__(self, sink=None):
        from toplingdb_tpu.utils import concurrency as ccy

        self._sink = sink  # callable(str) or file-like; None = discarded
        self._mu = ccy.Lock("listener.EventLogger._mu")

    def log(self, event: str, **payload) -> str:
        rec = {"time_micros": int(time.time() * 1e6), "event": event}
        # Telemetry correlation: lifecycle events emitted inside a traced
        # operation carry its trace_id, so `ldb dump_events` lines join
        # against /traces waterfalls.
        from toplingdb_tpu.utils import telemetry as _tm

        tid = _tm.current_trace_id()
        if tid is not None:
            rec["trace_id"] = tid
        rec.update(payload)
        line = json.dumps(rec)
        if self._sink is not None:
            with self._mu:
                try:
                    if callable(self._sink):
                        self._sink(line)
                    else:
                        self._sink.write(line + "\n")
                except Exception as e:
                    # The info LOG is best-effort, like the reference's:
                    # a full or failing disk must not take down whatever
                    # background thread happened to emit an event (the
                    # disk-pressure poller, most ironically).
                    from toplingdb_tpu.utils import errors as _errors

                    _errors.swallow(reason="event-log-append", exc=e)
        return line
