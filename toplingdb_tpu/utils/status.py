"""Status/error model.

The reference threads a `Status` value through every call
(util/status.cc, include/rocksdb/status.h in /root/reference). Python has
exceptions; we use them, but keep a Status taxonomy so error classification
(ErrorHandler severity mapping, reference db/error_handler.h:28) has the same
vocabulary.
"""

from __future__ import annotations

import enum


class Code(enum.IntEnum):
    OK = 0
    NOT_FOUND = 1
    CORRUPTION = 2
    NOT_SUPPORTED = 3
    INVALID_ARGUMENT = 4
    IO_ERROR = 5
    MERGE_IN_PROGRESS = 6
    INCOMPLETE = 7
    SHUTDOWN_IN_PROGRESS = 8
    TIMED_OUT = 9
    ABORTED = 10
    BUSY = 11
    EXPIRED = 12
    TRY_AGAIN = 13
    COMPACTION_TOO_LARGE = 14
    COLUMN_FAMILY_DROPPED = 15


class Severity(enum.IntEnum):
    """Background-error severity, mirroring reference db/error_handler.h."""

    NO_ERROR = 0
    SOFT_ERROR = 1      # writes may stall, reads fine, auto-recoverable
    HARD_ERROR = 2      # writes stopped until Resume()
    FATAL_ERROR = 3     # DB must be reopened
    UNRECOVERABLE = 4


class Status(Exception):
    """Base error for the framework. `code` classifies it."""

    code: Code = Code.IO_ERROR

    def __init__(self, msg: str = "", *, retryable: bool = False):
        super().__init__(msg)
        self.retryable = retryable

    @property
    def message(self) -> str:
        return str(self)


class NotFound(Status):
    code = Code.NOT_FOUND


class Corruption(Status):
    code = Code.CORRUPTION


class NotSupported(Status):
    code = Code.NOT_SUPPORTED


class InvalidArgument(Status):
    code = Code.INVALID_ARGUMENT


class IOError_(Status):
    code = Code.IO_ERROR


class MergeInProgress(Status):
    code = Code.MERGE_IN_PROGRESS


class Incomplete(Status):
    code = Code.INCOMPLETE


class ShutdownInProgress(Status):
    code = Code.SHUTDOWN_IN_PROGRESS


class TryAgain(Status):
    code = Code.TRY_AGAIN


class Busy(Status):
    code = Code.BUSY


class Expired(Status):
    code = Code.EXPIRED
