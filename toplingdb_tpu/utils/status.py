"""Status/error model.

The reference threads a `Status` value through every call
(util/status.cc, include/rocksdb/status.h in /root/reference). Python has
exceptions; we use them, but keep a Status taxonomy so error classification
(ErrorHandler severity mapping, reference db/error_handler.h:28) has the same
vocabulary.
"""

from __future__ import annotations

import enum


class Code(enum.IntEnum):
    OK = 0
    NOT_FOUND = 1
    CORRUPTION = 2
    NOT_SUPPORTED = 3
    INVALID_ARGUMENT = 4
    IO_ERROR = 5
    MERGE_IN_PROGRESS = 6
    INCOMPLETE = 7
    SHUTDOWN_IN_PROGRESS = 8
    TIMED_OUT = 9
    ABORTED = 10
    BUSY = 11
    EXPIRED = 12
    TRY_AGAIN = 13
    COMPACTION_TOO_LARGE = 14
    COLUMN_FAMILY_DROPPED = 15


class Severity(enum.IntEnum):
    """Background-error severity, mirroring reference db/error_handler.h."""

    NO_ERROR = 0
    SOFT_ERROR = 1      # writes may stall, reads fine, auto-recoverable
    HARD_ERROR = 2      # writes stopped until Resume()
    FATAL_ERROR = 3     # DB must be reopened
    UNRECOVERABLE = 4


class Status(Exception):
    """Base error for the framework. `code` classifies it."""

    code: Code = Code.IO_ERROR

    def __init__(self, msg: str = "", *, retryable: bool = False):
        super().__init__(msg)
        self.retryable = retryable

    @property
    def message(self) -> str:
        return str(self)


class NotFound(Status):
    code = Code.NOT_FOUND


class Corruption(Status):
    code = Code.CORRUPTION


class NotSupported(Status):
    code = Code.NOT_SUPPORTED


class InvalidArgument(Status):
    code = Code.INVALID_ARGUMENT


class IOError_(Status):
    code = Code.IO_ERROR


class MergeInProgress(Status):
    code = Code.MERGE_IN_PROGRESS


class Incomplete(Status):
    code = Code.INCOMPLETE


class ShutdownInProgress(Status):
    code = Code.SHUTDOWN_IN_PROGRESS


class TryAgain(Status):
    code = Code.TRY_AGAIN


class Busy(Status):
    code = Code.BUSY


class Expired(Status):
    code = Code.EXPIRED


class NoSpace(IOError_):
    """Out-of-disk-space IO error (reference Status::NoSpace() subcode
    kNoSpace). Retryable by default: the error-handler latches it SOFT
    and the auto-recover loop clears it once space frees."""

    def __init__(self, msg: str = "", *, retryable: bool = True):
        super().__init__(msg, retryable=retryable)


def is_no_space(e: BaseException) -> bool:
    """Does this exception chain mean the disk (or byte budget) is full?
    Recognizes our NoSpace, a raw OSError ENOSPC anywhere in the cause
    chain, and wrapped messages (the posix Env re-raises OSErrors as
    IOError_ with the strerror text embedded)."""
    import errno

    seen = 0
    while e is not None and seen < 8:
        if isinstance(e, NoSpace):
            return True
        if isinstance(e, OSError) and e.errno == errno.ENOSPC:
            return True
        msg = str(e).lower()
        if "enospc" in msg or "no space left" in msg:
            return True
        e = e.__cause__ or e.__context__
        seen += 1
    return False
