"""Stats history: time-series snapshots of the Statistics tickers.

Analogue of the reference's InMemoryStatsHistoryIterator /
PersistentStatsHistoryIterator (monitoring/in_memory_stats_history.cc,
monitoring/persistent_stats_history.cc; surfaced via DBImpl::GetStatsHistory,
db/db_impl/db_impl.cc:1102). Snapshots are delta-encoded like the reference
(each sample stores the ticker increase since the previous sample).

Health-plane extension: each sample also carries per-histogram interval
rows (count/sum/max delta since the previous snapshot), so /stats_history
can reconstruct latency and rate time series — the sensing the SLO engine
and the fleet autopilot (ROADMAP item 1) consume.
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import time
import warnings

from . import statistics as _st


class StatsHistory:
    """Bounded in-memory ring of (timestamp, ticker deltas, histogram
    interval rows) samples."""

    def __init__(self, statistics, max_samples: int = 1024):
        self._stats = statistics
        self._max = max_samples
        self._samples: list[tuple[int, dict[str, int], dict[str, dict]]] = []
        self._last_absolute: dict[str, int] = {}
        # Per-histogram (count, sum) at the previous snapshot, for the
        # interval-delta rows.
        self._last_hist: dict[str, tuple[int, float]] = {}
        self._mu = ccy.Lock("stats_history.StatsHistory._mu")

    def snapshot(self, now: int | None = None) -> None:
        """Record the ticker + histogram deltas since the previous
        snapshot."""
        if self._stats is None:
            return
        now = int(time.time()) if now is None else now
        with self._stats._lock:
            absolute = dict(self._stats._tickers)
            hist_abs = {
                k: (h.count, h.sum, h) for k, h in
                self._stats._histograms.items() if h.count
            }
        with self._mu:
            delta = {
                k: v - self._last_absolute.get(k, 0)
                for k, v in absolute.items()
                if v - self._last_absolute.get(k, 0)
            }
            self._last_absolute = absolute
            hist_rows: dict[str, dict] = {}
            for k, (cnt, total, h) in hist_abs.items():
                pc, ps = self._last_hist.get(k, (0, 0))
                dc = cnt - pc
                if dc <= 0:
                    continue
                # Interval max: the windowed ring's recent max when the
                # histogram keeps one (exact enough for sensing); the
                # lifetime max otherwise.
                if isinstance(h, _st.WindowedHistogram):
                    mx = h.windowed().max
                else:
                    mx = h.max
                hist_rows[k] = {"count": dc, "sum": total - ps, "max": mx}
            self._last_hist = {k: (c, s) for k, (c, s, _) in hist_abs.items()}
            self._samples.append((now, delta, hist_rows))
            if len(self._samples) > self._max:
                del self._samples[: len(self._samples) - self._max]

    def last_sample(self):
        """Most recent (ts, delta) or None — taken under the lock so a
        concurrent snapshot() can't hand back someone else's sample."""
        with self._mu:
            if not self._samples:
                return None
            ts, d, _ = self._samples[-1]
            return ts, dict(d)

    def get(self, start_time: int = 0,
            end_time: int = 2 ** 62) -> list[tuple[int, dict[str, int]]]:
        """Samples with start_time <= ts < end_time (reference
        GetStatsHistory contract). Ticker deltas only — see series()
        for the histogram rows."""
        with self._mu:
            return [
                (ts, dict(d)) for ts, d, _ in self._samples
                if start_time <= ts < end_time
            ]

    def series(self, start_time: int = 0,
               end_time: int = 2 ** 62) -> list[dict]:
        """Full samples: [{"ts", "tickers", "histograms"}] where
        histograms is {name: {"count", "sum", "max"}} per interval."""
        with self._mu:
            return [
                {"ts": ts, "tickers": dict(d), "histograms":
                 {k: dict(r) for k, r in hr.items()}}
                for ts, d, hr in self._samples
                if start_time <= ts < end_time
            ]


class StatsDumpScheduler:
    """Periodic snapshot thread (reference stats_persist_period_sec /
    stats_dump_period_sec via the periodic task scheduler). Daemonized;
    stop() joins. `on_snapshot` (optional) fires after each snapshot —
    the DB hooks its event-log stats_dump line there."""

    def __init__(self, history: StatsHistory, period_sec: float,
                 on_snapshot=None, statistics=None):
        self._history = history
        self._period = period_sec
        self._on_snapshot = on_snapshot
        # Swallowed-exception accounting goes to the stats the history
        # samples, so a perpetually-failing dump line is visible.
        self._statistics = statistics if statistics is not None \
            else history._stats
        self.errors = 0
        self._stop = threading.Event()
        self._thread = ccy.spawn("stats-dump", self._run, owner=self,
                                 stop=self.stop)

    def _run(self) -> None:
        while not self._stop.wait(self._period):
            self._history.snapshot()
            if self._on_snapshot is not None:
                try:
                    self._on_snapshot()
                except Exception:
                    # A dump-line failure must not kill the sampler, but
                    # it must not be invisible either.
                    self.errors += 1
                    if self._statistics is not None:
                        self._statistics.record_tick(_st.STATS_DUMP_ERRORS)

    def stop(self) -> bool:
        """Stop and join. Returns True when the thread exited; False
        (with a RuntimeWarning) when it is still alive after the join
        timeout — a hung on_snapshot callback."""
        self._stop.set()
        self._thread.join(timeout=2.0)
        if self._thread.is_alive():
            warnings.warn(
                "StatsDumpScheduler thread did not exit within 2s "
                "(on_snapshot hung?)", RuntimeWarning, stacklevel=2)
            return False
        return True
