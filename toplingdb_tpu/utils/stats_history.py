"""Stats history: time-series snapshots of the Statistics tickers.

Analogue of the reference's InMemoryStatsHistoryIterator /
PersistentStatsHistoryIterator (monitoring/in_memory_stats_history.cc,
monitoring/persistent_stats_history.cc; surfaced via DBImpl::GetStatsHistory,
db/db_impl/db_impl.cc:1102). Snapshots are delta-encoded like the reference
(each sample stores the ticker increase since the previous sample).
"""

from __future__ import annotations

import threading
import time


class StatsHistory:
    """Bounded in-memory ring of (timestamp, {ticker: delta}) samples."""

    def __init__(self, statistics, max_samples: int = 1024):
        self._stats = statistics
        self._max = max_samples
        self._samples: list[tuple[int, dict[str, int]]] = []
        self._last_absolute: dict[str, int] = {}
        self._mu = threading.Lock()

    def snapshot(self, now: int | None = None) -> None:
        """Record the ticker deltas since the previous snapshot."""
        if self._stats is None:
            return
        now = int(time.time()) if now is None else now
        with self._stats._lock:
            absolute = dict(self._stats._tickers)
        with self._mu:
            delta = {
                k: v - self._last_absolute.get(k, 0)
                for k, v in absolute.items()
                if v - self._last_absolute.get(k, 0)
            }
            self._last_absolute = absolute
            self._samples.append((now, delta))
            if len(self._samples) > self._max:
                del self._samples[: len(self._samples) - self._max]

    def last_sample(self):
        """Most recent (ts, delta) or None — taken under the lock so a
        concurrent snapshot() can't hand back someone else's sample."""
        with self._mu:
            if not self._samples:
                return None
            ts, d = self._samples[-1]
            return ts, dict(d)

    def get(self, start_time: int = 0,
            end_time: int = 2 ** 62) -> list[tuple[int, dict[str, int]]]:
        """Samples with start_time <= ts < end_time (reference
        GetStatsHistory contract)."""
        with self._mu:
            return [
                (ts, dict(d)) for ts, d in self._samples
                if start_time <= ts < end_time
            ]


class StatsDumpScheduler:
    """Periodic snapshot thread (reference stats_persist_period_sec /
    stats_dump_period_sec via the periodic task scheduler). Daemonized;
    stop() joins. `on_snapshot` (optional) fires after each snapshot —
    the DB hooks its event-log stats_dump line there."""

    def __init__(self, history: StatsHistory, period_sec: float,
                 on_snapshot=None):
        self._history = history
        self._period = period_sec
        self._on_snapshot = on_snapshot
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self._period):
            self._history.snapshot()
            if self._on_snapshot is not None:
                try:
                    self._on_snapshot()
                except Exception:
                    pass  # a dump-line failure must not kill the sampler

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)
