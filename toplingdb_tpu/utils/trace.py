"""Workload tracing + replay (reference trace_replay/trace_replay.cc,
include/rocksdb/utilities/replayer.h, tools/trace_analyzer_tool.cc in
/root/reference): record Get/Put/Delete/Merge/DeleteRange/Iterator ops with
timestamps to a log-framed file; replay them against any DB; analyze
per-type/key statistics."""

from __future__ import annotations

import time

from toplingdb_tpu.db.log import LogReader, LogWriter
from toplingdb_tpu.utils import coding

OP_GET = 1
OP_PUT = 2
OP_DELETE = 3
OP_MERGE = 4
OP_DELETE_RANGE = 5
OP_ITER_SEEK = 6
OP_WRITE_BATCH = 7
OP_ITER_SEEK_FOR_PREV = 8
OP_MULTIGET = 9

_OP_NAMES = {
    OP_GET: "get", OP_PUT: "put", OP_DELETE: "delete", OP_MERGE: "merge",
    OP_DELETE_RANGE: "delete_range", OP_ITER_SEEK: "iter_seek",
    OP_WRITE_BATCH: "write_batch",
    OP_ITER_SEEK_FOR_PREV: "iter_seek_for_prev", OP_MULTIGET: "multiget",
}


class TraceOptions:
    """Reference TraceOptions (include/rocksdb/trace_reader_writer.h):
    byte cap on the trace file + 1-in-N op sampling."""

    def __init__(self, max_trace_file_size: int = 0,
                 sampling_frequency: int = 1):
        self.max_trace_file_size = max_trace_file_size
        self.sampling_frequency = max(1, sampling_frequency)


class OpTracer:
    """DB-attached operation recorder (reference DB::StartTrace /
    trace_replay/trace_replay.cc): the DB calls record_* from its own
    read/write entry points, so EVERY op is captured — unlike the wrapper
    Tracer below, which only sees calls routed through it. Thread-safe;
    silently stops at max_trace_file_size (the reference's behavior)."""

    def __init__(self, env, trace_path: str,
                 options: TraceOptions | None = None):
        from toplingdb_tpu.utils import concurrency as ccy

        self.options = options or TraceOptions()
        self._w = LogWriter(env.new_writable_file(trace_path))
        self._mu = ccy.Lock("trace.OpTracer._mu")
        self._written = 0
        self._seq = 0
        self.stopped = False

    def _record(self, op: int, *slices: bytes) -> None:
        if self.stopped:
            return
        with self._mu:
            # Sampling decides BEFORE any encoding work: on a 1-in-N
            # config the hot read path must not pay the record build for
            # dropped ops. stopped re-checks under the lock so a racing
            # close() can't hand us a closed writer.
            if self.stopped:
                return
            self._seq += 1
            if self._seq % self.options.sampling_frequency:
                return
            out = bytearray()
            out += coding.encode_varint32(op)
            out += coding.encode_varint64(int(time.time() * 1e6))
            for s in slices:
                coding.put_length_prefixed_slice(out, s)
            cap = self.options.max_trace_file_size
            if cap and self._written + len(out) > cap:
                self.stopped = True
                return
            self._written += len(out) + 7  # log framing overhead
            self._w.add_record(bytes(out))

    def record_get(self, key: bytes) -> None:
        self._record(OP_GET, key)

    def record_multiget(self, keys) -> None:
        self._record(OP_MULTIGET, *keys)

    def record_write(self, batch_rep: bytes) -> None:
        self._record(OP_WRITE_BATCH, batch_rep)

    def record_iter_seek(self, key: bytes, for_prev: bool = False) -> None:
        self._record(OP_ITER_SEEK_FOR_PREV if for_prev else OP_ITER_SEEK,
                     key)

    def close(self) -> None:
        with self._mu:
            self._w.sync()
            self._w.close()
            self.stopped = True


class TracingIterator:
    """Proxy recording the seeks of one DB iterator (reference traces
    Iterator::Seek/SeekForPrev through the same mechanism)."""

    def __init__(self, it, tracer: OpTracer):
        self._it = it
        self._tr = tracer

    def seek(self, key):
        self._tr.record_iter_seek(key)
        return self._it.seek(key)

    def seek_for_prev(self, key):
        self._tr.record_iter_seek(key, for_prev=True)
        return self._it.seek_for_prev(key)

    def __getattr__(self, name):
        return getattr(self._it, name)


class Tracer:
    """Wraps a DB; every operation is both executed and recorded."""

    def __init__(self, db, trace_path: str):
        self._db = db
        self._w = LogWriter(db.env.new_writable_file(trace_path))

    def _rec(self, op: int, *slices: bytes) -> None:
        out = bytearray()
        out += coding.encode_varint32(op)
        out += coding.encode_varint64(int(time.time() * 1e6))
        for s in slices:
            coding.put_length_prefixed_slice(out, s)
        self._w.add_record(bytes(out))

    def get(self, key, opts=None):
        self._rec(OP_GET, key)
        return self._db.get(key) if opts is None else self._db.get(key, opts)

    def put(self, key, value, opts=None):
        self._rec(OP_PUT, key, value)
        return self._db.put(key, value) if opts is None else self._db.put(key, value, opts)

    def delete(self, key, opts=None):
        self._rec(OP_DELETE, key)
        return self._db.delete(key)

    def merge(self, key, value, opts=None):
        self._rec(OP_MERGE, key, value)
        return self._db.merge(key, value)

    def delete_range(self, begin, end, opts=None):
        self._rec(OP_DELETE_RANGE, begin, end)
        return self._db.delete_range(begin, end)

    def close(self) -> None:
        self._w.sync()
        self._w.close()


def read_trace(env, trace_path: str):
    """Yields (op, time_micros, [slices])."""
    for rec in LogReader(env.new_sequential_file(trace_path)).records():
        op, off = coding.decode_varint32(rec, 0)
        ts, off = coding.decode_varint64(rec, off)
        slices = []
        while off < len(rec):
            s, off = coding.get_length_prefixed_slice(rec, off)
            slices.append(s)
        yield op, ts, slices


class Replayer:
    """Replay a trace against a DB (reference Replayer,
    include/rocksdb/utilities/replayer.h): fast-forward or
    timing-faithful (inter-op gaps divided by `speedup`, the reference's
    fast-forward factor), optionally fanned out over worker threads (the
    reference's MultiThreadReplay)."""

    def __init__(self, db, trace_path: str):
        self._db = db
        self._path = trace_path

    def _apply(self, op, slices):
        db = self._db
        if op in (OP_GET,):
            db.get(slices[0])
        elif op == OP_MULTIGET:
            db.multi_get(list(slices))
        elif op == OP_PUT:
            db.put(slices[0], slices[1])
        elif op == OP_DELETE:
            db.delete(slices[0])
        elif op == OP_MERGE:
            db.merge(slices[0], slices[1])
        elif op == OP_DELETE_RANGE:
            db.delete_range(slices[0], slices[1])
        elif op == OP_WRITE_BATCH:
            from toplingdb_tpu.db.write_batch import WriteBatch

            db.write(WriteBatch(data=slices[0]))
        elif op in (OP_ITER_SEEK, OP_ITER_SEEK_FOR_PREV):
            it = db.new_iterator()
            if op == OP_ITER_SEEK:
                it.seek(slices[0])
            else:
                it.seek_for_prev(slices[0])

    def replay(self, fast_forward: bool = True, speedup: float = 1.0,
               threads: int = 1) -> int:
        """Returns the number of ops replayed. fast_forward=True ignores
        recorded timing entirely; otherwise inter-op gaps are honored,
        divided by `speedup`. With threads > 1, LOOKUP ops fan out over a
        pool while writes stay ordered on the caller thread (writes
        reordering against each other would corrupt the replayed state)."""
        n = 0
        prev_ts = None
        pool = None
        futures = []
        if threads > 1:
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(threads)
        try:
            for op, ts, slices in read_trace(self._db.env, self._path):
                if not fast_forward and prev_ts is not None:
                    time.sleep(max(0, (ts - prev_ts) / 1e6 / speedup))
                prev_ts = ts
                if pool is not None and op in (OP_GET, OP_MULTIGET,
                                               OP_ITER_SEEK,
                                               OP_ITER_SEEK_FOR_PREV):
                    futures.append(pool.submit(self._apply, op, slices))
                else:
                    self._apply(op, slices)
                n += 1
        finally:
            if pool is not None:
                pool.shutdown(wait=True)
        for f in futures:
            f.result()  # surface worker failures, not a clean count
        return n


def analyze_trace(env, trace_path: str) -> dict:
    """Per-op-type counts + hottest keys (reference trace_analyzer).
    Thin wrapper over the full CLI analyzer so there is exactly ONE
    aggregation loop (tools/trace_analyzer.py)."""
    from toplingdb_tpu.tools.trace_analyzer import analyze

    return analyze(env, trace_path)
