"""Workload tracing + replay (reference trace_replay/trace_replay.cc,
include/rocksdb/utilities/replayer.h, tools/trace_analyzer_tool.cc in
/root/reference): record Get/Put/Delete/Merge/DeleteRange/Iterator ops with
timestamps to a log-framed file; replay them against any DB; analyze
per-type/key statistics."""

from __future__ import annotations

import time

from toplingdb_tpu.db.log import LogReader, LogWriter
from toplingdb_tpu.utils import coding

OP_GET = 1
OP_PUT = 2
OP_DELETE = 3
OP_MERGE = 4
OP_DELETE_RANGE = 5
OP_ITER_SEEK = 6
OP_WRITE_BATCH = 7

_OP_NAMES = {
    OP_GET: "get", OP_PUT: "put", OP_DELETE: "delete", OP_MERGE: "merge",
    OP_DELETE_RANGE: "delete_range", OP_ITER_SEEK: "iter_seek",
    OP_WRITE_BATCH: "write_batch",
}


class Tracer:
    """Wraps a DB; every operation is both executed and recorded."""

    def __init__(self, db, trace_path: str):
        self._db = db
        self._w = LogWriter(db.env.new_writable_file(trace_path))

    def _rec(self, op: int, *slices: bytes) -> None:
        out = bytearray()
        out += coding.encode_varint32(op)
        out += coding.encode_varint64(int(time.time() * 1e6))
        for s in slices:
            coding.put_length_prefixed_slice(out, s)
        self._w.add_record(bytes(out))

    def get(self, key, opts=None):
        self._rec(OP_GET, key)
        return self._db.get(key) if opts is None else self._db.get(key, opts)

    def put(self, key, value, opts=None):
        self._rec(OP_PUT, key, value)
        return self._db.put(key, value) if opts is None else self._db.put(key, value, opts)

    def delete(self, key, opts=None):
        self._rec(OP_DELETE, key)
        return self._db.delete(key)

    def merge(self, key, value, opts=None):
        self._rec(OP_MERGE, key, value)
        return self._db.merge(key, value)

    def delete_range(self, begin, end, opts=None):
        self._rec(OP_DELETE_RANGE, begin, end)
        return self._db.delete_range(begin, end)

    def close(self) -> None:
        self._w.sync()
        self._w.close()


def read_trace(env, trace_path: str):
    """Yields (op, time_micros, [slices])."""
    for rec in LogReader(env.new_sequential_file(trace_path)).records():
        op, off = coding.decode_varint32(rec, 0)
        ts, off = coding.decode_varint64(rec, off)
        slices = []
        while off < len(rec):
            s, off = coding.get_length_prefixed_slice(rec, off)
            slices.append(s)
        yield op, ts, slices


class Replayer:
    """Replay a trace against a DB (reference Replayer)."""

    def __init__(self, db, trace_path: str):
        self._db = db
        self._path = trace_path

    def replay(self, fast_forward: bool = True) -> int:
        n = 0
        prev_ts = None
        for op, ts, slices in read_trace(self._db.env, self._path):
            if not fast_forward and prev_ts is not None:
                time.sleep(max(0, (ts - prev_ts) / 1e6))
            prev_ts = ts
            if op == OP_GET:
                self._db.get(slices[0])
            elif op == OP_PUT:
                self._db.put(slices[0], slices[1])
            elif op == OP_DELETE:
                self._db.delete(slices[0])
            elif op == OP_MERGE:
                self._db.merge(slices[0], slices[1])
            elif op == OP_DELETE_RANGE:
                self._db.delete_range(slices[0], slices[1])
            n += 1
        return n


def analyze_trace(env, trace_path: str) -> dict:
    """Per-op-type counts + hottest keys (reference trace_analyzer).
    Thin wrapper over the full CLI analyzer so there is exactly ONE
    aggregation loop (tools/trace_analyzer.py)."""
    from toplingdb_tpu.tools.trace_analyzer import analyze

    return analyze(env, trace_path)
