"""SliceTransform: key→prefix extractors.

Analogue of the reference's SliceTransform (include/rocksdb/slice_transform.h
in /root/reference): maps a user key to a prefix used by prefix bloom
filters, the plain-table prefix hash index (table/plain/ role), the
prefix-bucketed memtables, and prefix-mode iteration
(ReadOptions.prefix_same_as_start). `in_domain` marks keys the transform
applies to — out-of-domain keys are excluded from prefix indexes/filters and
lookups for them fall back to total-order search.
"""

from __future__ import annotations


class SliceTransform:
    def name(self) -> str:
        raise NotImplementedError

    def transform(self, key: bytes) -> bytes:
        raise NotImplementedError

    def in_domain(self, key: bytes) -> bool:
        return True


class FixedPrefixTransform(SliceTransform):
    """First `n` bytes; keys shorter than n are out of domain
    (reference util/slice.cc FixedPrefixTransform)."""

    def __init__(self, n: int):
        if n <= 0:
            raise ValueError("fixed prefix length must be positive")
        self.n = n

    def name(self) -> str:
        return f"tpulsm.FixedPrefix.{self.n}"

    def transform(self, key: bytes) -> bytes:
        return key[: self.n]

    def in_domain(self, key: bytes) -> bool:
        return len(key) >= self.n


class CappedPrefixTransform(SliceTransform):
    """First min(len, n) bytes; every key is in domain
    (reference CappedPrefixTransform)."""

    def __init__(self, n: int):
        if n <= 0:
            raise ValueError("capped prefix length must be positive")
        self.n = n

    def name(self) -> str:
        return f"tpulsm.CappedPrefix.{self.n}"

    def transform(self, key: bytes) -> bytes:
        return key[: self.n]


class NoopTransform(SliceTransform):
    """Identity: the whole key is its own prefix."""

    def name(self) -> str:
        return "tpulsm.Noop"

    def transform(self, key: bytes) -> bytes:
        return key


def slice_transform_from_name(name: str) -> SliceTransform | None:
    """Reconstruct a stock transform from its serialized name (how the
    extractor travels through TableProperties and the dcompact boundary).
    Unknown/custom names return None, as the reference treats unknown
    customizables."""
    if name.startswith("tpulsm.FixedPrefix."):
        return FixedPrefixTransform(int(name.rsplit(".", 1)[1]))
    if name.startswith("tpulsm.CappedPrefix."):
        return CappedPrefixTransform(int(name.rsplit(".", 1)[1]))
    if name == "tpulsm.Noop":
        return NoopTransform()
    return None


def resolve_file_extractor(opts_extractor, recorded_name: str):
    """The extractor to use against a FILE's prefix structures (prefix hash
    index, prefix bloom). The live options extractor is only trusted when it
    matches the name the file was built with — an extractor change across
    reopen must not make probes of old files report false absence — else the
    recorded name is reconstructed (None for custom/unknown names: callers
    fail open / fall back to total-order search)."""
    if opts_extractor is not None and opts_extractor.name() == recorded_name:
        return opts_extractor
    return slice_transform_from_name(recorded_name) if recorded_name else None
