"""Accelerator-backend reachability probe.

The axon (TPU-tunnel) jax plugin can hang FOREVER inside backend client
creation when the tunnel is down — no error, no timeout (observed stack:
``jaxlib/xla_client.py make_c_api_client`` never returns; the PJRT C-API
client dials the relay and blocks). Anything that may touch the
accelerator non-interactively (bench, driver entry points) probes first in
a KILLABLE subprocess and falls back to the cpu backend when unreachable.

Unlike a bare liveness check, the probe RECORDS EVIDENCE: the child runs
with ``faulthandler.dump_traceback_later`` armed so a hang produces the
exact blocking stack on stderr, and the parent keeps the stderr tail. The
bench embeds that evidence in its JSON so an unreachable-TPU run is
diagnosable after the fact instead of a silent CPU fallback.

Shared here so the tunnel-handling logic cannot diverge between callers.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

# The child arms faulthandler a little inside the parent's budget so ITS
# stack dump (the evidence) wins the race against the parent's SIGKILL.
_CHILD_GRACE_S = 5.0

_PROBE_SRC = r"""
import faulthandler, sys, time
budget = float(sys.argv[1])
faulthandler.dump_traceback_later(budget, exit=True)
t0 = time.time()
import jax
print("probe: import jax ok %.1fs" % (time.time() - t0), file=sys.stderr)
t0 = time.time()
d = jax.devices()
print("probe: devices ok %.1fs %s" % (time.time() - t0, d), file=sys.stderr)
import jax.numpy as jnp
from toplingdb_tpu.utils import errors as _errors
t0 = time.time()
x = jnp.ones((256, 256), dtype=jnp.bfloat16)
(x @ x).block_until_ready()
print("probe: matmul ok %.1fs" % (time.time() - t0), file=sys.stderr)
"""


def probe_jax_backend(timeout_s: float):
    """Run ``import jax; jax.devices(); tiny matmul`` in a fresh process.

    Returns ``(ok, diag)`` where diag is a JSON-able dict:
    ``{"ok", "elapsed_s", "rc", "stderr_tail"}``. On a hang the child's
    faulthandler stack (e.g. ``make_c_api_client``) appears in
    stderr_tail — the recorded root cause VERDICT r03 asked for.

    Runs in its own session: a timeout kills the whole process GROUP (the
    plugin may spawn helpers that would otherwise hold pipes open past the
    child's death)."""
    import tempfile

    t0 = time.time()
    diag = {"ok": False, "elapsed_s": 0.0, "rc": None, "stderr_tail": ""}
    # Child stderr goes to a FILE, not a pipe: a plugin helper that outlives
    # the child would hold a pipe open and stall p.communicate() past the
    # child's exit (the DEVNULL rationale of the original probe) — a file fd
    # has no reader to block on, and we read it after wait().
    with tempfile.TemporaryFile() as errf:
        try:
            p = subprocess.Popen(
                [sys.executable, "-u", "-c", _PROBE_SRC,
                 str(max(1.0, timeout_s - _CHILD_GRACE_S))],
                stdout=subprocess.DEVNULL, stderr=errf,
                start_new_session=True,
            )
        except OSError as e:
            diag["stderr_tail"] = f"popen failed: {e!r}"
            return False, diag
        try:
            diag["rc"] = p.wait(timeout=timeout_s)
            diag["ok"] = diag["rc"] == 0
        except subprocess.TimeoutExpired:
            try:
                os.killpg(p.pid, signal.SIGKILL)
            except OSError:
                p.kill()
            p.wait()
            diag["rc"] = "killed-after-timeout"
        diag["elapsed_s"] = round(time.time() - t0, 1)
        errf.seek(0, os.SEEK_END)
        size = errf.tell()
        errf.seek(max(0, size - 2000))
        diag["stderr_tail"] = errf.read().decode("utf-8", "replace")
    return diag["ok"], diag


def redirect_to_cpu_backend() -> None:
    """Point THIS process at the cpu backend — env vars for a not-yet-
    imported jax, jax.config for one the sitecustomize pre-imported."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    if "jax" in sys.modules:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception as e:
            _errors.swallow(reason="jax-platform-pin", exc=e)


def ensure_reachable_backend(timeout_s: float = 120.0,
                             attempts: int = 1,
                             backoff_s: float = 30.0,
                             diagnostics: list | None = None) -> bool:
    """Returns True when the configured accelerator is reachable (or no
    accelerator is configured); on False the process has been redirected to
    the cpu backend. `attempts` > 1 retries with `backoff_s` sleeps so one
    transient tunnel outage doesn't decide an entire bench run. Each
    attempt's evidence dict is appended to `diagnostics` when given."""
    if os.environ.get("JAX_PLATFORMS") != "axon":
        return True
    for i in range(max(1, attempts)):
        if i:
            time.sleep(backoff_s)
        ok, diag = probe_jax_backend(timeout_s)
        diag["attempt"] = i + 1
        if diagnostics is not None:
            diagnostics.append(diag)
        if ok:
            return True
    redirect_to_cpu_backend()
    return False


def retry_redirect(orig_platforms, orig_pool_ips, timeout_s: float,
                   attempt_label: str, diagnostics: list) -> bool:
    """One mid-run tunnel retry, shared by every caller so the restore/
    flip protocol cannot diverge: restore the accelerator env, probe with
    evidence, and either flip an already-imported jax back to the
    accelerator platform (safe only while no backend has been
    initialized) or redirect to cpu again. Returns True when the
    accelerator is reachable."""
    import sys as _sys

    os.environ["JAX_PLATFORMS"] = orig_platforms or ""
    if orig_pool_ips is not None:
        os.environ["PALLAS_AXON_POOL_IPS"] = orig_pool_ips
    ok, diag = probe_jax_backend(timeout_s)
    diag["attempt"] = attempt_label
    diagnostics.append(diag)
    if ok:
        os.environ.pop("TPULSM_HOST_SORT", None)
        if "jax" in _sys.modules:
            import jax

            try:
                jax.config.update("jax_platforms", orig_platforms or "")
            except Exception as e:
                _errors.swallow(reason="jax-platform-restore", exc=e)
        return True
    redirect_to_cpu_backend()
    return False
