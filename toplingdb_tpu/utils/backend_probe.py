"""Accelerator-backend reachability probe.

The axon (TPU-tunnel) jax plugin can hang FOREVER inside backend client
creation when the tunnel is down — no error, no timeout. Anything that may
touch the accelerator non-interactively (bench, driver entry points) probes
first in a KILLABLE subprocess and falls back to the cpu backend when
unreachable. Shared here so the tunnel-handling logic cannot diverge
between callers."""

from __future__ import annotations

import os
import signal
import subprocess
import sys


def probe_jax_backend(timeout_s: float) -> bool:
    """True iff `import jax; jax.devices()` completes in a fresh process.
    Runs in its own session with output discarded: a timeout kills the
    whole process GROUP (the plugin may spawn helpers that would otherwise
    hold pipes open past the child's death)."""
    try:
        p = subprocess.Popen(
            [sys.executable, "-c", "import jax; jax.devices()"],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True,
        )
    except OSError:
        return False
    try:
        return p.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except OSError:
            p.kill()
        p.wait()
        return False


def redirect_to_cpu_backend() -> None:
    """Point THIS process at the cpu backend — env vars for a not-yet-
    imported jax, jax.config for one the sitecustomize pre-imported."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    if "jax" in sys.modules:
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass


def ensure_reachable_backend(timeout_s: float = 120.0,
                             attempts: int = 1,
                             backoff_s: float = 30.0) -> bool:
    """Returns True when the configured accelerator is reachable (or no
    accelerator is configured); on False the process has been redirected to
    the cpu backend. `attempts` > 1 retries with `backoff_s` sleeps so one
    transient tunnel outage doesn't decide an entire bench run."""
    import time

    if os.environ.get("JAX_PLATFORMS") != "axon":
        return True
    for i in range(max(1, attempts)):
        if i:
            time.sleep(backoff_s)
        if probe_jax_backend(timeout_s):
            return True
    redirect_to_cpu_backend()
    return False
