"""Seqno ↔ wall-time mapping.

Analogue of the reference's SeqnoToTimeMapping (db/seqno_to_time_mapping.cc):
a sorted list of (seqno, time) pairs sampled as writes happen, used to answer
"roughly when was this sequence number written" — the basis for
tiered/temperature compaction decisions and preclude_last_level_data_seconds.
Capacity-bounded: when full, every other pair is dropped (halving the
sampling resolution, like the reference's enforced max_capacity)."""

from __future__ import annotations

import bisect
import threading

from toplingdb_tpu.utils import concurrency as ccy


class SeqnoToTimeMapping:
    def __init__(self, max_capacity: int = 100):
        self._pairs: list[tuple[int, int]] = []  # (seqno, unix_time) ascending
        self._max = max(2, max_capacity)
        self._mu = ccy.Lock("seqno_to_time.SeqnoToTimeMapping._mu")

    def append(self, seqno: int, time_: int) -> None:
        """Record seqno existed at time_; out-of-order appends are ignored
        (the mapping must stay monotonic in both axes)."""
        with self._mu:
            if self._pairs:
                ls, lt = self._pairs[-1]
                if seqno <= ls or time_ < lt:
                    return
            self._pairs.append((seqno, time_))
            if len(self._pairs) > self._max:
                self._pairs = self._pairs[::2] + [self._pairs[-1]] \
                    if len(self._pairs) % 2 == 0 else self._pairs[::2]

    def get_proximal_time(self, seqno: int) -> int | None:
        """Largest recorded time T such that everything at/below `seqno`
        was written at/before T is unknowable; we return the time of the
        greatest recorded seqno <= seqno (None if seqno predates the
        mapping) — the reference's GetProximalTimeBeforeSeqno."""
        with self._mu:
            i = bisect.bisect_right([s for s, _ in self._pairs], seqno)
            if i == 0:
                return None
            return self._pairs[i - 1][1]

    def get_proximal_seqno(self, time_: int) -> int | None:
        """Greatest recorded seqno written at/before time_ (reference
        GetProximalSeqnoBeforeTime) — None if time_ predates the mapping."""
        with self._mu:
            i = bisect.bisect_right([t for _, t in self._pairs], time_)
            if i == 0:
                return None
            return self._pairs[i - 1][0]

    def __len__(self) -> int:
        with self._mu:
            return len(self._pairs)

    def to_list(self) -> list:
        with self._mu:
            return [list(p) for p in self._pairs]

    def load(self, pairs) -> None:
        """Replace contents from a persisted list (monotonic enforcement
        re-applied)."""
        with self._mu:
            self._pairs = []
        for seqno, t in pairs:
            self.append(int(seqno), int(t))
