"""Whole-file checksums (the integrity plane's at-rest half).

Role of the reference's FileChecksumGenFactory / FileChecksumGenCrc32c
(include/rocksdb/file_checksum.h, util/file_checksum_helper.cc in
/root/reference): every SST gets a whole-file checksum computed when the
file is produced (flush, compaction, ingest, import, repair), recorded in
its FileMetaData and persisted through the MANIFEST, then re-verified by
`DB.verify_file_checksums()`, checkpoint/backup creation, CF import, the
replication follower's checkpoint bootstrap, and the background
IntegrityScrubber (db/integrity.py).

Two generators ship: `crc32c` (streaming crc32c.extend over the file) and
`xxh64` (per-chunk xxh64 chained through the seed — an xxh-style
combinator). Factories are name-keyed so the MANIFEST records WHICH
function produced each digest and verification always replays the same
one.
"""

from __future__ import annotations

from toplingdb_tpu.utils import crc32c
from toplingdb_tpu.utils.status import Corruption, InvalidArgument

DEFAULT_CHECKSUM_NAME = "crc32c"
_CHUNK = 1 << 20


class FileChecksumGenerator:
    """Streaming digest over a file's bytes (reference
    FileChecksumGenerator): update() with consecutive chunks, then
    finalize() -> digest bytes."""

    name = "base"

    def update(self, data: bytes) -> None:
        raise NotImplementedError

    def finalize(self) -> bytes:
        raise NotImplementedError


class Crc32cFileChecksumGen(FileChecksumGenerator):
    name = "crc32c"

    def __init__(self):
        self._crc = 0

    def update(self, data: bytes) -> None:
        self._crc = crc32c.extend(self._crc, data)

    def finalize(self) -> bytes:
        return self._crc.to_bytes(4, "little")


class Xxh64FileChecksumGen(FileChecksumGenerator):
    """xxh64 combinator: chunk digests chain through the seed, so the
    result is order- and framing-sensitive without buffering the file."""

    name = "xxh64"

    def __init__(self):
        self._h = 0
        self._len = 0

    def update(self, data: bytes) -> None:
        self._h = crc32c.xxh64(bytes(data), seed=self._h)
        self._len += len(data)

    def finalize(self) -> bytes:
        # Fold the total length so ab|c and a|bc differ even when chunk
        # digests collide.
        return crc32c.xxh64(self._len.to_bytes(8, "little"),
                            seed=self._h).to_bytes(8, "little")


class FileChecksumGenFactory:
    """Name -> generator registry (reference FileChecksumGenFactory)."""

    _GENS = {
        "crc32c": Crc32cFileChecksumGen,
        "xxh64": Xxh64FileChecksumGen,
    }

    def __init__(self, default: str = DEFAULT_CHECKSUM_NAME):
        if default not in self._GENS:
            raise InvalidArgument(
                f"unknown file checksum function {default!r}; "
                f"known: {sorted(self._GENS)}"
            )
        self.default_name = default

    def create(self, name: str | None = None) -> FileChecksumGenerator:
        name = name or self.default_name
        cls = self._GENS.get(name)
        if cls is None:
            raise InvalidArgument(
                f"unknown file checksum function {name!r}; "
                f"known: {sorted(self._GENS)}"
            )
        return cls()

    def names(self) -> list[str]:
        return sorted(self._GENS)


def factory_for(options) -> FileChecksumGenFactory | None:
    """The effective factory for an Options: `file_checksum` names the
    default generator; None/''/'off' disables whole-file checksums."""
    name = getattr(options, "file_checksum", DEFAULT_CHECKSUM_NAME)
    if not name or name == "off":
        return None
    return FileChecksumGenFactory(name)


def compute_file_checksum(env, path: str, gen: FileChecksumGenerator,
                          pacer=None, aio_ring=None) -> bytes:
    """Digest the whole file through the Env in chunks. `pacer`, when
    given, is called with each chunk's size (the scrubber's rate
    limiter). `aio_ring` (env/env.py AsyncIORing — the shared Env async
    batched-I/O primitive) double-buffers: the NEXT chunk's read is
    submitted to the ring while the current chunk digests, overlapping
    the scrubber's I/O with its checksum compute."""
    f = env.new_random_access_file(path)
    try:
        size = f.size()
        off = 0
        pending = None
        if aio_ring is not None and size:
            want = min(_CHUNK, size)
            pending = aio_ring.submit_task(lambda o=0, w=want: f.read(o, w))
        while off < size:
            want = min(_CHUNK, size - off)
            if pending is not None:
                data = pending.wait()
                pending = None
                nxt = off + (len(data) or 0)
                if nxt < size and data:
                    w2 = min(_CHUNK, size - nxt)
                    pending = aio_ring.submit_task(
                        lambda o=nxt, w=w2: f.read(o, w))
            else:
                data = f.read(off, want)
            if not data:
                raise Corruption(f"{path}: short read at {off}/{size}")
            gen.update(data)
            off += len(data)
            if pacer is not None:
                pacer(len(data))
    finally:
        f.close()
    return gen.finalize()


def stamp_file_checksum(env, path: str, meta, factory) -> None:
    """Compute + record the file checksum on one FileMetaData (no-op when
    disabled or already stamped)."""
    if factory is None or meta.file_checksum:
        return
    gen = factory.create()
    meta.file_checksum = compute_file_checksum(env, path, gen)
    meta.file_checksum_func_name = gen.name


def verify_recorded_checksum(env, path: str, meta, pacer=None) -> int:
    """Recompute and compare one file's recorded checksum; returns bytes
    verified (0 when the meta carries none). Raises Corruption on
    mismatch."""
    if not meta.file_checksum:
        return 0
    gen = FileChecksumGenFactory(meta.file_checksum_func_name
                                 or DEFAULT_CHECKSUM_NAME).create()
    actual = compute_file_checksum(env, path, gen, pacer=pacer)
    if actual != meta.file_checksum:
        raise Corruption(
            f"file checksum mismatch on {path}: recorded "
            f"{meta.file_checksum.hex()} ({meta.file_checksum_func_name}), "
            f"recomputed {actual.hex()}"
        )
    return env.get_file_size(path)


def manifest_file_checksums(dbdir: str, env=None) -> dict[int, tuple[str, bytes]]:
    """file_number -> (func_name, digest) from a DB/checkpoint directory's
    CURRENT+MANIFEST, without opening a DB — the offline half used by
    Checkpoint.restore_to, backup verification, and tools/sst_dump."""
    from toplingdb_tpu.db import filename
    from toplingdb_tpu.db.log import LogReader
    from toplingdb_tpu.db.version_edit import VersionEdit

    if env is None:
        from toplingdb_tpu.env import default_env

        env = default_env()
    cur = env.read_file(filename.current_file_name(dbdir)).decode().strip()
    path = f"{dbdir}/{cur}"
    out: dict[int, tuple[str, bytes]] = {}
    live: set[int] = set()
    for rec in LogReader(env.new_sequential_file(path)).records():
        e = VersionEdit.decode(rec)
        for _lvl, num in e.deleted_files:
            live.discard(num)
        for _lvl, meta in e.new_files:
            live.add(meta.number)
            if meta.file_checksum:
                out[meta.number] = (meta.file_checksum_func_name,
                                    meta.file_checksum)
    return {n: v for n, v in out.items() if n in live}


def verify_dir_file_checksums(dbdir: str, env=None) -> dict:
    """Verify every MANIFEST-recorded SST checksum in a directory (the
    checkpoint-restore / follower-bootstrap / ldb offline check). Returns
    {'files_verified': n, 'bytes_verified': n, 'files_skipped': n}."""
    from toplingdb_tpu.db import filename

    if env is None:
        from toplingdb_tpu.env import default_env

        env = default_env()
    recorded = manifest_file_checksums(dbdir, env)
    verified = bytes_v = skipped = 0
    for num, (fname, digest) in sorted(recorded.items()):
        path = filename.table_file_name(dbdir, num)
        if not env.file_exists(path):
            raise Corruption(f"{dbdir}: MANIFEST references missing {path}")
        gen = FileChecksumGenFactory(fname or DEFAULT_CHECKSUM_NAME).create()
        actual = compute_file_checksum(env, path, gen)
        if actual != digest:
            raise Corruption(
                f"file checksum mismatch on {path}: recorded "
                f"{digest.hex()} ({fname}), recomputed {actual.hex()}"
            )
        verified += 1
        bytes_v += env.get_file_size(path)
    # Live SSTs without a recorded checksum (pre-upgrade files) are
    # counted so callers can see partial coverage.
    for child in env.get_children(dbdir):
        t, num = filename.parse_file_name(child)
        if t == filename.FileType.TABLE and num not in recorded:
            skipped += 1
    return {"files_verified": verified, "bytes_verified": bytes_v,
            "files_skipped": skipped}
