"""Production block codecs: Snappy, LZ4/LZ4HC, ZSTD (+ dictionary).

The reference's production codec set (include/rocksdb/compression_type.h:22-28
in /root/reference: kSnappyCompression=1, kLZ4Compression=4, kLZ4HCCompression=5,
kZSTD=7) with ZSTD dictionary training/compression
(util/compression.h:1435-1476). Bound via ctypes to the system libraries —
the calls release the GIL, so block compression parallelizes across threads
(the reference's parallel-compression role,
block_based_table_builder.cc:818-825).

Payload formats are our own (this is a new SST format, not byte-compatible
with RocksDB): snappy and zstd frames are self-describing; LZ4 raw blocks
carry a varint32 uncompressed-length prefix (same trick the reference uses
for its format_version>=2 LZ4 blocks).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import threading

from toplingdb_tpu.utils import concurrency as ccy

from toplingdb_tpu.utils import coding
from toplingdb_tpu.utils.status import Corruption, NotSupported

_lock = ccy.Lock("codecs._lock")
_libs: dict[str, ctypes.CDLL | None] = {}


def _load(name: str, sonames: tuple[str, ...]) -> ctypes.CDLL | None:
    with _lock:
        if name in _libs:
            return _libs[name]
        lib = None
        for so in sonames:
            try:
                lib = ctypes.CDLL(so)
                break
            except OSError:
                continue
        _libs[name] = lib
        return lib


def _snappy():
    lib = _load("snappy", ("libsnappy.so.1", "libsnappy.so"))
    if lib is not None and not getattr(lib, "_tpulsm_ready", False):
        st = ctypes.c_size_t
        lib.snappy_max_compressed_length.restype = st
        lib.snappy_max_compressed_length.argtypes = [st]
        lib.snappy_compress.restype = ctypes.c_int
        lib.snappy_compress.argtypes = [
            ctypes.c_char_p, st, ctypes.c_char_p, ctypes.POINTER(st)]
        lib.snappy_uncompressed_length.restype = ctypes.c_int
        lib.snappy_uncompressed_length.argtypes = [
            ctypes.c_char_p, st, ctypes.POINTER(st)]
        lib.snappy_uncompress.restype = ctypes.c_int
        lib.snappy_uncompress.argtypes = [
            ctypes.c_char_p, st, ctypes.c_char_p, ctypes.POINTER(st)]
        lib._tpulsm_ready = True
    return lib


def _lz4():
    lib = _load("lz4", ("liblz4.so.1", "liblz4.so"))
    if lib is not None and not getattr(lib, "_tpulsm_ready", False):
        i = ctypes.c_int
        lib.LZ4_compressBound.restype = i
        lib.LZ4_compressBound.argtypes = [i]
        lib.LZ4_compress_default.restype = i
        lib.LZ4_compress_default.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, i, i]
        lib.LZ4_compress_HC.restype = i
        lib.LZ4_compress_HC.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, i, i, i]
        lib.LZ4_decompress_safe.restype = i
        lib.LZ4_decompress_safe.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, i, i]
        lib._tpulsm_ready = True
    return lib


def _zstd():
    lib = _load("zstd", ("libzstd.so.1", "libzstd.so"))
    if lib is not None and not getattr(lib, "_tpulsm_ready", False):
        st = ctypes.c_size_t
        p = ctypes.c_char_p
        lib.ZSTD_compressBound.restype = st
        lib.ZSTD_compressBound.argtypes = [st]
        lib.ZSTD_compress.restype = st
        lib.ZSTD_compress.argtypes = [p, st, p, st, ctypes.c_int]
        lib.ZSTD_decompress.restype = st
        lib.ZSTD_decompress.argtypes = [p, st, p, st]
        lib.ZSTD_isError.restype = ctypes.c_uint
        lib.ZSTD_isError.argtypes = [st]
        lib.ZSTD_getFrameContentSize.restype = ctypes.c_ulonglong
        lib.ZSTD_getFrameContentSize.argtypes = [p, st]
        lib.ZSTD_createCCtx.restype = ctypes.c_void_p
        lib.ZSTD_freeCCtx.argtypes = [ctypes.c_void_p]
        lib.ZSTD_createDCtx.restype = ctypes.c_void_p
        lib.ZSTD_freeDCtx.argtypes = [ctypes.c_void_p]
        lib.ZSTD_compress_usingDict.restype = st
        lib.ZSTD_compress_usingDict.argtypes = [
            ctypes.c_void_p, p, st, p, st, p, st, ctypes.c_int]
        lib.ZSTD_decompress_usingDict.restype = st
        lib.ZSTD_decompress_usingDict.argtypes = [
            ctypes.c_void_p, p, st, p, st, p, st]
        lib.ZDICT_trainFromBuffer.restype = st
        lib.ZDICT_trainFromBuffer.argtypes = [
            p, st, p, ctypes.POINTER(st), ctypes.c_uint]
        lib.ZDICT_isError.restype = ctypes.c_uint
        lib.ZDICT_isError.argtypes = [st]
        lib._tpulsm_ready = True
    return lib


def available(codec: str) -> bool:
    return {"snappy": _snappy, "lz4": _lz4, "zstd": _zstd}[codec]() is not None


def snappy_compress(data: bytes) -> bytes:
    lib = _snappy()
    if lib is None:
        raise NotSupported("libsnappy unavailable")
    out_len = ctypes.c_size_t(lib.snappy_max_compressed_length(len(data)))
    out = ctypes.create_string_buffer(out_len.value)
    if lib.snappy_compress(data, len(data), out, ctypes.byref(out_len)) != 0:
        raise Corruption("snappy_compress failed")
    return out.raw[: out_len.value]


def snappy_decompress(data: bytes) -> bytes:
    lib = _snappy()
    if lib is None:
        raise NotSupported("libsnappy unavailable")
    n = ctypes.c_size_t(0)
    if lib.snappy_uncompressed_length(data, len(data), ctypes.byref(n)) != 0:
        raise Corruption("corrupt snappy block header")
    out = ctypes.create_string_buffer(max(1, n.value))
    out_len = ctypes.c_size_t(n.value)
    if lib.snappy_uncompress(data, len(data), out, ctypes.byref(out_len)) != 0:
        raise Corruption("corrupt snappy block")
    return out.raw[: out_len.value]


def lz4_compress(data: bytes, hc: bool = False, level: int = 0) -> bytes:
    lib = _lz4()
    if lib is None:
        raise NotSupported("liblz4 unavailable")
    bound = lib.LZ4_compressBound(len(data))
    out = ctypes.create_string_buffer(bound)
    if hc:
        n = lib.LZ4_compress_HC(data, out, len(data), bound, level or 9)
    else:
        n = lib.LZ4_compress_default(data, out, len(data), bound)
    if n <= 0:
        raise Corruption("LZ4 compression failed")
    return coding.encode_varint32(len(data)) + out.raw[:n]


def lz4_decompress(data: bytes) -> bytes:
    lib = _lz4()
    if lib is None:
        raise NotSupported("liblz4 unavailable")
    raw_len, off = coding.decode_varint32(data, 0)
    out = ctypes.create_string_buffer(max(1, raw_len))
    n = lib.LZ4_decompress_safe(data[off:], out, len(data) - off, raw_len)
    if n < 0 or n != raw_len:
        raise Corruption("corrupt LZ4 block")
    return out.raw[:raw_len]


def zstd_compress(data: bytes, level: int = 3, dict_: bytes = b"") -> bytes:
    lib = _zstd()
    if lib is None:
        raise NotSupported("libzstd unavailable")
    bound = lib.ZSTD_compressBound(len(data))
    out = ctypes.create_string_buffer(bound)
    if dict_:
        cctx = lib.ZSTD_createCCtx()
        try:
            n = lib.ZSTD_compress_usingDict(
                cctx, out, bound, data, len(data), dict_, len(dict_), level)
        finally:
            lib.ZSTD_freeCCtx(cctx)
    else:
        n = lib.ZSTD_compress(out, bound, data, len(data), level)
    if lib.ZSTD_isError(n):
        raise Corruption("ZSTD compression failed")
    return out.raw[:n]


def zstd_decompress(data: bytes, dict_: bytes = b"") -> bytes:
    lib = _zstd()
    if lib is None:
        raise NotSupported("libzstd unavailable")
    size = lib.ZSTD_getFrameContentSize(data, len(data))
    if size in (2 ** 64 - 1, 2 ** 64 - 2):  # ERROR / UNKNOWN
        raise Corruption("corrupt zstd block header")
    # The content size is untrusted frame-header bytes: bound it before
    # allocating (a crafted block can claim ~2^64 and OOM the process).
    # The floor must admit any block a builder can legitimately write —
    # a single huge RLE-friendly value can compress >100000x — so only
    # reject sizes beyond a 4 GiB absolute ceiling.
    if size > max(1 << 32, 1000 * len(data)):
        raise Corruption("zstd block claims implausible content size")
    out = ctypes.create_string_buffer(max(1, size))
    if dict_:
        dctx = lib.ZSTD_createDCtx()
        try:
            n = lib.ZSTD_decompress_usingDict(
                dctx, out, size, data, len(data), dict_, len(dict_))
        finally:
            lib.ZSTD_freeDCtx(dctx)
    else:
        n = lib.ZSTD_decompress(out, size, data, len(data))
    if lib.ZSTD_isError(n) or n != size:
        raise Corruption("corrupt zstd block")
    return out.raw[:size]


def zstd_train_dictionary(samples: list[bytes], max_dict_bytes: int) -> bytes:
    """ZDICT training over sample blocks (reference
    util/compression.h:1435-1476 ZSTD_TrainDictionary). Returns b"" when
    training fails (too few/too-uniform samples) — callers then compress
    without a dictionary, which is always safe."""
    lib = _zstd()
    if lib is None or not samples or max_dict_bytes <= 0:
        return b""
    blob = b"".join(samples)
    sizes = (ctypes.c_size_t * len(samples))(*[len(s) for s in samples])
    out = ctypes.create_string_buffer(max_dict_bytes)
    n = lib.ZDICT_trainFromBuffer(
        out, max_dict_bytes, blob, sizes, len(samples))
    if lib.ZDICT_isError(n):
        return b""
    return out.raw[:n]
