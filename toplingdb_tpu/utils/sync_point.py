"""SyncPoint: named test markers with runtime callbacks and dependency
edges — the concurrency-interleaving test mechanism (reference
test_util/sync_point.h:57-158 in /root/reference).

Production code calls sync_point("Name") / sync_point_callback("Name", arg)
at interesting spots; tests load dependencies ("A" must happen before "B")
and callbacks, then enable processing. Disabled (the default), a marker is a
dict lookup + None check — negligible.
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy


class _SyncPointRegistry:
    def __init__(self):
        self._enabled = False
        self._mu = ccy.Lock("sync_point._SyncPointRegistry._mu")
        self._cv = ccy.Condition(lock=self._mu)
        self._callbacks: dict[str, object] = {}
        self._successors: dict[str, list[str]] = {}   # A → [B]: A before B
        self._predecessors: dict[str, list[str]] = {}
        self._cleared: set[str] = set()

    def load_dependency(self, edges: list[tuple[str, str]]) -> None:
        """edges: [(before, after), ...]."""
        with self._mu:
            self._successors.clear()
            self._predecessors.clear()
            self._cleared.clear()
            for a, b in edges:
                self._successors.setdefault(a, []).append(b)
                self._predecessors.setdefault(b, []).append(a)

    def set_callback(self, name: str, fn) -> None:
        with self._mu:
            self._callbacks[name] = fn

    def clear_callback(self, name: str) -> None:
        with self._mu:
            self._callbacks.pop(name, None)

    def enable_processing(self) -> None:
        self._enabled = True

    def disable_processing(self) -> None:
        self._enabled = False
        with self._cv:
            self._cv.notify_all()

    def clear_all(self) -> None:
        self.disable_processing()
        with self._mu:
            self._callbacks.clear()
            self._successors.clear()
            self._predecessors.clear()
            self._cleared.clear()

    def process(self, name: str, arg=None) -> None:
        if not self._enabled:
            return
        # Wait for predecessors FIRST, then run the callback (reference
        # sync_point_impl.cc: PredecessorsAllCleared gates the callback), so
        # a callback on "B" with dependency A→B observes post-A state.
        with self._cv:
            preds = self._predecessors.get(name)
            if preds:
                while self._enabled and not all(
                    p in self._cleared for p in preds
                ):
                    self._cv.wait(timeout=5.0)
        cb = self._callbacks.get(name)
        if cb is not None:
            cb(arg)
        with self._cv:
            self._cleared.add(name)
            self._cv.notify_all()


_registry = _SyncPointRegistry()


def sync_point(name: str) -> None:
    _registry.process(name)


def sync_point_callback(name: str, arg) -> None:
    _registry.process(name, arg)


def get_sync_point_registry() -> _SyncPointRegistry:
    return _registry
