"""Statistics: tickers + histograms (reference include/rocksdb/statistics.h
in /root/reference), including the Topling local-vs-distributed compaction
split (LCOMPACTION_*/DCOMPACTION_*, statistics.h:643-651) that makes the
BASELINE.json metric directly measurable."""

from __future__ import annotations

import math
import threading
from collections import defaultdict

# Ticker names (the subset the engine records; extensible by string).
BLOCK_CACHE_HIT = "block.cache.hit"
BLOCK_CACHE_MISS = "block.cache.miss"
BLOOM_USEFUL = "bloom.filter.useful"
BYTES_WRITTEN = "bytes.written"
BYTES_READ = "bytes.read"
NUMBER_KEYS_WRITTEN = "number.keys.written"
NUMBER_KEYS_READ = "number.keys.read"
COMPACT_READ_BYTES = "compact.read.bytes"
COMPACT_WRITE_BYTES = "compact.write.bytes"
FLUSH_WRITE_BYTES = "flush.write.bytes"
STALL_MICROS = "stall.micros"
WAL_SYNCS = "wal.syncs"
# Topling split: local vs distributed (device/remote) compaction bytes.
LCOMPACTION_READ_BYTES = "lcompaction.read.bytes"
LCOMPACTION_WRITE_BYTES = "lcompaction.write.bytes"
DCOMPACTION_READ_BYTES = "dcompaction.read.bytes"
DCOMPACTION_WRITE_BYTES = "dcompaction.write.bytes"

# Histogram names.
DB_GET_MICROS = "db.get.micros"
DB_WRITE_MICROS = "db.write.micros"
COMPACTION_TIME_MICROS = "compaction.time.micros"
LCOMPACTION_TIME_MICROS = "lcompaction.time.micros"
DCOMPACTION_TIME_MICROS = "dcompaction.time.micros"
FLUSH_TIME_MICROS = "flush.time.micros"
SST_READ_MICROS = "sst.read.micros"


class Histogram:
    """Power-of-two bucketed histogram (lock-free-ish: GIL-atomic adds)."""

    __slots__ = ("buckets", "count", "sum", "min", "max")

    def __init__(self):
        self.buckets = [0] * 64
        self.count = 0
        self.sum = 0
        self.min = math.inf
        self.max = 0

    def add(self, v: float) -> None:
        b = max(0, min(63, int(v).bit_length())) if v >= 1 else 0
        self.buckets[b] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    @property
    def average(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        if not self.count:
            return 0.0
        target = self.count * p / 100.0
        acc = 0
        for b, n in enumerate(self.buckets):
            acc += n
            if acc >= target:
                return float(1 << b)
        return float(self.max)

    def to_string(self) -> str:
        return (
            f"count={self.count} avg={self.average:.1f} "
            f"p50={self.percentile(50):.0f} p99={self.percentile(99):.0f} "
            f"max={self.max:.0f}"
        )


class Statistics:
    def __init__(self):
        self._tickers: dict[str, int] = defaultdict(int)
        self._histograms: dict[str, Histogram] = defaultdict(Histogram)
        self._lock = threading.Lock()

    def record_tick(self, name: str, count: int = 1) -> None:
        with self._lock:
            self._tickers[name] += count

    def get_ticker_count(self, name: str) -> int:
        with self._lock:
            return self._tickers.get(name, 0)

    def record_in_histogram(self, name: str, value: float) -> None:
        with self._lock:
            self._histograms[name].add(value)

    def get_histogram(self, name: str) -> Histogram:
        with self._lock:
            return self._histograms[name]

    def record_compaction(self, stats) -> None:
        """Merge a CompactionStats from a finished job; distributed/device
        jobs go to the D* counters (reference compaction_job.cc:1113-1135
        stat merge-back)."""
        local = stats.device == "cpu"
        if local:
            self.record_tick(LCOMPACTION_READ_BYTES, stats.input_bytes)
            self.record_tick(LCOMPACTION_WRITE_BYTES, stats.output_bytes)
            self.record_in_histogram(LCOMPACTION_TIME_MICROS, stats.work_time_usec)
        else:
            self.record_tick(DCOMPACTION_READ_BYTES, stats.input_bytes)
            self.record_tick(DCOMPACTION_WRITE_BYTES, stats.output_bytes)
            self.record_in_histogram(DCOMPACTION_TIME_MICROS, stats.work_time_usec)
        self.record_tick(COMPACT_READ_BYTES, stats.input_bytes)
        self.record_tick(COMPACT_WRITE_BYTES, stats.output_bytes)
        self.record_in_histogram(COMPACTION_TIME_MICROS, stats.work_time_usec)

    def to_string(self) -> str:
        lines = []
        for k in sorted(self._tickers):
            lines.append(f"{k} COUNT : {self._tickers[k]}")
        for k in sorted(self._histograms):
            lines.append(f"{k} : {self._histograms[k].to_string()}")
        return "\n".join(lines)


class PerfContext:
    """Per-thread perf counters (reference include/rocksdb/perf_context.h).
    Access via perf_context() — a thread-local instance."""

    _FIELDS = (
        "user_key_comparison_count", "block_read_count", "block_read_byte",
        "block_cache_hit_count", "bloom_memtable_hit_count",
        "bloom_sst_hit_count", "bloom_sst_miss_count",
        "get_from_memtable_count", "seek_on_memtable_count",
        "next_on_memtable_count", "write_wal_time", "write_memtable_time",
        "get_snapshot_time", "get_from_output_files_time",
    )

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        for f in self._FIELDS:
            setattr(self, f, 0)

    def to_dict(self) -> dict:
        return {f: getattr(self, f) for f in self._FIELDS}


_perf_tls = threading.local()


def perf_context() -> PerfContext:
    ctx = getattr(_perf_tls, "ctx", None)
    if ctx is None:
        ctx = PerfContext()
        _perf_tls.ctx = ctx
    return ctx


class IOStatsContext:
    """Per-thread IO counters (reference include/rocksdb/iostats_context.h)."""

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        self.bytes_written = 0
        self.bytes_read = 0
        self.write_nanos = 0
        self.read_nanos = 0
        self.fsync_nanos = 0


_iostats_tls = threading.local()


def iostats_context() -> IOStatsContext:
    ctx = getattr(_iostats_tls, "ctx", None)
    if ctx is None:
        ctx = IOStatsContext()
        _iostats_tls.ctx = ctx
    return ctx
