"""Statistics: tickers + histograms (reference include/rocksdb/statistics.h
in /root/reference), including the Topling local-vs-distributed compaction
split (LCOMPACTION_*/DCOMPACTION_*, statistics.h:643-651) that makes the
BASELINE.json metric directly measurable."""

from __future__ import annotations

import math
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time
from collections import defaultdict

# Ticker names, grouped by the reference's families
# (include/rocksdb/statistics.h Tickers enum); extensible by string.
#
# -- block cache -----------------------------------------------------
BLOCK_CACHE_HIT = "block.cache.hit"
BLOCK_CACHE_MISS = "block.cache.miss"
BLOCK_CACHE_ADD = "block.cache.add"
BLOCK_CACHE_ADD_FAILURES = "block.cache.add.failures"
BLOCK_CACHE_DATA_HIT = "block.cache.data.hit"
BLOCK_CACHE_DATA_MISS = "block.cache.data.miss"
BLOCK_CACHE_DATA_ADD = "block.cache.data.add"
BLOCK_CACHE_INDEX_HIT = "block.cache.index.hit"
BLOCK_CACHE_INDEX_MISS = "block.cache.index.miss"
BLOCK_CACHE_INDEX_ADD = "block.cache.index.add"
BLOCK_CACHE_FILTER_HIT = "block.cache.filter.hit"
BLOCK_CACHE_FILTER_MISS = "block.cache.filter.miss"
BLOCK_CACHE_FILTER_ADD = "block.cache.filter.add"
BLOCK_CACHE_BYTES_READ = "block.cache.bytes.read"
BLOCK_CACHE_BYTES_WRITE = "block.cache.bytes.write"
# -- bloom filters ---------------------------------------------------
BLOOM_USEFUL = "bloom.filter.useful"
BLOOM_CHECKED = "bloom.filter.checked"
BLOOM_FULL_POSITIVE = "bloom.filter.full.positive"
BLOOM_FULL_TRUE_POSITIVE = "bloom.filter.full.true.positive"
BLOOM_MEMTABLE_HIT = "bloom.memtable.hit"
BLOOM_MEMTABLE_MISS = "bloom.memtable.miss"
# -- reads -----------------------------------------------------------
BYTES_READ = "bytes.read"
NUMBER_KEYS_READ = "number.keys.read"
MEMTABLE_HIT = "memtable.hit"
MEMTABLE_MISS = "memtable.miss"
GET_HIT_L0 = "get.hit.l0"
GET_HIT_L1 = "get.hit.l1"
GET_HIT_L2_AND_UP = "get.hit.l2andup"
NUMBER_MULTIGET_CALLS = "number.multiget.get"
NUMBER_MULTIGET_KEYS_READ = "number.multiget.keys.read"
NUMBER_MULTIGET_BYTES_READ = "number.multiget.bytes.read"
# Async read plane (env/async_reads.py AsyncReadBatcher serving db.py
# multi_get/get behind TPULSM_ASYNC_READS): block-fetch batches submitted
# to the reader rings, requests merged away by per-file range coalescing,
# and reads the plane had to refuse (non-block tables, knob off mid-call,
# closed rings) — served synchronously instead.
READ_ASYNC_BATCHES = "read.async.batches"
READ_ASYNC_COALESCED = "read.async.coalesced"
READ_ASYNC_FALLBACKS = "read.async.fallbacks"
# -- iteration -------------------------------------------------------
NUMBER_DB_SEEK = "number.db.seek"
NUMBER_DB_NEXT = "number.db.next"
NUMBER_DB_PREV = "number.db.prev"
NUMBER_DB_SEEK_FOUND = "number.db.seek.found"
ITER_BYTES_READ = "db.iter.bytes.read"
NO_ITERATOR_CREATED = "no.iterator.created"
NO_ITERATOR_DELETED = "no.iterator.deleted"
# Chunked scan plane (ops/scan_plane.py): chunk refills served to
# DBIter, and mid-stream degradations to the per-entry path.
ITER_CHUNK_REFILLS = "db.iter.chunk.refills"
ITER_CHUNK_FALLBACKS = "db.iter.chunk.fallbacks"
# Searchable-compression zip data plane (table/zip_table.py serving
# ops/scan_plane.py): value groups bulk-decoded per scan window, raw
# bytes those decodes produced, and zip files the plane had to refuse
# (TPULSM_ZIP_PLANE=0 or native zip kernels missing).
ZIP_GROUP_DECODES = "zip.group.decodes"
ZIP_GROUP_DECODE_BYTES = "zip.group.decode.bytes"
ZIP_PLANE_FALLBACKS = "zip.plane.fallbacks"
# -- writes ----------------------------------------------------------
BYTES_WRITTEN = "bytes.written"
NUMBER_KEYS_WRITTEN = "number.keys.written"
NUMBER_KEYS_UPDATED = "number.keys.updated"
WRITE_DONE_BY_SELF = "write.self"
WRITE_DONE_BY_OTHER = "write.other"
WRITE_WITH_WAL = "write.wal"
WAL_SYNCS = "wal.synced"
WAL_BYTES = "wal.bytes"
# Group-commit write plane (db.py _lead_write_group family + the native
# fused plane): groups led by a leader, follower batches merged into them,
# groups committed through tpulsm_wb_group_commit vs the Python interiors,
# and sync barriers merged into shared fsyncs by the async WAL writer.
WRITE_GROUP_LED = "write.group.led"
WRITE_GROUP_FOLLOWERS = "write.group.followers"
WRITE_GROUP_NATIVE_COMMITS = "write.group.native.commits"
WRITE_GROUP_FALLBACKS = "write.group.fallbacks"
WRITE_GROUP_FSYNCS_COALESCED = "write.group.fsyncs.coalesced"
# -- compaction ------------------------------------------------------
COMPACT_READ_BYTES = "compact.read.bytes"
COMPACT_WRITE_BYTES = "compact.write.bytes"
COMPACTION_KEY_DROP_OBSOLETE = "compaction.key.drop.obsolete"
COMPACTION_KEY_DROP_RANGE_DEL = "compaction.key.drop.range_del"
COMPACTION_CANCELLED = "compaction.cancelled"
NUMBER_SUPERVERSION_ACQUIRES = "number.superversion_acquires"
MERGE_OPERATION_TOTAL_TIME = "merge.operation.time.nanos"
NUMBER_MERGE_FAILURES = "number.merge.failures"
# Topling split: local vs distributed (device/remote) compaction bytes.
LCOMPACTION_READ_BYTES = "lcompaction.read.bytes"
LCOMPACTION_WRITE_BYTES = "lcompaction.write.bytes"
DCOMPACTION_READ_BYTES = "dcompaction.read.bytes"
DCOMPACTION_WRITE_BYTES = "dcompaction.write.bytes"
# Compaction input-scan readahead (FilePrefetchBuffer hits vs preads).
PREFETCH_HITS = "compaction.prefetch.hits"
PREFETCH_MISSES = "compaction.prefetch.misses"
# -- dcompact resilience (compaction/resilience.py) ------------------
DCOMPACTION_ATTEMPTS = "dcompaction.attempts"            # remote tries
DCOMPACTION_RETRIES = "dcompaction.retries"              # re-tries only
DCOMPACTION_JOB_FAILURES = "dcompaction.job.failures"    # attempts exhausted
DCOMPACTION_FALLBACK_LOCAL = "dcompaction.fallback.local"
DCOMPACTION_FALLBACK_PINNED = "dcompaction.fallback.pinned"
DCOMPACTION_LOCAL_PINS = "dcompaction.local.pins"        # gate engagements
DCOMPACTION_DEADLINE_EXCEEDED = "dcompaction.deadline.exceeded"
DCOMPACTION_BREAKER_OPEN = "dcompaction.breaker.open"
DCOMPACTION_BREAKER_CLOSE = "dcompaction.breaker.close"
DCOMPACTION_BREAKER_SKIPPED = "dcompaction.breaker.skipped"
DCOMPACTION_ORPHANS_SWEPT = "dcompaction.orphans.swept"
# -- mesh compaction (ops/mesh_compaction.py): one job fanned over chips
DCOMPACTION_MESH_JOBS = "dcompaction.mesh.jobs"          # mesh-mode jobs
DCOMPACTION_MESH_SHARDS = "dcompaction.mesh.shards"      # shards dispatched
DCOMPACTION_MESH_FALLBACKS = "dcompaction.mesh.fallbacks"  # misses+demotions

# Replication plane (replication/): WAL shipping, follower apply, router.
REPLICATION_FRAMES_SHIPPED = "replication.frames.shipped"
REPLICATION_BYTES_SHIPPED = "replication.bytes.shipped"
REPLICATION_FRAMES_APPLIED = "replication.frames.applied"
REPLICATION_RECORDS_APPLIED = "replication.records.applied"
REPLICATION_FRAME_GAPS = "replication.frame.gaps"          # missing seq run
REPLICATION_FRAME_CORRUPT = "replication.frame.corrupt"    # bad CRC/frame
REPLICATION_EPOCH_RELOADS = "replication.epoch.reloads"    # MANIFEST re-read
REPLICATION_BOOTSTRAPS = "replication.bootstraps"          # checkpoint restore
ROUTER_FOLLOWER_READS = "replication.router.follower.reads"
ROUTER_PRIMARY_READS = "replication.router.primary.reads"  # fallbacks
ROUTER_STALE_SKIPS = "replication.router.stale.skips"      # applied < token
ROUTER_BREAKER_SKIPS = "replication.router.breaker.skips"
ROUTER_EPOCH_REJECTS = "replication.router.epoch.rejects"  # token epoch old
# Sharding plane (toplingdb_tpu/sharding/): key-range shard map, front-door
# router, split/merge/migration, per-tenant admission control.
SHARD_ROUTED_READS = "shard.routed.reads"
SHARD_ROUTED_WRITES = "shard.routed.writes"
SHARD_TOKEN_REJECTS = "shard.token.rejects"        # shard/epoch moved → re-route
SHARD_SPLITS = "shard.splits"
SHARD_MERGES = "shard.merges"
SHARD_MIGRATIONS = "shard.migrations"              # attempts started
SHARD_MIGRATION_FAILURES = "shard.migration.failures"
SHARD_FENCE_WAITS = "shard.fence.waits"            # writers parked at a fence
SHARD_WRITES_SHED = "shard.writes.shed"            # admission denied (Busy)
SHARD_ADMISSION_WAITS = "shard.admission.waits"    # rate-limit throttles
# Fleet plane (sharding/lease.py, sharding/fleet.py): out-of-process shard
# servers behind a lease-based shard-map coordinator.
LEASE_GRANTS = "lease.grants"                      # fresh fencing tokens
LEASE_RENEWALS = "lease.renewals"
LEASE_EXPIRIES = "lease.expiries"                  # lapsed at grant/renew time
LEASE_REJECTS = "lease.rejects"                    # fencing-token/holder mismatch
LEASE_CAS_CONFLICTS = "lease.cas.conflicts"        # map version CAS lost
FLEET_MAP_REFRESHES = "fleet.map.refreshes"        # router map re-pulls
FLEET_WRITE_REJECTS = "fleet.write.rejects"        # router map lease expired
FLEET_STALE_EPOCH_REJECTS = "fleet.stale.epoch.rejects"  # server 409s
FLEET_SELF_FENCES = "fleet.self.fences"            # server lost its lease
FLEET_PROMOTIONS = "fleet.promotions"              # follower -> primary
FLEET_RESTARTS = "fleet.restarts"                  # supervisor respawns
FLEET_HEARTBEAT_MISSES = "fleet.heartbeat.misses"  # renew attempts that failed
FLEET_MIGRATIONS_RECOVERED = "fleet.migrations.recovered"  # cross-process recover
# -- flush / WAL / files ---------------------------------------------
FLUSH_WRITE_BYTES = "flush.write.bytes"
NO_FILE_OPENS = "no.file.opens"
NO_FILE_CLOSES = "no.file.closes"
NO_FILE_ERRORS = "no.file.errors"
# -- stalls ----------------------------------------------------------
STALL_MICROS = "stall.micros"
WRITE_STALL_COUNT = "write.stall.count"
# -- transactions ----------------------------------------------------
TXN_COMMIT = "txn.commit"
TXN_ROLLBACK = "txn.rollback"
TXN_PREPARE = "txn.prepare"
TXN_LOCK_TIMEOUT = "txn.lock.timeout"
TXN_DEADLOCK = "txn.deadlock"
# -- blob files ------------------------------------------------------
BLOB_DB_CACHE_HIT = "blob.db.cache.hit"
BLOB_DB_CACHE_MISS = "blob.db.cache.miss"
BLOB_DB_CACHE_BYTES_READ = "blob.db.cache.bytes.read"
BLOB_DB_CACHE_BYTES_WRITE = "blob.db.cache.bytes.write"
BLOB_DB_BLOB_FILE_BYTES_READ = "blob.db.blob.file.bytes.read"
BLOB_DB_NUM_KEYS_READ = "blob.db.num.keys.read"
BLOB_DB_NUM_KEYS_WRITTEN = "blob.db.num.keys.written"
BLOB_DB_BYTES_READ = "blob.db.bytes.read"
BLOB_DB_BYTES_WRITTEN = "blob.db.bytes.written"
BLOB_DB_GC_NUM_FILES = "blob.db.gc.num.files"
# -- row cache / persistent tiers ------------------------------------
SECONDARY_CACHE_HITS = "secondary.cache.hits"
PERSISTENT_CACHE_HIT = "persistent.cache.hit"
PERSISTENT_CACHE_MISS = "persistent.cache.miss"
# -- disaggregated SST storage (toplingdb_tpu/storage/): the
# content-addressed shared object store behind SharedSstEnv -----------
STORE_HITS = "store.hits"                    # resident serves (cache tier)
STORE_MISSES = "store.misses"                # cold fetches from the store
STORE_PUBLISHES = "store.publishes"          # objects published on install
STORE_BYTES_FETCHED = "store.bytes.fetched"  # payload bytes pulled cold
STORE_GC_SWEPT = "store.gc.swept"            # objects removed by mark-sweep
STORE_FETCH_RETRIES = "store.fetch.retries"  # verify/transport re-fetches
# -- integrity plane (db/integrity.py, utils/protection.py) ----------
INTEGRITY_SCRUB_PASSES = "integrity.scrub.passes"
INTEGRITY_BYTES_VERIFIED = "integrity.bytes.verified"
INTEGRITY_CORRUPTIONS_DETECTED = "integrity.corruptions.detected"
INTEGRITY_PROTECTION_MISMATCHES = "integrity.protection.mismatches"
# -- health plane (utils/slo.py, utils/stats_history.py) -------------
SLO_EVALUATIONS = "slo.evaluations"                # engine passes
SLO_WINDOWS_BREACHED = "slo.windows.breached"      # fast+slow both over
SLO_ALERTS_FIRED = "slo.alerts.fired"              # firing transitions
SLO_ALERTS_RESOLVED = "slo.alerts.resolved"        # recovery transitions
STATS_DUMP_ERRORS = "stats.dump.errors"            # swallowed on_snapshot
# -- error-policy plane (utils/errors.py) ----------------------------
BG_ERROR_SWALLOWED = "bg.error.swallowed"          # policy-swallowed excs
BG_ERROR_RESUMES = "bg.error.resumes"              # latch cleared (manual+auto)
# -- storage-pressure plane (utils/rate_limiter.py SstFileManager,
# db flush/compaction preflight, sharding admission) ------------------
DISK_PRESSURE_POLLS = "disk.pressure.polls"            # poller passes
DISK_PRESSURE_POLLS_BAD = "disk.pressure.polls.bad"    # passes at amber/red
DISK_PRESSURE_TRANSITIONS = "disk.pressure.transitions"  # level changes
DISK_RECLAIM_RUNS = "disk.reclaim.runs"                # reclaim-ladder firings
DISK_TRASH_BYTES_FREED = "disk.trash.bytes.freed"      # paced deleter drains
NO_SPACE_ERRORS = "no_space.errors"                    # ENOSPC/budget latches
NO_SPACE_PREFLIGHT_BLOCKS = "no_space.preflight.blocks"  # jobs refused start
NO_SPACE_WRITES_SHED = "no_space.writes.shed"          # admission/fleet sheds

# Histogram names (reference Histograms enum families).
DB_GET_MICROS = "db.get.micros"
DB_WRITE_MICROS = "db.write.micros"
DB_SEEK_MICROS = "db.seek.micros"
DB_MULTIGET_MICROS = "db.multiget.micros"
COMPACTION_TIME_MICROS = "compaction.time.micros"
COMPACTION_PREPARE_MICROS = "compaction.prepare.micros"
COMPACTION_WAITING_MICROS = "compaction.waiting.micros"
COMPACTION_TRANSFER_MICROS = "compaction.transfer.micros"
COMPACTION_DEVICE_WAIT_MICROS = "compaction.device.wait.micros"
LCOMPACTION_TIME_MICROS = "lcompaction.time.micros"
DCOMPACTION_TIME_MICROS = "dcompaction.time.micros"
DCOMPACTION_PREPARE_MICROS = "dcompaction.prepare.micros"
DCOMPACTION_WAITING_MICROS = "dcompaction.waiting.micros"
DCOMPACTION_RPC_MICROS = "dcompaction.rpc.micros"
DCOMPACTION_ATTEMPT_MICROS = "dcompaction.attempt.micros"
FLUSH_TIME_MICROS = "flush.time.micros"
SST_READ_MICROS = "sst.read.micros"
TABLE_OPEN_IO_MICROS = "table.open.io.micros"
WAL_FILE_SYNC_MICROS = "wal.file.sync.micros"
MANIFEST_FILE_SYNC_MICROS = "manifest.file.sync.micros"
WRITE_STALL_MICROS_HIST = "write.stall.micros"
REPLICATION_LAG_MICROS = "replication.lag.micros"  # ship→apply wall lag
SCRUB_LATENCY_MICROS = "scrub.latency.micros"      # one scrubber pass
SHARD_FENCE_MICROS = "shard.fence.micros"          # write-block cutover window
SHARD_MIGRATION_MICROS = "shard.migration.micros"  # whole migration wall
STORE_FETCH_MICROS = "store.fetch.micros"          # cold-tier object fetch
NUM_FILES_IN_SINGLE_COMPACTION = "numfiles.in.singlecompaction"
BYTES_PER_READ = "bytes.per.read"
BYTES_PER_WRITE = "bytes.per.write"
WRITE_GROUP_BYTES = "write.group.bytes"  # bytes merged per commit group
NUM_SUBCOMPACTIONS_SCHEDULED = "num.subcompactions.scheduled"

# Every `tpulsm_<name>` gauge the HTTP planes may emit (config.py g(),
# replication/dcompact /metrics). tools/check_telemetry.py lints literal
# gauge emissions against this set so a typo'd metric name fails CI
# instead of silently forking a new series.
GAUGE_NAMES = frozenset({
    # per-DB gauges (config._prometheus_gauges)
    "memtable_bytes", "immutable_memtables", "level_files", "level_bytes",
    "last_sequence", "async_wal_ring_depth", "dcompaction_breaker_state",
    "trace_ring_retained", "traces_started_total",
    "write_stall_state", "write_stall_l0_files", "write_stall_micros_total",
    # per-cluster gauges (config._prometheus_cluster_gauges)
    "shard_map_version", "shard_count", "shard_epoch", "shard_fenced",
    "shard_stall_state", "shard_health",
    # SLO engine gauges (config: /metrics burn-rate block)
    "slo_burn_rate_fast", "slo_burn_rate_slow", "slo_firing", "slo_health",
    # fleet aggregator gauges (/cluster/health)
    "fleet_members", "fleet_members_unreachable",
    # dcompact worker /metrics (per-chip rows carry a chip="<i>" label)
    "dcompact_jobs_done", "dcompact_jobs_failed",
    "dcompact_chip_queue_depth", "dcompact_chip_busy",
    "dcompact_chip_wedged",
    # error-policy plane (utils/errors.py, process-wide)
    "bg_error_swallowed_total",
    # storage-pressure plane (config: per-DB SstFileManager block)
    "disk_free_bytes", "disk_tracked_bytes", "disk_trash_bytes",
    "disk_pressure_state", "disk_budget_bytes", "disk_reserved_bytes",
})


class Histogram:
    """Power-of-two bucketed histogram (lock-free-ish: GIL-atomic adds).
    Bucket b holds values in [2^(b-1), 2^b) (b=0 holds [0, 1)), so two
    histograms merge exactly by summing buckets — the property the
    windowed ring and the fleet aggregator both lean on."""

    __slots__ = ("buckets", "count", "sum", "min", "max")

    def __init__(self):
        self.buckets = [0] * 64
        self.count = 0
        self.sum = 0
        self.min = math.inf
        self.max = 0

    def add(self, v: float) -> None:
        b = max(0, min(63, int(v).bit_length())) if v >= 1 else 0
        self.buckets[b] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    @property
    def average(self) -> float:
        return self.sum / self.count if self.count else 0.0

    @property
    def observed_min(self) -> float:
        """min with the empty case guarded: an empty histogram reports
        0.0, never `inf` (which would corrupt Prometheus exposition)."""
        return 0.0 if self.count == 0 else float(self.min)

    def percentile(self, p: float) -> float:
        """In-bucket-interpolated quantile, clamped to [min, max].
        The plain power-of-two bucket upper bound was up to 2x above the
        true value; assuming a uniform spread inside the crossing bucket
        and clamping to the observed extremes keeps every quantile inside
        the data's actual range (a one-sample histogram reports the
        sample itself)."""
        if not self.count:
            return 0.0
        target = self.count * p / 100.0
        acc = 0
        for b, n in enumerate(self.buckets):
            if not n:
                continue
            if acc + n >= target:
                lo = float(1 << (b - 1)) if b else 0.0
                hi = float(1 << b)
                if hi <= lo:  # bucket 63 clamp overflow guard
                    hi = lo * 2.0
                v = lo + (hi - lo) * ((target - acc) / n)
                return min(max(v, self.observed_min), float(self.max))
            acc += n
        return float(self.max)

    def fraction_above(self, threshold: float) -> float:
        """Fraction of recorded values above `threshold`, interpolating
        inside the bucket the threshold lands in — the SLO engine's
        bad-event estimator for latency objectives."""
        if not self.count:
            return 0.0
        above = 0.0
        for b, n in enumerate(self.buckets):
            if not n:
                continue
            lo = float(1 << (b - 1)) if b else 0.0
            hi = float(1 << b)
            if hi <= lo:
                hi = lo * 2.0
            if threshold < lo:
                above += n
            elif threshold < hi:
                above += n * (hi - threshold) / (hi - lo)
        return min(1.0, above / self.count)

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold `other` into self (exact: buckets sum). Returns self."""
        sb, ob = self.buckets, other.buckets
        for i in range(64):
            if ob[i]:
                sb[i] += ob[i]
        self.count += other.count
        self.sum += other.sum
        if other.min < self.min:
            self.min = other.min
        if other.max > self.max:
            self.max = other.max
        return self

    def to_dict(self) -> dict:
        """JSON-portable form (sparse buckets) — the aggregator wire
        format; from_dict() round-trips it and merge() recombines."""
        return {
            "count": self.count,
            "sum": self.sum,
            "min": None if self.count == 0 else self.min,
            "max": self.max,
            "buckets": {str(i): n for i, n in enumerate(self.buckets) if n},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Histogram":
        h = cls()
        h.count = int(d.get("count", 0))
        h.sum = d.get("sum", 0)
        mn = d.get("min")
        h.min = math.inf if mn is None else mn
        h.max = d.get("max", 0)
        for i, n in (d.get("buckets") or {}).items():
            h.buckets[int(i)] = int(n)
        return h

    def to_string(self) -> str:
        return (
            f"count={self.count} avg={self.average:.1f} "
            f"p50={self.percentile(50):.0f} p99={self.percentile(99):.0f} "
            f"max={self.max:.0f}"
        )


class WindowedHistogram(Histogram):
    """Histogram with a ring of K per-interval slots AND a lifetime view.

    The hot path writes ONE place: add() lands in the ring slot covering
    the current `window_sec / intervals`-second interval (a single bucket
    add plus a countdown — the clock is only consulted every
    `_CHECK_EVERY` adds, so attribution near an interval boundary can lag
    by up to `_CHECK_EVERY - 1` samples, which a health plane does not
    care about). Slots evicted from the ring fold into a lifetime base
    histogram, and the cumulative attributes (`count`, `sum`, `min`,
    `max`, `buckets`) are derived on read as base ⊕ live slots — exact,
    because power-of-two buckets merge by summation. That keeps the
    per-add cost within noise of a plain Histogram (the bench gate
    asserts ≤2% on fill+read) while `windowed()` still answers recent
    quantiles from at most the last `window_sec` seconds — a p99
    regression after an hour of uptime shows up within one window instead
    of being diluted into the lifetime distribution. Readers rotate too
    (`windowed()` checks the clock unconditionally), so a stale slot
    never leaks into a fresh window after a quiet period."""

    __slots__ = ("window_sec", "interval_sec", "_ring", "_ring_epochs",
                 "_folded", "_slot", "_slot_epoch", "_clock", "_tick")

    _CHECK_EVERY = 16  # adds between clock reads on the hot path

    def __init__(self, window_sec: float = 60.0, intervals: int = 6,
                 clock=None):
        # Deliberately no super().__init__(): the Histogram attrs are
        # shadowed by the derived properties below.
        intervals = max(1, int(intervals))
        self.window_sec = float(window_sec)
        self.interval_sec = max(1e-9, self.window_sec / intervals)
        self._ring = [Histogram() for _ in range(intervals)]
        self._ring_epochs = [-1] * intervals
        self._folded = Histogram()
        self._clock = clock if clock is not None else time.monotonic
        e = int(self._clock() / self.interval_sec)
        i = e % intervals
        self._ring_epochs[i] = e
        self._slot = self._ring[i]
        self._slot_epoch = e
        self._tick = self._CHECK_EVERY

    # Lifetime view: folded evicted slots ⊕ live ring. Read-side cost is
    # O(intervals) (O(64 * intervals) for buckets); every reader of these
    # is a cold path (exposition, snapshots, SLO evaluation).

    @property
    def count(self) -> int:
        c = self._folded.count
        for h in self._ring:
            c += h.count
        return c

    @property
    def sum(self):
        s = self._folded.sum
        for h in self._ring:
            s += h.sum
        return s

    @property
    def min(self):
        m = self._folded.min
        for h in self._ring:
            if h.min < m:
                m = h.min
        return m

    @property
    def max(self):
        m = self._folded.max
        for h in self._ring:
            if h.max > m:
                m = h.max
        return m

    @property
    def buckets(self) -> list:
        out = list(self._folded.buckets)
        for h in self._ring:
            if h.count:
                hb = h.buckets
                for i in range(64):
                    if hb[i]:
                        out[i] += hb[i]
        return out

    def _rotate(self, epoch: int) -> None:
        ring = self._ring
        k = len(ring)
        steps = epoch - self._slot_epoch
        if steps <= 0:
            return
        # Every interval entered (or skipped over) evicts whatever slot
        # held its ring index: fold it into the lifetime base, then give
        # the index a fresh object (a reader merging the ring
        # concurrently keeps a consistent old slot).
        lo = self._slot_epoch + 1 if steps < k else epoch - k + 1
        for e in range(lo, epoch + 1):
            old = ring[e % k]
            if old.count:
                self._folded.merge(old)
            ring[e % k] = Histogram()
            self._ring_epochs[e % k] = -1
        self._ring_epochs[epoch % k] = epoch
        self._slot = ring[epoch % k]
        self._slot_epoch = epoch

    def add(self, v: float) -> None:
        t = self._tick - 1
        if t > 0:
            self._tick = t
        else:
            self._tick = self._CHECK_EVERY
            epoch = int(self._clock() / self.interval_sec)
            if epoch != self._slot_epoch:
                self._rotate(epoch)
        self._slot.add(v)

    def merge(self, other: "Histogram") -> "Histogram":
        # Merged-in data is historical, not "recent": it folds into the
        # lifetime base so the window stays honest.
        self._folded.merge(other)
        return self

    def windowed(self, seconds: float | None = None) -> Histogram:
        """Merge the live ring slots (at most the trailing `seconds`,
        default the full window) into one mergeable Histogram."""
        now_epoch = int(self._clock() / self.interval_sec)
        if now_epoch != self._slot_epoch:
            self._rotate(now_epoch)
            self._tick = self._CHECK_EVERY
        k = len(self._ring)
        span = k if seconds is None else min(
            k, max(1, math.ceil(seconds / self.interval_sec)))
        lo = now_epoch - span + 1
        out = Histogram()
        for i in range(k):
            e = self._ring_epochs[i]
            if lo <= e <= now_epoch:
                out.merge(self._ring[i])
        return out


class Statistics:
    def __init__(self, histogram_window_sec: float = 60.0,
                 histogram_window_intervals: int = 6):
        self._tickers: dict[str, int] = defaultdict(int)
        self._window_sec = float(histogram_window_sec)
        self._window_intervals = max(1, int(histogram_window_intervals))
        self._histograms: dict[str, Histogram] = defaultdict(
            self._new_histogram)
        self._lock = ccy.Lock("statistics.Statistics._lock")
        # Hot read-path histograms pre-created so record_get skips the
        # defaultdict machinery per call.
        self._h_get_micros = self._histograms[DB_GET_MICROS]
        self._h_bytes_read = self._histograms[BYTES_PER_READ]

    def _new_histogram(self) -> Histogram:
        """histogram_window_sec > 0 → windowed (cumulative + recent ring);
        0 disables the ring entirely (plain cumulative Histogram)."""
        if self._window_sec > 0:
            return WindowedHistogram(self._window_sec, self._window_intervals)
        return Histogram()

    def set_histogram_window(self, window_sec: float,
                             intervals: int = 6) -> None:
        """Re-key the windowed ring (Options.histogram_window_sec wiring).
        Only empty histograms are rebuilt — a populated cumulative series
        is never discarded mid-flight."""
        with self._lock:
            self._window_sec = float(window_sec)
            self._window_intervals = max(1, int(intervals))
            for name, h in list(self._histograms.items()):
                if h.count == 0:
                    self._histograms[name] = self._new_histogram()
            self._h_get_micros = self._histograms[DB_GET_MICROS]
            self._h_bytes_read = self._histograms[BYTES_PER_READ]

    def record_get(self, micros: float, val_len, src) -> None:
        """ONE-lock fast path for the per-Get ticker/histogram family
        (DB_GET_MICROS + NUMBER_KEYS_READ + BYTES_READ + MEMTABLE_HIT/
        MISS + GET_HIT_L*). Three separate lock acquisitions here were
        the bulk of a stats-on Get's cost. GET_HIT_* ticks only on REAL
        value hits — a tombstone-decided miss is not a level 'hit'."""
        with self._lock:
            t = self._tickers
            self._h_get_micros.add(micros)
            t[NUMBER_KEYS_READ] += 1
            if val_len is not None:
                t[BYTES_READ] += val_len
                self._h_bytes_read.add(val_len)
            if src == "mem":
                t[MEMTABLE_HIT] += 1
            else:
                t[MEMTABLE_MISS] += 1
                if val_len is not None:
                    if src == 0:
                        t[GET_HIT_L0] += 1
                    elif src == 1:
                        t[GET_HIT_L1] += 1
                    elif src is not None:
                        t[GET_HIT_L2_AND_UP] += 1

    def record_tick(self, name: str, count: int = 1) -> None:
        with self._lock:
            self._tickers[name] += count

    def record_ticks(self, pairs) -> None:
        """Batch ticker bump under ONE lock acquisition — the read hot
        path records 3-6 tickers per Get, and per-tick locking was ~40%
        of a warm native Get."""
        with self._lock:
            t = self._tickers
            for name, count in pairs:
                t[name] += count

    def get_ticker_count(self, name: str) -> int:
        with self._lock:
            return self._tickers.get(name, 0)

    def tickers(self) -> dict:
        """Consistent snapshot of every ticker (reference getTickerMap)."""
        with self._lock:
            return dict(self._tickers)

    def record_in_histogram(self, name: str, value: float) -> None:
        with self._lock:
            self._histograms[name].add(value)

    def get_histogram(self, name: str) -> Histogram:
        with self._lock:
            return self._histograms[name]

    def record_compaction(self, stats) -> None:
        """Merge a CompactionStats from a finished job; distributed/device
        jobs go to the D* counters with the reference's per-job timing
        breakdown (compaction_job.cc:1113-1135 stat merge-back +
        compaction_executor.h:146-150 prepare/waiting/work fields)."""
        local = stats.device == "cpu" and not getattr(stats, "remote", False)
        if local:
            self.record_tick(LCOMPACTION_READ_BYTES, stats.input_bytes)
            self.record_tick(LCOMPACTION_WRITE_BYTES, stats.output_bytes)
            self.record_in_histogram(LCOMPACTION_TIME_MICROS, stats.work_time_usec)
        else:
            self.record_tick(DCOMPACTION_READ_BYTES, stats.input_bytes)
            self.record_tick(DCOMPACTION_WRITE_BYTES, stats.output_bytes)
            self.record_in_histogram(DCOMPACTION_TIME_MICROS, stats.work_time_usec)
            if stats.prepare_time_usec:
                self.record_in_histogram(DCOMPACTION_PREPARE_MICROS,
                                         stats.prepare_time_usec)
            if stats.waiting_time_usec:
                self.record_in_histogram(DCOMPACTION_WAITING_MICROS,
                                         stats.waiting_time_usec)
            if stats.rpc_time_usec:
                self.record_in_histogram(DCOMPACTION_RPC_MICROS,
                                         stats.rpc_time_usec)
        if getattr(stats, "mesh_chips", 0) > 1:
            self.record_tick(DCOMPACTION_MESH_JOBS)
            self.record_tick(DCOMPACTION_MESH_SHARDS,
                             getattr(stats, "mesh_shards", 0))
        if getattr(stats, "mesh_fallbacks", 0):
            self.record_tick(DCOMPACTION_MESH_FALLBACKS,
                             stats.mesh_fallbacks)
        self.record_tick(COMPACT_READ_BYTES, stats.input_bytes)
        self.record_tick(COMPACT_WRITE_BYTES, stats.output_bytes)
        self.record_in_histogram(COMPACTION_TIME_MICROS, stats.work_time_usec)
        if getattr(stats, "prefetch_hits", 0):
            self.record_tick(PREFETCH_HITS, stats.prefetch_hits)
        if getattr(stats, "prefetch_misses", 0):
            self.record_tick(PREFETCH_MISSES, stats.prefetch_misses)
        if stats.transfer_time_usec:
            self.record_in_histogram(COMPACTION_TRANSFER_MICROS,
                                     stats.transfer_time_usec)
        if getattr(stats, "device_wait_usec", 0):
            # Blocking device-compute + D2H waits, split out of the
            # transfer histogram by the r04 phase breakdown.
            self.record_in_histogram(COMPACTION_DEVICE_WAIT_MICROS,
                                     stats.device_wait_usec)
        if stats.dropped_obsolete or stats.dropped_tombstone:
            # CPU path: the iterator counts drops precisely.
            self.record_tick(COMPACTION_KEY_DROP_OBSOLETE,
                             stats.dropped_obsolete)
            if stats.dropped_tombstone:
                self.record_tick(COMPACTION_KEY_DROP_RANGE_DEL,
                                 stats.dropped_tombstone)
        else:
            # Device/columnar path reports only totals: attribute the
            # non-merge-collapsed remainder to obsolete drops.
            drops = max(0, stats.input_records - stats.output_records
                        - stats.merged_records)
            if drops:
                self.record_tick(COMPACTION_KEY_DROP_OBSOLETE, drops)
        if stats.input_files:
            self.record_in_histogram(NUM_FILES_IN_SINGLE_COMPACTION,
                                     stats.input_files)

    def to_prometheus(self, prefix: str = "tpulsm",
                      labels: str = "") -> str:
        """Prometheus text exposition of every ticker (counter) and
        histogram (count/sum + p50/p99 gauges) — the rockside WebView /
        Prometheus-metrics role (reference README.md:9-10)."""
        lab = "{" + labels + "}" if labels else ""
        lines = []
        with self._lock:
            tickers = sorted(self._tickers.items())
            hists = sorted(self._histograms.items())
        for k, v in tickers:
            m = f"{prefix}_{k.replace('.', '_')}"
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m}{lab} {v}")
        for k, h in hists:
            m = f"{prefix}_{k.replace('.', '_')}"
            lines.append(f"# TYPE {m} summary")
            lines.append(f"{m}_count{lab} {h.count}")
            lines.append(f"{m}_sum{lab} {h.sum}")
            for q, val in ((0.5, h.percentile(50)), (0.99, h.percentile(99))):
                ql = (labels + "," if labels else "") + f'quantile="{q}"'
                lines.append(f"{m}{{{ql}}} {val}")
            if isinstance(h, WindowedHistogram):
                # Recent-window twin: quantiles over the trailing ring
                # only, so a p99 regression shows within one window.
                w = h.windowed()
                r = f"{m}_recent"
                lines.append(f"# TYPE {r} summary")
                lines.append(f"{r}_count{lab} {w.count}")
                lines.append(f"{r}_sum{lab} {w.sum}")
                for q, val in ((0.5, w.percentile(50)),
                               (0.95, w.percentile(95)),
                               (0.99, w.percentile(99))):
                    ql = (labels + "," if labels else "") + f'quantile="{q}"'
                    lines.append(f"{r}{{{ql}}} {val}")
        return "\n".join(lines) + "\n"

    def to_string(self) -> str:
        lines = []
        for k in sorted(self._tickers):
            lines.append(f"{k} COUNT : {self._tickers[k]}")
        for k in sorted(self._histograms):
            lines.append(f"{k} : {self._histograms[k].to_string()}")
        return "\n".join(lines)


class PerfContext:
    """Per-thread perf counters (reference include/rocksdb/perf_context.h —
    the same measurement families, grouped as there).
    Access via perf_context() — a thread-local instance."""

    _FIELDS = (
        # comparisons / blocks
        "user_key_comparison_count", "block_read_count", "block_read_byte",
        "block_read_time", "block_cache_hit_count", "block_cache_miss_count",
        "block_cache_index_hit_count", "block_cache_filter_hit_count",
        "block_checksum_time", "block_decompress_time",
        "raw_block_contents_count",
        # bloom
        "bloom_memtable_hit_count", "bloom_memtable_miss_count",
        "bloom_sst_hit_count", "bloom_sst_miss_count",
        # memtable / key resolution
        "get_from_memtable_count", "get_from_memtable_time",
        "seek_on_memtable_count", "seek_on_memtable_time",
        "next_on_memtable_count", "prev_on_memtable_count",
        "internal_key_skipped_count", "internal_delete_skipped_count",
        "internal_merge_count", "internal_range_del_reseek_count",
        # get path
        "get_snapshot_time", "get_from_output_files_time",
        "get_post_process_time", "get_read_bytes",
        # seek path
        "seek_child_seek_count", "seek_child_seek_time",
        "seek_internal_seek_time", "find_next_user_entry_time",
        "iter_read_bytes",
        # write path
        "write_wal_time", "write_memtable_time", "write_pre_and_post_process_time",
        "write_delay_time", "write_thread_wait_nanos",
        "wal_write_bytes",
        # file / env
        "open_table_file_nanos", "find_table_nanos",
        "new_table_iterator_nanos", "table_cache_hit_count",
        "env_read_nanos", "env_write_nanos", "env_sync_nanos",
        # txn
        "key_lock_wait_count", "key_lock_wait_time",
        # blob
        "blob_read_count", "blob_read_byte", "blob_checksum_time",
        "blob_decompress_time",
        # merge operator
        "merge_operator_time_nanos",
    )

    def __init__(self):
        self.reset()

    def reset(self) -> None:
        for f in self._FIELDS:
            setattr(self, f, 0)

    def to_dict(self) -> dict:
        return {f: getattr(self, f) for f in self._FIELDS}


# PerfContext collection level (reference SetPerfLevel): 0 = disabled
# (the default, matching the reference's PerfLevel::kDisable), 1 =
# count-only, 2+ = reserved for timed fields.
perf_level = 0

_perf_tls = threading.local()


def perf_context() -> PerfContext:
    ctx = getattr(_perf_tls, "ctx", None)
    if ctx is None:
        ctx = PerfContext()
        _perf_tls.ctx = ctx
    return ctx


class IOStatsContext:
    """Per-thread IO counters (reference include/rocksdb/iostats_context.h)."""

    def __init__(self):
        self.reset()

    _FIELDS = ("bytes_written", "bytes_read", "write_nanos", "read_nanos",
               "fsync_nanos")

    def reset(self) -> None:
        self.bytes_written = 0
        self.bytes_read = 0
        self.write_nanos = 0
        self.read_nanos = 0
        self.fsync_nanos = 0

    def to_dict(self) -> dict:
        return {f: getattr(self, f) for f in self._FIELDS}


_iostats_tls = threading.local()


def iostats_context() -> IOStatsContext:
    ctx = getattr(_iostats_tls, "ctx", None)
    if ctx is None:
        ctx = IOStatsContext()
        _iostats_tls.ctx = ctx
    return ctx
