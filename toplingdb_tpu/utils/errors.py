"""Error-policy helpers: the ONE sanctioned way to swallow broad exceptions.

`tools/check_errors.py` (tier-1) forbids unannotated `except Exception`
handlers anywhere in the package: every broad handler must re-raise,
latch the DB background error, tick a declared ticker, or route through
this module with a literal reason. In exchange, every deliberately
swallowed failure becomes countable instead of invisible: the
`BG_ERROR_SWALLOWED` ticker ticks on the attributed `Statistics` when
one is supplied, and a process-wide counter always increments — exposed
at `/metrics` as `tpulsm_bg_error_swallowed_total` so the fleet-health
plane can see background paths degrading quietly.

Two spellings, one policy:

    # Replace `try: ... except Exception: pass` wholesale:
    with errors.swallow(reason="cache-probe-best-effort"):
        probe()

    # Inside a handler that still needs fallback work:
    try:
        return native_path()
    except Exception as e:
        errors.swallow(reason="native-fallback", exc=e)
        return python_path()

    # Listener/callback fan-out (user code must never kill the engine):
    with errors.guard(listener=method):
        cb(*args)

`KeyboardInterrupt`/`SystemExit` are `BaseException`, not `Exception`,
so neither helper ever suppresses them. Set `TPULSM_ERRORS_DEBUG=1` to
print every swallowed traceback to stderr while debugging.
"""

from __future__ import annotations

import collections
import os
import sys
import traceback

from toplingdb_tpu.utils import concurrency as ccy

_UNSET = object()

_mu = ccy.Lock("errors._mu")
_total = 0
_recent: collections.deque = collections.deque(maxlen=64)


def _record(reason: str, exc: BaseException | None, stats) -> None:
    global _total
    with _mu:
        _total += 1
        _recent.append((reason, type(exc).__name__ if exc else None))
    if stats is not None:
        # Outside _mu: record_tick takes statistics.Statistics._lock and
        # the two classes share rank 3 (never nested).
        from toplingdb_tpu.utils import statistics as st

        stats.record_tick(st.BG_ERROR_SWALLOWED)
    if os.environ.get("TPULSM_ERRORS_DEBUG"):
        print(f"[errors.swallow] reason={reason!r} "
              f"exc={type(exc).__name__ if exc else None}", file=sys.stderr)
        if exc is not None:
            traceback.print_exception(type(exc), exc, exc.__traceback__)


class _Swallow:
    """Context manager: suppress `Exception`, record the swallow."""

    __slots__ = ("reason", "stats")

    def __init__(self, reason: str, stats=None):
        self.reason = reason
        self.stats = stats

    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if et is not None and issubclass(et, Exception):
            _record(self.reason, ev, self.stats)
            return True
        return False


def swallow(reason: str, exc=_UNSET, stats=None):
    """Declare a deliberate broad-exception swallow.

    As a context manager (`exc` omitted) it replaces the whole
    try/except; called with `exc=` inside an existing handler it records
    the swallow and returns None so fallback work can follow. `stats=`
    attributes the `BG_ERROR_SWALLOWED` tick to a specific DB's
    Statistics; the process-wide counter increments either way.
    """
    if exc is not _UNSET:
        _record(reason, exc, stats)
        return None
    return _Swallow(reason, stats)


def guard(listener, stats=None) -> _Swallow:
    """Swallow policy for listener/callback fan-out: user callbacks must
    never take down the engine. `listener` names the hook (string or the
    bound method itself)."""
    name = listener if isinstance(listener, str) else getattr(
        listener, "__name__", str(listener))
    return _Swallow(f"listener:{name}", stats)


def swallowed_total() -> int:
    """Process-wide count of policy-swallowed exceptions (the
    `tpulsm_bg_error_swallowed_total` /metrics gauge)."""
    with _mu:
        return _total


def recent() -> list:
    """Last 64 (reason, exc_type_name) swallows, oldest first."""
    with _mu:
        return list(_recent)
