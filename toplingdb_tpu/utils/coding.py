"""Integer/byte coding primitives.

Wire-compatible semantics with the reference's util/coding.h: little-endian
fixed 32/64, LEB128 varint 32/64, and length-prefixed slices. These encodings
appear in every on-disk structure (blocks, SST footers, MANIFEST edits, WAL
payloads), so they are frozen here first (SURVEY.md §7 step 1).
"""

from __future__ import annotations

import struct

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_U16 = struct.Struct("<H")

MAX_VARINT64_LEN = 10
MAX_VARINT32_LEN = 5


def encode_fixed16(v: int) -> bytes:
    return _U16.pack(v & 0xFFFF)


def encode_fixed32(v: int) -> bytes:
    return _U32.pack(v & 0xFFFFFFFF)


def encode_fixed64(v: int) -> bytes:
    return _U64.pack(v & 0xFFFFFFFFFFFFFFFF)


def decode_fixed16(buf: bytes, off: int = 0) -> int:
    return _U16.unpack_from(buf, off)[0]


def decode_fixed32(buf: bytes, off: int = 0) -> int:
    return _U32.unpack_from(buf, off)[0]


def decode_fixed64(buf: bytes, off: int = 0) -> int:
    return _U64.unpack_from(buf, off)[0]


def encode_varint32(v: int) -> bytes:
    return encode_varint64(v & 0xFFFFFFFF)


def encode_varint64(v: int) -> bytes:
    if v < 0:
        raise ValueError("varint must be non-negative")
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def decode_varint32(buf, off: int = 0) -> tuple[int, int]:
    """Returns (value, new_offset)."""
    v, off = decode_varint64(buf, off)
    if v > 0xFFFFFFFF:
        from toplingdb_tpu.utils.status import Corruption

        raise Corruption("varint32 overflow")
    return v, off


def decode_varint64(buf, off: int = 0) -> tuple[int, int]:
    """Returns (value, new_offset)."""
    result = 0
    shift = 0
    n = len(buf)
    while shift <= 63:
        if off >= n:
            from toplingdb_tpu.utils.status import Corruption

            raise Corruption("truncated varint")
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if b < 0x80:
            return result, off
        shift += 7
    from toplingdb_tpu.utils.status import Corruption

    raise Corruption("varint too long")


def varint_length(v: int) -> int:
    n = 1
    while v >= 0x80:
        v >>= 7
        n += 1
    return n


def put_length_prefixed_slice(out: bytearray, s: bytes) -> None:
    out += encode_varint32(len(s))
    out += s


def get_length_prefixed_slice(buf, off: int = 0) -> tuple[bytes, int]:
    """Returns (slice, new_offset)."""
    n, off = decode_varint32(buf, off)
    if off + n > len(buf):
        from toplingdb_tpu.utils.status import Corruption

        raise Corruption("truncated length-prefixed slice")
    return bytes(buf[off : off + n]), off + n
