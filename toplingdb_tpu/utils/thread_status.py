"""Thread status registry.

Analogue of the reference's thread-status mechanism
(monitoring/thread_status_updater.cc, ThreadStatus::STAGE_COMPACTION_RUN
used at compaction_job.cc:660-661): background workers report their current
operation/stage into a process-wide registry that operators can list —
the "what is the DB doing right now" introspection surface."""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import time

_REGISTRY: dict[int, dict] = {}
_MU = ccy.Lock("thread_status._MU")


def set_thread_operation(operation: str, stage: str = "",
                         db_name: str = "") -> None:
    """Record what the CURRENT thread is doing (empty operation clears)."""
    tid = threading.get_ident()
    with _MU:
        if not operation:
            _REGISTRY.pop(tid, None)
            return
        _REGISTRY[tid] = {
            "thread_id": tid,
            "thread_name": threading.current_thread().name,
            "operation": operation,
            "stage": stage,
            "db": db_name,
            "since": time.time(),
        }


class thread_operation:
    """Context manager: report an operation for the scope's duration.
    Nesting-safe: the previous entry (e.g. an outer 'ingest' around a
    write-triggered flush) is restored on exit."""

    def __init__(self, operation: str, stage: str = "", db_name: str = ""):
        self._args = (operation, stage, db_name)
        self._prev = None

    def __enter__(self):
        tid = threading.get_ident()
        with _MU:
            self._prev = _REGISTRY.get(tid)
        set_thread_operation(*self._args)
        return self

    def __exit__(self, *exc):
        tid = threading.get_ident()
        with _MU:
            if self._prev is not None:
                _REGISTRY[tid] = self._prev
            else:
                _REGISTRY.pop(tid, None)


def get_thread_list() -> list[dict]:
    """Snapshot of active background operations (reference
    Env::GetThreadList)."""
    now = time.time()
    with _MU:
        return [
            {**info, "elapsed_s": round(now - info["since"], 3)}
            for info in _REGISTRY.values()
        ]
