"""Block cache: sharded LRU with optional strict capacity
(reference cache/lru_cache.cc, cache/sharded_cache.h in /root/reference),
plus an optional secondary tier (reference SecondaryCache /
utilities/persistent_cache): evicted byte values spill to the secondary,
and primary misses promote secondary hits back.
Plugged into TableReader via TableCache(block_cache=...)."""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
from collections import OrderedDict


class BlockCacheTracer:
    """Access-trace hook (reference trace_replay/block_cache_tracer.cc +
    tools/block_cache_analyzer): JSONL records of every cache lookup."""

    def __init__(self, trace_path: str):
        import json
        import time

        self._json = json
        self._time = time
        self._f = open(trace_path, "a", buffering=1)
        self._mu = ccy.Lock("cache.BlockCacheTracer._mu")

    def record_access(self, key: bytes, hit: bool) -> None:
        line = self._json.dumps({
            "ts_us": int(self._time.time() * 1e6),
            "key": key.hex(), "hit": hit,
        })
        with self._mu:
            self._f.write(line + "\n")

    def close(self) -> None:
        self._f.close()


def analyze_block_cache_trace(trace_path: str) -> dict:
    """Aggregate hit/miss counts + per-key-prefix reuse (the
    block_cache_analyzer role). Delegates to the CLI analyzer so there is
    exactly ONE aggregation loop (tools/block_cache_analyzer.py)."""
    from toplingdb_tpu.tools.block_cache_analyzer import analyze

    r = analyze(trace_path, top_n=None)
    per_file: dict[str, int] = {}
    for e in r["hottest_blocks"]:
        per_file[e["key"][:32]] = per_file.get(e["key"][:32], 0) + e["accesses"]
    return {"hits": r["hits"], "misses": r["misses"],
            "hit_ratio": r["hit_ratio"],
            "accesses_per_file_prefix": per_file}


def _spill_fn(secondary):
    """Adapt secondary.insert to the (key, value, charge) spill callback:
    charge-aware secondaries record the primary's charge; legacy 2-arg
    tiers just drop it."""
    import inspect

    ins = secondary.insert
    try:
        takes_charge = len(inspect.signature(ins).parameters) >= 3
    except (TypeError, ValueError):
        takes_charge = False
    if takes_charge:
        return ins
    return lambda k, v, c: ins(k, v)


def _secondary_hit(secondary, key):
    """(value, charge) from the secondary, or None. The charge is the
    secondary's RECORDED charge when it tracks one (lookup_with_charge);
    otherwise len(value) for raw bytes, and None for non-bytes values —
    promotion with an unknown charge would under-account the shard
    budget, so those are served without promoting."""
    lw = getattr(secondary, "lookup_with_charge", None)
    if lw is not None:
        return lw(key)
    v = secondary.lookup(key)
    if v is None:
        return None
    charge = len(v) if isinstance(v, (bytes, bytearray, memoryview)) else None
    return v, charge


class LRUCache:
    def __init__(self, capacity_bytes: int, num_shards: int = 16,
                 secondary=None, tracer: BlockCacheTracer | None = None):
        self._shards = [
            _Shard(max(1, capacity_bytes // num_shards),
                   spill=_spill_fn(secondary) if secondary is not None
                   else None)
            for _ in range(num_shards)
        ]
        self._n = num_shards
        self.capacity = capacity_bytes
        self.secondary = secondary
        self.tracer = tracer

    def _shard(self, key: bytes) -> "_Shard":
        return self._shards[hash(key) % self._n]

    def lookup(self, key: bytes):
        v = self._shard(key).lookup(key)
        if v is None and self.secondary is not None:
            hit = _secondary_hit(self.secondary, key)
            if hit is not None:
                v, charge = hit
                if charge is not None:
                    self._shard(key).insert(key, v, charge)  # promote
        if self.tracer is not None:
            self.tracer.record_access(key, v is not None)
        return v

    def insert(self, key: bytes, value, charge: int) -> None:
        self._shard(key).insert(key, value, charge)

    def erase(self, key: bytes) -> None:
        self._shard(key).erase(key)
        if self.secondary is not None:
            erase = getattr(self.secondary, "erase", None)
            if erase is not None:
                erase(key)  # or the secondary would resurrect the entry

    def usage(self) -> int:
        return sum(s.usage for s in self._shards)

    def hit_rate(self) -> float:
        hits = sum(s.hits for s in self._shards)
        total = hits + sum(s.misses for s in self._shards)
        return hits / total if total else 0.0


class ClockCache:
    """CLOCK-eviction cache (reference cache/clock_cache.cc HyperClockCache's
    role): a ring of slots with reference bits — lookups only SET a bit (no
    list reordering, far less lock work than LRU), eviction sweeps the clock
    hand clearing bits until it finds a cold slot. Same surface as LRUCache
    (lookup/insert/erase/usage/hit_rate + optional secondary tier)."""

    def __init__(self, capacity_bytes: int, secondary=None, tracer=None):
        self._cap = capacity_bytes
        self._slots: dict[bytes, list] = {}  # key -> [value, charge, refbit]
        self._ring: list[bytes] = []
        self._hand = 0
        self._usage = 0
        self._mu = ccy.Lock("cache.ClockCache._mu")
        self.hits = 0
        self.misses = 0
        self.secondary = secondary
        self._spill = _spill_fn(secondary) if secondary is not None else None
        self.tracer = tracer

    def lookup(self, key: bytes):
        slot = self._slots.get(key)
        if slot is not None:
            slot[2] = 1  # reference bit: no lock, no reordering
            self.hits += 1
            if self.tracer is not None:
                self.tracer.record_access(key, True)
            return slot[0]
        self.misses += 1
        v = None
        if self.secondary is not None:
            hit = _secondary_hit(self.secondary, key)
            if hit is not None:
                v, charge = hit
                if charge is not None:
                    self.insert(key, v, charge)  # promote
        if self.tracer is not None:
            self.tracer.record_access(key, v is not None)
        return v

    def insert(self, key: bytes, value, charge: int) -> None:
        evicted = []
        with self._mu:
            old = self._slots.get(key)
            if old is not None:
                self._usage += charge - old[1]
                old[0], old[1], old[2] = value, charge, 1
            else:
                self._slots[key] = [value, charge, 1]
                self._ring.append(key)
                self._usage += charge
            # CLOCK sweep: clear ref bits until cold slots free the budget.
            # Bound captured ONCE — recomputing against the shrinking ring
            # could stop the sweep while cold evictable slots remain.
            spins = 0
            limit = 2 * len(self._ring) + 2
            while self._usage > self._cap and self._ring and spins < limit:
                if self._hand >= len(self._ring):
                    self._hand = 0
                k = self._ring[self._hand]
                slot = self._slots.get(k)
                if slot is None:  # lazily drop erased keys from the ring
                    self._ring.pop(self._hand)
                    continue
                if slot[2]:
                    slot[2] = 0
                    self._hand += 1
                elif k == key:
                    self._hand += 1  # never evict the entry being inserted
                else:
                    self._ring.pop(self._hand)
                    del self._slots[k]
                    self._usage -= slot[1]
                    evicted.append((k, slot[0], slot[1]))
                spins += 1
        if self._spill is not None:
            for k, v, c in evicted:
                self._spill(k, v, c)

    def erase(self, key: bytes) -> None:
        with self._mu:
            slot = self._slots.pop(key, None)
            if slot is not None:
                self._usage -= slot[1]
                try:
                    # Eager purge: lazy cleanup only runs during eviction
                    # sweeps, so under-capacity erase/re-insert churn would
                    # grow the ring without bound.
                    self._ring.remove(key)
                except ValueError:
                    pass
        if self.secondary is not None:
            erase = getattr(self.secondary, "erase", None)
            if erase is not None:
                erase(key)

    def usage(self) -> int:
        return self._usage

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0


class CompressedSecondaryCache:
    """In-RAM compressed tier (reference cache/compressed_secondary_cache.cc):
    evicted uncompressed blocks are zlib-compressed and kept in a bounded
    FIFO dict; hits decompress and promote back to the primary."""

    def __init__(self, capacity_bytes: int = 64 << 20, level: int = 1):
        import zlib

        self._zlib = zlib
        self._cap = capacity_bytes
        self._level = level
        # key -> (compressed, original primary charge): the recorded
        # charge rides along so promotion re-inserts with the SAME
        # accounting the primary evicted with (a charge > len(value)
        # would otherwise under-account the shard budget).
        self._items: "OrderedDict[bytes, tuple[bytes, int]]" = OrderedDict()
        self._usage = 0
        self._mu = ccy.Lock("cache.CompressedSecondaryCache._mu")
        self.hits = 0
        self.misses = 0

    def insert(self, key: bytes, value, charge: int | None = None) -> None:
        if not isinstance(value, (bytes, bytearray)):
            return
        c = self._zlib.compress(bytes(value), self._level)
        rec = (c, charge if charge is not None else len(value))
        with self._mu:
            old = self._items.pop(key, None)
            if old is not None:
                self._usage -= len(old[0])  # REPLACE: no stale bytes
            self._items[key] = rec
            self._usage += len(c)
            while self._usage > self._cap and self._items:
                _, (dropped, _ch) = self._items.popitem(last=False)
                self._usage -= len(dropped)

    def lookup_with_charge(self, key: bytes):
        """(value, recorded charge) — hit = ownership transfer: the entry
        is POPPED (the caller promotes it to the primary, as the
        reference secondary cache hands its value over)."""
        with self._mu:
            rec = self._items.pop(key, None)
            if rec is not None:
                self._usage -= len(rec[0])
        if rec is None:
            self.misses += 1
            return None
        self.hits += 1
        return self._zlib.decompress(rec[0]), rec[1]

    def lookup(self, key: bytes):
        hit = self.lookup_with_charge(key)
        return None if hit is None else hit[0]

    def erase(self, key: bytes) -> None:
        with self._mu:
            rec = self._items.pop(key, None)
            if rec is not None:
                self._usage -= len(rec[0])

    def usage(self) -> int:
        return self._usage


class SimCache:
    """Cache simulator (reference utilities/simulator_cache/sim_cache.cc):
    wraps a real cache and ALSO tracks what the hit rate WOULD be at a
    different capacity — key-only ghost LRU, no values stored — so capacity
    planning doesn't need a second deployment."""

    def __init__(self, real_cache, sim_capacity_bytes: int):
        self.real = real_cache
        # Key-only ghost reuses the LRU shard (correct charge replacement
        # on re-insert, one eviction implementation) — the reference wraps
        # a key-only cache object the same way.
        self._ghost = _Shard(sim_capacity_bytes)
        self.sim_hits = 0
        self.sim_misses = 0

    def lookup(self, key: bytes):
        v = self.real.lookup(key)
        if self._ghost.lookup(key) is not None:
            self.sim_hits += 1
        else:
            self.sim_misses += 1
            if isinstance(v, (bytes, bytearray)):
                # Real hit the ghost had evicted: re-admit with the TRUE
                # charge. Real misses admit via the follow-up insert().
                self._ghost.insert(key, True, len(v))
        return v

    def insert(self, key: bytes, value, charge: int) -> None:
        self.real.insert(key, value, charge)
        self._ghost.insert(key, True, charge)

    def erase(self, key: bytes) -> None:
        self.real.erase(key)
        self._ghost.erase(key)

    def usage(self) -> int:
        return self.real.usage()

    def sim_hit_rate(self) -> float:
        total = self.sim_hits + self.sim_misses
        return self.sim_hits / total if total else 0.0

    def hit_rate(self) -> float:
        return self.real.hit_rate()


class _Shard:
    def __init__(self, capacity: int, spill=None):
        self._cap = capacity
        self._items: OrderedDict[bytes, tuple[object, int]] = OrderedDict()
        self.usage = 0
        self.hits = 0
        self.misses = 0
        self._mu = ccy.Lock("cache._Shard._mu")
        self._spill = spill  # spill(key, value, charge) on eviction

    def lookup(self, key: bytes):
        with self._mu:
            v = self._items.get(key)
            if v is None:
                self.misses += 1
                return None
            self._items.move_to_end(key)
            self.hits += 1
            return v[0]

    def insert(self, key: bytes, value, charge: int) -> None:
        with self._mu:
            old = self._items.pop(key, None)
            if old is not None:
                self.usage -= old[1]
            self._items[key] = (value, charge)
            self.usage += charge
            evicted = []
            while self.usage > self._cap and self._items:
                k, (v, c) = self._items.popitem(last=False)
                self.usage -= c
                evicted.append((k, v, c))
        if self._spill is not None:
            for k, v, c in evicted:
                self._spill(k, v, c)

    def erase(self, key: bytes) -> None:
        with self._mu:
            old = self._items.pop(key, None)
            if old is not None:
                self.usage -= old[1]
