"""Persistent (on-disk) block cache tier.

Analogue of the reference's persistent cache / compressed secondary cache
(utilities/persistent_cache/, cache/compressed_secondary_cache.cc in
/root/reference): blocks evicted from the in-memory LRU spill to local
cache files; lookups that miss memory are served from disk and promoted
back. Survives process restarts (the index is rebuilt by scanning the
cache files; CRC-checked records, torn tails ignored).

Layout: `cache-NNNNNN.data` files of records
    varint32 klen | varint32 vlen | key | value | fixed32 masked_crc(value)
rolled at `file_size` bytes; eviction drops whole files oldest-first once
total size exceeds `capacity` (the reference's persistent cache evicts at
file granularity too).
"""

from __future__ import annotations

import os
import threading

from toplingdb_tpu.utils import coding, crc32c


class PersistentCache:
    def __init__(self, path: str, capacity_bytes: int = 256 << 20,
                 file_size: int = 4 << 20):
        self._dir = path
        self._cap = capacity_bytes
        self._file_size = max(4096, file_size)
        self._index: dict[bytes, tuple[int, int, int]] = {}  # key -> (file, off, vlen)
        self._files: list[int] = []       # file numbers, oldest first
        self._sizes: dict[int, int] = {}
        self._cur: int | None = None
        self._cur_f = None
        self._mu = threading.Lock()
        self.hits = 0
        self.misses = 0
        os.makedirs(path, exist_ok=True)
        self._recover()

    # -- layout helpers -------------------------------------------------

    def _fname(self, num: int) -> str:
        return os.path.join(self._dir, f"cache-{num:06d}.data")

    def _recover(self) -> None:
        nums = sorted(
            int(n[len("cache-"):-len(".data")])
            for n in os.listdir(self._dir)
            if n.startswith("cache-") and n.endswith(".data")
        )
        for num in nums:
            path = self._fname(num)
            try:
                data = open(path, "rb").read()
            except OSError:
                continue
            off = 0
            while off < len(data):
                try:
                    klen, o = coding.decode_varint32(data, off)
                    vlen, o = coding.decode_varint32(data, o)
                    key = bytes(data[o : o + klen])
                    vo = o + klen
                    value = data[vo : vo + vlen]
                    stored = coding.decode_fixed32(data, vo + vlen)
                    if len(value) != vlen or crc32c.unmask(stored) != \
                            crc32c.value(value):
                        break  # torn/corrupt tail: ignore the rest
                    self._index[key] = (num, vo, vlen)
                    off = vo + vlen + 4
                except Exception:
                    break
            self._files.append(num)
            self._sizes[num] = off
        self._enforce_capacity()

    # -- cache interface ------------------------------------------------

    def lookup(self, key: bytes) -> bytes | None:
        with self._mu:
            loc = self._index.get(key)
        if loc is None:
            self.misses += 1
            return None
        num, off, vlen = loc
        try:
            with open(self._fname(num), "rb") as f:
                f.seek(off)
                value = f.read(vlen)
        except OSError:
            return None
        if len(value) != vlen:
            return None
        self.hits += 1
        return value

    def insert(self, key: bytes, value: bytes) -> None:
        if not isinstance(value, (bytes, bytearray)):
            return  # only raw blocks spill to disk
        rec = bytearray()
        rec += coding.encode_varint32(len(key))
        rec += coding.encode_varint32(len(value))
        rec += key
        voff_in_rec = len(rec)
        rec += value
        rec += coding.encode_fixed32(crc32c.mask(crc32c.value(bytes(value))))
        with self._mu:
            if key in self._index:
                return
            if self._cur_f is None or \
                    self._sizes.get(self._cur, 0) >= self._file_size:
                self._roll_locked()
            base = self._sizes[self._cur]
            self._cur_f.write(rec)
            self._cur_f.flush()
            self._index[key] = (self._cur, base + voff_in_rec, len(value))
            self._sizes[self._cur] = base + len(rec)
            self._enforce_capacity()

    def _roll_locked(self) -> None:
        if self._cur_f is not None:
            self._cur_f.close()
        num = (self._files[-1] + 1) if self._files else 0
        self._cur = num
        self._files.append(num)
        self._sizes[num] = 0
        self._cur_f = open(self._fname(num), "ab")

    def _enforce_capacity(self) -> None:
        while sum(self._sizes.values()) > self._cap and len(self._files) > 1:
            old = self._files.pop(0)
            if old == self._cur:
                self._files.insert(0, old)
                break
            self._index = {
                k: loc for k, loc in self._index.items() if loc[0] != old
            }
            self._sizes.pop(old, None)
            try:
                os.remove(self._fname(old))
            except OSError:
                pass

    def erase(self, key: bytes) -> None:
        """Drop the index entry (the record's bytes are reclaimed when its
        file ages out — file-granularity storage, key-granularity delete)."""
        with self._mu:
            self._index.pop(key, None)

    def close(self) -> None:
        with self._mu:
            if self._cur_f is not None:
                self._cur_f.close()
                self._cur_f = None

    def usage(self) -> int:
        with self._mu:
            return sum(self._sizes.values())
