"""Persistent (on-disk) block cache tier.

Analogue of the reference's persistent cache (utilities/persistent_cache/
block_cache_tier.{h,cc} in /root/reference, plus the compressed secondary
cache role of cache/compressed_secondary_cache.cc): blocks evicted from
the in-memory LRU spill to local cache files through a WRITE-BEHIND queue
(the reference's insert_ops_ writeback thread), lookups that miss memory
are served from disk (optionally decompressed) and promoted back by the
primary's chain, and the index is rebuilt on open by scanning the cache
files (CRC-checked records; torn tails ignored) — the tier survives
process restarts.

Layout: `cache-NNNNNN.data` files of records
    varint32 klen | varint32 plen | 1B flags | key | payload |
    fixed32 masked_crc(payload)
flags bit0 = snappy-compressed payload. Files roll at `file_size` bytes;
eviction drops whole LEAST-RECENTLY-ACCESSED files once total size
exceeds `capacity` (the reference's block_cache_tier also stores and
reclaims at file granularity).
"""

from __future__ import annotations

import os
import threading

from toplingdb_tpu.utils import concurrency as ccy

from toplingdb_tpu.utils import coding, crc32c
from toplingdb_tpu.utils import errors as _errors

_F_SNAPPY = 0x1


class PersistentCache:
    def __init__(self, path: str, capacity_bytes: int = 256 << 20,
                 file_size: int = 4 << 20, compress: bool = True,
                 write_behind: bool = True, queue_bytes: int = 8 << 20):
        from toplingdb_tpu.utils import codecs

        self._dir = path
        self._cap = capacity_bytes
        self._file_size = max(4096, file_size)
        # key -> (file, payload_off, plen, flags)
        self._index: dict[bytes, tuple[int, int, int, int]] = {}
        self._files: list[int] = []       # file numbers, oldest first
        self._sizes: dict[int, int] = {}
        self._atime: dict[int, int] = {}  # file -> last-access tick
        self._tick = 0
        self._cur: int | None = None
        self._cur_f = None
        self._mu = ccy.Lock("persistent_cache.PersistentCache._mu")
        self._compress = compress and codecs.available("snappy")
        # -- stats (reference PersistentCache::Stats role) --------------
        self.hits = 0
        self.misses = 0
        self.bytes_written = 0
        self.bytes_read = 0
        self.inserts = 0
        self.insert_dropped = 0
        os.makedirs(path, exist_ok=True)
        self._recover()
        # -- write-behind queue (reference block_cache_tier insert_ops_
        # writeback thread): inserts enqueue; a background writer encodes,
        # compresses, and appends outside every reader's path.
        self._pending: dict[bytes, bytes] = {}
        self._inflight: dict[bytes, bytes] = {}  # taken by the writer,
        self._pending_bytes = 0                  # not yet appended
        self._queue_cap = max(1 << 16, queue_bytes)
        self._closed = False
        self._wake = ccy.Condition(lock=self._mu)
        self._writer = None
        if write_behind:
            self._writer = ccy.spawn("pcache-writeback",
                                     self._writeback_loop, owner=self,
                                     stop=self.close)

    # -- layout helpers -------------------------------------------------

    def _fname(self, num: int) -> str:
        return os.path.join(self._dir, f"cache-{num:06d}.data")

    def _recover(self) -> None:
        nums = sorted(
            int(n[len("cache-"):-len(".data")])
            for n in os.listdir(self._dir)
            if n.startswith("cache-") and n.endswith(".data")
        )
        for num in nums:
            path = self._fname(num)
            try:
                data = open(path, "rb").read()
            except OSError:
                continue
            off = 0
            while off < len(data):
                try:
                    klen, o = coding.decode_varint32(data, off)
                    plen, o = coding.decode_varint32(data, o)
                    flags = data[o]
                    o += 1
                    key = bytes(data[o : o + klen])
                    po = o + klen
                    payload = data[po : po + plen]
                    stored = coding.decode_fixed32(data, po + plen)
                    if len(payload) != plen or crc32c.unmask(stored) != \
                            crc32c.value(payload):
                        break  # torn/corrupt tail: ignore the rest
                    self._index[key] = (num, po, plen, flags)
                    off = po + plen + 4
                except Exception as e:
                    _errors.swallow(reason="cache-index-scan-stop", exc=e)
                    break
            self._files.append(num)
            self._sizes[num] = off
            self._atime[num] = self._tick
            self._tick += 1
        self._enforce_capacity()

    # -- cache interface ------------------------------------------------

    def lookup(self, key: bytes) -> bytes | None:
        with self._mu:
            pending = self._pending.get(key)
            if pending is None:
                pending = self._inflight.get(key)
            if pending is not None:
                self.hits += 1
                return pending
            loc = self._index.get(key)
            if loc is not None:
                self._tick += 1
                self._atime[loc[0]] = self._tick
            else:
                self.misses += 1
        if loc is None:
            return None
        num, off, plen, flags = loc
        try:
            with open(self._fname(num), "rb") as f:
                f.seek(off)
                payload = f.read(plen)
        except OSError:
            return None
        if len(payload) != plen:
            return None
        if flags & _F_SNAPPY:
            from toplingdb_tpu.utils import codecs

            try:
                payload = codecs.snappy_decompress(payload)
            except Exception as e:
                _errors.swallow(reason="cache-snappy-corrupt", exc=e)
                return None
        with self._mu:
            self.hits += 1
            self.bytes_read += len(payload)
        return payload

    def insert(self, key: bytes, value: bytes) -> None:
        if not isinstance(value, (bytes, bytearray)):
            return  # only raw blocks spill to disk
        value = bytes(value)
        with self._mu:
            if self._closed:
                return  # writes must not resurrect a shut-down tier
            if (key in self._index or key in self._pending
                    or key in self._inflight):
                return
            self.inserts += 1
            if self._writer is not None and not self._closed:
                if self._pending_bytes + len(value) > self._queue_cap:
                    self.insert_dropped += 1  # backpressure: drop, a cache
                    return
                self._pending[key] = value
                self._pending_bytes += len(value)
                self._wake.notify()
                return
        self._write_record(key, value)

    def _encode(self, key: bytes, value: bytes):
        """(record_bytes, payload_offset_in_record, plen, flags)."""
        payload = value
        flags = 0
        if self._compress and len(value) >= 64:
            from toplingdb_tpu.utils import codecs

            c = codecs.snappy_compress(value)
            if len(c) < len(value):
                payload = c
                flags = _F_SNAPPY
        rec = bytearray()
        rec += coding.encode_varint32(len(key))
        rec += coding.encode_varint32(len(payload))
        rec.append(flags)
        rec += key
        poff = len(rec)
        rec += payload
        rec += coding.encode_fixed32(crc32c.mask(crc32c.value(payload)))
        return bytes(rec), poff, len(payload), flags

    def _write_record(self, key: bytes, value: bytes) -> None:
        rec, poff, plen, flags = self._encode(key, value)
        with self._mu:
            if self._closed and self._cur_f is None:
                # Fully shut down (close() already closed the data file):
                # appending would silently roll a FRESH cache file. The
                # `_cur_f is not None` window keeps close()'s own final
                # flush of queued inserts working.
                return
            self._append_locked(key, rec, poff, plen, flags)

    def _append_locked(self, key, rec, poff, plen, flags) -> None:
        if self._cur_f is None or \
                self._sizes.get(self._cur, 0) >= self._file_size:
            self._roll_locked()
        base = self._sizes[self._cur]
        self._cur_f.write(rec)
        self._cur_f.flush()
        self._index[key] = (self._cur, base + poff, plen, flags)
        self._sizes[self._cur] = base + len(rec)
        self.bytes_written += len(rec)
        self._tick += 1
        self._atime[self._cur] = self._tick
        self._enforce_capacity()

    def _writeback_loop(self) -> None:
        while True:
            with self._mu:
                while not self._pending and not self._closed:
                    self._wake.wait(timeout=0.5)
                if self._closed and not self._pending:
                    return
                # Move the batch to _inflight so it stays VISIBLE to
                # lookups, flush() waits for it, and erase() can veto an
                # entry while we encode outside the lock.
                batch = list(self._pending.items())
                self._inflight.update(self._pending)
                self._pending.clear()
                self._pending_bytes = 0
            # Encode/compress OUTSIDE the lock; append under it.
            encoded = [(k, self._encode(k, v)) for k, v in batch]
            with self._mu:
                for k, (rec, poff, plen, flags) in encoded:
                    # An erase() during encoding removed the key from
                    # _inflight — appending it anyway would resurrect a
                    # deleted block.
                    if k in self._inflight and k not in self._index:
                        self._append_locked(k, rec, poff, plen, flags)
                    self._inflight.pop(k, None)
                self._wake.notify_all()  # flush() waiters

    def _roll_locked(self) -> None:
        if self._cur_f is not None:
            self._cur_f.close()
        num = (self._files[-1] + 1) if self._files else 0
        self._cur = num
        self._files.append(num)
        self._sizes[num] = 0
        self._atime[num] = self._tick
        self._cur_f = open(self._fname(num), "ab")

    def _enforce_capacity(self) -> None:
        while sum(self._sizes.values()) > self._cap and len(self._files) > 1:
            # Least-recently-ACCESSED file goes first (never the one being
            # written); lookups bump their file's atime.
            victims = [f for f in self._files if f != self._cur]
            if not victims:
                break
            old = min(victims, key=lambda f: self._atime.get(f, 0))
            self._files.remove(old)
            self._index = {
                k: loc for k, loc in self._index.items() if loc[0] != old
            }
            self._sizes.pop(old, None)
            self._atime.pop(old, None)
            try:
                os.remove(self._fname(old))
            except OSError:
                pass

    def erase(self, key: bytes) -> None:
        """Drop the index entry (the record's bytes are reclaimed when its
        file ages out — file-granularity storage, key-granularity delete)."""
        with self._mu:
            self._index.pop(key, None)
            self._inflight.pop(key, None)  # vetoes an in-flight append
            if key in self._pending:
                self._pending_bytes -= len(self._pending.pop(key))

    def flush(self) -> None:
        """Drain the write-behind queue INCLUDING the in-flight batch
        (tests / clean shutdown)."""
        import time as _t

        while True:
            with self._mu:
                if not self._pending and not self._inflight:
                    return
                if self._writer is None or not self._writer.is_alive():
                    batch = list(self._pending.items()) + \
                        list(self._inflight.items())
                    self._pending.clear()
                    self._inflight.clear()
                    self._pending_bytes = 0
                else:
                    batch = None
                    self._wake.notify_all()
            if batch is not None:
                for k, v in batch:
                    self._write_record(k, v)
                return
            _t.sleep(0.005)

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._wake.notify_all()
        if self._writer is not None:
            self._writer.join(timeout=5)
        self.flush()
        with self._mu:
            if self._cur_f is not None:
                self._cur_f.close()
                self._cur_f = None

    def prune(self) -> int:
        """Drop every sealed cache file (disk-pressure reclaim: everything
        here is a clean copy of a store object, so dropping costs only
        refetch latency). Returns bytes freed. The file being written
        stays — its writer handle is live."""
        freed = 0
        with self._mu:
            victims = [f for f in self._files if f != self._cur]
            for old in victims:
                self._files.remove(old)
                self._index = {
                    k: loc for k, loc in self._index.items()
                    if loc[0] != old
                }
                freed += self._sizes.pop(old, 0)
                self._atime.pop(old, None)
                try:
                    os.remove(self._fname(old))
                except OSError:
                    pass
        return freed

    def usage(self) -> int:
        with self._mu:
            return sum(self._sizes.values())

    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        """Counter snapshot (reference PersistentCache::Stats / the
        block_cache_tier stats surface)."""
        with self._mu:
            return {
                "hits": self.hits, "misses": self.misses,
                "hit_rate": round(self.hit_rate(), 4),
                "bytes_written": self.bytes_written,
                "bytes_read": self.bytes_read,
                "inserts": self.inserts,
                "insert_dropped": self.insert_dropped,
                "files": len(self._files),
                "usage": sum(self._sizes.values()),
                "pending_bytes": self._pending_bytes,
                "compressed": self._compress,
            }
