"""Declarative SLOs with multi-window burn-rate alerting.

The health plane's decision layer: each `SLOSpec` names an objective
("99.9% of Gets under 2ms", "stall fraction under 1%", "replication lag
under 500ms") and the engine evaluates the *bad-event fraction* over two
trailing windows — a fast window that reacts within seconds and a slow
window that filters blips. An alert fires only when BOTH windows burn
error budget faster than their thresholds (the SRE multiwindow
multi-burn-rate pattern), and resolves when the fast window recovers.

Bad-event counts are derived from cumulative, monotone measures
(histogram buckets above the threshold; ticker sums; the stall-micros
counter), so a window is just a difference of two snapshots — the engine
keeps a small time-bounded ring of them and never needs the histograms'
ring to span the slow window.

Alerts surface four ways: the `on_slo_alert` EventListener callback, the
SLO_* ticker family, `/slo/<name>` JSON, and burn-rate gauges on
`/metrics`. Per-shard health scores (health_score) fold the SLO verdict
together with stall state, breaker state, and replication lag into the
green/degraded/unhealthy rubric ShardRouter.status() reports.
"""

from __future__ import annotations

import threading

from toplingdb_tpu.utils import concurrency as ccy
import time
from toplingdb_tpu.utils import errors as _errors
from dataclasses import asdict, dataclass, field

from . import statistics as _st
from .listener import SLOAlertInfo, notify

# The closed set of spec kinds; tools/check_telemetry.py lints literal
# SLOSpec(kind=...) arguments against it.
KINDS = ("latency", "fraction", "stall", "replication_lag",
         "disk_pressure")

HEALTH_GREEN = "green"
HEALTH_DEGRADED = "degraded"
HEALTH_UNHEALTHY = "unhealthy"
_HEALTH_RANK = {HEALTH_GREEN: 0, HEALTH_DEGRADED: 1, HEALTH_UNHEALTHY: 2}


@dataclass
class SLOSpec:
    """One objective. `objective` is the good-event target (0.999 =
    99.9%); the error budget is 1-objective and burn rate 1.0 means
    "spending budget exactly at the sustainable rate"."""

    name: str
    kind: str = "latency"
    objective: float = 0.99
    # latency / replication_lag: the histogram sampled and the
    # threshold above which a sample is a bad event.
    histogram: str = _st.DB_GET_MICROS
    threshold_usec: float = 10_000.0
    # fraction: bad/total ticker families (sums of each tuple).
    bad_tickers: tuple = ()
    total_tickers: tuple = ()
    # Windows; None inherits the engine default (fast) / 5x fast (slow).
    window_fast_sec: float | None = None
    window_slow_sec: float | None = None
    # Burn-rate thresholds (Google SRE workbook's page-tier defaults:
    # a fast window burning >= `burn_fast` x budget AND the slow window
    # confirming at >= `burn_slow` x).
    burn_fast: float = 6.0
    burn_slow: float = 1.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown SLO kind {self.kind!r}; one of {KINDS}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError("objective must be in (0, 1)")
        if self.kind == "replication_lag":
            # Sugar: a latency objective over the ship->apply lag series.
            self.histogram = _st.REPLICATION_LAG_MICROS
        if self.kind == "disk_pressure":
            # Sugar: a fraction objective over the free-space poller —
            # bad events are passes that landed at amber/red, so
            # "objective=0.99" reads "99% of polls see a healthy disk".
            self.bad_tickers = (_st.DISK_PRESSURE_POLLS_BAD,)
            self.total_tickers = (_st.DISK_PRESSURE_POLLS,)
        if self.kind == "fraction" and (not self.bad_tickers
                                        or not self.total_tickers):
            raise ValueError(
                "fraction SLO needs bad_tickers and total_tickers "
                "(total = the full event denominator)")


def _as_spec(s) -> SLOSpec:
    if isinstance(s, SLOSpec):
        return s
    d = dict(s)
    for k in ("bad_tickers", "total_tickers"):
        if k in d and isinstance(d[k], list):
            d[k] = tuple(d[k])
    return SLOSpec(**d)


@dataclass
class _SpecState:
    firing: bool = False
    since: float | None = None      # wall ts of the firing transition
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    value: float = 0.0              # bad fraction over the fast window
    last_alert: dict | None = None


class SLOEngine:
    """Evaluates a set of SLOSpecs against one Statistics instance.

    evaluate() is cheap (a few dict lookups + one 64-bucket scan per
    latency spec) and safe to call from any thread; start(period) runs
    it on a daemon thread. Tests drive evaluate(now=...) with synthetic
    clocks."""

    def __init__(self, statistics, specs, db=None, db_name: str = "",
                 listeners=(), default_window_sec: float = 60.0,
                 clock=None):
        self._stats = statistics
        self.specs = [_as_spec(s) for s in (specs or ())]
        names = [s.name for s in self.specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO spec names: {names}")
        self._db = db
        self.db_name = db_name
        self._listeners = list(listeners or ())
        self._default_fast = float(default_window_sec) or 60.0
        self._clock = clock if clock is not None else time.time
        self._mu = ccy.Lock("slo.SLOEngine._mu")
        # Ring of (ts, {spec_name: (bad, total)}) cumulative measures.
        self._ring: list[tuple[float, dict[str, tuple[float, float]]]] = []
        self._state: dict[str, _SpecState] = {
            s.name: _SpecState() for s in self.specs}
        self._stop_ev = threading.Event()
        self._thread: threading.Thread | None = None
        self._max_slow = max(
            [self._slow_sec(s) for s in self.specs] or [self._default_fast])

    # -- window plumbing -------------------------------------------------

    def _fast_sec(self, spec: SLOSpec) -> float:
        return float(spec.window_fast_sec or self._default_fast)

    def _slow_sec(self, spec: SLOSpec) -> float:
        return float(spec.window_slow_sec or 5 * self._fast_sec(spec))

    def _measure(self, spec: SLOSpec) -> tuple[float, float]:
        """Cumulative (bad, total) for one spec — both monotone, so any
        window is a difference of two of these."""
        if spec.kind in ("latency", "replication_lag"):
            h = self._stats.get_histogram(spec.histogram)
            return h.fraction_above(spec.threshold_usec) * h.count, h.count
        if spec.kind == "stall":
            # total is wall time; filled in per-window at delta time.
            return float(self._stats.get_ticker_count(_st.STALL_MICROS)), 0.0
        bad = sum(self._stats.get_ticker_count(t) for t in spec.bad_tickers)
        tot = sum(self._stats.get_ticker_count(t) for t in spec.total_tickers)
        return float(bad), float(tot)

    def _ref(self, now: float, window: float):
        """Most recent ring sample at least `window` old (so the delta
        covers >= window); the oldest sample while history is short —
        this is what lets an induced stall fire within a few evaluation
        periods instead of waiting out the slow window."""
        ref = None
        for ts, m in self._ring:
            if ts <= now - window:
                ref = (ts, m)
            else:
                break
        if ref is None and self._ring:
            ref = self._ring[0]
        return ref

    def _bad_fraction(self, spec: SLOSpec, now: float,
                      cur: tuple[float, float], window: float) -> float:
        ref = self._ref(now, window)
        if ref is None:
            return 0.0
        ts0, m0 = ref
        b0, t0 = m0.get(spec.name, (0.0, 0.0))
        db = max(0.0, cur[0] - b0)
        if spec.kind == "stall":
            wall_us = max(1.0, (now - ts0) * 1e6)
            return min(1.0, db / wall_us)
        dt = cur[1] - t0
        if dt <= 0:
            return 0.0
        return min(1.0, db / dt)

    # -- the evaluation pass ---------------------------------------------

    def evaluate(self, now: float | None = None) -> dict:
        """One pass: snapshot measures, compute burn rates, transition
        alerts. Returns the status() dict."""
        now = self._clock() if now is None else now
        measures = {s.name: self._measure(s) for s in self.specs}
        alerts: list[SLOAlertInfo] = []
        with self._mu:
            burst = 0
            for spec in self.specs:
                st = self._state[spec.name]
                budget = max(1e-9, 1.0 - spec.objective)
                fast = self._bad_fraction(
                    spec, now, measures[spec.name], self._fast_sec(spec))
                slow = self._bad_fraction(
                    spec, now, measures[spec.name], self._slow_sec(spec))
                st.burn_fast = fast / budget
                st.burn_slow = slow / budget
                st.value = fast
                breached = (st.burn_fast >= spec.burn_fast
                            and st.burn_slow >= spec.burn_slow)
                if breached:
                    burst += 1
                if breached and not st.firing:
                    st.firing, st.since = True, now
                    alerts.append(self._info(spec, st, "firing"))
                elif st.firing and st.burn_fast < spec.burn_fast:
                    st.firing, st.since = False, None
                    alerts.append(self._info(spec, st, "resolved"))
                if alerts and alerts[-1].slo_name == spec.name:
                    st.last_alert = asdict(alerts[-1])
            self._ring.append((now, measures))
            cutoff = now - self._max_slow * 2
            while len(self._ring) > 2 and self._ring[0][0] < cutoff:
                self._ring.pop(0)
        if self._stats is not None:
            self._stats.record_tick(_st.SLO_EVALUATIONS)
            if burst:
                self._stats.record_tick(_st.SLO_WINDOWS_BREACHED, burst)
            for a in alerts:
                self._stats.record_tick(
                    _st.SLO_ALERTS_FIRED if a.state == "firing"
                    else _st.SLO_ALERTS_RESOLVED)
        for a in alerts:
            notify(self._listeners, "on_slo_alert", self._db, a)
        return self.status()

    def _info(self, spec: SLOSpec, st: _SpecState,
              state: str) -> SLOAlertInfo:
        return SLOAlertInfo(
            db_name=self.db_name, slo_name=spec.name, kind=spec.kind,
            state=state, burn_rate_fast=st.burn_fast,
            burn_rate_slow=st.burn_slow, value=st.value,
            objective=spec.objective,
            window_fast_sec=self._fast_sec(spec),
            window_slow_sec=self._slow_sec(spec))

    # -- reporting -------------------------------------------------------

    def status(self) -> dict:
        with self._mu:
            specs = {}
            for spec in self.specs:
                st = self._state[spec.name]
                specs[spec.name] = {
                    "kind": spec.kind,
                    "objective": spec.objective,
                    "burn_rate_fast": round(st.burn_fast, 4),
                    "burn_rate_slow": round(st.burn_slow, 4),
                    "bad_fraction_fast": round(st.value, 6),
                    "firing": st.firing,
                    "since": st.since,
                    "window_fast_sec": self._fast_sec(spec),
                    "window_slow_sec": self._slow_sec(spec),
                    "last_alert": st.last_alert,
                }
        return {"health": self._health_locked(specs), "specs": specs}

    @staticmethod
    def _health_locked(specs: dict) -> str:
        if any(r["firing"] for r in specs.values()):
            return HEALTH_UNHEALTHY
        if any(r["burn_rate_fast"] >= 1.0 for r in specs.values()):
            return HEALTH_DEGRADED
        return HEALTH_GREEN

    def health(self) -> str:
        return self.status()["health"]

    def last_alerts(self) -> dict:
        """{spec_name: last alert dict} for specs that ever alerted."""
        with self._mu:
            return {n: dict(s.last_alert) for n, s in self._state.items()
                    if s.last_alert}

    # -- background thread -----------------------------------------------

    def start(self, period_sec: float) -> None:
        if self._thread is not None:
            return
        self._stop_ev.clear()

        def _run():
            while not self._stop_ev.wait(period_sec):
                try:
                    self.evaluate()
                except Exception as e:
                    # an evaluation bug must not kill the sampler
                    _errors.swallow(reason="slo-eval-retry", exc=e)

        self._thread = ccy.spawn("slo-eval", _run, owner=self,
                                 stop=self.stop)

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_ev.set()
        self._thread.join(timeout=2.0)
        self._thread = None


def health_score(stall_state: str | None = None,
                 slo_health: str = HEALTH_GREEN,
                 breakers_open: int = 0,
                 lag_exceeded: bool = False) -> str:
    """The shard-health rubric: fold stall state (db.write_stall_state),
    the SLO verdict, replica breaker state, and a lag flag into one
    green/degraded/unhealthy score (worst input wins)."""
    score = _HEALTH_RANK.get(slo_health, 0)
    if stall_state == "stopped":
        score = max(score, 2)
    elif stall_state == "delayed":
        score = max(score, 1)
    if breakers_open > 0 or lag_exceeded:
        score = max(score, 1)
    for name, rank in _HEALTH_RANK.items():
        if rank == score:
            return name
    return HEALTH_GREEN


def health_num(health: str) -> int:
    """Gauge encoding: green=0 degraded=1 unhealthy=2."""
    return _HEALTH_RANK.get(health, 0)


def health_doc(db, name: str, role: str = "primary") -> dict:
    """The aggregator wire format: one JSON-portable document carrying a
    member's identity, health verdict, stall state, SLO rows, mergeable
    histograms (cumulative + recent window), and tickers. Every fleet
    member endpoint (/health/<name>, /replication/health) serves this;
    tools/fleet_health.py merges them."""
    stats = getattr(db, "stats", None)
    engine = getattr(db, "slo_engine", None)
    slo = engine.status() if engine is not None else None
    stall = None
    ws = getattr(db, "write_stall_state", None)
    if callable(ws):
        stall = ws()
    stall_state = (stall or {}).get("state") if isinstance(stall, dict) \
        else stall
    doc = {
        "name": name,
        "role": role,
        "health": health_score(
            stall_state=stall_state,
            slo_health=(slo or {}).get("health", HEALTH_GREEN)),
        "stall": stall,
        "slo": slo,
        "histograms": {},
        "tickers": {},
        "last_sequence": getattr(
            getattr(db, "versions", None), "last_sequence", None),
    }
    if stats is not None:
        doc["tickers"] = stats.tickers()
        with stats._lock:
            hists = [(k, h) for k, h in stats._histograms.items() if h.count]
        for k, h in hists:
            row = {"cumulative": h.to_dict()}
            if isinstance(h, _st.WindowedHistogram):
                row["recent"] = h.windowed().to_dict()
                row["window_sec"] = h.window_sec
            doc["histograms"][k] = row
    return doc
