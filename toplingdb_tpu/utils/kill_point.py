"""Whitebox crash points — the reference's TEST_KILL_RANDOM mechanism
(util/kill_point? no: test_kill_random in /root/reference's
test_util/sync_point.h + db_crashtest.py whitebox mode, e.g.
version_set.cc:5769): named markers inside durability-critical code
self-kill the process with env-seeded probability, so the crash-recovery
matrix covers the exact windows between WAL append, memtable publish, SST
write and MANIFEST install.

Environment:
  TPULSM_KILL_ODDS    fire with probability 1/odds per marker (unset/0 = off)
  TPULSM_KILL_SEED    RNG seed (default: nondeterministic)
  TPULSM_KILL_PREFIX  comma-separated marker-name prefixes to arm (default:
                      all markers)

A fired marker exits with status 137 (the kill -9 status the blackbox
crash loop already expects), skipping all atexit/flush handlers — a real
crash, not a clean shutdown.
"""

from __future__ import annotations

import os
import random

KILLED_EXIT_CODE = 137

_state: tuple | None = None  # (odds, rng, prefixes)


def _load() -> tuple:
    global _state
    spec = os.environ.get("TPULSM_KILL_ODDS", "")
    try:
        odds = int(spec) if spec else 0
    except ValueError:
        odds = 0
    seed_spec = os.environ.get("TPULSM_KILL_SEED", "")
    rng = random.Random(int(seed_spec)) if seed_spec else random.Random()
    prefixes = tuple(
        p for p in os.environ.get("TPULSM_KILL_PREFIX", "").split(",") if p
    )
    _state = (odds, rng, prefixes)
    return _state


def test_kill_random(name: str) -> None:
    """Marker: maybe die here. Negligible when unarmed (one tuple check)."""
    st = _state if _state is not None else _load()
    odds, rng, prefixes = st
    if not odds:
        return
    if prefixes and not any(name.startswith(p) for p in prefixes):
        return
    if rng.randrange(odds) == 0:
        os._exit(KILLED_EXIT_CODE)


def reset_for_tests() -> None:
    """Re-read the environment (tests flip env vars mid-process)."""
    global _state
    _state = None
