"""CRC32C (Castagnoli) with the reference's masking scheme.

Semantics match reference util/crc32c.h: `value`/`extend`, plus `mask`/
`unmask` — CRCs stored inside CRC-protected payloads (WAL records, block
trailers) are rotated and offset so that computing the CRC of a string
containing embedded CRCs is well-behaved.

Hot path is the native C++ slicing-by-8 implementation
(toplingdb_tpu/native/tpulsm_native.cc); a table-driven Python fallback keeps
the package importable without a toolchain.
"""

from __future__ import annotations

from toplingdb_tpu import native

_MASK_DELTA = 0xA282EAD8

_POLY = 0x82F63B78
_py_table: list[int] | None = None


def _table() -> list[int]:
    global _py_table
    if _py_table is None:
        t = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (_POLY ^ (c >> 1)) if (c & 1) else (c >> 1)
            t.append(c)
        _py_table = t
    return _py_table


def extend(crc: int, data: bytes) -> int:
    l = native.lib()
    if l is not None:
        return l.tpulsm_crc32c_extend(crc & 0xFFFFFFFF, bytes(data), len(data))
    t = _table()
    c = (crc ^ 0xFFFFFFFF) & 0xFFFFFFFF
    for b in data:
        c = t[(c ^ b) & 0xFF] ^ (c >> 8)
    return (c ^ 0xFFFFFFFF) & 0xFFFFFFFF


def value(data: bytes) -> int:
    return extend(0, data)


def mask(crc: int) -> int:
    """Rotate right by 15 bits and add a constant (reference util/crc32c.h:46)."""
    crc &= 0xFFFFFFFF
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


def unmask(masked: int) -> int:
    rot = (masked - _MASK_DELTA) & 0xFFFFFFFF
    return ((rot >> 17) | (rot << 15)) & 0xFFFFFFFF


def xxh64(data: bytes, seed: int = 0) -> int:
    """xxHash64 of `data` (bloom probes, general hashing)."""
    l = native.lib()
    if l is not None:
        return l.tpulsm_xxh64(bytes(data), len(data), seed)
    # Pure-Python xxh64 fallback (from the public spec).
    P1 = 11400714785074694791
    P2 = 14029467366897019727
    P3 = 1609587929392839161
    P4 = 9650029242287828579
    P5 = 2870177450012600261
    M = 0xFFFFFFFFFFFFFFFF

    def rotl(x, r):
        return ((x << r) | (x >> (64 - r))) & M

    def rnd(acc, inp):
        acc = (acc + inp * P2) & M
        return (rotl(acc, 31) * P1) & M

    n = len(data)
    p = 0
    if n >= 32:
        v1, v2, v3, v4 = (seed + P1 + P2) & M, (seed + P2) & M, seed & M, (seed - P1) & M
        while p + 32 <= n:
            v1 = rnd(v1, int.from_bytes(data[p : p + 8], "little")); p += 8
            v2 = rnd(v2, int.from_bytes(data[p : p + 8], "little")); p += 8
            v3 = rnd(v3, int.from_bytes(data[p : p + 8], "little")); p += 8
            v4 = rnd(v4, int.from_bytes(data[p : p + 8], "little")); p += 8
        h = (rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18)) & M
        for v in (v1, v2, v3, v4):
            h = ((h ^ rnd(0, v)) * P1 + P4) & M
    else:
        h = (seed + P5) & M
    h = (h + n) & M
    while p + 8 <= n:
        h = ((rotl(h ^ rnd(0, int.from_bytes(data[p : p + 8], "little")), 27)) * P1 + P4) & M
        p += 8
    if p + 4 <= n:
        h = ((rotl(h ^ (int.from_bytes(data[p : p + 4], "little") * P1) & M, 23)) * P2 + P3) & M
        p += 4
    while p < n:
        h = (rotl(h ^ (data[p] * P5) & M, 11) * P1) & M
        p += 1
    h ^= h >> 33
    h = (h * P2) & M
    h ^= h >> 29
    h = (h * P3) & M
    h ^= h >> 32
    return h
