"""Per-entry protection info (the integrity plane's write-path half).

Role of the reference's `protection_bytes_per_key` / ProtectionInfo
(db/kv_checksum.h in /root/reference): every key/value gets a small
checksum the moment it enters a WriteBatch, carried alongside the entry
through the memtable and re-verified at each handoff — batch -> memtable
insert, memtable -> flush emission, compaction output emission, and
scan-plane chunk emission. Block CRCs protect bytes AT REST; protection
info protects them IN FLIGHT across the native/device hops where a buggy
kernel or bit flip could otherwise alter user bytes silently.

Like the reference, the checksum is XOR-composable from independently
hashed components (key, value, op type, column family), so a component
can be swapped without re-hashing the rest — `strip_cf` derives the
CF-free form the (per-CF) memtable stores from the CF-tagged form the
WriteBatch carries.

Hot path: this runs TWICE per record on every protected write (compute
at WriteBatch.add, re-verify at memtable insert), so the component hash
is zlib.crc32 (a builtin: no ctypes crossing) followed by ONE
multiply-xorshift lane mix — enough avalanche that even the 1-byte
truncation misses a flip only at the ideal 1/256 rate, at ~1.4us/call.
The hash is internal to the process (never persisted), so it owes no
format compatibility to anything.
"""

from __future__ import annotations

import zlib

_M64 = (1 << 64) - 1

# Domain-separation constants per component (arbitrary odd 64-bit).
_K_KEY = 0x9E3779B97F4A7C15
_K_VAL = 0xC2B2AE3D27D4EB4F
_K_TYPE = 0x165667B19E3779F9
_K_CF = 0x27D4EB2F165667C5

_crc = zlib.crc32


def _mix(h: int) -> int:
    """One multiply + xorshift: spreads the crc into all 8 lanes (the
    shift folds high bits down so low-byte truncation still sees them)."""
    h = (h * 0xBF58476D1CE4E5B9) & _M64
    return h ^ (h >> 29)


# Type terms are a tiny closed set (ValueType.MAX = 0x7F); CF terms
# memoize on demand.
_TYPE_TERMS = [_mix(_K_TYPE ^ t) for t in range(256)]
_CF_TERMS = {0: _mix(_K_CF ^ 1)}


def _cf_term(cf: int) -> int:
    t = _CF_TERMS.get(cf)
    if t is None:
        t = _CF_TERMS[cf] = _mix(_K_CF ^ (cf + 1))
    return t


def protect_entry(t: int, key: bytes, value: bytes | None, cf: int = 0) -> int:
    """Full (64-bit, untruncated) protection of one record. XOR of the
    four component hashes — see strip_cf."""
    v = value if value is not None else b""
    ct = _CF_TERMS.get(cf)
    if ct is None:
        ct = _cf_term(cf)
    return (
        _mix(_K_KEY ^ _crc(key) ^ (len(key) << 32))
        ^ _mix(_K_VAL ^ _crc(v) ^ (len(v) << 32))
        ^ _TYPE_TERMS[t]
        ^ ct
    )


def strip_cf(full: int, cf: int) -> int:
    """Swap the CF component for CF 0 (what a per-CF memtable stores:
    the memtable IS the column family, so the tag is redundant there)."""
    if cf == 0:
        return full
    return full ^ _cf_term(cf) ^ _CF_TERMS[0]


def kv_checksum(key: bytes, value: bytes) -> int:
    """Type/CF-free checksum of a (key, value) pair — the data-plane
    handoff form (scan-plane chunk emission banking)."""
    return (_mix(_K_KEY ^ _crc(key) ^ (len(key) << 32))
            ^ _mix(_K_VAL ^ _crc(value) ^ (len(value) << 32)))


def truncate(cs: int, nbytes: int) -> int:
    """Keep the low `nbytes` bytes (8/4/2/1, reference semantics)."""
    if nbytes >= 8:
        return cs & _M64
    return cs & ((1 << (8 * nbytes)) - 1)


VALID_PROTECTION_BYTES = (0, 1, 2, 4, 8)


def check_protection_bytes(n: int) -> None:
    if n not in VALID_PROTECTION_BYTES:
        from toplingdb_tpu.utils.status import InvalidArgument

        raise InvalidArgument(
            f"protection_bytes_per_key must be one of "
            f"{VALID_PROTECTION_BYTES}, got {n!r}"
        )
