"""Request-scoped span tracing: the telemetry plane's core.

A low-overhead tracer in the dapper/opentelemetry shape, scoped to what the
engine needs:

  Tracer      per-process (one per DB; one per dcompact worker / follower)
              span factory with 1-in-N root sampling, an always-sample
              latency backstop (ops slower than `slow_usec` leave at least
              a root span even when the sampling die missed them), and a
              bounded ring of finished traces.
  Span        one timed region. Monotonic-clock durations; wall-clock only
              at the trace root (for display). Spans form a tree via
              parent_id and serialize to plain dicts so they can cross
              process boundaries in results.json / replication pulls.
  propagation inject() exports the current (trace_id, span_id, sampled)
              context; a remote process adopts it with start_from() and
              returns its finished spans, which attach_remote() stitches
              back into the originating trace — dcompact workers and
              replication followers both ride this.

Hot-path cost discipline: the root-sampling check is inlined at call sites
(`tr.sample_every and next(tr.counter) % tr.sample_every == 0` — one
attribute read, one C-level count, one mod); everything heavier runs only
on the sampled 1-in-N. Child-span helpers no-op from a ~single dict lookup
when the current thread carries no sampled trace.

Chrome trace-event JSON export (`chrome_trace`) renders in chrome://tracing
or Perfetto; the SidePluginRepo serves it at /traces/<db>/<trace_id>.
"""

from __future__ import annotations

import itertools
import os
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time
from toplingdb_tpu.utils import errors as _errors
from collections import OrderedDict, deque

_tls = threading.local()


class Span:
    """One timed region of one trace. `start_us` is the offset from the
    trace root's start (µs); `dur_us` is filled at finish."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start_us",
                 "dur_us", "proc", "tags", "_t0", "_trace", "_tracer")

    def __init__(self, name, trace_id, span_id, parent_id, proc, tags):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.proc = proc
        self.tags = tags
        self.start_us = 0
        self.dur_us = 0
        self._t0 = 0.0
        self._trace = None
        self._tracer = None

    def tag(self, **kw) -> "Span":
        self.tags.update(kw)
        return self

    def finish(self) -> None:
        tr = self._tracer
        if tr is not None:
            tr._finish_span(self)

    # Context-manager protocol: `with tracer.span(...)` / module span().
    def __enter__(self):
        return self

    def __exit__(self, et, ev, tb):
        if et is not None:
            self.tags["error"] = repr(ev)[:200]
        self.finish()
        return False

    def to_dict(self) -> dict:
        return {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "start_us": self.start_us, "dur_us": self.dur_us,
            "proc": self.proc, "tags": self.tags,
        }

    @staticmethod
    def from_dict(d: dict) -> "Span":
        s = Span(d.get("name", "?"), d.get("trace_id", ""),
                 d.get("span_id", 0), d.get("parent_id", 0),
                 d.get("proc", "remote"), dict(d.get("tags") or {}))
        s.start_us = int(d.get("start_us", 0))
        s.dur_us = int(d.get("dur_us", 0))
        return s


class _NoopSpan:
    """Shared do-nothing span: returned when no sampled trace is active so
    instrumentation sites never branch."""

    __slots__ = ()

    def tag(self, **kw):
        return self

    def finish(self):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


NOOP_SPAN = _NoopSpan()


class Trace:
    """One finished (or in-flight) trace: the root span plus every local
    and stitched-remote child."""

    __slots__ = ("trace_id", "root", "spans", "slow", "start_unix_us",
                 "_mono0")

    def __init__(self, trace_id, root, start_unix_us, mono0):
        self.trace_id = trace_id
        self.root = root
        self.spans = [root]
        self.slow = False
        self.start_unix_us = start_unix_us
        self._mono0 = mono0

    @property
    def name(self) -> str:
        return self.root.name

    @property
    def dur_us(self) -> int:
        return self.root.dur_us

    def summary(self) -> dict:
        return {
            "trace_id": self.trace_id, "name": self.root.name,
            "start_unix_us": self.start_unix_us, "dur_us": self.root.dur_us,
            "slow": self.slow, "n_spans": len(self.spans),
            "procs": sorted({s.proc for s in self.spans}),
            "tags": self.root.tags,
        }


class Tracer:
    """Span factory + finished-trace ring for one process role.

    sample_every  N: roots created by maybe_sample() fire 1-in-N (0 = off).
                  Forced roots (start()) ignore sampling — used for rare,
                  high-value ops (flush, compaction).
    slow_usec     ops slower than this always leave a (root-only) trace
                  via note_slow(), even when unsampled. 0 = off.
    ring          bound on retained finished traces (and the trace_id
                  index and the seq→context map: nothing here grows with
                  uptime).
    """

    def __init__(self, sample_every: int = 0, slow_usec: int = 0,
                 ring: int = 256, proc: str = "db"):
        self.sample_every = max(0, int(sample_every))
        self.slow_usec = max(0, int(slow_usec))
        self.proc = proc
        self.counter = itertools.count(1)
        self._span_ids = itertools.count(1)
        # Trace ids: one urandom read per TRACER, then a counter — an
        # os.urandom syscall per trace was the bulk of a sampled op's
        # cost.
        self._tid_base = os.urandom(6).hex()
        self._tid_n = itertools.count(1)
        self._mu = ccy.Lock("telemetry.Tracer._mu")
        self._ring: deque[Trace] = deque(maxlen=max(1, int(ring)))
        self._by_id: dict[str, Trace] = {}
        self._active: dict[str, Trace] = {}
        # seq → trace context of recent sampled writes (replication
        # propagation); bounded independently of the ring.
        self._seq_ctx: OrderedDict[int, dict] = OrderedDict()
        self._seq_cap = 1024
        self.traces_started = 0
        self.traces_dropped = 0  # remote spans whose trace was evicted

    @property
    def enabled(self) -> bool:
        return self.sample_every > 0 or self.slow_usec > 0

    # -- root spans ----------------------------------------------------

    def maybe_sample(self, name: str, **tags) -> Span | None:
        """1-in-N root decision + creation; None when the die missed.
        Hot call sites inline the check via `tr.sample_every` and
        `tr.counter` instead and call start() only on the hit."""
        if self.sample_every and next(self.counter) % self.sample_every == 0:
            return self.start(name, **tags)
        return None

    def _new_tid(self) -> str:
        return f"{self._tid_base}{next(self._tid_n):06x}"

    def start(self, name: str, **tags) -> Span:
        """Forced root span (no sampling): flush/compaction-grade ops."""
        return self._root(name, self._new_tid(), 0, tags)

    def start_from(self, ctx: dict | None, name: str, **tags) -> Span:
        """Adopt a propagated context (remote side of a cross-process
        hop): the new root parents under ctx['span_id'] within
        ctx['trace_id']. Falls back to a fresh root when ctx is None."""
        if not ctx or not ctx.get("trace_id"):
            return self.start(name, **tags)
        return self._root(name, str(ctx["trace_id"]),
                          int(ctx.get("span_id", 0)), tags)

    def _root(self, name, trace_id, parent_id, tags) -> Span:
        sp = Span(name, trace_id, next(self._span_ids), parent_id,
                  self.proc, tags)
        now = time.monotonic()
        sp._t0 = now
        tr = Trace(trace_id, sp, int(time.time() * 1e6), now)
        sp._trace = tr
        sp._tracer = self
        # Lock-free registration (dict set/del are GIL-atomic): the lock
        # is reserved for ring retirement, keeping a sampled op cheap.
        self.traces_started += 1
        self._active[trace_id] = tr
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append(sp)
        return sp

    def note_slow(self, name: str, dur_us: float, **tags) -> None:
        """Always-sample backstop: record a root-only trace for an op the
        sampler skipped but whose latency crossed slow_usec."""
        sp = Span(name, self._new_tid(), next(self._span_ids), 0,
                  self.proc, tags)
        sp.dur_us = int(dur_us)
        tr = Trace(sp.trace_id, sp, int(time.time() * 1e6 - dur_us),
                   time.monotonic())
        tr.slow = True
        with self._mu:
            self._retire(tr)

    # -- child spans ---------------------------------------------------

    def _child(self, parent: Span, name: str, tags: dict) -> Span:
        trace = parent._trace
        sp = Span(name, parent.trace_id, next(self._span_ids),
                  parent.span_id, self.proc, tags)
        now = time.monotonic()
        sp._t0 = now
        sp.start_us = int((now - trace._mono0) * 1e6)
        sp._trace = trace
        sp._tracer = self
        trace.spans.append(sp)  # list.append: GIL-atomic
        return sp

    def _finish_span(self, sp: Span) -> None:
        sp.dur_us = int((time.monotonic() - sp._t0) * 1e6)
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is sp:
            stack.pop()
        elif stack is not None:
            try:
                stack.remove(sp)
            except ValueError:
                pass
        trace = sp._trace
        if trace is not None and trace.root is sp:
            if self.slow_usec and sp.dur_us >= self.slow_usec:
                trace.slow = True
            self._active.pop(trace.trace_id, None)
            with self._mu:
                self._retire(trace)

    def _retire(self, trace: Trace) -> None:
        # caller holds _mu
        if len(self._ring) == self._ring.maxlen:
            self._by_id.pop(self._ring[0].trace_id, None)
        self._ring.append(trace)
        self._by_id[trace.trace_id] = trace

    # -- cross-process stitching ---------------------------------------

    def attach_remote(self, spans) -> int:
        """Adopt finished remote span dicts (a dcompact worker's
        results.json, a follower's pull-time ack) into their originating
        traces. Unknown trace ids (ring already evicted) are dropped
        silently — a late ack must never error or leak. Returns the
        number of spans attached."""
        n = 0
        for d in spans or ():
            try:
                sp = Span.from_dict(d)
            except Exception as e:
                _errors.swallow(reason="span-ack-parse", exc=e)
                continue
            with self._mu:
                tr = self._active.get(sp.trace_id) \
                    or self._by_id.get(sp.trace_id)
                if tr is None:
                    self.traces_dropped += 1
                    continue
                tr.spans.append(sp)
                n += 1
        return n

    # -- replication seq → context map ---------------------------------

    def note_seq(self, seq: int, root: Span) -> None:
        """Remember a sampled write's context by its last sequence so WAL
        shipping can propagate it to followers."""
        with self._mu:
            self._seq_ctx[int(seq)] = {
                "seq": int(seq), "trace_id": root.trace_id,
                "span_id": root.span_id, "sampled": 1,
            }
            while len(self._seq_ctx) > self._seq_cap:
                self._seq_ctx.popitem(last=False)

    def ctxs_in_range(self, first_seq: int, last_seq: int) -> list[dict]:
        with self._mu:
            return [c for s, c in self._seq_ctx.items()
                    if first_seq <= s <= last_seq]

    # -- views ----------------------------------------------------------

    def finished(self, slow_only: bool = False, limit: int = 64):
        with self._mu:
            out = [t for t in reversed(self._ring)
                   if t.slow or not slow_only]
        return out[:limit]

    def get_trace(self, trace_id: str) -> Trace | None:
        with self._mu:
            return self._by_id.get(trace_id) or self._active.get(trace_id)

    def export_trace(self, trace_id: str) -> list[dict]:
        """Finished spans of one trace as plain dicts (the remote side's
        half of attach_remote)."""
        tr = self.get_trace(trace_id)
        return [s.to_dict() for s in tr.spans] if tr is not None else []

    def chrome_trace(self, trace_id: str) -> dict | None:
        """Chrome trace-event JSON (chrome://tracing / Perfetto)."""
        tr = self.get_trace(trace_id)
        if tr is None:
            return None
        events = []
        for s in tr.spans:
            events.append({
                "name": s.name, "ph": "X", "ts": s.start_us,
                "dur": max(1, s.dur_us), "pid": s.proc,
                "tid": s.proc, "args": dict(s.tags),
            })
        return {
            "traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {
                "trace_id": tr.trace_id, "slow": tr.slow,
                "start_unix_us": tr.start_unix_us,
            },
        }

    def status(self) -> dict:
        with self._mu:
            return {
                "sample_every": self.sample_every,
                "slow_usec": self.slow_usec,
                "traces_started": self.traces_started,
                "traces_retained": len(self._ring),
                "traces_active": len(self._active),
                "remote_spans_dropped": self.traces_dropped,
                "seq_ctx_entries": len(self._seq_ctx),
            }


# ---------------------------------------------------------------------------
# Module-level helpers: operate on the CALLING THREAD's active span, so
# instrumentation deep in the table/ops layers needs no tracer plumbing.
# ---------------------------------------------------------------------------


def current_span() -> Span | None:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def current_trace_id() -> str | None:
    sp = current_span()
    return sp.trace_id if sp is not None else None


def span(name: str, **tags):
    """Child span under the calling thread's active span; NOOP_SPAN when
    no sampled trace is active here."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return NOOP_SPAN
    parent = stack[-1]
    sp = parent._tracer._child(parent, name, tags)
    stack.append(sp)
    return sp


def span_event(name: str, dur_us, **tags) -> None:
    """Already-measured child span (native interiors, phase timers): no
    enter/exit pair, just the recorded duration attached under the calling
    thread's active span."""
    stack = getattr(_tls, "stack", None)
    if not stack:
        return
    parent = stack[-1]
    # _child pushes nothing onto the tls stack; just close the span out,
    # back-dating its start so the waterfall shows where the time went.
    sp = parent._tracer._child(parent, name, tags)
    sp.start_us = max(0, sp.start_us - int(dur_us))
    sp.dur_us = int(dur_us)


def current_handle():
    """Exportable handle of the calling thread's active span, for stages
    that run in OTHER threads (pipeline workers): pass it along and create
    children with span_under()/span_event_under(). None when untraced."""
    return current_span()


def span_under(parent: Span | None, name: str, **tags):
    """Cross-thread child span under an exported handle (NOT the calling
    thread's tls). NOOP_SPAN when the handle is None."""
    if parent is None:
        return NOOP_SPAN
    return parent._tracer._child(parent, name, tags)


def span_event_under(parent: Span | None, name: str, dur_us,
                     **tags) -> None:
    if parent is None:
        return
    sp = parent._tracer._child(parent, name, tags)
    sp.start_us = max(0, sp.start_us - int(dur_us))
    sp.dur_us = int(dur_us)


def inject() -> dict | None:
    """Export the calling thread's context for a process hop: {"trace_id",
    "span_id", "sampled"}. None when no trace is active (the remote side
    then runs untraced)."""
    sp = current_span()
    if sp is None:
        return None
    return {"trace_id": sp.trace_id, "span_id": sp.span_id, "sampled": 1}


def attach_current(spans) -> int:
    """attach_remote against the calling thread's active tracer."""
    sp = current_span()
    if sp is None or sp._tracer is None:
        return 0
    return sp._tracer.attach_remote(spans)


def tracer_from_options(options, proc: str = "db") -> Tracer | None:
    """The DB-side construction point: None unless a knob turns it on."""
    se = int(getattr(options, "trace_sample_every", 0) or 0)
    su = int(getattr(options, "trace_slow_usec", 0) or 0)
    if se <= 0 and su <= 0:
        return None
    return Tracer(sample_every=se, slow_usec=su,
                  ring=int(getattr(options, "trace_ring", 256) or 256),
                  proc=proc)
