"""RateLimiter + WriteController + WriteBufferManager + SstFileManager —
the flow-control quartet (reference util/rate_limiter.cc,
db/write_controller.cc, memtable/write_buffer_manager.cc,
file/sst_file_manager_impl.cc in /root/reference)."""

from __future__ import annotations

import os
import threading

from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils import errors as _errors
import time


class RateLimiter:
    """Token-bucket byte rate limiter (reference GenericRateLimiter)."""

    def __init__(self, bytes_per_second: int, refill_period_us: int = 100_000):
        self.rate = bytes_per_second
        self._period = refill_period_us / 1e6
        self._available = bytes_per_second * self._period
        self._last_refill = time.monotonic()
        self._mu = ccy.Lock("rate_limiter.RateLimiter._mu")
        self.total_through = 0

    def request(self, n: int) -> None:
        """Blocks until n bytes of budget are available. Oversized requests
        are split into period-sized chunks (reference GenericRateLimiter), so
        a 1MB write against a 100KB/period budget still throttles."""
        budget = max(1, int(self.rate * self._period))
        while n > 0:
            chunk = min(n, budget)
            self._request_chunk(chunk)
            n -= chunk

    def try_request(self, n: int, timeout: float = 0.0) -> bool:
        """Bounded-wait variant of request() for admission control
        (sharding/admission.py): take n units within `timeout` seconds or
        return False taking nothing. Requests larger than one period's
        budget are admitted against the full accumulated budget and carry
        the remainder as debt (available goes negative), so a big batch
        pays its cost by delaying LATER requests instead of blocking the
        caller unboundedly."""
        deadline = time.monotonic() + timeout
        while True:
            with self._mu:
                now = time.monotonic()
                elapsed = now - self._last_refill
                if elapsed >= self._period:
                    self._available = min(
                        self.rate * self._period,
                        self._available + self.rate * elapsed,
                    )
                    self._last_refill = now
                need = min(n, self.rate * self._period)
                if self._available >= need:
                    self._available -= n  # may go negative: debt
                    self.total_through += n
                    return True
                now = time.monotonic()
            if now >= deadline:
                return False
            time.sleep(min(self._period / 4, max(0.0, deadline - now)))

    def _request_chunk(self, n: int) -> None:
        while True:
            with self._mu:
                now = time.monotonic()
                elapsed = now - self._last_refill
                if elapsed >= self._period:
                    self._available = min(
                        self.rate * self._period,
                        self._available + self.rate * elapsed,
                    )
                    self._last_refill = now
                if self._available >= n:
                    self._available -= n
                    self.total_through += n
                    return
            time.sleep(self._period / 4)


class WriteController:
    """Write throttling state: normal / delayed / stopped
    (reference db/write_controller.h). The DB consults it before each write;
    compaction pressure sets delays."""

    def __init__(self):
        self._stopped = False
        self._delay_bytes_per_sec = 0
        self._mu = ccy.Lock("rate_limiter.WriteController._mu")
        self._cv = ccy.Condition(lock=self._mu)
        self.total_stall_micros = 0

    def stop_writes(self) -> None:
        with self._mu:
            self._stopped = True

    def resume_writes(self) -> None:
        with self._cv:
            self._stopped = False
            self._delay_bytes_per_sec = 0
            self._cv.notify_all()

    def set_delay(self, bytes_per_sec: int) -> None:
        with self._mu:
            self._delay_bytes_per_sec = bytes_per_sec

    def wait_if_stalled(self, write_bytes: int, timeout: float = 10.0) -> None:
        t0 = time.monotonic()
        with self._cv:
            while self._stopped and time.monotonic() - t0 < timeout:
                self._cv.wait(0.05)
        if self._delay_bytes_per_sec > 0 and write_bytes > 0:
            delay = write_bytes / self._delay_bytes_per_sec
            time.sleep(min(delay, 1.0))
        stall = time.monotonic() - t0
        if stall > 0.001:
            self.total_stall_micros += int(stall * 1e6)


class WriteBufferManager:
    """DB-wide memtable memory budget (reference write_buffer_manager.h:37):
    when the sum over all DBs exceeds the budget, callers should flush."""

    def __init__(self, buffer_size: int):
        self.buffer_size = buffer_size
        self._usage = 0
        self._mu = ccy.Lock("rate_limiter.WriteBufferManager._mu")

    def reserve(self, n: int) -> None:
        with self._mu:
            self._usage += n

    def free(self, n: int) -> None:
        with self._mu:
            self._usage = max(0, self._usage - n)

    def memory_usage(self) -> int:
        return self._usage

    def should_flush(self) -> bool:
        return self.buffer_size > 0 and self._usage >= self.buffer_size


PRESSURE_LEVELS = ("ok", "amber", "red")


class SstFileManager:
    """Tracks live SST+WAL+blob disk usage per DB root, paces trash
    deletion, and publishes a three-state disk-pressure level (reference
    include/rocksdb/sst_file_manager.h:26, file/delete_scheduler.cc,
    sst_file_manager_impl's free-space poller + SetMaxAllowedSpaceUsage).

    Pressure basis is the tighter of two fractions: remaining budget over
    `max_allowed_space_usage` (when a budget is set) and the Env's real
    free space over (free + tracked). Escalation happens the moment the
    fraction crosses a threshold; de-escalation requires clearing the
    threshold by `pressure_hysteresis` so a level never flaps on noise.
    Callbacks registered with add_pressure_callback fire OUTSIDE _mu."""

    def __init__(self, bytes_per_sec_delete: int = 0,
                 max_trash_db_ratio: float = 0.25,
                 env=None, path: str | None = None,
                 max_allowed_space_usage: int = 0,
                 compaction_buffer_size: int = 0,
                 flush_headroom_bytes: int = 0,
                 free_space_poll_period_sec: float = 0.0,
                 amber_free_ratio: float = 0.10,
                 red_free_ratio: float = 0.05,
                 pressure_hysteresis: float = 0.02,
                 statistics=None):
        self.rate = bytes_per_sec_delete
        self.max_trash_db_ratio = max_trash_db_ratio
        self._env = env
        self._path = path
        self.max_allowed_space_usage = max_allowed_space_usage
        self.compaction_buffer_size = compaction_buffer_size
        self.flush_headroom_bytes = flush_headroom_bytes
        self.poll_period = free_space_poll_period_sec
        self.amber_free_ratio = amber_free_ratio
        self.red_free_ratio = red_free_ratio
        self.pressure_hysteresis = pressure_hysteresis
        self._stats = statistics
        self._tracked: dict[str, int] = {}
        self._trash: dict[str, int] = {}
        self._level = "ok"
        self._callbacks: list = []
        self._mu = ccy.Lock("rate_limiter.SstFileManager._mu")
        self._stop = threading.Event()
        self._wake = threading.Event()  # unpaces sleeping trash deleters
        self._delete_threads: list[threading.Thread] = []
        self._poller: threading.Thread | None = None

    # -- accounting ------------------------------------------------------

    def on_add_file(self, path: str, size: int | None = None) -> None:
        if size is None:
            size = self._probe_size(path)  # env IO stays outside _mu
        with self._mu:
            self._tracked[path] = size

    def on_file_size(self, path: str, size: int) -> None:
        """Update a tracked file's size (growing WALs/blobs)."""
        with self._mu:
            if path in self._tracked:
                self._tracked[path] = size

    def on_delete_file(self, path: str) -> None:
        with self._mu:
            self._tracked.pop(path, None)

    def _probe_size(self, path: str) -> int:
        try:
            if self._env is not None:
                return self._env.get_file_size(path)
            return os.path.getsize(path)
        except Exception as e:
            _errors.swallow(reason="sfm-size-probe", exc=e)
            return 0

    def total_size(self) -> int:
        with self._mu:
            return sum(self._tracked.values())

    def trash_size(self) -> int:
        with self._mu:
            return sum(self._trash.values())

    def free_space(self) -> int:
        if self._env is not None and self._path is not None:
            return self._env.get_free_space(self._path)
        if self._path is not None:
            from toplingdb_tpu.env import default_env
            return default_env().get_free_space(self._path)
        return 1 << 62

    def set_max_allowed_space_usage(self, nbytes: int) -> None:
        with self._mu:
            self.max_allowed_space_usage = int(nbytes)

    def reserved_bytes(self) -> int:
        return self.flush_headroom_bytes + self.compaction_buffer_size

    # -- pressure --------------------------------------------------------

    def _free_fraction(self, free: int) -> float:
        """Tighter of budget-remaining and filesystem-free fractions.
        `free` is sampled by the caller BEFORE taking _mu (env IO — raw
        statvfs or a nested env lock — never happens under the leaf lock)."""
        fracs = []
        used = sum(self._tracked.values())
        budget = self.max_allowed_space_usage
        if budget > 0:
            fracs.append(max(0.0, budget - used) / budget)
        if free < (1 << 61):
            basis = free + used
            if basis > 0:
                fracs.append(free / basis)
        return min(fracs) if fracs else 1.0

    def _level_for(self, frac: float, prev: str) -> str:
        h = self.pressure_hysteresis
        if frac <= self.red_free_ratio:
            return "red"
        if prev == "red" and frac <= self.red_free_ratio + h:
            return "red"
        if frac <= self.amber_free_ratio:
            return "amber"
        if prev in ("amber", "red") and frac <= self.amber_free_ratio + h:
            return "amber"
        return "ok"

    def pressure(self) -> str:
        with self._mu:
            return self._level

    def poll(self) -> str:
        """One pressure evaluation; fires callbacks on level transitions."""
        try:
            free = self.free_space()
        except Exception as e:
            _errors.swallow(reason="sfm-free-space", exc=e)
            free = 1 << 62
        with self._mu:
            prev = self._level
            frac = self._free_fraction(free)
            level = self._level_for(frac, prev)
            self._level = level
            callbacks = list(self._callbacks) if level != prev else []
            info = {
                "level": level, "prev": prev, "free_fraction": frac,
                "tracked_bytes": sum(self._tracked.values()),
                "trash_bytes": sum(self._trash.values()),
                "budget_bytes": self.max_allowed_space_usage,
            }
        if self._stats is not None:
            from toplingdb_tpu.utils import statistics as _st
            self._stats.record_tick(_st.DISK_PRESSURE_POLLS, 1)
            if level != "ok":
                self._stats.record_tick(_st.DISK_PRESSURE_POLLS_BAD, 1)
            if level != prev:
                self._stats.record_tick(_st.DISK_PRESSURE_TRANSITIONS, 1)
        if level != prev:
            if level == "ok":
                self._wake.clear()  # back to paced trash deletion
            for cb in callbacks:
                cb(level, prev, info)
        return level

    def add_pressure_callback(self, fn) -> None:
        """fn(level, prev_level, info_dict), called outside manager locks."""
        with self._mu:
            self._callbacks.append(fn)

    def start_poller(self) -> None:
        if self.poll_period <= 0 or self._poller is not None:
            return

        def loop():
            while not self._stop.is_set():
                try:
                    self.poll()
                except Exception as e:
                    # A failing callback (or a statvfs error on a sick
                    # disk) must not kill the poller — pressure sensing
                    # is most needed exactly when IO is failing.
                    from toplingdb_tpu.utils import errors as _errors

                    _errors.swallow(reason="disk-pressure-poll", exc=e)
                self._stop.wait(self.poll_period)

        self._poller = ccy.spawn("disk-pressure-poller", loop, owner=self)

    # -- preflight -------------------------------------------------------

    def check_flush(self, out_bytes: int) -> bool:
        """May a flush writing ~out_bytes start? Flushes/WAL may consume
        the reserved headroom (ingest must always be able to drain), so
        they check against the FULL budget and raw free space."""
        with self._mu:
            budget = self.max_allowed_space_usage
            if budget > 0:
                used = sum(self._tracked.values())
                if used + out_bytes > budget:
                    return False
        try:
            free = self.free_space()
        except Exception as e:
            _errors.swallow(reason="sfm-free-space", exc=e)
            return True
        return free >= out_bytes

    def check_compaction(self, out_bytes: int) -> bool:
        """May a compaction writing ~out_bytes start? Compactions must
        leave the flush headroom AND the compaction buffer untouched."""
        reserve = self.flush_headroom_bytes + self.compaction_buffer_size
        with self._mu:
            budget = self.max_allowed_space_usage
            if budget > 0:
                used = sum(self._tracked.values())
                if used + out_bytes + reserve > budget:
                    return False
        try:
            free = self.free_space()
        except Exception as e:
            _errors.swallow(reason="sfm-free-space", exc=e)
            return True
        return free >= out_bytes + reserve

    def has_headroom(self) -> bool:
        """Recovery gate: is there enough space to resume background work?
        True once a fresh poll lands outside red AND the budget (if any)
        has at least the flush headroom available again."""
        level = self.poll()
        if level == "red":
            return False
        with self._mu:
            budget = self.max_allowed_space_usage
            if budget > 0:
                used = sum(self._tracked.values())
                if used + self.flush_headroom_bytes > budget:
                    return False
        return True

    # -- trash deletion --------------------------------------------------

    def accelerate_deletes(self) -> None:
        """Reclaim ladder rung 1: unpace every sleeping trash deleter."""
        self._wake.set()

    def _unpaced(self) -> bool:
        if self._wake.is_set():
            return True
        with self._mu:
            if self._level != "ok":
                return True
            total = sum(self._tracked.values())
            trash = sum(self._trash.values())
            return (self.max_trash_db_ratio > 0 and total > 0
                    and trash > self.max_trash_db_ratio * total)

    def schedule_delete(self, path: str) -> None:
        """Rate-limited deletion: rename to .trash, delete slowly. Pacing
        is skipped outright when trash already exceeds `max_trash_db_ratio`
        of the live tree or pressure is amber/red (the reference
        DeleteScheduler's ratio bypass, which previously never fired
        because nothing routed real deletions through the manager)."""
        with self._mu:
            size = self._tracked.get(path)
        if size is None:
            size = self._probe_size(path)
        trash = path + ".trash"
        try:
            if self._env is not None:
                self._env.rename_file(path, trash)
            else:
                os.replace(path, trash)
        except Exception as e:
            _errors.swallow(reason="sfm-trash-rename", exc=e)
            return
        self.on_delete_file(path)
        with self._mu:
            self._trash[trash] = size

        def worker():
            if self.rate > 0 and size > 0 and not self._unpaced():
                # Interruptible pacing: wait_for_deletes()/close() and the
                # reclaim ladder's accelerate_deletes() must not block
                # behind a sleeping deleter.
                self._wake.wait(min(size / self.rate, 10.0))
            try:
                if self._env is not None:
                    self._env.delete_file(trash)
                else:
                    os.remove(trash)
            except Exception as e:
                _errors.swallow(reason="sfm-trash-delete", exc=e)
            with self._mu:
                self._trash.pop(trash, None)
            if self._stats is not None and size:
                from toplingdb_tpu.utils import statistics as _st
                self._stats.record_tick(_st.DISK_TRASH_BYTES_FREED, size)

        t = ccy.spawn("sst-trash-delete", worker, owner=self)
        with self._mu:
            self._delete_threads = [
                x for x in self._delete_threads if x.is_alive()]
            self._delete_threads.append(t)

    def wait_for_deletes(self, timeout: float = 15.0) -> None:
        """Join every in-flight trash deleter (close path / tests)."""
        self._stop.set()
        self._wake.set()
        with self._mu:
            pending, self._delete_threads = self._delete_threads, []
            poller, self._poller = self._poller, None
        for t in pending:
            t.join(timeout)
        if poller is not None:
            poller.join(timeout)
        self._stop.clear()
        self._wake.clear()

    close = wait_for_deletes
