"""RateLimiter + WriteController + WriteBufferManager + SstFileManager —
the flow-control quartet (reference util/rate_limiter.cc,
db/write_controller.cc, memtable/write_buffer_manager.cc,
file/sst_file_manager_impl.cc in /root/reference)."""

from __future__ import annotations

import os
import threading

from toplingdb_tpu.utils import concurrency as ccy
import time


class RateLimiter:
    """Token-bucket byte rate limiter (reference GenericRateLimiter)."""

    def __init__(self, bytes_per_second: int, refill_period_us: int = 100_000):
        self.rate = bytes_per_second
        self._period = refill_period_us / 1e6
        self._available = bytes_per_second * self._period
        self._last_refill = time.monotonic()
        self._mu = ccy.Lock("rate_limiter.RateLimiter._mu")
        self.total_through = 0

    def request(self, n: int) -> None:
        """Blocks until n bytes of budget are available. Oversized requests
        are split into period-sized chunks (reference GenericRateLimiter), so
        a 1MB write against a 100KB/period budget still throttles."""
        budget = max(1, int(self.rate * self._period))
        while n > 0:
            chunk = min(n, budget)
            self._request_chunk(chunk)
            n -= chunk

    def try_request(self, n: int, timeout: float = 0.0) -> bool:
        """Bounded-wait variant of request() for admission control
        (sharding/admission.py): take n units within `timeout` seconds or
        return False taking nothing. Requests larger than one period's
        budget are admitted against the full accumulated budget and carry
        the remainder as debt (available goes negative), so a big batch
        pays its cost by delaying LATER requests instead of blocking the
        caller unboundedly."""
        deadline = time.monotonic() + timeout
        while True:
            with self._mu:
                now = time.monotonic()
                elapsed = now - self._last_refill
                if elapsed >= self._period:
                    self._available = min(
                        self.rate * self._period,
                        self._available + self.rate * elapsed,
                    )
                    self._last_refill = now
                need = min(n, self.rate * self._period)
                if self._available >= need:
                    self._available -= n  # may go negative: debt
                    self.total_through += n
                    return True
                now = time.monotonic()
            if now >= deadline:
                return False
            time.sleep(min(self._period / 4, max(0.0, deadline - now)))

    def _request_chunk(self, n: int) -> None:
        while True:
            with self._mu:
                now = time.monotonic()
                elapsed = now - self._last_refill
                if elapsed >= self._period:
                    self._available = min(
                        self.rate * self._period,
                        self._available + self.rate * elapsed,
                    )
                    self._last_refill = now
                if self._available >= n:
                    self._available -= n
                    self.total_through += n
                    return
            time.sleep(self._period / 4)


class WriteController:
    """Write throttling state: normal / delayed / stopped
    (reference db/write_controller.h). The DB consults it before each write;
    compaction pressure sets delays."""

    def __init__(self):
        self._stopped = False
        self._delay_bytes_per_sec = 0
        self._mu = ccy.Lock("rate_limiter.WriteController._mu")
        self._cv = ccy.Condition(lock=self._mu)
        self.total_stall_micros = 0

    def stop_writes(self) -> None:
        with self._mu:
            self._stopped = True

    def resume_writes(self) -> None:
        with self._cv:
            self._stopped = False
            self._delay_bytes_per_sec = 0
            self._cv.notify_all()

    def set_delay(self, bytes_per_sec: int) -> None:
        with self._mu:
            self._delay_bytes_per_sec = bytes_per_sec

    def wait_if_stalled(self, write_bytes: int, timeout: float = 10.0) -> None:
        t0 = time.monotonic()
        with self._cv:
            while self._stopped and time.monotonic() - t0 < timeout:
                self._cv.wait(0.05)
        if self._delay_bytes_per_sec > 0 and write_bytes > 0:
            delay = write_bytes / self._delay_bytes_per_sec
            time.sleep(min(delay, 1.0))
        stall = time.monotonic() - t0
        if stall > 0.001:
            self.total_stall_micros += int(stall * 1e6)


class WriteBufferManager:
    """DB-wide memtable memory budget (reference write_buffer_manager.h:37):
    when the sum over all DBs exceeds the budget, callers should flush."""

    def __init__(self, buffer_size: int):
        self.buffer_size = buffer_size
        self._usage = 0
        self._mu = ccy.Lock("rate_limiter.WriteBufferManager._mu")

    def reserve(self, n: int) -> None:
        with self._mu:
            self._usage += n

    def free(self, n: int) -> None:
        with self._mu:
            self._usage = max(0, self._usage - n)

    def memory_usage(self) -> int:
        return self._usage

    def should_flush(self) -> bool:
        return self.buffer_size > 0 and self._usage >= self.buffer_size


class SstFileManager:
    """Tracks SST disk usage; rate-limited trash deletion (reference
    include/rocksdb/sst_file_manager.h:26, file/delete_scheduler.cc)."""

    def __init__(self, bytes_per_sec_delete: int = 0,
                 max_trash_db_ratio: float = 0.25):
        self.rate = bytes_per_sec_delete
        self._tracked: dict[str, int] = {}
        self._mu = ccy.Lock("rate_limiter.SstFileManager._mu")
        self._stop = threading.Event()
        self._delete_threads: list[threading.Thread] = []

    def on_add_file(self, path: str, size: int | None = None) -> None:
        with self._mu:
            if size is None:
                try:
                    size = os.path.getsize(path)
                except OSError:
                    size = 0
            self._tracked[path] = size

    def on_delete_file(self, path: str) -> None:
        with self._mu:
            self._tracked.pop(path, None)

    def total_size(self) -> int:
        with self._mu:
            return sum(self._tracked.values())

    def schedule_delete(self, path: str) -> None:
        """Rate-limited deletion: rename to .trash, delete slowly."""
        size = self._tracked.get(path, 0)
        trash = path + ".trash"
        try:
            os.replace(path, trash)
        except OSError:
            return
        self.on_delete_file(path)

        def worker():
            if self.rate > 0 and size > 0:
                # Interruptible pacing: wait_for_deletes()/close() must not
                # block behind a sleeping deleter (the lifecycle hole the
                # concurrency lint flagged — these workers were
                # fire-and-forget).
                self._stop.wait(min(size / self.rate, 10.0))
            try:
                os.remove(trash)
            except OSError:
                pass

        t = ccy.spawn("sst-trash-delete", worker, owner=self)
        with self._mu:
            self._delete_threads = [
                x for x in self._delete_threads if x.is_alive()]
            self._delete_threads.append(t)

    def wait_for_deletes(self, timeout: float = 15.0) -> None:
        """Join every in-flight trash deleter (close path / tests)."""
        self._stop.set()
        with self._mu:
            pending, self._delete_threads = self._delete_threads, []
        for t in pending:
            t.join(timeout)
        self._stop.clear()

    close = wait_for_deletes
