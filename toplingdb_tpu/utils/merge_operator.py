"""MergeOperator API + stock operators.

Same contract as the reference (include/rocksdb/merge_operator.h,
utilities/merge_operators/ in /root/reference): `full_merge` folds an operand
chain onto an optional base value (newest operand LAST in our convention —
operands are passed oldest→newest); `partial_merge` may combine adjacent
operands without a base. Stock operators mirror the reference's set.
"""

from __future__ import annotations

import struct


class MergeOperator:
    def name(self) -> str:
        raise NotImplementedError

    def full_merge(self, key: bytes, existing: bytes | None,
                   operands: list[bytes]) -> bytes:
        """Fold operands (oldest→newest) onto existing; must succeed."""
        raise NotImplementedError

    def partial_merge(self, key: bytes, left: bytes, right: bytes) -> bytes | None:
        """Combine two adjacent operands (left older); None = cannot."""
        return None

    def allow_single_operand(self) -> bool:
        return False


class PutOperator(MergeOperator):
    """Merge == overwrite: last operand wins (reference put.cc)."""

    def name(self) -> str:
        return "PutOperator"

    def full_merge(self, key, existing, operands):
        return operands[-1] if operands else (existing or b"")

    def partial_merge(self, key, left, right):
        return right


class UInt64AddOperator(MergeOperator):
    """uint64 little-endian addition (reference uint64add.cc)."""

    def name(self) -> str:
        return "UInt64AddOperator"

    @staticmethod
    def _dec(v: bytes | None) -> int:
        if not v:
            return 0
        if len(v) == 8:
            return struct.unpack("<Q", v)[0]
        return int.from_bytes(v[:8].ljust(8, b"\x00"), "little")

    def full_merge(self, key, existing, operands):
        total = self._dec(existing)
        for op in operands:
            total = (total + self._dec(op)) & 0xFFFFFFFFFFFFFFFF
        return struct.pack("<Q", total)

    def partial_merge(self, key, left, right):
        return struct.pack(
            "<Q", (self._dec(left) + self._dec(right)) & 0xFFFFFFFFFFFFFFFF
        )


class StringAppendOperator(MergeOperator):
    """Append with delimiter (reference string_append/stringappend.cc)."""

    def __init__(self, delim: bytes = b","):
        self.delim = delim

    def name(self) -> str:
        return "StringAppendOperator"

    def full_merge(self, key, existing, operands):
        parts = ([existing] if existing is not None else []) + list(operands)
        return self.delim.join(parts)

    def partial_merge(self, key, left, right):
        return left + self.delim + right


class MaxOperator(MergeOperator):
    """Bytewise max (reference max.cc)."""

    def name(self) -> str:
        return "MaxOperator"

    def full_merge(self, key, existing, operands):
        best = existing if existing is not None else b""
        for op in operands:
            if op > best:
                best = op
        return best

    def partial_merge(self, key, left, right):
        return max(left, right)


class BytesXOROperator(MergeOperator):
    """Bytewise XOR, shorter operand zero-extended (reference
    utilities/merge_operators/bytesxor.cc)."""

    def name(self) -> str:
        return "BytesXOROperator"

    @staticmethod
    def _xor(a: bytes, b: bytes) -> bytes:
        if len(a) < len(b):
            a, b = b, a
        out = bytearray(a)
        for i, c in enumerate(b):
            out[i] ^= c
        return bytes(out)

    def full_merge(self, key, existing, operands):
        acc = existing or b""
        for op in operands:
            acc = self._xor(acc, op)
        return acc

    def partial_merge(self, key, left, right):
        return self._xor(left, right)


class SortListOperator(MergeOperator):
    """Merge comma-separated sorted integer lists into one sorted list
    (reference utilities/merge_operators/sortlist.cc)."""

    def name(self) -> str:
        return "MergeSortOperator"

    @staticmethod
    def _nums(v: bytes | None) -> list[int]:
        if not v:
            return []
        return [int(x) for x in v.split(b",") if x]

    def full_merge(self, key, existing, operands):
        out = self._nums(existing)
        for op in operands:
            out.extend(self._nums(op))
        out.sort()
        return b",".join(b"%d" % n for n in out)

    def partial_merge(self, key, left, right):
        return self.full_merge(key, None, [left, right])


class AggMergeOperator(MergeOperator):
    """Pluggable per-record aggregation (reference utilities/agg_merge/):
    every value/operand is `varint-len aggregator-name | payload`; the
    newest record's aggregator folds the whole chain. Stock aggregators:
    sum/max/min (uint64 LE), last, first."""

    NAME_SEP = b"\x00"

    def name(self) -> str:
        return "AggMergeOperator.v1"

    @staticmethod
    def pack(agg: bytes, payload: bytes) -> bytes:
        """Encode one aggregatable value (reference EncodeAggFuncAndPayload)."""
        return bytes([len(agg)]) + agg + payload

    @staticmethod
    def _unpack(v: bytes) -> tuple[bytes | None, bytes]:
        """(aggregator, payload); aggregator None for values that were not
        written through pack() (reference agg_merge degrades gracefully on
        unpackaged input instead of crashing)."""
        if not v or 1 + v[0] > len(v):
            return None, v
        n = v[0]
        return v[1 : 1 + n], v[1 + n :]

    @staticmethod
    def _u64(p: bytes) -> int:
        return int.from_bytes(p[:8].ljust(8, b"\x00"), "little")

    def full_merge(self, key, existing, operands):
        chain = ([existing] if existing is not None else []) + list(operands)
        # Newest PACKED record picks the function; an all-unpackaged chain
        # degrades to last-value-wins.
        agg = None
        for v in reversed(chain):
            agg, _ = self._unpack(v)
            if agg is not None:
                break
        if agg is None:
            return chain[-1]
        payloads = [self._unpack(v)[1] for v in chain]
        if agg == b"sum":
            out = sum(self._u64(p) for p in payloads) & 0xFFFFFFFFFFFFFFFF
            return self.pack(agg, struct.pack("<Q", out))
        if agg == b"max":
            return self.pack(agg, struct.pack(
                "<Q", max(self._u64(p) for p in payloads)))
        if agg == b"min":
            return self.pack(agg, struct.pack(
                "<Q", min(self._u64(p) for p in payloads)))
        if agg == b"first":
            return self.pack(agg, payloads[0])
        # "last" and any unknown aggregator: newest record wins.
        return self.pack(agg, payloads[-1])


class CassandraValueMergeOperator(MergeOperator):
    """Cassandra-style row merge (reference utilities/cassandra/): a value is
    a serialized row of columns `varint32 col_id | fixed64 timestamp |
    varint32 len | bytes`; merging keeps the newest timestamp per column.
    A zero-length value for a column is a column tombstone."""

    def name(self) -> str:
        return "CassandraValueMergeOperator"

    @staticmethod
    def _cols(v: bytes) -> dict[int, tuple[int, bytes]]:
        from toplingdb_tpu.utils import coding

        out: dict[int, tuple[int, bytes]] = {}
        off = 0
        while off < len(v):
            cid, off = coding.decode_varint32(v, off)
            ts = struct.unpack_from("<Q", v, off)[0]
            off += 8
            ln, off = coding.decode_varint32(v, off)
            out[cid] = (ts, bytes(v[off : off + ln]))
            off += ln
        return out

    @staticmethod
    def _encode(cols: dict[int, tuple[int, bytes]]) -> bytes:
        from toplingdb_tpu.utils import coding

        out = bytearray()
        for cid in sorted(cols):
            ts, val = cols[cid]
            out += coding.encode_varint32(cid)
            out += struct.pack("<Q", ts)
            out += coding.encode_varint32(len(val))
            out += val
        return bytes(out)

    def full_merge(self, key, existing, operands):
        merged: dict[int, tuple[int, bytes]] = {}
        for v in ([existing] if existing is not None else []) + list(operands):
            for cid, (ts, val) in self._cols(v).items():
                if cid not in merged or ts >= merged[cid][0]:
                    merged[cid] = (ts, val)
        return self._encode(merged)

    def partial_merge(self, key, left, right):
        return self.full_merge(key, None, [left, right])


_REGISTRY = {
    "put": PutOperator,
    "uint64add": UInt64AddOperator,
    "stringappend": StringAppendOperator,
    "max": MaxOperator,
    "bytesxor": BytesXOROperator,
    "sortlist": SortListOperator,
    "aggmerge": AggMergeOperator,
    "cassandra": CassandraValueMergeOperator,
}

# Class-name aliases: the serialized dcompact boundary ships
# MergeOperator.name() strings (ObjectRpcParam.clazz analogue).
_BY_CLASS = {cls().name(): cls for cls in set(_REGISTRY.values())}


def create_merge_operator(name: str) -> MergeOperator:
    cls = _REGISTRY.get(name) or _BY_CLASS.get(name)
    if cls is None:
        from toplingdb_tpu.utils.status import InvalidArgument

        raise InvalidArgument(f"unknown merge operator {name!r}")
    return cls()
