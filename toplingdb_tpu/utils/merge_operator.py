"""MergeOperator API + stock operators.

Same contract as the reference (include/rocksdb/merge_operator.h,
utilities/merge_operators/ in /root/reference): `full_merge` folds an operand
chain onto an optional base value (newest operand LAST in our convention —
operands are passed oldest→newest); `partial_merge` may combine adjacent
operands without a base. Stock operators mirror the reference's set.
"""

from __future__ import annotations

import struct


class MergeOperator:
    def name(self) -> str:
        raise NotImplementedError

    def full_merge(self, key: bytes, existing: bytes | None,
                   operands: list[bytes]) -> bytes:
        """Fold operands (oldest→newest) onto existing; must succeed."""
        raise NotImplementedError

    def partial_merge(self, key: bytes, left: bytes, right: bytes) -> bytes | None:
        """Combine two adjacent operands (left older); None = cannot."""
        return None

    def allow_single_operand(self) -> bool:
        return False


class PutOperator(MergeOperator):
    """Merge == overwrite: last operand wins (reference put.cc)."""

    def name(self) -> str:
        return "PutOperator"

    def full_merge(self, key, existing, operands):
        return operands[-1] if operands else (existing or b"")

    def partial_merge(self, key, left, right):
        return right


class UInt64AddOperator(MergeOperator):
    """uint64 little-endian addition (reference uint64add.cc)."""

    def name(self) -> str:
        return "UInt64AddOperator"

    @staticmethod
    def _dec(v: bytes | None) -> int:
        if not v:
            return 0
        if len(v) == 8:
            return struct.unpack("<Q", v)[0]
        return int.from_bytes(v[:8].ljust(8, b"\x00"), "little")

    def full_merge(self, key, existing, operands):
        total = self._dec(existing)
        for op in operands:
            total = (total + self._dec(op)) & 0xFFFFFFFFFFFFFFFF
        return struct.pack("<Q", total)

    def partial_merge(self, key, left, right):
        return struct.pack(
            "<Q", (self._dec(left) + self._dec(right)) & 0xFFFFFFFFFFFFFFFF
        )


class StringAppendOperator(MergeOperator):
    """Append with delimiter (reference string_append/stringappend.cc)."""

    def __init__(self, delim: bytes = b","):
        self.delim = delim

    def name(self) -> str:
        return "StringAppendOperator"

    def full_merge(self, key, existing, operands):
        parts = ([existing] if existing is not None else []) + list(operands)
        return self.delim.join(parts)

    def partial_merge(self, key, left, right):
        return left + self.delim + right


class MaxOperator(MergeOperator):
    """Bytewise max (reference max.cc)."""

    def name(self) -> str:
        return "MaxOperator"

    def full_merge(self, key, existing, operands):
        best = existing if existing is not None else b""
        for op in operands:
            if op > best:
                best = op
        return best

    def partial_merge(self, key, left, right):
        return max(left, right)


_REGISTRY = {
    "put": PutOperator,
    "uint64add": UInt64AddOperator,
    "stringappend": StringAppendOperator,
    "max": MaxOperator,
}


def create_merge_operator(name: str) -> MergeOperator:
    try:
        return _REGISTRY[name]()
    except KeyError:
        from toplingdb_tpu.utils.status import InvalidArgument

        raise InvalidArgument(f"unknown merge operator {name!r}") from None
