"""CompactionFilter API + stock filters.

Same contract as the reference (include/rocksdb/compaction_filter.h,
utilities/compaction_filters/ in /root/reference): consulted for each
surviving VALUE entry during compaction; may drop or rewrite it.
"""

from __future__ import annotations

import enum


class Decision(enum.Enum):
    KEEP = 0
    REMOVE = 1
    CHANGE_VALUE = 2


class CompactionFilter:
    def name(self) -> str:
        raise NotImplementedError

    def filter(self, level: int, key: bytes, value: bytes) -> tuple[Decision, bytes | None]:
        """Returns (decision, new_value). new_value used for CHANGE_VALUE."""
        return Decision.KEEP, None


class RemoveEmptyValueCompactionFilter(CompactionFilter):
    """Drop entries whose value is empty (reference
    utilities/compaction_filters/remove_emptyvalue_compactionfilter.cc)."""

    def name(self) -> str:
        return "RemoveEmptyValueCompactionFilter"

    def filter(self, level, key, value):
        if value == b"":
            return Decision.REMOVE, None
        return Decision.KEEP, None


# Name → factory registry: how filters travel across the serialized
# compaction boundary (the ObjectRpcParam.clazz analogue, reference
# compaction_executor.h:9-14). Custom filters must register to be usable by
# remote/subprocess workers.
_REGISTRY: dict[str, type] = {
    "RemoveEmptyValueCompactionFilter": RemoveEmptyValueCompactionFilter,
}


def register_compaction_filter(cls: type) -> type:
    _REGISTRY[cls().name()] = cls
    return cls


def create_compaction_filter(name: str) -> CompactionFilter:
    try:
        return _REGISTRY[name]()
    except KeyError:
        from toplingdb_tpu.utils.status import InvalidArgument

        raise InvalidArgument(f"unknown compaction filter {name!r}") from None
