"""CompactionFilter API + stock filters.

Same contract as the reference (include/rocksdb/compaction_filter.h,
utilities/compaction_filters/ in /root/reference): consulted for each
surviving VALUE entry during compaction; may drop or rewrite it.
"""

from __future__ import annotations

import enum


class Decision(enum.Enum):
    KEEP = 0
    REMOVE = 1
    CHANGE_VALUE = 2


class CompactionFilter:
    def name(self) -> str:
        raise NotImplementedError

    def filter(self, level: int, key: bytes, value: bytes) -> tuple[Decision, bytes | None]:
        """Returns (decision, new_value). new_value used for CHANGE_VALUE."""
        return Decision.KEEP, None


class RemoveEmptyValueCompactionFilter(CompactionFilter):
    """Drop entries whose value is empty (reference
    utilities/compaction_filters/remove_emptyvalue_compactionfilter.cc)."""

    def name(self) -> str:
        return "RemoveEmptyValueCompactionFilter"

    def filter(self, level, key, value):
        if value == b"":
            return Decision.REMOVE, None
        return Decision.KEEP, None
