"""JSON-driven configuration + object registry + HTTP introspection.

The SidePlugin-equivalent layer (reference README.md:8-16 and the in-tree
ObjectRegistry ancestor, utilities/object_registry.cc in /root/reference):

  ObjectRegistry      (category, name) → factory; objects created from JSON
                      specs {"class": name, "params": {...}} or plain names.
  SidePluginRepo      named objects + DBs opened from one JSON document;
                      embedded HTTP server exposing stats/levels/config
                      (the WebView analogue).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils import errors as _errors
from toplingdb_tpu.utils.status import Busy, IOError_, InvalidArgument


class ObjectRegistry:
    _global: "ObjectRegistry | None" = None

    def __init__(self):
        self._factories: dict[tuple[str, str], object] = {}

    @classmethod
    def default(cls) -> "ObjectRegistry":
        if cls._global is None:
            cls._global = cls()
            _register_builtins(cls._global)
        return cls._global

    def register(self, category: str, name: str, factory) -> None:
        self._factories[(category, name)] = factory

    def create(self, category: str, spec):
        """spec: name string, or {"class": name, "params": {...}}."""
        if spec is None:
            return None
        if isinstance(spec, str):
            name, params = spec, {}
        elif isinstance(spec, dict):
            name = spec.get("class") or spec.get("name")
            params = spec.get("params", {})
        else:
            return spec  # already an object
        f = self._factories.get((category, name))
        if f is None:
            raise InvalidArgument(f"no {category} factory named {name!r}")
        return f(**params)

    def names(self, category: str) -> list[str]:
        return sorted(n for c, n in self._factories if c == category)


def _register_builtins(reg: ObjectRegistry) -> None:
    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.compaction.executor import (
        DeviceCompactionExecutorFactory,
        SubprocessCompactionExecutorFactory,
    )
    from toplingdb_tpu.table.filter import BloomFilterPolicy
    from toplingdb_tpu.utils.compaction_filter import (
        RemoveEmptyValueCompactionFilter,
    )
    from toplingdb_tpu.utils.merge_operator import (
        MaxOperator, PutOperator, StringAppendOperator, UInt64AddOperator,
    )
    from toplingdb_tpu.utils.statistics import Statistics

    reg.register("comparator", "bytewise", lambda: dbformat.BYTEWISE)
    reg.register("comparator", "reverse_bytewise", lambda: dbformat.REVERSE_BYTEWISE)
    reg.register("comparator", "u64ts_bytewise", lambda: dbformat.U64_TS_BYTEWISE)
    from toplingdb_tpu.utils.merge_operator import (
        AggMergeOperator, BytesXOROperator, CassandraValueMergeOperator,
        SortListOperator,
    )

    reg.register("merge_operator", "put", PutOperator)
    reg.register("merge_operator", "uint64add", UInt64AddOperator)
    reg.register("merge_operator", "stringappend", StringAppendOperator)
    reg.register("merge_operator", "max", MaxOperator)
    reg.register("merge_operator", "bytesxor", BytesXOROperator)
    reg.register("merge_operator", "sortlist", SortListOperator)
    reg.register("merge_operator", "aggmerge", AggMergeOperator)
    reg.register("merge_operator", "cassandra", CassandraValueMergeOperator)
    reg.register("compaction_filter", "remove_empty_value",
                 RemoveEmptyValueCompactionFilter)
    reg.register("filter_policy", "bloom",
                 lambda bits_per_key=10.0: BloomFilterPolicy(bits_per_key))
    reg.register("compaction_executor_factory", "device",
                 DeviceCompactionExecutorFactory)
    reg.register("compaction_executor_factory", "subprocess",
                 SubprocessCompactionExecutorFactory)

    def _http_factory(worker_urls=(), **kw):
        from toplingdb_tpu.compaction.dcompact_service import (
            HttpCompactionExecutorFactory,
        )

        return HttpCompactionExecutorFactory(list(worker_urls), **kw)

    reg.register("compaction_executor_factory", "http", _http_factory)
    reg.register("statistics", "default", Statistics)
    from toplingdb_tpu.utils.slice_transform import (
        CappedPrefixTransform, FixedPrefixTransform, NoopTransform,
    )

    reg.register("prefix_extractor", "fixed",
                 lambda length=8: FixedPrefixTransform(length))
    reg.register("prefix_extractor", "capped",
                 lambda length=8: CappedPrefixTransform(length))
    reg.register("prefix_extractor", "noop", NoopTransform)


_SIMPLE_OPTION_KEYS = {
    "create_if_missing", "error_if_exists", "paranoid_checks",
    "write_buffer_size", "max_write_buffer_number", "wal_enabled",
    "num_levels", "level0_file_num_compaction_trigger",
    "level0_slowdown_writes_trigger", "level0_stop_writes_trigger",
    "max_bytes_for_level_base", "max_bytes_for_level_multiplier",
    "target_file_size_base", "target_file_size_multiplier",
    "max_compaction_bytes", "compaction_style", "max_background_jobs",
    "max_subcompactions", "disable_auto_compactions",
    "universal_size_ratio", "universal_min_merge_width",
    "universal_max_merge_width",
    "universal_max_size_amplification_percent",
    "fifo_max_table_files_size", "fifo_ttl_seconds",
    "periodic_compaction_seconds",
    "full_history_ts_low",
    "enable_blob_files", "min_blob_size",
    "enable_blob_garbage_collection", "blob_garbage_collection_age_cutoff",
    "stats_persist_period_sec", "stats_dump_period_sec",
    "trace_sample_every", "trace_slow_usec", "trace_ring",
    "seqno_time_sample_period_sec",
    "read_only", "memtable_rep", "db_write_buffer_size",
    "allow_concurrent_memtable_write", "enable_pipelined_write",
    "unordered_write", "preclude_last_level_data_seconds",
    "compression", "bottommost_compression", "bottommost_format",
    "recycle_log_file_num", "wal_ttl_seconds",
    "protection_bytes_per_key", "file_checksum",
    "integrity_scrub_period_sec", "integrity_scrub_bytes_per_sec",
    "enable_async_wal", "async_wal_ring_size",
    "histogram_window_sec", "slo_eval_period_sec", "slo_window_sec",
}

# MergeOperator.name() → registry key, for options_to_config round-trips.
_MERGE_OP_NAMES = {
    "PutOperator": "put", "UInt64AddOperator": "uint64add",
    "StringAppendOperator": "stringappend", "MaxOperator": "max",
    "BytesXOROperator": "bytesxor", "MergeSortOperator": "sortlist",
    "AggMergeOperator.v1": "aggmerge",
    "CassandraValueMergeOperator": "cassandra",
}

_SIMPLE_TABLE_KEYS = (
    "format", "block_size", "restart_interval", "index_restart_interval",
    "compression", "whole_key_filtering", "verify_checksums", "index_type",
    "metadata_block_size", "hash_index", "auto_sort",
)


def options_from_config(cfg: dict):
    """Build Options from a JSON-style dict (the SidePlugin config shape)."""
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.table.builder import TableOptions

    reg = ObjectRegistry.default()
    opts = Options()
    for k, v in cfg.items():
        if k in _SIMPLE_OPTION_KEYS:
            setattr(opts, k, v)
        elif k == "comparator":
            opts.comparator = reg.create("comparator", v)
        elif k == "merge_operator":
            opts.merge_operator = reg.create("merge_operator", v)
        elif k == "compaction_filter":
            opts.compaction_filter = reg.create("compaction_filter", v)
        elif k == "prefix_extractor":
            opts.prefix_extractor = reg.create("prefix_extractor", v)
        elif k == "compaction_executor_factory":
            opts.compaction_executor_factory = reg.create(
                "compaction_executor_factory", v
            )
        elif k == "shared_store":
            # A string spec (store root path or http:// URL) rides the
            # JSON config; live store objects are code-only.
            opts.shared_store = v
        elif k == "dcompact":
            from toplingdb_tpu.compaction.resilience import DcompactOptions

            opts.dcompact = DcompactOptions.from_config(v)
        elif k == "statistics":
            opts.statistics = reg.create("statistics", v)
        elif k == "slo_specs":
            # Plain dicts straight from JSON; utils/slo.SLOEngine
            # normalizes them into SLOSpec at engine construction.
            opts.slo_specs = tuple(v)
        elif k == "table_options":
            t = TableOptions()
            for tk, tv in v.items():
                if tk == "filter_policy":
                    t.filter_policy = reg.create("filter_policy", tv)
                else:
                    setattr(t, tk, tv)
            opts.table_options = t
        else:
            raise InvalidArgument(f"unknown option {k!r}")
    return opts


def options_to_config(opts) -> dict:
    """Serialize Options to the same JSON-style dict options_from_config
    reads — the OPTIONS-NNNN persistence format (reference
    options/options_parser.cc PersistRocksDBOptions). Non-default simple
    fields plus registry-known plugin objects; unregistered plugin objects
    (custom user classes) are skipped, as the reference skips unknown
    customizables."""
    from toplingdb_tpu.options import Options

    base = Options()
    out: dict = {}
    for k in sorted(_SIMPLE_OPTION_KEYS):
        v = getattr(opts, k)
        if v != getattr(base, k):
            out[k] = v
    if isinstance(getattr(opts, "shared_store", None), str) \
            and opts.shared_store:
        out["shared_store"] = opts.shared_store
    if opts.comparator.name() == "tpulsm.ReverseBytewiseComparator":
        out["comparator"] = "reverse_bytewise"
    elif opts.comparator.name() == "tpulsm.BytewiseComparator.u64ts":
        out["comparator"] = "u64ts_bytewise"
    # (any other non-bytewise comparator is an unregistered custom object —
    # skipped, like the reference skips unknown customizables)
    if opts.merge_operator is not None:
        key = _MERGE_OP_NAMES.get(opts.merge_operator.name())
        if key is not None:
            out["merge_operator"] = key
    if (opts.compaction_filter is not None
            and opts.compaction_filter.name()
            == "RemoveEmptyValueCompactionFilter"):
        out["compaction_filter"] = "remove_empty_value"
    if opts.statistics is not None:
        out["statistics"] = "default"
    if getattr(opts, "slo_specs", ()):
        from dataclasses import asdict, is_dataclass

        out["slo_specs"] = [
            asdict(s) if is_dataclass(s) else dict(s)
            for s in opts.slo_specs
        ]
    if opts.dcompact is not None:
        dc = opts.dcompact.to_config()
        if dc:
            out["dcompact"] = dc
    pe = opts.prefix_extractor
    if pe is not None:
        pname = pe.name()
        if pname.startswith("tpulsm.FixedPrefix."):
            out["prefix_extractor"] = {
                "class": "fixed", "params": {"length": pe.n},
            }
        elif pname.startswith("tpulsm.CappedPrefix."):
            out["prefix_extractor"] = {
                "class": "capped", "params": {"length": pe.n},
            }
        elif pname == "tpulsm.Noop":
            out["prefix_extractor"] = "noop"
    t = opts.table_options
    from toplingdb_tpu.table.builder import TableOptions

    tbase = TableOptions()
    tout: dict = {}
    for k in _SIMPLE_TABLE_KEYS:
        v = getattr(t, k)
        if v != getattr(tbase, k):
            tout[k] = v
    if t.filter_policy is None:
        tout["filter_policy"] = None
    elif t.filter_policy.name().startswith("tpulsm.BloomFilter"):
        bits = getattr(t.filter_policy, "bits_per_key", 10.0)
        if bits != 10.0:
            tout["filter_policy"] = {
                "class": "bloom", "params": {"bits_per_key": bits},
            }
    if tout:
        out["table_options"] = tout
    return out


def persist_options(db) -> None:
    """Write OPTIONS-NNNN next to the DB (reference PersistRocksDBOptions on
    every successful open); older OPTIONS files become obsolete."""
    import json as _json

    from toplingdb_tpu.db import filename as _fn

    num = db.versions.new_file_number()
    db.env.write_file(
        _fn.options_file_name(db.dbname, num),
        _json.dumps(options_to_config(db.options), indent=1).encode(),
    )
    db._options_file_number = num


def load_latest_options(dbname: str, env=None):
    """Rebuild Options from the newest OPTIONS-NNNN file (reference
    LoadLatestOptions). Returns None if no OPTIONS file exists."""
    import json as _json

    from toplingdb_tpu.db import filename as _fn

    if env is None:
        from toplingdb_tpu.env import default_env

        env = default_env()
    nums = [
        num for child in env.get_children(dbname)
        for t, num in [_fn.parse_file_name(child)]
        if t == _fn.FileType.OPTIONS
    ]
    if not nums:
        return None
    data = env.read_file(_fn.options_file_name(dbname, max(nums)))
    return options_from_config(_json.loads(data.decode()))


_BREAKER_STATE_NUM = {"closed": 0, "half_open": 1, "open": 2}


def _prometheus_gauges(name: str, db) -> str:
    """Point-in-time gauges beside the ticker/histogram exposition:
    memtable bytes, per-level file counts/bytes, async-WAL ring depth,
    replication status numbers, dcompact breaker states, and tracer ring
    occupancy. Best-effort: a half-closed DB yields what it can."""
    lines = []
    lab = f'{{db="{name}"}}'

    def g(metric, value, labels=None):
        m = f"tpulsm_{metric}"
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{labels or lab} {value}")

    try:
        cfs = getattr(db, "_cfs", None)
        if cfs:
            g("memtable_bytes", sum(
                c.mem.approximate_memory_usage()
                + sum(m.approximate_memory_usage() for m in c.imm)
                for c in cfs.values()))
            g("immutable_memtables", sum(len(c.imm) for c in cfs.values()))
    except Exception as e:
        _errors.swallow(reason="prom-gauge-memtable", exc=e)
    try:
        v = db.versions.current
        for lvl in range(v.num_levels):
            files = v.files[lvl]
            if files:
                ll = f'{{db="{name}",level="{lvl}"}}'
                g("level_files", len(files), ll)
                g("level_bytes", sum(f.file_size for f in files), ll)
        g("last_sequence", db.versions.last_sequence)
    except Exception as e:
        _errors.swallow(reason="prom-gauge-levels", exc=e)
    try:
        ring = getattr(db, "_wal_ring", None)
        if ring is not None:
            g("async_wal_ring_depth", len(ring._q))
    except Exception as e:
        _errors.swallow(reason="prom-gauge-wal-ring", exc=e)
    try:
        provider = getattr(db, "_repl_status_provider", None)
        if provider is not None:
            for k, val in provider().items():
                if isinstance(val, bool) or not isinstance(val,
                                                           (int, float)):
                    continue
                g(f"replication_{k}", val)
    except Exception as e:
        _errors.swallow(reason="prom-gauge-replication", exc=e)
    try:
        health = getattr(
            getattr(db.options, "compaction_executor_factory", None),
            "health", None)
        breakers = getattr(health, "_breakers", None)
        if breakers:
            for url, b in sorted(breakers.items()):
                ul = f'{{db="{name}",url="{url}"}}'
                g("dcompaction_breaker_state",
                  _BREAKER_STATE_NUM.get(b.state, -1), ul)
    except Exception as e:
        _errors.swallow(reason="prom-gauge-dcompact-breaker", exc=e)
    try:
        tracer = getattr(db, "tracer", None)
        if tracer is not None:
            st = tracer.status()
            g("trace_ring_retained", st["traces_retained"])
            g("traces_started_total", st["traces_started"])
    except Exception as e:
        _errors.swallow(reason="prom-gauge-tracer", exc=e)
    try:
        stall_fn = getattr(db, "write_stall_state", None)
        if stall_fn is not None:
            stall = stall_fn()
            g("write_stall_state",
              {"none": 0, "delayed": 1, "stopped": 2}.get(
                  stall.get("state"), -1))
            g("write_stall_l0_files", stall.get("l0_files", 0))
            g("write_stall_micros_total", stall.get("stall_micros", 0))
    except Exception as e:
        _errors.swallow(reason="prom-gauge-write-stall", exc=e)
    try:
        engine = getattr(db, "slo_engine", None)
        if engine is not None:
            from toplingdb_tpu.utils.slo import health_num

            s = engine.status()
            g("slo_health", health_num(s["health"]))
            for sname, row in sorted(s["specs"].items()):
                sl = f'{{db="{name}",slo="{sname}"}}'
                g("slo_burn_rate_fast", row["burn_rate_fast"], sl)
                g("slo_burn_rate_slow", row["burn_rate_slow"], sl)
                g("slo_firing", int(row["firing"]), sl)
    except Exception as e:
        _errors.swallow(reason="prom-gauge-slo", exc=e)
    try:
        sfm = getattr(db, "_sfm", None)
        if sfm is not None:
            g("disk_free_bytes", sfm.free_space())
            g("disk_tracked_bytes", sfm.total_size())
            g("disk_trash_bytes", sfm.trash_size())
            g("disk_pressure_state",
              {"ok": 0, "amber": 1, "red": 2}.get(sfm.pressure(), -1))
            g("disk_budget_bytes", sfm.max_allowed_space_usage)
            g("disk_reserved_bytes", sfm.reserved_bytes())
    except Exception as e:
        _errors.swallow(reason="prom-gauge-disk", exc=e)
    return "\n".join(lines) + "\n" if lines else ""


def _prometheus_cluster_gauges(name: str, router) -> str:
    """Per-shard gauges for a registered ShardRouter: map version, shard
    epochs/fence state, and the router's traffic counters."""
    lines = []

    def g(metric, value, labels):
        m = f"tpulsm_{metric}"
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{labels} {value}")

    try:
        status = router.status()
        g("shard_map_version", status["map_version"],
          f'{{cluster="{name}"}}')
        g("shard_count", status["n_shards"], f'{{cluster="{name}"}}')
        for row in status["shards"]:
            lab = f'{{cluster="{name}",shard="{row["name"]}"}}'
            g("shard_epoch", row["epoch"], lab)
            g("shard_fenced", int(bool(row.get("fenced"))), lab)
            g("shard_stall_state",
              {"none": 0, "delayed": 1, "stopped": 2}.get(
                  row.get("stall"), -1), lab)
            if row.get("health") is not None:
                from toplingdb_tpu.utils.slo import health_num

                g("shard_health", health_num(row["health"]), lab)
            for k in ("reads", "writes", "write_bytes"):
                g(f"shard_traffic_{k}", row.get("traffic", {}).get(k, 0),
                  lab)
    except Exception as e:
        _errors.swallow(reason="prom-gauge-shard", exc=e)
    return "\n".join(lines) + "\n" if lines else ""


class SidePluginRepo:
    """Open DBs from one JSON document; serve introspection over HTTP
    (reference java SidePluginRepo + rockside WebView)."""

    def __init__(self):
        self._dbs: dict[str, object] = {}
        self._configs: dict[str, dict] = {}
        self._clusters: dict[str, object] = {}
        # Remote fleet members for /cluster/health: (name, url) pairs,
        # each url pointing at a health-doc endpoint (/health/<db> on a
        # sibling repo, /replication/health on a follower's
        # ReplicationServer, /health on a dcompact worker).
        self._fleet: list[tuple[str, str]] = []
        self._fleet_timeout = 2.0
        self._fleet_last_errors: dict[str, str] = {}
        # Out-of-process fleets (sharding.FleetSupervisor) for /fleet/*.
        self._fleet_sups: dict[str, object] = {}
        self._server: ThreadingHTTPServer | None = None

    def attach_db(self, name: str, db, config: dict | None = None) -> None:
        """Register an externally-opened DB (a FollowerDB, a router's
        primary) so the HTTP layer serves its stats//replication views."""
        self._dbs[name] = db
        self._configs[name] = config or {}

    def attach_cluster(self, name: str, router) -> None:
        """Register a sharding.ShardRouter: GET /shards/<name> serves its
        status (shard map + per-shard epoch/fence/stall/traffic), POST
        /shards/<name>/{split,merge,migrate,balance} drive topology
        changes (tools/shard_admin.py is the CLI), and /metrics grows
        per-shard gauges."""
        self._clusters[name] = router

    def attach_fleet_supervisor(self, name: str, supervisor) -> None:
        """Register a sharding.FleetSupervisor: GET /fleet lists fleets,
        GET /fleet/<name> serves the fleet view — every supervised
        ShardServer process (holder/role/url/alive + its own
        /fleet/status document) merged with the lease coordinator's
        lease table (tools/fleet_admin.py is the per-process CLI)."""
        self._fleet_sups[name] = supervisor

    def attach_fleet_member(self, name: str, url: str) -> None:
        """Register a remote process for /cluster/health aggregation;
        `url` must serve a health document (utils/slo.health_doc shape,
        or a dcompact worker's bare /health)."""
        self._fleet.append((name, url))

    def open_db(self, config, name: str | None = None):
        """config: dict or JSON string: {"path": ..., "options": {...}}."""
        from toplingdb_tpu.db.db import DB

        if isinstance(config, str):
            config = json.loads(config)
        path = config["path"]
        name = name or config.get("name") or path
        cfg_opts = dict(config.get("options", {}))
        # The rockside role always exposes live metrics: repo-opened DBs
        # get a Statistics sink unless the config explicitly disables it
        # ({"statistics": false}).
        if cfg_opts.get("statistics", True) is False:
            cfg_opts.pop("statistics", None)
        else:
            cfg_opts.setdefault("statistics", "default")
        opts = options_from_config(cfg_opts)
        db = DB.open(path, opts)
        self._dbs[name] = db
        self._configs[name] = config
        return db

    def get_db(self, name: str):
        return self._dbs.get(name)

    def close_all(self) -> None:
        self.stop_http()
        for db in self._dbs.values():
            db.close()
        self._dbs.clear()

    # -- HTTP introspection --------------------------------------------

    def start_http(self, port: int = 0) -> int:
        """Serves /dbs, /stats/<name>, /levels/<name>, /config/<name>,
        /db/<name> (write-plane view: WAL_* + WRITE_GROUP_* counters,
        write.group.bytes histogram, async-WAL ring state),
        /replication/<name> (role/lag/applied-seq of the replication
        plane), /integrity/<name> (scrub progress, quarantined files,
        mismatch counters — the integrity plane's view), /store/<name>
        (disaggregated-SST-storage view: reference counts, cache tier,
        store.* tickers), and /metrics
        (Prometheus text format over every registered DB's Statistics —
        the rockside Prometheus role). POST /promote/<name> promotes a
        registered FollowerDB to a read-write primary in place
        (tools/repl_admin.py drives it); POST /scrub/<name> runs one
        integrity-scrub pass and returns its report. Returns the bound
        port."""
        repo = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send_json(self, code: int, body) -> None:
                data = json.dumps(body, indent=1, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                from urllib.parse import parse_qs, urlsplit

                split = urlsplit(self.path)
                query = {k: v[-1] for k, v in
                         parse_qs(split.query).items()}
                parts = [p for p in split.path.split("/") if p]
                if parts and parts[0] == "view":
                    # The rockside WebView role: a human-readable HTML
                    # dashboard over the same introspection routes.
                    try:
                        html = repo._render_view("/".join(parts[1:]))
                        code = 200 if html is not None else 404
                        data = (html or "<h1>not found</h1>").encode()
                    except Exception as e:
                        code, data = 500, repr(e).encode()
                    self.send_response(code)
                    self.send_header("Content-Type",
                                     "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return
                if parts and parts[0] == "metrics":
                    try:
                        out = []
                        for name, db in sorted(repo._dbs.items()):
                            if db.stats is not None:
                                out.append(db.stats.to_prometheus(
                                    labels=f'db="{name}"'))
                            out.append(_prometheus_gauges(name, db))
                        for name, cl in sorted(repo._clusters.items()):
                            out.append(
                                _prometheus_cluster_gauges(name, cl))
                            cs = getattr(cl, "stats", None)
                            if cs is not None:
                                out.append(cs.to_prometheus(
                                    labels=f'cluster="{name}"'))
                        if repo._fleet:
                            out.append(repo._fleet_gauges())
                        from toplingdb_tpu.utils import errors as _errs

                        out.append(
                            "# TYPE tpulsm_bg_error_swallowed_total gauge\n"
                            "tpulsm_bg_error_swallowed_total "
                            f"{_errs.swallowed_total()}\n")
                        data = "".join(out).encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/plain; version=0.0.4")
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                    except Exception as e:
                        self._send_json(500, {"error": repr(e)})
                    return
                try:
                    body = repo._route(parts, query)
                    code = 200 if body is not None else 404
                    body = body if body is not None else {"error": "not found"}
                except Exception as e:  # introspection must not crash
                    code, body = 500, {"error": repr(e)}
                self._send_json(code, body)

            def do_POST(self):
                # Online option change (the rockside online-config role):
                # POST /setoptions/<name> {"write_buffer_size": ...}
                parts = [p for p in self.path.split("/") if p]
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if parts and parts[0] == "setoptions":
                        db = repo._dbs.get("/".join(parts[1:]))
                        if db is None:
                            code, body = 404, {"error": "no such db"}
                        else:
                            db.set_options(payload)
                            code, body = 200, {"ok": True, "applied": payload}
                    elif parts and parts[0] == "promote":
                        name = "/".join(parts[1:])
                        code, body = repo._promote(name)
                    elif parts and parts[0] == "shards" \
                            and len(parts) >= 3:
                        # POST /shards/<cluster>/{split,merge,migrate,
                        # balance} — the sharding control plane.
                        code, body = repo._shard_action(
                            "/".join(parts[1:-1]), parts[-1], payload)
                    elif parts and parts[0] == "scrub":
                        # Trigger one synchronous integrity-scrub pass:
                        # POST /scrub/<name> [{"deep": true}]
                        db = repo._dbs.get("/".join(parts[1:]))
                        if db is None:
                            code, body = 404, {"error": "no such db"}
                        else:
                            rep = db.scrub(
                                deep=bool(payload.get("deep", False)))
                            code, body = 200, {"ok": True, "report": rep}
                    else:
                        code, body = 404, {"error": "not found"}
                except (InvalidArgument, ValueError) as e:  # client's fault
                    code, body = 400, {"error": repr(e)}
                except Exception as e:  # server-side failure
                    code, body = 500, {"error": repr(e)}
                self._send_json(code, body)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        ccy.spawn("sideplugin-http", self._server.serve_forever, owner=self,
                  stop=self.stop_http)
        return self._server.server_address[1]

    def stop_http(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None

    def _render_view(self, name: str):
        """HTML dashboard (the rockside WebView role): / lists DBs;
        /view/<name> shows stats, levels, and the live config with an
        online-options form posting to /setoptions/<name>."""
        import html as _html

        def esc(x):
            return _html.escape(str(x))

        if not name:
            rows = "".join(
                f'<li><a href="/view/{esc(n)}">{esc(n)}</a> '
                f'(<a href="/view/traces/{esc(n)}">traces</a>)</li>'
                for n in sorted(self._dbs))
            return (f"<html><head><title>toplingdb_tpu</title></head>"
                    f"<body><h1>toplingdb_tpu repo</h1><ul>{rows}</ul>"
                    f'<p><a href="/metrics">/metrics</a> (Prometheus) · '
                    f'<a href="/dbs">/dbs</a> (JSON)</p></body></html>')
        if name.startswith("traces/"):
            return self._render_traces_view(name[len("traces/"):])
        db = self._dbs.get(name)
        if db is None:
            return None
        levels = self._route(["levels", name]) or {}
        cfg = self._configs.get(name, {})
        stats_rows = ""
        if db.stats is not None:
            tickers = db.stats.tickers()
            top = sorted(tickers.items(), key=lambda kv: -kv[1])[:30]
            stats_rows = "".join(
                f"<tr><td>{esc(k)}</td><td>{v}</td></tr>"
                for k, v in top if v)
        lvl_rows = "".join(
            f"<tr><td>{esc(lv)}</td>"
            f"<td>{len(files)} files, "
            f"{sum(f['size'] for f in files)} bytes</td></tr>"
            for lv, files in sorted(levels.items()))
        return (
            f"<html><head><title>{esc(name)}</title></head><body>"
            f"<h1>{esc(name)}</h1>"
            f"<h2>Levels</h2><table border=1>{lvl_rows}</table>"
            f"<h2>Top tickers</h2><table border=1>{stats_rows}</table>"
            f"<h2>Config</h2><pre>{esc(json.dumps(cfg, indent=1, default=str))}"
            f"</pre>"
            f"<h2>Online options</h2>"
            f"<form onsubmit=\"fetch('/setoptions/{esc(name)}',"
            f"{{method:'POST',body:this.body.value}})"
            f".then(r=>r.json()).then(j=>alert(JSON.stringify(j)));"
            f"return false\">"
            f'<textarea name="body" rows="4" cols="60">'
            f'{{"write_buffer_size": 67108864}}</textarea><br>'
            f'<input type="submit" value="Apply"></form>'
            f'<p><a href="/view">&larr; all dbs</a></p></body></html>')

    def _render_traces_view(self, name: str):
        """Waterfall rendering of recent traces (slow first): one block per
        trace, one proportional bar per span, remote spans tinted — the
        human half of the /traces JSON routes."""
        import html as _html

        db = self._dbs.get(name)
        tracer = getattr(db, "tracer", None) if db is not None else None
        if db is None or tracer is None:
            return None

        def esc(x):
            return _html.escape(str(x))

        blocks = []
        traces = tracer.finished(limit=32)
        traces.sort(key=lambda t: (not t.slow, -t.dur_us))
        for t in traces:
            total = max(1, t.dur_us,
                        max((s.start_us + s.dur_us for s in t.spans),
                            default=1))
            bars = []
            for s in t.spans:
                left = 100.0 * s.start_us / total
                width = max(0.5, 100.0 * max(1, s.dur_us) / total)
                color = "#4a90d9" if s.proc == tracer.proc else "#d98a4a"
                label = (f"{esc(s.name)} [{esc(s.proc)}] "
                         f"{s.dur_us}µs {esc(s.tags) if s.tags else ''}")
                bars.append(
                    f'<div style="position:relative;height:14px;'
                    f'margin:1px 0;font-size:10px">'
                    f'<div title="{label}" style="position:absolute;'
                    f'left:{left:.2f}%;width:{width:.2f}%;height:12px;'
                    f'background:{color}"></div>'
                    f'<span style="position:absolute;left:0">{esc(s.name)}'
                    f'</span></div>')
            slow = " ⚠ slow" if t.slow else ""
            blocks.append(
                f'<div style="border:1px solid #ccc;margin:6px;padding:4px">'
                f'<b>{esc(t.name)}</b>{slow} — {t.dur_us}µs, '
                f'{len(t.spans)} spans, procs={esc(",".join(sorted({s.proc for s in t.spans})))} '
                f'(<a href="/traces/{esc(name)}/{esc(t.trace_id)}">json</a>)'
                f'{"".join(bars)}</div>')
        st = tracer.status()
        return (
            f"<html><head><title>traces: {esc(name)}</title></head><body>"
            f"<h1>traces: {esc(name)}</h1>"
            f"<p>sample 1-in-{st['sample_every'] or '∞'}, "
            f"slow ≥ {st['slow_usec']}µs, "
            f"{st['traces_retained']} retained / "
            f"{st['traces_started']} started</p>"
            f'{"".join(blocks) or "<p>no finished traces yet</p>"}'
            f'<p><a href="/view/{esc(name)}">&larr; {esc(name)}</a>'
            f"</p></body></html>")

    def _route(self, parts: list[str], query: dict | None = None):
        query = query or {}
        if not parts or parts == ["dbs"]:
            return {"dbs": sorted(self._dbs)}
        kind, name = parts[0], "/".join(parts[1:])
        if kind == "shards":
            # /shards (list clusters) and /shards/<name> (one router's
            # status: map + per-shard epoch/fence/stall/traffic rows).
            if not name:
                return {"clusters": sorted(self._clusters)}
            cl = self._clusters.get(name)
            if cl is None:
                return None
            out = cl.status()
            out["map"] = cl.map.to_config()
            return out
        if kind == "fleet":
            # /fleet (list fleets) and /fleet/<name> (one supervisor's
            # members + the lease coordinator's lease table).
            if not name:
                return {"fleets": sorted(self._fleet_sups)}
            sup = self._fleet_sups.get(name)
            if sup is None:
                return None
            out = sup.status()
            try:
                out["coordinator"] = sup.coordinator.status()
            except (Busy, IOError_, OSError) as e:
                out["coordinator_error"] = str(e)[:200]
            return out
        if kind == "traces":
            # /traces/<name> (recent traces; ?slow=1 filters),
            # /traces/<name>/<trace_id> (one trace as Chrome trace JSON).
            trace_id = None
            if len(parts) >= 3:
                name, trace_id = "/".join(parts[1:-1]), parts[-1]
                if self._dbs.get(name) is None:
                    name, trace_id = "/".join(parts[1:]), None
            db = self._dbs.get(name)
            tracer = getattr(db, "tracer", None) if db is not None else None
            if db is None or tracer is None:
                return None
            if trace_id is not None:
                return tracer.chrome_trace(trace_id)
            slow_only = query.get("slow") in ("1", "true")
            return {
                "tracer": tracer.status(),
                "traces": [t.summary()
                           for t in tracer.finished(slow_only=slow_only)],
            }
        if kind == "stats_history":
            # /stats_history/<name>?window=SECONDS (0/absent = everything
            # retained in the ring).
            db = self._dbs.get(name)
            if db is None or getattr(db, "stats_history", None) is None:
                return None
            import time as _time

            start = 0
            try:
                window = int(query.get("window", 0))
            except ValueError:
                window = 0
            if window > 0:
                start = int(_time.time()) - window
            samples = db.stats_history.series(start_time=start)
            return {
                "window_sec": window or None,
                "n_samples": len(samples),
                "samples": samples,
            }
        if kind == "cluster" and name == "health":
            # The fleet view: every registered DB's local health doc +
            # every attach_fleet_member() remote, merged into one table.
            return self._cluster_health()
        if kind == "slo":
            # /slo/<name>: the SLO engine's burn-rate rows;
            # ?evaluate=1 forces one evaluation pass first (ops/tests).
            db = self._dbs.get(name)
            engine = getattr(db, "slo_engine", None) \
                if db is not None else None
            if engine is None:
                return None
            if query.get("evaluate") in ("1", "true"):
                engine.evaluate()
            return engine.status()
        if kind == "health":
            # /health/<name>: this member's aggregator health doc — what
            # a sibling repo's /cluster/health scrapes.
            db = self._dbs.get(name)
            if db is None:
                return None
            from toplingdb_tpu.utils.slo import health_doc

            return health_doc(db, name, role=self._role_of(db))
        db = self._dbs.get(name)
        if db is None:
            return None
        if kind == "stats":
            out = {"levelstats": db.get_property("tpulsm.stats")}
            if db.stats is not None:
                out["statistics"] = db.stats.to_string().split("\n")
            return out
        if kind == "levels":
            v = db.versions.current
            return {
                f"L{lvl}": [
                    {"file": f.number, "size": f.file_size,
                     "entries": f.num_entries}
                    for f in v.files[lvl]
                ]
                for lvl in range(v.num_levels) if v.files[lvl]
            }
        if kind == "config":
            return self._configs.get(name)
        if kind == "replication":
            provider = getattr(db, "_repl_status_provider", None)
            if provider is not None:
                out = dict(provider())
            else:
                out = {
                    "role": ("standalone-readonly"
                             if getattr(db.options, "read_only", False)
                             else "primary-unshipped"),
                }
            out.setdefault("last_sequence", db.versions.last_sequence)
            return out
        if kind == "db":
            # Write-plane view: WAL_* counters with the WRITE_GROUP_*
            # family beside them (groups led, followers merged, native
            # plane commits vs fallbacks, coalesced fsyncs) plus the
            # write.group.bytes histogram and the plane's live config.
            out = {
                "write_plane_enabled": bool(
                    getattr(db, "_write_plane_knob", False)),
                "write_plane_resolved": bool(
                    getattr(db, "_write_plane", None)),
                "async_wal": getattr(db, "_wal_ring", None) is not None,
                "last_sequence": db.versions.last_sequence,
            }
            ring = getattr(db, "_wal_ring", None)
            if ring is not None:
                out["async_wal_ring"] = {
                    "appends": ring.appends, "syncs": ring.syncs,
                    "fsyncs": ring.fsyncs,
                    "fsyncs_coalesced": ring.fsyncs_coalesced,
                }
            if db.stats is not None:
                from toplingdb_tpu.utils import statistics as _st

                t = db.stats.tickers()
                out["tickers"] = {
                    k: t.get(k, 0)
                    for k in (_st.WAL_BYTES, _st.WAL_SYNCS,
                              _st.WRITE_WITH_WAL,
                              _st.WRITE_GROUP_LED,
                              _st.WRITE_GROUP_FOLLOWERS,
                              _st.WRITE_GROUP_NATIVE_COMMITS,
                              _st.WRITE_GROUP_FALLBACKS,
                              _st.WRITE_GROUP_FSYNCS_COALESCED)
                }
                h = db.stats.get_histogram(_st.WRITE_GROUP_BYTES)
                out["write_group_bytes"] = {
                    "count": h.count, "avg": round(h.average, 1),
                    "p99": h.percentile(99),
                }
            return out
        if kind == "integrity":
            # Scrub progress + quarantine + mismatch counters (mirrors the
            # /replication view pattern; POST /scrub/<name> runs a pass).
            out = dict(db.scrub_status())
            out["protection_bytes_per_key"] = getattr(
                db.options, "protection_bytes_per_key", 0)
            out["file_checksum"] = getattr(db.options, "file_checksum",
                                           None)
            if db.stats is not None:
                from toplingdb_tpu.utils import statistics as _st

                t = db.stats.tickers()
                out["tickers"] = {
                    k: t.get(k, 0)
                    for k in (_st.INTEGRITY_SCRUB_PASSES,
                              _st.INTEGRITY_BYTES_VERIFIED,
                              _st.INTEGRITY_CORRUPTIONS_DETECTED,
                              _st.INTEGRITY_PROTECTION_MISMATCHES)
                }
            return out
        if kind == "store":
            # Disaggregated-SST-storage view (toplingdb_tpu/storage/):
            # per-directory reference counts, cache-tier stats, backend
            # status, and the store.* ticker block.
            if not hasattr(db.env, "publish_sst"):
                return {"enabled": False}
            out = {"enabled": True}
            out.update(db.env.status())
            if db.stats is not None:
                from toplingdb_tpu.utils import statistics as _st

                t = db.stats.tickers()
                out["tickers"] = {
                    k: t.get(k, 0)
                    for k in (_st.STORE_HITS, _st.STORE_MISSES,
                              _st.STORE_PUBLISHES,
                              _st.STORE_BYTES_FETCHED,
                              _st.STORE_GC_SWEPT,
                              _st.STORE_FETCH_RETRIES)
                }
            return out
        return None

    @staticmethod
    def _role_of(db) -> str:
        """Role for a local DB's health doc: whatever the replication
        plane reports, else primary/readonly."""
        provider = getattr(db, "_repl_status_provider", None)
        if provider is not None:
            try:
                return str(provider().get("role", "primary"))
            except Exception as e:
                _errors.swallow(reason="repl-role-probe", exc=e)
        return ("standalone-readonly"
                if getattr(db.options, "read_only", False) else "primary")

    def _fleet_gauges(self) -> str:
        """Registry-size gauges for /metrics. Reachability reflects the
        LAST /cluster/health collection — a scrape must not itself probe
        the fleet."""
        lines = []

        def g(metric, value):
            m = f"tpulsm_{metric}"
            lines.append(f"# TYPE {m} gauge")
            lines.append(f'{m}{{repo="fleet"}} {value}')

        g("fleet_members", len(self._fleet))
        g("fleet_members_unreachable", len(self._fleet_last_errors))
        return "\n".join(lines) + "\n"

    def _cluster_health(self) -> dict:
        """GET /cluster/health: local DBs' health docs + remote fleet
        members, merged by tools/fleet_health.py; per-cluster shard
        health rows ride along so one page answers 'which shard'."""
        from toplingdb_tpu.tools.fleet_health import FleetHealthAggregator
        from toplingdb_tpu.utils.slo import health_doc

        docs = [health_doc(db, name, role=self._role_of(db))
                for name, db in sorted(self._dbs.items())]
        agg = FleetHealthAggregator(self._fleet,
                                    timeout=self._fleet_timeout)
        remote_docs, errors = agg.collect()
        self._fleet_last_errors = errors
        out = FleetHealthAggregator.summarize(docs + remote_docs, errors)
        clusters = {}
        for cname, cl in sorted(self._clusters.items()):
            try:
                rows = [
                    {"name": r["name"], "health": r.get("health"),
                     "stall": r.get("stall"),
                     "slo_firing": r.get("slo_firing"),
                     "last_alert": r.get("last_slo_alert")}
                    for r in cl.status()["shards"]
                ]
                clusters[cname] = {"shards": rows}
            except Exception as e:
                clusters[cname] = {"error": repr(e)}
        if clusters:
            out["clusters"] = clusters
        return out

    @staticmethod
    def _payload_key(payload: dict, field: str = "split_key") -> bytes:
        """A key from JSON: `<field>` (utf-8 string) or `<field>_hex`."""
        if payload.get(f"{field}_hex"):
            return bytes.fromhex(payload[f"{field}_hex"])
        v = payload.get(field)
        if not isinstance(v, str) or not v:
            raise InvalidArgument(f"need {field!r} or {field}_hex")
        return v.encode()

    def _shard_action(self, name: str, action: str, payload: dict):
        """The sharding control plane behind POST /shards/<name>/<action>:
        split {"shard", "split_key"|"split_key_hex"}, merge {"left",
        "right"}, migrate {"shard", "dest"} (synchronous: replies when the
        cutover finished or the migration aborted), balance {} (one
        ShardBalancer pass)."""
        cl = self._clusters.get(name)
        if cl is None:
            return 404, {"error": "no such cluster"}
        if action == "split":
            shard = payload.get("shard")
            if not shard:
                raise InvalidArgument("split needs 'shard'")
            left, right = cl.split_shard(shard, self._payload_key(payload))
            return 200, {"ok": True, "left": left.to_config(),
                         "right": right.to_config()}
        if action == "merge":
            left, right = payload.get("left"), payload.get("right")
            if not left or not right:
                raise InvalidArgument("merge needs 'left' and 'right'")
            orphan = cl.merge_shards(left, right)
            if orphan is not None:
                # Cross-backend merge: the copied-out stack is done
                # serving; retire it here rather than leak it.
                for db in [*orphan.followers, orphan.primary]:
                    try:
                        db.close()
                    except Exception as e:
                        _errors.swallow(reason="merge-retire-close", exc=e)
            return 200, {"ok": True,
                         "merged": cl.map.get(left).to_config()}
        if action == "migrate":
            from toplingdb_tpu.sharding.migration import (
                MigrationAborted, ShardMigration,
            )

            shard, dest = payload.get("shard"), payload.get("dest")
            if not shard or not dest:
                raise InvalidArgument("migrate needs 'shard' and 'dest'")
            try:
                out = ShardMigration(cl, shard, dest).run()
            except MigrationAborted as e:
                return 500, {"error": f"migration aborted: {e}"}
            return 200, {"ok": True, "migration": out}
        if action == "balance":
            from toplingdb_tpu.sharding.balancer import (
                BalancerOptions, ShardBalancer,
            )

            kw = {k: int(v) for k, v in payload.items()
                  if k in ("split_bytes", "split_writes", "merge_bytes",
                           "max_shards", "min_shards")}
            actions = ShardBalancer(cl, BalancerOptions(**kw)).run_once()
            return 200, {"ok": True, "actions": actions}
        return 404, {"error": f"unknown shard action {action!r}"}

    def _promote(self, name: str):
        """Promote a registered FollowerDB: detach it from the (dead)
        primary and reopen its directory read-write under the same name —
        the failover half of the replication plane."""
        db = self._dbs.get(name)
        if db is None:
            return 404, {"error": "no such db"}
        promote = getattr(db, "promote", None)
        if promote is None:
            return 400, {"error": f"{name} is not a follower"}
        from toplingdb_tpu.db.db import DB
        from toplingdb_tpu.options import Options

        path = promote()  # final catch-up + close; returns the directory
        opts_cfg = dict(self._configs.get(name, {}).get("options", {}))
        opts_cfg.pop("read_only", None)
        opts = options_from_config(opts_cfg) if opts_cfg else Options()
        opts.create_if_missing = False
        opts.read_only = False
        new_db = DB.open(path, opts, env=db.env)
        self._dbs[name] = new_db
        new_db.event_logger.log("promote_finished", name=name, path=path,
                                last_sequence=new_db.versions.last_sequence)
        return 200, {"promoted": name, "path": path,
                     "last_sequence": new_db.versions.last_sequence}
