"""Profile harness for the headline compaction path (host-sort fallback).
Not part of the package; repo-root scratch tool."""
import os
import sys
import tempfile
import time

os.environ["TPULSM_HOST_SORT"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import bench as B
from toplingdb_tpu.db.dbformat import InternalKeyComparator
from toplingdb_tpu.env import default_env
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.utils import codecs

n = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
runs = int(sys.argv[2]) if len(sys.argv) > 2 else 2
comp = sys.argv[3] if len(sys.argv) > 3 else "snappy"

icmp = InternalKeyComparator()
env = default_env()
base = tempfile.mkdtemp(prefix="prof_", dir="/dev/shm")
codec = fmt.SNAPPY_COMPRESSION if comp == "snappy" and codecs.available(
    "snappy") else fmt.NO_COMPRESSION
topts = TableOptions(block_size=4096, compression=codec)
t0 = time.time()
metas = B.build_inputs(env, base, icmp, n, topts)
print(f"input_build: {time.time()-t0:.2f}s", flush=True)
dt, stats, fbytes, rts = B.time_compaction(
    env, base, icmp, metas, topts, topts, "tpu", runs, 1000)
raw = 28 * n
print(f"comp={comp} n={n} wall={dt:.3f} run_times={rts} "
      f"MBps={raw/dt/1e6:.1f}")
print("phases:", stats.phase_dict())
import shutil
shutil.rmtree(base, ignore_errors=True)
