package org.toplingdb;

/** Atomic update batch (reference org.rocksdb.WriteBatch over
 *  rocksdb_writebatch_*). */
public class WriteBatch implements AutoCloseable {
    private long handle;

    public WriteBatch() {
        handle = createNative();
    }

    public void put(byte[] key, byte[] value) throws TpuLsmException {
        check();
        putNative(handle, key, value);
    }

    public void delete(byte[] key) throws TpuLsmException {
        check();
        deleteNative(handle, key);
    }

    public void merge(byte[] key, byte[] value) throws TpuLsmException {
        check();
        mergeNative(handle, key, value);
    }

    public void deleteRange(byte[] begin, byte[] end)
            throws TpuLsmException {
        check();
        deleteRangeNative(handle, begin, end);
    }

    public void clear() throws TpuLsmException {
        check();
        clearNative(handle);
    }

    public int count() throws TpuLsmException {
        check();
        return countNative(handle);
    }

    long handle() throws TpuLsmException {
        check();
        return handle;
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            destroyNative(handle);
            handle = 0;
        }
    }

    private void check() throws TpuLsmException {
        if (handle == 0) {
            throw new TpuLsmException("write batch is closed");
        }
    }

    private static native long createNative();

    private static native void destroyNative(long h);

    private static native void putNative(long h, byte[] k, byte[] v)
            throws TpuLsmException;

    private static native void deleteNative(long h, byte[] k)
            throws TpuLsmException;

    private static native void mergeNative(long h, byte[] k, byte[] v)
            throws TpuLsmException;

    private static native void deleteRangeNative(long h, byte[] b, byte[] e)
            throws TpuLsmException;

    private static native void clearNative(long h);

    private static native int countNative(long h);
}
