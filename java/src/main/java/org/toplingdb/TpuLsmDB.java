package org.toplingdb;

/**
 * Java binding of the toplingdb_tpu engine — the RocksJava role
 * (reference java/src/main/java/org/rocksdb/RocksDB.java) over the flat C
 * ABI in toplingdb_tpu/bindings/c (reference db/c.cc), via the JNI glue in
 * java/jni/tpulsm_jni.c.
 *
 * Usage:
 *   try (TpuLsmDB db = TpuLsmDB.open("/data/db", true)) {
 *       db.put(key, value);
 *       byte[] v = db.get(key);
 *   }
 *
 * The engine embeds a Python interpreter (the C ABI handles
 * initialization); the JVM process needs PYTHONPATH to reach the
 * toplingdb_tpu package, and java.library.path must contain
 * libtpulsm_jni.so + libtpulsm_c.so.
 */
public class TpuLsmDB implements AutoCloseable {
    static {
        System.loadLibrary("tpulsm_jni");
        initEngine();
    }

    private long handle;

    private TpuLsmDB(long handle) {
        this.handle = handle;
    }

    /** Open (and optionally create) a database at {@code path}. */
    public static TpuLsmDB open(String path, boolean createIfMissing)
            throws TpuLsmException {
        long h = openNative(path, createIfMissing);
        return new TpuLsmDB(h);
    }

    public void put(byte[] key, byte[] value) throws TpuLsmException {
        checkOpen();
        putNative(handle, key, value);
    }

    /** @return the value, or null when the key is absent. */
    public byte[] get(byte[] key) throws TpuLsmException {
        checkOpen();
        return getNative(handle, key);
    }

    public void delete(byte[] key) throws TpuLsmException {
        checkOpen();
        deleteNative(handle, key);
    }

    /** Merge-operator operand append (reference RocksDB#merge). */
    public void merge(byte[] key, byte[] value) throws TpuLsmException {
        checkOpen();
        mergeNative(handle, key, value);
    }

    /** Delete every key in [begin, end) (reference deleteRange). */
    public void deleteRange(byte[] begin, byte[] end) throws TpuLsmException {
        checkOpen();
        deleteRangeNative(handle, begin, end);
    }

    /** Consistent point-in-time read view (reference Snapshot). */
    public Snapshot getSnapshot() throws TpuLsmException {
        checkOpen();
        return new Snapshot(snapshotNative(handle));
    }

    /** Read at a snapshot; null when absent. */
    public byte[] get(byte[] key, Snapshot snapshot) throws TpuLsmException {
        checkOpen();
        return getAtSnapshotNative(handle, snapshot.handle(), key);
    }

    /** Batched point lookups (reference RocksDB.multiGetAsList): a null
     *  element marks a missing key. */
    public java.util.List<byte[]> multiGetAsList(java.util.List<byte[]> keys)
            throws TpuLsmException {
        checkOpen();
        java.util.ArrayList<byte[]> out =
                new java.util.ArrayList<byte[]>(keys.size());
        for (byte[] k : keys) {
            out.add(getNative(handle, k));
        }
        return out;
    }

    /** True when the key exists (reference RocksDB.keyExists role). */
    public boolean keyExists(byte[] key) throws TpuLsmException {
        return get(key) != null;
    }

    /** Hard-link consistent checkpoint (reference Checkpoint). */
    public void createCheckpoint(String destDir) throws TpuLsmException {
        checkOpen();
        checkpointNative(handle, destDir);
    }

    /** Atomically apply a batch of updates. */
    public void write(WriteBatch batch) throws TpuLsmException {
        checkOpen();
        writeNative(handle, batch.handle());
    }

    public void flush() throws TpuLsmException {
        checkOpen();
        flushNative(handle);
    }

    public void compactRange() throws TpuLsmException {
        checkOpen();
        compactRangeNative(handle);
    }

    /** Engine property (e.g. "tpulsm.stats"), or null when unknown. */
    public String getProperty(String name) {
        if (handle == 0) {
            return null;
        }
        return propertyNative(handle, name);
    }

    public TpuLsmIterator newIterator() throws TpuLsmException {
        checkOpen();
        return new TpuLsmIterator(iteratorNative(handle));
    }

    // -- column families (reference RocksDB#createColumnFamily etc.) ----

    public ColumnFamilyHandle createColumnFamily(String name)
            throws TpuLsmException {
        checkOpen();
        return new ColumnFamilyHandle(createColumnFamilyNative(handle, name));
    }

    /** Handle to an existing family by name. */
    public ColumnFamilyHandle getColumnFamilyHandle(String name)
            throws TpuLsmException {
        checkOpen();
        return new ColumnFamilyHandle(columnFamilyHandleNative(handle, name));
    }

    public void dropColumnFamily(ColumnFamilyHandle cf)
            throws TpuLsmException {
        checkOpen();
        dropColumnFamilyNative(handle, cf.handle);
    }

    public void put(ColumnFamilyHandle cf, byte[] key, byte[] value)
            throws TpuLsmException {
        checkOpen();
        putCfNative(handle, cf.handle, key, value);
    }

    public byte[] get(ColumnFamilyHandle cf, byte[] key)
            throws TpuLsmException {
        checkOpen();
        return getCfNative(handle, cf.handle, key);
    }

    public void delete(ColumnFamilyHandle cf, byte[] key)
            throws TpuLsmException {
        checkOpen();
        deleteCfNative(handle, cf.handle, key);
    }

    /** Ingest an externally built SST (see {@link SstFileWriter}). */
    public void ingestExternalFile(String path) throws TpuLsmException {
        checkOpen();
        ingestExternalFileNative(handle, path);
    }

    /** For sibling bindings (BackupEngine) only. */
    long handleForInternalUse() {
        return handle;
    }

    /** For SidePluginRepo only: wrap a repo-owned native handle. */
    static TpuLsmDB fromHandleForInternalUse(long h) {
        return new TpuLsmDB(h);
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            closeNative(handle);
            handle = 0;
        }
    }

    private void checkOpen() throws TpuLsmException {
        if (handle == 0) {
            throw new TpuLsmException("database is closed");
        }
    }

    private static native void initEngine();

    private static native long openNative(String path, boolean create)
            throws TpuLsmException;

    private static native void closeNative(long h);

    private static native void putNative(long h, byte[] k, byte[] v)
            throws TpuLsmException;

    private static native byte[] getNative(long h, byte[] k)
            throws TpuLsmException;

    private static native void deleteNative(long h, byte[] k)
            throws TpuLsmException;

    private static native void writeNative(long h, long wb)
            throws TpuLsmException;

    private static native void flushNative(long h) throws TpuLsmException;

    private static native void compactRangeNative(long h)
            throws TpuLsmException;

    private static native String propertyNative(long h, String name);

    private static native long iteratorNative(long h) throws TpuLsmException;

    private static native void mergeNative(long h, byte[] k, byte[] v)
            throws TpuLsmException;

    private static native void deleteRangeNative(long h, byte[] b, byte[] e)
            throws TpuLsmException;

    private static native long snapshotNative(long h) throws TpuLsmException;

    static native void releaseSnapshotNative(long snap);

    private static native byte[] getAtSnapshotNative(long h, long snap,
            byte[] k) throws TpuLsmException;

    private static native long createColumnFamilyNative(long h, String name)
            throws TpuLsmException;

    private static native long columnFamilyHandleNative(long h, String name)
            throws TpuLsmException;

    private static native void dropColumnFamilyNative(long h, long cf)
            throws TpuLsmException;

    private static native void putCfNative(long h, long cf, byte[] k,
                                           byte[] v) throws TpuLsmException;

    private static native byte[] getCfNative(long h, long cf, byte[] k)
            throws TpuLsmException;

    private static native void deleteCfNative(long h, long cf, byte[] k)
            throws TpuLsmException;

    private static native void ingestExternalFileNative(long h, String path)
            throws TpuLsmException;

    private static native void checkpointNative(long h, String dest)
            throws TpuLsmException;
}
