package org.toplingdb;

/**
 * Incremental backup engine (reference
 * java/src/main/java/org/rocksdb/BackupEngine.java over our
 * utilities.backup_engine): create/restore/count/purge.
 */
public class BackupEngine implements AutoCloseable {
    static {
        System.loadLibrary("tpulsm_jni");
    }

    private long handle;

    private BackupEngine(long handle) {
        this.handle = handle;
    }

    public static BackupEngine open(String backupDir)
            throws TpuLsmException {
        return new BackupEngine(openNative(backupDir));
    }

    /** @return the new backup's id (&gt; 0). */
    public int createBackup(TpuLsmDB db) throws TpuLsmException {
        checkOpen();
        return createBackupNative(handle, db.handleForInternalUse());
    }

    public int backupCount() throws TpuLsmException {
        checkOpen();
        return countNative(handle);
    }

    public void restore(int backupId, String destDir)
            throws TpuLsmException {
        checkOpen();
        restoreNative(handle, backupId, destDir);
    }

    public void purgeOldBackups(int keep) throws TpuLsmException {
        checkOpen();
        purgeOldNative(handle, keep);
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            closeNative(handle);
            handle = 0;
        }
    }

    private void checkOpen() throws TpuLsmException {
        if (handle == 0) {
            throw new TpuLsmException("backup engine is closed");
        }
    }

    private static native long openNative(String dir)
            throws TpuLsmException;

    private static native void closeNative(long h);

    private static native int createBackupNative(long h, long db)
            throws TpuLsmException;

    private static native int countNative(long h);

    private static native void restoreNative(long h, int id, String dest)
            throws TpuLsmException;

    private static native void purgeOldNative(long h, int keep)
            throws TpuLsmException;
}
