package org.toplingdb;

/** A consistent read view pinned until {@link #close()} (the reference
 * RocksDB Snapshot role; backed by tpulsm_create_snapshot). */
public final class Snapshot implements AutoCloseable {
    private long handle;

    Snapshot(long handle) {
        this.handle = handle;
    }

    long handle() {
        return handle;
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            TpuLsmDB.releaseSnapshotNative(handle);
            handle = 0;
        }
    }
}
