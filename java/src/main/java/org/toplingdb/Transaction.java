package org.toplingdb;

/**
 * Pessimistic transaction (reference
 * java/src/main/java/org/rocksdb/Transaction.java): point ops acquire
 * locks in the owning {@link TransactionDB}; commit/rollback end it.
 */
public class Transaction implements AutoCloseable {
    private long handle;

    Transaction(long handle) {
        this.handle = handle;
    }

    public void put(byte[] key, byte[] value) throws TpuLsmException {
        checkOpen();
        putNative(handle, key, value);
    }

    /** Read-your-writes get through the transaction. */
    public byte[] get(byte[] key) throws TpuLsmException {
        checkOpen();
        return getNative(handle, key);
    }

    public void delete(byte[] key) throws TpuLsmException {
        checkOpen();
        deleteNative(handle, key);
    }

    public void commit() throws TpuLsmException {
        checkOpen();
        commitNative(handle);
    }

    public void rollback() throws TpuLsmException {
        checkOpen();
        rollbackNative(handle);
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            destroyNative(handle);
            handle = 0;
        }
    }

    private void checkOpen() throws TpuLsmException {
        if (handle == 0) {
            throw new TpuLsmException("transaction is closed");
        }
    }

    private static native void putNative(long h, byte[] k, byte[] v)
            throws TpuLsmException;

    private static native byte[] getNative(long h, byte[] k)
            throws TpuLsmException;

    private static native void deleteNative(long h, byte[] k)
            throws TpuLsmException;

    private static native void commitNative(long h) throws TpuLsmException;

    private static native void rollbackNative(long h)
            throws TpuLsmException;

    private static native void destroyNative(long h);
}
