package org.toplingdb;

/**
 * Builds an external SST for ingestion (reference
 * java/src/main/java/org/rocksdb/SstFileWriter.java): put keys in sorted
 * order, finish, then {@link TpuLsmDB#ingestExternalFile}.
 */
public class SstFileWriter implements AutoCloseable {
    static {
        System.loadLibrary("tpulsm_jni");
    }

    private long handle;

    private SstFileWriter(long handle) {
        this.handle = handle;
    }

    public static SstFileWriter create(String path) throws TpuLsmException {
        return new SstFileWriter(createNative(path));
    }

    /** Keys must arrive in ascending order. */
    public void put(byte[] key, byte[] value) throws TpuLsmException {
        checkOpen();
        putNative(handle, key, value);
    }

    public void finish() throws TpuLsmException {
        checkOpen();
        finishNative(handle);
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            destroyNative(handle);
            handle = 0;
        }
    }

    private void checkOpen() throws TpuLsmException {
        if (handle == 0) {
            throw new TpuLsmException("sst file writer is closed");
        }
    }

    private static native long createNative(String path)
            throws TpuLsmException;

    private static native void putNative(long h, byte[] k, byte[] v)
            throws TpuLsmException;

    private static native void finishNative(long h) throws TpuLsmException;

    private static native void destroyNative(long h);
}
