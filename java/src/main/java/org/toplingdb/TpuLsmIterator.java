package org.toplingdb;

/** Ordered cursor over the database (reference org.rocksdb.RocksIterator
 *  over rocksdb_iter_*). Obtain via {@link TpuLsmDB#newIterator()}. */
public class TpuLsmIterator implements AutoCloseable {
    private long handle;

    TpuLsmIterator(long handle) {
        this.handle = handle;
    }

    public void seekToFirst() {
        seekToFirstNative(handle);
    }

    public void seekToLast() {
        seekToLastNative(handle);
    }

    public void seek(byte[] target) {
        seekNative(handle, target);
    }

    public boolean isValid() {
        return handle != 0 && validNative(handle);
    }

    public void next() {
        nextNative(handle);
    }

    public void prev() {
        prevNative(handle);
    }

    public byte[] key() {
        return keyNative(handle);
    }

    public byte[] value() {
        return valueNative(handle);
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            destroyNative(handle);
            handle = 0;
        }
    }

    private static native void destroyNative(long h);

    private static native void seekToFirstNative(long h);

    private static native void seekToLastNative(long h);

    private static native void seekNative(long h, byte[] target);

    private static native boolean validNative(long h);

    private static native void nextNative(long h);

    private static native void prevNative(long h);

    private static native byte[] keyNative(long h);

    private static native byte[] valueNative(long h);
}
