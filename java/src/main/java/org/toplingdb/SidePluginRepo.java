package org.toplingdb;

/**
 * Open DBs from a JSON config document and serve HTTP introspection —
 * the reference's Topling SidePluginRepo
 * (java/src/main/java/org/rocksdb/SidePluginRepo.java:10-104):
 *
 * <pre>
 *   SidePluginRepo repo = SidePluginRepo.create();
 *   TpuLsmDB db = repo.openDB(
 *       "{\"path\": \"/data/db\", \"name\": \"main\", "
 *       + "\"options\": {\"create_if_missing\": true}}");
 *   int port = repo.startHttp(0);   // /dbs /stats/<n> /levels/<n> /metrics
 *   ...
 *   repo.closeAll();
 * </pre>
 */
public class SidePluginRepo implements AutoCloseable {
    static {
        System.loadLibrary("tpulsm_jni");
    }

    private long handle;

    private SidePluginRepo(long handle) {
        this.handle = handle;
    }

    public static SidePluginRepo create() throws TpuLsmException {
        return new SidePluginRepo(createNative());
    }

    /** configJson: {"path": ..., "name": ..., "options": {...}} */
    public TpuLsmDB openDB(String configJson) throws TpuLsmException {
        checkOpen();
        return TpuLsmDB.fromHandleForInternalUse(
            openDBNative(handle, configJson));
    }

    /** @return the bound port (pass 0 to auto-pick). */
    public int startHttp(int port) throws TpuLsmException {
        checkOpen();
        return startHttpNative(handle, port);
    }

    public void stopHttp() throws TpuLsmException {
        checkOpen();
        stopHttpNative(handle);
    }

    /** Stops HTTP and closes every DB this repo opened. */
    public synchronized void closeAll() {
        if (handle != 0) {
            closeAllNative(handle);
            handle = 0;
        }
    }

    @Override
    public void close() {
        closeAll();
    }

    private void checkOpen() throws TpuLsmException {
        if (handle == 0) {
            throw new TpuLsmException("repo is closed");
        }
    }

    private static native long createNative() throws TpuLsmException;

    private static native long openDBNative(long h, String json)
            throws TpuLsmException;

    private static native int startHttpNative(long h, int port)
            throws TpuLsmException;

    private static native void stopHttpNative(long h);

    private static native void closeAllNative(long h);
}
