package org.toplingdb;

/** Engine error surfaced through the C ABI's errptr convention (the role
 *  of the reference's org.rocksdb.RocksDBException). */
public class TpuLsmException extends Exception {
    public TpuLsmException(String msg) {
        super(msg);
    }
}
