package org.toplingdb;

/**
 * Transactional database (reference
 * java/src/main/java/org/rocksdb/TransactionDB.java over our
 * utilities.transactions engine): open, begin transactions, committed
 * reads.
 */
public class TransactionDB implements AutoCloseable {
    static {
        System.loadLibrary("tpulsm_jni");
    }

    private long handle;

    private TransactionDB(long handle) {
        this.handle = handle;
    }

    public static TransactionDB open(String path, boolean createIfMissing)
            throws TpuLsmException {
        return new TransactionDB(openNative(path, createIfMissing));
    }

    public Transaction beginTransaction() throws TpuLsmException {
        checkOpen();
        return new Transaction(beginNative(handle));
    }

    /** Committed-state read (outside any transaction). */
    public byte[] get(byte[] key) throws TpuLsmException {
        checkOpen();
        return getNative(handle, key);
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            closeNative(handle);
            handle = 0;
        }
    }

    private void checkOpen() throws TpuLsmException {
        if (handle == 0) {
            throw new TpuLsmException("transaction db is closed");
        }
    }

    private static native long openNative(String path, boolean create)
            throws TpuLsmException;

    private static native void closeNative(long h);

    private static native long beginNative(long h) throws TpuLsmException;

    private static native byte[] getNative(long h, byte[] k)
            throws TpuLsmException;
}
