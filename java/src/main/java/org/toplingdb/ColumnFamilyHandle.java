package org.toplingdb;

/**
 * Handle to one column family (reference
 * java/src/main/java/org/rocksdb/ColumnFamilyHandle.java). Obtained from
 * {@link TpuLsmDB#createColumnFamily} or
 * {@link TpuLsmDB#getColumnFamilyHandle}; close() releases only the
 * handle, not the family.
 */
public class ColumnFamilyHandle implements AutoCloseable {
    long handle;

    ColumnFamilyHandle(long handle) {
        this.handle = handle;
    }

    @Override
    public synchronized void close() {
        if (handle != 0) {
            destroyNative(handle);
            handle = 0;
        }
    }

    private static native void destroyNative(long h);
}
