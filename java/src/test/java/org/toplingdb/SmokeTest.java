package org.toplingdb;

/** End-to-end smoke test (run by java/Makefile's `make test` and the
 *  pytest gate). Prints JAVA-API-OK and exits 0 on success. */
public final class SmokeTest {
    private SmokeTest() { }

    public static void main(String[] args) throws Exception {
        String path = args.length > 0 ? args[0] : "/tmp/tpulsm_java_smoke";
        try (TpuLsmDB db = TpuLsmDB.open(path, true)) {
            db.put(b("hello"), b("world"));
            expect(eq(db.get(b("hello")), b("world")), "get");
            expect(db.get(b("missing")) == null, "missing get");
            db.delete(b("hello"));
            expect(db.get(b("hello")) == null, "delete");

            try (WriteBatch wb = new WriteBatch()) {
                wb.put(b("a"), b("1"));
                wb.put(b("b"), b("2"));
                wb.delete(b("a"));
                db.write(wb);
            }
            expect(db.get(b("a")) == null, "batch delete");
            expect(eq(db.get(b("b")), b("2")), "batch put");

            db.put(b("c"), b("3"));
            int n = 0;
            try (TpuLsmIterator it = db.newIterator()) {
                for (it.seekToFirst(); it.isValid(); it.next()) {
                    expect(it.key() != null && it.value() != null,
                           "iter kv");
                    n++;
                }
            }
            expect(n == 2, "iterator count " + n);
            expect(db.getProperty("tpulsm.estimate-num-keys") != null,
                   "property");
            db.flush();
        }
        try (TpuLsmDB db = TpuLsmDB.open(path, false)) {
            expect(eq(db.get(b("b")), b("2")), "durability");
        }
        System.out.println("JAVA-API-OK");
    }

    private static byte[] b(String s) {
        return s.getBytes(java.nio.charset.StandardCharsets.UTF_8);
    }

    private static boolean eq(byte[] x, byte[] y) {
        return java.util.Arrays.equals(x, y);
    }

    private static void expect(boolean ok, String what) {
        if (!ok) {
            System.err.println("FAIL: " + what);
            System.exit(1);
        }
    }
}
