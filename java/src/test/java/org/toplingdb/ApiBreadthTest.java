package org.toplingdb;

/**
 * Breadth test over every surface the C ABI exposes (VERDICT r03 item 9):
 * column families, transactions, backup engine, checkpoint, external SST
 * ingest, and the SidePluginRepo open-from-JSON-config flow (reference
 * java/src/main/java/org/rocksdb/SidePluginRepo.java:10-104). Run by
 * java/Makefile `make test-breadth`; prints JAVA-BREADTH-OK on success.
 */
public final class ApiBreadthTest {
    private ApiBreadthTest() { }

    public static void main(String[] args) throws Exception {
        String base = args.length > 0 ? args[0] : "/tmp/tpulsm_java_breadth";

        // -- column families --------------------------------------------
        try (TpuLsmDB db = TpuLsmDB.open(base + "/cfdb", true)) {
            try (ColumnFamilyHandle cf = db.createColumnFamily("meta")) {
                db.put(cf, b("mk"), b("mv"));
                db.put(b("dk"), b("dv"));
                expect(eq(db.get(cf, b("mk")), b("mv")), "cf get");
                expect(db.get(b("mk")) == null, "cf isolation");
                db.delete(cf, b("mk"));
                expect(db.get(cf, b("mk")) == null, "cf delete");
                db.put(cf, b("mk2"), b("mv2"));
            }
            try (ColumnFamilyHandle cf2 =
                     db.getColumnFamilyHandle("meta")) {
                expect(eq(db.get(cf2, b("mk2")), b("mv2")), "cf reopen");
                db.dropColumnFamily(cf2);
            }
        }

        // -- transactions -----------------------------------------------
        try (TransactionDB tdb = TransactionDB.open(base + "/txndb", true)) {
            try (Transaction txn = tdb.beginTransaction()) {
                txn.put(b("tk"), b("tv"));
                expect(eq(txn.get(b("tk")), b("tv")), "txn ryw");
                expect(tdb.get(b("tk")) == null, "txn isolation");
                txn.commit();
            }
            expect(eq(tdb.get(b("tk")), b("tv")), "txn committed");
            try (Transaction txn = tdb.beginTransaction()) {
                txn.put(b("tk2"), b("x"));
                txn.rollback();
            }
            expect(tdb.get(b("tk2")) == null, "txn rollback");
        }

        // -- external SST build + ingest --------------------------------
        try (TpuLsmDB db = TpuLsmDB.open(base + "/ingestdb", true)) {
            String sst = base + "/ext.sst";
            try (SstFileWriter w = SstFileWriter.create(sst)) {
                w.put(b("ik1"), b("iv1"));
                w.put(b("ik2"), b("iv2"));
                w.finish();
            }
            db.ingestExternalFile(sst);
            expect(eq(db.get(b("ik1")), b("iv1")), "ingest get");

            // -- checkpoint + backup + restore --------------------------
            db.createCheckpoint(base + "/ckpt");
            try (BackupEngine be = BackupEngine.open(base + "/backups")) {
                int id = be.createBackup(db);
                expect(id > 0, "backup id");
                expect(be.backupCount() == 1, "backup count");
                be.restore(id, base + "/restored");
            }
        }
        try (TpuLsmDB db = TpuLsmDB.open(base + "/restored", false)) {
            expect(eq(db.get(b("ik2")), b("iv2")), "restored get");
        }
        try (TpuLsmDB db = TpuLsmDB.open(base + "/ckpt", false)) {
            expect(eq(db.get(b("ik1")), b("iv1")), "checkpoint get");
        }

        // -- WriteBatch breadth + multiGet + iterator walk --------------
        try (TpuLsmDB db = TpuLsmDB.open(base + "/wbdb", true)) {
            try (WriteBatch wb = new WriteBatch()) {
                wb.put(b("wa"), b("1"));
                wb.put(b("wb"), b("2"));
                wb.put(b("wc"), b("3"));
                wb.delete(b("wa"));
                expect(wb.count() == 4, "wb count");
                db.write(wb);
                wb.clear();
                expect(wb.count() == 0, "wb clear");
                wb.deleteRange(b("wb"), b("wc"));
                db.write(wb);
            }
            expect(db.get(b("wa")) == null, "wb delete applied");
            expect(db.get(b("wb")) == null, "wb deleteRange applied");
            expect(eq(db.get(b("wc")), b("3")), "wb survivor");
            java.util.List<byte[]> got = db.multiGetAsList(
                java.util.Arrays.asList(b("wa"), b("wc")));
            expect(got.get(0) == null && eq(got.get(1), b("3")),
                   "multiGetAsList");
            expect(db.keyExists(b("wc")) && !db.keyExists(b("wa")),
                   "keyExists");
            try (TpuLsmIterator it = db.newIterator()) {
                it.seekToFirst();
                expect(it.isValid() && eq(it.key(), b("wc")), "iter first");
                it.next();
                expect(!it.isValid(), "iter end");
            }
            // No-crash smoke: property names are engine-defined; a miss
            // returns null without throwing.
            db.getProperty("tpulsm.stats");
        }

        // -- SidePluginRepo: open from JSON config + HTTP ---------------
        try (SidePluginRepo repo = SidePluginRepo.create()) {
            TpuLsmDB db = repo.openDB(
                "{\"path\": \"" + base + "/repodb\", \"name\": \"main\", "
                + "\"options\": {\"create_if_missing\": true}}");
            db.put(b("rk"), b("rv"));
            expect(eq(db.get(b("rk")), b("rv")), "repo db get");
            int port = repo.startHttp(0);
            expect(port > 0, "http port");
            java.net.URL url =
                new java.net.URL("http://127.0.0.1:" + port + "/dbs");
            try (java.io.BufferedReader r = new java.io.BufferedReader(
                     new java.io.InputStreamReader(url.openStream()))) {
                StringBuilder sb = new StringBuilder();
                String line;
                while ((line = r.readLine()) != null) {
                    sb.append(line);
                }
                expect(sb.toString().contains("main"), "http /dbs");
            }
            repo.stopHttp();
            repo.closeAll();
        }

        System.out.println("JAVA-BREADTH-OK");
    }

    private static byte[] b(String s) {
        return s.getBytes(java.nio.charset.StandardCharsets.UTF_8);
    }

    private static boolean eq(byte[] a, byte[] e) {
        return java.util.Arrays.equals(a, e);
    }

    private static void expect(boolean cond, String what) {
        if (!cond) {
            throw new IllegalStateException("FAILED: " + what);
        }
    }
}
