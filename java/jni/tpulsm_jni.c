/* JNI glue: org.toplingdb.* ↔ the flat C ABI (tpulsm_c.h).
 *
 * The role of the reference's java/rocksjni/*.cc. Every errptr-style
 * failure becomes a thrown org.toplingdb.TpuLsmException; byte[] keys and
 * values move through Get/Release with JNI_ABORT on read-only access.
 *
 * Build (java/Makefile): gcc -shared -fPIC tpulsm_jni.c -ltpulsm_c \
 *   -I$JAVA_HOME/include -I$JAVA_HOME/include/linux \
 *   -I../toplingdb_tpu/bindings/c
 */
#include <jni.h>
#include <stdlib.h>
#include <string.h>

#include "tpulsm_c.h"

static void throw_tpulsm(JNIEnv* env, const char* msg) {
    jclass cls = (*env)->FindClass(env, "org/toplingdb/TpuLsmException");
    if (cls != NULL) {
        (*env)->ThrowNew(env, cls, msg ? msg : "unknown engine error");
    }
}

static int check_err(JNIEnv* env, char* err) {
    if (err != NULL) {
        throw_tpulsm(env, err);
        tpulsm_free(err);
        return 1;
    }
    return 0;
}

/* -- TpuLsmDB ----------------------------------------------------------- */

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_initEngine(JNIEnv* env, jclass cls) {
    (void)env; (void)cls;
    tpulsm_init();
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TpuLsmDB_openNative(JNIEnv* env, jclass cls, jstring path,
                                       jboolean create) {
    (void)cls;
    char* err = NULL;
    const char* cpath = (*env)->GetStringUTFChars(env, path, NULL);
    if (cpath == NULL) return 0;
    tpulsm_db_t* db = tpulsm_open(cpath, create == JNI_TRUE, &err);
    (*env)->ReleaseStringUTFChars(env, path, cpath);
    if (check_err(env, err)) return 0;
    if (db == NULL) {
        throw_tpulsm(env, "open failed");
        return 0;
    }
    return (jlong)(intptr_t)db;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_closeNative(JNIEnv* env, jclass cls, jlong h) {
    (void)env; (void)cls;
    tpulsm_close((tpulsm_db_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_putNative(JNIEnv* env, jclass cls, jlong h,
                                      jbyteArray key, jbyteArray val) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jsize vlen = (*env)->GetArrayLength(env, val);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    jbyte* v = (*env)->GetByteArrayElements(env, val, NULL);
    if (k != NULL && v != NULL) {
        tpulsm_put((tpulsm_db_t*)(intptr_t)h, (const char*)k, (size_t)klen,
                   (const char*)v, (size_t)vlen, &err);
    }
    if (k != NULL) (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (v != NULL) (*env)->ReleaseByteArrayElements(env, val, v, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmDB_getNative(JNIEnv* env, jclass cls, jlong h,
                                      jbyteArray key) {
    (void)cls;
    char* err = NULL;
    size_t vlen = 0;
    jsize klen = (*env)->GetArrayLength(env, key);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    if (k == NULL) return NULL;
    char* v = tpulsm_get((tpulsm_db_t*)(intptr_t)h, (const char*)k,
                         (size_t)klen, &vlen, &err);
    (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (check_err(env, err)) {
        if (v != NULL) tpulsm_free(v);
        return NULL;
    }
    if (v == NULL) return NULL; /* absent */
    jbyteArray out = (*env)->NewByteArray(env, (jsize)vlen);
    if (out != NULL) {
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)vlen,
                                   (const jbyte*)v);
    }
    tpulsm_free(v);
    return out;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_deleteNative(JNIEnv* env, jclass cls, jlong h,
                                         jbyteArray key) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    if (k != NULL) {
        tpulsm_delete((tpulsm_db_t*)(intptr_t)h, (const char*)k,
                      (size_t)klen, &err);
        (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    }
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_writeNative(JNIEnv* env, jclass cls, jlong h,
                                        jlong wb) {
    (void)cls;
    char* err = NULL;
    tpulsm_write((tpulsm_db_t*)(intptr_t)h,
                 (tpulsm_writebatch_t*)(intptr_t)wb, &err);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_flushNative(JNIEnv* env, jclass cls, jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_flush((tpulsm_db_t*)(intptr_t)h, &err);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_compactRangeNative(JNIEnv* env, jclass cls,
                                               jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_compact_range((tpulsm_db_t*)(intptr_t)h, &err);
    check_err(env, err);
}

JNIEXPORT jstring JNICALL
Java_org_toplingdb_TpuLsmDB_propertyNative(JNIEnv* env, jclass cls, jlong h,
                                           jstring name) {
    (void)cls;
    const char* cname = (*env)->GetStringUTFChars(env, name, NULL);
    if (cname == NULL) return NULL;
    char* v = tpulsm_property_value((tpulsm_db_t*)(intptr_t)h, cname);
    (*env)->ReleaseStringUTFChars(env, name, cname);
    if (v == NULL) return NULL;
    jstring out = (*env)->NewStringUTF(env, v);
    tpulsm_free(v);
    return out;
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TpuLsmDB_iteratorNative(JNIEnv* env, jclass cls,
                                           jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_iterator_t* it =
        tpulsm_create_iterator((tpulsm_db_t*)(intptr_t)h, &err);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)it;
}

/* -- WriteBatch --------------------------------------------------------- */

JNIEXPORT jlong JNICALL
Java_org_toplingdb_WriteBatch_createNative(JNIEnv* env, jclass cls) {
    (void)env; (void)cls;
    return (jlong)(intptr_t)tpulsm_writebatch_create();
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_destroyNative(JNIEnv* env, jclass cls,
                                            jlong h) {
    (void)env; (void)cls;
    tpulsm_writebatch_destroy((tpulsm_writebatch_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_putNative(JNIEnv* env, jclass cls, jlong h,
                                        jbyteArray key, jbyteArray val) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jsize vlen = (*env)->GetArrayLength(env, val);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    jbyte* v = (*env)->GetByteArrayElements(env, val, NULL);
    if (k != NULL && v != NULL) {
        tpulsm_writebatch_put((tpulsm_writebatch_t*)(intptr_t)h,
                              (const char*)k, (size_t)klen,
                              (const char*)v, (size_t)vlen, &err);
    }
    if (k != NULL) (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (v != NULL) (*env)->ReleaseByteArrayElements(env, val, v, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_deleteNative(JNIEnv* env, jclass cls, jlong h,
                                           jbyteArray key) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    if (k != NULL) {
        tpulsm_writebatch_delete((tpulsm_writebatch_t*)(intptr_t)h,
                                 (const char*)k, (size_t)klen, &err);
        (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    }
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_mergeNative(JNIEnv* env, jclass cls, jlong h,
                                          jbyteArray key, jbyteArray val) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jsize vlen = (*env)->GetArrayLength(env, val);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    jbyte* v = (*env)->GetByteArrayElements(env, val, NULL);
    if (k != NULL && v != NULL) {
        tpulsm_writebatch_merge((tpulsm_writebatch_t*)(intptr_t)h,
                                (const char*)k, (size_t)klen,
                                (const char*)v, (size_t)vlen, &err);
    }
    if (k != NULL) (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (v != NULL) (*env)->ReleaseByteArrayElements(env, val, v, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_deleteRangeNative(JNIEnv* env, jclass cls,
                                                jlong h, jbyteArray beg,
                                                jbyteArray end) {
    (void)cls;
    char* err = NULL;
    jsize blen = (*env)->GetArrayLength(env, beg);
    jsize elen = (*env)->GetArrayLength(env, end);
    jbyte* b = (*env)->GetByteArrayElements(env, beg, NULL);
    jbyte* e = (*env)->GetByteArrayElements(env, end, NULL);
    if (b != NULL && e != NULL) {
        tpulsm_writebatch_delete_range((tpulsm_writebatch_t*)(intptr_t)h,
                                       (const char*)b, (size_t)blen,
                                       (const char*)e, (size_t)elen, &err);
    }
    if (b != NULL) (*env)->ReleaseByteArrayElements(env, beg, b, JNI_ABORT);
    if (e != NULL) (*env)->ReleaseByteArrayElements(env, end, e, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_clearNative(JNIEnv* env, jclass cls, jlong h) {
    (void)env; (void)cls;
    tpulsm_writebatch_clear((tpulsm_writebatch_t*)(intptr_t)h);
}

JNIEXPORT jint JNICALL
Java_org_toplingdb_WriteBatch_countNative(JNIEnv* env, jclass cls, jlong h) {
    (void)env; (void)cls;
    return (jint)tpulsm_writebatch_count((tpulsm_writebatch_t*)(intptr_t)h);
}

/* -- TpuLsmIterator ------------------------------------------------------ */

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_destroyNative(JNIEnv* env, jclass cls,
                                                jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_destroy((tpulsm_iterator_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_seekToFirstNative(JNIEnv* env, jclass cls,
                                                    jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_seek_to_first((tpulsm_iterator_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_seekToLastNative(JNIEnv* env, jclass cls,
                                                   jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_seek_to_last((tpulsm_iterator_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_seekNative(JNIEnv* env, jclass cls,
                                             jlong h, jbyteArray target) {
    (void)cls;
    jsize tlen = (*env)->GetArrayLength(env, target);
    jbyte* t = (*env)->GetByteArrayElements(env, target, NULL);
    if (t != NULL) {
        tpulsm_iter_seek((tpulsm_iterator_t*)(intptr_t)h, (const char*)t,
                         (size_t)tlen);
        (*env)->ReleaseByteArrayElements(env, target, t, JNI_ABORT);
    }
}

JNIEXPORT jboolean JNICALL
Java_org_toplingdb_TpuLsmIterator_validNative(JNIEnv* env, jclass cls,
                                              jlong h) {
    (void)env; (void)cls;
    return tpulsm_iter_valid((tpulsm_iterator_t*)(intptr_t)h)
        ? JNI_TRUE : JNI_FALSE;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_nextNative(JNIEnv* env, jclass cls,
                                             jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_next((tpulsm_iterator_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_prevNative(JNIEnv* env, jclass cls,
                                             jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_prev((tpulsm_iterator_t*)(intptr_t)h);
}

static jbyteArray iter_bytes_to_java(JNIEnv* env, char* buf, size_t n) {
    if (buf == NULL) return NULL;
    jbyteArray out = (*env)->NewByteArray(env, (jsize)n);
    if (out != NULL) {
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)n,
                                   (const jbyte*)buf);
    }
    tpulsm_free(buf);
    return out;
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmIterator_keyNative(JNIEnv* env, jclass cls,
                                            jlong h) {
    (void)cls;
    size_t n = 0;
    char* buf = tpulsm_iter_key((tpulsm_iterator_t*)(intptr_t)h, &n);
    return iter_bytes_to_java(env, buf, n);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmIterator_valueNative(JNIEnv* env, jclass cls,
                                              jlong h) {
    (void)cls;
    size_t n = 0;
    char* buf = tpulsm_iter_value((tpulsm_iterator_t*)(intptr_t)h, &n);
    return iter_bytes_to_java(env, buf, n);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_mergeNative(JNIEnv* env, jclass cls, jlong h,
                                        jbyteArray key, jbyteArray val) {
    (void)cls;
    char* err = NULL;
    jsize kl = (*env)->GetArrayLength(env, key);
    jsize vl = (*env)->GetArrayLength(env, val);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    jbyte* v = (*env)->GetByteArrayElements(env, val, NULL);
    if (k && v)
        tpulsm_merge((tpulsm_db_t*)(intptr_t)h, (const char*)k, (size_t)kl,
                     (const char*)v, (size_t)vl, &err);
    if (k) (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (v) (*env)->ReleaseByteArrayElements(env, val, v, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_deleteRangeNative(JNIEnv* env, jclass cls,
                                              jlong h, jbyteArray b,
                                              jbyteArray e) {
    (void)cls;
    char* err = NULL;
    jsize bl = (*env)->GetArrayLength(env, b);
    jsize el = (*env)->GetArrayLength(env, e);
    jbyte* bb = (*env)->GetByteArrayElements(env, b, NULL);
    jbyte* eb = (*env)->GetByteArrayElements(env, e, NULL);
    if (bb && eb)
        tpulsm_delete_range((tpulsm_db_t*)(intptr_t)h, (const char*)bb,
                            (size_t)bl, (const char*)eb, (size_t)el, &err);
    if (bb) (*env)->ReleaseByteArrayElements(env, b, bb, JNI_ABORT);
    if (eb) (*env)->ReleaseByteArrayElements(env, e, eb, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TpuLsmDB_snapshotNative(JNIEnv* env, jclass cls, jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_snapshot_t* s =
        tpulsm_create_snapshot((tpulsm_db_t*)(intptr_t)h, &err);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)s;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_releaseSnapshotNative(JNIEnv* env, jclass cls,
                                                  jlong snap) {
    (void)env; (void)cls;
    tpulsm_release_snapshot((tpulsm_snapshot_t*)(intptr_t)snap);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmDB_getAtSnapshotNative(JNIEnv* env, jclass cls,
                                                jlong h, jlong snap,
                                                jbyteArray key) {
    (void)cls;
    char* err = NULL;
    size_t vl = 0;
    jsize kl = (*env)->GetArrayLength(env, key);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    if (!k) return NULL;
    char* v = tpulsm_get_at_snapshot(
        (tpulsm_db_t*)(intptr_t)h, (tpulsm_snapshot_t*)(intptr_t)snap,
        (const char*)k, (size_t)kl, &vl, &err);
    (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (check_err(env, err)) { tpulsm_free(v); return NULL; }
    if (!v) return NULL;
    jbyteArray out = (*env)->NewByteArray(env, (jsize)vl);
    if (out)
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)vl, (const jbyte*)v);
    tpulsm_free(v);
    return out;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_checkpointNative(JNIEnv* env, jclass cls,
                                             jlong h, jstring dest) {
    (void)cls;
    char* err = NULL;
    const char* cdest = (*env)->GetStringUTFChars(env, dest, NULL);
    if (!cdest) return;
    tpulsm_checkpoint_create((tpulsm_db_t*)(intptr_t)h, cdest, &err);
    (*env)->ReleaseStringUTFChars(env, dest, cdest);
    check_err(env, err);
}

/* -- column families (reference rocksjni/rocksjni.cc CF surface) -------- */

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TpuLsmDB_createColumnFamilyNative(JNIEnv* env, jclass cls,
                                                     jlong h, jstring name) {
    (void)cls;
    char* err = NULL;
    const char* cname = (*env)->GetStringUTFChars(env, name, NULL);
    if (cname == NULL) return 0;
    tpulsm_cf_t* cf = tpulsm_create_column_family(
        (tpulsm_db_t*)(intptr_t)h, cname, &err);
    (*env)->ReleaseStringUTFChars(env, name, cname);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)cf;
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TpuLsmDB_columnFamilyHandleNative(JNIEnv* env, jclass cls,
                                                     jlong h, jstring name) {
    (void)cls;
    char* err = NULL;
    const char* cname = (*env)->GetStringUTFChars(env, name, NULL);
    if (cname == NULL) return 0;
    tpulsm_cf_t* cf = tpulsm_column_family_handle(
        (tpulsm_db_t*)(intptr_t)h, cname, &err);
    (*env)->ReleaseStringUTFChars(env, name, cname);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)cf;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_dropColumnFamilyNative(JNIEnv* env, jclass cls,
                                                   jlong h, jlong cf) {
    (void)cls;
    char* err = NULL;
    tpulsm_drop_column_family((tpulsm_db_t*)(intptr_t)h,
                              (tpulsm_cf_t*)(intptr_t)cf, &err);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_ColumnFamilyHandle_destroyNative(JNIEnv* env, jclass cls,
                                                    jlong cf) {
    (void)env; (void)cls;
    tpulsm_cf_handle_destroy((tpulsm_cf_t*)(intptr_t)cf);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_putCfNative(JNIEnv* env, jclass cls, jlong h,
                                        jlong cf, jbyteArray k,
                                        jbyteArray v) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, k);
    jsize vlen = (*env)->GetArrayLength(env, v);
    jbyte* kb = (*env)->GetByteArrayElements(env, k, NULL);
    jbyte* vb = (*env)->GetByteArrayElements(env, v, NULL);
    tpulsm_put_cf((tpulsm_db_t*)(intptr_t)h, (tpulsm_cf_t*)(intptr_t)cf,
                  (const char*)kb, (size_t)klen,
                  (const char*)vb, (size_t)vlen, &err);
    (*env)->ReleaseByteArrayElements(env, k, kb, JNI_ABORT);
    (*env)->ReleaseByteArrayElements(env, v, vb, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmDB_getCfNative(JNIEnv* env, jclass cls, jlong h,
                                        jlong cf, jbyteArray k) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, k);
    jbyte* kb = (*env)->GetByteArrayElements(env, k, NULL);
    size_t vlen = 0;
    char* v = tpulsm_get_cf((tpulsm_db_t*)(intptr_t)h,
                            (tpulsm_cf_t*)(intptr_t)cf,
                            (const char*)kb, (size_t)klen, &vlen, &err);
    (*env)->ReleaseByteArrayElements(env, k, kb, JNI_ABORT);
    if (check_err(env, err)) return NULL;
    if (v == NULL) return NULL;
    jbyteArray out = (*env)->NewByteArray(env, (jsize)vlen);
    if (out != NULL)
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)vlen,
                                   (const jbyte*)v);
    tpulsm_free(v);
    return out;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_deleteCfNative(JNIEnv* env, jclass cls, jlong h,
                                           jlong cf, jbyteArray k) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, k);
    jbyte* kb = (*env)->GetByteArrayElements(env, k, NULL);
    tpulsm_delete_cf((tpulsm_db_t*)(intptr_t)h, (tpulsm_cf_t*)(intptr_t)cf,
                     (const char*)kb, (size_t)klen, &err);
    (*env)->ReleaseByteArrayElements(env, k, kb, JNI_ABORT);
    check_err(env, err);
}

/* -- external SST ingest ------------------------------------------------ */

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_ingestExternalFileNative(JNIEnv* env, jclass cls,
                                                     jlong h, jstring path) {
    (void)cls;
    char* err = NULL;
    const char* cpath = (*env)->GetStringUTFChars(env, path, NULL);
    if (cpath == NULL) return;
    tpulsm_ingest_external_file((tpulsm_db_t*)(intptr_t)h, cpath, &err);
    (*env)->ReleaseStringUTFChars(env, path, cpath);
    check_err(env, err);
}

/* -- SstFileWriter ------------------------------------------------------ */

JNIEXPORT jlong JNICALL
Java_org_toplingdb_SstFileWriter_createNative(JNIEnv* env, jclass cls,
                                              jstring path) {
    (void)cls;
    char* err = NULL;
    const char* cpath = (*env)->GetStringUTFChars(env, path, NULL);
    if (cpath == NULL) return 0;
    tpulsm_sstwriter_t* w = tpulsm_sstfilewriter_create(cpath, &err);
    (*env)->ReleaseStringUTFChars(env, path, cpath);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)w;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_SstFileWriter_putNative(JNIEnv* env, jclass cls, jlong h,
                                           jbyteArray k, jbyteArray v) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, k);
    jsize vlen = (*env)->GetArrayLength(env, v);
    jbyte* kb = (*env)->GetByteArrayElements(env, k, NULL);
    jbyte* vb = (*env)->GetByteArrayElements(env, v, NULL);
    tpulsm_sstfilewriter_put((tpulsm_sstwriter_t*)(intptr_t)h,
                             (const char*)kb, (size_t)klen,
                             (const char*)vb, (size_t)vlen, &err);
    (*env)->ReleaseByteArrayElements(env, k, kb, JNI_ABORT);
    (*env)->ReleaseByteArrayElements(env, v, vb, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_SstFileWriter_finishNative(JNIEnv* env, jclass cls,
                                              jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_sstfilewriter_finish((tpulsm_sstwriter_t*)(intptr_t)h, &err);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_SstFileWriter_destroyNative(JNIEnv* env, jclass cls,
                                               jlong h) {
    (void)env; (void)cls;
    tpulsm_sstfilewriter_destroy((tpulsm_sstwriter_t*)(intptr_t)h);
}

/* -- transactions (reference rocksjni/transaction.cc role) -------------- */

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TransactionDB_openNative(JNIEnv* env, jclass cls,
                                            jstring path, jboolean create) {
    (void)cls;
    char* err = NULL;
    const char* cpath = (*env)->GetStringUTFChars(env, path, NULL);
    if (cpath == NULL) return 0;
    tpulsm_txndb_t* t = tpulsm_txndb_open(cpath, create == JNI_TRUE, &err);
    (*env)->ReleaseStringUTFChars(env, path, cpath);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)t;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TransactionDB_closeNative(JNIEnv* env, jclass cls,
                                             jlong h) {
    (void)env; (void)cls;
    tpulsm_txndb_close((tpulsm_txndb_t*)(intptr_t)h);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TransactionDB_getNative(JNIEnv* env, jclass cls, jlong h,
                                           jbyteArray k) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, k);
    jbyte* kb = (*env)->GetByteArrayElements(env, k, NULL);
    size_t vlen = 0;
    char* v = tpulsm_txndb_get((tpulsm_txndb_t*)(intptr_t)h,
                               (const char*)kb, (size_t)klen, &vlen, &err);
    (*env)->ReleaseByteArrayElements(env, k, kb, JNI_ABORT);
    if (check_err(env, err)) return NULL;
    if (v == NULL) return NULL;
    jbyteArray out = (*env)->NewByteArray(env, (jsize)vlen);
    if (out != NULL)
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)vlen,
                                   (const jbyte*)v);
    tpulsm_free(v);
    return out;
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TransactionDB_beginNative(JNIEnv* env, jclass cls,
                                             jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_txn_t* t = tpulsm_txn_begin((tpulsm_txndb_t*)(intptr_t)h, &err);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)t;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_Transaction_putNative(JNIEnv* env, jclass cls, jlong h,
                                         jbyteArray k, jbyteArray v) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, k);
    jsize vlen = (*env)->GetArrayLength(env, v);
    jbyte* kb = (*env)->GetByteArrayElements(env, k, NULL);
    jbyte* vb = (*env)->GetByteArrayElements(env, v, NULL);
    tpulsm_txn_put((tpulsm_txn_t*)(intptr_t)h, (const char*)kb,
                   (size_t)klen, (const char*)vb, (size_t)vlen, &err);
    (*env)->ReleaseByteArrayElements(env, k, kb, JNI_ABORT);
    (*env)->ReleaseByteArrayElements(env, v, vb, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_Transaction_getNative(JNIEnv* env, jclass cls, jlong h,
                                         jbyteArray k) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, k);
    jbyte* kb = (*env)->GetByteArrayElements(env, k, NULL);
    size_t vlen = 0;
    char* v = tpulsm_txn_get((tpulsm_txn_t*)(intptr_t)h, (const char*)kb,
                             (size_t)klen, &vlen, &err);
    (*env)->ReleaseByteArrayElements(env, k, kb, JNI_ABORT);
    if (check_err(env, err)) return NULL;
    if (v == NULL) return NULL;
    jbyteArray out = (*env)->NewByteArray(env, (jsize)vlen);
    if (out != NULL)
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)vlen,
                                   (const jbyte*)v);
    tpulsm_free(v);
    return out;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_Transaction_deleteNative(JNIEnv* env, jclass cls, jlong h,
                                            jbyteArray k) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, k);
    jbyte* kb = (*env)->GetByteArrayElements(env, k, NULL);
    tpulsm_txn_delete((tpulsm_txn_t*)(intptr_t)h, (const char*)kb,
                      (size_t)klen, &err);
    (*env)->ReleaseByteArrayElements(env, k, kb, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_Transaction_commitNative(JNIEnv* env, jclass cls,
                                            jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_txn_commit((tpulsm_txn_t*)(intptr_t)h, &err);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_Transaction_rollbackNative(JNIEnv* env, jclass cls,
                                              jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_txn_rollback((tpulsm_txn_t*)(intptr_t)h, &err);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_Transaction_destroyNative(JNIEnv* env, jclass cls,
                                             jlong h) {
    (void)env; (void)cls;
    tpulsm_txn_destroy((tpulsm_txn_t*)(intptr_t)h);
}

/* -- backup engine (reference rocksjni/backup_engine.cc role) ----------- */

JNIEXPORT jlong JNICALL
Java_org_toplingdb_BackupEngine_openNative(JNIEnv* env, jclass cls,
                                           jstring dir) {
    (void)cls;
    char* err = NULL;
    const char* cdir = (*env)->GetStringUTFChars(env, dir, NULL);
    if (cdir == NULL) return 0;
    tpulsm_backup_engine_t* be = tpulsm_backup_engine_open(cdir, &err);
    (*env)->ReleaseStringUTFChars(env, dir, cdir);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)be;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_BackupEngine_closeNative(JNIEnv* env, jclass cls,
                                            jlong h) {
    (void)env; (void)cls;
    tpulsm_backup_engine_close((tpulsm_backup_engine_t*)(intptr_t)h);
}

JNIEXPORT jint JNICALL
Java_org_toplingdb_BackupEngine_createBackupNative(JNIEnv* env, jclass cls,
                                                   jlong h, jlong db) {
    (void)cls;
    char* err = NULL;
    int id = tpulsm_backup_engine_create_backup(
        (tpulsm_backup_engine_t*)(intptr_t)h, (tpulsm_db_t*)(intptr_t)db,
        &err);
    if (check_err(env, err)) return 0;
    return (jint)id;
}

JNIEXPORT jint JNICALL
Java_org_toplingdb_BackupEngine_countNative(JNIEnv* env, jclass cls,
                                            jlong h) {
    (void)env; (void)cls;
    return (jint)tpulsm_backup_engine_count(
        (tpulsm_backup_engine_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_BackupEngine_restoreNative(JNIEnv* env, jclass cls,
                                              jlong h, jint backup_id,
                                              jstring dest) {
    (void)cls;
    char* err = NULL;
    const char* cdest = (*env)->GetStringUTFChars(env, dest, NULL);
    if (cdest == NULL) return;
    tpulsm_backup_engine_restore((tpulsm_backup_engine_t*)(intptr_t)h,
                                 (int)backup_id, cdest, &err);
    (*env)->ReleaseStringUTFChars(env, dest, cdest);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_BackupEngine_purgeOldNative(JNIEnv* env, jclass cls,
                                               jlong h, jint keep) {
    (void)cls;
    char* err = NULL;
    tpulsm_backup_engine_purge_old((tpulsm_backup_engine_t*)(intptr_t)h,
                                   (int)keep, &err);
    check_err(env, err);
}

/* -- SidePluginRepo (reference SidePluginRepo.java:10-104 + its JNI) ---- */

JNIEXPORT jlong JNICALL
Java_org_toplingdb_SidePluginRepo_createNative(JNIEnv* env, jclass cls) {
    (void)cls;
    char* err = NULL;
    tpulsm_repo_t* r = tpulsm_repo_create(&err);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)r;
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_SidePluginRepo_openDBNative(JNIEnv* env, jclass cls,
                                               jlong h, jstring json) {
    (void)cls;
    char* err = NULL;
    const char* cjson = (*env)->GetStringUTFChars(env, json, NULL);
    if (cjson == NULL) return 0;
    tpulsm_db_t* db = tpulsm_repo_open_db((tpulsm_repo_t*)(intptr_t)h,
                                          cjson, &err);
    (*env)->ReleaseStringUTFChars(env, json, cjson);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)db;
}

JNIEXPORT jint JNICALL
Java_org_toplingdb_SidePluginRepo_startHttpNative(JNIEnv* env, jclass cls,
                                                  jlong h, jint port) {
    (void)cls;
    char* err = NULL;
    int bound = tpulsm_repo_start_http((tpulsm_repo_t*)(intptr_t)h,
                                       (int)port, &err);
    if (check_err(env, err)) return -1;
    return (jint)bound;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_SidePluginRepo_stopHttpNative(JNIEnv* env, jclass cls,
                                                 jlong h) {
    (void)env; (void)cls;
    tpulsm_repo_stop_http((tpulsm_repo_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_SidePluginRepo_closeAllNative(JNIEnv* env, jclass cls,
                                                 jlong h) {
    (void)env; (void)cls;
    tpulsm_repo_close_all((tpulsm_repo_t*)(intptr_t)h);
}
