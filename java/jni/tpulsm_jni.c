/* JNI glue: org.toplingdb.* ↔ the flat C ABI (tpulsm_c.h).
 *
 * The role of the reference's java/rocksjni/*.cc. Every errptr-style
 * failure becomes a thrown org.toplingdb.TpuLsmException; byte[] keys and
 * values move through Get/Release with JNI_ABORT on read-only access.
 *
 * Build (java/Makefile): gcc -shared -fPIC tpulsm_jni.c -ltpulsm_c \
 *   -I$JAVA_HOME/include -I$JAVA_HOME/include/linux \
 *   -I../toplingdb_tpu/bindings/c
 */
#include <jni.h>
#include <stdlib.h>
#include <string.h>

#include "tpulsm_c.h"

static void throw_tpulsm(JNIEnv* env, const char* msg) {
    jclass cls = (*env)->FindClass(env, "org/toplingdb/TpuLsmException");
    if (cls != NULL) {
        (*env)->ThrowNew(env, cls, msg ? msg : "unknown engine error");
    }
}

static int check_err(JNIEnv* env, char* err) {
    if (err != NULL) {
        throw_tpulsm(env, err);
        tpulsm_free(err);
        return 1;
    }
    return 0;
}

/* -- TpuLsmDB ----------------------------------------------------------- */

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_initEngine(JNIEnv* env, jclass cls) {
    (void)env; (void)cls;
    tpulsm_init();
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TpuLsmDB_openNative(JNIEnv* env, jclass cls, jstring path,
                                       jboolean create) {
    (void)cls;
    char* err = NULL;
    const char* cpath = (*env)->GetStringUTFChars(env, path, NULL);
    if (cpath == NULL) return 0;
    tpulsm_db_t* db = tpulsm_open(cpath, create == JNI_TRUE, &err);
    (*env)->ReleaseStringUTFChars(env, path, cpath);
    if (check_err(env, err)) return 0;
    if (db == NULL) {
        throw_tpulsm(env, "open failed");
        return 0;
    }
    return (jlong)(intptr_t)db;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_closeNative(JNIEnv* env, jclass cls, jlong h) {
    (void)env; (void)cls;
    tpulsm_close((tpulsm_db_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_putNative(JNIEnv* env, jclass cls, jlong h,
                                      jbyteArray key, jbyteArray val) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jsize vlen = (*env)->GetArrayLength(env, val);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    jbyte* v = (*env)->GetByteArrayElements(env, val, NULL);
    if (k != NULL && v != NULL) {
        tpulsm_put((tpulsm_db_t*)(intptr_t)h, (const char*)k, (size_t)klen,
                   (const char*)v, (size_t)vlen, &err);
    }
    if (k != NULL) (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (v != NULL) (*env)->ReleaseByteArrayElements(env, val, v, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmDB_getNative(JNIEnv* env, jclass cls, jlong h,
                                      jbyteArray key) {
    (void)cls;
    char* err = NULL;
    size_t vlen = 0;
    jsize klen = (*env)->GetArrayLength(env, key);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    if (k == NULL) return NULL;
    char* v = tpulsm_get((tpulsm_db_t*)(intptr_t)h, (const char*)k,
                         (size_t)klen, &vlen, &err);
    (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (check_err(env, err)) {
        if (v != NULL) tpulsm_free(v);
        return NULL;
    }
    if (v == NULL) return NULL; /* absent */
    jbyteArray out = (*env)->NewByteArray(env, (jsize)vlen);
    if (out != NULL) {
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)vlen,
                                   (const jbyte*)v);
    }
    tpulsm_free(v);
    return out;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_deleteNative(JNIEnv* env, jclass cls, jlong h,
                                         jbyteArray key) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    if (k != NULL) {
        tpulsm_delete((tpulsm_db_t*)(intptr_t)h, (const char*)k,
                      (size_t)klen, &err);
        (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    }
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_writeNative(JNIEnv* env, jclass cls, jlong h,
                                        jlong wb) {
    (void)cls;
    char* err = NULL;
    tpulsm_write((tpulsm_db_t*)(intptr_t)h,
                 (tpulsm_writebatch_t*)(intptr_t)wb, &err);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_flushNative(JNIEnv* env, jclass cls, jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_flush((tpulsm_db_t*)(intptr_t)h, &err);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_compactRangeNative(JNIEnv* env, jclass cls,
                                               jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_compact_range((tpulsm_db_t*)(intptr_t)h, &err);
    check_err(env, err);
}

JNIEXPORT jstring JNICALL
Java_org_toplingdb_TpuLsmDB_propertyNative(JNIEnv* env, jclass cls, jlong h,
                                           jstring name) {
    (void)cls;
    const char* cname = (*env)->GetStringUTFChars(env, name, NULL);
    if (cname == NULL) return NULL;
    char* v = tpulsm_property_value((tpulsm_db_t*)(intptr_t)h, cname);
    (*env)->ReleaseStringUTFChars(env, name, cname);
    if (v == NULL) return NULL;
    jstring out = (*env)->NewStringUTF(env, v);
    tpulsm_free(v);
    return out;
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TpuLsmDB_iteratorNative(JNIEnv* env, jclass cls,
                                           jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_iterator_t* it =
        tpulsm_create_iterator((tpulsm_db_t*)(intptr_t)h, &err);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)it;
}

/* -- WriteBatch --------------------------------------------------------- */

JNIEXPORT jlong JNICALL
Java_org_toplingdb_WriteBatch_createNative(JNIEnv* env, jclass cls) {
    (void)env; (void)cls;
    return (jlong)(intptr_t)tpulsm_writebatch_create();
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_destroyNative(JNIEnv* env, jclass cls,
                                            jlong h) {
    (void)env; (void)cls;
    tpulsm_writebatch_destroy((tpulsm_writebatch_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_putNative(JNIEnv* env, jclass cls, jlong h,
                                        jbyteArray key, jbyteArray val) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jsize vlen = (*env)->GetArrayLength(env, val);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    jbyte* v = (*env)->GetByteArrayElements(env, val, NULL);
    if (k != NULL && v != NULL) {
        tpulsm_writebatch_put((tpulsm_writebatch_t*)(intptr_t)h,
                              (const char*)k, (size_t)klen,
                              (const char*)v, (size_t)vlen, &err);
    }
    if (k != NULL) (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (v != NULL) (*env)->ReleaseByteArrayElements(env, val, v, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_WriteBatch_deleteNative(JNIEnv* env, jclass cls, jlong h,
                                           jbyteArray key) {
    (void)cls;
    char* err = NULL;
    jsize klen = (*env)->GetArrayLength(env, key);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    if (k != NULL) {
        tpulsm_writebatch_delete((tpulsm_writebatch_t*)(intptr_t)h,
                                 (const char*)k, (size_t)klen, &err);
        (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    }
    check_err(env, err);
}

/* -- TpuLsmIterator ------------------------------------------------------ */

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_destroyNative(JNIEnv* env, jclass cls,
                                                jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_destroy((tpulsm_iterator_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_seekToFirstNative(JNIEnv* env, jclass cls,
                                                    jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_seek_to_first((tpulsm_iterator_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_seekToLastNative(JNIEnv* env, jclass cls,
                                                   jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_seek_to_last((tpulsm_iterator_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_seekNative(JNIEnv* env, jclass cls,
                                             jlong h, jbyteArray target) {
    (void)cls;
    jsize tlen = (*env)->GetArrayLength(env, target);
    jbyte* t = (*env)->GetByteArrayElements(env, target, NULL);
    if (t != NULL) {
        tpulsm_iter_seek((tpulsm_iterator_t*)(intptr_t)h, (const char*)t,
                         (size_t)tlen);
        (*env)->ReleaseByteArrayElements(env, target, t, JNI_ABORT);
    }
}

JNIEXPORT jboolean JNICALL
Java_org_toplingdb_TpuLsmIterator_validNative(JNIEnv* env, jclass cls,
                                              jlong h) {
    (void)env; (void)cls;
    return tpulsm_iter_valid((tpulsm_iterator_t*)(intptr_t)h)
        ? JNI_TRUE : JNI_FALSE;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_nextNative(JNIEnv* env, jclass cls,
                                             jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_next((tpulsm_iterator_t*)(intptr_t)h);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmIterator_prevNative(JNIEnv* env, jclass cls,
                                             jlong h) {
    (void)env; (void)cls;
    tpulsm_iter_prev((tpulsm_iterator_t*)(intptr_t)h);
}

static jbyteArray iter_bytes_to_java(JNIEnv* env, char* buf, size_t n) {
    if (buf == NULL) return NULL;
    jbyteArray out = (*env)->NewByteArray(env, (jsize)n);
    if (out != NULL) {
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)n,
                                   (const jbyte*)buf);
    }
    tpulsm_free(buf);
    return out;
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmIterator_keyNative(JNIEnv* env, jclass cls,
                                            jlong h) {
    (void)cls;
    size_t n = 0;
    char* buf = tpulsm_iter_key((tpulsm_iterator_t*)(intptr_t)h, &n);
    return iter_bytes_to_java(env, buf, n);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmIterator_valueNative(JNIEnv* env, jclass cls,
                                              jlong h) {
    (void)cls;
    size_t n = 0;
    char* buf = tpulsm_iter_value((tpulsm_iterator_t*)(intptr_t)h, &n);
    return iter_bytes_to_java(env, buf, n);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_mergeNative(JNIEnv* env, jclass cls, jlong h,
                                        jbyteArray key, jbyteArray val) {
    (void)cls;
    char* err = NULL;
    jsize kl = (*env)->GetArrayLength(env, key);
    jsize vl = (*env)->GetArrayLength(env, val);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    jbyte* v = (*env)->GetByteArrayElements(env, val, NULL);
    if (k && v)
        tpulsm_merge((tpulsm_db_t*)(intptr_t)h, (const char*)k, (size_t)kl,
                     (const char*)v, (size_t)vl, &err);
    if (k) (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (v) (*env)->ReleaseByteArrayElements(env, val, v, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_deleteRangeNative(JNIEnv* env, jclass cls,
                                              jlong h, jbyteArray b,
                                              jbyteArray e) {
    (void)cls;
    char* err = NULL;
    jsize bl = (*env)->GetArrayLength(env, b);
    jsize el = (*env)->GetArrayLength(env, e);
    jbyte* bb = (*env)->GetByteArrayElements(env, b, NULL);
    jbyte* eb = (*env)->GetByteArrayElements(env, e, NULL);
    if (bb && eb)
        tpulsm_delete_range((tpulsm_db_t*)(intptr_t)h, (const char*)bb,
                            (size_t)bl, (const char*)eb, (size_t)el, &err);
    if (bb) (*env)->ReleaseByteArrayElements(env, b, bb, JNI_ABORT);
    if (eb) (*env)->ReleaseByteArrayElements(env, e, eb, JNI_ABORT);
    check_err(env, err);
}

JNIEXPORT jlong JNICALL
Java_org_toplingdb_TpuLsmDB_snapshotNative(JNIEnv* env, jclass cls, jlong h) {
    (void)cls;
    char* err = NULL;
    tpulsm_snapshot_t* s =
        tpulsm_create_snapshot((tpulsm_db_t*)(intptr_t)h, &err);
    if (check_err(env, err)) return 0;
    return (jlong)(intptr_t)s;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_releaseSnapshotNative(JNIEnv* env, jclass cls,
                                                  jlong snap) {
    (void)env; (void)cls;
    tpulsm_release_snapshot((tpulsm_snapshot_t*)(intptr_t)snap);
}

JNIEXPORT jbyteArray JNICALL
Java_org_toplingdb_TpuLsmDB_getAtSnapshotNative(JNIEnv* env, jclass cls,
                                                jlong h, jlong snap,
                                                jbyteArray key) {
    (void)cls;
    char* err = NULL;
    size_t vl = 0;
    jsize kl = (*env)->GetArrayLength(env, key);
    jbyte* k = (*env)->GetByteArrayElements(env, key, NULL);
    if (!k) return NULL;
    char* v = tpulsm_get_at_snapshot(
        (tpulsm_db_t*)(intptr_t)h, (tpulsm_snapshot_t*)(intptr_t)snap,
        (const char*)k, (size_t)kl, &vl, &err);
    (*env)->ReleaseByteArrayElements(env, key, k, JNI_ABORT);
    if (check_err(env, err)) { tpulsm_free(v); return NULL; }
    if (!v) return NULL;
    jbyteArray out = (*env)->NewByteArray(env, (jsize)vl);
    if (out)
        (*env)->SetByteArrayRegion(env, out, 0, (jsize)vl, (const jbyte*)v);
    tpulsm_free(v);
    return out;
}

JNIEXPORT void JNICALL
Java_org_toplingdb_TpuLsmDB_checkpointNative(JNIEnv* env, jclass cls,
                                             jlong h, jstring dest) {
    (void)cls;
    char* err = NULL;
    const char* cdest = (*env)->GetStringUTFChars(env, dest, NULL);
    if (!cdest) return;
    tpulsm_checkpoint_create((tpulsm_db_t*)(intptr_t)h, cdest, &err);
    (*env)->ReleaseStringUTFChars(env, dest, cdest);
    check_err(env, err);
}
