"""Benchmark: L2+ compaction throughput per chip (the BASELINE.json metric).

Workload: fillrandom-style overwrite stream (8B keys, 20B values, 2x
overwrite factor) pre-built into 4 sorted input runs (real SSTs), then ONE
compaction job — merge + MVCC GC + SST encode — executed through the device
data plane (ops/device_compaction) on the available chip, end-to-end
including SST read and write.

Baseline: the reference's published manual compaction of 100M keys (8B/20B)
in 24.34 s (BlockBasedTable config, 16-core Xeon 8369HB —
BASELINE.md "manual compact"), i.e. ~115 MB/s of raw KV per machine. That is
the closest published number to "L2 compaction MB/s"; vs_baseline is
ours / 115.

Prints ONE JSON line:
  {"metric": "l2_compaction_MBps_per_chip", "value": ..., "unit": "MB/s",
   "vs_baseline": ...}

Env knobs: BENCH_N (entries, default 1_000_000), BENCH_DEVICE (tpu|cpu-jax|
cpu, default tpu), BENCH_RUNS (timed repetitions, default 4; best is kept).
"""

import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")

BASELINE_MBPS = 115.0  # reference manual compact: 2.8 GB raw / 24.34 s


def build_inputs(env, dbdir, icmp, n_entries, num_runs=4):
    """Vectorized input builder: 8B keys / 20B values, ~2x overwrite
    factor, one sorted run per file, written through the native columnar
    writer (byte-identical to TableBuilder per tests/test_columnar_writer)."""
    import numpy as np

    from toplingdb_tpu.db import filename as fn
    from toplingdb_tpu.db.dbformat import ValueType
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.ops.columnar_io import ColumnarKV, write_tables_columnar
    from toplingdb_tpu.table.builder import TableOptions

    rng = np.random.default_rng(1234)
    topts = TableOptions(block_size=4096)
    key_space = max(n_entries // 2, 1)  # ~2x overwrite factor
    per_run = n_entries // num_runs
    metas = []
    raw_bytes = 0
    counter = [9]

    def alloc():
        counter[0] += 1
        return counter[0]

    for run in range(num_runs):
        n = per_run
        draws = rng.integers(0, key_space, n, dtype=np.int64)
        seqs = np.arange(run * per_run + 1, run * per_run + n + 1,
                         dtype=np.uint64)
        # 8 ASCII decimal digits per key ("%08d"), then the 8B trailer.
        ik = np.empty((n, 16), dtype=np.uint8)
        for j in range(8):
            ik[:, 7 - j] = (draws // 10 ** j) % 10 + ord("0")
        packed = (seqs << np.uint64(8)) | np.uint64(int(ValueType.VALUE))
        ik[:, 8:] = packed[:, None] >> (np.arange(8) * 8).astype(
            np.uint64)[None, :] & np.uint64(0xFF)
        vals = np.full((n, 20), ord("v"), dtype=np.uint8)
        vals[:, 19] = (seqs % 10 + ord("0")).astype(np.uint8)
        # user key asc, seqno desc
        s = np.lexsort((np.iinfo(np.int64).max - seqs.view(np.int64), draws))
        kv = ColumnarKV(
            np.ascontiguousarray(ik[s]).reshape(-1),
            np.arange(n, dtype=np.int32) * 16,
            np.full(n, 16, dtype=np.int32),
            np.ascontiguousarray(vals[s]).reshape(-1),
            np.arange(n, dtype=np.int32) * 20,
            np.full(n, 20, dtype=np.int32),
        )
        files = write_tables_columnar(
            env, dbdir, alloc, icmp, topts, kv,
            np.arange(n, dtype=np.int32),
            np.full(n, -1, dtype=np.int64),
            np.full(n, int(ValueType.VALUE), dtype=np.int32),
            seqs[s], [], creation_time=1,
        )
        raw_bytes += 36 * n
        for fnum, path, props, smallest, largest, _sel in files:
            metas.append(FileMetaData(
                number=fnum, file_size=env.get_file_size(path),
                smallest=smallest, largest=largest,
                smallest_seqno=props.smallest_seqno,
                largest_seqno=props.largest_seqno,
            ))
    return metas, topts, raw_bytes


def main():
    n_entries = int(os.environ.get("BENCH_N", "1000000"))
    device = os.environ.get("BENCH_DEVICE", "tpu")
    # Best-of-N: the first run eats compiles, and tunneled transfers have
    # high variance, so give the steady state a few chances to show.
    runs = int(os.environ.get("BENCH_RUNS", "4"))

    tpu_fallback = False
    if device in ("tpu", "cpu-jax"):
        from toplingdb_tpu.utils.backend_probe import ensure_reachable_backend

        probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
        probe_tries = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
        print(f"probing jax backend ({probe_tries}x{probe_s:.0f}s budget)...",
              file=sys.stderr, flush=True)
        if not ensure_reachable_backend(probe_s, attempts=probe_tries,
                                        backoff_s=30.0):
            # Unreachable accelerator (process now on the cpu backend):
            # run the same data plane through the byte-parity host twins
            # and SAY SO rather than hang with no output.
            tpu_fallback = True
            os.environ["TPULSM_HOST_SORT"] = "1"
            print("jax backend unreachable; falling back to cpu backend",
                  file=sys.stderr, flush=True)

    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.dbformat import InternalKeyComparator
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops.device_compaction import run_device_compaction

    icmp = InternalKeyComparator()
    env = default_env()
    base = tempfile.mkdtemp(prefix="bench_", dir="/dev/shm"
                            if os.path.isdir("/dev/shm") else None)
    metas, topts, raw_bytes = build_inputs(env, base, icmp, n_entries)
    input_bytes = sum(m.file_size for m in metas)

    tc = TableCache(env, base, icmp, topts)
    best = None
    counter = [1000]

    def alloc():
        counter[0] += 1
        return counter[0]

    for r in range(runs):
        # Overlapping sorted runs are L0-shaped inputs (each gets its own
        # iterator on the CPU path); output level 2 = the "L2+" metric shape.
        c = Compaction(
            level=0, output_level=2, inputs=list(metas), bottommost=True,
            max_output_file_size=1 << 62,
        )
        t0 = time.time()
        if device in ("tpu", "cpu-jax"):
            outputs, stats = run_device_compaction(
                env, base, icmp, c, tc, topts, [], new_file_number=alloc,
                creation_time=1, device_name=device,
            )
        else:
            outputs, stats = run_compaction_to_tables(
                env, base, icmp, c, tc, topts, [], new_file_number=alloc,
                creation_time=1,
            )
        dt = time.time() - t0
        if best is None or dt < best[0]:
            best = (dt, outputs, stats)
        for m in outputs:
            from toplingdb_tpu.db import filename as fn

            env.delete_file(fn.table_file_name(base, m.number))

    dt, outputs, stats = best
    mbps = input_bytes / dt / 1e6
    result = {
        "metric": "l2_compaction_MBps_per_chip",
        "value": round(mbps, 2),
        "unit": "MB/s",
        "vs_baseline": round(mbps / BASELINE_MBPS, 4),
        "detail": {
            "device": device,
            "tpu_unreachable_cpu_fallback": tpu_fallback,
            "n_entries": n_entries,
            "input_bytes": input_bytes,
            "raw_kv_bytes": raw_bytes,
            "wall_s": round(dt, 3),
            "output_records": stats.output_records,
            "input_records": stats.input_records,
        },
    }
    print(json.dumps(result))
    import shutil

    shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
