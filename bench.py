"""Benchmark: L2+ compaction throughput per chip (the BASELINE.json metric).

Workload: fillrandom-style overwrite stream (8B keys, 20B values, 2x
overwrite factor) pre-built into 4 sorted input runs (real SSTs, SNAPPY
compressed — the reference db_bench default the 24.34s manual-compact
baseline ran with), then ONE compaction job — merge + MVCC GC + SST encode
— executed through the device data plane (ops/device_compaction) on the
available chip, end-to-end including SST read and write.

Honest accounting: the metric numerator is RAW USER KV BYTES (8B key +
20B value = 28B/entry), matching the baseline's definition (2.8 GB of user
data / 24.34 s = ~115 MB/s on a 16-core Xeon 8369HB) — NOT file bytes,
which carry trailers/framing and would inflate the ratio ~30%.

Prints ONE JSON line:
  {"metric": "l2_compaction_MBps_per_chip", "value": ..., "unit": "MB/s",
   "vs_baseline": ...}
with `detail` rows: a NO_COMPRESSION + a zstd compaction variant, a
bottommost ZipTable emission run, multi-thread fillrandom (plain vs
unordered+concurrent-memtable) and readrandom ops/s through the full DB
(sustained multi-job flush+compaction sequence), and the DB's write
amplification over that sequence.

Env knobs: BENCH_N (compaction entries, default 10_000_000), BENCH_DB_N
(DB-path entries, default 1_000_000), BENCH_DEVICE (tpu|cpu-jax|cpu),
BENCH_RUNS (timed repetitions, default 3; best kept), BENCH_FAST=1 (skip
the detail variants; headline metric only).
"""

import json
import os
import shutil
import sys
import tempfile
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")

BASELINE_MBPS = 115.0  # reference manual compact: 2.8 GB raw / 24.34 s
RAW_PER_ENTRY = 28     # 8B user key + 20B value (the baseline's accounting)

# Full probe evidence (multi-KB hang stacks) goes to a SIDE FILE, never the
# result line: r04's record was destroyed by embedding it (the driver keeps
# only the stdout tail, so a bloated line loses its head — and its "value").
PROBE_EVIDENCE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_PROBES.json")


def file_probe_evidence(detail, probe_diags):
    """Write full probe diagnostics to the side file; keep only a one-line
    summary per attempt (≤160 chars) in the result record."""
    if not probe_diags:
        return
    try:
        with open(PROBE_EVIDENCE_PATH, "w") as f:
            json.dump({"probes": probe_diags}, f, indent=1)
        detail["backend_probes_file"] = os.path.basename(PROBE_EVIDENCE_PATH)
    except OSError as e:
        detail["backend_probes_file_error"] = str(e)[:120]
    summaries = []
    for p in probe_diags:
        s = p if isinstance(p, str) else json.dumps(p)
        summaries.append(" ".join(s.split())[:160])
    detail["backend_probes_summary"] = summaries


def fill_phase_detail(detail, stats):
    """phase_breakdown + top_phases from a CompactionStats — NUMERIC values
    only in the sort, excluding the derived overlap row (it is not a busy
    phase; it is sum(phases) - wall under the pipelined data plane)."""
    detail["phase_breakdown"] = stats.phase_dict()
    phases = {k: v for k, v in detail["phase_breakdown"].items()
              if k not in ("work_time_s", "pipeline_overlap_s")
              and isinstance(v, (int, float))}
    detail["top_phases"] = sorted(phases, key=phases.get, reverse=True)[:2]


def build_inputs(env, dbdir, icmp, n_entries, topts, num_runs=4, seed=1234):
    """Vectorized input builder: 8B keys / 20B values, ~2x overwrite
    factor, one sorted run per file, written through the native columnar
    writer (byte-identical to TableBuilder per tests/test_columnar_writer)."""
    import numpy as np

    from toplingdb_tpu.db.dbformat import ValueType
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.ops.columnar_io import ColumnarKV, write_tables_columnar

    rng = np.random.default_rng(seed)
    key_space = max(n_entries // 2, 1)  # ~2x overwrite factor
    per_run = n_entries // num_runs
    metas = []
    counter = [9]

    def alloc():
        counter[0] += 1
        return counter[0]

    for run in range(num_runs):
        n = per_run
        draws = rng.integers(0, key_space, n, dtype=np.int64)
        seqs = np.arange(run * per_run + 1, run * per_run + n + 1,
                         dtype=np.uint64)
        # 8 ASCII decimal digits per key ("%08d"), then the 8B trailer.
        ik = np.empty((n, 16), dtype=np.uint8)
        for j in range(8):
            ik[:, 7 - j] = (draws // 10 ** j) % 10 + ord("0")
        packed = (seqs << np.uint64(8)) | np.uint64(int(ValueType.VALUE))
        ik[:, 8:] = packed[:, None] >> (np.arange(8) * 8).astype(
            np.uint64)[None, :] & np.uint64(0xFF)
        vals = np.full((n, 20), ord("v"), dtype=np.uint8)
        vals[:, 19] = (seqs % 10 + ord("0")).astype(np.uint8)
        # user key asc, seqno desc
        s = np.lexsort((np.iinfo(np.int64).max - seqs.view(np.int64), draws))
        kv = ColumnarKV(
            np.ascontiguousarray(ik[s]).reshape(-1),
            np.arange(n, dtype=np.int32) * 16,
            np.full(n, 16, dtype=np.int32),
            np.ascontiguousarray(vals[s]).reshape(-1),
            np.arange(n, dtype=np.int32) * 20,
            np.full(n, 20, dtype=np.int32),
        )
        files = write_tables_columnar(
            env, dbdir, alloc, icmp, topts, kv,
            np.arange(n, dtype=np.int32),
            np.full(n, -1, dtype=np.int64),
            np.full(n, int(ValueType.VALUE), dtype=np.int32),
            seqs[s], [], creation_time=1,
        )
        for fnum, path, props, smallest, largest, _sel in files:
            metas.append(FileMetaData(
                number=fnum, file_size=env.get_file_size(path),
                smallest=smallest, largest=largest,
                smallest_seqno=props.smallest_seqno,
                largest_seqno=props.largest_seqno,
            ))
    return metas


def time_compaction(env, base, icmp, metas, topts, out_topts, device, runs,
                    alloc_base):
    """Best-of-N wall of one L0->L2 job; returns (dt, stats, input_bytes)."""
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db import filename as fn
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.ops.device_compaction import run_device_compaction

    tc = TableCache(env, base, icmp, topts)
    counter = [alloc_base]

    def alloc():
        counter[0] += 1
        return counter[0]

    best = None
    run_times = []
    for _ in range(runs):
        c = Compaction(
            level=0, output_level=2, inputs=list(metas), bottommost=True,
            max_output_file_size=1 << 62,
        )
        t0 = time.time()
        if device in ("tpu", "cpu-jax"):
            try:
                outputs, stats = run_device_compaction(
                    env, base, icmp, c, tc, out_topts, [],
                    new_file_number=alloc, creation_time=1,
                    device_name=device,
                )
            except Exception as e:  # noqa: BLE001
                # A compiled-kernel failure on the real chip (e.g. a
                # Mosaic lowering gap) must degrade to the conservative
                # kernels, not kill the bench. Clear the trace caches so
                # the kernel-choice env vars re-read.
                print(f"device path failed ({e!r:.200}); retrying with "
                      "conservative kernels", file=sys.stderr, flush=True)
                os.environ["TPULSM_PALLAS_GC"] = "0"
                os.environ["TPULSM_DEVICE_MERGE"] = "0"
                import jax

                jax.clear_caches()
                t0 = time.time()
                outputs, stats = run_device_compaction(
                    env, base, icmp, c, tc, out_topts, [],
                    new_file_number=alloc, creation_time=1,
                    device_name=device,
                )
        else:
            outputs, stats = run_compaction_to_tables(
                env, base, icmp, c, tc, out_topts, [], new_file_number=alloc,
                creation_time=1,
            )
        dt = time.time() - t0
        run_times.append(round(dt, 3))
        if best is None or dt < best[0]:
            best = (dt, stats)
        for m in outputs:
            env.delete_file(fn.table_file_name(base, m.number))
    return best[0], best[1], sum(m.file_size for m in metas), run_times


def replication_rows(detail):
    """readwhilewriting_replica_ops: router read throughput while a writer
    hammers the primary, reads served by a tailing follower (the
    replication plane's whole point: read fan-out off the primary's write
    path); replication_lag_ms from the ship→apply lag histogram."""
    import random as _r
    import threading

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.replication import (
        FollowerDB, LocalTransport, LogShipper, ReplicaRouter,
    )
    from toplingdb_tpu.utils import statistics as st

    d = tempfile.mkdtemp(prefix="benchrepl_", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
    stats = st.Statistics()
    db = DB.open(d, Options(create_if_missing=True,
                            write_buffer_size=64 << 20, statistics=stats))
    n_seed = 20_000
    for i in range(0, n_seed, 500):
        b = WriteBatch()
        for j in range(i, i + 500):
            b.put(b"%016d" % j, b"v" * 64)
        db.write(b)
    ship = LogShipper(db)
    fol = FollowerDB.open(d, Options(statistics=stats),
                          transport=LocalTransport(ship), mode="shared")
    fol.start_tailing(interval=0.002)
    router = ReplicaRouter(db, [fol])
    stop = threading.Event()

    def writer():
        i = n_seed
        while not stop.is_set():
            b = WriteBatch()
            for j in range(i, i + 100):
                b.put(b"%016d" % (j % (2 * n_seed)), b"w" * 64)
            router.write(b)
            i += 100

    wt = threading.Thread(target=writer)
    wt.start()
    rng = _r.Random(17)
    t0 = time.time()
    reads = 0
    try:
        while time.time() - t0 < 2.0:
            for _ in range(200):
                router.get(b"%016d" % rng.randrange(n_seed))
            reads += 200
    finally:
        stop.set()
        wt.join()
    dt = time.time() - t0
    detail["readwhilewriting_replica_ops"] = round(reads / dt)
    fr = stats.get_ticker_count(st.ROUTER_FOLLOWER_READS)
    pr = stats.get_ticker_count(st.ROUTER_PRIMARY_READS)
    if fr + pr:
        detail["replica_read_pct"] = round(100 * fr / (fr + pr), 1)
    h = stats.get_histogram(st.REPLICATION_LAG_MICROS)
    if h.count:
        detail["replication_lag_ms"] = round(h.average / 1000, 3)
    fol.close()
    db.close()
    shutil.rmtree(d, ignore_errors=True)


def sharding_rows(detail):
    """1 vs 4 local shards through the ShardRouter: prebuilt per-shard
    WriteBatches pushed by 4 writer threads (the native write plane
    releases the GIL for frame+insert, so independent shard primaries
    genuinely overlap), then readrandom through the router; finally a
    hot-tenant admission check — one rate-limited tenant hammering shard
    s0 while siblings keep writing, sibling throughput must hold."""
    import random as _r
    import threading

    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.sharding import (
        AdmissionController, TenantQuota, open_local_cluster,
    )

    n_keys = 200_000
    vlen = 400
    bs = 250
    T = 4

    def bounds(nsh):
        step = n_keys // nsh
        return [(f"s{i}",
                 None if i == 0 else b"%016d" % (i * step),
                 None if i == nsh - 1 else b"%016d" % ((i + 1) * step))
                for i in range(nsh)]

    def mkbatches(nsh):
        per = n_keys // nsh
        out = []
        for i in range(nsh):
            keys = list(range(i * per, (i + 1) * per))
            _r.Random(i).shuffle(keys)
            out.append([
                _mk_batch(keys[j:j + bs], vlen, WriteBatch)
                for j in range(0, per, bs)
            ])
        return out

    def run(nsh):
        d = tempfile.mkdtemp(prefix=f"benchshard{nsh}_", dir="/dev/shm"
                             if os.path.isdir("/dev/shm") else None)
        # Small memtables so the fill actually flushes + compacts: the
        # scaling story is N independent LSM pipelines, not N memtables.
        router = open_local_cluster(
            d, bounds(nsh),
            options_factory=lambda n: Options(create_if_missing=True,
                                              write_buffer_size=8 << 20))
        batches = mkbatches(nsh)

        def wfill(t):
            if nsh == 1:
                mine, shard = batches[0][t::T], "s0"
            else:
                mine, shard = batches[t % nsh], f"s{t % nsh}"
            for b in mine:
                router.write(b, shard=shard)

        threads = [threading.Thread(target=wfill, args=(t,))
                   for t in range(T)]
        t0 = time.time()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        fill_ops = n_keys / (time.time() - t0)

        stop = threading.Event()
        counts = [0] * T

        def rrd(t):
            rng = _r.Random(100 + t)
            while not stop.is_set():
                for _ in range(100):
                    router.get(b"%016d" % rng.randrange(n_keys))
                counts[t] += 100

        threads = [threading.Thread(target=rrd, args=(t,))
                   for t in range(T)]
        t0 = time.time()
        for t in threads:
            t.start()
        time.sleep(1.2)
        stop.set()
        for t in threads:
            t.join()
        read_ops = sum(counts) / (time.time() - t0)
        router.close()
        shutil.rmtree(d, ignore_errors=True)
        return fill_ops, read_ops

    f1, r1 = run(1)
    f4, r4 = run(4)
    detail["fillrandom_1shard_ops_s"] = round(f1)
    detail["fillrandom_4shard_ops_s"] = round(f4)
    detail["readrandom_1shard_ops_s"] = round(r1)
    detail["readrandom_4shard_ops_s"] = round(r4)
    detail["shard_scaling_x"] = round(f4 / max(1.0, f1), 2)

    # -- hot-tenant isolation: siblings keep their throughput -------------
    # Fair comparison: SAME thread count in both phases (a 4th GIL-bound
    # thread alone costs ~25% in-process, which a multi-process deployment
    # would not see) — the 4th tenant goes from in-quota pacing to
    # flooding, and admission shedding must keep the siblings level.
    # Fresh cluster per phase + interleaved best-of-2 (the integrity_rows
    # pattern) to damp scheduler noise.
    from toplingdb_tpu.utils.status import Busy

    def sibling_phase(flood: bool, dur: float = 1.2):
        d = tempfile.mkdtemp(prefix="benchshardht_", dir="/dev/shm"
                             if os.path.isdir("/dev/shm") else None)
        adm = AdmissionController()
        adm.set_quota("hot", TenantQuota(write_ops_per_sec=500,
                                         max_wait=0.0))
        router = open_local_cluster(
            d, bounds(4), admission=adm,
            options_factory=lambda n: Options(create_if_missing=True,
                                              write_buffer_size=64 << 20))
        stop = threading.Event()
        sib = [0] * 3
        hot = [0, 0]  # served, shed

        def sib_writer(t):
            shard = t + 1  # shards s1..s3
            step = n_keys // 4
            i = shard * step
            while not stop.is_set():
                b = _mk_batch(range(i, i + 100), vlen, WriteBatch,
                              lo=shard * step, hi=(shard + 1) * step)
                router.write(b, shard=f"s{shard}", tenant=f"sib{t}")
                sib[t] += 100
                i += 100

        def hot_writer():
            rng = _r.Random(9)
            while not stop.is_set():
                try:
                    router.put(b"%016d" % rng.randrange(n_keys // 4),
                               b"h" * vlen, tenant="hot")
                    hot[0] += 1
                except Busy:
                    hot[1] += 1
                    time.sleep(0.001)  # client backoff after a shed
                if not flood:
                    time.sleep(1 / 400)  # a well-behaved tenant's pacing

        threads = [threading.Thread(target=sib_writer, args=(t,))
                   for t in range(3)]
        threads.append(threading.Thread(target=hot_writer))
        t0 = time.time()
        for t in threads:
            t.start()
        time.sleep(dur)
        stop.set()
        for t in threads:
            t.join()
        rate = sum(sib) / (time.time() - t0)
        router.close()
        shutil.rmtree(d, ignore_errors=True)
        return rate, hot

    sib_base = sib_loaded = 0.0
    hot = [0, 0]
    for _ in range(2):
        rate, _h = sibling_phase(flood=False)
        sib_base = max(sib_base, rate)
        rate, h = sibling_phase(flood=True)
        if rate > sib_loaded:
            sib_loaded, hot = rate, h
    detail["sibling_base_ops_s"] = round(sib_base)
    detail["sibling_with_hot_ops_s"] = round(sib_loaded)
    detail["sibling_keep_pct"] = round(100 * sib_loaded
                                       / max(1.0, sib_base), 1)
    detail["hot_tenant_served_ops"] = hot[0]
    detail["hot_tenant_shed_ops"] = hot[1]


_FLEET_DRIVER = """
import random
import sys
import time

from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.sharding.fleet import FleetRouter
from toplingdb_tpu.sharding.lease import LeaseClient

co_url, shard = sys.argv[1], sys.argv[2]
lo, hi, bs, vlen, seed = (int(a) for a in sys.argv[3:8])
keys = list(range(lo, hi))
random.Random(seed).shuffle(keys)
v = b"s" * vlen
batches = []
for j in range(0, len(keys), bs):
    b = WriteBatch()
    for k in keys[j:j + bs]:
        b.put(b"%016d" % k, v)
    batches.append(b)
router = FleetRouter(LeaseClient(co_url), map_lease=60.0)
print("READY", flush=True)   # batches prebuilt; wait for the gun
sys.stdin.readline()
for b in batches:
    router.write(b, shard=shard)
"""


def fleet_rows(detail):
    """1-process vs 4-process out-of-process fleet fillrandom: a real
    lease-coordinator process plus one ShardServer process per shard,
    prebuilt per-shard WriteBatches pushed over HTTP through the
    FleetRouter by 4 driver PROCESSES (one client process cannot feed
    4 servers — its GIL becomes the bottleneck and the measurement
    flattens). Everything here genuinely overlaps across cores, so the
    4-process fleet must sustain at least the in-process plane's
    shard_scaling_x despite paying the HTTP hop."""
    import subprocess

    from toplingdb_tpu.sharding.fleet import FleetSupervisor
    from toplingdb_tpu.sharding.shard_map import ShardMap

    n_keys = 100_000
    vlen = 400
    bs = 250
    T = 4

    def bounds(nsh):
        step = n_keys // nsh
        return [(f"s{i}",
                 None if i == 0 else b"%016d" % (i * step),
                 None if i == nsh - 1 else b"%016d" % ((i + 1) * step))
                for i in range(nsh)]

    def run(nsh):
        d = tempfile.mkdtemp(prefix=f"benchfleet{nsh}_", dir="/dev/shm"
                             if os.path.isdir("/dev/shm") else None)
        co_proc, co_url = FleetSupervisor.start_coordinator(
            os.path.join(d, "lease.jsonl"), ttl=30.0)
        sup = FleetSupervisor(co_url, lease_ttl=30.0)
        drivers = []
        try:
            sup.coordinator.install_map(
                ShardMap.from_bounds(bounds(nsh)).to_config(), {})
            members = [sup.spawn_server(f"s{i}", os.path.join(d, f"s{i}"))
                       for i in range(nsh)]
            doc = sup.coordinator.get_map()
            sup.coordinator.cas_map(doc["version"], doc["map"],
                                    {m.shard: m.url for m in members})
            # One driver process per writer: disjoint key slices, each
            # slice entirely inside one shard's range.
            per = n_keys // T
            step = n_keys // max(nsh, 1)
            for t in range(T):
                if nsh == 1:
                    shard, lo, hi = "s0", t * per, (t + 1) * per
                else:
                    i = t % nsh
                    shard, lo, hi = f"s{i}", i * step, (i + 1) * step
                drivers.append(subprocess.Popen(
                    [sys.executable, "-c", _FLEET_DRIVER, co_url, shard,
                     str(lo), str(hi), str(bs), str(vlen), str(t)],
                    env=FleetSupervisor._proc_env(),
                    stdin=subprocess.PIPE, stdout=subprocess.PIPE))
            for p in drivers:  # all batches built before the clock starts
                assert p.stdout.readline().strip() == b"READY"
            t0 = time.time()
            for p in drivers:
                p.stdin.write(b"\n")
                p.stdin.flush()
            for p in drivers:
                if p.wait() != 0:
                    raise RuntimeError("fleet fill driver failed")
            return n_keys / (time.time() - t0)
        finally:
            for p in drivers:
                if p.poll() is None:
                    p.kill()
                    p.wait()
            sup.stop_all()
            co_proc.terminate()
            try:
                co_proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 - bench teardown
                co_proc.kill()
                co_proc.wait()
            shutil.rmtree(d, ignore_errors=True)

    f1 = run(1)
    f4 = run(4)
    detail["fleet_fill_1proc_ops_s"] = round(f1)
    detail["fleet_fill_4proc_ops_s"] = round(f4)
    detail["fleet_scaling_x"] = round(f4 / max(1.0, f1), 2)


def _mk_batch(keys, vlen, WriteBatch, lo=None, hi=None):
    b = WriteBatch()
    v = b"s" * vlen
    for k in keys:
        if hi is not None:
            k = lo + (k - lo) % (hi - lo)
        b.put(b"%016d" % k, v)
    return b


def integrity_rows(detail, n_db):
    """Integrity-plane rows: protected fillrandom (per-entry protection
    computed at WriteBatch build + fused re-verify at memtable insert)
    vs an unprotected twin, and the scrubber's sweep throughput over the
    protected DB's SSTs. Plain/protected runs are INTERLEAVED and the
    best of each kept — the overhead row divides two measurements, so
    machine drift between them would otherwise read as fake overhead."""
    import threading

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options

    n = max(50_000, min(200_000, n_db // 5))
    n_threads = int(os.environ.get("BENCH_THREADS", "4"))
    per_thread = n // n_threads
    batch = 100

    def fill(pb):
        d = tempfile.mkdtemp(prefix="benchint_", dir="/dev/shm"
                             if os.path.isdir("/dev/shm") else None)
        db = DB.open(d, Options(create_if_missing=True,
                                write_buffer_size=8 << 20,
                                protection_bytes_per_key=pb,
                                integrity_scrub_bytes_per_sec=0))
        errs = []

        def worker(t):
            try:
                for i in range(0, per_thread, batch):
                    b = WriteBatch()
                    for j in range(i, i + batch):
                        k = (t * per_thread + j) * 2654435761 % (n * 2)
                        b.put(b"%016d" % k, b"v" * 20)
                    db.write(b)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(n_threads)]
        t0 = time.time()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.time() - t0
        assert not errs, errs
        return db, d, n / dt

    best_plain = best_prot = 0.0
    scrub_db = scrub_dir = None
    for _ in range(3):
        db, d, rate = fill(0)
        best_plain = max(best_plain, rate)
        db.close()
        shutil.rmtree(d, ignore_errors=True)
        db, d, rate = fill(8)
        best_prot = max(best_prot, rate)
        if scrub_db is not None:
            scrub_db.close()
            shutil.rmtree(scrub_dir, ignore_errors=True)
        scrub_db, scrub_dir = db, d

    user_bytes_per_entry = 36  # 16B key + 20B value (this row's workload)
    detail["fillrandom_protected_MBps"] = round(
        best_prot * user_bytes_per_entry / 1e6, 2)
    detail["fillrandom_plain_twin_MBps"] = round(
        best_plain * user_bytes_per_entry / 1e6, 2)
    detail["protection_overhead_pct"] = round(
        100 * (1 - best_prot / best_plain), 1)

    # Scrubber sweep rate: every live SST re-read from disk and its
    # whole-file checksum compared against the MANIFEST — the background
    # pass's work, unpaced (the default 32 MiB/s token bucket would
    # measure the pacer, not the scrubber).
    scrub_db.flush()
    scrub_db.wait_for_compactions()
    rep = scrub_db.scrub()
    if rep.get("bytes_verified") and rep.get("pass_micros"):
        detail["integrity_scrub_MBps"] = round(
            rep["bytes_verified"] / rep["pass_micros"], 2)
    detail["integrity_scrub_corruptions"] = len(rep.get("corruptions", ()))
    scrub_db.close()
    shutil.rmtree(scrub_dir, ignore_errors=True)


def observability_rows(detail, n_db):
    """Telemetry-plane overhead rows: fillrandom/readrandom twins with
    tracing off / sampled 1-in-64 / always-on. All three modes run as
    fine-grained INTERLEAVED segments on the SAME DB instance (separate
    twin DBs drift by several percent from layout/compaction timing
    alone, which would swamp a ~1% effect); Statistics is attached —
    the repo-served rockside-role DB this plane exists for always
    carries a stats sink, so that is the measured baseline. Gate:
    sampled <= 2% (`trace_overhead_pct`)."""
    import itertools as _it

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import telemetry as _tm
    from toplingdb_tpu.utils.statistics import Statistics

    n = max(60_000, min(240_000, n_db // 5))
    batch = 100
    seg = 3000  # ops per timed segment before rotating modes
    keys = [b"%016d" % ((i * 2654435761) % (n * 2)) for i in range(n)]

    d = tempfile.mkdtemp(prefix="benchobs_", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
    db = DB.open(d, Options(create_if_missing=True,
                            write_buffer_size=1 << 30,
                            statistics=Statistics()))

    def make_state(se):
        if se == 0:
            return (None, None)
        tr = _tm.Tracer(sample_every=se)
        return (tr, _it.cycle([0] * (se - 1) + [1]).__next__)

    import gc

    modes = ("off", "sampled", "always")
    states = {"off": make_state(0), "sampled": make_state(64),
              "always": make_state(1)}
    spent = {m: [0.0, 0] for m in modes}  # wall, ops (fill)
    rspent = {m: [0.0, 0] for m in modes}  # wall, ops (read)

    def set_mode(m):
        # Collect OUTSIDE the timed region so one mode's allocation debt
        # (always-on churns a trace per op) never bills a neighbor.
        gc.collect(0)
        db.tracer, db._trace_sched = states[m]

    def fill_seg(m, s0, hi):
        set_mode(m)
        t0 = time.perf_counter()
        for i in range(s0, hi, batch):
            b = WriteBatch()
            for k in keys[i:i + batch]:
                b.put(k, b"v" * 20)
            db.write(b)
        spent[m][0] += time.perf_counter() - t0
        spent[m][1] += hi - s0

    def read_seg(m, s0, hi):
        set_mode(m)
        t0 = time.perf_counter()
        for i in range(s0, hi):
            db.get(keys[(i * 7919) % n])
        rspent[m][0] += time.perf_counter() - t0
        rspent[m][1] += hi - s0

    try:
        # The GATED pair (off vs sampled) alternates in balanced A/B
        # order on one DB; always-on — informational, and heavy enough
        # to pollute neighbors — runs as its own tail slice.
        n_ab = n * 3 // 4
        for idx, s0 in enumerate(range(0, n_ab, seg)):
            fill_seg(("off", "sampled")[(idx + idx // 2) % 2],
                     s0, min(s0 + seg, n_ab))
        for s0 in range(n_ab, n, seg):
            fill_seg("always", s0, min(s0 + seg, n))
        # readrandom reads SST-resident data (the workload's normal
        # shape): flush so gets walk bloom + table, not just memtable.
        set_mode("off")
        db.flush()
        db.wait_for_compactions()
        nr = min(2 * n, 300_000)
        for i in range(0, nr, seg):
            db.get(keys[(i * 7919) % n])  # keep caches warm at rotation
        nr_ab = nr * 3 // 4
        for idx, s0 in enumerate(range(0, nr_ab, seg)):
            read_seg(("off", "sampled")[(idx + idx // 2) % 2],
                     s0, min(s0 + seg, nr_ab))
        for s0 in range(nr_ab, nr, seg):
            read_seg("always", s0, min(s0 + seg, nr))
    finally:
        db.tracer = None
        db._trace_sched = None
        db.close()
        shutil.rmtree(d, ignore_errors=True)

    for m in modes:
        detail[f"fillrandom_trace_{m}_ops_s"] = round(
            spent[m][1] / spent[m][0])
        detail[f"readrandom_trace_{m}_ops_s"] = round(
            rspent[m][1] / rspent[m][0])
    overhead = max(
        100 * (1 - detail["fillrandom_trace_sampled_ops_s"]
               / detail["fillrandom_trace_off_ops_s"]),
        100 * (1 - detail["readrandom_trace_sampled_ops_s"]
               / detail["readrandom_trace_off_ops_s"]),
    )
    detail["trace_overhead_pct"] = round(max(0.0, overhead), 2)


def health_rows(detail, n_db):
    """Health-plane overhead rows (ISSUE 12): fillrandom/readrandom with
    cumulative-only histograms vs windowed histograms + a live SLO
    engine, as interleaved A/B segments on the SAME DB (the
    observability_rows pattern — twin DBs drift more than the effect
    measured). The 'win' mode over-counts SLO cost on purpose: one full
    evaluation per ~3000-op segment, far more frequent than any real
    slo_eval_period_sec. Gate: `health_overhead_pct` <= 2, computed as
    the median win/cum rate ratio over adjacent segment pairs (robust to
    background-compaction spikes that whipsaw an aggregate mean)."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import statistics as _st
    from toplingdb_tpu.utils.slo import SLOEngine, SLOSpec
    from toplingdb_tpu.utils.statistics import Statistics

    n = max(60_000, min(240_000, n_db // 5))
    batch = 100
    seg = 3000
    segs = {"fill": [], "read": []}  # (mode, ops_per_sec) per segment
    keys = [b"%016d" % ((i * 2654435761) % (n * 2)) for i in range(n)]

    d = tempfile.mkdtemp(prefix="benchhp_", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
    cum = Statistics(histogram_window_sec=0)
    win = Statistics(histogram_window_sec=60.0)
    engine = SLOEngine(win, [
        SLOSpec(name="get-p99", kind="latency",
                histogram=_st.DB_GET_MICROS, objective=0.99,
                threshold_usec=10_000),
        SLOSpec(name="write-p99", kind="latency",
                histogram=_st.DB_WRITE_MICROS, objective=0.99,
                threshold_usec=50_000),
        SLOSpec(name="stall", kind="stall", objective=0.999),
    ], db_name="bench")
    # Opened with the cumulative sink; the windowed twin swaps in per
    # segment (every hot-path histogram add resolves through db.stats).
    db = DB.open(d, Options(create_if_missing=True,
                            write_buffer_size=1 << 30, statistics=cum))
    import gc

    modes = ("cum", "win")
    sinks = {"cum": cum, "win": win}
    spent = {m: [0.0, 0] for m in modes}   # wall, ops (fill)
    rspent = {m: [0.0, 0] for m in modes}  # wall, ops (read)

    def set_mode(m):
        gc.collect(0)
        db.stats = sinks[m]

    def fill_seg(m, s0, hi):
        set_mode(m)
        t0 = time.perf_counter()
        for i in range(s0, hi, batch):
            b = WriteBatch()
            for k in keys[i:i + batch]:
                b.put(k, b"v" * 20)
            db.write(b)
        if m == "win":
            engine.evaluate()
        dt = time.perf_counter() - t0
        spent[m][0] += dt
        spent[m][1] += hi - s0
        segs["fill"].append((m, (hi - s0) / dt))

    def read_seg(m, s0, hi):
        set_mode(m)
        t0 = time.perf_counter()
        for i in range(s0, hi):
            db.get(keys[(i * 7919) % n])
        if m == "win":
            engine.evaluate()
        dt = time.perf_counter() - t0
        rspent[m][0] += dt
        rspent[m][1] += hi - s0
        segs["read"].append((m, (hi - s0) / dt))

    try:
        for idx, s0 in enumerate(range(0, n, seg)):
            fill_seg(("cum", "win")[(idx + idx // 2) % 2],
                     s0, min(s0 + seg, n))
        set_mode("cum")
        db.flush()
        db.wait_for_compactions()
        nr = min(2 * n, 300_000)
        for i in range(0, nr, seg):
            db.get(keys[(i * 7919) % n])  # warm caches at rotation
        for idx, s0 in enumerate(range(0, nr, seg)):
            read_seg(("cum", "win")[(idx + idx // 2) % 2],
                     s0, min(s0 + seg, nr))
    finally:
        db.stats = cum
        db.close()
        shutil.rmtree(d, ignore_errors=True)

    for m in modes:
        detail[f"fillrandom_hist_{m}_ops_s"] = round(
            spent[m][1] / spent[m][0])
        detail[f"readrandom_hist_{m}_ops_s"] = round(
            rspent[m][1] / rspent[m][0])

    def paired_overhead(rows):
        # The interleave pattern is cum,win,win,cum,... — every adjacent
        # pair holds one segment of each mode, in alternating order, so
        # the per-pair win/cum rate ratio cancels slow drift (compaction
        # debt) and the MEDIAN over pairs shrugs off the occasional
        # background-compaction spike that dominates an aggregate mean.
        ratios = []
        for (ma, ra), (mb, rb) in zip(rows[::2], rows[1::2]):
            if ma == mb:
                continue
            w, c = (ra, rb) if ma == "win" else (rb, ra)
            ratios.append(w / c)
        if not ratios:
            return 0.0
        ratios.sort()
        return 100 * (1 - ratios[len(ratios) // 2])

    overhead = max(paired_overhead(segs["fill"]),
                   paired_overhead(segs["read"]))
    detail["health_overhead_pct"] = round(max(0.0, overhead), 2)


def concurrency_rows(detail, n_db):
    """Concurrency-plane overhead rows (ISSUE 13).

    `lock_factory_overhead_pct`: off-mode `ccy.Lock(name)` hands back a
    PLAIN threading.Lock, so an acquire/release spin through it must
    price identically to a raw lock — best-of interleaved reps, gate
    <= 1%.

    `lock_debug_overhead_pct`: fillrandom with every DB lock created as
    an instrumented debug wrapper vs a plain twin. Lock mode is fixed at
    creation time, so this is a twin-DB A/B: the same key segments run
    on both DBs in alternating order and the MEDIAN per-segment rate
    ratio sets the row (the health_rows drift argument). Reported as
    slowdown-minus-one percent; gate <= 100 (debug stays within 2x).
    The debug twin doubles as a soak: a lock inversion anywhere on the
    write path would raise out of this row."""
    import threading

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import concurrency as ccy

    # -- factory microbench (off mode) -----------------------------------
    raw = threading.Lock()
    fac = ccy.Lock("bench.concurrency_rows.fac")
    spins = 200_000

    def spin(lk):
        t0 = time.perf_counter()
        for _ in range(spins):
            with lk:
                pass
        return time.perf_counter() - t0

    best = {"raw": float("inf"), "fac": float("inf")}
    for rep in range(7):
        order = (("raw", raw), ("fac", fac)) if rep % 2 == 0 \
            else (("fac", fac), ("raw", raw))
        for name, lk in order:
            best[name] = min(best[name], spin(lk))
    detail["lock_factory_overhead_pct"] = round(
        max(0.0, 100.0 * (best["fac"] / best["raw"] - 1.0)), 2)

    # -- debug-wrapper fillrandom A/B (twin DBs) --------------------------
    n = max(40_000, min(120_000, n_db // 10))
    seg = 2000
    batch = 100
    keys = [b"%016d" % ((i * 2654435761) % (n * 2)) for i in range(n)]

    ccy.reset_lock_graph()
    dbs = {}
    try:
        for mode in ("off", "dbg"):
            d = tempfile.mkdtemp(prefix=f"benchccy_{mode}_",
                                 dir="/dev/shm"
                                 if os.path.isdir("/dev/shm") else None)
            ccy.set_debug(mode == "dbg")
            try:
                dbs[mode] = (DB.open(d, Options(create_if_missing=True,
                                                write_buffer_size=1 << 30)),
                             d)
            finally:
                ccy.set_debug(False)

        spent = {m: [0.0, 0] for m in ("off", "dbg")}
        ratios = []

        def fill_seg(mode, s0, hi):
            db = dbs[mode][0]
            t0 = time.perf_counter()
            for i in range(s0, hi, batch):
                b = WriteBatch()
                for k in keys[i:i + batch]:
                    b.put(k, b"v" * 20)
                db.write(b)
            dt = time.perf_counter() - t0
            spent[mode][0] += dt
            spent[mode][1] += hi - s0
            return (hi - s0) / dt

        for idx, s0 in enumerate(range(0, n, seg)):
            hi = min(s0 + seg, n)
            order = ("off", "dbg") if idx % 2 == 0 else ("dbg", "off")
            rates = {m: fill_seg(m, s0, hi) for m in order}
            ratios.append(rates["dbg"] / rates["off"])

        for m in ("off", "dbg"):
            detail[f"fillrandom_lock_{m}_ops_s"] = round(
                spent[m][1] / spent[m][0])
        ratios.sort()
        median = ratios[len(ratios) // 2]
        detail["lock_debug_overhead_pct"] = round(
            max(0.0, 100.0 * (1.0 / median - 1.0)), 2)
        detail["lock_debug_edges"] = len(ccy.lock_order_edges())
    finally:
        for db, d in dbs.values():
            try:
                db.close()
            finally:
                shutil.rmtree(d, ignore_errors=True)
        ccy.set_debug(False)
        ccy.reset_lock_graph()


def disk_pressure_rows(detail, n_db):
    """Storage-pressure plane overhead (ISSUE 20): fillrandom with the
    whole plane armed — a byte budget, the flush/compaction preflight
    math that budget enables, per-file manager accounting on every
    install/delete, and a HOT free-space poller (20ms cadence, far
    faster than any real deployment) — vs the plain twin with no
    manager at all. Interleaved best-of so drift can't read as
    overhead. Gate: `disk_pressure_overhead_pct` <= 1."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options

    n = max(60_000, min(200_000, n_db // 2))
    keys = [b"%016d" % ((i * 2654435761) % (n * 2)) for i in range(n)]

    def fill(armed):
        opts = Options(create_if_missing=True, write_buffer_size=1 << 22,
                       level0_file_num_compaction_trigger=4)
        if armed:
            opts.max_allowed_space_usage = 1 << 40  # never binds
            opts.free_space_poll_period_sec = 0.02
        d = tempfile.mkdtemp(prefix="benchdp_", dir="/dev/shm"
                             if os.path.isdir("/dev/shm") else None)
        db = DB.open(d, opts)
        try:
            t0 = time.perf_counter()
            for i in range(0, n, 100):
                b = WriteBatch()
                for k in keys[i:i + 100]:
                    b.put(k, b"v" * 20)
                db.write(b)
            dt = time.perf_counter() - t0
            if armed:
                assert db._sfm is not None and db.disk_pressure() == "ok"
        finally:
            db.close()
            shutil.rmtree(d, ignore_errors=True)
        return n / dt

    best = {"on": 0.0, "off": 0.0}
    for r in range(3):
        for mode in (("on", "off"), ("off", "on"))[r % 2]:
            best[mode] = max(best[mode], fill(mode == "on"))
    detail["fillrandom_disk_pressure_ops_s"] = round(best["on"])
    detail["fillrandom_disk_plain_ops_s"] = round(best["off"])
    detail["disk_pressure_overhead_pct"] = round(
        max(0.0, 100 * (1 - best["on"] / best["off"])), 2)


def write_plane_rows(detail, n_db):
    """Native group-commit write plane rows (ISSUE 7): protected WAL-on
    write-PATH fillrandom (prebuilt mixed-size batches so the row
    isolates queue + WAL + protection + memtable insert) with
    TPULSM_WRITE_PLANE=1 vs the =0 serial twin; a coalesced-fsync sync
    row (async WAL writer merging concurrent leaders' fsync barriers)
    vs inline-fsync; and an 8-writer concurrent run with its twin.
    Runs are interleaved best-of like integrity_rows: the headline
    divides two measurements, so drift must not read as speedup."""
    import threading

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options, WriteOptions

    n = max(100_000, min(1_000_000, n_db))

    def fill(knob, nt, sync=False, async_wal=False, batch_sizes=(100, 1000),
             on_disk=False, pipelined=False):
        saved = os.environ.get("TPULSM_WRITE_PLANE")
        os.environ["TPULSM_WRITE_PLANE"] = knob
        try:
            per = n // nt
            allb = []
            for t in range(nt):
                bs, i, si = [], 0, 0
                while i < per:
                    bsz = min(batch_sizes[si % len(batch_sizes)], per - i)
                    si += 1
                    b = WriteBatch(protection_bytes_per_key=8)
                    for j in range(i, i + bsz):
                        k = ((t * per + j) * 2654435761) % (n * 2)
                        b.put(b"%016d" % k, b"v" * 20)
                    bs.append(b)
                    i += bsz
                allb.append(bs)
            # Sync rows run on REAL disk (fsync on tmpfs is a no-op, which
            # would measure nothing); throughput rows stay on /dev/shm.
            d = tempfile.mkdtemp(prefix="benchwp_", dir=None if on_disk else (
                "/dev/shm" if os.path.isdir("/dev/shm") else None))
            db = DB.open(d, Options(create_if_missing=True,
                                    write_buffer_size=1 << 30,
                                    protection_bytes_per_key=8,
                                    enable_async_wal=async_wal,
                                    enable_pipelined_write=pipelined))
            wo = WriteOptions(sync=sync)
            errs = []

            def w(bs):
                try:
                    for b in bs:
                        db.write(b, wo)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            ts = [threading.Thread(target=w, args=(bs,)) for bs in allb]
            t0 = time.time()
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            dt = time.time() - t0
            assert not errs, errs
            db.close()
            shutil.rmtree(d, ignore_errors=True)
            return (nt * per) / dt
        finally:
            if saved is None:
                os.environ.pop("TPULSM_WRITE_PLANE", None)
            else:
                os.environ["TPULSM_WRITE_PLANE"] = saved

    rows = {
        "fillrandom_native_plane_ops_s": lambda: fill("1", 4),
        "fillrandom_plane_off_ops_s": lambda: fill("0", 4),
        "fillrandom_8w_ops_s": lambda: fill("1", 8),
        "fillrandom_8w_plane_off_ops_s": lambda: fill("0", 8),
    }
    best = {k: 0.0 for k in rows}
    for _ in range(3):
        for k, f in rows.items():
            best[k] = max(best[k], f())
    for k, v in best.items():
        detail[k] = round(v)

    # Sync rows at reduced scale (each group pays durability): coalesced
    # fsyncs through the async WAL writer vs inline per-group fsync.
    saved_n = n
    n = max(2_000, saved_n // 50)  # fill() closes over n
    # Pipelined: the durability barrier waits OUTSIDE the commit mutex, so
    # concurrent leaders' sync tokens overlap in the ring and coalesce.
    sync_rows = {
        "fillrandom_sync_ops_s": lambda: fill(
            "1", 4, sync=True, async_wal=True, on_disk=True,
            pipelined=True),
        "fillrandom_sync_inline_ops_s": lambda: fill(
            "1", 4, sync=True, async_wal=False, on_disk=True,
            pipelined=True),
    }
    sbest = {k: 0.0 for k in sync_rows}
    for _ in range(2):
        for k, f in sync_rows.items():
            sbest[k] = max(sbest[k], f())
    for k, v in sbest.items():
        detail[k] = round(v)
    n = saved_n


def async_read_rows(detail):
    """Cold-cache multireadrandom: batched block fan-out through the
    reader rings (TPULSM_ASYNC_READS=1) vs the serial sync twin (=0).

    Cold means tiny block cache + fresh file handles (the DB is
    reopened per run). Both twins run on a DelayedReadEnv modeling
    device read latency: on a page-cache-warm box a real pread is ~µs,
    so there is nothing to overlap — and the wrapped handles also keep
    both twins off the native fast chains (same Python walk), so the
    0/1 ratio isolates ring fan-out + coalescing, nothing else.
    Byte parity across the twins is asserted every run. Interleaved
    best-of, like write_plane_rows: the headline divides two
    measurements, so drift must not read as speedup."""
    import random as _r

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.env.fault_injection import DelayedReadEnv
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils.cache import LRUCache

    n = 30_000
    d = tempfile.mkdtemp(prefix="benchar_", dir="/dev/shm"
                         if os.path.isdir("/dev/shm") else None)
    db = DB.open(d, Options(create_if_missing=True,
                            write_buffer_size=128 * 1024))
    for i in range(n):
        db.put(b"%016d" % ((i * 2654435761) % (n * 2)), b"value-%016d" % i)
    db.flush()
    db.wait_for_compactions()
    db.close()
    rng = _r.Random(11)
    probes = [b"%016d" % ((rng.randrange(n) * 2654435761) % (n * 2))
              for _ in range(4096)]

    def run(knob):
        saved = os.environ.get("TPULSM_ASYNC_READS")
        os.environ["TPULSM_ASYNC_READS"] = knob
        try:
            env = DelayedReadEnv(default_env(), delay_sec=0.0002)
            dbr = DB.open(d, Options(block_cache=LRUCache(64 * 1024)),
                          env=env)
            t0 = time.time()
            out = [dbr.multi_get(probes[i:i + 128])
                   for i in range(0, len(probes), 128)]
            dt = time.time() - t0
            dbr.close()
            return len(probes) / dt, out
        finally:
            if saved is None:
                os.environ.pop("TPULSM_ASYNC_READS", None)
            else:
                os.environ["TPULSM_ASYNC_READS"] = saved

    best = {"1": 0.0, "0": 0.0}
    view = {}
    for _ in range(3):
        for knob in ("1", "0"):
            r, out = run(knob)
            best[knob] = max(best[knob], r)
            if knob in view:
                assert out == view[knob], "async/sync drift across runs"
            view[knob] = out
    assert view["1"] == view["0"], "async read plane parity violation"
    detail["multireadrandom_cold_ops_s"] = round(best["1"])
    detail["multireadrandom_cold_sync_ops_s"] = round(best["0"])
    detail["async_read_speedup_x"] = round(best["1"] / max(1.0, best["0"]),
                                           2)
    detail["async_read_delay_model_us"] = 200
    if os.cpu_count() == 1:
        # One core executes the ring threads serially: report the twin
        # ratio with its provenance instead of a hollow multi-core claim.
        detail["async_read_speedup_source"] = "1-core-host"
    shutil.rmtree(d, ignore_errors=True)


def storage_rows(detail):
    """Disaggregated SST storage (storage/): shard-migration wall-clock
    copy vs reference at 2 shard sizes, dcompact bytes shipped in store
    mode, and cold reads through the cache tier.

    The migration destination lives on a DIFFERENT filesystem than the
    source (/dev/shm vs disk) so the copy baseline pays real byte
    movement — same-fs restores hardlink, which would understate what a
    cross-node bootstrap costs. Reference mode swaps manifests + refs
    regardless of filesystem, so its wall-clock should be ~flat in
    shard size; migration_ref_speedup_x is the large-size copy/ref
    ratio."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.sharding import ShardMigration, open_local_cluster

    shm = "/dev/shm" if os.path.isdir("/dev/shm") else None
    vlen = 400

    def migrate(n_keys, shared):
        src_root = tempfile.mkdtemp(prefix="benchstore_", dir=shm)
        dest_root = tempfile.mkdtemp(prefix="benchstore_dst_",
                                     dir="/var/tmp")
        spec = os.path.join(src_root, "store") if shared else None

        def of(_name):
            return Options(create_if_missing=True,
                           write_buffer_size=1 << 20, shared_store=spec)

        r = open_local_cluster(src_root, [("s", None, None)],
                               options_factory=of)
        try:
            db = r._serving("s").primary
            v = b"s" * vlen
            for lo in range(0, n_keys, 1000):
                b = WriteBatch()
                for i in range(lo, min(lo + 1000, n_keys)):
                    b.put(b"%012d" % i, v)
                db.write(b)
            db.flush()
            db.compact_range()
            t0 = time.time()
            ShardMigration(r, "s", os.path.join(dest_root, "new")).run()
            return time.time() - t0
        finally:
            r.close()
            shutil.rmtree(src_root, ignore_errors=True)
            shutil.rmtree(dest_root, ignore_errors=True)

    small, large = 25_000, 100_000
    copy_s = migrate(small, shared=False)
    copy_l = migrate(large, shared=False)
    ref_s = migrate(small, shared=True)
    ref_l = migrate(large, shared=True)
    detail["migration_copy_small_s"] = round(copy_s, 3)
    detail["migration_copy_large_s"] = round(copy_l, 3)
    detail["migration_ref_small_s"] = round(ref_s, 3)
    detail["migration_ref_large_s"] = round(ref_l, 3)
    # ~1.0 when reference bootstrap is truly metadata-only.
    detail["migration_ref_flatness_x"] = round(ref_l / max(1e-6, ref_s), 2)
    detail["migration_ref_speedup_x"] = round(copy_l / max(1e-6, ref_l), 2)

    # -- dcompact store mode: zero SST bytes on the job transport ------
    from toplingdb_tpu.compaction.executor import (
        SubprocessCompactionExecutorFactory,
    )

    d = tempfile.mkdtemp(prefix="benchstore_dc_", dir=shm)
    shipped = []

    class Recording(SubprocessCompactionExecutorFactory):
        def new_executor(self, compaction):
            ex = super().new_executor(compaction)
            orig = ex.execute

            def execute(db, compaction, snapshots, new_file_number):
                outputs, stats = orig(db, compaction, snapshots,
                                      new_file_number)
                shipped.append(stats.sst_bytes_shipped)
                return outputs, stats

            ex.execute = execute
            return ex

    opts = Options(create_if_missing=True, write_buffer_size=256 << 10,
                   shared_store=os.path.join(d, "store"),
                   compaction_executor_factory=Recording(
                       device="cpu", job_root=os.path.join(d, "jobs")))
    db = DB.open(os.path.join(d, "db"), opts)
    try:
        v = b"s" * vlen
        for lo in (0, 4000):
            b = WriteBatch()
            for i in range(lo, lo + 4000):
                b.put(b"%012d" % i, v)
            db.write(b)
            db.flush()
        db.compact_range()
        db.wait_for_compactions()
        detail["dcompact_store_jobs"] = len(shipped)
        detail["dcompact_store_sst_bytes_shipped"] = sum(shipped)

        # -- cold reads through the cache tier -------------------------
        # A reference-restored twin of the DB: every table is a store
        # ref, so the first touch is a cold fetch (tier miss -> store),
        # after which reads run on local bytes.
        from toplingdb_tpu.utilities.checkpoint import Checkpoint

        ck = os.path.join(d, "ckpt")
        Checkpoint.create(db, ck)
        cold_dir = os.path.join(d, "cold")
        Checkpoint(ck, db.env).restore_to(cold_dir)
        db2 = DB.open(cold_dir, Options(create_if_missing=False),
                      env=db.env)
        try:
            import random as _r

            rng = _r.Random(7)
            keys = [b"%012d" % rng.randrange(8000) for _ in range(20_000)]
            t0 = time.time()
            for k in keys:
                assert db2.get(k) is not None
            detail["store_cold_read_ops_s"] = round(
                len(keys) / (time.time() - t0))
        finally:
            db2.close()
    finally:
        db.close()
        shutil.rmtree(d, ignore_errors=True)


def db_path_rows(detail, n_db):
    """Sustained multi-job DB rows: multi-thread fillrandom (plain vs
    unordered+concurrent), readrandom, write amplification."""
    import threading

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import statistics as st

    n_threads = int(os.environ.get("BENCH_THREADS", "4"))
    per_thread = n_db // n_threads
    batch = 100

    def fill(opts_kw):
        d = tempfile.mkdtemp(prefix="benchdb_", dir="/dev/shm"
                             if os.path.isdir("/dev/shm") else None)
        stats = st.Statistics()
        opts = Options(create_if_missing=True,
                       write_buffer_size=8 << 20,
                       statistics=stats, **opts_kw)
        db = DB.open(d, opts)
        errs = []

        def worker(t):
            try:
                for i in range(0, per_thread, batch):
                    b = WriteBatch()
                    for j in range(i, i + batch):
                        k = (t * per_thread + j) * 2654435761 % (n_db * 2)
                        b.put(b"%016d" % k, b"v" * 20)
                    db.write(b)
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(t,))
              for t in range(n_threads)]
        t0 = time.time()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        dt = time.time() - t0
        assert not errs, errs
        return db, d, dt

    # plain group commit
    db, d, dt = fill({})
    detail["fillrandom_ops_s"] = round(n_threads * per_thread / dt)
    db.close()
    shutil.rmtree(d, ignore_errors=True)

    # CSPP-role trie memtable (reference README.md:50's headline rep)
    db2, d2, dt2 = fill({"memtable_rep": "cspp"})
    detail["fillrandom_cspp_ops_s"] = round(n_threads * per_thread / dt2)
    db2.close()
    shutil.rmtree(d2, ignore_errors=True)

    # unordered + concurrent native memtable insert (the write levers)
    db, d, dt = fill({"unordered_write": True,
                      "allow_concurrent_memtable_write": True})
    detail["fillrandom_unordered_ops_s"] = round(n_threads * per_thread / dt)
    # Drain this (kept-open) DB's background queue BEFORE the write-path
    # rows: timing them against leftover flush/compaction load understates
    # the write path by 3-4x.
    db.flush()
    db.wait_for_compactions()

    # Write-PATH rows: batches prebuilt, so the measurement isolates
    # queue + WAL + memtable insert (what the unordered/concurrent levers
    # actually target; 100B values so native work dominates Python).
    def prebuilt_rows():
        n_wp = max(10_000, n_db // 2)
        per = n_wp // n_threads

        def mkbatches():
            out = []
            for t in range(n_threads):
                bs = []
                for i in range(0, per, 500):
                    b = WriteBatch()
                    for j in range(i, i + 500):
                        k = (t * per + j) * 2654435761 % (n_db * 2)
                        b.put(b"%016d" % k, b"w" * 100)
                    bs.append(b)
                out.append(bs)
            return out

        for label, kw in (("fillrandom_100B_path_ops_s", {}),
                          ("fillrandom_100B_path_unordered_ops_s",
                           {"unordered_write": True,
                            "allow_concurrent_memtable_write": True})):
            batches = mkbatches()
            d2 = tempfile.mkdtemp(prefix="benchwp_", dir="/dev/shm"
                                  if os.path.isdir("/dev/shm") else None)
            db2 = DB.open(d2, Options(create_if_missing=True,
                                      write_buffer_size=256 << 20, **kw))
            errs2 = []

            def w2(bs):
                try:
                    for b in bs:
                        db2.write(b)
                except Exception as e:  # noqa: BLE001
                    errs2.append(e)

            ts2 = [threading.Thread(target=w2, args=(bs,)) for bs in batches]
            t0 = time.time()
            for t in ts2:
                t.start()
            for t in ts2:
                t.join()
            dt2 = time.time() - t0
            assert not errs2, errs2
            detail[label] = round(n_threads * per / dt2)
            db2.close()
            shutil.rmtree(d2, ignore_errors=True)

    prebuilt_rows()

    # sustained flush+compaction sequence: wait out the bg queue, then
    # write amp = (flush + compaction bytes written) / user bytes.
    db.flush()
    db.wait_for_compactions()
    stats = db.stats
    user_bytes = stats.get_ticker_count(st.BYTES_WRITTEN)
    flush_bytes = stats.get_ticker_count(st.FLUSH_WRITE_BYTES)
    comp_bytes = stats.get_ticker_count(st.COMPACT_WRITE_BYTES)
    if user_bytes:
        detail["write_amplification"] = round(
            (user_bytes + flush_bytes + comp_bytes) / user_bytes, 2)
    detail["compaction_read_bytes"] = stats.get_ticker_count(
        st.COMPACT_READ_BYTES)

    # readrandom through the full read path (memtable + levels).
    import random as _r

    rng = _r.Random(5)
    probes = [b"%016d" % ((rng.randrange(n_db) * 2654435761) % (n_db * 2))
              for _ in range(min(100_000, n_db))]
    # Stats-ON rate first (the reference's db_bench runs with statistics
    # DISABLED by default, so the headline readrandom row below measures
    # stats-off on a reopen; this row records the instrumented cost).
    n_warm = min(20_000, len(probes))
    for k in probes[:n_warm]:
        db.get(k)
    t0 = time.time()
    for k in probes[:n_warm]:
        db.get(k)
    detail["readrandom_stats_ops_s"] = round(n_warm / (time.time() - t0))
    db.close()

    db = DB.open(d, Options())  # stats-off: reference db_bench parity
    for k in probes[:n_warm]:
        db.get(k)
    t0 = time.time()
    hits = 0
    for k in probes:
        if db.get(k) is not None:
            hits += 1
    dt = time.time() - t0
    detail["readrandom_ops_s"] = round(len(probes) / dt)
    detail["readrandom_hit_pct"] = round(100 * hits / len(probes), 1)

    # multireadrandom (reference db_bench workload): batched native
    # MultiGet, one GIL-released chain walk per 128-key batch.
    db.multi_get(probes[:n_warm])
    t0 = time.time()
    batches = [db.multi_get(probes[i:i + 128])
               for i in range(0, len(probes), 128)]
    dt_mg = time.time() - t0
    detail["multireadrandom_ops_s"] = round(len(probes) / dt_mg)
    mg_hits = sum(v is not None for b in batches for v in b)
    detail["multireadrandom_hit_pct"] = round(
        100 * mg_hits / len(probes), 1)

    # readseq / seekrandom (reference db_bench workloads): the chunked
    # scan plane (TPULSM_ITER_CHUNK=1, the default) vs the per-entry
    # path (=0) on the same multi-level DB; byte-identical output is
    # asserted so the ratio is pure data-plane.
    def _scan_all():
        it = db.new_iterator()
        it.seek_to_first()
        c = by = 0
        while it.valid():
            by += len(it.key()) + len(it.value())
            c += 1
            it.next()
        return c, by

    saved_chunk = os.environ.get("TPULSM_ITER_CHUNK")
    try:
        os.environ["TPULSM_ITER_CHUNK"] = "1"
        _scan_all()  # warm the page cache for a fair serial comparison
        t0 = time.time()
        c_c, by_c = _scan_all()
        dt_c = time.time() - t0
        os.environ["TPULSM_ITER_CHUNK"] = "0"
        t0 = time.time()
        c_s, by_s = _scan_all()
        dt_s = time.time() - t0
        assert (c_c, by_c) == (c_s, by_s), "scan-plane output mismatch"
        detail["readseq_MBps"] = round(by_c / dt_c / 1e6, 2)
        detail["readseq_serial_MBps"] = round(by_s / dt_s / 1e6, 2)
        detail["readseq_entries_s"] = round(c_c / dt_c)
        detail["readseq_speedup"] = round(dt_s / dt_c, 2)
        sk = probes[: min(20_000, len(probes))]
        for label, knob in (("seekrandom_ops", "1"),
                            ("seekrandom_serial_ops", "0")):
            os.environ["TPULSM_ITER_CHUNK"] = knob
            it = db.new_iterator()
            for k in sk[:2000]:
                it.seek(k)
            t0 = time.time()
            for k in sk:
                it.seek(k)
            detail[label] = round(len(sk) / (time.time() - t0))
    finally:
        if saved_chunk is None:
            os.environ.pop("TPULSM_ITER_CHUNK", None)
        else:
            os.environ["TPULSM_ITER_CHUNK"] = saved_chunk
    db.close()
    shutil.rmtree(d, ignore_errors=True)

    # Zip data plane read rows: the same keyspace rebuilt with
    # bottommost_format="zip" so readrandom probes compressed value
    # groups (native zip Get — one mini-group inflate per hit, never a
    # whole-file inflate) and readseq runs the zip scan window
    # (ZipTableReader.scan_columnar). Block-table twins are the
    # readrandom_ops_s / readseq_MBps rows above.
    try:
        n_z = min(n_db, 200_000)
        dz = tempfile.mkdtemp(prefix="benchdb_zip_", dir="/dev/shm"
                              if os.path.isdir("/dev/shm") else None)
        dbz = DB.open(dz, Options(create_if_missing=True,
                                  write_buffer_size=8 << 20,
                                  bottommost_format="zip",
                                  disable_auto_compactions=True))
        for i in range(0, n_z, 1000):
            b = WriteBatch()
            for j in range(i, min(i + 1000, n_z)):
                k = (j * 2654435761) % (n_z * 2)
                b.put(b"%016d" % k, b"value-%016d" % j)
            dbz.write(b)
        dbz.flush()
        dbz.compact_range()  # -> bottommost zip tables
        rngz = _r.Random(9)
        pz = [b"%016d" % ((rngz.randrange(n_z) * 2654435761) % (n_z * 2))
              for _ in range(min(20_000, n_z))]
        for k in pz[:2000]:
            dbz.get(k)
        t0 = time.time()
        hz = sum(dbz.get(k) is not None for k in pz)
        detail["readrandom_zip_ops_s"] = round(len(pz) / (time.time() - t0))
        detail["readrandom_zip_hit_pct"] = round(100 * hz / len(pz), 1)

        def _scan_zip():
            it = dbz.new_iterator()
            it.seek_to_first()
            c = by = 0
            while it.valid():
                by += len(it.key()) + len(it.value())
                c += 1
                it.next()
            return c, by

        _scan_zip()  # warm
        t0 = time.time()
        c_z, by_z = _scan_zip()
        dt_z = time.time() - t0
        detail["readseq_zip_MBps"] = round(by_z / dt_z / 1e6, 2)
        detail["readseq_zip_entries_s"] = round(c_z / dt_z)
        dbz.close()
        shutil.rmtree(dz, ignore_errors=True)
    except Exception as e:  # noqa: BLE001
        detail["zip_read_rows_error"] = repr(e)[:120]


def main():
    n_entries = int(os.environ.get("BENCH_N", "10000000"))
    n_db = int(os.environ.get("BENCH_DB_N", "1000000"))
    device = os.environ.get("BENCH_DEVICE", "tpu")
    runs = int(os.environ.get("BENCH_RUNS", "3"))
    fast = os.environ.get("BENCH_FAST") == "1"

    tpu_fallback = False
    probe_diags = []
    orig_platforms = os.environ.get("JAX_PLATFORMS")
    orig_pool_ips = os.environ.get("PALLAS_AXON_POOL_IPS")
    if device in ("tpu", "cpu-jax"):
        from toplingdb_tpu.utils.backend_probe import ensure_reachable_backend

        probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
        probe_tries = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "3"))
        print(f"probing jax backend ({probe_tries}x{probe_s:.0f}s budget)...",
              file=sys.stderr, flush=True)
        if not ensure_reachable_backend(probe_s, attempts=probe_tries,
                                        backoff_s=30.0,
                                        diagnostics=probe_diags):
            tpu_fallback = True
            os.environ["TPULSM_HOST_SORT"] = "1"
            print("jax backend unreachable; falling back to cpu backend "
                  "(will re-probe after input build)",
                  file=sys.stderr, flush=True)

    import dataclasses

    from toplingdb_tpu.db.dbformat import InternalKeyComparator
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.builder import TableOptions

    icmp = InternalKeyComparator()
    env = default_env()
    base = tempfile.mkdtemp(prefix="bench_", dir="/dev/shm"
                            if os.path.isdir("/dev/shm") else None)
    raw_bytes = RAW_PER_ENTRY * n_entries
    detail = {
        "device": device,
        "tpu_unreachable_cpu_fallback": tpu_fallback,
        "n_entries": n_entries,
        "raw_kv_bytes": raw_bytes,
        "metric_note": "MB/s of raw user KV (28B/entry), baseline's units",
    }

    # Headline: snappy-compressed inputs+outputs (the reference db_bench
    # default config the 24.34s baseline ran with).
    from toplingdb_tpu.utils import codecs

    headline_codec = fmt.SNAPPY_COMPRESSION if codecs.available("snappy") \
        else fmt.NO_COMPRESSION
    topts = TableOptions(block_size=4096, compression=headline_codec)
    t0 = time.time()
    metas = build_inputs(env, base, icmp, n_entries, topts)
    detail["input_build_s"] = round(time.time() - t0, 2)

    # Re-probe across the run (VERDICT r03 item 1): a transient tunnel
    # outage at bench start must not decide the whole run. Input building
    # is host-only, so minutes have passed — try the accelerator again
    # before the timed compaction. Safe while no jax backend has been
    # initialized in this process (the host-sort fallback runs no jax ops).
    if tpu_fallback:
        from toplingdb_tpu.utils import backend_probe as bp

        if bp.retry_redirect(orig_platforms, orig_pool_ips, probe_s,
                             "post-input-build", probe_diags):
            tpu_fallback = False
            print("jax backend came back; using accelerator",
                  file=sys.stderr, flush=True)
    detail["tpu_unreachable_cpu_fallback"] = tpu_fallback
    file_probe_evidence(detail, probe_diags)

    dt, stats, input_file_bytes, run_times = time_compaction(
        env, base, icmp, metas, topts, topts, device, runs, 1000)
    detail["headline_run_times_s"] = run_times  # all N, not just best
    fill_phase_detail(detail, stats)
    mbps = raw_bytes / dt / 1e6
    detail["wall_s"] = round(dt, 3)
    detail["input_file_bytes"] = input_file_bytes
    detail["compression"] = "snappy" if headline_codec else "none"
    detail["input_records"] = stats.input_records
    detail["output_records"] = stats.output_records

    if not fast:
        # Variant rows at 1/10 scale (shape-compile reuse; bounded wall).
        n_small = max(1, n_entries // 10)
        sbase = tempfile.mkdtemp(prefix="bench_s_", dir="/dev/shm"
                                 if os.path.isdir("/dev/shm") else None)
        sm = {}
        t_none = TableOptions(block_size=4096)
        sm["none"] = build_inputs(env, sbase, icmp, n_small, t_none)
        dt2, _, _, _ = time_compaction(env, sbase, icmp, sm["none"], t_none,
                                       t_none, device, max(1, runs - 1), 5000)
        detail["compaction_nocomp_MBps"] = round(
            RAW_PER_ENTRY * n_small / dt2 / 1e6, 2)
        # Same job with the pipeline forced OFF: the serial comparator for
        # compaction_nocomp_MBps (which runs pipelined by default).
        saved_pipe = os.environ.get("TPULSM_PIPELINE")
        os.environ["TPULSM_PIPELINE"] = "0"
        try:
            dt2s, _, _, _ = time_compaction(
                env, sbase, icmp, sm["none"], t_none, t_none, device,
                max(1, runs - 1), 5200)
            detail["compaction_nocomp_serial_MBps"] = round(
                RAW_PER_ENTRY * n_small / dt2s / 1e6, 2)
        finally:
            if saved_pipe is None:
                os.environ.pop("TPULSM_PIPELINE", None)
            else:
                os.environ["TPULSM_PIPELINE"] = saved_pipe
        if device in ("tpu", "cpu-jax") and not tpu_fallback:
            # Same job with FULL on-device block assembly
            # (TPULSM_DEVICE_BLOCKS=1; single shard, uncompressed — its
            # eligibility envelope). Both rows land in the detail so the
            # default can be chosen from measured data per link class.
            saved = {k: os.environ.get(k) for k in
                     ("TPULSM_DEVICE_BLOCKS", "TPULSM_DEVICE_SHARDS")}
            os.environ["TPULSM_DEVICE_BLOCKS"] = "1"
            os.environ["TPULSM_DEVICE_SHARDS"] = "1"
            try:
                dt2b, _, _, _ = time_compaction(
                    env, sbase, icmp, sm["none"], t_none, t_none, device,
                    max(1, runs - 1), 5500)
                detail["compaction_nocomp_deviceblocks_MBps"] = round(
                    RAW_PER_ENTRY * n_small / dt2b / 1e6, 2)
            finally:
                for k, v in saved.items():
                    if v is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = v
        if codecs.available("zstd"):
            t_z = dataclasses.replace(t_none,
                                      compression=fmt.ZSTD_COMPRESSION)
            dt3, _, _, _ = time_compaction(env, sbase, icmp, sm["none"],
                                           t_none, t_z, device,
                                           max(1, runs - 1), 6000)
            detail["compaction_zstd_out_MBps"] = round(
                RAW_PER_ENTRY * n_small / dt3 / 1e6, 2)
        # ZipTable emission (searchable-compression bottommost output).
        # The batched native zip plane (tpulsm_zip_* kernels inside the
        # pipeline's encode stage) builds these at full scale; the serial
        # twin (TPULSM_ZIP_PLANE=0: per-entry Python ZipTableBuilder)
        # runs at reduced scale so its cost doesn't dominate the round.
        zbase = tempfile.mkdtemp(prefix="bench_z_", dir="/dev/shm"
                                 if os.path.isdir("/dev/shm") else None)
        zm = build_inputs(env, zbase, icmp, n_small, t_none)
        t_zip = dataclasses.replace(t_none, format="zip")
        dt4, _, _, _ = time_compaction(env, zbase, icmp, zm, t_none,
                                       t_zip, device, max(1, runs - 1),
                                       7000)
        detail["compaction_zip_out_MBps"] = round(
            RAW_PER_ENTRY * n_small / dt4 / 1e6, 2)
        shutil.rmtree(zbase, ignore_errors=True)
        n_zs = max(1, n_small // 5)
        zsbase = tempfile.mkdtemp(prefix="bench_zs_", dir="/dev/shm"
                                  if os.path.isdir("/dev/shm") else None)
        zsm = build_inputs(env, zsbase, icmp, n_zs, t_none)
        saved_zp = os.environ.get("TPULSM_ZIP_PLANE")
        os.environ["TPULSM_ZIP_PLANE"] = "0"
        try:
            dt5, _, _, _ = time_compaction(env, zsbase, icmp, zsm, t_none,
                                           t_zip, device, 1, 7500)
            detail["compaction_zip_serial_MBps"] = round(
                RAW_PER_ENTRY * n_zs / dt5 / 1e6, 2)
        finally:
            if saved_zp is None:
                os.environ.pop("TPULSM_ZIP_PLANE", None)
            else:
                os.environ["TPULSM_ZIP_PLANE"] = saved_zp
        shutil.rmtree(zsbase, ignore_errors=True)
        shutil.rmtree(sbase, ignore_errors=True)

        db_path_rows(detail, n_db)

        try:
            write_plane_rows(detail, n_db)
        except Exception as e:  # noqa: BLE001
            detail["write_plane_rows_error"] = repr(e)[:120]

        try:
            replication_rows(detail)
        except Exception as e:  # noqa: BLE001
            detail["replication_rows_error"] = repr(e)[:120]

        try:
            integrity_rows(detail, n_db)
        except Exception as e:  # noqa: BLE001
            detail["integrity_rows_error"] = repr(e)[:120]

        try:
            observability_rows(detail, n_db)
        except Exception as e:  # noqa: BLE001
            detail["observability_rows_error"] = repr(e)[:120]

        try:
            health_rows(detail, n_db)
        except Exception as e:  # noqa: BLE001
            detail["health_rows_error"] = repr(e)[:120]

        try:
            sharding_rows(detail)
        except Exception as e:  # noqa: BLE001
            detail["sharding_rows_error"] = repr(e)[:120]

        try:
            fleet_rows(detail)
        except Exception as e:  # noqa: BLE001
            detail["fleet_rows_error"] = repr(e)[:120]

        try:
            concurrency_rows(detail, n_db)
        except Exception as e:  # noqa: BLE001
            detail["concurrency_rows_error"] = repr(e)[:120]

        try:
            async_read_rows(detail)
        except Exception as e:  # noqa: BLE001
            detail["async_read_rows_error"] = repr(e)[:120]

        try:
            storage_rows(detail)
        except Exception as e:  # noqa: BLE001
            detail["storage_rows_error"] = repr(e)[:120]

        try:
            disk_pressure_rows(detail, n_db)
        except Exception as e:  # noqa: BLE001
            detail["disk_pressure_rows_error"] = repr(e)[:120]

        # Range-axis weak-scaling of the distributed GC step (VERDICT r04
        # item 10): a subprocess because virtual device counts must be set
        # before the jax backend exists. Failure just drops the row.
        import subprocess as _sp

        try:
            out = _sp.run(
                [sys.executable, "-m",
                 "toplingdb_tpu.parallel.scaling_probe",
                 "--rows-per-device", "32768", "--devices", "8",
                 "--repeats", "2"],
                capture_output=True, timeout=600, cwd=os.path.dirname(
                    os.path.abspath(__file__)))
            if out.returncode == 0 and out.stdout:
                detail["range_weak_scaling"] = json.loads(
                    out.stdout.decode().strip().splitlines()[-1]
                )["weak_scaling"]
        except Exception as e:  # noqa: BLE001
            detail["range_weak_scaling_error"] = str(e)[:120]

        # MEASURED mesh compaction (§2.2.4): the MULTICHIP dry-run
        # promoted — the same uniform shard set through the mesh shard
        # runner at 1 chip vs 8. Exit 3 = skip (environment), not error.
        try:
            out = _sp.run(
                [sys.executable, "-m",
                 "toplingdb_tpu.parallel.scaling_probe",
                 "--mode", "mesh",
                 "--rows-per-device", "16384", "--devices", "8",
                 "--repeats", "2"],
                capture_output=True, timeout=600, cwd=os.path.dirname(
                    os.path.abspath(__file__)))
            if out.returncode == 0 and out.stdout:
                rows = json.loads(
                    out.stdout.decode().strip().splitlines()[-1]
                )["mesh_compact"]
                detail["mesh_compact"] = rows
                base = rows[0]["rows_per_s"]
                if base and len(rows) > 1:
                    detail["compaction_mesh_MBps"] = rows[-1]["MBps"]
                    detail["mesh_scaling_x"] = round(
                        rows[-1]["rows_per_s"] / base, 2)
            elif out.returncode == 3 and out.stdout:
                detail["mesh_compact_skip"] = json.loads(
                    out.stdout.decode().strip().splitlines()[-1]
                ).get("skip", "")[:120]
        except Exception as e:  # noqa: BLE001
            detail["mesh_compact_error"] = str(e)[:120]

    # LAST-CHANCE tunnel retry: the DB rows took minutes more — if the
    # accelerator is back now, re-measure the HEADLINE on it (the input
    # SSTs still exist; host-sort mode never initialized a jax backend,
    # so the platform can still be flipped). Skipped under BENCH_FAST
    # (the variants didn't run, so no meaningful time has passed).
    if tpu_fallback and not fast:
        from toplingdb_tpu.utils import backend_probe as bp

        ok = bp.retry_redirect(
            orig_platforms, orig_pool_ips,
            float(os.environ.get("BENCH_PROBE_TIMEOUT", "120")),
            "post-db-rows", probe_diags)
        file_probe_evidence(detail, probe_diags)
        if ok:
            print("jax backend came back late; re-measuring headline on "
                  "the accelerator", file=sys.stderr, flush=True)
            # A brief tunnel window must still yield a RECORDED device
            # row: one quick single run lands first (compile + measure,
            # ~seconds); the full best-of-N follows while the window
            # holds.
            try:
                t_q = time.time()
                dt_q, stats_q, _, _ = time_compaction(
                    env, base, icmp, metas, topts, topts, device, 1, 7800)
                detail["headline_quick_tpu_MBps"] = round(
                    raw_bytes / dt_q / 1e6, 2)
                detail["headline_quick_tpu_total_s"] = round(
                    time.time() - t_q, 2)  # incl. compile: window budget
            except Exception as e:  # noqa: BLE001
                # Window closed during the quick run: keep the CPU record.
                detail["tpu_late_retry_error"] = repr(e)[:160]
                dt_q = None
            if dt_q is not None:
                # Quick row is banked; the full best-of-N upgrades it if
                # the window holds — a drop mid-run must not lose either
                # the quick device row or the whole record.
                mbps = raw_bytes / dt_q / 1e6
                tpu_fallback = False
                detail["tpu_unreachable_cpu_fallback"] = False
                detail["headline_source"] = "tpu-late-probe-quick"
                # The non-headline rows above were measured BEFORE the
                # tunnel came back (ADVICE r04): record their provenance
                # explicitly instead of letting the global flag claim an
                # all-TPU run.
                detail["variant_rows_source"] = "cpu-fallback"
                detail["headline_run_times_s"] = [round(dt_q, 3)]
                detail["wall_s"] = round(dt_q, 3)
                fill_phase_detail(detail, stats_q)
                try:
                    dt_l, stats_l, _, run_times_l = time_compaction(
                        env, base, icmp, metas, topts, topts, device,
                        runs, 8000)
                    mbps = raw_bytes / dt_l / 1e6
                    detail["headline_source"] = "tpu-late-probe"
                    detail["headline_run_times_s"] = run_times_l
                    detail["wall_s"] = round(dt_l, 3)
                    fill_phase_detail(detail, stats_l)
                except Exception as e:  # noqa: BLE001
                    detail["tpu_full_rerun_error"] = repr(e)[:160]
        else:
            bp.redirect_to_cpu_backend()

    # Record layout (VERDICT r05 weak #1): the driver captures only the
    # LAST ~2000 chars of stdout, so the headline keys must be the FINAL
    # keys of the line (json.dumps preserves dict insertion order) and the
    # whole line must stay ≤ 1800 bytes — otherwise the tail keeps the
    # detail blob and drops "value", making the round's perf work
    # officially invisible.
    def make_record(det):
        return {
            "metric": "l2_compaction_MBps_per_chip",
            "unit": "MB/s",
            "detail": det,
            # headline keys LAST so a tail capture always preserves them
            "value": round(mbps, 2),
            "vs_baseline": round(mbps / BASELINE_MBPS, 4),
            "device": device,
            "tpu_unreachable_cpu_fallback": tpu_fallback,
            # Pipelined-data-plane headline rows: measured scan/compute/
            # encode overlap of the headline job, and the pipelined
            # nocomp variant (its serial twin is
            # detail.compaction_nocomp_serial_MBps).
            "pipeline_overlap_s": detail.get("phase_breakdown", {}).get(
                "pipeline_overlap_s", 0.0),
            "compaction_pipelined_MBps": detail.get(
                "compaction_nocomp_MBps"),
            # Chunked scan-plane headline rows (serial twins are
            # detail.readseq_serial_MBps / detail.seekrandom_serial_ops).
            "readseq_MBps": detail.get("readseq_MBps"),
            "seekrandom_ops": detail.get("seekrandom_ops"),
            # Replication plane: router read rate under a concurrent
            # writer (detail.readwhilewriting_replica_ops is the row) and
            # mean ship→apply lag of the tailing follower.
            "replication_lag_ms": detail.get("replication_lag_ms"),
            # Native group-commit write plane (serial twin is
            # detail.fillrandom_plane_off_ops_s; sync twin is
            # detail.fillrandom_sync_inline_ops_s).
            "fillrandom_native_plane_ops_s": detail.get(
                "fillrandom_native_plane_ops_s"),
            "fillrandom_sync_ops_s": detail.get("fillrandom_sync_ops_s"),
            # Telemetry plane: sampled (1-in-64) tracing cost vs the
            # tracing-off twin (gate: <= 2%).
            "trace_overhead_pct": detail.get("trace_overhead_pct"),
            # Health plane: windowed histograms + per-segment SLO
            # evaluation vs cumulative-only twin (gate: <= 2%).
            "health_overhead_pct": detail.get("health_overhead_pct"),
            # Sharding plane: 4-shard vs 1-shard router fillrandom ratio
            # (detail has the per-config ops/s + hot-tenant isolation).
            "shard_scaling_x": detail.get("shard_scaling_x"),
            # Out-of-process fleet: 4 ShardServer processes vs 1 through
            # the FleetRouter's HTTP data plane (gate: >= in-process
            # shard_scaling_x — no shared GIL across primaries).
            "fleet_scaling_x": detail.get("fleet_scaling_x"),
            # Concurrency plane: off-mode factories must price as raw
            # locks (gate: <= 1%) and debug-instrumented fillrandom must
            # stay within 2x of plain (gate: <= 100).
            "lock_factory_overhead_pct": detail.get(
                "lock_factory_overhead_pct"),
            "lock_debug_overhead_pct": detail.get(
                "lock_debug_overhead_pct"),
            # Searchable-compression zip data plane: batched native zip
            # emission inside the compaction pipeline (serial twin is
            # detail.compaction_zip_serial_MBps) and compressed-block
            # reads without whole-file inflate (block-table twins are
            # readrandom_ops_s / readseq_MBps).
            "compaction_zip_out_MBps": detail.get(
                "compaction_zip_out_MBps"),
            "readrandom_zip_ops_s": detail.get("readrandom_zip_ops_s"),
            "readseq_zip_MBps": detail.get("readseq_zip_MBps"),
            # Mesh compaction execution mode (§2.2.4): the MULTICHIP
            # dry-run promoted to a measured row — the same shard set at
            # 8 chips (1-chip twin is detail.mesh_compact[0]). On virtual
            # CPU devices the chips share one host threadpool, so
            # mesh_scaling_x reports ~1x there; >=4x is the real-chip
            # expectation.
            "compaction_mesh_MBps": detail.get("compaction_mesh_MBps"),
            "mesh_scaling_x": detail.get("mesh_scaling_x"),
            # Async read plane (§2.2.5): cold-cache batched MultiGet
            # through the reader rings vs its sync twin
            # (detail.multireadrandom_cold_ops_s /
            # detail.multireadrandom_cold_sync_ops_s; both on the
            # 200µs DelayedReadEnv latency model, byte parity asserted).
            # On a 1-core host the rings serialize:
            # detail.async_read_speedup_source tags that provenance.
            "async_read_speedup_x": detail.get("async_read_speedup_x"),
            # Disaggregated SST storage (storage/): large-shard migration
            # bootstrap, cross-filesystem byte copy vs metadata-only
            # store references (flatness twin is
            # detail.migration_ref_flatness_x; dcompact store mode ships
            # detail.dcompact_store_sst_bytes_shipped == 0).
            "migration_ref_speedup_x": detail.get(
                "migration_ref_speedup_x"),
            # Storage-pressure plane (§2.5.1): fillrandom with budget +
            # manager accounting + hot free-space poller vs the no-manager
            # twin (detail.fillrandom_disk_plain_ops_s; gate: <= 1%).
            "disk_pressure_overhead_pct": detail.get(
                "disk_pressure_overhead_pct"),
        }

    line = json.dumps(make_record(detail))
    if len(line) > 1800:
        slim = {k: detail[k] for k in (
            "n_entries", "raw_kv_bytes", "wall_s", "headline_run_times_s",
            "phase_breakdown", "compression", "headline_source",
            "variant_rows_source", "readwhilewriting_replica_ops",
            "replica_read_pct", "shard_scaling_x", "fleet_scaling_x",
            "sibling_keep_pct", "fillrandom_4shard_ops_s",
            "compaction_zip_serial_MBps") if k in detail}
        slim["detail_truncated"] = True
        line = json.dumps(make_record(slim))
    if len(line) > 1800:
        line = json.dumps(make_record({"detail_truncated": True}))
    json.loads(line)  # hard guarantee: the printed record parses
    assert len(line) <= 1800, len(line)
    print(line)
    shutil.rmtree(base, ignore_errors=True)


if __name__ == "__main__":
    main()
