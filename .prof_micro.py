"""Micro-profile of headline compaction components (repo-root scratch)."""
import os
import sys
import tempfile
import time

os.environ["TPULSM_HOST_SORT"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import numpy as np

import bench as B
from toplingdb_tpu.db.dbformat import InternalKeyComparator
from toplingdb_tpu.env import default_env
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.builder import TableOptions
from toplingdb_tpu.utils import codecs

n = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
comp = sys.argv[2] if len(sys.argv) > 2 else "snappy"

icmp = InternalKeyComparator()
env = default_env()
base = tempfile.mkdtemp(prefix="prof_", dir="/dev/shm")
codec = fmt.SNAPPY_COMPRESSION if comp == "snappy" and codecs.available(
    "snappy") else fmt.NO_COMPRESSION
topts = TableOptions(block_size=4096, compression=codec)
metas = B.build_inputs(env, base, icmp, n, topts)

from toplingdb_tpu.compaction.picker import Compaction
from toplingdb_tpu.db.table_cache import TableCache
from toplingdb_tpu.ops.columnar_io import ColumnarKV, scan_table_columnar
from toplingdb_tpu.ops import compaction_kernels as ck

tc = TableCache(env, base, icmp, topts)
c = Compaction(level=0, output_level=2, inputs=list(metas), bottommost=True,
               max_output_file_size=1 << 62)
readers = [tc.get_reader(f.number) for _, f in c.all_inputs()]

t0 = time.time()
parts = [scan_table_columnar(r) for r in readers]
t_scan = time.time() - t0
t0 = time.time()
kv = ColumnarKV.concat(parts)
t_concat = time.time() - t0
print(f"scan={t_scan:.3f} concat={t_concat:.3f} n={kv.n}")

rs = np.cumsum([0] + [p.n for p in parts], dtype=np.int64)
t0 = time.time()
nat = ck.host_sort_order(kv.key_buf, kv.key_offs, kv.key_lens, run_starts=rs)
t_merge = time.time() - t0
s, new_key, packed = nat
seq = packed >> np.uint64(8)
vtype = (packed & np.uint64(0xFF)).astype(np.int32)
t0 = time.time()
keep, zero_seq, host_resolve, _ = ck.host_gc_mask(
    new_key, seq[s], vtype[s], [], None, True)
t_gc = time.time() - t0
t0 = time.time()
out = keep | host_resolve
order = s[out].astype(np.int32)
zero_flags = zero_seq[out]
t_post = time.time() - t0
print(f"native_merge={t_merge:.3f} gc_mask={t_gc:.3f} post={t_post:.3f} "
      f"survivors={len(order)}")

# encode/write
from toplingdb_tpu.ops.columnar_io import write_tables_columnar
from toplingdb_tpu.ops.device_compaction import _kv_seq_vtype
t0 = time.time()
col = _kv_seq_vtype(kv)
t_tr = time.time() - t0
trailer_override = np.full(kv.n, -1, dtype=np.int64)
seqs = col.seq.copy()
zero_orig = order[zero_flags]
trailer_override[zero_orig] = col.vtype[zero_orig].astype(np.int64)
seqs[zero_orig] = 0
ctr = [2000]
def alloc():
    ctr[0] += 1
    return ctr[0]
t0 = time.time()
files = write_tables_columnar(
    env, base, alloc, icmp, topts, kv, order, trailer_override,
    col.vtype, seqs, [], 1, max_output_file_size=1 << 62)
t_wr = time.time() - t0
print(f"trailers={t_tr:.3f} write={t_wr:.3f} files={len(files)}")
total = t_scan + t_concat + t_merge + t_gc + t_post + t_tr + t_wr
print(f"total={total:.3f} => {28*n/total/1e6:.1f} MB/s")
import shutil
shutil.rmtree(base, ignore_errors=True)
