"""Native group-commit write plane (ISSUE 7): fused WAL encode + group
memtable insert (tpulsm_wb_group_commit) must be byte-for-byte
interchangeable with the Python interiors — WAL files, recovery, shipped
replication frames — across the write-mode matrix, with the async WAL
writer's fsync coalescing and fault propagation proven on top."""

import glob
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.db.write_batch import WriteBatch
from toplingdb_tpu.options import Options, WriteOptions
from toplingdb_tpu.utils import statistics as st

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODES = {
    "plain": {},
    "pipelined": {"enable_pipelined_write": True},
    "unordered": {"unordered_write": True},
    "parallel": {"allow_concurrent_memtable_write": True},
}


def _plane_available() -> bool:
    from toplingdb_tpu import native

    lib = native.lib()
    return lib is not None and hasattr(lib, "tpulsm_wb_group_commit")


pytestmark = pytest.mark.skipif(not _plane_available(),
                                reason="native write plane unavailable")


def _fill(d, knob, opts_kw, n=1500, pb=8, sync_every=0):
    os.environ["TPULSM_WRITE_PLANE"] = knob
    try:
        stats = st.Statistics()
        db = DB.open(d, Options(create_if_missing=True, statistics=stats,
                                protection_bytes_per_key=pb, **opts_kw))
        for i in range(0, n, 10):
            b = WriteBatch(protection_bytes_per_key=pb)
            for j in range(i, i + 10):
                b.put(b"k%06d" % j, b"v%06d" % j)
                if j % 7 == 0:
                    b.delete(b"k%06d" % (j // 2))
            wo = WriteOptions(sync=bool(sync_every and i % sync_every == 0))
            db.write(b, wo)
        return db, stats
    finally:
        os.environ.pop("TPULSM_WRITE_PLANE", None)


def _dump(db, n=1500):
    return ([(k, db.get(b"k%06d" % k)) for k in range(n)],
            db.versions.last_sequence)


def _wal_bytes(d):
    out = {}
    for p in sorted(glob.glob(d + "/*.log")):
        with open(p, "rb") as f:
            out[os.path.basename(p)] = f.read()
    return out


@pytest.mark.parametrize("mode", sorted(MODES))
def test_plane_parity_wal_bytes_and_recovery(tmp_path, mode):
    """WAL bytes, visible contents, last_sequence, and a post-reopen dump
    must be identical between TPULSM_WRITE_PLANE=0 and =1 (protection on)."""
    d0, d1 = str(tmp_path / "p0"), str(tmp_path / "p1")
    db0, s0 = _fill(d0, "0", MODES[mode])
    db1, s1 = _fill(d1, "1", MODES[mode])
    assert _dump(db0) == _dump(db1)
    assert _wal_bytes(d0) == _wal_bytes(d1)
    assert s1.get_ticker_count(st.WRITE_GROUP_NATIVE_COMMITS) > 0
    assert s0.get_ticker_count(st.WRITE_GROUP_NATIVE_COMMITS) == 0
    assert s0.get_ticker_count(st.WRITE_GROUP_LED) > 0
    # WAL accounting parity between the two encoders.
    for t in (st.WAL_BYTES, st.WRITE_WITH_WAL):
        assert s0.get_ticker_count(t) == s1.get_ticker_count(t)
    db0.close()
    db1.close()
    with DB.open(d0, Options()) as r0, DB.open(d1, Options()) as r1:
        assert _dump(r0) == _dump(r1)


def test_plane_fallback_matrix(tmp_path):
    """Merge-heavy, wide-column, CF-prefixed, and range-delete batches keep
    the Python interiors (fallback ticker) and stay correct."""
    from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

    stats = st.Statistics()
    os.environ["TPULSM_WRITE_PLANE"] = "1"
    try:
        db = DB.open(str(tmp_path / "f"),
                     Options(create_if_missing=True, statistics=stats,
                             merge_operator=UInt64AddOperator()))
        import struct

        db.put(b"point", b"v")  # native plane
        for _ in range(3):
            db.merge(b"ctr", struct.pack("<Q", 1))  # merge-heavy: fallback
        cf = db.create_column_family("other")
        db.put(b"cfk", b"cfv", cf=cf)  # CF-prefixed: fallback
        db.delete_range(b"a", b"b")    # range delete: fallback
        from toplingdb_tpu.db.wide_columns import encode_entity

        b = WriteBatch()
        b.put_entity(b"wide", encode_entity({b"c": b"1"}))
        db.write(b)                    # wide columns: fallback
        assert stats.get_ticker_count(st.WRITE_GROUP_NATIVE_COMMITS) >= 1
        assert stats.get_ticker_count(st.WRITE_GROUP_FALLBACKS) >= 4
        assert struct.unpack("<Q", db.get(b"ctr"))[0] == 3
        assert db.get(b"cfk", cf=cf) == b"cfv"
        db.close()
    finally:
        os.environ.pop("TPULSM_WRITE_PLANE", None)


_CRASH_SRC = textwrap.dedent("""
    import sys
    sys.path.insert(0, %(repo)r)
    import os
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.db.write_batch import WriteBatch
    from toplingdb_tpu.options import Options, WriteOptions
    mode = %(mode)r
    kw = {"pipelined": {"enable_pipelined_write": True},
          "unordered": {"unordered_write": True},
          "parallel": {"allow_concurrent_memtable_write": True},
          "sync": {}}[mode]
    db = DB.open(%(db)r, Options(create_if_missing=True,
                                 protection_bytes_per_key=8, **kw))
    wo = WriteOptions(sync=(mode == "sync"))
    for i in range(400):
        b = WriteBatch(protection_bytes_per_key=8)
        for j in range(5):
            b.put(b"c%%07d" %% (i * 5 + j), b"v%%07d" %% (i * 5 + j))
        db.write(b, wo)
    print("survived")  # the kill point must fire before 400 writes
""")


@pytest.mark.parametrize("mode", ["pipelined", "unordered", "parallel",
                                  "sync"])
def test_crash_after_wal_recovery_parity(tmp_path, mode):
    """kill_point crash at DBImpl::WriteImpl:AfterWAL under the native
    plane: the recovered DB must be byte-identical to the Python-path
    twin that died at the SAME (seeded) point."""
    dumps = {}
    for knob in ("0", "1"):
        d = str(tmp_path / f"c{knob}")
        src = _CRASH_SRC % {"repo": REPO, "mode": mode, "db": d}
        env = dict(os.environ, TPULSM_WRITE_PLANE=knob,
                   TPULSM_KILL_ODDS="60", TPULSM_KILL_SEED="1234",
                   TPULSM_KILL_PREFIX="DBImpl::WriteImpl:AfterWAL",
                   JAX_PLATFORMS="cpu")
        r = subprocess.run([sys.executable, "-c", src], env=env,
                           capture_output=True, timeout=120)
        assert r.returncode == 137, (knob, r.returncode,
                                     r.stdout, r.stderr)
        # Recover with the OTHER path than the one that wrote (replay is
        # encoder-agnostic), dump everything.
        with DB.open(d, Options(protection_bytes_per_key=8)) as db:
            dumps[knob] = (
                [(k, db.get(b"c%07d" % k)) for k in range(2000)],
                db.versions.last_sequence,
            )
        dumps[knob + "_wal"] = _wal_bytes(d)
    assert dumps["0"] == dumps["1"], mode
    assert dumps["0_wal"] == dumps["1_wal"], mode


def test_log_shipper_frame_parity(tmp_path):
    """The replication plane must see identical shipped batches from
    either encoder (PR 4's LogShipper tails the WAL both planes write)."""
    from toplingdb_tpu.replication import LogShipper

    frames = {}
    for knob in ("0", "1"):
        d = str(tmp_path / f"s{knob}")
        db, _ = _fill(d, knob, {}, n=600)
        ship = LogShipper(db)
        fs, state = ship.frames_since(None)
        frames[knob] = [(f.first_seq, f.last_seq, f.batches) for f in fs]
        db.close()
    assert frames["0"] == frames["1"]
    assert frames["0"], "no frames shipped"


def test_async_wal_fsync_coalescing(tmp_path):
    """Concurrent sync=True leaders through the async WAL writer must
    merge into shared fsyncs (WRITE_GROUP_FSYNCS_COALESCED > 0) with
    every acknowledged write durable. Pipelined mode: the durability
    barrier waits OUTSIDE _mutex, so several groups' sync tokens overlap
    in the ring; seeded fsync delays widen the window deterministically."""
    import threading

    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.env.fault_injection import WalWriterFaultInjector

    env = PosixEnv()
    env.wal_writer_fault = WalWriterFaultInjector(
        rate=0.5, plans=("delay",), delay_sec=0.002, ops=("sync",), seed=5)
    stats = st.Statistics()
    db = DB.open(str(tmp_path / "a"),
                 Options(create_if_missing=True, statistics=stats,
                         enable_pipelined_write=True,
                         enable_async_wal=True), env=env)
    wo = WriteOptions(sync=True)
    errs = []

    def w(t):
        try:
            for i in range(60):
                db.put(b"t%d-%04d" % (t, i), b"v", wo)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [__import__("threading").Thread(target=w, args=(t,))
          for t in range(6)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    ring = db._wal_ring
    assert ring is not None
    assert ring.fsyncs_coalesced > 0
    assert stats.get_ticker_count(st.WRITE_GROUP_FSYNCS_COALESCED) \
        == ring.fsyncs_coalesced
    # Syncs acknowledged => durable: drop unsynced bytes cannot lose them.
    db.close()
    with DB.open(str(tmp_path / "a"), Options()) as r:
        for t in range(6):
            for i in range(60):
                assert r.get(b"t%d-%04d" % (t, i)) == b"v"


def test_async_wal_fault_injection_error_and_resume(tmp_path):
    """Seeded WAL-writer-thread failures (env/fault_injection.py
    WalWriterFaultInjector): the covered group's writer gets the error, a
    HARD background error latches, resume() clears it, later writes and a
    reopen stay consistent."""
    from toplingdb_tpu.env import PosixEnv
    from toplingdb_tpu.env.env import AsyncIORing
    from toplingdb_tpu.env.fault_injection import WalWriterFaultInjector

    env = PosixEnv()
    inj = WalWriterFaultInjector(schedule={3: "fail", 6: "delay"})
    env.wal_writer_fault = inj
    d = str(tmp_path / "fi")
    db = DB.open(d, Options(create_if_missing=True, enable_async_wal=True),
                 env=env)
    assert db._wal_ring.fault_hook is inj
    wo = WriteOptions(sync=True)
    acked, failed = [], []
    for i in range(10):
        k = b"f%04d" % i
        try:
            db.put(k, b"v", wo)
            acked.append(k)
        except Exception:
            failed.append(k)
            db.resume()  # clean resume after the injected failure
    assert failed, "no injected failure surfaced"
    assert inj.injected_counts().get("fail", 0) >= 1
    for k in acked:
        assert db.get(k) == b"v"
    db.close()
    with DB.open(d, Options()) as r:
        for k in acked:
            assert r.get(k) == b"v"


def test_aio_ring_coalescing_unit():
    """AsyncIORing: N sync tokens drained together -> ONE fsync; append
    errors park per-file and surface on the next barrier, then clear."""
    from toplingdb_tpu.env.env import AsyncIORing
    from toplingdb_tpu.utils.status import IOError_

    class SlowFile:
        def __init__(self):
            self.data = b""
            self.fsyncs = 0
            self.fail_next_append = False

        def append(self, d):
            if self.fail_next_append:
                self.fail_next_append = False
                raise IOError_("boom")
            self.data += bytes(d)

        def flush(self):
            pass

        def sync(self):
            self.fsyncs += 1

    ring = AsyncIORing(capacity=64)
    f = SlowFile()
    # Stall the worker so all submissions land in one drained batch.
    import threading

    gate = threading.Event()
    ring.submit_task(gate.wait)
    toks = []
    for i in range(4):
        ring.submit_append(f, b"x%d" % i)
        toks.append(ring.submit_sync(f))
    gate.set()
    for t in toks:
        t.wait()
    assert f.data == b"x0x1x2x3"
    assert f.fsyncs == 1
    assert ring.fsyncs_coalesced == 3
    # Error propagation: failed append -> next barrier raises, then clear.
    gate2 = threading.Event()
    ring.submit_task(gate2.wait)
    f.fail_next_append = True
    atok = ring.submit_append(f, b"bad")
    btok = ring.submit_barrier(f)
    gate2.set()
    with pytest.raises(IOError_):
        atok.wait()
    with pytest.raises(IOError_):
        btok.wait()
    ring.submit_append(f, b"ok")
    ring.submit_barrier(f).wait()  # clean resume
    assert f.data.endswith(b"ok")
    ring.close()


def test_prefetch_buffer_async_readahead():
    """FilePrefetchBuffer submits the NEXT window through an AsyncIORing
    and serves sequential reads from the adopted async window."""
    from toplingdb_tpu.env.env import AsyncIORing
    from toplingdb_tpu.table.prefetch import FilePrefetchBuffer

    class CountingFile:
        def __init__(self, n):
            self.blob = bytes(range(256)) * (n // 256)
            self.reads = 0

        def read(self, off, n):
            self.reads += 1
            return self.blob[off:off + n]

        def size(self):
            return len(self.blob)

    ring = AsyncIORing(capacity=16)
    f = CountingFile(1 << 20)
    pf = FilePrefetchBuffer(f, initial_readahead=64 * 1024,
                            arm_immediately=True, aio_ring=ring)
    out = b""
    off = 0
    while off < f.size():
        chunk = pf.read(off, 4096)
        out += chunk
        off += len(chunk)
    assert out == f.blob
    assert pf.hits > pf.misses  # windows served most reads
    ring.close()


def test_db_http_view_write_plane(tmp_path):
    """/db/<name> surfaces the WRITE_GROUP_* family next to WAL_*."""
    from toplingdb_tpu.utils.config import SidePluginRepo

    repo = SidePluginRepo()
    db = repo.open_db({"path": str(tmp_path / "h"),
                       "options": {"statistics": "default"}})
    name = list(repo._dbs)[0]
    for i in range(50):
        db.put(b"h%04d" % i, b"v")
    view = repo._route(["db", name])
    assert view is not None
    t = view["tickers"]
    for key in (st.WAL_BYTES, st.WRITE_GROUP_LED,
                st.WRITE_GROUP_NATIVE_COMMITS, st.WRITE_GROUP_FALLBACKS,
                st.WRITE_GROUP_FSYNCS_COALESCED):
        assert key in t
    assert t[st.WRITE_GROUP_LED] > 0
    assert view["write_group_bytes"]["count"] > 0
    repo.close_all()


def test_watermark_bookkeeping_unordered_stress(tmp_path):
    """The deque+watermark publish bookkeeping: many small staged groups
    publish in allocation order with no lost watermark advance."""
    import threading

    db = DB.open(str(tmp_path / "w"),
                 Options(create_if_missing=True, unordered_write=True))
    errs = []

    def w(t):
        try:
            for i in range(300):
                db.put(b"u%d-%05d" % (t, i), b"x")
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [__import__("threading").Thread(target=w, args=(t,))
          for t in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs
    assert not db._alloc_ranges and not db._alloc_entry
    assert db.versions.last_sequence == 4 * 300
    for t in range(4):
        for i in range(300):
            assert db.get(b"u%d-%05d" % (t, i)) == b"x"
    db.close()
