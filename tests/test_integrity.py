"""Integrity plane tests (ISSUE 5): per-entry protection info, whole-file
checksums in the MANIFEST, the IntegrityScrubber, and the corruption soak
— flip bits on the read path under concurrent load with protection on and
assert every corruption is DETECTED (error or quarantine), zero wrong
bytes are ever served, and scrub+repair+resume returns the DB to byte
parity with an uncorrupted twin."""

import json
import os
import random
import shutil
import tempfile
import threading
import urllib.request

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.env import PosixEnv
from toplingdb_tpu.env.fault_injection import FaultInjectionEnv
from toplingdb_tpu.options import Options
from toplingdb_tpu.utils import protection as prot
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.status import Corruption, InvalidArgument


def dump(db, cf=None):
    it = db.new_iterator(cf=cf) if cf is not None else db.new_iterator()
    it.seek_to_first()
    out = []
    while it.valid():
        out.append((it.key(), it.value()))
        it.next()
    return out


def fill(db, n, seed=0, vrep=10):
    rng = random.Random(seed)
    for i in range(n):
        k = b"k%06d" % i
        v = (b"v%05d." % rng.randrange(10**5)) * vrep
        db.put(k, v)
    return n


# ===========================================================================
# Protection primitives (utils/protection.py)
# ===========================================================================


def test_protect_entry_component_sensitivity():
    base = prot.protect_entry(1, b"key", b"value", cf=0)
    assert prot.protect_entry(1, b"kez", b"value", cf=0) != base
    assert prot.protect_entry(1, b"key", b"valuf", cf=0) != base
    assert prot.protect_entry(2, b"key", b"value", cf=0) != base
    assert prot.protect_entry(1, b"key", b"value", cf=1) != base
    # Deterministic (no per-process salt: checksums cross process hops).
    assert prot.protect_entry(1, b"key", b"value", cf=0) == base


def test_strip_cf_swaps_only_the_cf_component():
    full = prot.protect_entry(1, b"k", b"v", cf=7)
    assert prot.strip_cf(full, 7) == prot.protect_entry(1, b"k", b"v", cf=0)
    assert prot.strip_cf(full, 0) == full


def test_truncate_widths():
    cs = prot.protect_entry(1, b"a", b"b")
    for nb in (1, 2, 4):
        assert prot.truncate(cs, nb) == cs & ((1 << (8 * nb)) - 1)
    assert prot.truncate(cs, 8) == cs


def test_check_protection_bytes_rejects_odd_widths():
    for bad in (3, 5, 16, -1):
        with pytest.raises(InvalidArgument):
            prot.check_protection_bytes(bad)
    for ok in prot.VALID_PROTECTION_BYTES:
        prot.check_protection_bytes(ok)


# ===========================================================================
# WriteBatch / memtable handoffs
# ===========================================================================


def test_write_batch_detects_tampered_rep():
    from toplingdb_tpu.db.write_batch import WriteBatch

    b = WriteBatch(protection_bytes_per_key=8)
    b.put(b"alpha", b"one")
    b.put(b"beta", b"two")
    b.verify_protection()  # clean batch passes
    # Flip one byte of a value inside the wire rep: the next verification
    # (explicit, or the memtable-insert handoff) must refuse the batch.
    raw = bytearray(b._rep)
    raw[raw.index(b"two")] ^= 0x40
    b._rep = raw
    with pytest.raises(Corruption):
        b.verify_protection()
    from toplingdb_tpu.db.dbformat import InternalKeyComparator
    from toplingdb_tpu.db.memtable import MemTable

    mem = MemTable(InternalKeyComparator(), protection_bytes=8)
    with pytest.raises(Corruption):
        b.insert_into(mem, sequence=1)


def test_wire_loaded_batch_attach_protection():
    from toplingdb_tpu.db.write_batch import WriteBatch

    src = WriteBatch()
    src.put(b"x", b"1")
    src.delete(b"y")
    loaded = WriteBatch(src.data(), protection_bytes_per_key=4)
    loaded.verify_protection()
    assert loaded._prot is not None and len(loaded._prot) == 2


def test_flush_detects_memtable_corruption(tmp_path):
    d = str(tmp_path / "db")
    db = DB.open(d, Options(protection_bytes_per_key=8))
    try:
        for i in range(50):
            db.put(b"k%03d" % i, b"v%03d" % i)
        # Simulate the rep silently changing an entry under the recorded
        # checksum: the memtable->flush handoff must refuse to emit.
        mem = db._cfs[0].mem
        pmap = mem.protection_map()
        assert len(pmap) == 50  # wire-path checksums materialize here
        skey = next(iter(pmap))
        pmap[skey] ^= 1
        with pytest.raises(Corruption):
            db.flush()
    finally:
        try:
            db.close()  # close re-flushes and hits the same mismatch
        except Corruption:
            pass


# ===========================================================================
# Whole-file checksums (utils/file_checksum.py + MANIFEST)
# ===========================================================================


def test_file_checksum_generators():
    from toplingdb_tpu.utils.file_checksum import (
        Crc32cFileChecksumGen,
        FileChecksumGenFactory,
        Xxh64FileChecksumGen,
    )

    g1, g2 = Crc32cFileChecksumGen(), Crc32cFileChecksumGen()
    g1.update(b"hello world")
    g2.update(b"hello ")
    g2.update(b"world")
    assert g1.finalize() == g2.finalize()  # crc32c streams chunk-agnostic

    x1, x2 = Xxh64FileChecksumGen(), Xxh64FileChecksumGen()
    x1.update(b"ab")
    x1.update(b"c")
    x2.update(b"abc")
    # The xxh combinator chains per-chunk digests — framing-sensitive by
    # design; compute_file_checksum always feeds fixed-size chunks.
    assert x1.finalize() != x2.finalize()

    with pytest.raises(InvalidArgument):
        FileChecksumGenFactory("sha0")
    with pytest.raises(InvalidArgument):
        FileChecksumGenFactory().create("nope")
    assert FileChecksumGenFactory().names() == ["crc32c", "xxh64"]


def test_file_meta_checksum_manifest_roundtrip():
    from toplingdb_tpu.db.version_edit import FileMetaData

    m = FileMetaData(7, 123, b"a\x00" * 5, b"z\x00" * 5, 1, 9,
                     file_checksum=b"\xde\xad\xbe\xef",
                     file_checksum_func_name="crc32c")
    dec, _ = FileMetaData.decode(m.encode(extended=True), 0, extended=True)
    assert dec.file_checksum == b"\xde\xad\xbe\xef"
    assert dec.file_checksum_func_name == "crc32c"
    assert dec.quarantined is False  # in-memory only, never persisted
    # Plain (non-extended) encoding still round-trips without checksums.
    dec2, _ = FileMetaData.decode(m.encode(extended=False), 0,
                                  extended=False)
    assert dec2.file_checksum == b""


@pytest.mark.parametrize("func", ["crc32c", "xxh64"])
def test_checksums_recorded_and_survive_reopen(tmp_path, func):
    d = str(tmp_path / "db")
    db = DB.open(d, Options(protection_bytes_per_key=8, file_checksum=func,
                            write_buffer_size=16 * 1024))
    fill(db, 1500, seed=1)
    db.flush()
    db.wait_for_compactions()
    res = db.verify_file_checksums()
    assert res["files_verified"] >= 1 and res["files_skipped"] == 0
    db.close()

    db2 = DB.open(d, Options(file_checksum=func))
    try:
        res2 = db2.verify_file_checksums()
        assert res2["files_verified"] == res["files_verified"]
        metas = [f for cf_id in db2.versions.column_families
                 for _, f in db2.versions.cf_current(cf_id).all_files()]
        assert metas and all(m.file_checksum_func_name == func
                             for m in metas)
    finally:
        db2.close()

    # Offline (no DB open): the MANIFEST alone yields the digests.
    from toplingdb_tpu.utils.file_checksum import (
        manifest_file_checksums,
        verify_dir_file_checksums,
    )

    rec = manifest_file_checksums(d)
    assert rec and all(name == func for name, _ in rec.values())
    offline = verify_dir_file_checksums(d)
    assert offline["files_verified"] == res["files_verified"]


def _corrupt_table_file(dbdir, skip=None):
    """Flip one byte mid-file in the first (or first non-skipped) live
    SST; returns (path, original_bytes)."""
    ssts = sorted(f for f in os.listdir(dbdir) if f.endswith(".sst")
                  and f != skip)
    path = os.path.join(dbdir, ssts[0])
    orig = open(path, "rb").read()
    buf = bytearray(orig)
    buf[len(buf) // 2] ^= 0x01
    with open(path, "wb") as f:
        f.write(buf)
    return path, orig


def test_verify_file_checksums_detects_on_disk_corruption(tmp_path):
    d = str(tmp_path / "db")
    db = DB.open(d, Options(write_buffer_size=16 * 1024))
    try:
        fill(db, 1200, seed=2)
        db.flush()
        db.wait_for_compactions()
        _corrupt_table_file(d)
        with pytest.raises(Corruption, match="file checksum mismatch"):
            db.verify_file_checksums()
    finally:
        db.close()


# ===========================================================================
# IntegrityScrubber: detect, quarantine, repair, resume
# ===========================================================================


def test_scrubber_quarantine_repair_resume(tmp_path):
    from toplingdb_tpu.utils.listener import EventListener
    from toplingdb_tpu.utils.statistics import Statistics

    events = []

    class L(EventListener):
        def on_corruption_detected(self, db, info):
            events.append(info)

    d = str(tmp_path / "db")
    stats = Statistics()
    db = DB.open(d, Options(protection_bytes_per_key=8,
                            write_buffer_size=16 * 1024,
                            statistics=stats, listeners=[L()],
                            disable_auto_compactions=True))
    try:
        fill(db, 1500, seed=3)
        db.flush()
        expected = dump(db)
        path, orig = _corrupt_table_file(d)
        bad_num = int(os.path.basename(path).split(".")[0])

        rep = db.scrub()
        assert [c["file_number"] for c in rep["corruptions"]] == [bad_num]
        assert rep["quarantined"] == [bad_num]
        assert bad_num in db._quarantined
        assert events and events[0].file_number == bad_num
        assert events[0].recorded_checksum
        t = stats.tickers()
        assert t[st.INTEGRITY_CORRUPTIONS_DETECTED] == 1
        assert t[st.INTEGRITY_SCRUB_PASSES] >= 1
        assert stats.get_histogram(st.SCRUB_LATENCY_MICROS).count >= 1

        # The latch is HARD (resumable after repair), not FATAL: writes
        # fail now, resume() is allowed once the scrub is clean again.
        with pytest.raises(Exception):
            db.put(b"blocked", b"x")

        # Quarantine excludes the file from every compaction pick.
        from toplingdb_tpu.compaction.picker import LeveledCompactionPicker

        picker = LeveledCompactionPicker(db.options, db.icmp)
        c = picker.pick_compaction(db.versions.cf_current(0))
        assert c is None or all(
            f.number != bad_num
            for f in c.inputs + c.output_level_inputs)

        # Operator restores the bytes; a clean re-scrub lifts quarantine.
        with open(path, "wb") as f:
            f.write(orig)
        rep2 = db.scrub()
        assert not rep2["corruptions"] and rep2["repaired"] == [bad_num]
        assert bad_num not in db._quarantined
        db.resume()
        db.put(b"resumed", b"yes")
        assert db.get(b"resumed") == b"yes"
        assert dump(db) == expected + [(b"resumed", b"yes")]
    finally:
        db.close()


def test_background_scrubber_thread_runs_passes(tmp_path):
    import time

    d = str(tmp_path / "db")
    db = DB.open(d, Options(protection_bytes_per_key=8,
                            integrity_scrub_period_sec=1,
                            integrity_scrub_bytes_per_sec=0))
    try:
        fill(db, 300, seed=4)
        db.flush()
        assert db._integrity_scrubber is not None
        deadline = time.time() + 10
        while (db._integrity_scrubber.passes == 0
               and time.time() < deadline):
            time.sleep(0.05)
        assert db._integrity_scrubber.passes >= 1
        assert db.scrub_status()["running"]
    finally:
        db.close()


def test_verify_checksum_sweeps_blob_files(tmp_path):
    d = str(tmp_path / "db")
    db = DB.open(d, Options(enable_blob_files=True, min_blob_size=64,
                            write_buffer_size=1 << 20))
    try:
        for i in range(200):
            db.put(b"b%03d" % i, b"B%03d" % i * 40)  # > min_blob_size
        db.flush()
        db.verify_checksum()  # clean sweep incl. blob records
        blobs = [f for f in os.listdir(d) if f.endswith(".blob")]
        assert blobs
        path = os.path.join(d, blobs[0])
        buf = bytearray(open(path, "rb").read())
        buf[len(buf) // 2] ^= 0x10
        with open(path, "wb") as f:
            f.write(buf)
        with pytest.raises(Corruption):
            db.verify_checksum()
    finally:
        db.close()


# ===========================================================================
# Read-side corruption injection (env/fault_injection.py)
# ===========================================================================


def test_corrupt_read_is_deterministic_and_targeted(tmp_path):
    base = PosixEnv()
    fe = FaultInjectionEnv(base)
    p_sst = str(tmp_path / "000001.sst")
    p_log = str(tmp_path / "000002.log")
    payload = bytes(range(256)) * 64
    for p in (p_sst, p_log):
        with open(p, "wb") as f:
            f.write(payload)
    fe.corrupt_reads(pattern="*.sst", rate=1e-2, seed=42)

    def read_all(path):
        f = fe.new_random_access_file(path)
        try:
            return f.read(0, len(payload))
        finally:
            f.close()

    a, b = read_all(p_sst), read_all(p_sst)
    assert a == b  # seeded: the same read corrupts identically
    assert a != payload
    assert fe.corruptions_injected
    assert read_all(p_log) == payload  # pattern-targeted: logs untouched
    fe.clear_corrupt_reads()
    assert read_all(p_sst) == payload  # disk was never touched


def test_corrupted_wal_reads_fail_recovery_not_serve_garbage(tmp_path):
    d = str(tmp_path / "db")
    db = DB.open(d, Options(protection_bytes_per_key=8))
    for i in range(2000):
        db.put(b"w%04d" % i, b"v%04d" % i * 8)
    db.flush_wal(sync=True)
    # Simulate a crash: snapshot the live dir (WAL still holds every
    # write), then recover from the copy.
    crashed = str(tmp_path / "crashed")
    shutil.copytree(d, crashed)
    db.close()

    fe = FaultInjectionEnv(PosixEnv())
    fe.corrupt_reads(pattern="*.log", rate=1e-3, seed=9)
    with pytest.raises(Corruption):
        DB.open(crashed, Options(protection_bytes_per_key=8), env=fe)
    assert fe.corruptions_injected  # the injector really hit the WAL
    # Uncorrupted recovery from the same image replays everything.
    db2 = DB.open(crashed, Options(protection_bytes_per_key=8))
    try:
        assert db2.get(b"w0007") == b"v0007" * 8
        assert db2.get(b"w1999") == b"v1999" * 8
    finally:
        db2.close()


# ===========================================================================
# The corruption soak (acceptance criterion, CI-scaled)
# ===========================================================================


def test_corruption_soak_zero_wrong_bytes_and_twin_parity(tmp_path):
    """Concurrent read/write/flush/compaction with seeded read-side bit
    flips at 1e-5/byte across SST+blob reads, protection_bytes_per_key=8:
    every served read must be correct-or-error (never silently wrong),
    and after clearing faults + scrub + resume the DB must be
    byte-identical to an uncorrupted twin fed the same ops."""
    rng = random.Random(1234)
    ops = []
    for i in range(4000):
        k = b"s%05d" % rng.randrange(1500)
        if rng.random() < 0.12:
            ops.append(("del", k, None))
        else:
            ops.append(("put", k, b"V%07d." % rng.randrange(10**7) * 6))

    def build(dbdir, env=None):
        opts = Options(protection_bytes_per_key=8,
                       write_buffer_size=24 * 1024,
                       level0_file_num_compaction_trigger=3,
                       enable_blob_files=True, min_blob_size=40)
        return (DB.open(dbdir, opts, env=env) if env is not None
                else DB.open(dbdir, opts))

    fe = FaultInjectionEnv(PosixEnv())
    dbdir = str(tmp_path / "db")
    holder = {"db": build(dbdir, env=fe)}
    twin = build(str(tmp_path / "twin"))
    model = {}
    wrong = []
    detected = [0]
    stop = threading.Event()

    gen = [0]  # recovery generation: reads racing a swap aren't "wrong"

    def recover():
        """An injected-corruption hit may have latched the bg error
        (compaction-found corruption is even UNRECOVERABLE): resume when
        allowed, else reopen — the DISK is intact, only reads lied."""
        try:
            holder["db"].resume()
            return
        except Exception:
            pass
        gen[0] += 1
        old = holder["db"]
        try:
            # Acknowledged writes must survive the reopen even if close()
            # dies mid-flush on another injected fault.
            old.flush_wal(sync=True)
        except Exception:
            pass
        try:
            old.close()
        except Exception:
            pass
        holder["db"] = build(dbdir, env=fe)

    pending = {}  # key -> value of the op the writer is mid-applying

    def reader():
        r = random.Random(99)
        while not stop.is_set():
            k = b"s%05d" % r.randrange(1500)
            g0 = gen[0]
            exp = model.get(k)  # racy: only flag definite corruption
            p0 = pending.get(k)
            try:
                got = holder["db"].get(k)
            except Corruption:
                detected[0] += 1
                continue
            except Exception:
                continue  # latched/closed mid-recovery: not wrong bytes
            if (exp is not None and got is not None and got != exp
                    and got != model.get(k) and got != p0
                    and got != pending.get(k) and gen[0] == g0):
                wrong.append((k, got))

    t = threading.Thread(target=reader)
    t.start()
    try:
        for i, (op, k, v) in enumerate(ops):
            # The op counts as acknowledged only once it SUCCEEDS on the
            # faulted DB; then the twin/model apply it (retries are
            # idempotent: same key, same value). `pending` lets the
            # reader tell the in-flight new value from corruption.
            if op == "put":
                pending[k] = v
            for _attempt in range(10):
                try:
                    if op == "put":
                        holder["db"].put(k, v)
                    else:
                        holder["db"].delete(k)
                    break
                except Exception:
                    detected[0] += 1
                    recover()
            else:
                raise AssertionError("op never recovered")
            if op == "put":
                twin.put(k, v)
                model[k] = v
                pending.pop(k, None)
            else:
                twin.delete(k)
                model.pop(k, None)
            if i == len(ops) // 3:
                # Faults arm only after some SSTs exist to read through.
                # transient=True: a retried read draws fresh randomness
                # (bus-flip model), so recovery can make progress while
                # detection still fires at 1e-5/byte.
                fe.corrupt_reads(pattern="*.sst", rate=1e-5, seed=77,
                                 transient=True)
                fe.corrupt_reads(pattern="*.blob", rate=1e-5, seed=78,
                                 transient=True)
    finally:
        stop.set()
        t.join()

    assert not wrong, wrong[:3]  # ZERO corrupted bytes ever served
    # The injector really fired (otherwise the soak proved nothing).
    assert fe.corruptions_injected

    fe.clear_corrupt_reads()
    recover()
    db = holder["db"]
    db.wait_for_compactions()
    rep = db.scrub()
    assert not rep["corruptions"]  # disk was never damaged, reads were
    try:
        db.resume()
    except Exception:
        pass
    twin.wait_for_compactions()
    assert dump(db) == dump(twin)  # byte parity with the control run
    for d2 in (db, twin):
        d2.close()


@pytest.mark.parametrize("knob,value", [("TPULSM_PIPELINE", "1"),
                                        ("TPULSM_ITER_CHUNK", "1")])
def test_protected_parity_with_data_planes(tmp_path, monkeypatch, knob,
                                           value):
    """Protection-on runs through the pipelined compaction plane and the
    chunked scan plane must produce byte-identical results to the
    protection-off serial twin (the handoff checks must be pure
    verification, never a behavior change)."""
    if knob == "TPULSM_PIPELINE":
        import toplingdb_tpu.ops.pipeline as pl

        monkeypatch.setattr(pl, "MIN_PIPELINE_ROWS", 256)
        monkeypatch.setenv("TPULSM_PIPELINE_SHARDS", "4")
    monkeypatch.setenv(knob, value)

    def build(dbdir, pb):
        db = DB.open(dbdir, Options(protection_bytes_per_key=pb,
                                    write_buffer_size=24 * 1024,
                                    level0_file_num_compaction_trigger=3))
        rng = random.Random(5)
        for i in range(3000):
            db.put(b"p%05d" % rng.randrange(1200),
                   b"val%06d" % rng.randrange(10**6) * 4)
        db.flush()
        db.compact_range()
        return db

    db_p = build(str(tmp_path / "prot"), 8)
    monkeypatch.setenv(knob, "0")
    db_o = build(str(tmp_path / "off"), 0)
    try:
        monkeypatch.setenv(knob, value)
        got = dump(db_p)
        monkeypatch.setenv(knob, "0")
        want = dump(db_o)
        assert got == want
        res = db_p.verify_file_checksums()
        assert res["files_verified"] >= 1
    finally:
        db_p.close()
        db_o.close()


def test_scan_plane_emission_verification_catches_tampering(tmp_path,
                                                            monkeypatch):
    """White-box: served bytes that re-hash to a checksum absent from the
    source-side bank must raise at chunk emission — and an empty bank
    (nothing was ever decoded) must refuse everything."""
    from toplingdb_tpu.utils.statistics import Statistics

    monkeypatch.setenv("TPULSM_ITER_CHUNK", "1")
    d = str(tmp_path / "db")
    stats = Statistics()
    db = DB.open(d, Options(protection_bytes_per_key=8,
                            write_buffer_size=16 * 1024,
                            statistics=stats))
    try:
        fill(db, 2000, seed=6)
        db.flush()
        it = db.new_iterator()
        plane = getattr(it, "_plane", None)
        if plane is None:
            pytest.skip("scan plane ineligible in this configuration")
        assert plane._prot_bank is not None
        it.seek_to_first()
        n = 0
        while it.valid():
            n += 1
            it.next()
        assert n == 2000  # clean protected chunked scan

        # The emission check itself: a (key, value) whose checksum was
        # never banked — i.e. bytes that match no decoded source row —
        # is a Corruption and bumps the mismatch ticker.
        with pytest.raises(Corruption, match="protection mismatch"):
            plane._verify_emission(b"fabricated-key", b"fabricated-value")
        assert stats.tickers()[st.INTEGRITY_PROTECTION_MISMATCHES] >= 1
        # A banked row passes.
        uk = b"k000000"
        v = db.get(uk)
        plane._verify_emission(uk, v)
    finally:
        db.close()


# ===========================================================================
# Propagation guards: checkpoint + import
# ===========================================================================


def test_checkpoint_refuses_to_propagate_corruption(tmp_path):
    from toplingdb_tpu.utilities.checkpoint import create_checkpoint

    d = str(tmp_path / "db")
    db = DB.open(d, Options(write_buffer_size=16 * 1024,
                            disable_auto_compactions=True))
    try:
        fill(db, 1200, seed=7)
        db.flush()
        create_checkpoint(db, str(tmp_path / "ck_good"))
        from toplingdb_tpu.utils.file_checksum import (
            verify_dir_file_checksums,
        )

        good = verify_dir_file_checksums(str(tmp_path / "ck_good"))
        assert good["files_verified"] >= 1

        _corrupt_table_file(d)
        with pytest.raises(Corruption):
            create_checkpoint(db, str(tmp_path / "ck_bad"))
    finally:
        db.close()


def test_import_verifies_exported_file_checksums(tmp_path):
    from toplingdb_tpu.db.import_column_family_job import (
        export_column_family,
        import_column_family,
    )

    src = DB.open(str(tmp_path / "src"), Options(write_buffer_size=1 << 20))
    cf = src.create_column_family("payload")
    for i in range(400):
        src.put(b"i%04d" % i, b"v%04d" % i * 6, cf=cf)
    src.flush()
    exp_dir = str(tmp_path / "export")
    meta = export_column_family(src, cf, exp_dir)
    assert all(f.file_checksum for f in meta.files)  # digests ride along
    src.close()

    # Clean import re-verifies and succeeds.
    dst = DB.open(str(tmp_path / "dst1"), Options())
    try:
        h = import_column_family(dst, "payload", exp_dir)
        assert dst.get(b"i0007", cf=h) == b"v0007" * 6
    finally:
        dst.close()

    # A tampered exported file must be refused at import time.
    sst = [f for f in os.listdir(exp_dir) if f.endswith(".sst")][0]
    p = os.path.join(exp_dir, sst)
    buf = bytearray(open(p, "rb").read())
    buf[len(buf) // 2] ^= 0x04
    with open(p, "wb") as f:
        f.write(buf)
    dst2 = DB.open(str(tmp_path / "dst2"), Options())
    try:
        with pytest.raises(Corruption):
            import_column_family(dst2, "payload", exp_dir)
    finally:
        dst2.close()


# ===========================================================================
# Tooling + HTTP view
# ===========================================================================


def test_ldb_and_sst_dump_integrity_commands(tmp_path, capsys):
    from toplingdb_tpu.tools.ldb import main as ldb_main
    from toplingdb_tpu.tools.sst_dump import main as sst_main

    d = str(tmp_path / "db")
    db = DB.open(d, Options(write_buffer_size=16 * 1024,
                            disable_auto_compactions=True))
    fill(db, 1200, seed=8)
    db.flush()
    db.close()

    assert ldb_main(["--db", d, "verify_file_checksums"]) == 0
    assert "verified" in capsys.readouterr().out
    assert ldb_main(["--db", d, "scrub", "--report"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["files_scanned"] >= 1 and not rep["corruptions"]

    sst = sorted(f for f in os.listdir(d) if f.endswith(".sst"))[0]
    sst_path = os.path.join(d, sst)
    assert sst_main(["--file", sst_path, "--verify-file-checksum"]) == 0
    assert "OK" in capsys.readouterr().out

    # Corrupt the file: every tool must now refuse it.
    path, _ = _corrupt_table_file(d)
    assert sst_main(["--file", path, "--verify-file-checksum"]) == 1
    capsys.readouterr()
    assert ldb_main(["--db", d, "scrub"]) == 1
    assert "quarantined" in capsys.readouterr().out


def test_http_integrity_view_and_scrub_trigger(tmp_path):
    from toplingdb_tpu.utils.config import SidePluginRepo

    repo = SidePluginRepo()
    db = repo.open_db({"path": str(tmp_path / "db"),
                       "options": {"create_if_missing": True,
                                   "protection_bytes_per_key": 8,
                                   "write_buffer_size": 16384}},
                      name="main")
    port = repo.start_http()
    base = f"http://127.0.0.1:{port}"
    try:
        fill(db, 600, seed=9)
        db.flush()
        req = urllib.request.Request(f"{base}/scrub/main", data=b"{}",
                                     method="POST")
        with urllib.request.urlopen(req) as r:
            body = json.loads(r.read())
        assert body["ok"] and body["report"]["files_scanned"] >= 1
        with urllib.request.urlopen(f"{base}/integrity/main") as r:
            view = json.loads(r.read())
        assert view["protection_bytes_per_key"] == 8
        assert view["passes"] >= 1
        assert view["quarantined_files"] == []
    finally:
        repo.stop_http()
        db.close()
