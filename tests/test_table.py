import random

import pytest

from toplingdb_tpu.db.dbformat import (
    BYTEWISE,
    InternalKeyComparator,
    ValueType,
    make_internal_key,
)
from toplingdb_tpu.env import MemEnv
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.builder import TableBuilder, TableOptions
from toplingdb_tpu.table.reader import TableReader
from toplingdb_tpu.utils.status import Corruption

ICMP = InternalKeyComparator(BYTEWISE)


def build_table(env, path, entries, opts=None, tombstones=()):
    w = env.new_writable_file(path)
    b = TableBuilder(w, ICMP, opts)
    for k, v in entries:
        b.add(k, v)
    for begin, end in tombstones:
        b.add_tombstone(begin, end)
    props = b.finish()
    w.close()
    return props


def make_entries(n, vlen=20, seed=3):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        ik = make_internal_key(f"user{i:06d}".encode(), n - i, ValueType.VALUE)
        out.append((ik, rng.randbytes(vlen)))
    return out


@pytest.mark.parametrize("compression", [fmt.NO_COMPRESSION, fmt.ZLIB_COMPRESSION])
def test_table_roundtrip(compression):
    env = MemEnv()
    entries = make_entries(500)
    opts = TableOptions(block_size=512, compression=compression)
    props = build_table(env, "/t.sst", entries, opts)
    assert props.num_entries == 500
    assert props.num_data_blocks > 1

    r = TableReader(env.new_random_access_file("/t.sst"), ICMP, opts)
    assert r.properties.num_entries == 500
    it = r.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == entries


def test_table_seek_and_bounds():
    env = MemEnv()
    entries = make_entries(300)
    build_table(env, "/t.sst", entries, TableOptions(block_size=256))
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    it = r.new_iterator()
    # Seek to a key in the middle (user key order).
    target = make_internal_key(b"user000150", 2**56 - 1, 0x7F)
    it.seek(target)
    assert it.valid()
    assert it.key() == entries[150][0]
    # Past the end.
    it.seek(make_internal_key(b"zzzz", 0, 0))
    assert not it.valid()
    it.seek_to_last()
    assert it.key() == entries[-1][0]


def test_filter_blocks_negative_lookups():
    env = MemEnv()
    entries = make_entries(200)
    build_table(env, "/t.sst", entries)
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    for i in range(0, 200, 10):
        assert r.key_may_match(f"user{i:06d}".encode())
    misses = sum(
        1 for i in range(2000) if r.key_may_match(f"absent{i:06d}".encode())
    )
    assert misses < 100  # ~10 bits/key bloom: <<5% false positives


def test_checksum_detects_corruption():
    env = MemEnv()
    entries = make_entries(100)
    build_table(env, "/t.sst", entries)
    # Flip one byte in the middle of the file.
    st = env._files["/t.sst"]
    st.data[50] ^= 0xFF
    with pytest.raises(Corruption):
        r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
        it = r.new_iterator()
        it.seek_to_first()
        list(it.entries())


def test_range_del_block():
    env = MemEnv()
    entries = make_entries(50)
    begin = make_internal_key(b"user000010", 1000, ValueType.RANGE_DELETION)
    build_table(env, "/t.sst", entries, tombstones=[(begin, b"user000020")])
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    assert r.properties.num_range_deletions == 1
    tombs = r.range_del_entries()
    assert tombs == [(begin, b"user000020")]


def test_anchors_and_offsets():
    env = MemEnv()
    entries = make_entries(1000)
    build_table(env, "/t.sst", entries, TableOptions(block_size=256))
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    anchors = r.anchors(8)
    assert 1 <= len(anchors) <= 8
    offs = [r.approximate_offset_of(a) for a in anchors]
    assert offs == sorted(offs)


def test_empty_table():
    env = MemEnv()
    build_table(env, "/t.sst", [])
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    it = r.new_iterator()
    it.seek_to_first()
    assert not it.valid()


def test_two_level_index_parity(mem_env):
    """Partitioned (two-level) index: same read behavior as the flat index
    (reference kTwoLevelIndexSearch partitioned index)."""
    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions
    from toplingdb_tpu.table.reader import TableReader

    icmp = InternalKeyComparator(dbformat.BYTEWISE)
    entries = [
        (dbformat.make_internal_key(b"key%05d" % i, 100 + i, ValueType.VALUE),
         b"val%05d" % i)
        for i in range(5000)
    ]
    readers = {}
    for kind in ("binary", "two_level"):
        path = f"/{kind}.sst"
        w = mem_env.new_writable_file(path)
        b = TableBuilder(w, icmp, TableOptions(
            block_size=256, index_type=kind, metadata_block_size=512,
        ))
        for k, v in entries:
            b.add(k, v)
        props = b.finish()
        w.close()
        assert props.index_type == kind
        r = TableReader(mem_env.new_random_access_file(path), icmp,
                        TableOptions(block_size=256))
        assert r.properties.index_type == kind
        readers[kind] = r
    flat, part = readers["binary"], readers["two_level"]
    assert part._partitioned_index and not flat._partitioned_index
    # Top-level index must be much smaller than the flat one.
    assert len(part._index_data) < len(flat._index_data) / 4
    # Full scan equality.
    itf, itp = flat.new_iterator(), part.new_iterator()
    itf.seek_to_first(); itp.seek_to_first()
    assert list(itf.entries()) == list(itp.entries())
    # Seeks across partitions, boundaries, misses.
    for probe in (b"key00000", b"key02500", b"key04999", b"key03333x",
                  b"aaa", b"zzz"):
        t = dbformat.make_internal_key(probe, 2 ** 40, ValueType.VALUE)
        itf, itp = flat.new_iterator(), part.new_iterator()
        itf.seek(t); itp.seek(t)
        assert itf.valid() == itp.valid(), probe
        if itf.valid():
            assert itf.key() == itp.key() and itf.value() == itp.value()
    # Reverse iteration parity.
    itf, itp = flat.new_iterator(), part.new_iterator()
    itf.seek_to_last(); itp.seek_to_last()
    got_f, got_p = [], []
    while itf.valid():
        got_f.append(itf.key()); itf.prev()
    while itp.valid():
        got_p.append(itp.key()); itp.prev()
    assert got_f == got_p
    assert part.anchors(8) == flat.anchors(8)


def test_two_level_index_in_db_compaction(tmp_path):
    """A DB configured with partitioned indexes round-trips through flush,
    compaction (device fast path falls back), and reopen."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    d = str(tmp_path / "db")
    o = Options(write_buffer_size=16 * 1024, disable_auto_compactions=True)
    o.table_options.index_type = "two_level"
    o.table_options.metadata_block_size = 512
    with DB.open(d, o) as db:
        for i in range(4000):
            db.put(b"key%05d" % (i % 3000), b"v%05d" % i)
        db.flush()
        db.compact_range()
        assert db.get(b"key01500") is not None
        f = [f for lvl in db.versions.current.files for f in lvl][0]
        assert db.table_cache.get_reader(f.number).properties.index_type == \
            "two_level"
    with DB.open(d, o) as db:
        assert db.get(b"key02999") == b"v%05d" % 2999


def test_parallel_compression_byte_identical(mem_env):
    """The parallel-compression pipeline produces byte-identical files to
    the sequential path (reference ParallelCompressionRep ordering)."""
    import time

    from toplingdb_tpu.db import dbformat
    from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions
    from toplingdb_tpu.table.reader import TableReader

    icmp = InternalKeyComparator(dbformat.BYTEWISE)
    entries = [
        (dbformat.make_internal_key(b"key%06d" % i, 10 + i, ValueType.VALUE),
         (b"payload-%06d " % i) * 8)
        for i in range(4000)
    ]
    outs = {}
    for threads in (1, 4):
        path = f"/par{threads}.sst"
        w = mem_env.new_writable_file(path)
        b = TableBuilder(w, icmp, TableOptions(
            block_size=1024, compression=fmt.ZLIB_COMPRESSION,
            compression_parallel_threads=threads,
        ), creation_time=5)
        for k, v in entries:
            b.add(k, v)
        props = b.finish()
        w.close()
        assert props.num_data_blocks > 10
        outs[threads] = mem_env.read_file(path)
    assert outs[1] == outs[4], "parallel compression changed the bytes"
    r = TableReader(mem_env.new_random_access_file("/par4.sst"), icmp,
                    TableOptions(block_size=1024))
    it = r.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == entries
