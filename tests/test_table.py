import random

import pytest

from toplingdb_tpu.db.dbformat import (
    BYTEWISE,
    InternalKeyComparator,
    ValueType,
    make_internal_key,
)
from toplingdb_tpu.env import MemEnv
from toplingdb_tpu.table import format as fmt
from toplingdb_tpu.table.builder import TableBuilder, TableOptions
from toplingdb_tpu.table.reader import TableReader
from toplingdb_tpu.utils.status import Corruption

ICMP = InternalKeyComparator(BYTEWISE)


def build_table(env, path, entries, opts=None, tombstones=()):
    w = env.new_writable_file(path)
    b = TableBuilder(w, ICMP, opts)
    for k, v in entries:
        b.add(k, v)
    for begin, end in tombstones:
        b.add_tombstone(begin, end)
    props = b.finish()
    w.close()
    return props


def make_entries(n, vlen=20, seed=3):
    rng = random.Random(seed)
    out = []
    for i in range(n):
        ik = make_internal_key(f"user{i:06d}".encode(), n - i, ValueType.VALUE)
        out.append((ik, rng.randbytes(vlen)))
    return out


@pytest.mark.parametrize("compression", [fmt.NO_COMPRESSION, fmt.ZLIB_COMPRESSION])
def test_table_roundtrip(compression):
    env = MemEnv()
    entries = make_entries(500)
    opts = TableOptions(block_size=512, compression=compression)
    props = build_table(env, "/t.sst", entries, opts)
    assert props.num_entries == 500
    assert props.num_data_blocks > 1

    r = TableReader(env.new_random_access_file("/t.sst"), ICMP, opts)
    assert r.properties.num_entries == 500
    it = r.new_iterator()
    it.seek_to_first()
    assert list(it.entries()) == entries


def test_table_seek_and_bounds():
    env = MemEnv()
    entries = make_entries(300)
    build_table(env, "/t.sst", entries, TableOptions(block_size=256))
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    it = r.new_iterator()
    # Seek to a key in the middle (user key order).
    target = make_internal_key(b"user000150", 2**56 - 1, 0x7F)
    it.seek(target)
    assert it.valid()
    assert it.key() == entries[150][0]
    # Past the end.
    it.seek(make_internal_key(b"zzzz", 0, 0))
    assert not it.valid()
    it.seek_to_last()
    assert it.key() == entries[-1][0]


def test_filter_blocks_negative_lookups():
    env = MemEnv()
    entries = make_entries(200)
    build_table(env, "/t.sst", entries)
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    for i in range(0, 200, 10):
        assert r.key_may_match(f"user{i:06d}".encode())
    misses = sum(
        1 for i in range(2000) if r.key_may_match(f"absent{i:06d}".encode())
    )
    assert misses < 100  # ~10 bits/key bloom: <<5% false positives


def test_checksum_detects_corruption():
    env = MemEnv()
    entries = make_entries(100)
    build_table(env, "/t.sst", entries)
    # Flip one byte in the middle of the file.
    st = env._files["/t.sst"]
    st.data[50] ^= 0xFF
    with pytest.raises(Corruption):
        r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
        it = r.new_iterator()
        it.seek_to_first()
        list(it.entries())


def test_range_del_block():
    env = MemEnv()
    entries = make_entries(50)
    begin = make_internal_key(b"user000010", 1000, ValueType.RANGE_DELETION)
    build_table(env, "/t.sst", entries, tombstones=[(begin, b"user000020")])
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    assert r.properties.num_range_deletions == 1
    tombs = r.range_del_entries()
    assert tombs == [(begin, b"user000020")]


def test_anchors_and_offsets():
    env = MemEnv()
    entries = make_entries(1000)
    build_table(env, "/t.sst", entries, TableOptions(block_size=256))
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    anchors = r.anchors(8)
    assert 1 <= len(anchors) <= 8
    offs = [r.approximate_offset_of(a) for a in anchors]
    assert offs == sorted(offs)


def test_empty_table():
    env = MemEnv()
    build_table(env, "/t.sst", [])
    r = TableReader(env.new_random_access_file("/t.sst"), ICMP)
    it = r.new_iterator()
    it.seek_to_first()
    assert not it.valid()
