from toplingdb_tpu.db import dbformat
from toplingdb_tpu.db.dbformat import (
    BYTEWISE,
    InternalKeyComparator,
    LookupKey,
    ParsedInternalKey,
    ValueType,
    make_internal_key,
    split_internal_key,
)


def test_pack_roundtrip():
    for seq in (0, 1, 12345, dbformat.MAX_SEQUENCE_NUMBER):
        for t in (ValueType.VALUE, ValueType.DELETION, ValueType.MERGE):
            ik = make_internal_key(b"key", seq, t)
            uk, s, tt = split_internal_key(ik)
            assert (uk, s, tt) == (b"key", seq, t)


def test_internal_key_ordering():
    icmp = InternalKeyComparator(BYTEWISE)
    # Same user key: higher seqno sorts FIRST.
    a = make_internal_key(b"k", 100, ValueType.VALUE)
    b = make_internal_key(b"k", 99, ValueType.VALUE)
    assert icmp.compare(a, b) < 0
    # Different user keys: bytewise order dominates.
    c = make_internal_key(b"ka", 1, ValueType.VALUE)
    assert icmp.compare(a, c) < 0
    # Same (key, seqno): higher type sorts first.
    d = make_internal_key(b"k", 100, ValueType.MERGE)
    assert icmp.compare(d, a) < 0


def test_lookup_key_sees_older_versions():
    icmp = InternalKeyComparator(BYTEWISE)
    lk = LookupKey(b"k", 50)
    # Seeking to lk.internal_key must land at-or-after entries with seq <= 50.
    newer = make_internal_key(b"k", 51, ValueType.VALUE)
    visible = make_internal_key(b"k", 50, ValueType.VALUE)
    older = make_internal_key(b"k", 10, ValueType.VALUE)
    assert icmp.compare(newer, lk.internal_key) < 0
    assert icmp.compare(lk.internal_key, visible) < 0  # seek key sorts before
    assert icmp.compare(visible, older) < 0


def test_shortest_separator():
    icmp = InternalKeyComparator(BYTEWISE)
    a = make_internal_key(b"abcdefg", 5, ValueType.VALUE)
    b = make_internal_key(b"abzzzzz", 3, ValueType.VALUE)
    sep = icmp.find_shortest_separator(a, b)
    assert icmp.compare(a, sep) <= 0
    assert icmp.compare(sep, b) < 0
    assert len(sep) <= len(a)


def test_parsed_internal_key():
    p = ParsedInternalKey(b"u", 7, ValueType.MERGE)
    assert ParsedInternalKey.parse(p.encode()) == p
