"""Java binding (java/ — the RocksJava role). The full build+smoke runs
only when a JDK is present (gated; the CI image has none); the JNI C glue
is additionally syntax-checked whenever gcc is available so breakage
surfaces even without a JDK."""

import os
import shutil
import subprocess

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
JDIR = os.path.join(ROOT, "java")


def _java_home():
    javac = shutil.which("javac")
    if javac is None:
        return None
    home = os.path.dirname(os.path.dirname(os.path.realpath(javac)))
    if os.path.exists(os.path.join(home, "include", "jni.h")):
        return home
    return None


@pytest.mark.skipif(shutil.which("gcc") is None,
                    reason="C toolchain unavailable")
def test_jni_glue_compiles_against_c_abi():
    """Without jni.h we can still verify the JNI glue parses and its calls
    match the C ABI: compile with a minimal jni.h stand-in, syntax-only."""
    stub = os.path.join(JDIR, "jni", "_jni_stub")
    os.makedirs(stub, exist_ok=True)
    with open(os.path.join(stub, "jni.h"), "w") as f:
        f.write("""
#ifndef _TPULSM_JNI_STUB
#define _TPULSM_JNI_STUB
#include <stdint.h>
#include <stddef.h>
typedef int jint; typedef long long jlong; typedef signed char jbyte;
typedef unsigned char jboolean; typedef int jsize;
typedef void* jobject; typedef jobject jclass; typedef jobject jstring;
typedef jobject jarray; typedef jarray jbyteArray; typedef jobject jthrowable;
struct JNINativeInterface_; typedef const struct JNINativeInterface_* JNIEnv;
struct JNINativeInterface_ {
  jclass (*FindClass)(JNIEnv*, const char*);
  jint (*ThrowNew)(JNIEnv*, jclass, const char*);
  const char* (*GetStringUTFChars)(JNIEnv*, jstring, jboolean*);
  void (*ReleaseStringUTFChars)(JNIEnv*, jstring, const char*);
  jstring (*NewStringUTF)(JNIEnv*, const char*);
  jsize (*GetArrayLength)(JNIEnv*, jarray);
  jbyte* (*GetByteArrayElements)(JNIEnv*, jbyteArray, jboolean*);
  void (*ReleaseByteArrayElements)(JNIEnv*, jbyteArray, jbyte*, jint);
  jbyteArray (*NewByteArray)(JNIEnv*, jsize);
  void (*SetByteArrayRegion)(JNIEnv*, jbyteArray, jsize, jsize, const jbyte*);
};
#define JNIEXPORT __attribute__((visibility("default")))
#define JNICALL
#define JNI_TRUE 1
#define JNI_FALSE 0
#define JNI_ABORT 2
#endif
""")
    # The stub's JNIEnv is a pointer-to-struct-of-fn-pointers like the real
    # one, so (*env)->Fn(env, ...) calls type-check; -fsyntax-only keeps it
    # honest without linking.
    subprocess.run(
        ["gcc", "-fsyntax-only", "-I" + stub,
         "-I" + os.path.join(ROOT, "toplingdb_tpu", "bindings", "c"),
         os.path.join(JDIR, "jni", "tpulsm_jni.c")],
        check=True,
    )


@pytest.mark.skipif(_java_home() is None, reason="JDK unavailable")
def test_java_binding_end_to_end(tmp_path):
    env = dict(os.environ)
    env["JAVA_HOME"] = _java_home()
    r = subprocess.run(["make", "test"], cwd=JDIR, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "JAVA-API-OK" in r.stdout

@pytest.mark.skipif(_java_home() is None, reason="JDK unavailable")
def test_java_api_breadth(tmp_path):
    """CFs, transactions, backup, checkpoint, SST ingest, and the
    SidePluginRepo open-from-JSON flow through the Java API."""
    env = dict(os.environ)
    env["JAVA_HOME"] = _java_home()
    r = subprocess.run(["make", "test-breadth"], cwd=JDIR, env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "JAVA-BREADTH-OK" in r.stdout
