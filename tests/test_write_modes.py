"""Write-path levers: parallel memtable insert, pipelined writes,
unordered writes (reference db/db_impl/db_impl_write.cc:267-301,657 and
memtable/inlineskiplist.h:61 InsertConcurrently)."""

import threading

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, WriteOptions


def _fill_threads(db, n_threads=4, per_thread=300, batch=10):
    errs = []

    def worker(t):
        try:
            from toplingdb_tpu.db.write_batch import WriteBatch

            for i in range(0, per_thread, batch):
                b = WriteBatch()
                for j in range(i, i + batch):
                    b.put(b"t%02d-k%06d" % (t, j), b"v%06d-%02d" % (j, t))
                db.write(b)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs, errs


def _verify_all(db, n_threads=4, per_thread=300):
    for t in range(n_threads):
        for j in range(per_thread):
            assert db.get(b"t%02d-k%06d" % (t, j)) == b"v%06d-%02d" % (j, t)
    it = db.new_iterator()
    it.seek_to_first()
    n = sum(1 for _ in it.entries())
    assert n == n_threads * per_thread


@pytest.mark.parametrize("mode", ["parallel", "pipelined", "unordered",
                                  "pipelined+parallel"])
def test_concurrent_fill_modes(tmp_path, mode):
    opts = Options(create_if_missing=True)
    opts.allow_concurrent_memtable_write = "parallel" in mode
    opts.enable_pipelined_write = "pipelined" in mode
    opts.unordered_write = mode == "unordered"
    d = str(tmp_path / mode)
    db = DB.open(d, opts)
    _fill_threads(db)
    _verify_all(db)
    db.close()
    # Recovery: WAL replay must reconstruct everything.
    db2 = DB.open(d, opts)
    _verify_all(db2)
    db2.close()


def test_unordered_snapshot_drains(tmp_path):
    opts = Options(create_if_missing=True)
    opts.unordered_write = True
    db = DB.open(str(tmp_path / "u"), opts)
    stop = threading.Event()
    errs = []

    def writer():
        i = 0
        try:
            while not stop.is_set():
                db.put(b"w%08d" % i, b"x" * 16)
                i += 1
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(50):
            snap = db.get_snapshot()
            # At snapshot creation every allocated write <= snap seq must be
            # visible: a read at the snapshot must never miss a published key.
            assert snap.sequence <= db.versions.last_sequence
            db.release_snapshot(snap)
    finally:
        stop.set()
        t.join()
    assert not errs, errs
    db.close()


def test_pipelined_flush_and_recovery(tmp_path):
    opts = Options(create_if_missing=True)
    opts.enable_pipelined_write = True
    opts.write_buffer_size = 32 * 1024  # force memtable switches mid-run
    d = str(tmp_path / "p")
    db = DB.open(d, opts)
    _fill_threads(db, n_threads=3, per_thread=400)
    _verify_all(db, n_threads=3, per_thread=400)
    db.close()
    db2 = DB.open(d, opts)
    _verify_all(db2, n_threads=3, per_thread=400)
    db2.close()


def test_parallel_group_mixed_ops(tmp_path):
    """Deletes/merges/range-dels must survive the parallel fan-out."""
    from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

    opts = Options(create_if_missing=True)
    opts.allow_concurrent_memtable_write = True
    opts.merge_operator = UInt64AddOperator()
    db = DB.open(str(tmp_path / "m"), opts)
    import struct

    def worker(t):
        for i in range(200):
            db.merge(b"ctr%02d" % t, struct.pack("<Q", 1))

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    for t in range(4):
        assert struct.unpack("<Q", db.get(b"ctr%02d" % t))[0] == 200
    db.delete_range(b"ctr00", b"ctr02")
    assert db.get(b"ctr00") is None
    assert db.get(b"ctr01") is None
    assert struct.unpack("<Q", db.get(b"ctr02"))[0] == 200
    db.close()


def test_native_skiplist_concurrent_insert_stress():
    """Lock-free skiplist: concurrent batch inserts from multiple threads must not
    lose entries, and a concurrent reader must see a consistent ordered
    view (reference InlineSkipList::InsertConcurrently)."""
    import numpy as np

    from toplingdb_tpu.db.memtable import MemTable, NativeSkipListRep
    from toplingdb_tpu.db.dbformat import InternalKeyComparator, ValueType

    mt = MemTable(InternalKeyComparator(), NativeSkipListRep())
    n_threads, per_thread = 8, 2000
    barrier = threading.Barrier(n_threads)

    def worker(t):
        barrier.wait()
        ops = [(ValueType.VALUE, b"%02d-%08d" % (t, i), b"val%08d" % i)
               for i in range(per_thread)]
        # several small add_batch calls to maximize interleaving
        for s in range(0, per_thread, 100):
            mt.add_batch(t * per_thread + s + 1, ops[s:s + 100])

    ts = [threading.Thread(target=worker, args=(t,)) for t in range(n_threads)]
    for th in ts:
        th.start()
    for th in ts:
        th.join()
    entries = list(mt.iter_entries())
    assert len(entries) == n_threads * per_thread
    keys = [k for k, _ in entries]
    assert keys == sorted(keys)
