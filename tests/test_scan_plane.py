"""Chunked scan plane (ops/scan_plane.py): entry-for-entry parity with
the per-entry DBIter path, ticker agreement, fallback behavior, and the
secondary-cache promotion charge fix that rode along in the same PR."""

import os
import random

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions


@pytest.fixture
def chunk_env():
    """Restore TPULSM_ITER_CHUNK after each test."""
    saved = os.environ.get("TPULSM_ITER_CHUNK")
    yield
    if saved is None:
        os.environ.pop("TPULSM_ITER_CHUNK", None)
    else:
        os.environ["TPULSM_ITER_CHUNK"] = saved


def set_chunk(v):
    os.environ["TPULSM_ITER_CHUNK"] = v


def build_db(path, n=3000, compression=None, **opt_kw):
    """Multi-source DB: several SST files (flushes), overwrites,
    deletions, plus live memtable entries."""
    kw = dict(create_if_missing=True, write_buffer_size=32 * 1024)
    if compression is not None:
        from toplingdb_tpu.table.builder import TableOptions

        kw["table_options"] = TableOptions(compression=compression)
    kw.update(opt_kw)
    db = DB.open(path, Options(**kw))
    rng = random.Random(7)
    for i in range(n):
        db.put(b"key%06d" % rng.randrange(n), b"v%06d" % i)
    for i in range(0, n, 11):
        db.delete(b"key%06d" % i)
    db.flush()
    db.wait_for_compactions()
    for i in range(n // 2, n // 2 + n // 10):
        db.put(b"key%06d" % i, b"memv%06d" % i)
    return db


def scan_all(db, **ro_kw):
    it = db.new_iterator(ReadOptions(**ro_kw))
    it.seek_to_first()
    return list(it.entries())


def test_forward_parity_multi_source(tmp_db_path, chunk_env):
    db = build_db(tmp_db_path)
    try:
        set_chunk("0")
        a = scan_all(db)
        set_chunk("1")
        it = db.new_iterator()
        assert it._plane is not None, "plane must engage on eligible DBs"
        it.seek_to_first()
        b = list(it.entries())
        assert a == b and len(a) > 1000
        # small chunks force many refills + resume cuts
        set_chunk("64")
        assert scan_all(db) == a
    finally:
        db.close()


@pytest.mark.parametrize("codec", ["snappy", "zstd"])
def test_forward_parity_codecs(tmp_path, chunk_env, codec):
    from toplingdb_tpu.table import format as fmt
    from toplingdb_tpu.utils import codecs

    if not codecs.available(codec):
        pytest.skip(f"{codec} unavailable")
    comp = fmt.SNAPPY_COMPRESSION if codec == "snappy" \
        else fmt.ZSTD_COMPRESSION
    db = build_db(str(tmp_path / "db"), compression=comp)
    try:
        set_chunk("0")
        a = scan_all(db)
        set_chunk("1")
        assert scan_all(db) == a
    finally:
        db.close()


def test_seek_and_resume_parity(tmp_db_path, chunk_env):
    db = build_db(tmp_db_path)
    try:
        probes = [b"key%06d" % i for i in range(0, 3000, 37)]
        probes += [b"", b"zzz", b"key002999", b"key000000"]
        set_chunk("64")
        it1 = db.new_iterator()
        set_chunk("0")
        it0 = db.new_iterator()
        for k in probes:
            it1.seek(k)
            it0.seek(k)
            assert it1.valid() == it0.valid(), k
            # resume: walk a few entries from the seek point
            for _ in range(5):
                if not it0.valid():
                    break
                assert (it1.key(), it1.value()) == (it0.key(), it0.value())
                it0.next()
                it1.next()
                assert it1.valid() == it0.valid()
    finally:
        db.close()


def test_snapshot_parity(tmp_db_path, chunk_env):
    db = build_db(tmp_db_path, n=1000)
    try:
        snap = db.get_snapshot()
        for i in range(0, 1000, 3):
            db.put(b"key%06d" % i, b"after-snap")
        db.delete(b"key000500")
        set_chunk("0")
        a = scan_all(db, snapshot=snap)
        set_chunk("1")
        b = scan_all(db, snapshot=snap)
        assert a == b
        assert all(v != b"after-snap" for _, v in b)
        db.release_snapshot(snap)
    finally:
        db.close()


def test_range_tombstone_parity(tmp_db_path, chunk_env):
    db = build_db(tmp_db_path, n=1500)
    try:
        db.delete_range(b"key000200", b"key000400")
        db.flush()
        db.delete_range(b"key000900", b"key000950")
        set_chunk("0")
        a = scan_all(db)
        set_chunk("64")
        b = scan_all(db)
        assert a == b
        assert not any(b"key000200" <= k < b"key000400" for k, _ in b)
    finally:
        db.close()


def test_bounds_parity(tmp_db_path, chunk_env):
    db = build_db(tmp_db_path)
    try:
        for lo, hi in ((b"key000100", b"key002500"),
                       (b"key001499", b"key001500"),
                       (None, b"key000001"), (b"key002990", None)):
            kw = {}
            if lo is not None:
                kw["iterate_lower_bound"] = lo
            if hi is not None:
                kw["iterate_upper_bound"] = hi
            set_chunk("0")
            a = scan_all(db, **kw)
            set_chunk("64")
            b = scan_all(db, **kw)
            assert a == b, (lo, hi)
            if a and lo is not None:
                assert a[0][0] >= lo
            if a and hi is not None:
                assert a[-1][0] < hi
    finally:
        db.close()


def test_direction_switch_fallback(tmp_db_path, chunk_env):
    db = build_db(tmp_db_path, n=800)
    try:
        set_chunk("64")
        it1 = db.new_iterator()
        set_chunk("0")
        it0 = db.new_iterator()
        for it in (it1, it0):
            it.seek(b"key000300")
            for _ in range(7):
                it.next()
        assert it1.key() == it0.key()
        it1.prev()
        it0.prev()
        assert it1._plane is None, "prev must degrade to per-entry"
        for _ in range(5):
            assert it1.valid() == it0.valid()
            if not it0.valid():
                break
            assert (it1.key(), it1.value()) == (it0.key(), it0.value())
            it1.prev()
            it0.prev()
        # seek_to_last / seek_for_prev drop the plane up front
        set_chunk("1")
        it2 = db.new_iterator()
        it2.seek_to_last()
        assert it2._plane is None
        set_chunk("0")
        it3 = db.new_iterator()
        it3.seek_to_last()
        assert (it2.valid(), it2.key()) == (it3.valid(), it3.key())
    finally:
        db.close()


def test_mutate_while_iterating_soak(tmp_db_path, chunk_env):
    """The chunk must stay pinned to its creation-time view: concurrent
    puts/deletes/flushes are invisible to an open iterator."""
    db = build_db(tmp_db_path, n=2000)
    try:
        set_chunk("0")
        expect = scan_all(db)
        set_chunk("128")
        it = db.new_iterator()
        it.seek_to_first()
        got = []
        rng = random.Random(3)
        steps = 0
        while it.valid():
            got.append((it.key(), it.value()))
            steps += 1
            if steps % 150 == 0:
                for _ in range(40):
                    k = b"key%06d" % rng.randrange(2000)
                    db.put(k, b"mutated")
                    db.delete(b"key%06d" % rng.randrange(2000))
                db.flush()
            it.next()
        assert got == expect
    finally:
        db.close()


def test_ticker_parity_and_prefetch(tmp_db_path, chunk_env):
    from toplingdb_tpu.utils import statistics as st

    def run(mode):
        set_chunk(mode)
        stats = st.Statistics()
        db = DB.open(tmp_db_path, Options(create_if_missing=True,
                                          statistics=stats))
        try:
            it = db.new_iterator()
            it.seek_to_first()
            n = 0
            while it.valid():
                it.key(), it.value()
                it.next()
                n += 1
            it.seek(b"key000100")
            while it.valid():
                it.next()
            g = stats.get_ticker_count
            return (n, g(st.NUMBER_DB_SEEK), g(st.NUMBER_DB_NEXT),
                    g(st.NUMBER_DB_SEEK_FOUND), g(st.ITER_BYTES_READ),
                    g(st.PREFETCH_HITS) + g(st.PREFETCH_MISSES),
                    g(st.ITER_CHUNK_REFILLS))
        finally:
            db.close()

    db = build_db(tmp_db_path, n=2500)
    db.close()
    r0 = run("0")
    r1 = run("1")
    # op/byte accounting agrees exactly between the two paths
    assert r0[:5] == r1[:5]
    assert r1[5] > 0, "chunked path must feed PREFETCH_* tickers"
    assert r0[5] > 0, "per-entry path must feed PREFETCH_* tickers"
    assert r1[6] > 0 and r0[6] == 0


def test_plane_gating(tmp_db_path, chunk_env):
    from toplingdb_tpu.utils.merge_operator import StringAppendOperator

    set_chunk("1")
    db = DB.open(tmp_db_path, Options(
        create_if_missing=True, merge_operator=StringAppendOperator()))
    try:
        db.put(b"a", b"1")
        it = db.new_iterator()
        assert it._plane is None, "merge operator must gate the plane off"
    finally:
        db.close()


def test_plane_with_snapshot_less_refresh(tmp_db_path, chunk_env):
    set_chunk("1")
    db = build_db(tmp_db_path, n=500)
    try:
        it = db.new_iterator()
        it.seek_to_first()
        k0 = it.key()
        db.put(b"key000000a", b"fresh")
        it.refresh()
        it.seek_to_first()
        assert it.valid()
        keys = [k for k, _ in it.entries()]
        assert b"key000000a" in keys and k0 in keys
    finally:
        db.close()


def test_readahead_size_option(tmp_db_path, chunk_env):
    """ReadOptions.readahead_size pins a fixed prefetch window through
    TableIterator/LevelIterator (and the scan plane)."""
    set_chunk("0")
    db = build_db(tmp_db_path, n=2000)
    try:
        a = scan_all(db)
        b = scan_all(db, readahead_size=128 * 1024)
        assert a == b
        set_chunk("1")
        c = scan_all(db, readahead_size=128 * 1024)
        assert a == c
        # the fixed window reaches the file iterator
        from toplingdb_tpu.table.reader import TableIterator

        v = db.versions.cf_current(0)
        meta = next(f for lvl in v.files for f in lvl)
        r = db.table_cache.get_reader(meta.number)
        ti = r.new_iterator(readahead_size=64 * 1024)
        assert isinstance(ti, TableIterator)
        assert ti._pf._max == 64 * 1024
        assert ti._pf._readahead == 64 * 1024
    finally:
        db.close()


def test_blob_db_parity(tmp_db_path, chunk_env):
    db = DB.open(tmp_db_path, Options(
        create_if_missing=True, enable_blob_files=True, min_blob_size=8,
        write_buffer_size=16 * 1024))
    try:
        for i in range(400):
            db.put(b"k%04d" % i, b"blobvalue-%04d" % i * 4)
        db.flush()
        for i in range(400, 450):
            db.put(b"k%04d" % i, b"small")
        set_chunk("0")
        a = scan_all(db)
        set_chunk("1")
        b = scan_all(db)
        assert a == b and len(a) == 450
    finally:
        db.close()


# -- searchable-compression zip tables on the plane ---------------------


@pytest.fixture
def zip_env():
    """Restore TPULSM_ZIP_PLANE after each test."""
    saved = os.environ.get("TPULSM_ZIP_PLANE")
    yield
    if saved is None:
        os.environ.pop("TPULSM_ZIP_PLANE", None)
    else:
        os.environ["TPULSM_ZIP_PLANE"] = saved


def build_zip_db(path, n=3000):
    """Multi-level mixed-format DB: zip tables at the bottommost level
    under block-format L0 files, plus live memtable entries (overwrites
    and deletions layered on top of the zip level)."""
    db = DB.open(path, Options(create_if_missing=True,
                               write_buffer_size=64 * 1024,
                               bottommost_format="zip",
                               disable_auto_compactions=True))
    rng = random.Random(13)
    for i in range(n):
        db.put(b"key%06d" % rng.randrange(n), b"zipv%06d" % i)
    db.flush()
    db.compact_range()          # bottommost level is now zip tables
    for i in range(0, n, 5):    # block-format L0 on top
        db.put(b"key%06d" % i, b"over%06d" % i)
    for i in range(0, n, 17):
        db.delete(b"key%06d" % i)
    db.flush()
    for i in range(n // 3, n // 3 + n // 10):  # live memtable layer
        db.put(b"key%06d" % i, b"memv%06d" % i)
    return db


def _assert_zip_bottom(db):
    from toplingdb_tpu.table.zip_table import ZipTableReader

    files = [f for lvl, f in db.versions.current.all_files() if lvl > 0]
    assert files, "no bottommost files"
    assert all(isinstance(db.table_cache.get_reader(f.number),
                          ZipTableReader) for f in files)


def test_zip_plane_readseq_and_seek_parity(tmp_path, chunk_env, zip_env):
    db = build_zip_db(str(tmp_path / "db"))
    try:
        _assert_zip_bottom(db)
        set_chunk("0")
        a = scan_all(db)
        set_chunk("1")
        it = db.new_iterator()
        assert it._plane is not None, "zip tables must stay plane-eligible"
        it.seek_to_first()
        assert list(it.entries()) == a and len(a) > 1000
        # small chunks force refills that straddle zip value groups
        set_chunk("64")
        assert scan_all(db) == a
        # seek + resume parity into and across the zip level
        probes = [k for k, _ in a[:: len(a) // 16]] + [b"", b"zzz"]
        probes += [k + b"\x00" for k, _ in a[:: len(a) // 7]]
        set_chunk("64")
        it1 = db.new_iterator()
        set_chunk("0")
        it0 = db.new_iterator()
        for k in probes:
            it1.seek(k)
            it0.seek(k)
            assert it1.valid() == it0.valid(), k
            for _ in range(4):
                if not it0.valid():
                    break
                assert (it1.key(), it1.value()) == (it0.key(), it0.value())
                it0.next()
                it1.next()
                assert it1.valid() == it0.valid()
        # upper bound cutting inside the zip level
        mid = a[len(a) // 2][0]
        set_chunk("1")
        b = scan_all(db, iterate_upper_bound=mid)
        set_chunk("0")
        assert b == scan_all(db, iterate_upper_bound=mid)
    finally:
        db.close()


def test_zip_plane_ticker_parity(tmp_path, chunk_env, zip_env):
    from toplingdb_tpu.utils import statistics as st

    d = str(tmp_path / "db")
    db = build_zip_db(d, n=2500)
    db.close()

    def run(mode):
        set_chunk(mode)
        stats = st.Statistics()
        db = DB.open(d, Options(bottommost_format="zip",
                                disable_auto_compactions=True,
                                statistics=stats))
        try:
            it = db.new_iterator()
            it.seek_to_first()
            n = 0
            while it.valid():
                it.key(), it.value()
                it.next()
                n += 1
            it.seek(b"key000100")
            while it.valid():
                it.next()
            g = stats.get_ticker_count
            return (n, g(st.NUMBER_DB_SEEK), g(st.NUMBER_DB_NEXT),
                    g(st.NUMBER_DB_SEEK_FOUND), g(st.ITER_BYTES_READ),
                    g(st.ITER_CHUNK_REFILLS), g(st.ITER_CHUNK_FALLBACKS),
                    g(st.ZIP_GROUP_DECODES), g(st.ZIP_GROUP_DECODE_BYTES),
                    g(st.ZIP_PLANE_FALLBACKS))
        finally:
            db.close()

    r0 = run("0")
    r1 = run("1")
    # op/byte accounting agrees exactly between the two paths
    assert r0[:5] == r1[:5]
    assert r1[5] > 0 and r0[5] == 0, "refills only on the chunked path"
    assert r1[6] == 0, "zip tables must not trigger chunk fallbacks"
    assert r1[7] > 0 and r1[8] > 0, "zip group decodes must serve the scan"
    assert r0[7] == 0, "per-entry path never bulk-decodes groups"

    # knob off: identical scan via per-entry fallback, fallback tickers fire
    os.environ["TPULSM_ZIP_PLANE"] = "0"
    roff = run("1")
    assert roff[:5] == r1[:5]
    assert roff[7] == 0, "no group decodes with the plane off"
    assert roff[9] > 0, "plane-off zip DB must tick ZIP_PLANE_FALLBACKS"
    assert roff[6] > 0, "plane-off zip DB degrades via ITER_CHUNK_FALLBACKS"


# -- secondary-cache promotion charge (utils/cache.py satellite) --------


def test_secondary_promote_uses_recorded_charge():
    from toplingdb_tpu.utils.cache import CompressedSecondaryCache, LRUCache

    sec = CompressedSecondaryCache(1 << 20)
    lru = LRUCache(4096, num_shards=1, secondary=sec)
    # Insert with a charge LARGER than len(value) (e.g. charged overhead):
    # eviction spills to the secondary, promotion must re-insert with the
    # SAME charge, not len(value).
    lru.insert(b"k1", b"x" * 100, 3000)
    lru.insert(b"k2", b"y" * 100, 3000)  # evicts k1 -> secondary
    assert lru.lookup(b"k1") == b"x" * 100  # promoted back
    shard = lru._shard(b"k1")
    assert shard._items[b"k1"][1] == 3000, \
        "promotion must use the secondary's recorded charge"
    # and the shard budget stays enforced: usage <= capacity wiggle
    assert shard.usage <= 3000


def test_secondary_promote_guards_non_bytes():
    from toplingdb_tpu.utils.cache import LRUCache

    class OddSecondary:
        def __init__(self):
            self.store = {}

        def insert(self, k, v):
            self.store[k] = v

        def lookup(self, k):
            return self.store.get(k)

    sec = OddSecondary()
    lru = LRUCache(1024, num_shards=1, secondary=sec)
    sec.store[b"obj"] = ["not", "bytes"]
    # Served, but NOT promoted (unknown charge would corrupt accounting).
    assert lru.lookup(b"obj") == ["not", "bytes"]
    assert b"obj" not in lru._shard(b"obj")._items
