"""BlockedBloomFilterPolicy (reference FastLocalBloom role): no false
negatives, sane false-positive rate, python/native build + probe parity."""

import numpy as np
import pytest

from toplingdb_tpu import native
from toplingdb_tpu.table.filter import (
    BlockedBloomFilterPolicy,
    filter_policy_from_name,
)


def test_no_false_negatives_and_fp_rate():
    bp = BlockedBloomFilterPolicy(10.0)
    keys = [b"key%07d" % i for i in range(20_000)]
    f = bp.create_filter(keys)
    assert all(bp.key_may_match(k, f) for k in keys)
    fps = sum(bp.key_may_match(b"miss%06d" % i, f) for i in range(20_000))
    # Blocked blooms trade a little FP rate for locality; ~1-3% at 10bpk.
    assert fps / 20_000 < 0.05, fps


def test_name_roundtrip():
    bp = BlockedBloomFilterPolicy(12.0)
    p2 = filter_policy_from_name(bp.name())
    assert isinstance(p2, BlockedBloomFilterPolicy)
    assert p2.bits_per_key == 12.0


@pytest.mark.skipif(native.lib() is None
                    or not hasattr(native.lib(),
                                   "tpulsm_bloom_build_blocked"),
                    reason="native blocked build unavailable")
def test_native_build_matches_python():
    bp = BlockedBloomFilterPolicy(10.0)
    keys = [b"uk%06d" % i for i in range(5_000)]
    want = bp.create_filter(keys)
    from toplingdb_tpu.utils import coding

    lib = native.lib()
    n = len(keys)
    num_lines = max(1, (int(n * bp.bits_per_key) + 511) // 512)
    buf = b"".join(keys)
    kb = np.frombuffer(buf, np.uint8)
    offs = np.arange(n, dtype=np.int32) * 8
    lens = np.full(n, 8, np.int32)
    bits = np.zeros(num_lines * 64, np.uint8)
    lib.tpulsm_bloom_build_blocked(
        native.np_u8p(kb), native.np_i32p(offs), native.np_i32p(lens), n,
        num_lines, bp.num_probes, native.np_u8p(bits))
    got = (coding.encode_varint32(num_lines) + bytes([bp.num_probes])
           + bits.tobytes())
    assert got == want
