"""BlobSource cache tier (reference db/blob/blob_source.{h,cc} +
blob_file_cache.cc): value-cache hits skip file reads, the open-reader
set is LRU-capped, and the tickers tell the story."""

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.cache import LRUCache


@pytest.fixture
def tmp_db_path(tmp_path):
    return str(tmp_path / "db")


def _fill_blob_db(path, n=300, vsize=600, **kw):
    stats = st.Statistics()
    opts = Options(create_if_missing=True, enable_blob_files=True,
                   min_blob_size=256, statistics=stats, **kw)
    db = DB.open(path, opts)
    for i in range(n):
        db.put(b"k%06d" % i, b"B%05d" % i + b"x" * (vsize - 6))
    db.flush()
    db.wait_for_compactions()
    return db, stats


def test_blob_value_cache_hits(tmp_db_path):
    db, stats = _fill_blob_db(tmp_db_path, blob_cache=4 << 20)
    # Cold pass populates; warm pass must be all cache hits.
    for i in range(300):
        assert db.get(b"k%06d" % i)[:6] == b"B%05d" % i
    misses0 = stats.get_ticker_count(st.BLOB_DB_CACHE_MISS)
    file_bytes0 = stats.get_ticker_count(st.BLOB_DB_BLOB_FILE_BYTES_READ)
    assert misses0 > 0 and file_bytes0 > 0
    for i in range(300):
        assert db.get(b"k%06d" % i)[:6] == b"B%05d" % i
    assert stats.get_ticker_count(st.BLOB_DB_CACHE_MISS) == misses0, \
        "warm pass must not miss"
    assert stats.get_ticker_count(st.BLOB_DB_BLOB_FILE_BYTES_READ) \
        == file_bytes0, "warm pass must not touch blob files"
    assert stats.get_ticker_count(st.BLOB_DB_CACHE_HIT) >= 300
    db.close()


def test_blob_cache_capacity_evicts(tmp_db_path):
    # Capacity for only a few values: the second pass must re-read.
    db, stats = _fill_blob_db(tmp_db_path, blob_cache=2048)
    for i in range(300):
        db.get(b"k%06d" % i)
    m0 = stats.get_ticker_count(st.BLOB_DB_CACHE_MISS)
    for i in range(300):
        db.get(b"k%06d" % i)
    assert stats.get_ticker_count(st.BLOB_DB_CACHE_MISS) > m0
    db.close()


def test_blob_cache_accepts_cache_instance(tmp_db_path):
    shared = LRUCache(1 << 20)
    db, stats = _fill_blob_db(tmp_db_path, blob_cache=shared)
    for i in range(100):
        db.get(b"k%06d" % i)
    for i in range(100):
        db.get(b"k%06d" % i)
    assert stats.get_ticker_count(st.BLOB_DB_CACHE_HIT) >= 100
    db.close()


def test_no_cache_still_reads(tmp_db_path):
    db, stats = _fill_blob_db(tmp_db_path)  # blob_cache=None
    for i in range(50):
        assert db.get(b"k%06d" % i) is not None
    assert stats.get_ticker_count(st.BLOB_DB_CACHE_HIT) == 0
    assert stats.get_ticker_count(st.BLOB_DB_BLOB_FILE_BYTES_READ) > 0
    db.close()


def test_reader_open_limit(tmp_db_path):
    # Many blob files (tiny write buffer forces many flushes), open cap 2.
    db, stats = _fill_blob_db(tmp_db_path, n=400,
                              write_buffer_size=16 << 10,
                              blob_file_open_limit=2)
    for i in range(0, 400, 7):
        assert db.get(b"k%06d" % i) is not None
    assert len(db.blob_source._readers) <= 2
    db.close()


def test_db_bench_blob_workloads(tmp_path):
    from toplingdb_tpu.tools import db_bench as dbb

    argv = ["--benchmarks=fillrandomblob,readrandomblob",
            "--num=400", "--value-size=512",
            f"--db={tmp_path}/benchdb", "--statistics"]
    rc = dbb.main(argv)
    assert rc in (0, None)
