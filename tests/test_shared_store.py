"""Disaggregated SST storage (toplingdb_tpu/storage/).

Acceptance matrix:
  - address scheme: stability, self-verification, free dedup
  - concurrent publish idempotence (racing publishers, one object)
  - SharedSstEnv parity matrix: TPULSM_SHARED_STORE off/on byte-identical
    across table formats x codecs x snapshots x range tombstones
  - reference-mode checkpoint: no SST bytes in the snapshot dir, restore
    equivalence, hardlink fast path == copy fallback
  - migration bootstrap under 30% store faults: merged-oracle parity,
    corrupt fetches caught by checksum verify and never installed
  - GC never sweeps live (manifest-live, refs-live, pinned, leased)
  - dcompact store mode: second-process job with ZERO SST bytes shipped
  - HTTP store round trip under no_thread_leaks
"""

import glob
import json
import os
import threading

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.env import default_env
from toplingdb_tpu.env.env import MemEnv
from toplingdb_tpu.env.fault_injection import StoreFaultInjector
from toplingdb_tpu.options import Options
from toplingdb_tpu.storage import (
    LocalObjectStore,
    REFS_NAME,
    SharedSstEnv,
    StoreClient,
    StoreServer,
    collect_live_addresses,
    mark_sweep,
    object_address,
    open_store,
    parse_address,
    store_spec_enabled,
    verify_payload,
)
from toplingdb_tpu.storage.object_store import address_of_meta
from toplingdb_tpu.table import format as tfmt
from toplingdb_tpu.utilities.checkpoint import Checkpoint
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.file_checksum import FileChecksumGenFactory
from toplingdb_tpu.utils.statistics import Statistics
from toplingdb_tpu.utils.status import Busy, Corruption, NotFound


def _addr_for(payload: bytes, func: str = "crc32c") -> str:
    gen = FileChecksumGenFactory(func).create()
    gen.update(payload)
    return object_address(func, gen.finalize(), len(payload))


def _opts(**kw):
    kw.setdefault("create_if_missing", True)
    kw.setdefault("write_buffer_size", 1 << 20)
    return Options(**kw)


def _workload(db, n=400):
    """Flush-spanning workload with overwrites, deletes, snapshots, and a
    range tombstone — every read-plane shape the parity matrix covers."""
    for i in range(n):
        db.put(b"k%05d" % i, b"v%d" % i * 17)
    db.flush()
    snap = db.get_snapshot()
    for i in range(0, n, 3):
        db.put(b"k%05d" % i, b"w%d" % i * 11)
    for i in range(0, n, 7):
        db.delete(b"k%05d" % i)
    db.delete_range(b"k%05d" % (n // 2), b"k%05d" % (n // 2 + 20))
    db.flush()
    db.compact_range()
    return snap


def _fingerprint(db, snap, n=400):
    rows = []
    it = db.new_iterator()
    it.seek_to_first()
    while it.valid():
        rows.append((it.key(), it.value()))
        it.next()
    gets = [db.get(b"k%05d" % i) for i in range(n)]
    snap_gets = []
    if snap is not None:
        from toplingdb_tpu.options import ReadOptions
        ro = ReadOptions(snapshot=snap)
        snap_gets = [db.get(b"k%05d" % i, ro) for i in range(0, n, 13)]
    return rows, gets, snap_gets


# ---------------------------------------------------------------------------
# Addresses + object store
# ---------------------------------------------------------------------------


def test_address_scheme_stability_and_verification():
    payload = b"block" * 1000
    a1, a2 = _addr_for(payload), _addr_for(payload)
    assert a1 == a2  # same bytes -> same address, always
    func, digest, size = parse_address(a1)
    assert func == "crc32c" and size == len(payload)
    assert object_address(func, digest, size) == a1
    verify_payload(a1, payload)
    with pytest.raises(Corruption):
        verify_payload(a1, payload[:-1])  # truncation
    with pytest.raises(Corruption):
        verify_payload(a1, b"X" + payload[1:])  # bitrot
    assert _addr_for(payload) != _addr_for(payload + b"x")


def test_local_store_dedup_and_pins(tmp_path):
    store = LocalObjectStore(str(tmp_path / "store"))
    payload = b"sst" * 500
    addr = _addr_for(payload)
    assert store.put(addr, payload) is True
    assert store.put(addr, payload) is False  # dedup: second put is a no-op
    assert store.fetch(addr) == payload
    with pytest.raises(Corruption):
        store.put(_addr_for(b"other"), payload)  # wrong bytes never land
    with pytest.raises(NotFound):
        store.fetch(_addr_for(b"missing"))
    store.pin(addr, "tester", ttl=60.0)
    assert addr in store.pinned()
    store.unpin(addr)
    assert addr not in store.pinned()


def test_concurrent_publish_idempotent(tmp_path):
    store = LocalObjectStore(str(tmp_path / "store"))
    payload = os.urandom(64 * 1024)
    addr = _addr_for(payload)
    results, errs = [], []

    def racer():
        try:
            results.append(store.put(addr, payload))
        except Exception as e:  # noqa: BLE001 — the test records it
            errs.append(e)

    threads = [threading.Thread(target=racer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert store.fetch(addr) == payload
    assert store.list_addresses() == [addr]  # one object, no tmp residue


def test_open_store_spec_forms(tmp_path):
    assert not store_spec_enabled(None)
    assert not store_spec_enabled("")
    assert not store_spec_enabled("0")
    assert store_spec_enabled(str(tmp_path / "s"))
    s = open_store(str(tmp_path / "s"))
    assert isinstance(s, LocalObjectStore)
    assert open_store(s) is s  # store objects pass through


# ---------------------------------------------------------------------------
# SharedSstEnv parity matrix
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("fmt_name,codec", [
    ("block", tfmt.NO_COMPRESSION),
    ("zip", tfmt.ZLIB_COMPRESSION),
])
def test_shared_env_parity_matrix(tmp_path, monkeypatch, no_thread_leaks,
                                  fmt_name, codec):
    """TPULSM_SHARED_STORE off vs on: byte-identical iterator + point +
    snapshot reads over the same workload (the local-files path is the
    byte-parity oracle)."""
    def run(mode_dir, spec):
        if spec:
            monkeypatch.setenv("TPULSM_SHARED_STORE", spec)
        else:
            monkeypatch.delenv("TPULSM_SHARED_STORE", raising=False)
        opts = _opts(compression=codec)
        opts.table_options.format = fmt_name
        db = DB.open(str(tmp_path / mode_dir), opts)
        try:
            snap = _workload(db)
            return _fingerprint(db, snap)
        finally:
            db.close()

    oracle = run("oracle", None)
    shared = run("shared", str(tmp_path / "store"))
    assert shared == oracle
    # The store actually holds the shared run's tables.
    store = LocalObjectStore(str(tmp_path / "store"))
    assert store.list_addresses()


def test_shared_env_reads_are_reference_then_local(tmp_path, no_thread_leaks):
    """A referenced file serves metadata (exists/size) without bytes, and
    materializes exactly once on first read; the refs table is invisible
    to directory listings."""
    store = LocalObjectStore(str(tmp_path / "store"))
    payload = os.urandom(32 * 1024)
    addr = _addr_for(payload)
    store.put(addr, payload)
    stats = Statistics()
    env = SharedSstEnv(default_env(), store,
                       cache_dir=str(tmp_path / "cache"), stats=stats)
    try:
        d = str(tmp_path / "d")
        os.makedirs(d)
        env.adopt(f"{d}/000007.sst", addr)
        assert env.file_exists(f"{d}/000007.sst")
        assert env.get_file_size(f"{d}/000007.sst") == len(payload)
        assert not os.path.exists(f"{d}/000007.sst")  # still metadata-only
        assert env.get_children(d) == ["000007.sst"]  # refs table hidden
        assert env.read_file(f"{d}/000007.sst") == payload
        assert os.path.exists(f"{d}/000007.sst")      # materialized
        t = stats.tickers()
        assert t.get(st.STORE_MISSES, 0) == 1
        assert t.get(st.STORE_BYTES_FETCHED, 0) == len(payload)
        env.read_file(f"{d}/000007.sst")
        assert stats.tickers().get(st.STORE_MISSES, 0) == 1  # local now
        # Deleting the referenced name drops the ref.
        env.delete_file(f"{d}/000007.sst")
        assert not env.file_exists(f"{d}/000007.sst")
        assert env.refs_of(d) == {}
    finally:
        env.close()


def test_warm_refs_prefetches_into_cache(tmp_path, no_thread_leaks):
    store = LocalObjectStore(str(tmp_path / "store"))
    payloads = [os.urandom(8 * 1024) for _ in range(4)]
    env = SharedSstEnv(default_env(), store,
                       cache_dir=str(tmp_path / "cache"))
    try:
        d = str(tmp_path / "d")
        os.makedirs(d)
        for i, p in enumerate(payloads):
            addr = _addr_for(p)
            store.put(addr, p)
            env.adopt(f"{d}/{i:06d}.sst", addr)
        assert env.warm_refs(d) == 4
        env.tier.drain()
        for i, p in enumerate(payloads):
            assert os.path.exists(f"{d}/{i:06d}.sst")
            assert env.read_file(f"{d}/{i:06d}.sst") == p
    finally:
        env.close()


# ---------------------------------------------------------------------------
# Reference-mode checkpoint + restore
# ---------------------------------------------------------------------------


def test_reference_checkpoint_and_restore_equivalence(tmp_path, monkeypatch,
                                                      no_thread_leaks):
    monkeypatch.delenv("TPULSM_SHARED_STORE", raising=False)
    spec = str(tmp_path / "store")
    db = DB.open(str(tmp_path / "db"), _opts(shared_store=spec))
    snap = _workload(db)
    want = _fingerprint(db, snap)

    ck = str(tmp_path / "ckpt")
    Checkpoint.create(db, ck)
    # The checkpoint holds its SSTs by reference: no SST bytes on disk,
    # a refs table instead.
    assert not glob.glob(os.path.join(ck, "*.sst"))
    refs = db.env.refs_of(ck)
    assert refs
    for addr in refs.values():
        parse_address(addr)  # every ref is a well-formed address

    dest = str(tmp_path / "restored")
    Checkpoint(ck, db.env).restore_to(dest)
    db2 = DB.open(dest, Options(create_if_missing=False), env=db.env)
    try:
        got = _fingerprint(db2, None)
        assert got[0] == want[0] and got[1] == want[1]
    finally:
        db2.close()
        db.close()


def test_restore_hardlink_fast_path_parity(tmp_path, monkeypatch):
    """Same-filesystem restore hardlinks; a link failure falls back to
    the byte copy. Both produce identical trees."""
    db = DB.open(str(tmp_path / "db"), _opts())
    _workload(db, n=200)
    ck = str(tmp_path / "ckpt")
    Checkpoint.create(db, ck)
    db.close()

    linked = str(tmp_path / "linked")
    Checkpoint(ck).restore_to(linked)
    ssts = glob.glob(os.path.join(linked, "*.sst"))
    assert ssts and all(os.stat(p).st_nlink >= 2 for p in ssts), \
        "same-filesystem restore should hardlink SSTs"

    def no_link(*a, **kw):
        raise OSError("EXDEV: cross-device link")

    monkeypatch.setattr(os, "link", no_link)
    copied = str(tmp_path / "copied")
    Checkpoint(ck).restore_to(copied)
    for name in sorted(os.listdir(linked)):
        with open(os.path.join(linked, name), "rb") as a, \
                open(os.path.join(copied, name), "rb") as b:
            assert a.read() == b.read(), name

    for dest in (linked, copied):
        db2 = DB.open(dest, Options(create_if_missing=False))
        assert db2.get(b"k00001") == b"v1" * 17
        db2.close()


def test_mem_env_restore_copy_path(tmp_path):
    """MemEnv has no hardlinks: the restore loop's copy path carries it."""
    env = MemEnv()
    db = DB.open("/db", _opts(), env=env)
    _workload(db, n=120)
    Checkpoint.create(db, "/ckpt")
    db.close()
    Checkpoint("/ckpt", env).restore_to("/restored")
    db2 = DB.open("/restored", Options(create_if_missing=False), env=env)
    try:
        assert db2.get(b"k00001") == b"v1" * 17
    finally:
        db2.close()


# ---------------------------------------------------------------------------
# Chaos: migration bootstrap under store faults
# ---------------------------------------------------------------------------


def test_migration_bootstrap_under_store_faults(tmp_path, monkeypatch,
                                                no_thread_leaks):
    """Shard migration with the source on a faulty shared store (30%
    drop/delay/corrupt/truncate): the bootstrap completes, data matches
    the pre-migration oracle, and every corrupt fetch was caught by the
    address verify (retried, never installed)."""
    from toplingdb_tpu.sharding import ShardMigration, open_local_cluster

    monkeypatch.delenv("TPULSM_SHARED_STORE", raising=False)
    spec = str(tmp_path / "store")

    def options_factory(_name):
        return _opts(shared_store=spec, statistics=Statistics())

    r = open_local_cluster(
        str(tmp_path), [("a", None, b"m"), ("b", b"m", None)],
        options_factory=options_factory, statistics=Statistics())
    try:
        db_b = r._serving("b").primary
        for lo in range(0, 300, 100):
            for i in range(lo, lo + 100):
                r.put(b"m%05d" % i, b"v%d" % i)
                r.put(b"a%05d" % i, b"w%d" % i)
            db_b.flush()  # several SSTs -> several cold fetches at dest
        oracle = {b"m%05d" % i: b"v%d" % i for i in range(300)}
        assert isinstance(db_b.env, SharedSstEnv)
        # 30% random faults, plus a pinned schedule so a corrupt and a
        # drop are guaranteed regardless of how the dice land.
        inj = StoreFaultInjector(db_b.env.store, rate=0.30, seed=11,
                                 schedule={0: "corrupt", 1: "drop"})
        db_b.env.store = inj
        db_b.env.tier.store = inj

        out = ShardMigration(r, "b", str(tmp_path / "b-new")).run()
        assert out["shard"] == "b"
        for k, v in oracle.items():
            assert r.get(k) == v, k
        counts = inj.injected_counts()
        assert counts.get("corrupt", 0) >= 1
        assert counts.get("drop", 0) >= 1
        # Corrupt payloads never materialized: reads above byte-match the
        # oracle, which is the "never installed" proof; the injector saw
        # its corrupt plans consumed by the verify-and-retry loop.
    finally:
        r.close()


def test_store_fault_injector_is_seeded_and_verified(tmp_path):
    """Determinism + the corrupt-fetch contract at the tier level: a 100%
    corrupt scheduler never lets bad bytes through StoreCacheTier."""
    from toplingdb_tpu.storage.shared_env import StoreCacheTier

    store = LocalObjectStore(str(tmp_path / "store"))
    payload = os.urandom(16 * 1024)
    addr = _addr_for(payload)
    store.put(addr, payload)

    a = StoreFaultInjector(store, rate=0.5, seed=3)
    b = StoreFaultInjector(store, rate=0.5, seed=3)
    plans_a = [a._plan("fetch") for _ in range(50)]
    plans_b = [b._plan("fetch") for _ in range(50)]
    assert plans_a == plans_b  # same seed -> same schedule

    inj = StoreFaultInjector(store, schedule={0: "corrupt", 1: "corrupt"},
                             rate=0.0)
    tier = StoreCacheTier(inj, attempts=4, backoff_base=0.0)
    assert tier.fetch(addr) == payload  # two corrupt responses, then clean
    assert inj.injected_counts().get("corrupt", 0) == 2


# ---------------------------------------------------------------------------
# GC
# ---------------------------------------------------------------------------


def test_gc_never_sweeps_live(tmp_path, monkeypatch, no_thread_leaks):
    monkeypatch.delenv("TPULSM_SHARED_STORE", raising=False)
    spec = str(tmp_path / "store")
    dbdir = str(tmp_path / "db")
    db = DB.open(dbdir, _opts(shared_store=spec, statistics=Statistics()))
    _workload(db)
    store = db.env.store

    # Garbage: a published object no manifest references.
    junk = os.urandom(4096)
    junk_addr = _addr_for(junk)
    store.put(junk_addr, junk)
    # Pinned garbage survives; young garbage survives a graced sweep.
    pinned = os.urandom(2048)
    pinned_addr = _addr_for(pinned)
    store.put(pinned_addr, pinned)
    store.pin(pinned_addr, "publisher", ttl=120.0)

    live = collect_live_addresses([dbdir])
    assert live  # manifest-stamped files are reachable offline

    graced = mark_sweep(store, [dbdir], grace_sec=3600.0)
    assert graced["swept"] == []  # everything is younger than the grace

    rep = mark_sweep(store, [dbdir])
    # The junk goes; compacted-away tables the manifest no longer names
    # may go with it. What matters: live and pinned objects NEVER go.
    assert junk_addr in rep["swept"]
    assert not store.contains(junk_addr)
    assert pinned_addr not in rep["swept"] and store.contains(pinned_addr)
    assert pinned_addr in store.pinned()
    for addr in live:
        assert store.contains(addr), f"GC swept live object {addr}"
    # The DB still reads everything after the sweep.
    assert db.get(b"k00001") == b"v1" * 17
    db.close()


def test_gc_respects_refs_table_and_lease(tmp_path):
    """Mid-bootstrap dirs (refs, no MANIFEST yet) count as live; sweeps
    serialize on the store-gc lease."""
    from toplingdb_tpu.sharding.lease import LeaseCoordinator

    store = LocalObjectStore(str(tmp_path / "store"))
    payload = os.urandom(1024)
    addr = _addr_for(payload)
    store.put(addr, payload)
    boot = tmp_path / "bootstrapping"
    boot.mkdir()
    (boot / REFS_NAME).write_text(json.dumps({"000001.sst": addr}))

    rep = mark_sweep(store, [str(boot)])
    assert rep["swept"] == [] and store.contains(addr)

    lease = LeaseCoordinator(str(tmp_path / "lease.log"))
    grant = lease.acquire("store-gc", "other-process", 60.0)
    with pytest.raises(Busy):
        mark_sweep(store, [str(boot)], lease=lease, holder="me")
    lease.release("store-gc", "other-process", grant["token"])
    rep = mark_sweep(store, [], lease=lease, holder="me")
    assert rep["swept"] == [addr]  # no roots -> garbage, lease released
    assert mark_sweep(store, [], lease=lease, holder="me")["swept"] == []


# ---------------------------------------------------------------------------
# dcompact store mode: zero SST bytes on the job transport
# ---------------------------------------------------------------------------


def test_dcompact_zero_sst_bytes_shipped(tmp_path, monkeypatch,
                                         no_thread_leaks):
    from toplingdb_tpu.compaction.executor import (
        SubprocessCompactionExecutor,
        SubprocessCompactionExecutorFactory,
    )

    monkeypatch.delenv("TPULSM_SHARED_STORE", raising=False)
    spec = str(tmp_path / "store")
    job_root = str(tmp_path / "jobs")
    captured = []

    class Capturing(SubprocessCompactionExecutor):
        def _spawn_local(self, job_dir, device):
            super()._spawn_local(job_dir, device)
            # The worker has finished: any SST payload it shipped back
            # would be sitting in the job dir right now.
            captured.append(
                glob.glob(os.path.join(job_dir, "**", "*.sst"),
                          recursive=True))

    class Factory(SubprocessCompactionExecutorFactory):
        def new_executor(self, compaction):
            ex = Capturing(self.device, self.job_root, policy=self.policy)
            captured_execs.append(ex)
            return ex

    captured_execs = []
    stats_out = []
    orig_execute = Capturing.execute

    def record_execute(self, db, compaction, snapshots, new_file_number):
        outputs, stats = orig_execute(self, db, compaction, snapshots,
                                      new_file_number)
        stats_out.append(stats)
        return outputs, stats

    monkeypatch.setattr(Capturing, "execute", record_execute)

    opts = _opts(shared_store=spec, statistics=Statistics(),
                 compaction_executor_factory=Factory(
                     device="cpu", job_root=job_root))
    db = DB.open(str(tmp_path / "db"), opts)
    try:
        for i in range(400):
            db.put(b"k%05d" % i, b"v%d" % i * 23)
        db.flush()
        for i in range(400, 800):
            db.put(b"k%05d" % i, b"v%d" % i * 23)
        db.flush()
        db.compact_range()
        db.wait_for_compactions()
        assert stats_out, "no dcompact job ran"
        for s in stats_out:
            assert s.remote is True
            assert s.sst_bytes_shipped == 0, \
                "store mode must ship zero SST bytes"
        assert captured and all(lst == [] for lst in captured), \
            f"SST payloads crossed the job dir: {captured}"
        # Outputs were adopted as references and published to the store.
        refs = db.env.refs_of(str(tmp_path / "db"))
        assert refs
        store = LocalObjectStore(spec)
        for addr in refs.values():
            assert store.contains(addr)
        for i in range(800):
            assert db.get(b"k%05d" % i) == b"v%d" % i * 23, i
    finally:
        db.close()


def test_dcompact_output_meta_checksum_matches_address(tmp_path,
                                                       monkeypatch,
                                                       no_thread_leaks):
    """An adopted output's MANIFEST checksum comes from the worker's
    digest — re-derived address equals the stored address, no re-read."""
    from toplingdb_tpu.compaction.executor import (
        SubprocessCompactionExecutorFactory,
    )

    monkeypatch.delenv("TPULSM_SHARED_STORE", raising=False)
    spec = str(tmp_path / "store")
    opts = _opts(shared_store=spec,
                 compaction_executor_factory=(
                     SubprocessCompactionExecutorFactory(
                         device="cpu", job_root=str(tmp_path / "jobs"))))
    db = DB.open(str(tmp_path / "db"), opts)
    try:
        for i in range(300):
            db.put(b"x%05d" % i, b"v%d" % i * 9)
        db.flush()
        for i in range(300):
            db.put(b"x%05d" % i, b"w%d" % i * 9)
        db.flush()
        db.compact_range()
        db.wait_for_compactions()
        refs = db.env.refs_of(str(tmp_path / "db"))
        assert refs
        live = [(lvl, f) for cf in db.versions.column_families.values()
                for lvl, f in cf.current.all_files()]
        by_name = {f"{f.number:06d}.sst": f for _, f in live}
        for name, addr in refs.items():
            meta = by_name.get(name)
            if meta is None:
                continue  # a ref the next obsolete-file sweep will drop
            assert address_of_meta(meta) == addr
    finally:
        db.close()


# ---------------------------------------------------------------------------
# HTTP store
# ---------------------------------------------------------------------------


def test_http_store_round_trip(tmp_path, no_thread_leaks):
    srv = StoreServer(LocalObjectStore(str(tmp_path / "store")))
    port = srv.start()
    try:
        cl = StoreClient(f"http://127.0.0.1:{port}")
        payload = os.urandom(24 * 1024)
        addr = _addr_for(payload)
        assert cl.put(addr, payload) is True
        assert cl.put(addr, payload) is False  # dedup over the wire
        assert cl.fetch(addr) == payload
        assert cl.contains(addr)
        with pytest.raises(NotFound):
            cl.fetch(_addr_for(b"nothing"))
        with pytest.raises(Corruption):
            cl.put(_addr_for(b"aaaa"), b"bbbb")  # 422 -> Corruption
        cl.pin(addr, "tester", ttl=60.0)
        assert addr in cl.pinned()
        cl.unpin(addr)
        assert cl.status()["backend"] == "http"
        # SharedSstEnv over the HTTP client: a remote store materializes
        # a reference the same way a local one does.
        env = SharedSstEnv(default_env(), cl,
                           cache_dir=str(tmp_path / "cache"))
        try:
            d = str(tmp_path / "d")
            os.makedirs(d)
            env.adopt(f"{d}/000001.sst", addr)
            assert env.read_file(f"{d}/000001.sst") == payload
        finally:
            env.close()
        assert cl.delete(addr) is True
        assert not cl.contains(addr)
    finally:
        srv.stop()


def test_store_client_maps_dead_server_to_ioerror():
    from toplingdb_tpu.compaction.resilience import DcompactOptions
    from toplingdb_tpu.utils.status import IOError_

    cl = StoreClient("http://127.0.0.1:9", timeout=0.2,
                     options=DcompactOptions(max_attempts=2,
                                             backoff_base=0.0))
    with pytest.raises(IOError_):
        cl.contains("crc32c-00000000-1")


# ---------------------------------------------------------------------------
# Observability glue
# ---------------------------------------------------------------------------


def test_slo_spec_over_cold_fetch_histogram(tmp_path):
    """The README/ARCHITECTURE example: a latency SLO on cold-tier
    fetches evaluates against STORE_FETCH_MICROS."""
    from toplingdb_tpu.storage.shared_env import StoreCacheTier
    from toplingdb_tpu.utils.slo import SLOEngine, SLOSpec

    stats = Statistics()
    store = LocalObjectStore(str(tmp_path / "store"))
    payload = os.urandom(4096)
    addr = _addr_for(payload)
    store.put(addr, payload)
    tier = StoreCacheTier(store, stats=stats)
    for _ in range(3):
        tier.fetch(addr)  # no cache dir: every fetch is cold
    spec = SLOSpec(name="store-cold-fetch", kind="latency",
                   histogram=st.STORE_FETCH_MICROS,
                   threshold_usec=5_000_000.0, objective=0.99)
    eng = SLOEngine(stats, [spec])
    doc = eng.evaluate()
    assert doc["health"] == "green"
    assert not doc["specs"]["store-cold-fetch"]["firing"]
    t = stats.tickers()
    assert t.get(st.STORE_MISSES, 0) == 3


def test_store_http_view(tmp_path, monkeypatch, no_thread_leaks):
    """GET /store/<name> on the SidePluginRepo serves the store view."""
    import urllib.request

    from toplingdb_tpu.utils.config import SidePluginRepo

    monkeypatch.delenv("TPULSM_SHARED_STORE", raising=False)
    db = DB.open(str(tmp_path / "db"),
                 _opts(shared_store=str(tmp_path / "store"),
                       statistics=Statistics()))
    repo = SidePluginRepo()
    try:
        _workload(db, n=100)
        repo.attach_db("d1", db)
        port = repo.start_http(0)
        doc = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/store/d1", timeout=5).read())
        assert doc["enabled"] is True
        assert "tickers" in doc and doc["tickers"][st.STORE_PUBLISHES] >= 1
        plain = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats/d1", timeout=5).read())
        assert plain is not None
    finally:
        repo.stop_http()
        db.close()
