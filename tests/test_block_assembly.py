"""On-device SST block assembly: whole-file byte parity with the CPU path
(reference block build loop, table/block_based/block_builder.cc:66-180,
re-expressed as one jit program — VERDICT r2 task 1)."""

import random

import pytest

from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
)

ICMP = InternalKeyComparator()


def _build_inputs(env, dbdir, rng, topts, n_files=3, n_per=350,
                  with_deletes=True, with_tombstones=False):
    import toplingdb_tpu.db.filename as fn
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.table.builder import TableBuilder

    metas = []
    seq = 1
    for fi in range(n_files):
        fnum = 31 + fi
        entries = []
        for _ in range(n_per):
            k = b"key%06d" % rng.randrange(500)
            t = ValueType.VALUE
            if with_deletes and rng.random() < 0.15:
                t = ValueType.DELETION
            v = b"" if t != ValueType.VALUE else b"v%0*d" % (
                rng.randrange(4, 40), seq)
            entries.append((make_internal_key(k, seq, t), v))
            seq += 1
        entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, topts)
        last = None
        for k, v in entries:
            if k == last:
                continue
            b.add(k, v)
            last = k
        if with_tombstones:
            lo = rng.randrange(400)
            b.add_tombstone(
                make_internal_key(b"key%06d" % lo, seq,
                                  ValueType.RANGE_DELETION),
                b"key%06d" % (lo + 50))
            seq += 1
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum,
            file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
        ))
    return metas, seq


@pytest.mark.parametrize("seed,block_size,restart,tombs,nsnaps,bloom", [
    (1, 512, 16, False, 0, False),
    (2, 512, 4, False, 2, False),
    (3, 4096, 16, False, 0, True),
    (4, 1024, 16, True, 3, False),
    (5, 256, 8, True, 0, True),
])
def test_block_assembly_byte_parity(tmp_path, monkeypatch, seed, block_size,
                                    restart, tombs, nsnaps, bloom):
    import os

    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops import device_compaction as dc
    from toplingdb_tpu.ops.device_compaction import run_device_compaction
    from toplingdb_tpu.table.builder import TableOptions
    import toplingdb_tpu.db.filename as fn

    monkeypatch.setenv("TPULSM_DEVICE_BLOCKS", "1")
    monkeypatch.delenv("TPULSM_HOST_SORT", raising=False)
    env = default_env()
    dbdir = str(tmp_path)
    rng = random.Random(seed)
    from toplingdb_tpu.table.filter import BloomFilterPolicy

    topts = TableOptions(
        block_size=block_size, restart_interval=restart,
        filter_policy=BloomFilterPolicy() if bloom else None)
    metas, seq_top = _build_inputs(env, dbdir, rng, topts,
                                   with_tombstones=tombs)
    tc = TableCache(env, dbdir, ICMP, topts)
    snaps = sorted(rng.sample(range(1, seq_top), nsnaps))

    def mk(base):
        s = [base]

        def alloc():
            s[0] += 1
            return s[0]

        return alloc

    c1 = Compaction(level=0, output_level=2, inputs=list(metas),
                    bottommost=True, max_output_file_size=1 << 62)
    out_cpu, _ = run_compaction_to_tables(
        env, dbdir, ICMP, c1, tc, topts, snaps, new_file_number=mk(100),
        creation_time=9,
    )

    # Assembly (not the columnar writer, not the per-entry path) must run.
    import toplingdb_tpu.ops.block_assembly as ba

    called = []
    orig = ba.run_block_assembly

    def spy(*a, **k):
        called.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ba, "run_block_assembly", spy)
    c2 = Compaction(level=0, output_level=2, inputs=list(metas),
                    bottommost=True, max_output_file_size=1 << 62)
    out_dev, _ = run_device_compaction(
        env, dbdir, ICMP, c2, tc, topts, snaps, new_file_number=mk(200),
        creation_time=9, device_name="cpu-jax",
    )
    assert called, "block assembly path was not taken"
    assert len(out_cpu) == len(out_dev) == 1
    bc = open(fn.table_file_name(dbdir, out_cpu[0].number), "rb").read()
    bd = open(fn.table_file_name(dbdir, out_dev[0].number), "rb").read()
    assert bc == bd, (
        f"device-assembled SST differs from CPU build "
        f"({len(bc)} vs {len(bd)} bytes)"
    )
    assert out_cpu[0].smallest == out_dev[0].smallest
    assert out_cpu[0].largest == out_dev[0].largest
    assert out_cpu[0].num_entries == out_dev[0].num_entries
