"""The exception-hygiene lint (tools/check_errors.py).

Static: every broad `except Exception/BaseException` in the tree must
carry an explicit policy — re-raise, latch the background error, tick a
ticker, or route through utils/errors.py with a literal reason — and the
lint must catch seeded bare swallows with a file:line witness. Runtime:
the errors plane itself (swallow/guard bookkeeping + the
BG_ERROR_SWALLOWED ticker).
"""

import os
import textwrap

from toplingdb_tpu.tools import check_errors as ce
from toplingdb_tpu.utils import errors as errs

# ---------------------------------------------------------------------------
# The real tree
# ---------------------------------------------------------------------------


def test_tree_is_clean_and_nonempty():
    assert ce.run() == []
    # The sweep actually happened: the tree routes a meaningful number of
    # swallow sites through the policy helper (not a silently-empty walk).
    pkg = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(ce.__file__))))
    n = 0
    for dirpath, _, names in os.walk(pkg):
        for name in names:
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name),
                          encoding="utf-8") as f:
                    n += f.read().count("swallow(reason=")
    assert n >= 30


def test_cli_exits_zero_on_clean_tree(capsys):
    assert ce.main([]) == 0
    out = capsys.readouterr().out
    assert "check_errors:" in out
    assert "0 violation(s)" in out


# ---------------------------------------------------------------------------
# Seeded violations on synthetic trees
# ---------------------------------------------------------------------------


def _lint(tmp_path, src):
    pkg = tmp_path / "toplingdb_tpu"
    pkg.mkdir(exist_ok=True)
    (pkg / "m.py").write_text(textwrap.dedent(src))
    return ce.run(str(tmp_path))


def test_detects_bare_swallow(tmp_path):
    out = _lint(tmp_path, """\
        def f():
            try:
                g()
            except Exception:
                pass
        """)
    assert len(out) == 1, out
    assert "m.py:4:" in out[0]  # file:line witness on the handler
    assert "broad except without an error policy" in out[0]


def test_detects_bare_base_exception(tmp_path):
    out = _lint(tmp_path, """\
        def f():
            try:
                g()
            except BaseException:
                return None
        """)
    assert len(out) == 1, out
    assert "m.py:4:" in out[0]


def test_detects_bound_but_unread_exception(tmp_path):
    out = _lint(tmp_path, """\
        def f():
            try:
                g()
            except Exception as e:
                x = 1
        """)
    assert len(out) == 1, out
    assert "m.py:4:" in out[0]


def test_detects_empty_swallow_reason(tmp_path):
    out = _lint(tmp_path, """\
        from toplingdb_tpu.utils import errors as _errors

        def f():
            try:
                g()
            except Exception as e:
                _errors.swallow(reason="", exc=e)
        """)
    hits = [v for v in out if "non-empty string-literal reason=" in v]
    assert len(hits) == 1, out


def test_detects_guard_without_listener(tmp_path):
    out = _lint(tmp_path, """\
        from toplingdb_tpu.utils import errors as _errors

        def f(cb):
            with _errors.guard(stats=None):
                cb()
        """)
    hits = [v for v in out if "listener=" in v]
    assert len(hits) == 1, out


def test_annotated_policies_pass(tmp_path):
    out = _lint(tmp_path, """\
        from toplingdb_tpu.utils import errors as _errors

        def a():
            try:
                g()
            except Exception:
                raise

        def b(self):
            try:
                g()
            except Exception as e:
                self._set_background_error(e)

        def c(stats, T):
            try:
                g()
            except Exception:
                stats.record_tick(T)

        def d():
            try:
                g()
            except Exception as e:
                _errors.swallow(reason="best-effort probe", exc=e)

        def e(cb):
            with _errors.guard(listener=cb):
                cb()
        """)
    assert out == [], out


# ---------------------------------------------------------------------------
# Runtime: the errors plane itself
# ---------------------------------------------------------------------------


def test_swallow_counts_and_ticks():
    before = errs.swallowed_total()
    try:
        raise ValueError("boom")
    except Exception as e:
        errs.swallow(reason="test-site", exc=e)
    assert errs.swallowed_total() == before + 1
    assert any(r[0] == "test-site" for r in errs.recent())


def test_guard_suppresses_and_records():
    before = errs.swallowed_total()
    with errs.guard(listener=test_guard_suppresses_and_records):
        raise RuntimeError("listener blew up")
    assert errs.swallowed_total() == before + 1


def test_guard_passes_system_exit():
    import pytest

    with pytest.raises(SystemExit):
        with errs.guard(listener=int):
            raise SystemExit(3)


def test_bg_error_swallowed_ticker():
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    with errs.guard(listener=int, stats=stats):
        raise RuntimeError("x")
    assert stats.get_ticker_count(st.BG_ERROR_SWALLOWED) == 1
