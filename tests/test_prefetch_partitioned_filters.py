"""FilePrefetchBuffer readahead + partitioned filters (VERDICT r2 task 7;
reference file/file_prefetch_buffer.h:63 and
table/block_based/partitioned_filter_block.h:27)."""

import random

from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
)
from toplingdb_tpu.table.builder import (
    METAINDEX_FILTER,
    METAINDEX_FILTER_PARTS,
    TableBuilder,
    TableOptions,
)
from toplingdb_tpu.table.reader import TableReader

ICMP = InternalKeyComparator()


def _build(env, path, n, topts):
    w = env.new_writable_file(path)
    b = TableBuilder(w, ICMP, topts)
    for i in range(n):
        b.add(make_internal_key(b"key%07d" % i, i + 1, ValueType.VALUE),
              b"value-%07d" % i)
    b.finish()
    w.close()


def test_prefetch_buffer_reduces_reads(tmp_path):
    from toplingdb_tpu.env import default_env

    env = default_env()
    path = str(tmp_path / "t.sst")
    topts = TableOptions(block_size=4096, filter_policy=None)
    _build(env, path, 20000, topts)
    r = TableReader(env.new_random_access_file(path), ICMP, topts)
    it = r.new_iterator()
    it.seek_to_first()
    n = sum(1 for _ in it.entries())
    assert n == 20000
    pf = it._pf
    nblocks = r.properties.num_data_blocks
    assert nblocks > 50
    # Sequential scan: most block loads served from the readahead window.
    assert pf.misses < nblocks / 4, (pf.misses, nblocks)
    assert pf.hits > nblocks / 2
    # Random seeks on a FRESH iterator never arm readahead windows larger
    # than the block itself (no pollution).
    it2 = r.new_iterator()
    rng = random.Random(3)
    for _ in range(50):
        it2.seek(make_internal_key(b"key%07d" % rng.randrange(20000),
                                   1 << 40, ValueType.MAX))
        assert it2.valid()
    assert it2._pf.hits <= 2  # random pattern: essentially all misses


def test_partitioned_filter_round_trip(tmp_path):
    from toplingdb_tpu.env import default_env

    env = default_env()
    path = str(tmp_path / "p.sst")
    topts = TableOptions(block_size=512, partition_filters=True,
                         metadata_block_size=1024)
    _build(env, path, 5000, topts)
    r = TableReader(env.new_random_access_file(path), ICMP, topts)
    assert r._filter_top is not None
    assert METAINDEX_FILTER_PARTS in r._meta_handles
    assert METAINDEX_FILTER not in r._meta_handles
    # several partitions actually exist
    from toplingdb_tpu.table.block import BlockIter
    from toplingdb_tpu.db import dbformat

    it = BlockIter(r._filter_top, dbformat.BYTEWISE.compare)
    it.seek_to_first()
    nparts = sum(1 for _ in it.entries())
    assert nparts > 3, nparts
    # all present keys pass, absent keys mostly rejected
    for i in range(0, 5000, 61):
        assert r.key_may_match(b"key%07d" % i)
    false_pos = sum(
        1 for i in range(5000) if r.key_may_match(b"zzz%07d" % i))
    assert false_pos < 5000 * 0.05
    # beyond the last partition: definitively absent
    assert not r.key_may_match(b"~~~~")


def test_partitioned_filter_in_db(tmp_path):
    import dataclasses

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.utils import statistics as st

    stats = st.Statistics()
    opts = Options(create_if_missing=True, write_buffer_size=1 << 20,
                   statistics=stats)
    opts.table_options = dataclasses.replace(
        opts.table_options, partition_filters=True, metadata_block_size=512,
        block_size=512)
    d = str(tmp_path / "db")
    with DB.open(d, opts) as db:
        for i in range(4000):
            db.put(b"key%06d" % i, b"v%06d" % i)
        db.flush()
        db.compact_range()
        assert db.get(b"key001234") == b"v001234"
        assert db.get(b"nope") is None
    with DB.open(d, opts) as db2:
        assert db2.get(b"key003999") == b"v003999"
    assert stats.get_ticker_count(st.BLOOM_USEFUL) >= 0
