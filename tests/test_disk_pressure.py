"""Storage-pressure resilience plane (env free-space sensing, the promoted
SstFileManager, flush/compaction preflight, no_space SOFT latch with
autonomous recovery, red-pressure write shedding, reclaim ladder, and the
disk-full chaos soak).

Acceptance bars covered here:
  - Env.get_free_space across Posix/Mem/wrapper envs
  - pressure hysteresis + callbacks; paced trash deletion with the
    trash-ratio bypass and accelerate_deletes
  - live-DB deletion/addition paths route through the manager
  - flush preflight refuses over-budget flushes, latches SOFT
    reason="no_space", and AUTO-resumes once space returns — zero
    operator resume() calls
  - compaction preflight pauses amber-first without hot-looping
  - manual AND auto resume() notify on_error_recovery_completed and
    tick BG_ERROR_RESUMES
  - SOFT→HARD escalation spawns exactly one successor recovery thread
    and never double-resumes (runtime lock-debug on)
  - admission + fleet front door shed writes at red (Busy / 503)
  - disk-full soak: genuine injected ENOSPC mid-append (torn short
    writes), merged-oracle parity for plain DB + replicated follower +
    fleet shard server
  - SLOSpec kind="disk_pressure" + /metrics disk gauges
"""

import threading
import time

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.env import MemEnv, PosixEnv
from toplingdb_tpu.env.fault_injection import FaultInjectionEnv
from toplingdb_tpu.options import Options, WriteOptions
from toplingdb_tpu.utils import concurrency as ccy
from toplingdb_tpu.utils import statistics as st
from toplingdb_tpu.utils.listener import EventListener
from toplingdb_tpu.utils.rate_limiter import SstFileManager
from toplingdb_tpu.utils.statistics import Statistics
from toplingdb_tpu.utils.status import (
    Busy,
    IOError_,
    NoSpace,
    Severity,
    is_no_space,
)


def _wait_until(cond, timeout=15.0, step=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return cond()


# ---------------------------------------------------------------------------
# Sensing: Env.get_free_space
# ---------------------------------------------------------------------------


def test_posix_free_space_real_and_unborn_paths(tmp_path):
    env = PosixEnv()
    free = env.get_free_space(str(tmp_path))
    assert 0 < free < (1 << 61)
    # A path that does not exist yet walks up to its closest live parent.
    assert env.get_free_space(str(tmp_path / "not" / "yet" / "made")) > 0


def test_mem_env_capacity_and_wrappers():
    env = MemEnv()
    assert env.get_free_space("/x") == 1 << 62  # unlimited by default
    env.set_capacity(1000)
    env.write_file("/x/a", b"z" * 300)
    assert env.get_free_space("/x") == 700
    fe = FaultInjectionEnv(env)
    assert fe.get_free_space("/x") == 700  # passthrough
    fe.set_disk_budget("*", 100)
    assert fe.get_free_space("/x") == 100  # injected budget wins when lower


def test_fault_env_budget_torn_write_refund_and_enospc():
    env = MemEnv()
    fe = FaultInjectionEnv(env)
    fe.set_disk_budget("*", 10)
    f = fe.new_writable_file("/d/a")
    with pytest.raises(OSError) as ei:
        f.append(b"x" * 25)
    assert is_no_space(ei.value)
    assert fe.enospc_injected == 1
    # Torn short write: the affordable prefix landed before the failure.
    assert env.get_file_size("/d/a") == 10
    with pytest.raises(OSError):
        f.sync()  # fsync on a full disk fails too
    f.close()
    fe.delete_file("/d/a")  # refund
    assert fe.disk_budget_remaining("*") == 10
    g = fe.new_writable_file("/d/b")
    g.append(b"y" * 8)
    g.sync()  # budget not exhausted: sync succeeds again
    g.close()


# ---------------------------------------------------------------------------
# The manager: accounting, hysteresis, trash pacing
# ---------------------------------------------------------------------------


def test_pressure_levels_and_hysteresis():
    stats = Statistics()
    m = SstFileManager(max_allowed_space_usage=1000, statistics=stats,
                       amber_free_ratio=0.10, red_free_ratio=0.05,
                       pressure_hysteresis=0.02)
    seen = []
    m.add_pressure_callback(lambda lvl, prev, info: seen.append((prev, lvl)))
    try:
        m.on_add_file("/x/a.sst", 850)
        assert m.poll() == "ok"
        m.on_add_file("/x/b.sst", 80)  # used 930 → frac 0.07 → amber
        assert m.poll() == "amber"
        m.on_add_file("/x/c.sst", 25)  # used 955 → frac 0.045 → red
        assert m.poll() == "red"
        # De-escalation needs to CLEAR the threshold plus hysteresis:
        # frac 0.06 is above red (0.05) but inside red+hysteresis (0.07).
        m.on_delete_file("/x/c.sst")
        m.on_file_size("/x/b.sst", 90)  # used 940 → frac 0.06
        assert m.poll() == "red"
        m.on_file_size("/x/b.sst", 20)  # used 870 → frac 0.13 → ok
        assert m.poll() == "ok"
        assert seen == [("ok", "amber"), ("amber", "red"), ("red", "ok")]
        assert stats.get_ticker_count(st.DISK_PRESSURE_TRANSITIONS) == 3
        assert stats.get_ticker_count(st.DISK_PRESSURE_POLLS) == 5
        assert stats.get_ticker_count(st.DISK_PRESSURE_POLLS_BAD) == 3
    finally:
        m.close()


def test_preflight_math_reserves_flush_headroom():
    m = SstFileManager(max_allowed_space_usage=1000,
                       flush_headroom_bytes=200,
                       compaction_buffer_size=100)
    try:
        m.on_add_file("/x/a.sst", 500)
        # Flushes may consume the headroom: full budget applies.
        assert m.check_flush(400)
        assert not m.check_flush(600)
        # Compactions must leave headroom + buffer (300) untouched.
        assert m.check_compaction(200)
        assert not m.check_compaction(300)
    finally:
        m.close()


def test_trash_ratio_bypass_and_accelerate(tmp_path):
    env = MemEnv()
    for name in ("a", "b"):
        env.write_file(f"/db/{name}.sst", b"z" * 100)
    stats = Statistics()
    # 1 byte/sec: a paced delete of 100 bytes would sleep ~10s (capped).
    m = SstFileManager(bytes_per_sec_delete=1, max_trash_db_ratio=0.25,
                       env=env, path="/db", statistics=stats)
    try:
        m.on_add_file("/db/a.sst", 100)
        m.on_add_file("/db/b.sst", 100)
        t0 = time.monotonic()
        m.schedule_delete("/db/a.sst")  # trash 100 > 0.25*100 live → bypass
        assert _wait_until(lambda: not env.file_exists("/db/a.sst.trash"),
                           timeout=5.0)
        assert time.monotonic() - t0 < 5.0  # ratio bypass skipped pacing
        assert not env.file_exists("/db/a.sst")
        # A paced delete wakes immediately under accelerate_deletes().
        m.on_add_file("/db/big.sst", 100_000)  # ratio no longer trips
        m.schedule_delete("/db/b.sst")
        m.accelerate_deletes()
        assert _wait_until(lambda: not env.file_exists("/db/b.sst.trash"),
                           timeout=5.0)
        assert stats.get_ticker_count(st.DISK_TRASH_BYTES_FREED) == 200
        assert m.trash_size() == 0
    finally:
        m.close()


# ---------------------------------------------------------------------------
# Live-DB wiring: additions and deletions route through the manager
# ---------------------------------------------------------------------------


def test_live_db_deletions_seen_by_manager(tmp_path, no_thread_leaks):
    stats = Statistics()
    db = DB.open(str(tmp_path / "d"),
                 Options(write_buffer_size=8 * 1024,
                         level0_file_num_compaction_trigger=2,
                         max_allowed_space_usage=1 << 30,
                         statistics=stats))
    try:
        assert db._sfm is not None
        for i in range(400):
            db.put(b"k%04d" % (i % 120), b"v" * 64)
            if i % 100 == 99:
                db.flush()
        db.wait_for_compactions()
        db._sfm.wait_for_deletes()
        tracked = dict(db._sfm._tracked)
        assert tracked, "manager lost track of the live tree"
        # Every tracked file exists; every obsolete SST went through
        # schedule_delete (no stale entries for vanished files).
        for path in tracked:
            assert db.env.file_exists(path), f"stale tracked entry {path}"
        live = {f"{db.dbname}/{c}" for c in db.env.get_children(db.dbname)}
        sst_on_disk = {p for p in live if p.endswith(".sst")}
        sst_tracked = {p for p in tracked if p.endswith(".sst")}
        assert sst_tracked == sst_on_disk
        assert stats.get_ticker_count(st.DISK_TRASH_BYTES_FREED) > 0
    finally:
        db.close()


# ---------------------------------------------------------------------------
# Degradation policy: preflight + SOFT latch + autonomous recovery
# ---------------------------------------------------------------------------


class _RecoveryWatch(EventListener):
    def __init__(self):
        self.recovered = []
        self.pressure = []
        self.bg_errors = []

    def on_error_recovery_completed(self, db, info):
        self.recovered.append(info)

    def on_disk_pressure(self, db, info):
        self.pressure.append((info.prev_level, info.level))

    def on_background_error(self, db, e):
        self.bg_errors.append(e)


def test_flush_preflight_latches_soft_and_auto_resumes(tmp_path,
                                                       no_thread_leaks):
    stats = Statistics()
    watch = _RecoveryWatch()
    db = DB.open(str(tmp_path / "d"),
                 Options(write_buffer_size=8 * 1024,
                         disable_auto_compactions=True,
                         max_allowed_space_usage=24 * 1024,
                         flush_headroom_bytes=1,  # starve the headroom
                         statistics=stats, listeners=[watch]))
    try:
        acked = {}
        latched = False
        for i in range(4000):
            k, v = b"k%05d" % i, b"v" * 120
            try:
                db.put(k, v)
                acked[k] = v
            except Exception as e:
                assert is_no_space(e), repr(e)
                latched = True
                break
            if db._bg_error is not None:
                latched = True
                break
        assert latched, "budget never tripped"
        assert _wait_until(lambda: db._bg_error is not None, timeout=5.0)
        assert db._bg_error_reason == "no_space"
        assert db._bg_error_severity == Severity.SOFT_ERROR
        assert stats.get_ticker_count(st.NO_SPACE_ERRORS) >= 1
        assert stats.get_ticker_count(st.NO_SPACE_PREFLIGHT_BLOCKS) >= 1
        # Operator-free recovery: GROW the budget (the "space came back"
        # event) and the auto-recover loop must clear the latch itself.
        db._sfm.set_max_allowed_space_usage(1 << 30)
        assert _wait_until(lambda: db._bg_error is None, timeout=20.0), \
            "auto-recovery never cleared the no_space latch"
        assert stats.get_ticker_count(st.BG_ERROR_RESUMES) >= 1
        assert any(i.auto and i.reason == "no_space"
                   for i in watch.recovered)
        # Zero lost acked writes.
        bad = [k for k, v in acked.items() if db.get(k) != v]
        assert not bad, bad[:3]
        # Red-pressure flush headroom: the DB can still flush now.
        db.flush()
    finally:
        db.close()


def test_compaction_preflight_pauses_amber_first(tmp_path, no_thread_leaks):
    stats = Statistics()
    db = DB.open(str(tmp_path / "d"),
                 Options(write_buffer_size=4 * 1024,
                         level0_file_num_compaction_trigger=2,
                         max_allowed_space_usage=1 << 30,
                         statistics=stats))
    try:
        for i in range(200):
            db.put(b"k%04d" % i, b"v" * 64)
        db.flush()
        for i in range(200):
            db.put(b"k%04d" % i, b"w" * 64)
        db.flush()
        db.wait_for_compactions()
        # Force amber and pile up L0: the scheduler must refuse to START
        # (ticker moves) and must not hot-loop (num_completed frozen).
        with db._sfm._mu:
            db._sfm._level = "amber"
        done_before = db._compaction_scheduler.num_completed
        for i in range(200):
            db.put(b"x%04d" % i, b"y" * 64)
        db.flush()
        for i in range(200):
            db.put(b"x%04d" % i, b"z" * 64)
        db.flush()
        db._maybe_schedule_compaction()
        db._compaction_scheduler.wait_idle()
        assert stats.get_ticker_count(st.NO_SPACE_PREFLIGHT_BLOCKS) >= 1
        assert db._compaction_scheduler.num_completed == done_before
        # Pressure clears → compactions resume via the pressure callback.
        with db._sfm._mu:
            db._sfm._level = "ok"
        db._maybe_schedule_compaction()
        db.wait_for_compactions()
        assert db._compaction_scheduler.num_completed > done_before
        assert db.get(b"x0000") == b"z" * 64
    finally:
        db.close()


def test_manual_resume_notifies_and_ticks(tmp_path, no_thread_leaks):
    stats = Statistics()
    watch = _RecoveryWatch()
    db = DB.open(str(tmp_path / "d"),
                 Options(statistics=stats, listeners=[watch]))
    try:
        err = IOError_("synthetic hard flush failure")  # not retryable
        db._set_background_error(err, reason="wal")
        assert db._bg_error is err
        assert db._bg_error_severity == Severity.HARD_ERROR
        db.resume()
        assert db._bg_error is None
        assert stats.get_ticker_count(st.BG_ERROR_RESUMES) == 1
        assert [i.auto for i in watch.recovered] == [False]
        assert watch.recovered[0].reason == "wal"
        db.resume()  # no latch: must NOT notify or tick again
        assert stats.get_ticker_count(st.BG_ERROR_RESUMES) == 1
        assert len(watch.recovered) == 1
    finally:
        db.close()


@pytest.fixture
def debug_locks():
    ccy.reset_lock_graph()
    ccy.set_debug(True)
    yield
    ccy.set_debug(False)
    ccy.reset_lock_graph()


def test_soft_to_hard_escalation_single_successor(tmp_path, debug_locks,
                                                  no_thread_leaks):
    """Race satellite: a SOFT no_space latch being chased by one recovery
    thread escalates to a HARD retryable error. Exactly one successor
    thread may resume; the first loop must bow out at its identity check
    — never a double resume (BG_ERROR_RESUMES == 1)."""
    stats = Statistics()
    watch = _RecoveryWatch()
    db = DB.open(str(tmp_path / "d"),
                 Options(statistics=stats, listeners=[watch],
                         max_allowed_space_usage=1000))
    try:
        # Pin the manager at red so the no_space chaser parks on its
        # headroom gate (it must never consume attempts while parked).
        db._sfm.on_add_file("/x/fill.sst", 990)
        db._sfm.poll()
        assert db.disk_pressure() == "red"
        soft = NoSpace("flush would breach budget")
        db._set_background_error(soft, reason="no_space")
        assert db._bg_error is soft
        assert _wait_until(lambda: any(
            t.name.startswith("db-auto-recover")
            for t in threading.enumerate()), timeout=5.0)
        # Escalate: HARD but retryable → replaces the latch, spawns ONE
        # successor; the soft chaser exits at `is not target`.
        hard = IOError_("wal torn tail", retryable=True)
        db._set_background_error(hard, reason="wal")
        assert db._bg_error is hard
        assert db._bg_error_severity == Severity.HARD_ERROR
        assert _wait_until(lambda: db._bg_error is None, timeout=20.0), \
            "successor thread never resumed the HARD retryable latch"
        # Free the manager and give the ex-chaser time to exit cleanly.
        db._sfm.on_delete_file("/x/fill.sst")
        db._sfm.poll()
        assert _wait_until(lambda: not any(
            t.name.startswith("db-auto-recover")
            for t in threading.enumerate()), timeout=10.0)
        assert stats.get_ticker_count(st.BG_ERROR_RESUMES) == 1
        assert len(watch.recovered) == 1
        assert watch.recovered[0].reason == "wal"
    finally:
        db.close()


# ---------------------------------------------------------------------------
# Shedding: admission + fleet front door
# ---------------------------------------------------------------------------


def test_admission_sheds_all_writes_at_red():
    from toplingdb_tpu.sharding.admission import (
        AdmissionController,
        TenantQuota,
    )

    stats = Statistics()
    ac = AdmissionController(default_quota=TenantQuota(), statistics=stats)
    assert ac.admit_write("t1", 100, disk_pressure="ok") < 0.5  # admitted
    with pytest.raises(Busy):
        ac.admit_write("t1", 100, disk_pressure="red")
    # Even quota-less tenants shed at red: this is capacity protection.
    ac2 = AdmissionController(statistics=stats)
    with pytest.raises(Busy):
        ac2.admit_write(None, 1, disk_pressure="red")
    assert stats.get_ticker_count(st.NO_SPACE_WRITES_SHED) == 2
    assert stats.get_ticker_count(st.SHARD_WRITES_SHED) == 2


def _mini_batch(key=b"k", val=b"v"):
    import base64

    from toplingdb_tpu.db.write_batch import WriteBatch

    b = WriteBatch()
    b.put(key, val)
    return base64.b64encode(b.data()).decode()


def test_fleet_shard_sheds_503_at_red_then_recovers(tmp_path,
                                                    no_thread_leaks):
    from toplingdb_tpu.sharding.fleet import ShardServer

    stats = Statistics()
    srv = ShardServer("s0", str(tmp_path / "s0"), statistics=stats,
                      options=Options(max_allowed_space_usage=1 << 20))
    try:
        srv.start()
        code, out = srv.handle_write({"epoch": 1, "batch_b64": _mini_batch()})
        assert code == 200
        sfm = srv.db._sfm
        sfm.on_add_file("/x/fill.sst", (1 << 20) - 1024)
        sfm.poll()
        assert srv.db.disk_pressure() == "red"
        code, out = srv.handle_write(
            {"epoch": 1, "batch_b64": _mini_batch(b"shed")})
        assert (code, out["error"]) == (503, "disk_pressure")
        assert stats.get_ticker_count(st.NO_SPACE_WRITES_SHED) == 1
        assert srv.router.get(b"shed") is None  # never reached the WAL
        # Space returns → the front door reopens; nothing was lost.
        sfm.on_delete_file("/x/fill.sst")
        sfm.poll()
        code, _ = srv.handle_write(
            {"epoch": 1, "batch_b64": _mini_batch(b"back")})
        assert code == 200
        assert srv.router.get(b"k") == b"v"
        assert srv.router.get(b"back") == b"v"
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# The disk-full chaos soak
# ---------------------------------------------------------------------------


def test_disk_full_soak_enospc_recover_parity(tmp_path, no_thread_leaks):
    """Fill a byte-budgeted injected filesystem until genuine ENOSPC
    latches the DB, free space, and require: autonomous un-latch (zero
    resume() calls), zero lost acked writes, zero resurrected failed
    writes (merged-oracle parity), a clean follower catch-up, and a clean
    reopen."""
    from toplingdb_tpu.replication import FollowerDB, LocalTransport
    from toplingdb_tpu.replication.log_shipper import LogShipper

    stats = Statistics()
    watch = _RecoveryWatch()
    fe = FaultInjectionEnv(PosixEnv())
    budget = 192 * 1024
    fe.set_disk_budget("*", budget)
    src = str(tmp_path / "d")
    db = DB.open(src, Options(write_buffer_size=16 * 1024,
                              level0_file_num_compaction_trigger=3,
                              free_space_poll_period_sec=0.02,
                              flush_headroom_bytes=32 * 1024,
                              statistics=stats, listeners=[watch]),
                 env=fe)
    ship = LogShipper(db, statistics=stats)
    oracle: dict[bytes, bytes] = {}
    wo = WriteOptions(sync=True)
    try:
        hit_wall = False
        # The live set (800 keys x 256B ~= 200KB) exceeds the budget, so
        # trash-refund reclamation alone can never dodge the wall.
        for i in range(6000):
            k = b"k%05d" % (i % 800)
            v = (b"v%06d" % i).ljust(256, b".")
            try:
                db.put(k, v, wo)
                oracle[k] = v  # acked → must survive
            except Exception as e:
                assert is_no_space(e) or isinstance(e, Busy), repr(e)
                hit_wall = True
            if hit_wall and db._bg_error is not None:
                break
        assert hit_wall, "budget never filled"
        assert _wait_until(lambda: db._bg_error is not None, timeout=10.0)
        assert db._bg_error_reason == "no_space"
        assert db._bg_error_severity == Severity.SOFT_ERROR
        # While latched SOFT, reads still serve every acked write.
        bad = [k for k, v in oracle.items() if db.get(k) != v]
        assert not bad, ("read during latch", bad[:3])
        # Space comes back (trash drain / operator): ZERO resume() calls
        # from here on — recovery must be autonomous.
        fe.add_disk_budget("*", 8 << 20)
        assert _wait_until(lambda: db._bg_error is None, timeout=30.0), \
            "no_space latch never auto-cleared after space returned"
        assert any(i.auto and i.reason == "no_space"
                   for i in watch.recovered)
        assert _wait_until(lambda: db.disk_pressure() == "ok", timeout=10.0)
        # Writes flow again; merged-oracle parity on the primary.
        for i in range(200):
            k, v = b"post%04d" % i, (b"p%06d" % i).ljust(256, b".")
            db.put(k, v, wo)
            oracle[k] = v
        db.flush()
        db.wait_for_compactions()
        bad = [k for k, v in oracle.items() if db.get(k) != v]
        assert not bad, ("post-recovery", bad[:3])
        # Follower leg: a replica fed from the recovered primary's WAL
        # stream converges to the same merged oracle.
        fol = FollowerDB.open(src, Options(statistics=stats),
                              transport=LocalTransport(ship), mode="shared")
        try:
            for _ in range(4):
                fol.catch_up()
            fbad = [k for k, v in oracle.items() if fol.get(k) != v]
            assert not fbad, ("follower", fbad[:3])
        finally:
            fol.close()
        db.close()
        db = None
        # Reopen on the REAL env: durability held through the chaos.
        with DB.open(src, Options()) as db2:
            rbad = [k for k, v in oracle.items() if db2.get(k) != v]
            assert not rbad, ("reopen", rbad[:3])
    finally:
        if db is not None:
            db.close()


def test_db_stress_disk_budget_mode(tmp_path):
    """Satellite: the --disk-budget stress mode runs its starve/refill
    cycle and exits 0 (serving / SOFT-latched / cleanly-shed only)."""
    from toplingdb_tpu.tools.db_stress import main

    rc = main([f"--db={tmp_path}/sdb", "--ops=300", "--max-key=200",
               "--write-buffer-size=16384",
               f"--disk-budget={128 * 1024}"])
    assert rc == 0


# ---------------------------------------------------------------------------
# Observability: SLO kind + /metrics gauges
# ---------------------------------------------------------------------------


def test_slo_disk_pressure_kind():
    from toplingdb_tpu.utils.slo import SLOEngine, SLOSpec

    stats = Statistics()
    engine = SLOEngine(stats, [SLOSpec(name="disk", kind="disk_pressure",
                                       objective=0.9, burn_fast=1.0,
                                       burn_slow=1.0)],
                       default_window_sec=10.0, clock=lambda: clock[0])
    clock = [1000.0]
    engine.evaluate(now=clock[0])
    for _ in range(40):
        stats.record_tick(st.DISK_PRESSURE_POLLS, 1)
        stats.record_tick(st.DISK_PRESSURE_POLLS_BAD, 1)  # 100% bad
    clock[0] += 11.0
    out = engine.evaluate(now=clock[0])
    assert out["specs"]["disk"]["bad_fraction_fast"] == pytest.approx(1.0)
    assert out["specs"]["disk"]["firing"]


def test_metrics_scrape_has_disk_gauges(tmp_path):
    import urllib.request

    from toplingdb_tpu.utils.config import SidePluginRepo

    stats = Statistics()
    db = DB.open(str(tmp_path / "d"),
                 Options(statistics=stats,
                         max_allowed_space_usage=1 << 30,
                         slo_specs=({"name": "disk-ok",
                                     "kind": "disk_pressure",
                                     "objective": 0.9},)))
    repo = SidePluginRepo()
    repo.attach_db("d", db)
    port = repo.start_http()
    try:
        db.put(b"k", b"v")
        db.flush()
        db._sfm.poll()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            text = r.read().decode()
        assert 'tpulsm_disk_pressure_state{db="d"} 0' in text
        assert 'tpulsm_disk_budget_bytes{db="d"}' in text
        assert 'tpulsm_disk_tracked_bytes{db="d"}' in text
        assert 'tpulsm_disk_free_bytes{db="d"}' in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/slo/d?evaluate=1", timeout=10) as r:
            import json as _json

            doc = _json.loads(r.read())
        assert "disk-ok" in doc["specs"]
    finally:
        repo.stop_http()
        db.close()
