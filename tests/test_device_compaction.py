"""Device data plane: parity with the CPU CompactionIterator, byte-identical
SST outputs, and the serialized worker boundary."""

import random
import struct

import pytest

from toplingdb_tpu.compaction.compaction_iterator import CompactionIterator
from toplingdb_tpu.db.dbformat import (
    InternalKeyComparator,
    ValueType,
    make_internal_key,
)
from toplingdb_tpu.db.range_del import RangeDelAggregator, RangeTombstone
from toplingdb_tpu.ops.device_compaction import device_gc_entries
from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

ICMP = InternalKeyComparator()


class ListIter:
    def __init__(self, items):
        self._items = items
        self._i = 0

    def valid(self):
        return self._i < len(self._items)

    def key(self):
        return self._items[self._i][0]

    def value(self):
        return self._items[self._i][1]

    def next(self):
        self._i += 1


def cpu_reference(entries, snaps, bottom, rd=None, op=None):
    srt = sorted(entries, key=lambda kv: ICMP.sort_key(kv[0]))
    ci = CompactionIterator(
        ListIter(srt), ICMP, snaps, bottommost_level=bottom,
        merge_operator=op, range_del_agg=rd,
    )
    return list(ci.entries())


def gen_workload(rng, n, key_space=200, with_merge=True):
    entries = []
    for seq in range(1, n + 1):
        k = b"key%04d" % rng.randrange(key_space)
        r = rng.random()
        if r < 0.6:
            entries.append((make_internal_key(k, seq, ValueType.VALUE),
                            b"v%06d" % seq))
        elif r < 0.75:
            entries.append((make_internal_key(k, seq, ValueType.DELETION), b""))
        elif r < 0.85 and with_merge:
            entries.append((make_internal_key(k, seq, ValueType.MERGE),
                            struct.pack("<Q", seq)))
        else:
            entries.append((make_internal_key(k, seq, ValueType.SINGLE_DELETION), b""))
    return entries


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_device_matches_cpu_state_machine(seed):
    rng = random.Random(seed)
    entries = gen_workload(rng, rng.randrange(50, 400))
    maxseq = len(entries)
    snaps = sorted(rng.sample(range(1, maxseq + 1), rng.randrange(0, 4)))
    bottom = rng.random() < 0.5
    rd = None
    if rng.random() < 0.6:
        rd = RangeDelAggregator(ICMP.user_comparator)
        for _ in range(rng.randrange(1, 4)):
            a = b"key%04d" % rng.randrange(200)
            b = b"key%04d" % rng.randrange(200)
            if a > b:
                a, b = b, a
            if a != b:
                rd.add(RangeTombstone(rng.randrange(1, maxseq), a, b))
        if rd.empty():
            rd = None
    op = UInt64AddOperator()
    want = cpu_reference(entries, snaps, bottom, rd, op)
    got = list(device_gc_entries(
        entries, ICMP, snaps, bottom, merge_operator=op, rd=rd
    ))
    assert got == want


def test_device_empty_and_single():
    assert list(device_gc_entries([], ICMP, [], True)) == []
    e = [(make_internal_key(b"k", 1, ValueType.VALUE), b"v")]
    assert list(device_gc_entries(e, ICMP, [], False)) == e


def test_device_unsorted_input_is_merged():
    # Entries arrive as concatenated runs, unsorted overall.
    run1 = [(make_internal_key(b"b", 2, ValueType.VALUE), b"v2"),
            (make_internal_key(b"d", 4, ValueType.VALUE), b"v4")]
    run2 = [(make_internal_key(b"a", 1, ValueType.VALUE), b"v1"),
            (make_internal_key(b"c", 3, ValueType.VALUE), b"v3")]
    got = list(device_gc_entries(run1 + run2, ICMP, [], False))
    assert [k[:-8] for k, _ in got] == [b"a", b"b", b"c", b"d"]


def test_full_sst_byte_parity(tmp_path):
    """run_compaction_to_tables vs run_device_compaction: identical bytes."""
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops.device_compaction import run_device_compaction
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions

    env = default_env()
    dbdir = str(tmp_path)
    rng = random.Random(99)
    topts = TableOptions(block_size=512)

    # Build two input "runs" as real SSTs.
    metas = []
    seq = 1
    for fnum in (11, 12):
        entries = []
        for i in range(300):
            k = b"key%05d" % rng.randrange(400)
            entries.append((make_internal_key(k, seq, ValueType.VALUE),
                            b"val%08d" % seq))
            seq += 1
        entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
        dedup = [e for i, e in enumerate(entries)
                 if i == 0 or ICMP.compare(entries[i - 1][0], e[0]) != 0]
        import toplingdb_tpu.db.filename as fn
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, topts)
        for k, v in dedup:
            b.add(k, v)
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum, file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno, largest_seqno=props.largest_seqno,
        ))

    tc = TableCache(env, dbdir, ICMP, topts)
    c = Compaction(level=0, output_level=1, inputs=metas, bottommost=True,
                   max_output_file_size=16 * 1024)

    def make_alloc(start):
        state = [start]

        def alloc():
            state[0] += 1
            return state[0]

        return alloc

    out_cpu, _ = run_compaction_to_tables(
        env, dbdir, ICMP, c, tc, topts, [], new_file_number=make_alloc(100),
        creation_time=12345,
    )
    out_dev, _ = run_device_compaction(
        env, dbdir, ICMP, c, tc, topts, [], new_file_number=make_alloc(200),
        creation_time=12345, device_name="cpu-jax",
    )
    assert len(out_cpu) == len(out_dev) >= 1
    import toplingdb_tpu.db.filename as fn
    for mc, md in zip(out_cpu, out_dev):
        bc = open(fn.table_file_name(dbdir, mc.number), "rb").read()
        bd = open(fn.table_file_name(dbdir, md.number), "rb").read()
        assert bc == bd  # bit-identical SSTs (BASELINE.json north-star check)
        assert mc.smallest == md.smallest and mc.largest == md.largest


def test_subprocess_worker_end_to_end(tmp_db_path):
    from toplingdb_tpu.compaction.executor import SubprocessCompactionExecutorFactory
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    opts = Options(
        write_buffer_size=8 * 1024,
        compaction_executor_factory=SubprocessCompactionExecutorFactory(device="cpu"),
    )
    with DB.open(tmp_db_path, opts) as db:
        for i in range(3000):
            db.put(b"key%05d" % (i % 1000), b"val%07d" % i)
        db.flush()
        db.compact_range()
        db.wait_for_compactions()
        for k in range(0, 1000, 83):
            last = max(i for i in range(k, 3000, 1000))
            assert db.get(b"key%05d" % k) == b"val%07d" % last
        v = db.versions.current
        assert sum(f.num_entries for _, f in v.all_files()) == 1000


def test_device_executor_in_db(tmp_db_path):
    from toplingdb_tpu.compaction.executor import DeviceCompactionExecutorFactory
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    opts = Options(
        write_buffer_size=8 * 1024,
        compaction_executor_factory=DeviceCompactionExecutorFactory(device="cpu-jax"),
    )
    with DB.open(tmp_db_path, opts) as db:
        for i in range(3000):
            db.put(b"key%05d" % (i % 1000), b"val%07d" % i)
        db.delete_range(b"key00100", b"key00200")
        db.flush()
        db.compact_range()
        assert db.get(b"key00150") is None
        assert db.get(b"key00250") is not None
        assert db._compaction_scheduler.last_error is None


def test_columnar_fast_path_byte_parity(tmp_path):
    """Single-output jobs take the native columnar path; bytes must equal the
    per-entry CPU path exactly."""
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops.device_compaction import run_device_compaction
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions
    import toplingdb_tpu.db.filename as fn

    env = default_env()
    dbdir = str(tmp_path)
    rng = random.Random(5)
    topts = TableOptions(block_size=512)
    metas = []
    seq = 1
    for fnum in (21, 22, 23):
        entries = []
        for i in range(250):
            k = b"key%05d" % rng.randrange(300)
            t = ValueType.VALUE if rng.random() < 0.8 else ValueType.DELETION
            entries.append((make_internal_key(k, seq, t), b"val%06d" % seq))
            seq += 1
        entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, topts)
        for k, v in entries:
            b.add(k, v)
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum, file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno, largest_seqno=props.largest_seqno,
        ))
    tc = TableCache(env, dbdir, ICMP, topts)
    # Single-output (huge max size) with snapshots: fast-path eligible.
    c = Compaction(level=0, output_level=2, inputs=metas, bottommost=True,
                   max_output_file_size=1 << 62)

    def mk(start):
        s = [start]

        def alloc():
            s[0] += 1
            return s[0]

        return alloc

    out_cpu, _ = run_compaction_to_tables(
        env, dbdir, ICMP, c, tc, topts, [200, 400], new_file_number=mk(500),
        creation_time=7,
    )
    out_dev, stats = run_device_compaction(
        env, dbdir, ICMP, c, tc, topts, [200, 400], new_file_number=mk(600),
        creation_time=7, device_name="cpu-jax",
    )
    assert len(out_cpu) == len(out_dev) == 1
    bc = open(fn.table_file_name(dbdir, out_cpu[0].number), "rb").read()
    bd = open(fn.table_file_name(dbdir, out_dev[0].number), "rb").read()
    assert bc == bd
    assert out_cpu[0].smallest == out_dev[0].smallest
    assert out_cpu[0].largest == out_dev[0].largest
    assert out_cpu[0].num_entries == out_dev[0].num_entries


def test_http_dcompact_service_end_to_end(tmp_db_path):
    """HTTP worker service: DB routes compactions over HTTP + shared dir
    (the curl+NFS transport shape of the reference's dcompact)."""
    from toplingdb_tpu.compaction.dcompact_service import (
        DcompactWorkerService, HttpCompactionExecutorFactory,
    )
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    svc = DcompactWorkerService(device="cpu")
    port = svc.start()
    try:
        opts = Options(
            write_buffer_size=8 * 1024,
            compaction_executor_factory=HttpCompactionExecutorFactory(
                [f"http://127.0.0.1:{port}"], device="cpu",
            ),
        )
        with DB.open(tmp_db_path, opts) as db:
            for i in range(3000):
                db.put(b"key%05d" % (i % 1000), b"val%07d" % i)
            db.flush()
            db.compact_range()
            db.wait_for_compactions()
            for k in range(0, 1000, 83):
                last = max(i for i in range(k, 3000, 1000))
                assert db.get(b"key%05d" % k) == b"val%07d" % last
        assert svc.jobs_done >= 1
    finally:
        svc.stop()


def test_http_dcompact_fallback_on_dead_worker(tmp_db_path):
    """Unreachable worker → fallback-to-local keeps the DB correct."""
    from toplingdb_tpu.compaction.dcompact_service import (
        HttpCompactionExecutorFactory,
    )
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    opts = Options(
        write_buffer_size=8 * 1024,
        compaction_executor_factory=HttpCompactionExecutorFactory(
            ["http://127.0.0.1:1"], device="cpu", timeout=0.5,
        ),
    )
    with DB.open(tmp_db_path, opts) as db:
        for i in range(2000):
            db.put(b"key%05d" % (i % 500), b"val%07d" % i)
        db.flush()
        db.compact_range()
        for k in range(0, 500, 41):
            last = max(i for i in range(k, 2000, 500))
            assert db.get(b"key%05d" % k) == b"val%07d" % last


def test_device_in_stripe_tombstone_not_masked_by_newer_stripe():
    """Regression (model-check seed 23): two range tombstones covering a key
    straddle a snapshot; the in-stripe (older) tombstone must still delete
    the value even though the max covering seq is above the snapshot —
    device and host must agree."""
    k = b"key084"
    entries = [(make_internal_key(k, 219, ValueType.VALUE), b"v000322")]
    rd = RangeDelAggregator(ICMP.user_comparator)
    rd.add(RangeTombstone(262, b"key031", b"key091"))  # below snapshot: kills
    rd.add(RangeTombstone(283, b"key063", b"key137"))  # above snapshot
    snaps = [276, 286]
    want = cpu_reference(entries, snaps, True, rd, None)
    got = list(device_gc_entries(entries, ICMP, snaps, True, rd=rd))
    assert got == want
    assert got == [], "value@219 must be deleted by tombstone@262 (stripe 0)"


@pytest.mark.parametrize("seed", [11, 12, 13, 14])
def test_host_sort_twin_matches_fused_kernel(seed):
    """fused_encode_sort_gc_host (the TPULSM_HOST_SORT numpy twin used when
    no accelerator is reachable) must produce IDENTICAL outputs to the jax
    fused kernel."""
    import numpy as np

    from toplingdb_tpu.ops import compaction_kernels as ck

    rng = random.Random(seed)
    entries = gen_workload(rng, rng.randrange(30, 300))
    entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))  # any order works; vary
    if seed % 2:
        rng.shuffle(entries)
    key_buf = bytearray()
    offs, lens = [], []
    for ik, _ in entries:
        offs.append(len(key_buf))
        lens.append(len(ik))
        key_buf += ik
    kb = np.frombuffer(bytes(key_buf), dtype=np.uint8)
    ko = np.array(offs, np.int64)
    kl = np.array(lens, np.int64)
    mkb = max(4, int(kl.max()) - 8)
    snaps = sorted(rng.sample(range(1, len(entries) + 2),
                              rng.randrange(0, 4)))
    bottom = rng.random() < 0.5
    a = ck.fused_encode_sort_gc(kb, ko, kl, mkb, snaps, bottom)
    b = ck.fused_encode_sort_gc_host(kb, ko, kl, mkb, snaps, bottom)
    assert np.array_equal(a[0], b[0]), "survivor order differs"
    assert np.array_equal(a[1], b[1]), "zero flags differ"
    assert np.array_equal(a[2], b[2]), "complex flags differ"
    assert a[3] == b[3], "has_complex differs"


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_host_sort_twin_varlen_keys_and_big_seqnos(seed):
    """Host-twin parity where it's riskiest: variable-length keys (length
    tie-break, prefix ordering) and seqnos crossing the 2^24/2^32 word
    boundaries of the device's split-word sort."""
    import numpy as np

    from toplingdb_tpu.db.dbformat import ValueType, make_internal_key
    from toplingdb_tpu.ops import compaction_kernels as ck

    rng = random.Random(seed)
    entries = []
    for i in range(rng.randrange(50, 250)):
        klen = rng.randrange(1, 24)
        uk = bytes(rng.randrange(97, 100) for _ in range(klen))  # a-c: dups
        seq = rng.choice([rng.randrange(1, 1 << 10),
                          rng.randrange(1 << 23, 1 << 25),
                          rng.randrange(1 << 31, 1 << 40)])
        t = ValueType.VALUE if rng.random() < 0.8 else ValueType.DELETION
        entries.append((make_internal_key(uk, seq, t), b"v%d" % i))
    key_buf = bytearray()
    offs, lens = [], []
    for ik, _ in entries:
        offs.append(len(key_buf)); lens.append(len(ik)); key_buf += ik
    kb = np.frombuffer(bytes(key_buf), dtype=np.uint8)
    ko = np.array(offs, np.int64); kl = np.array(lens, np.int64)
    mkb = max(4, int(kl.max()) - 8)
    snaps = sorted(rng.sample(range(1, 1 << 40), rng.randrange(0, 5)))
    bottom = rng.random() < 0.5
    a = ck.fused_encode_sort_gc(kb, ko, kl, mkb, snaps, bottom)
    b = ck.fused_encode_sort_gc_host(kb, ko, kl, mkb, snaps, bottom)
    assert np.array_equal(a[0], b[0])
    assert np.array_equal(a[1], b[1])
    assert np.array_equal(a[2], b[2])
    assert a[3] == b[3]


def test_host_sort_tombstone_path_byte_parity(tmp_path, monkeypatch):
    """TPULSM_HOST_SORT=1 covers the tombstone-bearing columnar branch too:
    same SST bytes as the jax path."""
    import os

    from toplingdb_tpu.compaction.executor import DeviceCompactionExecutorFactory
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options

    outs = {}
    for host in (0, 1):
        if host:
            monkeypatch.setenv("TPULSM_HOST_SORT", "1")
        else:
            monkeypatch.delenv("TPULSM_HOST_SORT", raising=False)
        d = str(tmp_path / f"db{host}")
        o = Options(write_buffer_size=1 << 20, disable_auto_compactions=True,
                    compaction_executor_factory=DeviceCompactionExecutorFactory(
                        device="cpu-jax"))
        with DB.open(d, o) as db:
            for i in range(3000):
                db.put(b"key%05d" % (i % 2000), b"v%05d" % i)
            snap = db.get_snapshot()  # pins the tombstone through compaction
            db.delete_range(b"key00500", b"key01500")
            db.flush()
            from unittest import mock

            with mock.patch("time.time", lambda: 1753750123.0):
                db.compact_range()
            snap.release()
            ssts = sorted(f for f in os.listdir(d) if f.endswith(".sst"))
            outs[host] = [open(os.path.join(d, f), "rb").read()
                          for f in ssts]
    assert len(outs[0]) == len(outs[1]) and outs[0], "no outputs"
    for x, y in zip(outs[0], outs[1]):
        assert x == y, "host-sort tombstone path bytes differ from jax path"


def test_multi_shard_parity(tmp_path, monkeypatch):
    """TPULSM_DEVICE_SHARDS>1 splits the job into user-key-range shards
    (per-shard device programs, stitched survivor orders); bytes must equal
    the single-shard device path and the CPU path — both uniform-length and
    variable-length keys."""
    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops import device_compaction as dc
    from toplingdb_tpu.ops.device_compaction import run_device_compaction
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions
    import os
    import toplingdb_tpu.db.filename as fn

    env = default_env()
    topts = TableOptions(block_size=512)
    # Shard even the small test inputs.
    monkeypatch.setattr(dc, "_SHARD_MIN_ROWS", 1)
    for mode, keyfmt in (
        ("uniform", lambda r: b"key%05d" % r.randrange(400)),
        ("varlen", lambda r: b"k%0*d" % (r.randrange(3, 9), r.randrange(400))),
    ):
        dbdir = str(tmp_path / mode)
        os.makedirs(dbdir)
        rng = random.Random(17)
        metas = []
        seq = 1
        for fnum in (41, 42, 43):
            entries = []
            for _ in range(300):
                t = (ValueType.VALUE if rng.random() < 0.8
                     else ValueType.DELETION)
                entries.append(
                    (make_internal_key(keyfmt(rng), seq, t), b"val%06d" % seq)
                )
                seq += 1
            entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
            w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
            b = TableBuilder(w, ICMP, topts)
            last = None
            for k, v in entries:
                if last == k:
                    continue
                b.add(k, v)
                last = k
            props = b.finish()
            w.close()
            metas.append(FileMetaData(
                number=fnum,
                file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
                smallest=b.smallest_key, largest=b.largest_key,
                smallest_seqno=props.smallest_seqno,
                largest_seqno=props.largest_seqno,
            ))
        tc = TableCache(env, dbdir, ICMP, topts)

        def mk(base):
            s = [base]

            def alloc():
                s[0] += 1
                return s[0]

            return alloc

        outs = {}
        for shards in (0, 1, 4, 7):
            c = Compaction(level=0, output_level=2, inputs=list(metas),
                           bottommost=True, max_output_file_size=1 << 62)
            if shards:
                monkeypatch.setenv("TPULSM_DEVICE_SHARDS", str(shards))
                outs[shards], _ = run_device_compaction(
                    env, dbdir, ICMP, c, tc, topts, [250, 600],
                    new_file_number=mk(500 + shards * 20), creation_time=7,
                    device_name="cpu-jax",
                )
            else:
                monkeypatch.delenv("TPULSM_DEVICE_SHARDS", raising=False)
                outs[0], _ = run_compaction_to_tables(
                    env, dbdir, ICMP, c, tc, topts, [250, 600],
                    new_file_number=mk(490), creation_time=7,
                )
        ref = [open(fn.table_file_name(dbdir, m.number), "rb").read()
               for m in outs[0]]
        assert ref, f"{mode}: no outputs"
        for shards in (1, 4, 7):
            got = [open(fn.table_file_name(dbdir, m.number), "rb").read()
                   for m in outs[shards]]
            assert got == ref, f"{mode}: shards={shards} bytes differ"


@pytest.mark.parametrize("shards", [0, 4])
def test_device_columnar_complex_tombstones_snapshots(tmp_path, monkeypatch,
                                                      shards):
    """The columnar device path (NOT the per-entry fallback) must handle a
    job with DeleteRange fragments + MERGE/SINGLE_DELETE groups + 200 live
    snapshots, byte-identical to the CPU path (VERDICT r2 task 2: cover
    rides the fused kernels, complex groups fold host-side in-stream, the
    snapshot cap is bucketed past 64)."""
    import os
    import struct

    from toplingdb_tpu.compaction.compaction_job import run_compaction_to_tables
    from toplingdb_tpu.compaction.picker import Compaction
    from toplingdb_tpu.db.dbformat import MAX_SEQUENCE_NUMBER
    from toplingdb_tpu.db.table_cache import TableCache
    from toplingdb_tpu.db.version_edit import FileMetaData
    from toplingdb_tpu.env import default_env
    from toplingdb_tpu.ops import device_compaction as dc
    from toplingdb_tpu.ops.device_compaction import run_device_compaction
    from toplingdb_tpu.table.builder import TableBuilder, TableOptions
    import toplingdb_tpu.db.filename as fn

    env = default_env()
    topts = TableOptions(block_size=512)
    dbdir = str(tmp_path / f"s{shards}")
    os.makedirs(dbdir)
    rng = random.Random(77 + shards)
    if shards:
        monkeypatch.setattr(dc, "_SHARD_MIN_ROWS", 1)
        monkeypatch.setenv("TPULSM_DEVICE_SHARDS", str(shards))
    else:
        monkeypatch.delenv("TPULSM_DEVICE_SHARDS", raising=False)

    metas = []
    seq = 1
    for fnum in (61, 62, 63):
        entries = []
        for _ in range(400):
            k = b"key%05d" % rng.randrange(500)
            r = rng.random()
            if r < 0.6:
                entries.append((make_internal_key(k, seq, ValueType.VALUE),
                                b"val%06d" % seq))
            elif r < 0.8:
                entries.append((make_internal_key(k, seq, ValueType.MERGE),
                                struct.pack("<Q", seq % 97)))
            elif r < 0.9:
                entries.append((make_internal_key(k, seq, ValueType.DELETION),
                                b""))
            else:
                entries.append((make_internal_key(
                    k, seq, ValueType.SINGLE_DELETION), b""))
            seq += 1
        entries.sort(key=lambda kv: ICMP.sort_key(kv[0]))
        dedup = [e for i, e in enumerate(entries)
                 if i == 0 or entries[i - 1][0] != e[0]]
        w = env.new_writable_file(fn.table_file_name(dbdir, fnum))
        b = TableBuilder(w, ICMP, topts)
        for k, v in dedup:
            b.add(k, v)
        # Two range tombstones per file, written into the range-del block.
        for _ in range(2):
            lo = rng.randrange(450)
            begin = b"key%05d" % lo
            end = b"key%05d" % (lo + rng.randrange(10, 60))
            b.add_tombstone(
                make_internal_key(begin, seq, ValueType.RANGE_DELETION), end)
            seq += 1
        props = b.finish()
        w.close()
        metas.append(FileMetaData(
            number=fnum,
            file_size=env.get_file_size(fn.table_file_name(dbdir, fnum)),
            smallest=b.smallest_key, largest=b.largest_key,
            smallest_seqno=props.smallest_seqno,
            largest_seqno=props.largest_seqno,
        ))
    tc = TableCache(env, dbdir, ICMP, topts)
    snapshots = sorted(rng.sample(range(1, seq), 200))  # > old 64 cap
    op = UInt64AddOperator()

    def mk(base):
        s = [base]

        def alloc():
            s[0] += 1
            return s[0]

        return alloc

    c1 = Compaction(level=0, output_level=2, inputs=list(metas),
                    bottommost=True, max_output_file_size=1 << 62)
    out_cpu, _ = run_compaction_to_tables(
        env, dbdir, ICMP, c1, tc, topts, snapshots, merge_operator=op,
        new_file_number=mk(700), creation_time=7,
    )

    # The per-entry fallback must NOT run: this job must stay columnar.
    def no_fallback(*a, **k):
        raise AssertionError("columnar path fell back to per-entry scan")

    monkeypatch.setattr(dc, "collect_raw_entries", no_fallback)
    c2 = Compaction(level=0, output_level=2, inputs=list(metas),
                    bottommost=True, max_output_file_size=1 << 62)
    out_dev, _ = run_device_compaction(
        env, dbdir, ICMP, c2, tc, topts, snapshots, merge_operator=op,
        new_file_number=mk(800), creation_time=7, device_name="cpu-jax",
    )
    assert len(out_cpu) == len(out_dev) >= 1
    for mc, md in zip(out_cpu, out_dev):
        bc = open(fn.table_file_name(dbdir, mc.number), "rb").read()
        bd = open(fn.table_file_name(dbdir, md.number), "rb").read()
        assert bc == bd, "complex/tombstone columnar path bytes differ"
        assert mc.smallest == md.smallest and mc.largest == md.largest
        assert mc.num_entries == md.num_entries


def test_device_columnar_complex_host_twin_parity(tmp_path, monkeypatch):
    """TPULSM_HOST_SORT=1 twin of the complex/tombstone columnar path."""
    monkeypatch.setenv("TPULSM_HOST_SORT", "1")
    test_device_columnar_complex_tombstones_snapshots(
        tmp_path, monkeypatch, 0)


@pytest.mark.parametrize("seed", [31, 32, 33])
def test_front_coded_upload_parity(seed):
    """Front-coded uploads (prefix lengths + suffixes, decoded on device
    with a cummax scan) must produce IDENTICAL survivor streams to the
    plain full-key upload."""
    import numpy as np

    from toplingdb_tpu.ops import compaction_kernels as ck

    rng = random.Random(seed)
    L = rng.choice([12, 16, 24])  # internal key len (uk_len = L - 8)
    chunks_raw = []
    seq = 1
    for _ in range(rng.randrange(1, 4)):  # chunks = sorted runs
        n = rng.randrange(5, 200)
        keys = sorted(
            b"k%0*d" % (L - 9, rng.randrange(100)) for _ in range(n)
        )
        buf = bytearray()
        for k in keys:
            buf += make_internal_key(k, seq, ValueType.VALUE)
            seq += 1
        chunks_raw.append((np.frombuffer(bytes(buf), np.uint8), n, L))
    chunks = [ck.prepare_uniform_chunk(b, n, l) for b, n, l in chunks_raw]
    snaps = sorted(rng.sample(range(1, seq + 1), rng.randrange(0, 3)))
    outs = []
    for fc in (False, True):
        h = ck.upload_uniform_shard(chunks, front_code=fc)
        assert ("plens" in h) == fc
        pending = ck.fused_uniform_shard_start(h, snaps, True)
        outs.append(ck.fused_uniform_shard_finish(pending))
    o0, z0, c0, h0 = outs[0]
    o1, z1, c1, h1 = outs[1]
    assert np.array_equal(o0, o1), "front-coded survivor order differs"
    assert np.array_equal(z0, z1) and np.array_equal(c0, c1) and h0 == h1


def test_segmented_merge_parity_vs_sort():
    """The segmented rank-merge of presorted runs (the reference's k-way
    heap merge role, table/merging_iterator.cc:476) must produce EXACTLY
    the lax.sort path's outputs — order, flags, counts — across run
    counts, including the single-run skip mode."""
    import os

    import numpy as np

    from toplingdb_tpu.ops import compaction_kernels as ck

    rng = np.random.default_rng(17)
    uk_len = 8

    def make_chunks(n_chunks, rows_per):
        chunks = []
        for _ in range(n_chunks):
            n = int(rows_per + rng.integers(-rows_per // 3,
                                            rows_per // 3 + 1))
            uk = rng.integers(0, 99999, n)
            seqs = rng.integers(1, 1 << 20, n).astype(np.uint64)
            ks = np.array([b"%08d" % k for k in uk])
            order = np.lexsort(
                (np.iinfo(np.int64).max - seqs.view(np.int64), ks))
            kb = np.zeros((n, uk_len + 8), np.uint8)
            for i, oi in enumerate(order):
                kb[i, :uk_len] = np.frombuffer(ks[oi], np.uint8)
                packed = (int(seqs[oi]) << 8) | 1
                kb[i, uk_len:] = np.frombuffer(
                    packed.to_bytes(8, "little"), np.uint8)
            chunks.append(ck.prepare_uniform_chunk(
                np.ascontiguousarray(kb).reshape(-1), n, uk_len + 8))
        return chunks

    old = os.environ.get("TPULSM_DEVICE_MERGE")
    try:
        for n_chunks in (1, 2, 4, 6):
            chunks = make_chunks(n_chunks, 900)
            outs = {}
            for mode in ("0", "1"):
                os.environ["TPULSM_DEVICE_MERGE"] = mode
                h = ck.upload_uniform_shard(chunks)
                pend = ck.fused_uniform_shard_start(h, [9, 4000], True)
                outs[mode] = ck.fused_uniform_shard_finish(pend)
            a, b = outs["0"], outs["1"]
            assert np.array_equal(a[0], b[0]), n_chunks
            assert np.array_equal(a[1], b[1])
            assert np.array_equal(a[2], b[2])
            assert a[3] == b[3]
    finally:
        if old is None:
            os.environ.pop("TPULSM_DEVICE_MERGE", None)
        else:
            os.environ["TPULSM_DEVICE_MERGE"] = old


def test_host_merge_runs_matches_full_sort():
    """tpulsm_merge_runs (multi-threaded k-way merge of presorted runs,
    the host twin of the device segmented merge) must reproduce
    tpulsm_sort_entries' exact order/new_key/packed outputs."""
    import numpy as np

    from toplingdb_tpu.ops import compaction_kernels as ck

    rng = np.random.default_rng(9)
    # (n_runs, rows_per_run, mixed_lens): the 60k-per-run case crosses the
    # 1<<16 threshold that enables the SPLITTER-PARTITIONED multithread
    # merge; mixed key lengths exercise the len tiebreak + kw padding.
    for n_runs, rows, mixed in ((1, 2000, False), (3, 1500, True),
                                (4, 60_000, False), (5, 1200, True)):
        parts = []
        for _ in range(n_runs):
            n = int(rng.integers(rows // 2, rows + 1))
            uk = np.sort(rng.integers(0, max(10, n // 2), n))
            seqs = rng.integers(1, 1 << 40, n).astype(np.uint64)
            if mixed:
                ks = np.array([(b"%08d" % k)[: 4 + (k % 5)] for k in uk])
                ks = np.array(sorted(ks))
            else:
                ks = np.array([b"%08d" % k for k in uk])
            order = np.lexsort(
                (np.iinfo(np.int64).max - seqs.view(np.int64), ks))
            recs = []
            for oi in order:
                packed = (int(seqs[oi]) << 8) | 1
                recs.append(bytes(ks[oi])
                            + packed.to_bytes(8, "little"))
            parts.append(recs)
        recs = [r for p_ in parts for r in p_]
        buf = np.frombuffer(b"".join(recs), np.uint8)
        lens = np.array([len(r) for r in recs], np.int64)
        offs = np.concatenate([[0], np.cumsum(lens[:-1])]).astype(np.int64)
        ns = [len(p_) for p_ in parts]
        rs = np.cumsum([0] + ns, dtype=np.int64)
        a = ck.host_sort_order(buf, offs, lens)
        b = ck.host_sort_order(buf, offs, lens, run_starts=rs)
        if a is None or b is None:
            import pytest

            pytest.skip("native lib unavailable")
        assert np.array_equal(a[0], b[0]), (n_runs, mixed)
        assert np.array_equal(a[1], b[1])
        assert np.array_equal(a[2], b[2])
        # malformed boundaries must fall back, not corrupt
        bad = rs.copy()
        bad[-1] -= 1
        c = ck.host_sort_order(buf, offs, lens, run_starts=bad)
        assert np.array_equal(a[0], c[0])
