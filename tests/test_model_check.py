"""Model-checked random-op fuzz (the reference's fuzz/db_map_fuzzer.cc:
execute random operations against the DB and a std::map-like model and
assert equivalence). Deterministic seeds; every round interleaves puts,
deletes, range deletes, flushes, compactions, snapshots, iterators, and
crash-reopen, checking the full keyspace against the model."""

import random

import pytest

from toplingdb_tpu.db.db import DB
from toplingdb_tpu.options import Options, ReadOptions


def _check_all(db, model, keyspace):
    for k in keyspace:
        assert db.get(k) == model.get(k), k
    it = db.new_iterator()
    it.seek_to_first()
    got = [(k, v) for k, v in it.entries()]
    want = sorted(model.items())
    assert got == want


@pytest.mark.parametrize("seed", [1, 7, 23])
def test_db_matches_model(tmp_path, seed):
    rng = random.Random(seed)
    d = str(tmp_path / "db")
    o = Options(write_buffer_size=4 * 1024, target_file_size_base=8 * 1024,
                level0_file_num_compaction_trigger=3)
    db = DB.open(d, o)
    model: dict[bytes, bytes] = {}
    keyspace = [b"key%03d" % i for i in range(150)]
    snapshots = []  # (snapshot, frozen model)
    try:
        for step in range(1200):
            r = rng.random()
            k = rng.choice(keyspace)
            if r < 0.50:
                v = b"v%06d" % step
                db.put(k, v)
                model[k] = v
            elif r < 0.65:
                db.delete(k)
                model.pop(k, None)
            elif r < 0.70:
                lo, hi = sorted((rng.randrange(150), rng.randrange(150)))
                b, e = b"key%03d" % lo, b"key%03d" % hi
                db.delete_range(b, e)
                for kk in list(model):
                    if b <= kk < e:
                        del model[kk]
            elif r < 0.74:
                db.flush()
            elif r < 0.76:
                db.compact_range()
            elif r < 0.79 and len(snapshots) < 4:
                snapshots.append((db.get_snapshot(), dict(model)))
            elif r < 0.82 and snapshots:
                snap, frozen = snapshots.pop(
                    rng.randrange(len(snapshots)))
                probe = rng.sample(keyspace, 20)
                for kk in probe:
                    assert db.get(kk, ReadOptions(snapshot=snap)) == \
                        frozen.get(kk), (step, kk)
                snap.release()
            elif r < 0.84:
                # Crash (no close-flush) and reopen: WAL replay must
                # restore exactly the model.
                for snap, _ in snapshots:
                    snap.release()
                snapshots.clear()
                db.wait_for_compactions()
                db._wal.sync()
                db._closed = True
                db._compaction_scheduler.shutdown()
                db = DB.open(d, o)
            if step % 300 == 299:
                db.wait_for_compactions()
                _check_all(db, model, keyspace)
        db.wait_for_compactions()
        _check_all(db, model, keyspace)
    finally:
        for snap, _ in snapshots:
            snap.release()
        db.close()
    with DB.open(d, o) as db2:
        _check_all(db2, model, keyspace)


def test_iterator_refresh(tmp_path):
    """Iterator::Refresh rebinds to the current DB state (new writes become
    visible); position resets as in the reference."""
    with DB.open(str(tmp_path / "db"), Options()) as db:
        db.put(b"a", b"1")
        it = db.new_iterator()
        it.seek_to_first()
        assert it.valid() and it.key() == b"a"
        db.put(b"b", b"2")
        db.flush()
        # Old view: no b.
        it.seek(b"b")
        assert not it.valid()
        it.refresh()
        it.seek(b"b")
        assert it.valid() and it.value() == b"2"
        it.seek_to_first()
        assert [k for k, _ in it.entries()] == [b"a", b"b"]


def test_iterator_refresh_rejected_with_snapshot(tmp_path):
    from toplingdb_tpu.utils.status import NotSupported

    with DB.open(str(tmp_path / "db"), Options()) as db:
        db.put(b"a", b"1")
        snap = db.get_snapshot()
        it = db.new_iterator(ReadOptions(snapshot=snap))
        with pytest.raises(NotSupported):
            it.refresh()
        snap.release()


@pytest.mark.parametrize("seed,rep", [(3, "skiplist"), (11, "cspp")])
def test_db_matches_model_extended_surfaces(tmp_path, seed, rep):
    """Round-4 surface fuzz: merges (model folds uint64add), wide-column
    entities (plain get sees the default column), batched MultiGet, and
    iterator columns — against both native memtable reps."""
    from toplingdb_tpu.db.wide_columns import encode_entity
    from toplingdb_tpu.utils.merge_operator import UInt64AddOperator

    rng = random.Random(seed)
    d = str(tmp_path / "db")
    o = Options(write_buffer_size=8 * 1024,
                target_file_size_base=16 * 1024,
                level0_file_num_compaction_trigger=3,
                memtable_rep=rep,
                merge_operator=UInt64AddOperator())
    db = DB.open(d, o)
    # model[k] = ("v", bytes) plain | ("e", dict) entity | ("m", int) counter
    model: dict[bytes, tuple] = {}
    keyspace = [b"key%03d" % i for i in range(120)]

    def visible(k):
        ent = model.get(k)
        if ent is None:
            return None
        kind, v = ent
        if kind == "v":
            return v
        if kind == "e":
            return v.get(b"", b"")
        return v.to_bytes(8, "little")

    try:
        for step in range(1000):
            r = rng.random()
            k = rng.choice(keyspace)
            if r < 0.35:
                v = b"v%06d" % step
                db.put(k, v)
                model[k] = ("v", v)
            elif r < 0.50:
                add = rng.randrange(1000)
                db.merge(k, add.to_bytes(8, "little"))
                kind, old = model.get(k, ("m", 0))
                if kind == "m":
                    model[k] = ("m", old + add)
                elif kind == "v" and len(old) == 8:
                    model[k] = ("m",
                                int.from_bytes(old, "little") + add)
                else:
                    # merging onto an entity/odd value: engine treats the
                    # base as bytes; keep the model out of that corner
                    # by overwriting with a fresh counter first.
                    db.put(k, (0).to_bytes(8, "little"))
                    db.merge(k, add.to_bytes(8, "little"))
                    model[k] = ("m", add)
            elif r < 0.62:
                cols = {b"": b"d%04d" % step, b"c1": b"x" * rng.randrange(9)}
                db.put_entity(k, cols)
                model[k] = ("e", cols)
            elif r < 0.72:
                db.delete(k)
                model.pop(k, None)
            elif r < 0.80:
                probe = rng.sample(keyspace, 16)
                got = db.multi_get(probe)
                for kk, vv in zip(probe, got):
                    assert vv == visible(kk), (step, kk)
            elif r < 0.84:
                db.flush()
            elif r < 0.87:
                db.compact_range()
            elif r < 0.90:
                ent = model.get(k)
                ge = db.get_entity(k)
                if ent is None:
                    assert ge is None
                elif ent[0] == "e":
                    assert ge == ent[1], (step, k)
                else:
                    assert ge == {b"": visible(k)}
            if step % 250 == 249:
                db.wait_for_compactions()
                for kk in keyspace:
                    assert db.get(kk) == visible(kk), (step, kk)
        db.wait_for_compactions()
        for kk in keyspace:
            assert db.get(kk) == visible(kk), kk
    finally:
        db.close()
    with DB.open(d, o) as db2:
        for kk in keyspace:
            assert db2.get(kk) == visible(kk), kk
