"""Out-of-process fleet tests: lease semantics, fencing, graceful
shutdown, transport resilience, and the multi-process chaos soak.

The lease-protocol tests drive LeaseCoordinator with a FAKE clock so
expiry, grace and restart scenarios are exact, not sleep-calibrated;
process-level behaviour (kill -9, partition, coordinator crash) is
covered by the seeded fast soak at the bottom — the full soak rides
behind the `slow` marker.
"""

import os
import time

import pytest

from toplingdb_tpu.sharding.lease import (
    LeaseClient,
    LeaseConflict,
    LeaseCoordinator,
    LeaseCoordinatorServer,
)
from toplingdb_tpu.sharding.shard_map import Shard, ShardMap
from toplingdb_tpu.utils.statistics import Statistics
from toplingdb_tpu.utils.status import Busy, IOError_


@pytest.fixture
def clk():
    """Mutable fake clock: clk.now to read, clk.tick(dt) to advance."""
    class _Clk:
        now = 1000.0

        def __call__(self):
            return self.now

        def tick(self, dt):
            self.now += dt
    return _Clk()


@pytest.fixture
def coord(tmp_path, clk):
    co = LeaseCoordinator(str(tmp_path / "lease.jsonl"), default_ttl=10.0,
                          grace=2.0, clock=clk, statistics=Statistics())
    co.install_map(ShardMap.uniform(2).to_config(),
                   {"s0": "http://a", "s1": "http://b"})
    yield co
    co.close()


# ---------------------------------------------------------------------------
# Lease semantics (satellite 4)
# ---------------------------------------------------------------------------


def test_lease_expiry_then_fencing_token_rejection(coord, clk):
    g1 = coord.acquire("s0", "h1")
    # Past expiry + grace the shard is up for grabs; the NEW grant's
    # token is strictly higher, and the old token is dead everywhere.
    clk.tick(10.0 + 2.0 + 0.001)
    g2 = coord.acquire("s0", "h2")
    assert g2["token"] > g1["token"]
    with pytest.raises(LeaseConflict):
        coord.renew("s0", "h1", g1["token"])
    with pytest.raises(LeaseConflict):
        coord.release("s0", "h1", g1["token"])
    with pytest.raises(LeaseConflict):
        coord.bump_epoch("s0", g1["token"])
    assert coord.stats.get_ticker_count("lease.rejects") >= 3
    assert coord.stats.get_ticker_count("lease.expiries") == 1


def test_clock_skew_grace_window(coord, clk):
    g = coord.acquire("s0", "h1")
    # Inside expiry+grace: the (possibly clock-lagged) holder may still
    # renew, and a competitor must keep waiting — the windows are the
    # same on both sides, so they can never overlap.
    clk.tick(11.0)  # expired 1s ago, grace is 2s
    with pytest.raises(LeaseConflict):
        coord.acquire("s0", "h2")
    renewed = coord.renew("s0", "h1", g["token"])
    assert renewed["expires"] == clk.now + 10.0
    # Fully past grace: renewals die too.
    clk.tick(12.001)
    with pytest.raises(LeaseConflict):
        coord.renew("s0", "h1", g["token"])


def test_double_grant_impossible_after_coordinator_restart(tmp_path, clk):
    path = str(tmp_path / "lease.jsonl")
    co = LeaseCoordinator(path, default_ttl=10.0, grace=2.0, clock=clk)
    co.install_map(ShardMap.uniform(1).to_config(), {})
    g = co.acquire("s0", "h1")
    co.close()  # coordinator "crashes" (state only in the log)
    co2 = LeaseCoordinator(path, default_ttl=10.0, grace=2.0, clock=clk)
    # The unexpired grant is still binding on the amnesiac restart...
    with pytest.raises(LeaseConflict):
        co2.acquire("s0", "h2")
    # ...the holder's token still works...
    renewed = co2.renew("s0", "h1", g["token"])
    assert renewed["token"] == g["token"]
    # ...and tokens granted after the restart are strictly higher
    # (next_token replays as max(seen) + 1, never reused).
    g2 = co2.reassign("s0", "h2", token=g["token"])
    assert g2["token"] > g["token"]
    co2.close()


def test_replay_ignores_torn_tail(tmp_path, clk):
    path = str(tmp_path / "lease.jsonl")
    co = LeaseCoordinator(path, default_ttl=10.0, grace=2.0, clock=clk)
    co.install_map(ShardMap.uniform(1).to_config(), {"s0": "http://a"})
    g = co.acquire("s0", "h1")
    co.close()
    with open(path, "ab") as f:  # crash mid-append: torn JSON tail
        f.write(b'{"op":"grant","shard":"s0","hol')
    co2 = LeaseCoordinator(path, default_ttl=10.0, grace=2.0, clock=clk)
    assert co2.status()["leases"]["s0"]["token"] == g["token"]
    assert co2.get_map()["placement"] == {"s0": "http://a"}
    co2.close()


def test_append_after_torn_tail_survives_second_restart(tmp_path, clk):
    """The torn tail is TRUNCATED on replay, so the first post-restart
    append starts on a fresh line. Without that, the new record would
    weld onto the fragment, and a SECOND restart would drop it plus
    every record after it — replayed tokens regress and the double
    grant the module rules out becomes possible."""
    path = str(tmp_path / "lease.jsonl")
    co = LeaseCoordinator(path, default_ttl=10.0, grace=2.0, clock=clk)
    co.install_map(ShardMap.uniform(1).to_config(), {"s0": "http://a"})
    g1 = co.acquire("s0", "h1")
    co.close()
    with open(path, "ab") as f:  # crash mid-append: torn JSON tail
        f.write(b'{"op":"grant","shard":"s0","hol')
    co2 = LeaseCoordinator(path, default_ttl=10.0, grace=2.0, clock=clk)
    g2 = co2.reassign("s0", "h2", token=g1["token"])  # fsynced post-tear
    co2.close()
    co3 = LeaseCoordinator(path, default_ttl=10.0, grace=2.0, clock=clk)
    lease = co3.status()["leases"]["s0"]
    assert (lease["holder"], lease["token"]) == ("h2", g2["token"])
    assert co3.status()["next_token"] == g2["token"] + 1  # never reused
    assert co3.get_map()["placement"] == {"s0": "http://a"}
    co3.close()


def test_lease_client_does_not_retry_non_idempotent_posts():
    """A mutating POST that dies in transit may already have been
    applied (epoch bumped, CAS landed): the client must fail fast and
    let the caller re-read the map, not blindly resend. Replay-safe
    renew still burns the whole retry budget."""
    from toplingdb_tpu.compaction.resilience import DcompactOptions

    c = LeaseClient("http://127.0.0.1:1",  # closed port: refused fast
                    timeout=0.5,
                    options=DcompactOptions(max_attempts=3,
                                            backoff_base=0.2,
                                            backoff_jitter=0.0,
                                            attempt_timeout=0.5))
    t0 = time.monotonic()
    with pytest.raises(IOError_, match="not idempotent"):
        c.reassign("s0", "h1", force=True)
    assert time.monotonic() - t0 < 0.5  # one attempt, no backoff sleeps
    with pytest.raises(IOError_, match="after 3 attempts"):
        c.renew("s0", "h1", token=1)


def test_map_cas_conflict(coord):
    doc = coord.get_map()
    m = ShardMap.from_config(doc["map"])
    m.split("s0", b"\x20" + b"\x00" * 15)
    coord.cas_map(doc["version"], m.to_config())  # winner
    with pytest.raises(LeaseConflict):
        coord.cas_map(doc["version"], m.to_config())  # loser: stale version
    assert coord.stats.get_ticker_count("lease.cas.conflicts") == 1


def test_reassign_requires_token_expiry_or_force(coord, clk):
    g = coord.acquire("s0", "h1")
    epoch0 = ShardMap.from_config(coord.get_map()["map"]).epoch_of("s0")
    with pytest.raises(LeaseConflict):
        coord.reassign("s0", "h2")  # live lease, no admission path
    out = coord.reassign("s0", "h2", force=True, url="http://c")
    assert out["token"] > g["token"]
    assert out["epoch"] > epoch0  # the cutover epoch bump fences stragglers
    assert coord.get_map()["placement"]["s0"] == "http://c"


# ---------------------------------------------------------------------------
# In-process ShardServer: epoch/lease write admission + graceful shutdown
# ---------------------------------------------------------------------------


def _mini_batch(key=b"k", val=b"v"):
    import base64

    from toplingdb_tpu.db.write_batch import WriteBatch

    b = WriteBatch()
    b.put(key, val)
    return base64.b64encode(b.data()).decode()


def test_server_rejects_stale_epoch_and_lapsed_lease(tmp_path,
                                                     no_thread_leaks):
    from toplingdb_tpu.sharding.fleet import ShardServer

    co = LeaseCoordinator(str(tmp_path / "lease.jsonl"), default_ttl=0.25,
                          grace=0.1)
    co.install_map(
        ShardMap([Shard(name="s0", start=None, end=None)]).to_config(), {})
    srv = ShardServer("s0", str(tmp_path / "s0"), coordinator=co,
                      lease_ttl=0.25, heartbeat_interval=30.0,
                      statistics=Statistics())
    try:
        srv.start()
        code, out = srv.handle_write({"epoch": 1,
                                      "batch_b64": _mini_batch()})
        assert code == 200 and out["epoch"] == 1
        # Wrong epoch: refused 409, counted, never applied.
        code, out = srv.handle_write({"epoch": 99,
                                      "batch_b64": _mini_batch(b"x")})
        assert (code, out["error"]) == (409, "stale_epoch")
        assert srv.stats.get_ticker_count("fleet.stale.epoch.rejects") == 1
        # Lease lapses (heartbeat disabled): server self-fences writes.
        time.sleep(0.3)
        assert not srv._lease_ok()
        code, out = srv.handle_write({"epoch": 1,
                                      "batch_b64": _mini_batch(b"y")})
        assert (code, out["error"]) == (503, "lease_expired")
        assert srv.stats.get_ticker_count("fleet.write.rejects") == 1
        assert srv.router.get(b"x") is None  # the 409 write never landed
        assert srv.router.get(b"y") is None  # nor the 503 one
    finally:
        srv.shutdown()
        co.close()


def test_graceful_shutdown_drains_flushes_and_reopens(tmp_path,
                                                      no_thread_leaks):
    """Satellite 3: shutdown fences + drains via the _WriteGate, flushes,
    closes — zero leaked owner-scoped threads (fixture) and a clean
    re-open that still holds every acked write."""
    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.sharding.fleet import ShardServer

    co = LeaseCoordinator(str(tmp_path / "lease.jsonl"), default_ttl=5.0,
                          grace=1.0)
    co.install_map(
        ShardMap([Shard(name="s0", start=None, end=None)]).to_config(), {})
    srv = ShardServer("s0", str(tmp_path / "s0"), coordinator=co,
                      statistics=Statistics())
    srv.start()
    for i in range(50):
        code, _ = srv.handle_write(
            {"epoch": 1, "batch_b64": _mini_batch(b"k%03d" % i, b"v")})
        assert code == 200
    srv.shutdown()
    srv.shutdown()  # idempotent
    assert co.status()["leases"] == {}  # lease released on the way out
    co.close()
    db = DB.open(str(tmp_path / "s0"), Options(create_if_missing=False))
    try:
        assert db.get(b"k000") == b"v" and db.get(b"k049") == b"v"
    finally:
        db.close()


def test_lease_validity_anchored_before_request(tmp_path, no_thread_leaks):
    """The self-fence deadline counts from BEFORE the acquire request
    went out: the coordinator stamps expires = its_now + ttl while the
    request is in flight, so a slow response must SHRINK the local
    validity window, never let it trail the coordinator's expiry."""
    from toplingdb_tpu.sharding.fleet import ShardServer

    co = LeaseCoordinator(str(tmp_path / "lease.jsonl"), default_ttl=1.0,
                          grace=0.2)
    co.install_map(
        ShardMap([Shard(name="s0", start=None, end=None)]).to_config(), {})

    class SlowCoordinator:
        """Delays acquire RESPONSES by 0.5s — the grant is already
        stamped at the coordinator when the delay happens."""

        def __getattr__(self, name):
            attr = getattr(co, name)
            if name != "acquire":
                return attr

            def acquire(*a, **k):
                out = attr(*a, **k)
                time.sleep(0.5)
                return out
            return acquire

    srv = ShardServer("s0", str(tmp_path / "s0"),
                      coordinator=SlowCoordinator(), lease_ttl=1.0,
                      heartbeat_interval=30.0, statistics=Statistics())
    try:
        srv.start()
        assert srv._lease_ok()
        remaining = srv._lease_valid_until - time.monotonic()
        assert remaining < 0.55  # ~ttl - delay; pre-fix it was ~ttl
    finally:
        srv.shutdown()
        co.close()


def test_release_lease_stops_heartbeat_reacquire(tmp_path,
                                                 no_thread_leaks):
    """Migration-cutover race: after /fleet/release_lease, a heartbeat
    landing before the supervisor's reassign must NOT re-acquire the
    surrendered lease (that aborts a fully caught-up migration). The
    endpoint stops the heartbeat and hands back the fencing token."""
    from toplingdb_tpu.sharding.fleet import ShardServer, _http_json

    co = LeaseCoordinator(str(tmp_path / "lease.jsonl"), default_ttl=5.0,
                          grace=0.2)
    co.install_map(
        ShardMap([Shard(name="s0", start=None, end=None)]).to_config(), {})
    srv = ShardServer("s0", str(tmp_path / "s0"), coordinator=co,
                      lease_ttl=0.5, heartbeat_interval=0.02,
                      statistics=Statistics())
    try:
        port = srv.start()
        out = _http_json(f"http://127.0.0.1:{port}",
                         "/fleet/release_lease", {})
        assert out["released"] and out["token"] is not None
        # A still-running heartbeat would re-acquire within a beat or
        # two (20ms); the surrendered lease must STAY surrendered.
        time.sleep(0.2)
        assert co.status()["leases"] == {}
        assert srv._lease is None
    finally:
        srv.shutdown()
        co.close()


def test_fleet_router_fails_closed_when_partitioned(tmp_path,
                                                    no_thread_leaks):
    """Satellite 4's router-side half: a router that cannot re-validate
    its map within the map lease refuses to route (Busy), and counts it
    — `shard.token.rejects` parity for the cross-process plane."""
    from toplingdb_tpu.env.fault_injection import PartitionGate
    from toplingdb_tpu.sharding.fleet import FleetRouter, ShardServer

    co = LeaseCoordinator(str(tmp_path / "lease.jsonl"), default_ttl=5.0,
                          grace=1.0)
    co.install_map(
        ShardMap([Shard(name="s0", start=None, end=None)]).to_config(), {})
    csrv = LeaseCoordinatorServer(co)
    cport = csrv.start()
    srv = ShardServer("s0", str(tmp_path / "s0"),
                      coordinator=LeaseClient(f"http://127.0.0.1:{cport}"),
                      statistics=Statistics())
    try:
        port = srv.start()
        doc = co.get_map()
        co.cas_map(doc["version"], doc["map"],
                   {"s0": f"http://127.0.0.1:{port}"})
        gate = PartitionGate()
        stats = Statistics()
        router = FleetRouter(
            LeaseClient(f"http://127.0.0.1:{cport}", timeout=2.0,
                        partition=gate),
            statistics=stats, map_lease=0.2, write_deadline=1.5)
        router.put(b"a", b"1")
        gate.engage()
        time.sleep(0.25)  # map lease lapses while partitioned
        with pytest.raises(Busy):
            router.put(b"b", b"2")
        assert stats.get_ticker_count("fleet.write.rejects") > 0
        gate.heal()
        router.put(b"b", b"2")  # heals transparently
        assert [k for k, _ in router.scan()] == [b"a", b"b"]
    finally:
        srv.shutdown()
        csrv.stop()
        co.close()


# ---------------------------------------------------------------------------
# HttpTransport resilience (satellite 2)
# ---------------------------------------------------------------------------


def test_http_transport_bounded_retry_and_breaker():
    from toplingdb_tpu.compaction.resilience import DcompactOptions
    from toplingdb_tpu.replication.log_shipper import HttpTransport

    t = HttpTransport("http://127.0.0.1:1",  # closed port: refused fast
                      timeout=0.5,
                      options=DcompactOptions(
                          max_attempts=2, backoff_base=0.01,
                          backoff_jitter=0.0, attempt_timeout=0.5,
                          breaker_failure_threshold=2,
                          breaker_reset_timeout=30.0))
    t0 = time.monotonic()
    with pytest.raises(IOError_, match="after 2 attempts"):
        t.pull(None)
    assert time.monotonic() - t0 < 5.0  # bounded, not wedged
    # Two strikes opened the breaker: the next call fails FAST without
    # touching the network at all.
    assert t.breaker.state == "open"
    with pytest.raises(IOError_, match="circuit open"):
        t.pull(None)


def test_http_transport_does_not_retry_http_answers(tmp_path):
    """An HTTP-level answer is deterministic: 410 maps to
    WalRetentionGone once, with no retry burn-down and no breaker
    strike (the peer is alive)."""
    import base64

    from toplingdb_tpu.db.db import DB
    from toplingdb_tpu.options import Options
    from toplingdb_tpu.replication.log_shipper import (
        HttpTransport,
        LogShipper,
        ReplicationServer,
        WalRetentionGone,
    )

    db = DB.open(str(tmp_path / "db"), Options(create_if_missing=True))
    shipper = LogShipper(db, max_frame_bytes=1 << 16)
    srv = ReplicationServer(db, shipper)
    try:
        port = srv.start()
        for i in range(20):
            db.put(b"k%05d" % i, os.urandom(256))
        # Flush twice so the WAL holding the early seqs is GC'd and a
        # pull from seq 3 is genuinely unservable (410 on the wire).
        db.flush()
        for i in range(5):
            db.put(b"x%02d" % i, b"y")
        db.flush()
        db.put(b"tail", b"t")
        t = HttpTransport(f"http://127.0.0.1:{port}", timeout=5.0)
        with pytest.raises(WalRetentionGone):
            t.pull(3)  # below the retention floor
        assert t.breaker.state == "closed"
        frames, state = t.pull(None)  # healthy pull still fine
        assert state["last_sequence"] == db.versions.last_sequence
    finally:
        srv.stop()
        db.close()


def test_spawn_ready_deadline_kills_wedged_child(no_thread_leaks):
    """A child wedged before its READY print (hung DB open, dead
    coordinator) must fail the spawn under a deadline — not hang the
    supervisor thread on a bare readline forever."""
    import subprocess
    import sys

    from toplingdb_tpu.sharding.fleet import FleetSupervisor

    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)"],
        stdout=subprocess.PIPE)
    t0 = time.monotonic()
    with pytest.raises(IOError_, match="did not come up"):
        FleetSupervisor._read_ready(proc, "wedged child", timeout=0.5)
    assert time.monotonic() - t0 < 5.0  # bounded, not wedged
    assert proc.poll() is not None  # killed, not orphaned


# ---------------------------------------------------------------------------
# The chaos soak (tentpole proof)
# ---------------------------------------------------------------------------


def test_fleet_soak_fast(tmp_path):
    """Seeded fast soak: 2 shard-server processes + coordinator process,
    concurrent writers, kill -9 mid-migration + recover, router
    partition fail-closed, coordinator crash/replay, stale-epoch 409 —
    then exact merged-oracle parity and all-zero exit codes."""
    from toplingdb_tpu.tools.fleet_soak import run_soak

    out = run_soak(str(tmp_path / "soak"), seed=1234, fast=True,
                   log=lambda *a: None)
    assert out["ok"]
    assert out["scanned_keys"] == out["oracle_keys"]
    assert out["acked_writes"] > 100
    assert out["router_fail_closed"] > 0


@pytest.mark.slow
def test_fleet_soak_full(tmp_path):
    from toplingdb_tpu.tools.fleet_soak import run_soak

    out = run_soak(str(tmp_path / "soak"), seed=99, fast=False,
                   log=lambda *a: None)
    assert out["ok"]
    assert out["scanned_keys"] == out["oracle_keys"]


def test_sideplugin_fleet_view(tmp_path, no_thread_leaks):
    """GET /fleet and /fleet/<name> on the SidePluginRepo HTTP layer:
    supervisor members merged with the coordinator's lease table."""
    import json as _json
    import urllib.error
    import urllib.request

    from toplingdb_tpu.sharding.fleet import FleetSupervisor
    from toplingdb_tpu.utils.config import SidePluginRepo

    co = LeaseCoordinator(str(tmp_path / "lease.jsonl"))
    co.install_map(ShardMap.uniform(1).to_config(), {})
    csrv = LeaseCoordinatorServer(co)
    cport = csrv.start()
    repo = SidePluginRepo()
    try:
        sup = FleetSupervisor(f"http://127.0.0.1:{cport}")
        repo.attach_fleet_supervisor("f1", sup)
        port = repo.start_http()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet", timeout=10) as r:
            assert _json.loads(r.read()) == {"fleets": ["f1"]}
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet/f1", timeout=10) as r:
            doc = _json.loads(r.read())
        assert doc["members"] == []
        assert doc["coordinator"]["map_version"] == 1
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/fleet/nope", timeout=10)
    finally:
        repo.stop_http()
        csrv.stop()
        co.close()


def test_fleet_admin_cli_roundtrip(tmp_path, no_thread_leaks):
    """The operator CLI against a live in-process coordinator + server:
    status, map, server-status, fence/unfence, kill."""
    from toplingdb_tpu.sharding.fleet import ShardServer
    from toplingdb_tpu.tools import fleet_admin

    co = LeaseCoordinator(str(tmp_path / "lease.jsonl"), default_ttl=5.0,
                          grace=1.0)
    co.install_map(
        ShardMap([Shard(name="s0", start=None, end=None)]).to_config(), {})
    csrv = LeaseCoordinatorServer(co)
    cport = csrv.start()
    srv = ShardServer("s0", str(tmp_path / "s0"),
                      coordinator=LeaseClient(f"http://127.0.0.1:{cport}"),
                      statistics=Statistics())
    try:
        port = srv.start()
        co_url = f"http://127.0.0.1:{cport}"
        s_url = f"http://127.0.0.1:{port}"
        assert fleet_admin.main(["--coordinator", co_url, "status"]) == 0
        assert fleet_admin.main(["--coordinator", co_url, "map"]) == 0
        assert fleet_admin.main(["--server", s_url, "server-status"]) == 0
        assert fleet_admin.main(["--server", s_url, "fence"]) == 0
        assert srv.router._gate("s0").fenced
        assert fleet_admin.main(["--server", s_url, "unfence"]) == 0
        assert not srv.router._gate("s0").fenced
        assert fleet_admin.main(["--server", s_url, "kill"]) == 0
        assert srv.shutdown_requested.wait(timeout=5.0)
        # missing required flag → usage error, not a traceback
        assert fleet_admin.main(["status"]) == 2
    finally:
        srv.shutdown()
        csrv.stop()
        co.close()
